#!/bin/bash
# Round-4 TPU evidence recapture (run when the axon tunnel is back).
# Serial on purpose: one TPU client at a time (never kill these
# mid-flight — a killed client can wedge the tunnel for the whole box).
set -u
cd /root/repo
mkdir -p artifacts
echo "=== $(date +%H:%M:%S) broadcast headline bench ==="
timeout 1800 python bench.py 2>artifacts/bench-r4-broadcast.log \
    | tee artifacts/bench-r4-broadcast.json
echo "rc=$?"
echo "=== $(date +%H:%M:%S) raft bench + partition-graded sample ==="
BENCH_MODE=raft timeout 3600 python bench.py \
    2>artifacts/bench-r4-raft.log | tee artifacts/bench-raft-r4.json
echo "rc=$?"
echo "=== $(date +%H:%M:%S) raft TPU phase profile ==="
timeout 3600 python -m maelstrom_tpu.profile_raft --clusters 10000 \
    --rounds 300 --chunk 100 2>artifacts/profile-raft-r4.log \
    | tee artifacts/profile-raft-r4.json
echo "rc=$?"
echo "=== $(date +%H:%M:%S) done ==="
