#!/bin/bash
# Round-5 TPU evidence recapture (run the moment the axon tunnel is
# back). Serial on purpose: ONE TPU client at a time, and never kill
# one mid-flight — a killed client can wedge the tunnel for the whole
# box (r3 lesson). Each step is separately resumable: rerun the script
# and finished steps are skipped by their artifact's existence. The
# persistent XLA cache (artifacts/xla-cache) makes retries cost seconds
# of compile instead of ~70 s.
set -u
cd /root/repo
mkdir -p artifacts

step() {  # step <artifact> <timeout_s> <cmd...>
    local out="$1" t="$2"; shift 2
    if [ -s "$out" ] && python -c "import json,sys; json.load(open('$out'))" \
            2>/dev/null; then
        echo "=== skip (exists): $out"
        return 0
    fi
    echo "=== $(date +%H:%M:%S) -> $out"
    timeout "$t" "$@" > "$out.tmp" 2> "${out%.json}.log" \
        && mv "$out.tmp" "$out" || echo "rc=$? (kept ${out%.json}.log)"
}

# 1. broadcast headline: ONE default run captures both protocols —
#    `value` is the efficient (send-once-plus-retry) 2.11M claim and
#    `eager_msgs_per_sec` the 4.10M eager-flood stress figure
#    (bench.py runs the efficient pass after the eager one when
#    BENCH_EAGER=1, the default). Since ISSUE 6 the same record also
#    carries the `fleet` section (clusters/sec + aggregate msgs/sec at
#    fleet sizes 1/8/64/512) — old and new metric land in one run
step artifacts/bench-r5-broadcast.json 2400 python bench.py

# 1b. fleet scaling as its own artifact (BENCH_MODE=fleet): headline
#     `value` = aggregate msgs/sec at the largest fleet size,
#     `vs_baseline` = the fleet-64/512 over fleet-1 speedup — the
#     ISSUE 6 clusters/sec lever measured on real TPU hardware
step artifacts/bench-fleet-r6.json 2400 env BENCH_MODE=fleet python bench.py

# 1c. open-world stream bench (BENCH_MODE=stream): continuous-mode
#     streaming kafka end to end — sustained msgs/sec + max checker lag
#     at 1x/4x/16x offered rate (doc/streams.md). CPU fallback honest:
#     host_cpus/devices ride the record
step artifacts/bench-stream-r7.json 2400 env BENCH_MODE=stream python bench.py

# 1d. batched atomic broadcast (BENCH_MODE=broadcast_batched, ISSUE 9):
#     distilled-batch node vs eager-resend at equal node count —
#     headline `value` = batched client-ops/s, `vs_baseline` = the
#     speedup (>= 2x acceptance; CPU r01 measured 50x at 1024 nodes).
#     The default run (step 1) also embeds the same record, so old and
#     new metric land in one recapture either way
step artifacts/bench-batched-r8.json 2400 \
    env BENCH_MODE=broadcast_batched python bench.py

# 1e. compartmentalized consensus (BENCH_MODE=compartment, ISSUE 10):
#     lin-kv client-ops/s vs proxy count (P=1/2/4/8) at fixed
#     leader/acceptor capacity on --node tpu:compartment — headline
#     `value` = client-ops/vsec at the largest proxy count,
#     `scaling_1_to_4` the >= 2x acceptance figure (doc/compartment.md).
#     CPU fallback honest: host_cpus/devices ride the record
step artifacts/bench-compartment-r9.json 2400 \
    env BENCH_MODE=compartment python bench.py

# 1f. device-resident checker (BENCH_MODE=checker, ISSUE 11): the elle
#     edge build + on-device cycle screen at 1M micro-ops — headline
#     `value` = jitted edge-build micro-ops/sec, `vs_baseline` = the
#     speedup over the pure-Python loop (>= 10x acceptance; CPU r01 in
#     artifacts/bench-checker-cpu-r01.json), plus the register/elle
#     host ratios and the screen decided-fraction (>= 0.9 gate) in one
#     record — so the pending recapture (BENCH r03-r06 gap) refreshes
#     the whole checker trajectory device-side in a single run
step artifacts/bench-checker-r11.json 2400 \
    env BENCH_MODE=checker python bench.py

# 1g. million-session open-world fleets (BENCH_MODE=fleet_stream,
#     ISSUE 12): `--fleet N --continuous` end to end — N streaming
#     kafka clusters in one vmapped sched-inject scan at fleet 1/8/64 x
#     offered rates 1x/4x. Headline `value` = sustained aggregate
#     client-ops/s at the top point, `vs_baseline` = the measured
#     host-poll amortization (>= 8x acceptance at fleet 64; CPU r01 in
#     artifacts/bench-fleet-stream-cpu-r01.json), with max checker-lag
#     bounded at every recorded rate (doc/perf.md "vectorized host
#     driver")
step artifacts/bench-fleet-stream-r12.json 3600 \
    env BENCH_MODE=fleet_stream python bench.py

# 1g2. columnar client sessions (BENCH_MODE=fleet_stream at scale,
#     ISSUE 17): fleet 8/64/512 columnar with the coroutine comparison
#     rows at >= 64 — `host_wall_per_wave` must stay flat (within 2x)
#     from fleet 8 to 512 on the columnar path and `session_speedup`
#     shows the coroutine/columnar host-wall ratio at the compared
#     sizes (CPU r01 in
#     artifacts/bench-fleet-stream-sessions-cpu-r01.json; doc/perf.md
#     "columnar client sessions")
step artifacts/bench-fleet-stream-sessions-r17.json 7200 \
    env BENCH_MODE=fleet_stream BENCH_FLEET_STREAM_SIZES=1,8,64,512 \
    BENCH_FLEET_STREAM_COMPARE_MIN=64 python bench.py

# 1h. flight-recorder overhead (BENCH_MODE=telemetry, ISSUE 13): the
#     same chunked broadcast scan with the device metric rings compiled
#     out vs in — headline `value` = overhead percent (< 5% acceptance;
#     CPU r01 measured noise-level -0.25%, artifacts/bench-telemetry-
#     cpu-r01.json). The TPU number is the one that matters: the ring
#     fold is ~20 small int32 ops beside the round's sorts, so any
#     measurable TPU overhead indicates a layout/fusion regression
#     (doc/observability.md "overhead")
step artifacts/bench-telemetry-r13.json 2400 \
    env BENCH_MODE=telemetry python bench.py

# 1i. leader failover (BENCH_MODE=failover, ISSUE 14): repeated
#     kill-the-live-sequencer (`--nemesis-targets kill=sequencer`) on
#     the 3-candidate elected compartment at the PR 9 acceptance shape
#     — headline `value` = max rounds-to-new-leader, with client-ops/s
#     before/during/after the kill windows and the availability block's
#     longest no-ok gap in the record (doc/compartment.md "leader
#     election"; CPU r01 in artifacts/bench-failover-cpu-r01.json).
#     Gates: linearizable at every point and >= 2 completed failovers
step artifacts/bench-failover-r14.json 2400 \
    env BENCH_MODE=failover python bench.py

# 1j. ordering-layer matrix (BENCH_MODE=ordering, ISSUE 15): lin-kv —
#     the SAME applier — end to end over each ordering engine
#     (`--ordering raft|compartment|batched`) at equal node count,
#     headline `value` = the fastest engine's client-ops/vsec
#     (doc/ordering.md; CPU r01 in artifacts/bench-ordering-cpu-
#     r01.json: batched 1594 > raft 1414 > compartment 645). Gate:
#     every engine's run grades linearizable
step artifacts/bench-ordering-r15.json 2400 \
    env BENCH_MODE=ordering python bench.py

# 1k. byzantine convictions (BENCH_MODE=byzantine, ISSUE 16): the SAME
#     compartment cluster benign and under the equivocating-sequencer
#     adversary (`--nemesis byzantine`), headline `value` = rounds from
#     injection to the proxy tier's first device conviction
#     (doc/faults.md "byzantine is a conviction driver"; CPU r01 in
#     artifacts/bench-byzantine-cpu-r01.json: 5 rounds to conviction,
#     1174/1174 injected corruptions convicted, 157.5 -> 153.2
#     client-ops/vsec under attack). Gates: byzantine block valid
#     (nothing unconvicted, nothing spurious) and the benign twin clean
step artifacts/bench-byzantine-r16.json 2400 \
    env BENCH_MODE=byzantine python bench.py

# 1l. pod-scale mixed mesh (BENCH_MODE=podmesh, ISSUE 18): the
#     end-to-end `--fleet N --mesh dp,sp` grid — fleet {2,8} x mesh
#     {1,1 / 2,1 / 1,2 / 2,2}, the 2,2 cells running the shard_map
#     manual scan body PR 2 had to reject — headline `value` =
#     aggregate msgs/sec on the biggest mixed cell, agg client
#     ops/vsec alongside (doc/perf.md "pod-scale mixed mesh"; CPU r01
#     in artifacts/bench-podmesh-cpu-r01.json, captured under a forced
#     4-device host mesh). Gate: every cell's run grades valid
step artifacts/bench-podmesh-r18.json 2400 \
    env BENCH_MODE=podmesh python bench.py

# 1m. predicted-vs-measured (ISSUE 20, doc/analyze.md "predicted vs
#     measured"): the fleet, batched-broadcast, and ordering benches
#     re-run on the TPU backend — every record row now carries a
#     `predicted` block (static roofline under the active device
#     profile) with the predicted/measured round-rate ratio stamped
#     in. These three artifacts are the TPU calibration points for the
#     cost model's tpu-v4/v5e profiles (the CPU band is committed in
#     doc/analyze.md; regenerate the table from these when captured)
step artifacts/bench-fleet-predicted-r20.json 2400 \
    env BENCH_MODE=fleet python bench.py
step artifacts/bench-batched-predicted-r20.json 2400 \
    env BENCH_MODE=broadcast_batched python bench.py
step artifacts/bench-ordering-predicted-r20.json 2400 \
    env BENCH_MODE=ordering python bench.py

# 2. raft fleet bench + the DESCRIBED graded config: 512 sampled of
#    10k clusters, 50 ops/worker, partition nemesis (README claim)
step artifacts/bench-raft-r5.json 3600 env BENCH_MODE=raft python bench.py

# 3. raft TPU phase profile at 10k clusters (verdict item 2: prove the
#    round-4 vectorization's win on TPU; round-3 measured 204 ms/round)
step artifacts/profile-raft-r5.json 3600 \
    python -m maelstrom_tpu.profile_raft --clusters 10000 \
    --rounds 300 --chunk 100

# 4. raft fault-mix fuzz on TPU (CPU insurance copies exist in
#    artifacts/fuzz-raft-cpu.jsonl / fuzz-kafka-cpu.jsonl)
if [ ! -s artifacts/fuzz-raft-tpu.jsonl ]; then
    echo "=== $(date +%H:%M:%S) -> artifacts/fuzz-raft-tpu.jsonl"
    # stream to .tmp, publish only on success: a timeout-killed partial
    # file must not satisfy the [ -s ] guard on rerun
    timeout 3600 python -c "
from maelstrom_tpu.fuzz import fuzz_raft
with open('artifacts/fuzz-raft-tpu.jsonl.tmp','w') as f:
    rows = fuzz_raft(n_clusters=10000, sample=128,
                     log=lambda s: (f.write(s+chr(10)), f.flush()))
import sys; sys.exit(0 if all(r['ok'] for r in rows) else 1)
" 2> artifacts/fuzz-raft-tpu.log \
        && mv artifacts/fuzz-raft-tpu.jsonl.tmp artifacts/fuzz-raft-tpu.jsonl
    echo "rc=$?"
fi

echo "=== $(date +%H:%M:%S) done; git add -f the artifacts that parsed"
