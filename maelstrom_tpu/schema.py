"""A tiny structural schema language for message bodies.

Fills the role of prismatic/schema in the reference: every RPC request and
response body is validated at the boundary, and schema violations become rich
teaching errors (reference `client.clj:242-273`, `process.clj:56-65`).
Schemas also render to readable JSON-ish text for the generated docs
(doc/workloads.md), mirroring `doc.clj`'s use of `s/explain`.

Schema language:
  Eq(x)                 -- exactly the value x
  Any                   -- anything
  int / str / bool      -- Python type atoms
  [schema]              -- list of schema
  Tup(s1, s2, ...)      -- fixed-length positional sequence
  Either(s1, s2, ...)   -- any of the alternatives
  {key: schema, ...}    -- map; string keys required, Optional(key) optional
  Optional(key)         -- marks a map key optional
"""

from __future__ import annotations


class _Any:
    def __repr__(self):
        return "any"


Any = _Any()


class Eq:
    def __init__(self, value):
        self.value = value

    def __repr__(self):
        import json
        return json.dumps(self.value)


class Optional:
    def __init__(self, key: str):
        self.key = key

    def __hash__(self):
        return hash(("optional", self.key))

    def __eq__(self, other):
        return isinstance(other, Optional) and other.key == self.key

    def __repr__(self):
        return f"{self.key}?"


class Either:
    def __init__(self, *alts):
        self.alts = alts

    def __repr__(self):
        return " | ".join(repr(explain(a)) for a in self.alts)


class Maybe:
    """Nullable: None or the inner schema (schema.core's `s/maybe`; the
    reference uses it for read results of missing keys,
    `txn_list_append.clj:55-59`)."""

    def __init__(self, inner):
        self.inner = inner


class Tup:
    """Fixed-length heterogeneous sequence, e.g. txn micro-ops
    (reference `txn_list_append.clj:55-59`)."""

    def __init__(self, *parts):
        self.parts = parts


def check(schema, data):
    """Returns None if data conforms to schema, else an 'explanation'
    structure mirroring the shape of the data (like schema.core checkers,
    reference `client.clj:242-247`)."""
    if schema is Any or schema is None:
        return None
    if isinstance(schema, Eq):
        if data != schema.value:
            return f"expected {schema.value!r}, got {data!r}"
        return None
    if schema is int:
        # bool is an int subtype in Python; exclude it.
        if isinstance(data, bool) or not isinstance(data, int):
            return f"expected an integer, got {data!r}"
        return None
    if schema is str:
        if not isinstance(data, str):
            return f"expected a string, got {data!r}"
        return None
    if schema is bool:
        if not isinstance(data, bool):
            return f"expected a boolean, got {data!r}"
        return None
    if isinstance(schema, Maybe):
        if data is None:
            return None
        return check(schema.inner, data)
    if isinstance(schema, Either):
        errs = []
        for alt in schema.alts:
            e = check(alt, data)
            if e is None:
                return None
            errs.append(e)
        return {"none-of": errs}
    if isinstance(schema, Tup):
        if not isinstance(data, (list, tuple)):
            return f"expected a {len(schema.parts)}-element array, got {data!r}"
        if len(data) != len(schema.parts):
            return (f"expected a {len(schema.parts)}-element array, "
                    f"got {len(data)} elements")
        errs = [check(p, d) for p, d in zip(schema.parts, data)]
        if any(e is not None for e in errs):
            return errs
        return None
    if isinstance(schema, list):
        assert len(schema) == 1, "list schemas take a single element schema"
        if not isinstance(data, (list, tuple)):
            return f"expected an array, got {data!r}"
        errs = {i: e for i, d in enumerate(data)
                if (e := check(schema[0], d)) is not None}
        return errs or None
    if isinstance(schema, dict):
        if not isinstance(data, dict):
            return f"expected an object, got {data!r}"
        errs = {}
        seen = set()
        for k, vschema in schema.items():
            optional = isinstance(k, Optional)
            key = k.key if optional else k
            # map-key schemas (e.g. {NodeId: [NodeId]}) — any-key maps
            if key is str or key is Any:
                for dk, dv in data.items():
                    seen.add(dk)
                    if key is str and not isinstance(dk, str):
                        errs[dk] = "key should be a string"
                    e = check(vschema, dv)
                    if e is not None:
                        errs[dk] = e
                continue
            seen.add(key)
            if key not in data:
                if not optional:
                    errs[key] = "missing required key"
                continue
            e = check(vschema, data[key])
            if e is not None:
                errs[key] = e
        for dk in data:
            if dk not in seen:
                errs[dk] = "disallowed key"
        return errs or None
    # Literal atom fallback
    if data != schema:
        return f"expected {schema!r}, got {data!r}"
    return None


def explain(schema):
    """Renders a schema as a JSON-ish plain structure for docs and error
    messages (the analogue of schema.core's `explain`)."""
    if schema is Any:
        return "any"
    if schema is int:
        return "int"
    if schema is str:
        return "string"
    if schema is bool:
        return "bool"
    if isinstance(schema, Eq):
        return schema.value
    if isinstance(schema, Maybe):
        return {"maybe": explain(schema.inner)}
    if isinstance(schema, Either):
        return {"either": [explain(a) for a in schema.alts]}
    if isinstance(schema, Tup):
        return [explain(p) for p in schema.parts]
    if isinstance(schema, list):
        return [explain(schema[0])]
    if isinstance(schema, dict):
        out = {}
        for k, v in schema.items():
            if isinstance(k, Optional):
                out[f"{k.key}?"] = explain(v)
            elif k is str:
                out["<string>"] = explain(v)
            else:
                out[k] = explain(v)
        return out
    return repr(schema)


def format_schema(schema, indent: int = 0) -> str:
    """Pretty-prints an explained schema, JSON-style."""
    import json
    return json.dumps(explain(schema), indent=2, default=str)
