"""The flight recorder: device-resident metric rings, host phase-span
tracing, and streaming run telemetry (doc/observability.md).

The reference Maelstrom's whole value is that a run *explains itself* —
stats, Lamport diagrams, journals. This module makes the reproduction
explain itself **while it runs**, in three layers:

  1. **Device metric rings** (`MetricRing`): a small int32 carry block
     accumulated INSIDE the compiled round — per-round message-flow
     counters (sent/delivered/dropped/duplicated), flight-pool and
     edge-channel occupancy histograms, per-role send counts under
     `sim.RolePartition`, and client-op latency-in-rounds buckets. The
     block rides `SimState.telemetry` through the scan carry and is
     drained only on the EXISTING dispatch-boundary packed fetches —
     zero new host transfers, zero history impact (counters never touch
     the PRNG stream or any message content, so telemetry-on and
     telemetry-off runs are byte-identical per seed).

  2. **Host phase spans** (`TelemetrySession.span`): the runner's wave
     loop phases — schedule/encode, dispatch, device_get, pipeline
     grading, checkpoint snapshots — recorded as Chrome trace events
     ("X" complete events, microsecond timestamps), written to
     `trace.json` so a whole run opens in Perfetto / chrome://tracing.
     TransferStats counters ride the spans as args.

  3. **Streaming export** (`TelemetrySession.wave`): one
     `telemetry.jsonl` record per window/wave — windowed AND cumulative
     p50/p95/p99 op latency via an exact counting sketch (`Sketch`),
     offered vs delivered rates, checker lag, ring deltas, per-cluster
     under `--fleet` — plus `render_top` (the `maelstrom_tpu top` tail
     view) and the fleet heatmap (`viz/fleet.py`).

Quantiles are EXACT, not approximate: virtual time makes op latencies a
small discrete domain, so the "sketch" is a counting histogram keyed by
latency value, and its quantile rule replicates
`checkers.perf.latency_stats` index-for-index — the final cumulative
record matches the post-hoc PerfChecker bit-for-bit (pinned by
tests/test_telemetry.py).

Everything here is observational: no telemetry code path may influence
scheduling, PRNG draws, or history contents.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

I32 = jnp.int32

# Bucket shapes are static (they size the carry block): occupancy is
# bucketed by fraction-of-capacity eighths, latency by powers of two in
# rounds (bucket b covers (2^(b-1), 2^b] rounds; bucket 0 is <= 1).
OCC_BUCKETS = 8
LAT_BUCKETS = 16


# ---------------------------------------------------------------------------
# Layer 1: the device-resident metric ring
# ---------------------------------------------------------------------------

@struct.dataclass
class MetricRing:
    """The int32 telemetry carry block (`SimState.telemetry`). All
    fields are cumulative over the rounds executed since the run (or
    resume) started; the host computes per-window deltas at each
    dispatch-boundary drain. `req_round` is internal bookkeeping: the
    in-flight invoke round per client slot (-1 = idle), the device-side
    half of the latency histogram."""
    rounds: jnp.ndarray         # i32 [] rounds accumulated
    sent: jnp.ndarray           # i32 [] messages sent (attempted)
    delivered: jnp.ndarray      # i32 [] messages delivered
    dropped: jnp.ndarray        # i32 [] lost + partition + down + overflow
    duplicated: jnp.ndarray     # i32 [] at-least-once extra copies
    pool_hist: jnp.ndarray      # i32 [OCC_BUCKETS] rounds by pool occupancy
    pool_max: jnp.ndarray       # i32 [] peak flight-pool occupancy
    chan_hist: jnp.ndarray      # i32 [OCC_BUCKETS] rounds by channel occ
    chan_max: jnp.ndarray       # i32 [] peak edge-channel occupancy
    role_sent: jnp.ndarray      # i32 [R] node sends per role slice
    lat_hist: jnp.ndarray       # i32 [LAT_BUCKETS] reply latency (rounds,
    #                             log2 buckets; device-side, so the delta
    #                             vs the history's stamp is a constant 1)
    lat_count: jnp.ndarray      # i32 [] replies measured
    lat_sum: jnp.ndarray        # i32 [] summed latency rounds
    req_round: jnp.ndarray      # i32 [C] in-flight invoke round (-1 idle)


def role_bounds(program) -> tuple:
    """The static ((lo, hi), ...) node-id slices `MetricRing.role_sent`
    buckets by: a `RolePartition`'s role ranges, or one whole-cluster
    slice for homogeneous programs. Hashable (rides `NetConfig`)."""
    bounds = getattr(program, "_bounds", None)
    if bounds:
        return tuple((int(lo), int(hi)) for lo, hi in bounds)
    return ((0, int(getattr(program, "n_nodes", 0))),)


def role_names(program) -> list:
    roles = getattr(program, "roles", None)
    if roles:
        return [name for name, _prog in roles]
    return ["nodes"]


def make_ring(cfg) -> MetricRing:
    z = jnp.zeros((), I32)
    n_roles = max(len(cfg.telemetry_roles), 1)
    return MetricRing(
        rounds=z, sent=z, delivered=z, dropped=z, duplicated=z,
        pool_hist=jnp.zeros(OCC_BUCKETS, I32), pool_max=z,
        chan_hist=jnp.zeros(OCC_BUCKETS, I32), chan_max=z,
        role_sent=jnp.zeros(n_roles, I32),
        lat_hist=jnp.zeros(LAT_BUCKETS, I32), lat_count=z, lat_sum=z,
        req_round=jnp.full(max(cfg.n_clients, 1), -1, I32))


def _occ_bucket(occ, cap: int):
    b = (occ * OCC_BUCKETS) // max(cap, 1)
    return jnp.clip(b, 0, OCC_BUCKETS - 1)


def ring_update(cfg, ring: MetricRing, st0, net, channels, round_i,
                node_sent, inject_sent, reply_msgs) -> MetricRing:
    """One round's telemetry fold, called at the END of `sim._round` /
    `sim._round_edge` (pure, int32, scatter-ADD only — the jaxpr
    auditor's host-transfer and scatter rules stay at zero findings).

    `st0` is the round-entry `NetStats`, `net` the post-round NetState
    (its stats are the round-exit values, so class deltas are exact),
    `node_sent` an [N] per-node valid-send count for role bucketing,
    `inject_sent` the id-stamped client inject view, and `reply_msgs` a
    flat Msgs view whose valid client-destined rows are this round's
    reply deliveries."""
    st1 = net.stats
    d_sent = st1.sent_all - st0.sent_all
    d_recv = st1.recv_all - st0.recv_all
    d_drop = ((st1.lost + st1.dropped_partition + st1.dropped_down
               + st1.dropped_overflow)
              - (st0.lost + st0.dropped_partition + st0.dropped_down
                 + st0.dropped_overflow))
    d_dup = st1.duplicated - st0.duplicated

    # occupancy (sampled once per round, post-delivery/post-send)
    pool_occ = jnp.sum(net.pool.valid.astype(I32))
    pool_hist = ring.pool_hist.at[_occ_bucket(pool_occ,
                                              cfg.pool_cap)].add(1)
    if channels is not None:
        chan_occ = jnp.sum(channels.valid.astype(I32))
        chan_hist = ring.chan_hist.at[
            _occ_bucket(chan_occ, int(channels.valid.size))].add(1)
        chan_max = jnp.maximum(ring.chan_max, chan_occ)
    else:
        chan_hist, chan_max = ring.chan_hist, ring.chan_max

    # per-role sends: static role slices over the [N] per-node counts
    role_sent = ring.role_sent
    bounds = cfg.telemetry_roles or ((0, cfg.n_nodes),)
    for i, (lo, hi) in enumerate(bounds):
        role_sent = role_sent.at[i].add(jnp.sum(node_sent[lo:hi]))

    # client-op latency in rounds: invokes arm req_round, replies read
    # it. Dense where-updates driven by scatter-ADD one-hots — no
    # scatter-set, so overlapping rows (a duplicated reply) stay
    # order-independent. Replies are matched against the PRE-ARM table:
    # a late reply delivered in the same round a timed-out worker
    # re-invokes must pair with the OLD op (its real latency) and leave
    # the fresh arm in place for the new op's reply.
    C = ring.req_round.shape[0]
    N = cfg.n_nodes
    req0 = ring.req_round

    rep_flat = jax.tree.map(lambda f: f.reshape(-1), reply_msgs)
    rep_valid = rep_flat.valid & (rep_flat.dest >= N)
    rep_idx = jnp.where(rep_valid,
                        jnp.clip(rep_flat.dest - N, 0, C - 1), C)
    hit = jnp.zeros(C, I32).at[rep_idx].add(
        rep_valid.astype(I32), mode="drop") > 0
    lat_c = jnp.where(hit & (req0 >= 0), round_i - req0, -1)  # [C]
    measured = lat_c >= 0
    lat_pos = jnp.maximum(lat_c, 1).astype(jnp.float32)
    bucket = jnp.clip(jnp.ceil(jnp.log2(lat_pos)).astype(I32),
                      0, LAT_BUCKETS - 1)
    lat_hist = ring.lat_hist.at[jnp.where(measured, bucket,
                                          LAT_BUCKETS)].add(
        measured.astype(I32), mode="drop")

    inv_valid = inject_sent.valid & (inject_sent.src >= N)
    inv_idx = jnp.where(inv_valid,
                        jnp.clip(inject_sent.src - N, 0, C - 1), C)
    armed = jnp.zeros(C, I32).at[inv_idx].add(
        jnp.where(inv_valid, round_i + 1, 0), mode="drop")
    req = jnp.where(armed > 0, armed - 1, req0)
    req = jnp.where(hit & ~(armed > 0), -1, req)

    return MetricRing(
        rounds=ring.rounds + 1,
        sent=ring.sent + d_sent,
        delivered=ring.delivered + d_recv,
        dropped=ring.dropped + d_drop,
        duplicated=ring.duplicated + d_dup,
        pool_hist=pool_hist,
        pool_max=jnp.maximum(ring.pool_max, pool_occ),
        chan_hist=chan_hist, chan_max=chan_max,
        role_sent=role_sent,
        lat_hist=lat_hist,
        lat_count=ring.lat_count + jnp.sum(measured.astype(I32)),
        lat_sum=ring.lat_sum + jnp.sum(jnp.where(measured, lat_c, 0)),
        req_round=req)


def ring_dict(ring, role_labels=None) -> dict:
    """The drained ring as a plain JSON-shaped dict (host numpy in,
    ints out). Used by the net-stats results block, the per-wave jsonl
    records (as deltas), and the parity tests."""
    g = lambda a: int(np.asarray(a).sum())      # noqa: E731
    labels = list(role_labels or [])
    role = np.asarray(ring.role_sent).reshape(
        -1, ring.role_sent.shape[-1]).sum(axis=0)
    out = {
        "rounds": g(ring.rounds),
        "sent": g(ring.sent),
        "delivered": g(ring.delivered),
        "dropped": g(ring.dropped),
        "duplicated": g(ring.duplicated),
        "pool-occupancy-hist": np.asarray(ring.pool_hist).reshape(
            -1, OCC_BUCKETS).sum(axis=0).tolist(),
        "pool-occupancy-max": int(np.asarray(ring.pool_max).max()),
        "latency-rounds-hist": np.asarray(ring.lat_hist).reshape(
            -1, LAT_BUCKETS).sum(axis=0).tolist(),
        "latency-count": g(ring.lat_count),
        "latency-rounds-sum": g(ring.lat_sum),
    }
    if int(np.asarray(ring.chan_hist).sum()):
        out["chan-occupancy-hist"] = np.asarray(ring.chan_hist).reshape(
            -1, OCC_BUCKETS).sum(axis=0).tolist()
        out["chan-occupancy-max"] = int(np.asarray(ring.chan_max).max())
    out["role-sent"] = {
        (labels[i] if i < len(labels) else f"role-{i}"): int(v)
        for i, v in enumerate(role.tolist())}
    return out


# ---------------------------------------------------------------------------
# Exact streaming quantiles
# ---------------------------------------------------------------------------

class Sketch:
    """An exact streaming quantile structure for a small discrete value
    domain: a counting histogram keyed by value. Virtual time makes op
    latencies multiples of ms_per_round, so this is lossless where a
    GK/t-digest sketch would approximate — and `quantiles()` replicates
    `checkers.perf.latency_stats` (sorted values, index
    `min(n-1, int(p*n))`, round(x, 3)) so the cumulative sketch matches
    the post-hoc PerfChecker exactly."""

    __slots__ = ("counts", "n")

    def __init__(self):
        self.counts: dict = {}
        self.n = 0

    def add(self, v: float):
        self.counts[v] = self.counts.get(v, 0) + 1
        self.n += 1

    def merge(self, other: "Sketch"):
        for v, c in other.counts.items():
            self.counts[v] = self.counts.get(v, 0) + c
        self.n += other.n

    def quantiles(self) -> dict:
        if not self.n:
            return {}
        items = sorted(self.counts.items())
        n = self.n

        def q(p):
            target = min(n - 1, int(p * n))
            seen = 0
            for v, c in items:
                seen += c
                if target < seen:
                    return v
            return items[-1][0]         # pragma: no cover - target < n
        return {"count": n, "p50": round(q(0.5), 3),
                "p95": round(q(0.95), 3), "p99": round(q(0.99), 3),
                "max": round(items[-1][0], 3)}


# ---------------------------------------------------------------------------
# Layers 2+3: the host session (spans + jsonl stream)
# ---------------------------------------------------------------------------

class _Cursor:
    """Per-cluster incremental history scan state: the open-slot pairing
    walk (same adjacency rule as `History.pairs_index`), a windowed and
    a cumulative latency sketch, window op counters, and the last ring
    drain (for deltas)."""

    __slots__ = ("row", "open", "win", "cum", "invokes", "oks", "fails",
                 "infos", "last_round", "last_ring", "windows",
                 "last_ok_ns", "max_gap_ns")

    def __init__(self):
        self.row = 0
        self.open: dict = {}
        self.win = Sketch()
        self.cum = Sketch()
        self.invokes = self.oks = self.fails = self.infos = 0
        self.last_round = 0
        self.last_ring: dict | None = None
        self.windows = 0
        # availability tracking (checkers/availability.py has the
        # post-hoc equivalent): time of the last committed reply and
        # the longest no-ok gap seen so far
        self.last_ok_ns = 0
        self.max_gap_ns = 0


class TelemetrySession:
    """One run's flight recorder (standalone or fleet-wide). Opened by
    `run_tpu_test` / `FleetRunner` when `--telemetry` names a directory;
    every method is cheap and observational — sessions never touch
    scheduling, PRNG, or history state.

    Thread safety: spans arrive from the analysis worker thread too, so
    the event list and jsonl writer are lock-guarded."""

    # span buffer cap: trace.json must be written as one JSON document,
    # so spans are held in memory until close — bounded, or a long
    # continuous fleet run would grow the buffer for days. Past the cap
    # the EARLIEST spans are already safe (they were recorded first);
    # later spans are counted as dropped in the trace metadata.
    TRACE_EVENT_CAP = 200_000

    def __init__(self, out_dir: str, ms_per_round: float = 1.0,
                 fleet: int = 1):
        os.makedirs(out_dir, exist_ok=True)
        self.dir = out_dir
        self.ms_per_round = float(ms_per_round)
        self.fleet = int(fleet)
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._events: list = []
        self._events_dropped = 0
        self._cursors: dict = {}
        self._seq = 0
        self._clusters: set = set()
        self._closed = False
        self._jsonl = open(os.path.join(out_dir, "telemetry.jsonl"), "w")

    # --- spans (Chrome trace events) ---

    def now(self) -> float:
        return time.perf_counter()

    def span(self, name: str, t0: float, t1: float, tid="runner",
             args: dict | None = None):
        """One completed phase span, perf_counter() endpoints."""
        ev = {"name": name, "ph": "X", "pid": "maelstrom",
              "tid": str(tid),
              "ts": round((t0 - self._t0) * 1e6, 1),
              "dur": round(max(t1 - t0, 0.0) * 1e6, 1)}
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) < self.TRACE_EVENT_CAP:
                self._events.append(ev)
            else:
                self._events_dropped += 1

    # --- per-wave records ---

    def _cursor(self, cluster) -> _Cursor:
        c = self._cursors.get(cluster)
        if c is None:
            c = self._cursors[cluster] = _Cursor()
        return c

    def _ingest(self, cur: _Cursor, history):
        """Advances the cursor over newly-appended history rows with the
        pairing adjacency rule `History.pairs_index` / the post-hoc
        PerfChecker use: an invoke pairs with the immediately following
        same-process completion; nemesis rows are skipped."""
        hi = len(history)
        if hi <= cur.row:
            return
        soa = history.soa()
        try:
            nem = soa.process_table.index("nemesis")
        except ValueError:
            nem = -1
        types, procs, times = soa.type, soa.process, soa.time
        for i in range(cur.row, hi):
            p = int(procs[i])
            if p == nem:
                continue
            if types[i] == 0:               # invoke
                cur.open[p] = int(times[i])
                cur.invokes += 1
                continue
            t0 = cur.open.pop(p, None)
            if types[i] == 1:               # ok
                cur.oks += 1
                t_ok = int(times[i])
                cur.max_gap_ns = max(cur.max_gap_ns,
                                     t_ok - cur.last_ok_ns)
                cur.last_ok_ns = max(cur.last_ok_ns, t_ok)
                if t0 is not None:
                    lat_ms = (t_ok - t0) / 1e6
                    cur.win.add(lat_ms)
                    cur.cum.add(lat_ms)
            elif types[i] == 2:
                cur.fails += 1
            else:
                cur.infos += 1
        cur.row = hi

    def wave(self, history, r: int, cluster=None, ring=None,
             pipeline=None, transfer=None):
        """Appends one window record to telemetry.jsonl: ops and exact
        windowed + cumulative latency quantiles from the rows this wave
        exposed, offered/delivered rates over the window's virtual
        span, ring deltas, and the stream grader's checker lag."""
        cur = self._cursor(cluster)
        inv0, ok0 = cur.invokes, cur.oks
        fail0, info0 = cur.fails, cur.infos
        cur.win = Sketch()
        self._ingest(cur, history)
        span_r = max(int(r) - cur.last_round, 0)
        span_s = span_r * self.ms_per_round / 1e3
        rec = {
            "type": "window", "seq": self._seq, "window": cur.windows,
            "round": int(r),
            "t_s": round(time.perf_counter() - self._t0, 6),
            "ops": cur.invokes - inv0,
            "oks": cur.oks - ok0,
            "fails": cur.fails - fail0,
            "infos": cur.infos - info0,
            "lat_ms": cur.win.quantiles(),
            "cum_lat_ms": cur.cum.quantiles(),
        }
        if cluster is not None:
            rec["cluster"] = cluster
        if span_s > 0:
            rec["offered_rate"] = round((cur.invokes - inv0) / span_s, 3)
            rec["delivered_rate"] = round((cur.oks - ok0) / span_s, 3)
        # live availability view (doc/compartment.md "leader election"):
        # the running longest no-committed-reply gap and the current
        # open gap, in virtual rounds — a failover dip shows up here
        # windows before the post-hoc availability block lands
        ns_pr = self.ms_per_round * 1e6
        rec["availability"] = {
            "max_ok_gap_rounds": int(cur.max_gap_ns / ns_pr),
            "rounds_since_ok": max(int(r) - int(cur.last_ok_ns / ns_pr),
                                   0),
        }
        if pipeline is not None and getattr(pipeline, "windows", None):
            lag = pipeline.windows[-1].get("lag-rounds")
            if lag is not None:
                rec["checker_lag_rounds"] = lag
        if ring is not None:
            rec["ring"] = self._ring_delta(cur, ring)
        if transfer is not None:
            rec["drains"] = transfer.drains
        cur.last_round = int(r)
        cur.windows += 1
        self._write(rec)

    def _ring_delta(self, cur: _Cursor, ring_now: dict) -> dict:
        prev = cur.last_ring or {}
        cur.last_ring = ring_now
        out = {}
        for k, v in ring_now.items():
            if isinstance(v, int):
                out[k] = v - int(prev.get(k, 0))
            elif isinstance(v, list):
                pv = prev.get(k) or [0] * len(v)
                out[k] = [a - b for a, b in zip(v, pv)]
        return out

    def flush(self, history, r: int, cluster=None, ring=None,
              pipeline=None):
        """The run's final record for one cluster: ingests the tail
        rows (replies folded after the last wave, timeouts) and writes
        the cumulative stats — `final.lat_ms` is the record the
        acceptance test compares against PerfChecker's latency-ms."""
        cur = self._cursor(cluster)
        cur.win = Sketch()
        self._ingest(cur, history)
        rec = {
            "type": "final", "seq": self._seq, "round": int(r),
            "t_s": round(time.perf_counter() - self._t0, 6),
            "ops": cur.invokes, "oks": cur.oks,
            "fails": cur.fails, "infos": cur.infos,
            "windows": cur.windows,
            "lat_ms": cur.cum.quantiles(),
        }
        if cluster is not None:
            rec["cluster"] = cluster
        if ring is not None:
            # the final record carries the CUMULATIVE ring (window
            # records carry deltas): the run's whole device telemetry
            # in one line, equal to the results block's
            rec["ring"] = ring
        if pipeline is not None and getattr(pipeline, "windows", None):
            lags = [w.get("lag-rounds") for w in pipeline.windows
                    if w.get("lag-rounds") is not None]
            if lags:
                rec["max_checker_lag_rounds"] = max(lags)
        self._write(rec)

    def _write(self, rec: dict):
        # records go straight to disk (flushed — `top` tails the live
        # file); nothing is buffered in memory, so session footprint
        # stays flat over arbitrarily long runs
        with self._lock:
            if self._closed:
                return
            self._seq += 1
            if rec.get("cluster") is not None:
                self._clusters.add(rec["cluster"])
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()

    # --- teardown ---

    def close(self):
        """Writes trace.json (Perfetto/chrome://tracing format) and —
        for fleet sessions — the per-cluster heatmap SVG. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._jsonl.close()
            events = self._events
            self._events = []
        trace = {"traceEvents": events, "displayTimeUnit": "ms"}
        if self._events_dropped:
            trace["otherData"] = {
                "spans-dropped-past-cap": self._events_dropped}
        with open(os.path.join(self.dir, "trace.json"), "w") as f:
            json.dump(trace, f)
        if len(self._clusters) > 1:
            try:
                # re-read the stream from disk (records are not kept in
                # memory) to render the per-cluster heatmap
                from .viz.fleet import fleet_heatmap
                fleet_heatmap(read_records(self.dir),
                              os.path.join(self.dir,
                                           "fleet-heatmap.svg"))
            except Exception:       # viz must never fail the run
                pass


def resolve_dir(spec, store_dir: str) -> str:
    """`--telemetry` value -> output directory: an explicit path is
    used as-is; the bare flag ("auto") lands telemetry/ inside the
    run's store dir, next to history.jsonl and results.json."""
    if spec in (None, "", "off"):
        raise ValueError("telemetry disabled")
    if spec in ("auto", "on", True):
        return os.path.join(store_dir, "telemetry")
    return str(spec)


def enabled(test: dict) -> bool:
    v = test.get("telemetry")
    return bool(v) and str(v) != "off"


# ---------------------------------------------------------------------------
# `maelstrom_tpu top`: the live tail view
# ---------------------------------------------------------------------------

def read_records(path: str) -> list:
    """Loads telemetry.jsonl records from a file, a telemetry dir, or a
    store test dir (searched at <dir>/telemetry/telemetry.jsonl)."""
    if os.path.isdir(path):
        for cand in (os.path.join(path, "telemetry.jsonl"),
                     os.path.join(path, "telemetry", "telemetry.jsonl")):
            if os.path.exists(cand):
                path = cand
                break
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue            # torn tail line of a live run
    return out


def render_top(records: list) -> str:
    """A `top`-style snapshot of the freshest window per cluster plus a
    totals line — pure function of the parsed records, so the renderer
    is unit-testable without a live run."""
    if not records:
        return "telemetry: no records yet"
    latest: dict = {}
    last_win: dict = {}
    for r in records:
        if r.get("type") in ("window", "final"):
            latest[r.get("cluster")] = r
        if r.get("type") == "window":
            last_win[r.get("cluster")] = r
    rows = []
    header = (f"{'cluster':>8} {'round':>9} {'win':>5} {'ops':>7} "
              f"{'ok/s':>9} {'p50ms':>8} {'p95ms':>8} {'p99ms':>8} "
              f"{'lag':>6}")
    rows.append(header)
    rows.append("-" * len(header))
    tot_ops = tot_oks = 0
    for cl in sorted(latest, key=lambda c: (c is None, c)):
        r = latest[cl]
        lat = r.get("lat_ms") or r.get("cum_lat_ms") or {}
        cum = r.get("cum_lat_ms") or lat
        tot_ops += r.get("ops", 0)
        tot_oks += r.get("oks", 0)
        # the rate column reads the freshest WINDOW record (finals
        # carry cumulative counts, not a windowed rate)
        rate = (r.get("delivered_rate")
                or last_win.get(cl, {}).get("delivered_rate", "-"))
        rows.append(
            f"{('-' if cl is None else cl):>8} "
            f"{r.get('round', 0):>9} "
            f"{r.get('window', r.get('windows', 0)):>5} "
            f"{r.get('ops', 0):>7} "
            f"{rate:>9} "
            f"{lat.get('p50', cum.get('p50', '-')):>8} "
            f"{lat.get('p95', cum.get('p95', '-')):>8} "
            f"{lat.get('p99', cum.get('p99', '-')):>8} "
            f"{r.get('checker_lag_rounds', '-'):>6}")
    finals = [r for r in records if r.get("type") == "final"]
    rows.append("")
    rows.append(f"clusters: {len(latest)}  records: {len(records)}  "
                f"final: {len(finals)}")
    return "\n".join(rows)


def top_main(path: str, follow: bool = False,
             interval: float = 1.0) -> int:
    """`maelstrom_tpu top PATH [--follow]`: renders the freshest
    telemetry snapshot; with --follow, re-renders every `interval`
    seconds until interrupted."""
    try:
        while True:
            try:
                records = read_records(path)
            except FileNotFoundError:
                print(f"top: no telemetry at {path!r} (run with "
                      f"--telemetry DIR)")
                return 1
            out = render_top(records)
            if follow:
                print("\x1b[2J\x1b[H" + out, flush=True)
                time.sleep(max(interval, 0.1))
            else:
                print(out)
                return 0
    except KeyboardInterrupt:
        return 0


# ---------------------------------------------------------------------------
# Introspection helpers shared by tests and docs
# ---------------------------------------------------------------------------

def lat_bucket_bounds() -> list:
    """[(lo, hi)] inclusive latency-in-rounds range per lat_hist
    bucket, for rendering (doc/observability.md's table)."""
    out = [(0, 1)]
    for b in range(1, LAT_BUCKETS):
        out.append((2 ** (b - 1) + 1, 2 ** b))
    return out


def validate_record(rec: dict) -> list:
    """Schema check for one telemetry.jsonl record (the check.sh smoke
    gate): returns a list of problems, empty when valid."""
    problems = []
    t = rec.get("type")
    if t not in ("window", "final"):
        problems.append(f"unknown record type {t!r}")
        return problems
    for k in ("seq", "round", "ops", "oks"):
        if not isinstance(rec.get(k), int):
            problems.append(f"{k}: expected int, got {rec.get(k)!r}")
    for k in ("lat_ms",) + (("cum_lat_ms",) if t == "window" else ()):
        v = rec.get(k)
        if not isinstance(v, dict):
            problems.append(f"{k}: expected dict, got {v!r}")
        elif v and not {"count", "p50", "p95", "p99",
                        "max"} <= set(v):
            problems.append(f"{k}: incomplete quantile block {v!r}")
    if math.isnan(rec.get("t_s", 0.0)):
        problems.append("t_s: NaN")
    return problems
