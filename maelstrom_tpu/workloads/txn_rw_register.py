"""Transactional read/write-register workload (classic Maelstrom's
`txn-rw-register`, beyond the reference's seven).

Transactions are arrays of micro-ops `[f, k, v]` with f in {"r", "w"}:
reads are submitted with v=null and completed with the observed value
(null = never written); writes set the register. The generator never
reuses a (key, value) pair — write uniqueness is what lets the checker
trace every read to its writer. Graded by
`checkers/txn_rw_register.py`, the honestly-scoped observable-subset
analysis (see its docstring for exactly what register reads can and
cannot prove)."""

from __future__ import annotations

import random

from .. import generators as g
from .. import schema as S
from ..checkers.txn_rw_register import RWRegisterChecker
from ..client import defrpc
from . import BaseClient
# error 30 (txn-conflict, DEFINITE) registration: the checker's G1a
# rule depends on aborted txns grading `fail`, not `info` — never rely
# on a sibling module's import side effect for that
from . import txn_list_append  # noqa: F401

ReadReq = S.Tup(S.Eq("r"), S.Any, S.Eq(None))
ReadRes = S.Tup(S.Eq("r"), S.Any, S.Any)
Write = S.Tup(S.Eq("w"), S.Any, S.Any)

txn_rpc = defrpc(
    "txn",
    "Requests that the node execute a single transaction of register "
    "reads and writes. Servers respond with a `txn_ok` message carrying "
    "the completed transaction — reads filled in with the observed "
    "value, or null for a never-written register.",
    {"type": S.Eq("txn"), "txn": [S.Either(ReadReq, Write)]},
    {"type": S.Eq("txn_ok"), "txn": [S.Either(ReadRes, Write)]},
    ns="maelstrom_tpu.workloads.txn_rw_register")


class RWClient(BaseClient):
    def invoke(self, test, op):
        def go():
            res = txn_rpc(self.conn, self.node,
                          {"txn": [list(m) for m in op["value"]]})
            return {**op, "type": "ok",
                    "value": [list(m) for m in res["txn"]]}
        return self.with_errors(op, set(), go)


class RWOpGen:
    """Random r/w transactions; per-key counters keep every written
    value unique (the checker's traceability contract). Picklable."""

    def __init__(self, opts: dict):
        self.rng = random.Random(opts.get("seed", 0))
        self.key_count = opts.get("key_count") or 8
        self.max_txn_length = opts.get("max_txn_length", 4)
        self.counters: dict = {}

    def __call__(self):
        n = self.rng.randint(1, self.max_txn_length)
        mops = []
        for _ in range(n):
            k = self.rng.randrange(self.key_count)
            if self.rng.random() < 0.5:
                mops.append(["r", k, None])
            else:
                self.counters[k] = self.counters.get(k, 0) + 1
                mops.append(["w", k, self.counters[k]])
        return {"f": "txn", "value": mops}


def workload(opts: dict) -> dict:
    return {
        "client": RWClient(opts["net"]),
        "generator": g.Fn(RWOpGen(opts)),
        "checker": RWRegisterChecker(),
    }
