"""Unique-ID generation workload (doc/tutorial/09-workloads.md's worked
example; classic Maelstrom's `unique-ids`, absent from the reference's
seven workloads).

Clients ask any node for a fresh id; the system's only obligation is
that no two acknowledged ids are equal — total availability is
trivially reachable (a node can mint from local state alone), which is
exactly why the workload makes a good first custom one: the protocol
is one RPC, and all the interest lives in the checker."""

from __future__ import annotations

from .. import generators as g
from .. import schema as S
from ..checkers.unique_ids import UniqueIdsChecker
from ..client import defrpc
from . import BaseClient

generate_rpc = defrpc(
    "generate",
    "Asks a node to generate a globally unique identifier. Servers "
    "respond with a `generate_ok` carrying the fresh id in `id`; any "
    "JSON value is a legal id, and two acknowledged ids must never be "
    "equal — across nodes, clients, and time.",
    {"type": S.Eq("generate")},
    {"type": S.Eq("generate_ok"), "id": S.Any},
    ns="maelstrom_tpu.workloads.unique_ids")


class UniqueIdsClient(BaseClient):
    def invoke(self, test, op):
        def go():
            res = generate_rpc(self.conn, self.node, {})
            return {**op, "type": "ok", "value": res["id"]}
        return self.with_errors(op, set(), go)


def workload(opts: dict) -> dict:
    return {
        "client": UniqueIdsClient(opts["net"]),
        "generator": g.Repeat({"f": "generate"}),
        "checker": UniqueIdsChecker(),
    }
