"""Batched atomic broadcast workload (serving
`nodes/broadcast_batched.py`; doc/perf.md "batched atomic broadcast").

The Chop Chop-shaped sibling of the broadcast workload: client values
aggregate into *distilled* batches on the sending side — the columnar
batch assembler (`generators.BatchCounting`) dedups and sorts each raw
submission burst in one numpy pass — and one batch rides ONE simulated
network message. Receivers expand batches under a server-side expansion
proof, and `BatchedBroadcastChecker` both audits every proof and grades
the expanded per-value stream with the stock set-full fold (verdict
bit-equal to the unbatched broadcast checker on the same op stream).

TPU-path only: batching is a property of the built-in batched node's
wire format; the bin path's JSON protocol has no batch RPC."""

from __future__ import annotations

from .. import generators as g
from ..checkers.set_full import BatchedBroadcastChecker
from . import BaseClient


class BatchedBroadcastClient(BaseClient):
    def invoke(self, test, op):
        raise RuntimeError(
            "broadcast-batched is a TPU-path workload "
            "(--node tpu:broadcast-batched); the bin path has no "
            "distilled-batch RPC")


def workload(opts: dict) -> dict:
    batch_max = int(opts.get("batch_max") or 16)
    dup_rate = float(opts.get("batch_dup_rate", 0.25))
    return {
        "client": BatchedBroadcastClient(opts["net"]),
        "generator": g.mix([
            g.BatchCounting(batch_max=batch_max, dup_rate=dup_rate,
                            seed=int(opts.get("seed", 0))),
            g.Repeat({"f": "read"})]),
        "final_generator": g.each_thread({"f": "read", "final": True}),
        "checker": BatchedBroadcastChecker(),
    }
