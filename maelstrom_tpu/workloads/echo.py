"""Echo workload: send a message, expect the same payload back
(reference `src/maelstrom/workload/echo.clj`)."""

from __future__ import annotations

import random

from .. import generators as g
from .. import schema as S
from ..client import defrpc
from ..checkers.echo import EchoChecker
from . import BaseClient

echo_rpc = defrpc(
    "echo",
    "Clients send `echo` messages to servers with an `echo` field containing "
    "an arbitrary payload they'd like to have sent back. Servers should "
    "respond with `echo_ok` messages containing that same payload.",
    {"type": S.Eq("echo"), "echo": S.Any},
    {"type": S.Eq("echo_ok"), "echo": S.Any},
    ns="maelstrom_tpu.workloads.echo")


class EchoClient(BaseClient):
    def invoke(self, test, op):
        def go():
            res = echo_rpc(self.conn, self.node, {"echo": op["value"]})
            return {**op, "type": "ok", "value": res}
        return self.with_errors(op, set(), go)


class EchoOpGen:
    """Picklable op source (generator trees checkpoint/resume)."""

    def __init__(self, seed: int):
        self.rng = random.Random(seed)

    def __call__(self):
        return {"f": "echo", "value": f"Please echo {self.rng.randrange(128)}"}


def workload(opts: dict) -> dict:
    return {
        "client": EchoClient(opts["net"]),
        "generator": g.Fn(EchoOpGen(opts.get("seed", 0))),
        "checker": EchoChecker(),
    }
