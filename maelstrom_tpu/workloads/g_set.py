"""Grow-only set workload (reference `src/maelstrom/workload/g_set.clj`)."""

from __future__ import annotations

from .. import generators as g
from .. import schema as S
from ..client import defrpc
from ..checkers.set_full import SetFullChecker
from . import BaseClient

add_rpc = defrpc(
    "add",
    "Requests that a server add a single element to the set. Acknowledged "
    "by an `add_ok` message.",
    {"type": S.Eq("add"), "element": S.Any},
    {"type": S.Eq("add_ok")},
    ns="maelstrom_tpu.workloads.g_set")

read_rpc = defrpc(
    "read",
    "Requests the current set of all elements. Servers respond with a "
    "message containing an `elements` key, whose `value` is a JSON array of "
    "added elements.",
    {"type": S.Eq("read")},
    {"type": S.Eq("read_ok"), "value": [S.Any]},
    ns="maelstrom_tpu.workloads.g_set")


class GSetClient(BaseClient):
    def invoke(self, test, op):
        def go():
            if op["f"] == "add":
                add_rpc(self.conn, self.node, {"element": op["value"]})
                return {**op, "type": "ok"}
            res = read_rpc(self.conn, self.node, {})
            return {**op, "type": "ok", "value": res["value"]}
        return self.with_errors(op, {"read"}, go)


def workload(opts: dict) -> dict:
    return {
        "client": GSetClient(opts["net"]),
        "generator": g.mix([
            g.Counting("add"),
            g.Repeat({"f": "read"})]),
        "final_generator": g.each_thread({"f": "read", "final": True}),
        "checker": SetFullChecker(),
    }
