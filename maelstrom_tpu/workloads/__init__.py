"""Workloads: each exposes `workload(opts) -> {"client": ..., "generator":
..., "final_generator": ..., "checker": ...}` exactly like the reference
(`workload/echo.clj:65-76` etc.). The registry mirrors `core.clj:30-38`."""

from __future__ import annotations


def registry() -> dict:
    from . import (broadcast, broadcast_batched, echo, g_counter, g_set,
                   kafka, lin_kv, lin_mutex, lin_tso, pn_counter,
                   txn_list_append, txn_rw_register, unique_ids)
    return {
        "lin-mutex": lin_mutex.workload,
        "lin-tso": lin_tso.workload,
        "broadcast": broadcast.workload,
        "broadcast-batched": broadcast_batched.workload,
        "echo": echo.workload,
        "g-set": g_set.workload,
        "g-counter": g_counter.workload,
        "pn-counter": pn_counter.workload,
        "lin-kv": lin_kv.workload,
        "txn-list-append": txn_list_append.workload,
        "unique-ids": unique_ids.workload,
        "kafka": kafka.workload,
        "txn-rw-register": txn_rw_register.workload,
    }


class BaseClient:
    """Shared shape for workload clients (reference jepsen client/Client):
    open(test, node) -> live client; setup(test); invoke(test, op) ->
    completed op; close()."""

    def __init__(self, net, conn=None, node=None):
        self.net = net
        self.conn = conn
        self.node = node
        self.retry = None       # RetryPolicy from test opts (open())

    def open(self, test, node):
        from ..client import RetryPolicy, SyncClient
        c = type(self)(self.net, SyncClient(self.net), node)
        c.retry = RetryPolicy.from_test(test, salt=c.conn.node_id)
        return c

    def with_errors(self, op, idempotent, thunk):
        """`client.with_errors` with this client's retry policy wired
        in: when --client-retries is set, unavailability failures back
        off exponentially (with jitter and a cap) and re-issue instead
        of surrendering to the RPC timeout."""
        from ..client import with_errors
        return with_errors(op, idempotent, thunk, retry=self.retry)

    def setup(self, test):
        pass

    def invoke(self, test, op):
        raise NotImplementedError

    def close(self):
        if self.conn is not None:
            self.conn.close()
