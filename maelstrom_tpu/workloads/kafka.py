"""Kafka-style replicated log workload (classic Maelstrom's `kafka`,
beyond the reference's seven workloads).

Clients append messages to per-key logs (`send`, acked with the
assigned offset), read logs back (`poll` — servers return each
requested key's full prefix so every poll is a complete observation),
and track consumption (`commit_offsets` / `list_committed_offsets`).
Graded by `checkers/kafka.py`: offset assignments must never diverge,
polls must be ordered and never lose an acknowledged write, and
committed offsets must be monotone."""

from __future__ import annotations

import random

from .. import generators as g
from .. import schema as S
from ..checkers.kafka import KafkaChecker
from ..client import defrpc
from . import BaseClient

send_rpc = defrpc(
    "send",
    "Appends `msg` to the log named `key`. Servers assign the next "
    "offset in that log and reply `send_ok` with it; two acknowledged "
    "sends may never share a (key, offset), and an assignment is "
    "permanent.",
    {"type": S.Eq("send"), "key": S.Any, "msg": S.Any},
    {"type": S.Eq("send_ok"), "offset": S.Any},
    ns="maelstrom_tpu.workloads.kafka")

poll_rpc = defrpc(
    "poll",
    "Requests the contents of the logs named in `keys`. Servers reply "
    "`poll_ok` with `msgs`: for each key, the list of [offset, msg] "
    "pairs from the head of that log, in strictly increasing offset "
    "order.",
    {"type": S.Eq("poll"), "keys": [S.Any]},
    {"type": S.Eq("poll_ok"), "msgs": S.Any},
    ns="maelstrom_tpu.workloads.kafka")

commit_rpc = defrpc(
    "commit_offsets",
    "Records that the client has consumed each named log up to the "
    "given offset. Committed offsets only ever advance.",
    {"type": S.Eq("commit_offsets"), "offsets": S.Any},
    {"type": S.Eq("commit_offsets_ok")},
    ns="maelstrom_tpu.workloads.kafka")

list_rpc = defrpc(
    "list_committed_offsets",
    "Requests the committed offset of each named log; replies "
    "`list_committed_offsets_ok` with an `offsets` map (keys with no "
    "commit yet may be omitted).",
    {"type": S.Eq("list_committed_offsets"), "keys": [S.Any]},
    {"type": S.Eq("list_committed_offsets_ok"), "offsets": S.Any},
    ns="maelstrom_tpu.workloads.kafka")


class KafkaClient(BaseClient):
    """Workers poll everything (full observation) and commit what they
    have seen: `last_polled` tracks each key's max polled offset, so a
    commit claims exactly what this worker actually consumed."""

    def __init__(self, net, conn=None, node=None, keys=4):
        super().__init__(net, conn, node)
        self.keys = keys
        self.last_polled: dict = {}

    def open(self, test, node):
        from ..client import RetryPolicy, SyncClient
        c = type(self)(self.net, SyncClient(self.net), node,
                       keys=self.keys)
        c.retry = RetryPolicy.from_test(test, salt=c.conn.node_id)
        return c

    def invoke(self, test, op):
        if op["f"] == "subscribe":
            raise RuntimeError(
                "kafka consumer groups (--kafka-groups) are a TPU-path "
                "protocol (--node tpu:kafka); the bin-path client "
                "speaks the classic full-prefix workload only")
        key_names = [str(k) for k in range(self.keys)]

        def go():
            if op["f"] == "send":
                k, m = op["value"]
                res = send_rpc(self.conn, self.node,
                               {"key": str(k), "msg": m})
                return {**op, "type": "ok",
                        "value": [str(k), m, res["offset"]]}
            if op["f"] == "poll":
                res = poll_rpc(self.conn, self.node, {"keys": key_names})
                msgs = res["msgs"]
                for k, pairs in msgs.items():
                    if pairs:
                        self.last_polled[k] = max(
                            self.last_polled.get(k, -1),
                            max(int(p[0]) for p in pairs))
                return {**op, "type": "ok", "value": msgs}
            if op["f"] == "commit":
                offs = dict(self.last_polled)
                # always round-trip, even with an empty offsets map —
                # every ok in the history must correspond to a real
                # server ack (an empty commit raises no offset floor,
                # but fabricating the ok would skew op counts/latency)
                commit_rpc(self.conn, self.node, {"offsets": offs})
                return {**op, "type": "ok", "value": offs}
            res = list_rpc(self.conn, self.node, {"keys": key_names})
            return {**op, "type": "ok", "value": res["offsets"]}
        return self.with_errors(op, {"poll", "list"}, go)


class KafkaOpGen:
    """Picklable op source: weighted mix of sends (per-key counters so
    every message is unique), polls, commits, and committed-offset
    reads."""

    def __init__(self, seed: int, keys: int = 4):
        self.rng = random.Random(seed)
        self.keys = keys
        self.counter = 0

    def __call__(self):
        r = self.rng.random()
        if r < 0.5:
            self.counter += 1
            k = self.counter % self.keys
            return {"f": "send", "value": [k, self.counter]}
        if r < 0.8:
            return {"f": "poll"}
        if r < 0.9:
            return {"f": "commit"}
        return {"f": "list"}


class KafkaStreamOpGen:
    """Group-mode op source (doc/streams.md): explicit subscribes join
    the worker's consumer group (first polls auto-subscribe too), polls
    become cursor fetches over the member's assigned keys, commits
    claim exactly what the member consumed (and double as the group
    heartbeat), lists read the group's committed floors."""

    def __init__(self, seed: int, keys: int = 4):
        self.rng = random.Random(seed)
        self.keys = keys
        self.counter = 0

    def __call__(self):
        r = self.rng.random()
        if r < 0.05:
            return {"f": "subscribe"}
        if r < 0.5:
            self.counter += 1
            k = self.counter % self.keys
            return {"f": "send", "value": [k, self.counter]}
        if r < 0.8:
            return {"f": "poll"}
        if r < 0.95:
            return {"f": "commit"}
        return {"f": "list"}


def workload(opts: dict) -> dict:
    keys = int(opts.get("key_count") or 4)
    groups = int(opts.get("kafka_groups") or 0)
    op_gen = (KafkaStreamOpGen(opts.get("seed", 0), keys) if groups
              else KafkaOpGen(opts.get("seed", 0), keys))
    return {
        "client": KafkaClient(opts["net"], keys=keys),
        "generator": g.Fn(op_gen),
        "checker": KafkaChecker(),
    }
