"""Linearizable key-value workload
(reference `src/maelstrom/workload/lin_kv.clj`)."""

from __future__ import annotations

import random

from .. import generators as g
from .. import schema as S
from ..client import defrpc
from ..errors import deferror
from ..checkers.linearizable import LinearizableRegisterChecker
from . import BaseClient

# KV errors are defined by this workload (reference lin_kv.clj:12-27)
deferror(20, "key-does-not-exist",
         "The client requested an operation on a key which does not exist "
         "(assuming the operation should not automatically create missing "
         "keys).",
         definite=True, ns="maelstrom_tpu.workloads.lin_kv")
deferror(21, "key-already-exists",
         "The client requested the creation of a key which already exists, "
         "and the server will not overwrite it.",
         definite=True, ns="maelstrom_tpu.workloads.lin_kv")
deferror(22, "precondition-failed",
         "The requested operation expected some conditions to hold, and "
         "those conditions were not met. For instance, a compare-and-set "
         "operation might assert that the value of a key is currently 5; if "
         "the value is 3, the server would return `precondition-failed`.",
         definite=True, ns="maelstrom_tpu.workloads.lin_kv")

read_rpc = defrpc(
    "read",
    "Reads the current value of a single key. Clients send a `read` request "
    "with the key they'd like to observe, and expect a response with the "
    "current `value` of that key.",
    {"type": S.Eq("read"), "key": S.Any},
    {"type": S.Eq("read_ok"), "value": S.Any},
    ns="maelstrom_tpu.workloads.lin_kv")

write_rpc = defrpc(
    "write",
    "Blindly overwrites the value of a key. Creates keys if they do not "
    "presently exist. Servers should respond with a `read_ok` response once "
    "the write is complete.",
    {"type": S.Eq("write"), "key": S.Any, "value": S.Any},
    {"type": S.Eq("write_ok")},
    ns="maelstrom_tpu.workloads.lin_kv")

cas_rpc = defrpc(
    "cas",
    "Atomically compare-and-sets a single key: if the value of `key` is "
    "currently `from`, sets it to `to`. Returns error 20 if the key doesn't "
    "exist, and 22 if the `from` value doesn't match.",
    {"type": S.Eq("cas"), "key": S.Any, "from": S.Any, "to": S.Any},
    {"type": S.Eq("cas_ok")},
    ns="maelstrom_tpu.workloads.lin_kv")


class LinKVClient(BaseClient):
    def invoke(self, test, op):
        k, v = op["value"]
        # Timeout scaled to latency (reference lin_kv.clj:71)
        timeout = max(10 * test.get("latency", {}).get("mean", 0), 1000)

        def go():
            if op["f"] == "read":
                res = read_rpc(self.conn, self.node, {"key": k}, timeout)
                return {**op, "type": "ok", "value": [k, res["value"]]}
            if op["f"] == "write":
                write_rpc(self.conn, self.node, {"key": k, "value": v},
                          timeout)
                return {**op, "type": "ok"}
            frm, to = v
            cas_rpc(self.conn, self.node,
                    {"key": k, "from": frm, "to": to}, timeout)
            return {**op, "type": "ok"}
        return self.with_errors(op, {"read"}, go)


class KVOpGen:
    """Independent per-key register ops, rotating through keys like
    jepsen.independent/concurrent-generator: each key sees a bounded number
    of ops, then a fresh key starts. Picklable (checkpoint/resume)."""

    def __init__(self, seed: int, ops_per_key: int):
        self.rng = random.Random(seed)
        self.ops_per_key = ops_per_key
        self.n = 0

    def __call__(self):
        key = self.n // self.ops_per_key
        self.n += 1
        r = self.rng.random()
        if r < 0.5:
            return {"f": "read", "value": [key, None]}
        if r < 0.8:
            return {"f": "write", "value": [key, self.rng.randrange(5)]}
        return {"f": "cas",
                "value": [key, [self.rng.randrange(5),
                                self.rng.randrange(5)]]}


def generator(opts):
    return g.Fn(KVOpGen(opts.get("seed", 0), opts.get("ops_per_key", 40)))


def workload(opts: dict) -> dict:
    return {
        "client": LinKVClient(opts["net"]),
        "generator": generator(opts),
        "checker": LinearizableRegisterChecker(),
    }
