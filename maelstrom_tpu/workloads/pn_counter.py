"""PN-counter workload: eventually-consistent counter with increments and
decrements (reference `src/maelstrom/workload/pn_counter.clj`)."""

from __future__ import annotations

import random

from .. import generators as g
from .. import schema as S
from ..client import defrpc
from ..checkers.pn_counter import PNCounterChecker
from . import BaseClient

add_rpc = defrpc(
    "add",
    "Adds a (potentially negative) integer, called `delta`, to the counter. "
    "Servers should respond with an `add_ok` message.",
    {"type": S.Eq("add"), "delta": int},
    {"type": S.Eq("add_ok")},
    ns="maelstrom_tpu.workloads.pn_counter")

read_rpc = defrpc(
    "read",
    "Reads the current value of the counter. Servers respond with a "
    "`read_ok` message containing a `value`, which should be the sum of all "
    "(known) added deltas.",
    {"type": S.Eq("read")},
    {"type": S.Eq("read_ok"), "value": int},
    ns="maelstrom_tpu.workloads.pn_counter")


class PNCounterClient(BaseClient):
    def invoke(self, test, op):
        def go():
            if op["f"] == "add":
                add_rpc(self.conn, self.node, {"delta": op["value"]})
                return {**op, "type": "ok"}
            res = read_rpc(self.conn, self.node, {})
            return {**op, "type": "ok", "value": int(res["value"])}
        return self.with_errors(op, {"read"}, go)


class AddOpGen:
    """Picklable op source: add with delta in [-5, 4]
    (reference `pn_counter.clj:127-133`)."""

    def __init__(self, seed: int):
        self.rng = random.Random(seed)

    def __call__(self):
        return {"f": "add", "value": self.rng.randint(-5, 4)}


def workload(opts: dict) -> dict:
    return {
        "client": PNCounterClient(opts["net"]),
        "generator": g.mix([
            g.Fn(AddOpGen(opts.get("seed", 0))),
            g.Repeat({"f": "read"})]),
        "final_generator": g.each_thread({"f": "read", "final": True}),
        "checker": PNCounterChecker(),
    }
