"""Transactional list-append workload
(reference `src/maelstrom/workload/txn_list_append.clj`).

Transactions are arrays of micro-ops `[f, k, v]` where f is "r" (read,
submitted with v=null, completed with the observed list) or "append".
Nonexistent keys read as null; lists are created implicitly on append.
"""

from __future__ import annotations

import random

from .. import generators as g
from .. import schema as S
from ..client import defrpc
from ..errors import deferror
from ..checkers.elle import ElleListAppendChecker
from . import BaseClient

deferror(30, "txn-conflict",
         "The requested transaction has been aborted because of a conflict "
         "with another transaction. Servers need not return this error on "
         "every conflict: they may choose to retry automatically instead.",
         definite=True, ns="maelstrom_tpu.workloads.txn_list_append")

ReadReq = S.Tup(S.Eq("r"), S.Any, S.Eq(None))
ReadRes = S.Tup(S.Eq("r"), S.Any, S.Maybe([S.Any]))
Append = S.Tup(S.Eq("append"), S.Any, S.Any)

txn_rpc = defrpc(
    "txn",
    "Requests that the node execute a single transaction. Servers respond "
    "with a `txn_ok` message, and a completed version of the requested "
    "transaction; e.g. with read values filled in. Keys and list elements "
    "may be of any type.",
    {"type": S.Eq("txn"), "txn": [S.Either(ReadReq, Append)]},
    {"type": S.Eq("txn_ok"), "txn": [S.Either(ReadRes, Append)]},
    ns="maelstrom_tpu.workloads.txn_list_append")


class TxnClient(BaseClient):
    def invoke(self, test, op):
        def go():
            res = txn_rpc(self.conn, self.node,
                          {"txn": [list(m) for m in op["value"]]})
            return {**op, "type": "ok",
                    "value": [list(m) for m in res["txn"]]}
        return self.with_errors(op, set(), go)


class TxnOpGen:
    """Random transactions over a sliding window of keys, honoring
    --key-count, --max-txn-length, --max-writes-per-key
    (reference `txn_list_append.clj:112-124` via jepsen append/test).
    Picklable (checkpoint/resume)."""

    def __init__(self, opts: dict):
        self.rng = random.Random(opts.get("seed", 0))
        self.key_count = opts.get("key_count") or 10
        self.max_txn_length = opts.get("max_txn_length", 4)
        self.min_txn_length = opts.get("min_txn_length", 1)
        self.max_writes = opts.get("max_writes_per_key", 16)
        self.base = 0
        self.appends: dict = {}

    def _next_value(self, k):
        self.appends[k] = self.appends.get(k, 0) + 1
        if self.appends[k] >= self.max_writes:
            # retire the oldest active key by advancing the window
            self.base += 1
        return self.appends[k]

    def __call__(self):
        length = self.rng.randint(self.min_txn_length, self.max_txn_length)
        txn = []
        for _ in range(length):
            k = self.base + self.rng.randrange(self.key_count)
            if self.rng.random() < 0.5:
                txn.append(["r", k, None])
            else:
                txn.append(["append", k, self._next_value(k)])
        return {"f": "txn", "value": txn}


def generator(opts):
    return g.Fn(TxnOpGen(opts))


def workload(opts: dict) -> dict:
    return {
        "client": TxnClient(opts["net"]),
        "generator": generator(opts),
        "checker": ElleListAppendChecker(
            opts.get("consistency_models", ["strict-serializable"]),
            device=opts.get("device_checker")),
    }
