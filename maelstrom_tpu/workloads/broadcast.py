"""Broadcast workload: eventually-consistent set addition with an initial
topology message (reference `src/maelstrom/workload/broadcast.clj`).

Topology generators (grid/line/total/tree2/3/4, reference
`broadcast.clj:39-177`) are produced both as node-id maps (the protocol
surface) and, for the TPU path, as dense neighbor index arrays."""

from __future__ import annotations

import math

from .. import generators as g
from .. import schema as S
from ..client import defrpc
from ..checkers.set_full import BroadcastChecker
from . import BaseClient

NodeId = str

topology_rpc = defrpc(
    "topology",
    "A topology message is sent at the start of the test, after "
    "initialization, and informs the node of an optional network topology "
    "to use for broadcast. The topology consists of a map of node IDs to "
    "lists of neighbor node IDs.",
    {"type": S.Eq("topology"), "topology": {str: [S.Any]}},
    {"type": S.Eq("topology_ok")},
    ns="maelstrom_tpu.workloads.broadcast")

broadcast_rpc = defrpc(
    "broadcast",
    "Sends a single message into the broadcast system, and requests that it "
    "be broadcast to everyone. Nodes respond with a simple acknowledgement "
    "message.",
    {"type": S.Eq("broadcast"), "message": S.Any},
    {"type": S.Eq("broadcast_ok")},
    ns="maelstrom_tpu.workloads.broadcast")

read_rpc = defrpc(
    "read",
    "Requests all messages present on a node.",
    {"type": S.Eq("read")},
    {"type": S.Eq("read_ok"), "messages": [S.Any]},
    ns="maelstrom_tpu.workloads.broadcast")


# --- Topologies (reference broadcast.clj:39-177) ---

def grid_topology(nodes):
    """Roughly-square grid; each node has at most 4 neighbors
    (reference `broadcast.clj:39-64`)."""
    nodes = list(nodes)
    n = len(nodes)
    side = math.ceil(math.sqrt(n))

    def node(i, j):
        if 0 <= i and 0 <= j < side:
            idx = i * side + j
            if idx < n:
                return nodes[idx]
        return None

    topo = {}
    for i in range(side):
        for j in range(side):
            me = node(i, j)
            if me is None:
                continue
            topo[me] = [x for x in (node(i + 1, j), node(i - 1, j),
                                    node(i, j + 1), node(i, j - 1))
                        if x is not None]
    return topo


def line_topology(nodes):
    """All nodes in a single line (reference `broadcast.clj:66-79`)."""
    nodes = list(nodes)
    n = len(nodes)
    if n < 2:
        return {nodes[0]: []} if nodes else {}
    topo = {nodes[0]: [nodes[1]], nodes[-1]: [nodes[-2]]}
    for i in range(1, n - 1):
        topo[nodes[i]] = [nodes[i - 1], nodes[i + 1]]
    return topo


def total_topology(nodes):
    """Every node connected to every other (reference
    `broadcast.clj:81-88`)."""
    nodes = list(nodes)
    return {me: [x for x in nodes if x != me] for me in nodes}


def tree_topology(b, nodes):
    """A b-ary tree laid out breadth-first; neighbors = parent + children
    (reference `broadcast.clj:90-166`)."""
    nodes = list(nodes)
    n = len(nodes)
    if n == 0:
        return {}
    topo = {me: [] for me in nodes}
    for i, me in enumerate(nodes):
        if i > 0:
            parent = nodes[(i - 1) // b]
            topo[me].append(parent)
        for c in range(b * i + 1, min(b * i + b + 1, n)):
            topo[me].append(nodes[c])
    return topo


TOPOLOGIES = {
    "line": line_topology,
    "grid": grid_topology,
    "tree": lambda ns: tree_topology(2, ns),
    "tree2": lambda ns: tree_topology(2, ns),
    "tree3": lambda ns: tree_topology(3, ns),
    "tree4": lambda ns: tree_topology(4, ns),
    "total": total_topology,
}


def topology(test) -> dict:
    """Topology map for the test's nodes (reference
    `broadcast.clj:179-184`)."""
    return TOPOLOGIES[test.get("topology", "grid")](test["nodes"])


def topology_indices(topo: dict, nodes: list[str], max_degree=None):
    """Dense [n, max_degree] neighbor index array (padded with -1) for the
    TPU path."""
    import numpy as np
    idx = {n: i for i, n in enumerate(nodes)}
    deg = max((len(v) for v in topo.values()), default=0)
    if max_degree is not None:
        deg = max(deg, max_degree)
    out = np.full((len(nodes), max(deg, 1)), -1, dtype=np.int32)
    for n, neighbors in topo.items():
        for j, m in enumerate(neighbors):
            out[idx[n], j] = idx[m]
    return out


class BroadcastClient(BaseClient):
    def setup(self, test):
        topo = topology(test)
        topology_rpc(self.conn, self.node,
                     {"topology": {k: list(v) for k, v in topo.items()}})

    def invoke(self, test, op):
        def go():
            if op["f"] == "broadcast":
                broadcast_rpc(self.conn, self.node, {"message": op["value"]})
                return {**op, "type": "ok"}
            res = read_rpc(self.conn, self.node, {})
            return {**op, "type": "ok", "value": res["messages"]}
        return self.with_errors(op, {"read"}, go)


def workload(opts: dict) -> dict:
    return {
        "client": BroadcastClient(opts["net"]),
        "generator": g.mix([
            g.Counting("broadcast"),
            g.Repeat({"f": "read"})]),
        "final_generator": g.each_thread({"f": "read", "final": True}),
        "checker": BroadcastChecker(),
    }
