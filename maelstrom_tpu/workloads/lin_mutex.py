"""Distributed-lock workload: mutual exclusion graded by the
holder-aware mutex model (`checkers/linearizable.MutexModel` — the
Knossos knossos.model/mutex role; one workload beyond the reference's
set, exercising the generalized WGL engine on a non-register model).

The lock IS a lin-kv register: the well-known key `LOCK_KEY` holds
`FREE` or a holder id, and clients contend with the standard cas RPC —
acquire = cas(FREE → 2+worker), release = cas(2+worker → FREE). That
means any server speaking the lin-kv surface serves this workload
unchanged, on both paths (`--node tpu:lin-kv`, or any `--bin` lin-kv
node like the raft demo) — the same way the reference's demos build
locks over its lin-kv service.

Histories are graded twice over the SAME ops:
  - the cas ops under the per-key WGL register checker (the server
    kept register semantics), and
  - mapped to acquire/release under the mutex model (mutual exclusion
    held: no two holders at once, no release by a non-holder).

Threads alternate acquire/release blindly: a failed acquire (22,
definite) makes the following release fail too — both excluded from
the WGL search, exactly the may-not-have-happened semantics the model
expects. An init phase writes FREE once before contention starts."""

from __future__ import annotations

from .. import generators as g
from .. import schema as S
from ..checkers import Checker
from ..client import defrpc
from ..checkers.linearizable import (INF, LinearizableRegisterChecker,
                                     MutexModel, check_history)
from ..history import coerce_history
from . import lin_kv

LOCK_KEY = 0
FREE = 1          # 0 is "absent" on the raft wire; FREE must be a value

# Doc-only RPC registrations (the live client reuses lin-kv's cas; the
# g-counter workload documents its pn-counter reuse the same way):
# these record the lock conventions in doc/workloads.md.
defrpc(
    "cas",
    "Acquire and release are both the lin-kv `cas` RPC on the "
    f"well-known lock key {LOCK_KEY}: acquire = cas(from={FREE} (free) "
    "-> holder id), release = cas(from=holder id -> free). A server "
    "speaking the lin-kv surface serves this workload unchanged; the "
    "checker grades the cas history under both the register model and "
    "the holder-aware mutex model.",
    {"type": S.Eq("cas"), "key": S.Any, "from": S.Any, "to": S.Any},
    {"type": S.Eq("cas_ok")},
    ns="maelstrom_tpu.workloads.lin_mutex")

defrpc(
    "write",
    f"Initialization: one retried write of the free value ({FREE}) to "
    f"the lock key before contention starts (the init phase).",
    {"type": S.Eq("write"), "key": S.Any, "value": S.Any},
    {"type": S.Eq("write_ok")},
    ns="maelstrom_tpu.workloads.lin_mutex")


class UntilOk(g.Gen):
    """Re-emits `op_map` (one attempt in flight at a time) until an
    attempt completes ok; used for the init write, which fails fast
    with error 11 while the cluster is still electing. An attempt
    graded info may still apply later — schedule nemeses after the
    init phase (the default nemesis interval does), or the late
    re-apply can reset the lock mid-contention."""

    def __init__(self, op_map: dict, in_flight: bool = False,
                 done: bool = False):
        self.op_map = op_map
        self.in_flight = in_flight
        self.done = done

    def op(self, ctx):
        if self.done:
            return None, self
        if self.in_flight:
            return g.PENDING, self
        free = g.free_clients(ctx)
        if not free:
            return g.PENDING, self
        return (g.fill_op(dict(self.op_map), ctx, free[0]),
                UntilOk(self.op_map, True, False))

    def update(self, ctx, event):
        if (self.done or not self.in_flight
                or event.get("f") != self.op_map["f"]
                or event.get("value") != self.op_map.get("value")):
            return self
        return UntilOk(self.op_map, False, event.get("type") == "ok")


class LockScriptGen(g.Gen):
    """Per-process alternating acquire/release cas script (picklable).
    Each process's holder id is stable across timeouts: jepsen-style
    process bumping keeps `p % workers` the worker lineage."""

    def __init__(self, counts: dict | None = None):
        self.counts = counts or {}

    def op(self, ctx):
        free = g.free_clients(ctx)
        if not free:
            return g.PENDING, self
        p = free[0]
        workers = max(len(g.client_processes(ctx)), 1)
        holder = 2 + (p % workers) % 250     # 8-bit wire value headroom
        i = self.counts.get(p, 0)
        val = ([LOCK_KEY, [FREE, holder]] if i % 2 == 0
               else [LOCK_KEY, [holder, FREE]])
        op = g.fill_op({"f": "cas", "value": val}, ctx, p)
        return op, LockScriptGen({**self.counts, p: i + 1})


def _mutex_ops(history):
    ops = []
    for invoke, complete in history.pairs():
        if invoke.f != "cas":
            continue                      # the init write, reads
        if complete is not None and complete.is_fail():
            continue
        ok = complete is not None and complete.is_ok()
        _k, (frm, to) = invoke.value
        if frm == FREE and to != FREE:
            f, holder = "acquire", to
        elif to == FREE and frm != FREE:
            f, holder = "release", frm
        else:
            continue
        ops.append({"f": f, "value": holder, "inv": invoke.time,
                    "ret": complete.time if ok else INF, "ok": ok})
    return ops


class LinMutexChecker(Checker):
    """Mutual exclusion via the holder-aware mutex model, plus the
    register-level WGL check of the same cas history."""

    name = "lin-mutex"
    # delegates to LinearizableRegisterChecker, which consumes the
    # overlapped pipeline's partitions when the runner provides them
    consumes_analysis = True

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        ops = _mutex_ops(history)
        mutex = check_history(ops, MutexModel())
        register = LinearizableRegisterChecker().check(test, history,
                                                       opts)
        valid = (False if (mutex["valid"] is False
                           or register["valid"] is False) else
                 ("unknown" if "unknown" in (mutex["valid"],
                                             register["valid"])
                  else True))
        out = {"valid": valid,
               "acquire-release-ops": len(ops),
               "mutex": mutex,
               "register": register}
        if not ops and out["valid"] is True:
            # found anomalies dominate unknown; only a clean-but-empty
            # history downgrades
            out["valid"] = "unknown"
            out["error"] = "no acquire/release ever completed"
        return out


def workload(opts: dict) -> dict:
    return {
        "client": lin_kv.LinKVClient(opts["net"]),
        "generator": g.phases(
            UntilOk({"f": "write", "value": [LOCK_KEY, FREE]}),
            LockScriptGen()),
        "checker": LinMutexChecker(),
    }
