"""Linearizable timestamp-oracle workload (the reference's built-in
`lin-tso` service, `service.clj:116-122` / `service.clj:289-295`).

Clients request timestamps; the oracle must hand out unique,
real-time-monotonic values (`checkers/tso.py`). On the TPU path this is
served by the role-partitioned services cluster
(`-w lin-tso --node tpu:services`, nodes/services.py)."""

from __future__ import annotations

from .. import generators as g
from .. import schema as S
from ..client import defrpc
from ..checkers.tso import TSOChecker
from . import BaseClient

ts_rpc = defrpc(
    "ts",
    "Requests a fresh timestamp from the oracle. The response carries a "
    "unique, strictly monotonic `ts`: if one request completes before "
    "another begins, the earlier request's timestamp is smaller.",
    {"type": S.Eq("ts")},
    {"type": S.Eq("ts_ok"), "ts": S.Any},
    ns="maelstrom_tpu.workloads.lin_tso")


class LinTSOClient(BaseClient):
    def invoke(self, test, op):
        def go():
            res = ts_rpc(self.conn, self.node, {}, 1000)
            return {**op, "type": "ok", "value": res["ts"]}
        return self.with_errors(op, {"ts"}, go)


class TSOpGen:
    """Picklable (checkpoint/resume) timestamp-request stream."""

    def __call__(self):
        return {"f": "ts", "value": None}


def generator(opts):
    return g.Fn(TSOpGen())


def workload(opts: dict) -> dict:
    return {
        "client": LinTSOClient(opts["net"]),
        "generator": generator(opts),
        "checker": TSOChecker(),
    }
