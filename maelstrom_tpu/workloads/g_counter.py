"""Grow-only counter workload: pn-counter restricted to non-negative deltas
(reference `src/maelstrom/workload/g_counter.clj:30-40`).

The add/read RPC docs are registered separately here (reference keeps
doc-only copies, `g_counter.clj:13-28`); the live RPCs are pn-counter's."""

from __future__ import annotations

from .. import generators as g
from . import pn_counter


def non_negative(op: dict) -> bool:
    """Drop negative-delta adds (picklable Filter predicate)."""
    return not (op.get("f") == "add" and op.get("value", 0) < 0)


def workload(opts: dict) -> dict:
    w = pn_counter.workload(opts)
    w["generator"] = g.Filter(non_negative, w["generator"])
    return w
