"""Grow-only counter workload: pn-counter restricted to non-negative deltas
(reference `src/maelstrom/workload/g_counter.clj:30-40`).

The add/read RPC docs are registered separately here (reference keeps
doc-only copies, `g_counter.clj:13-28`); the live RPCs are pn-counter's."""

from __future__ import annotations

from .. import generators as g
from .. import schema as S
from ..client import defrpc
from . import pn_counter

# Doc-only RPC registrations (reference `g_counter.clj:13-28`): the live
# client uses pn-counter's add/read; these document the workload's
# non-negative-delta contract in doc/workloads.md.
defrpc(
    "add",
    "Adds a non-negative integer, called `delta`, to the counter. Servers "
    "should respond with an `add_ok` message.",
    {"type": S.Eq("add"), "delta": int},
    {"type": S.Eq("add_ok")},
    ns="maelstrom_tpu.workloads.g_counter")

defrpc(
    "read",
    "Reads the current value of the counter. Servers respond with a "
    "`read_ok` message containing a `value`, which should be the sum of "
    "all (known) added deltas.",
    {"type": S.Eq("read")},
    {"type": S.Eq("read_ok"), "value": int},
    ns="maelstrom_tpu.workloads.g_counter")


def non_negative(op: dict) -> bool:
    """Drop negative-delta adds (picklable Filter predicate)."""
    return not (op.get("f") == "add" and op.get("value", 0) < 0)


def workload(opts: dict) -> dict:
    w = pn_counter.workload(opts)
    w["generator"] = g.Filter(non_negative, w["generator"])
    return w
