"""Synchronous network clients and typed RPCs.

Reimplements the reference client layer (`src/maelstrom/client.clj`):
one-outstanding-message synchronous clients with ids `c0, c1, ...`; `rpc`
send+recv with timeout (default 5000 ms); stale-reply discarding; error
interpretation via the error registry; `with_errors` mapping RPC failures to
history `fail`/`info` with idempotent-op awareness; and `defrpc` — typed,
schema-validated RPC functions that auto-register for doc generation.
"""

from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass, field

from . import schema as S
from .errors import RPCError, Timeout
from .history import FAIL, INFO

DEFAULT_TIMEOUT_MS = 5000     # reference client.clj:15-17


class SyncClient:
    """A client which can only do one thing at a time: send a message, or
    wait for a response (reference `client.clj:102-178`)."""

    def __init__(self, net):
        self.net = net
        self.node_id = f"c{next(net.next_client_id)}"
        net.add_node(self.node_id)
        self._next_msg_id = 0
        self._waiting_for = None
        self._lock = threading.Lock()

    def close(self):
        self._waiting_for = "closed"
        self.net.remove_node(self.node_id)

    def msg_id(self) -> int:
        with self._lock:
            self._next_msg_id += 1
            return self._next_msg_id

    def send(self, dest: str, body: dict) -> int:
        msg_id = body.get("msg_id") or self.msg_id()
        if self._waiting_for is not None:
            raise RuntimeError("Can't send more than one message at a time!")
        self._waiting_for = msg_id
        body = dict(body, msg_id=msg_id)
        try:
            self.net.send({"src": self.node_id, "dest": dest, "body": body})
        except Exception:
            # a failed send (e.g. node-not-found while the nemesis has
            # the destination killed) leaves nothing outstanding; the
            # client must stay usable for the next op
            self._waiting_for = None
            raise
        return msg_id

    def recv(self, timeout_ms: float = DEFAULT_TIMEOUT_MS) -> dict:
        """Waits for the reply to the outstanding msg_id, discarding stale
        replies (reference `client.clj:142-178`). Returns the full message."""
        target = self._waiting_for
        assert target is not None, "client isn't waiting for any response!"
        deadline = _time.monotonic() + timeout_ms / 1000.0
        try:
            while True:
                remaining_ms = (deadline - _time.monotonic()) * 1000.0
                msg = (self.net.recv(self.node_id, remaining_ms)
                       if remaining_ms > 0 else None)
                if msg is None:
                    if _time.monotonic() >= deadline:
                        raise Timeout()
                    continue
                if msg.body.get("in_reply_to") != target:
                    continue    # reply to something we gave up on
                return msg
        finally:
            self._waiting_for = None

    def rpc(self, dest: str, body: dict,
            timeout_ms: float = DEFAULT_TIMEOUT_MS) -> dict:
        """Send + recv, raising RPCError on error bodies
        (reference `client.clj:186-212`)."""
        self.send(dest, body)
        msg = self.recv(timeout_ms)
        rbody = msg.body
        if rbody.get("type") == "error":
            raise RPCError(rbody.get("code", 13), rbody)
        return rbody


class RetryPolicy:
    """Client-side RPC retry: truncated exponential backoff with full
    jitter and a retry budget cap. Where the client previously retried
    nothing (one attempt, then the full RPC timeout decided the op),
    a policy with a nonzero budget re-issues unavailability failures —
    sleep ~ U(0, min(cap, base * 2^attempt)) between attempts — which is
    what keeps availability up across kill/pause/partition windows.
    Configured from the CLI: --client-retries / --client-backoff-ms /
    --client-backoff-cap-ms."""

    def __init__(self, retries: int = 0, base_ms: float = 50.0,
                 cap_ms: float = 2000.0, seed=0):
        import random
        self.retries = int(retries)
        self.base_ms = float(base_ms)
        self.cap_ms = float(cap_ms)
        self.rng = random.Random(f"retry:{seed}")

    @classmethod
    def from_test(cls, test: dict, salt="") -> "RetryPolicy | None":
        """`salt` decorrelates jitter across clients (pass the client's
        own id): a fault window fails many concurrent ops at once, and
        identically-seeded policies would re-issue them in lockstep —
        a thundering herd against the recovering node, exactly what the
        jitter exists to prevent."""
        n = int(test.get("client_retries") or 0)
        if n <= 0:
            return None
        return cls(retries=n,
                   base_ms=float(test.get("client_backoff_ms") or 50.0),
                   cap_ms=float(test.get("client_backoff_cap_ms")
                                or 2000.0),
                   seed=f"{test.get('seed') or 0}:{salt}")

    def sleep(self, attempt: int):
        # shared truncated-exponential bound (runner/sessions.py): the
        # same curve the device-path redirect backoff draws from
        from .runner.sessions import trunc_exp_bound
        bound = trunc_exp_bound(self.base_ms, self.cap_ms, attempt)
        _time.sleep(self.rng.uniform(0, bound) / 1000.0)


# Definite unavailability errors: the op definitely did NOT happen, so a
# retry is safe even for non-idempotent ops (node-not-found covers RPCs
# to a crash-killed node; temporarily-unavailable covers e.g. a raft
# follower with no known leader).
RETRYABLE_DEFINITE = {1, 11}


def with_errors(op: dict, idempotent: set, thunk, retry=None):
    """Evaluates thunk() (which returns the completed op); maps RPC errors to
    completions: timeouts -> info (or fail if idempotent), definite errors ->
    fail, indefinite -> info (reference `client.clj:214-233`).

    With a RetryPolicy, unavailability failures are retried under
    exponential backoff before completing: definite unavailability
    (RETRYABLE_DEFINITE) retries for any op — it definitely didn't
    happen; timeouts and other indefinite errors retry only idempotent
    ops (re-issuing an op that may have happened would double-apply)."""
    attempt = 0
    idem = op.get("f") in idempotent
    while True:
        budget_left = retry is not None and attempt < retry.retries
        try:
            return thunk()
        except Timeout:
            if budget_left and idem:
                retry.sleep(attempt)
                attempt += 1
                continue
            t = FAIL if idem else INFO
            return {**op, "type": t, "error": "net-timeout"}
        except RPCError as e:
            retryable = (e.code in RETRYABLE_DEFINITE
                         or (not e.definite and idem))
            if budget_left and retryable:
                retry.sleep(attempt)
                attempt += 1
                continue
            t = FAIL if (e.definite or idem) else INFO
            return {**op, "type": t,
                    "error": [e.name, e.body.get("text")]}


# --- Typed RPC definitions (reference client.clj:237-331) ---

@dataclass
class RPCDef:
    ns: str
    name: str
    doc: str
    send: dict
    recv: dict


RPC_REGISTRY: list[RPCDef] = []


class MalformedRPC(Exception):
    pass


def check_body(kind: str, sch, dest, req, body):
    """Validates a request/response body, raising a rich teaching error
    (reference `client.clj:242-273`)."""
    errs = S.check(sch, body)
    if errs is None:
        return
    import json
    if kind == "send":
        head = ("Malformed RPC request. Maelstrom should have constructed a "
                "message body like:")
        verb = "sent"
    else:
        head = (f"Malformed RPC response. Maelstrom sent node {dest} the "
                f"following request:\n\n{json.dumps(req, indent=2)}\n\n"
                "And expected a response of the form:")
        verb = "received"
    raise MalformedRPC(
        f"{head}\n\n{S.format_schema(sch)}\n\n... but instead {verb}\n\n"
        f"{json.dumps(body, indent=2, default=str)}\n\nThis is malformed "
        f"because:\n\n{json.dumps(errs, indent=2, default=str)}\n\n"
        "See doc/protocol.md for more guidance.")


def send_schema(sch: dict) -> dict:
    return {**sch, "msg_id": int}


def recv_schema(sch: dict) -> dict:
    return {**sch, S.Optional("msg_id"): int, "in_reply_to": int}


def defrpc(name: str, doc: str, send: dict, recv: dict, ns: str):
    """Defines a typed RPC call: returns fn(client, dest, body, timeout_ms)
    which stamps the message type, validates both directions, and performs
    the RPC. Registers the spec for doc generation
    (reference `client.clj:289-331`)."""
    full_send = send_schema(send)
    full_recv = recv_schema(recv)
    msg_type = send["type"].value
    assert isinstance(msg_type, str)
    RPC_REGISTRY.append(RPCDef(ns=ns, name=name, doc=doc,
                               send=full_send, recv=full_recv))

    def rpc_fn(client: SyncClient, dest: str, body: dict,
               timeout_ms: float = DEFAULT_TIMEOUT_MS) -> dict:
        body = dict(body, type=msg_type, msg_id=client.msg_id())
        check_body("send", full_send, dest, body, body)
        res = client.rpc(dest, body, timeout_ms)
        check_body("recv", full_recv, dest, body, res)
        return res

    rpc_fn.__name__ = name
    rpc_fn.__doc__ = doc
    return rpc_fn
