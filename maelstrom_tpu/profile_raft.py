"""In-context phase profiling for the batched raft round.

Applies the doc/performance.md methodology (measure inside the real
`lax.scan`, never as isolated microbenchmarks) to the 10k x 5-node
cluster configuration: times the full compiled round, then re-times it
with individual edge_step phases stubbed out (the ablation deltas are
the phase costs — XLA dead-code-eliminates a stubbed phase's work as
long as downstream consumers get same-shaped zeros).

Usage:
    JAX_PLATFORMS=cpu python -m maelstrom_tpu.profile_raft --clusters 1000
    python -m maelstrom_tpu.profile_raft            # real TPU, 10k

Ablations are selected by RaftProgram.ablate (a frozenset checked at
trace time; production runs never set it, so the flag costs nothing).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax

from .net import tpu as T
from .nodes import get_program
from .parallel import make_cluster_round_fn, make_cluster_sims

PHASES = ("votes", "entries", "client", "proxy", "apply", "outlanes")


def time_round(program, cfg, clusters: int, rounds: int, chunk: int,
               seed: int = 0) -> float:
    """Wall seconds per simulated round, measured over a chunked scan
    (compile + first run excluded)."""
    chunk = max(1, min(chunk, rounds))
    round_fn = make_cluster_round_fn(program, cfg)
    scan = jax.jit(lambda sims: jax.lax.scan(
        lambda s, _: (round_fn(s, T.Msgs.empty((clusters, 1)))[0], None),
        sims, None, length=chunk)[0])

    def run(sims):
        for _ in range(rounds // chunk):
            sims = scan(sims)
        assert int(jax.device_get(sims.net.round[0])) == \
            (rounds // chunk) * chunk
        return sims

    run(make_cluster_sims(program, cfg, clusters, seed=seed))   # compile
    sims = make_cluster_sims(program, cfg, clusters, seed=seed + 1)
    t0 = time.perf_counter()
    run(sims)
    return (time.perf_counter() - t0) / ((rounds // chunk) * chunk)


def main(argv=None):
    from .util import honor_jax_platforms
    honor_jax_platforms()
    ap = argparse.ArgumentParser()
    ap.add_argument("--clusters", type=int, default=10_000)
    ap.add_argument("--nodes", type=int, default=5)
    ap.add_argument("--rounds", type=int, default=300)
    ap.add_argument("--chunk", type=int, default=100)
    ap.add_argument("--phases", default="all",
                    help="comma list of phases to ablate, or 'all'/'none'")
    args = ap.parse_args(argv)

    nodes = [f"n{i}" for i in range(args.nodes)]

    def build(ablate=frozenset()):
        program = get_program("lin-kv", {"latency": {"mean": 0}}, nodes)
        program.ablate = frozenset(ablate)
        cfg = T.NetConfig(n_nodes=args.nodes, n_clients=1, pool_cap=64,
                          inbox_cap=program.inbox_cap, client_cap=4)
        return program, cfg

    program, cfg = build()
    dev = jax.devices()[0]
    print(f"profile_raft: {args.clusters} clusters x {args.nodes} nodes, "
          f"{args.rounds} rounds ({args.chunk}/dispatch), "
          f"device {dev.device_kind}", file=sys.stderr)

    base = time_round(program, cfg, args.clusters, args.rounds, args.chunk)
    report = {"device": dev.device_kind, "clusters": args.clusters,
              "nodes": args.nodes,
              "ms_per_round": round(base * 1e3, 3),
              "cluster_rounds_per_sec": round(args.clusters / base, 1),
              "phases": {}}
    print(f"  full round: {base * 1e3:.2f} ms "
          f"({args.clusters / base:,.0f} cluster-rounds/s)",
          file=sys.stderr)

    wanted = (PHASES if args.phases == "all"
              else () if args.phases == "none"
              else tuple(args.phases.split(",")))
    for ph in wanted:
        p2, c2 = build({ph})
        t = time_round(p2, c2, args.clusters, args.rounds, args.chunk)
        delta = base - t
        report["phases"][ph] = {"ms_per_round": round(t * 1e3, 3),
                                "delta_ms": round(delta * 1e3, 3)}
        print(f"  -{ph:<9} {t * 1e3:7.2f} ms  (phase cost "
              f"{delta * 1e3:+.2f} ms)", file=sys.stderr)

    print(json.dumps(report))


if __name__ == "__main__":
    main()
