"""The Maelstrom error registry and RPC error exceptions.

Mirrors the behavior of the reference's error system
(`src/maelstrom/client.clj:19-100`): errors have an integer code, a friendly
name, a docstring, and a `definite` flag. A *definite* error means the
requested operation definitely did not happen; indefinite errors leave the
outcome unknown. The registry drives both client-side error interpretation
(`with_errors`) and documentation generation (doc/protocol.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class ErrorDef:
    code: int
    name: str
    doc: str
    definite: bool = False
    ns: str = "maelstrom_tpu.errors"


# code -> ErrorDef  (reference `client.clj:19-27`)
ERROR_REGISTRY: dict[int, ErrorDef] = {}


class DuplicateError(Exception):
    pass


def deferror(code: int, name: str, doc: str, definite: bool = False,
             ns: str = "maelstrom_tpu.errors") -> ErrorDef:
    """Defines a new type of error and registers it, checking for duplicate
    codes and names (reference `client.clj:29-55`)."""
    if code in ERROR_REGISTRY:
        # Idempotent re-registration (module reloads) is fine if identical.
        extant = ERROR_REGISTRY[code]
        if extant.name == name and extant.doc == doc:
            return extant
        raise DuplicateError(f"duplicate error code {code}: {extant}")
    for e in ERROR_REGISTRY.values():
        if e.name == name:
            raise DuplicateError(f"duplicate error name {name}: {e}")
    err = ErrorDef(code=code, name=name, doc=doc, definite=definite, ns=ns)
    ERROR_REGISTRY[code] = err
    return err


# --- Standard errors (reference `client.clj:57-100`) ---

TIMEOUT = deferror(
    0, "timeout",
    "Indicates that the requested operation could not be completed within a "
    "timeout.")

NODE_NOT_FOUND = deferror(
    1, "node-not-found",
    "Thrown when a client sends an RPC request to a node which does not "
    "exist.",
    definite=True)

NOT_SUPPORTED = deferror(
    10, "not-supported",
    "Use this error to indicate that a requested operation is not supported "
    "by the current implementation. Helpful for stubbing out APIs during "
    "development.",
    definite=True)

TEMPORARILY_UNAVAILABLE = deferror(
    11, "temporarily-unavailable",
    "Indicates that the operation definitely cannot be performed at this "
    "time--perhaps because the server is in a read-only state, has not yet "
    "been initialized, believes its peers to be down, and so on. Do *not* "
    "use this error for indeterminate cases, when the operation may actually "
    "have taken place.",
    definite=True)

MALFORMED_REQUEST = deferror(
    12, "malformed-request",
    "The client's request did not conform to the server's expectations, and "
    "could not possibly have been processed.",
    definite=True)

CRASH = deferror(
    13, "crash",
    "Indicates that some kind of general, indefinite error occurred. Use "
    "this as a catch-all for errors you can't otherwise categorize, or as a "
    "starting point for your error handler: it's safe to return "
    "`internal-error` for every problem by default, then add special cases "
    "for more specific errors later.",
    definite=False)

ABORT = deferror(
    14, "abort",
    "Indicates that some kind of general, definite error occurred. Use this "
    "as a catch-all for errors you can't otherwise categorize, when you "
    "specifically know that the requested operation has not taken place. "
    "For instance, you might encounter an indefinite failure during the "
    "prepare phase of a transaction: since you haven't started the commit "
    "process yet, the transaction can't have taken place. It's therefore "
    "safe to return a definite `abort` to the client.",
    definite=True)


NOT_LEADER = deferror(
    31, "not-leader",
    "The contacted node is not the cluster's current leader, so the "
    "operation definitely did not execute. The error body may carry a "
    "`hint` naming the node the sender believes leads (-1 when no live "
    "leader is known, e.g. mid-election); clients should retry against "
    "the hint under backoff (doc/compartment.md 'leader election').",
    definite=True)


BYZANTINE = deferror(
    32, "byzantine",
    "The receiver detected Byzantine (lying) behavior in this message — "
    "an equivocating assignment, a ballot outside the sender's residue "
    "class, or a forged expansion proof — and definitely did not act on "
    "it. The rejection is also booked as conviction evidence for the "
    "`byzantine` results block (doc/faults.md 'byzantine is a "
    "conviction driver').",
    definite=True)


class RPCError(Exception):
    """An error body returned by a node in response to an RPC
    (reference `client.clj:186-199`)."""

    def __init__(self, code: int, body: dict | None = None):
        self.code = code
        self.body = body or {}
        err = ERROR_REGISTRY.get(code)
        self.name = err.name if err else "unknown"
        self.definite = err.definite if err else False
        super().__init__(
            f"RPC error {code} ({self.name}): {self.body.get('text', '')}")


class Timeout(RPCError):
    """Client read timeout: indefinite (reference `client.clj:157-164`)."""

    def __init__(self, text: str = "Client read timeout"):
        super().__init__(0, {"text": text})
        self.definite = False


def error_body(code: int, text: str = "", **extra) -> dict:
    """Constructs a protocol error body (doc/protocol.md error format)."""
    body = {"type": "error", "code": code, "text": text}
    body.update(extra)
    return body
