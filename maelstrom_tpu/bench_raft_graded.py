"""Checker-graded histories from the 10k-cluster raft benchmark.

BASELINE's graded configs include "lin-kv: 10k independent 5-node raft
clusters"; the throughput bench (`bench.py --raft`) measures
cluster-rounds/s and leader uniqueness, but lin-kv is the one workload
where *grading* is the whole point (reference
`workload/lin_kv.clj:95-102`). This module drives a sampled subset of
the vmapped clusters with real client traffic — concurrent client
workers per sampled cluster issuing read/write/cas on a shared key
through the protocol (leader proxying included) — synthesizes one
operation history per cluster from the actual reply stream, and grades
every history with the stock WGL linearizability checker
(`checkers/linearizable.py`).

All `n_clusters` clusters advance in the same vmapped dispatches (the
benchmark's scaling claim); only the sampled ones receive traffic. The
reply path is exact: client messages are collected per round inside the
scan, sliced to the sampled clusters on device, and paired to their
requests **by message id** — the scan also emits each sampled cluster's
`next_mid` after every round, so the device-assigned id of every
injected request is reconstructed exactly (mid = next_mid before its
round + its rank among that round's injections; `net/tpu.py _send`).
A reply whose id matches no in-flight op must match a timed-out one
(the op was already graded indeterminate — `info` means exactly "may
have committed"; the late ack is dropped); anything else is an error.

A partition nemesis can run *during* the graded window
(`partition_at`/`partition_chunks`): every cluster gets an independent
majority/minority split (component labels, `net/tpu.py
partition_components` semantics — clients exempt), healed before the
end of the run; each worker holds back its final read until after the
heal, so the tail of every history exercises recovery. Ops that die in
the minority side surface as indeterminates, which WGL treats as
may-or-may-not-have-happened — the reference's flagship lin-kv +
partitions test (`workload/lin_kv.clj` + jepsen nemesis).

Used by bench.py (BENCH_MODE=raft) and unit-tested at small scale on
CPU (tests/test_bench_raft_graded.py).
"""

from __future__ import annotations

import time


def run_raft_graded(n_clusters: int = 10_000, n: int = 5, sample: int = 64,
                    ops_per_client: int = 12, clients: int = 2,
                    chunk: int = 10, seed: int = 0, warmup_chunks: int = 8,
                    max_chunks: int = 400, partition_at: int | None = None,
                    partition_chunks: int = 0, p_loss: float = 0.0,
                    latency: dict | None = None, verbose: bool = True,
                    return_failures: bool = False) -> dict:
    import sys

    import jax
    import jax.numpy as jnp
    import numpy as np

    from .checkers.linearizable import LinearizableRegisterChecker
    from .history import History, Op
    from .net import tpu as T
    from .nodes import get_program
    from .nodes.raft import (T_CAS, T_CAS_OK, T_READ, T_READ_OK, T_WRITE,
                             T_WRITE_OK)
    from .parallel import make_cluster_round_fn, make_cluster_sims

    latency = latency or {"mean": 0}
    nodes = [f"n{i}" for i in range(n)]
    program = get_program("lin-kv", {"latency": latency}, nodes)
    cfg = T.NetConfig(n_nodes=n, n_clients=clients, pool_cap=64,
                      inbox_cap=program.inbox_cap, client_cap=4,
                      latency_mean_rounds=float(latency.get("mean") or 0),
                      latency_dist=latency.get("dist", "constant"))
    round_fn = make_cluster_round_fn(program, cfg)

    S = min(sample, n_clusters)
    sampled = np.linspace(0, n_clusters - 1, S).astype(np.int32)
    sampled_d = jnp.asarray(sampled)
    M = clients

    def scan_chunk(sims, small_plan):
        """chunk rounds in one dispatch; injections only into the
        sampled clusters (scattered on device — the host ships
        [chunk, S, M], not [chunk, n_clusters, M]); client replies and
        the post-round next_mid of the sampled clusters leave the
        device per round (next_mid drives exact reply pairing)."""
        def body(s, small_round):
            full = T.Msgs.empty((n_clusters, M))
            full = jax.tree.map(
                lambda f, sm: f.at[sampled_d].set(sm), full, small_round)
            s, cm, _io = round_fn(s, full)
            return s, (jax.tree.map(lambda f: f[sampled_d], cm),
                       s.net.next_mid[sampled_d])
        return jax.lax.scan(body, sims, small_plan)

    scan_chunk = jax.jit(scan_chunk)

    minority = n // 2

    def set_partition(sims, comp):
        """Install per-cluster component labels [n_clusters, n] (clients
        exempt: their labels stay 0, and the pool path never blocks
        client messages)."""
        net = sims.net
        return sims.replace(net=net.replace(
            component=net.component.at[:, :n].set(comp)))

    set_partition = jax.jit(set_partition)

    sims = make_cluster_sims(program, cfg, n_clusters, seed=seed)
    if p_loss:
        # per-message loss on every cluster's net (raft's retries and
        # election timeouts absorb it; lost client requests surface as
        # indeterminate ops, which WGL grades as may-have-happened)
        sims = sims.replace(net=T.flaky(sims.net, p_loss))
    empty_plan = T.Msgs.empty((chunk, S, M))
    t0 = time.perf_counter()

    # --- warmup: let every sampled cluster elect a leader ---
    leader_fn = jax.jit(
        lambda s: ((s.nodes["role"][sampled_d] == 2).sum(axis=1)))
    for _ in range(warmup_chunks):
        sims, _out = scan_chunk(sims, empty_plan)
    leaders = np.asarray(jax.device_get(leader_fn(sims)))
    if not (leaders == 1).all():
        raise RuntimeError(
            f"{int((leaders != 1).sum())}/{S} sampled clusters lack a "
            f"unique leader after warmup")
    nm_prev = np.asarray(jax.device_get(
        jax.jit(lambda s: s.net.next_mid[sampled_d])(sims)))   # [S]

    # --- client traffic: per (sampled cluster, worker) op scripts on a
    # shared register (key = cluster index % 8) — writes, reads, and
    # cas chains that genuinely contend across the workers; the LAST op
    # of every script is a read, held back until any partition heals ---
    rng = np.random.default_rng(seed + 7)
    key_of = {s: int(s % 8) for s in range(S)}

    def script(s, w):
        k = key_of[s]
        ops = [("write", k, int(rng.integers(0, 100)), 0)]
        for _ in range(ops_per_client - 2):
            r = rng.random()
            if r < 0.4:
                ops.append(("read", k, 0, 0))
            elif r < 0.7:
                ops.append(("write", k, int(rng.integers(0, 100)), 0))
            else:
                ops.append(("cas", k, int(rng.integers(0, 100)),
                            int(rng.integers(0, 100))))
        ops.append(("read", k, 0, 0))            # final read, post-heal
        return ops

    scripts = {(s, w): script(s, w) for s in range(S)
               for w in range(clients)}
    cursor = {sw: 0 for sw in scripts}           # next op index
    in_flight = {}            # (s, w) -> (op, proc, rnd, mid-or-None)
    histories = {s: [] for s in range(S)}        # per-cluster Op lists
    n_procs = 0
    round_base = warmup_chunks * chunk
    pending_rounds = 200                          # reply SLA before `info`

    T_OF = {"read": T_READ, "write": T_WRITE, "cas": T_CAS}
    OK_OF = {T_READ_OK: "read", T_WRITE_OK: "write", T_CAS_OK: "cas"}

    def complete(s, w, typ, a, at_round):
        op, proc, _rnd, _mid = in_flight.pop((s, w))
        f, k, v1, v2 = op
        if typ == 1:                              # definite error (20/22)
            histories[s].append(Op(type="fail", f=f, process=proc,
                                   value=_val(f, k, v1, v2, None),
                                   time=int(at_round * 1e6)))
            return
        if OK_OF.get(typ) != f:
            raise RuntimeError(f"reply type {typ} for op {f}")
        rv = int(a) - 1 if typ == T_READ_OK else None
        histories[s].append(Op(type="ok", f=f, process=proc,
                               value=_val(f, k, v1, v2, rv),
                               time=int(at_round * 1e6)))

    def _val(f, k, v1, v2, read_v):
        if f == "read":
            return [k, read_v]
        if f == "write":
            return [k, v1]
        return [k, [v1, v2]]

    p0 = partition_at if partition_chunks else None
    p1 = (p0 + partition_chunks) if p0 is not None else None
    if p0 is not None and p1 >= max_chunks - 4:
        raise ValueError("partition window must heal well before "
                         "max_chunks so final reads can complete")
    partition_active = False

    # (cluster, mid) of ops graded indeterminate: next_mid is a
    # PER-CLUSTER counter, so bare mids collide across sampled clusters
    timed_out_mids = set()
    completed_mids = set()    # (cluster, mid) already ok/fail-completed
    duplicate_replies = 0
    chunks_run = 0
    while chunks_run < max_chunks:
        # --- nemesis schedule (host-side state surgery, like the
        # reference's nemesis thread; component semantics net.clj:104+) ---
        if p0 is not None and chunks_run == p0:
            prng = np.random.default_rng(seed + 31)
            order = prng.random((n_clusters, n)).argsort(axis=1)
            splits = (order < minority).astype(np.int32)
            sims = set_partition(sims, jnp.asarray(splits))
            partition_active = True
            if verbose:
                print(f"raft-graded: partition installed at round "
                      f"{round_base} (minority {minority}/{n}, every "
                      f"cluster)", file=sys.stderr)
        if p1 is not None and chunks_run == p1:
            sims = set_partition(
                sims, jnp.zeros((n_clusters, n), jnp.int32))
            partition_active = False
            if verbose:
                print(f"raft-graded: partition healed at round "
                      f"{round_base}", file=sys.stderr)

        plan_valid = np.zeros((chunk, S, M), bool)
        plan_dest = np.zeros((chunk, S, M), np.int32)
        plan_type = np.zeros((chunk, S, M), np.int32)
        plan_a = np.zeros((chunk, S, M), np.int32)
        plan_b = np.zeros((chunk, S, M), np.int32)
        plan_c = np.zeros((chunk, S, M), np.int32)
        plan_src = np.full((chunk, S, M), n, np.int32)
        injected = {}               # (s, rr) -> [(w, proc), ...] in order
        for (s, w), idx in list(cursor.items()):
            if (s, w) in in_flight or idx >= len(scripts[(s, w)]):
                continue
            if (idx == len(scripts[(s, w)]) - 1
                    and (partition_active
                         or (p1 is not None and chunks_run < p1))):
                continue          # final read waits for the heal
            f, k, v1, v2 = scripts[(s, w)][idx]
            # stagger workers across rounds and nodes: a non-leader
            # proxies at most ONE client request per round, so two
            # same-round arrivals at one node would silently shed one
            # (the interactive runner absorbs that as an RPC timeout;
            # here it would surface as a spurious indeterminate op)
            rr = w % chunk
            plan_valid[rr, s, w] = True
            plan_src[rr, s, w] = n + w
            plan_dest[rr, s, w] = (idx + s + 2 * w) % n
            plan_type[rr, s, w] = T_OF[f]
            plan_a[rr, s, w] = k
            plan_b[rr, s, w] = v1
            plan_c[rr, s, w] = v2
            proc = n_procs
            n_procs += 1
            histories[s].append(Op(
                type="invoke", f=f, process=proc,
                value=_val(f, k, v1, v2, None),
                time=int((round_base + rr) * 1e6)))
            in_flight[(s, w)] = ((f, k, v1, v2), proc,
                                 round_base + rr, None)
            injected.setdefault((s, rr), []).append(w)
            cursor[(s, w)] = idx + 1
        plan = T.Msgs.empty((chunk, S, M)).replace(
            valid=jnp.asarray(plan_valid), src=jnp.asarray(plan_src),
            dest=jnp.asarray(plan_dest), type=jnp.asarray(plan_type),
            a=jnp.asarray(plan_a), b=jnp.asarray(plan_b),
            c=jnp.asarray(plan_c))
        sims, (cm, nms) = scan_chunk(sims, plan)
        cm, nms = jax.device_get((cm, nms))
        valid = np.asarray(cm.valid)              # [chunk, S, CC]
        types = np.asarray(cm.type)
        dests = np.asarray(cm.dest)
        avals = np.asarray(cm.a)
        rtos = np.asarray(cm.reply_to)
        nms = np.asarray(nms)                     # [chunk, S]
        for i in range(chunk):
            # device mids of this round's injections: next_mid before
            # the round + rank in worker order (= plan row order)
            nm_before = nm_prev if i == 0 else nms[i - 1]
            for (s, rr), ws in injected.items():
                if rr != i:
                    continue
                for rank, w in enumerate(ws):
                    op, proc, rnd, _ = in_flight[(s, w)]
                    in_flight[(s, w)] = (op, proc, rnd,
                                         int(nm_before[s]) + rank)
            for s, j in zip(*np.nonzero(valid[i])):
                w = int(dests[i, s, j]) - n
                rto = int(rtos[i, s, j])
                cur = in_flight.get((int(s), w))
                if cur is not None and cur[3] == rto:
                    complete(int(s), w, int(types[i, s, j]),
                             int(avals[i, s, j]), round_base + i)
                    completed_mids.add((int(s), rto))
                elif (int(s), rto) in timed_out_mids:
                    # late ack for an op already graded indeterminate:
                    # `info` means exactly "may have committed" — drop
                    # (kept in the set: a re-applying post-heal leader
                    # can ack the same committed entry more than once)
                    pass
                elif (int(s), rto) in completed_mids:
                    # duplicate reply: a post-heal leader re-applying a
                    # committed entry (its applied index trailed the old
                    # leader's) answers the client a second time —
                    # idempotent at the client, counted for the record
                    duplicate_replies += 1
                else:
                    raise RuntimeError(
                        f"unmatched reply mid {rto} for c{s}/w{w}")
        nm_prev = nms[-1]
        round_base += chunk
        chunks_run += 1
        # reply SLA: an op outstanding past the window becomes info
        # (indeterminate: it may still commit later; WGL handles it) and
        # the worker moves on — its ops keep flowing through partitions
        for sw, (op, proc, rnd, mid) in list(in_flight.items()):
            if round_base - rnd > pending_rounds:
                s, w = sw
                f, k, v1, v2 = op
                histories[s].append(Op(type="info", f=f, process=proc,
                                       value=_val(f, k, v1, v2, None),
                                       time=int(round_base * 1e6)))
                del in_flight[sw]
                if mid is not None:
                    timed_out_mids.add((s, mid))
        if not in_flight and all(cursor[sw] >= len(scripts[sw])
                                 for sw in scripts):
            break

    if in_flight or any(cursor[sw] < len(scripts[sw]) for sw in scripts):
        raise RuntimeError(
            f"graded run hit max_chunks={max_chunks} with "
            f"{len(in_flight)} ops in flight and unfinished scripts")

    if verbose:
        print(f"raft-graded: {S} clusters x {clients} workers x "
              f"{ops_per_client} ops in {round_base} rounds "
              f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)

    # --- grade every sampled cluster's history ---
    checker = LinearizableRegisterChecker()
    results = []
    failures = []
    for s in range(S):
        # completions sort BEFORE invokes at equal round-quantized
        # timestamps: an op completing at round t must happen-before an
        # op invoked at round t, else the real-time order relaxes in the
        # lenient (false-valid) direction
        ops = sorted(histories[s], key=lambda o: (o.time,
                                                  o.type == "invoke"))
        res = checker.check({}, History(ops), {})
        results.append(res["valid"])
        if return_failures and res["valid"] is not True:
            cl = int(sampled[s])
            st = jax.device_get(jax.tree.map(lambda a: a[cl], sims.nodes))
            logs = []
            for node in range(n):
                ll = int(st["log_len"][node])
                ents = []
                for i in range(ll):
                    a = int(st["log_a"][node][i])
                    b = int(st["log_b"][node][i])
                    c = int(st["log_c"][node][i])
                    ents.append({"term": a >> 16, "key": (a >> 4) & 0xFFF,
                                 "op": a & 0xF, "client": b >> 16,
                                 "v1": (b >> 8) & 0xFF, "v2": b & 0xFF,
                                 "mid": c})
                logs.append({"node": node, "role": int(st["role"][node]),
                             "term": int(st["term"][node]),
                             "commit": int(st["commit"][node]),
                             "applied": int(st["applied"][node]),
                             "kv": st["kv"][node].tolist(),
                             "log": ents})
            failures.append({"cluster": cl, "sample": s,
                             "verdict": res, "ops": ops, "state": logs})
    ok_count = sum(1 for v in results if v is True)
    info_ops = sum(1 for s in range(S) for o in histories[s]
                   if o.type == "info")
    # conservation audit over the WHOLE fleet (stats_dict sums the
    # per-cluster counters): silent drops are a simulator bug regardless
    # of the fault mix, loss/partition drops are the injected faults
    net_stats = T.stats_dict(sims.net)
    out = {
        "sampled_clusters": S,
        "clusters_total": n_clusters,
        "workers_per_cluster": clients,
        "ops_per_worker": ops_per_client,
        "linearizable_clusters": ok_count,
        "all_linearizable": ok_count == S,
        "indeterminate_ops": info_ops,
        "duplicate_replies": duplicate_replies,
        "rounds": round_base,
        "wall_s": round(time.perf_counter() - t0, 3),
        "net_stats": net_stats,
        "dropped_overflow": net_stats.get("dropped_overflow", 0),
    }
    if p_loss:
        out["p_loss"] = p_loss
    if latency.get("mean"):
        out["latency"] = latency
    if return_failures:
        out["failures"] = failures
    if p0 is not None:
        out["partition"] = {
            "from_round": warmup_chunks * chunk + p0 * chunk,
            "rounds": partition_chunks * chunk,
            "minority_size": minority,
            "clusters_partitioned": n_clusters,
        }
    return out
