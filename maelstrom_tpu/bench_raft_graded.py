"""Checker-graded histories from the 10k-cluster raft benchmark.

BASELINE's graded configs include "lin-kv: 10k independent 5-node raft
clusters"; the throughput bench (`bench.py --raft`) measures
cluster-rounds/s and leader uniqueness, but lin-kv is the one workload
where *grading* is the whole point (reference
`workload/lin_kv.clj:95-102`). This module drives a sampled subset of
the vmapped clusters with real client traffic — two concurrent client
workers per sampled cluster issuing read/write/cas on a shared key
through the protocol (leader proxying included) — synthesizes one
operation history per cluster from the actual reply stream, and grades
every history with the stock WGL linearizability checker
(`checkers/linearizable.py`).

All `n_clusters` clusters advance in the same vmapped dispatches (the
benchmark's scaling claim); only the sampled ones receive traffic. The
reply path is exact: client messages are collected per round inside the
scan, sliced to the sampled clusters on device, and paired to their
requests by (cluster, client-src) — each worker keeps at most one op in
flight, and a worker whose reply never arrives records an indeterminate
(`info`) op, which the checker treats as may-or-may-not-have-happened.

Used by bench.py (BENCH_MODE=raft) and unit-tested at small scale on
CPU (tests/test_bench_raft_graded.py).
"""

from __future__ import annotations

import time


def run_raft_graded(n_clusters: int = 10_000, n: int = 5, sample: int = 64,
                    ops_per_client: int = 12, clients: int = 2,
                    chunk: int = 10, seed: int = 0, warmup_chunks: int = 8,
                    max_chunks: int = 400, verbose: bool = True) -> dict:
    import sys

    import jax
    import jax.numpy as jnp
    import numpy as np

    from .checkers.linearizable import LinearizableRegisterChecker
    from .history import History, Op
    from .net import tpu as T
    from .nodes import get_program
    from .nodes.raft import (T_CAS, T_CAS_OK, T_READ, T_READ_OK, T_WRITE,
                             T_WRITE_OK)
    from .parallel import make_cluster_round_fn, make_cluster_sims

    nodes = [f"n{i}" for i in range(n)]
    program = get_program("lin-kv", {"latency": {"mean": 0}}, nodes)
    cfg = T.NetConfig(n_nodes=n, n_clients=clients, pool_cap=64,
                      inbox_cap=program.inbox_cap, client_cap=4)
    round_fn = make_cluster_round_fn(program, cfg)

    S = min(sample, n_clusters)
    sampled = np.linspace(0, n_clusters - 1, S).astype(np.int32)
    sampled_d = jnp.asarray(sampled)
    M = clients

    def scan_chunk(sims, small_plan):
        """chunk rounds in one dispatch; injections only into the
        sampled clusters (scattered on device — the host ships
        [chunk, S, M], not [chunk, n_clusters, M]); client replies
        sliced to the sampled clusters before leaving the device."""
        def body(s, small_round):
            full = T.Msgs.empty((n_clusters, M))
            full = jax.tree.map(
                lambda f, sm: f.at[sampled_d].set(sm), full, small_round)
            s, cm, _io = round_fn(s, full)
            return s, jax.tree.map(lambda f: f[sampled_d], cm)
        return jax.lax.scan(body, sims, small_plan)

    scan_chunk = jax.jit(scan_chunk)

    sims = make_cluster_sims(program, cfg, n_clusters, seed=seed)
    empty_plan = T.Msgs.empty((chunk, S, M))
    t0 = time.perf_counter()

    # --- warmup: let every sampled cluster elect a leader ---
    leader_fn = jax.jit(
        lambda s: ((s.nodes["role"][sampled_d] == 2).sum(axis=1)))
    for _ in range(warmup_chunks):
        sims, _cm = scan_chunk(sims, empty_plan)
    leaders = np.asarray(jax.device_get(leader_fn(sims)))
    if not (leaders == 1).all():
        raise RuntimeError(
            f"{int((leaders != 1).sum())}/{S} sampled clusters lack a "
            f"unique leader after warmup")

    # --- client traffic: per (sampled cluster, worker) op scripts on a
    # shared register (key = cluster index % 8) — writes, reads, and
    # cas chains that genuinely contend across the two workers ---
    rng = np.random.default_rng(seed + 7)
    key_of = {s: int(s % 8) for s in range(S)}

    def script(s, w):
        k = key_of[s]
        ops = [("write", k, int(rng.integers(0, 100)), 0)]
        for _ in range(ops_per_client - 1):
            r = rng.random()
            if r < 0.4:
                ops.append(("read", k, 0, 0))
            elif r < 0.7:
                ops.append(("write", k, int(rng.integers(0, 100)), 0))
            else:
                ops.append(("cas", k, int(rng.integers(0, 100)),
                            int(rng.integers(0, 100))))
        return ops

    scripts = {(s, w): script(s, w) for s in range(S)
               for w in range(clients)}
    cursor = {sw: 0 for sw in scripts}           # next op index
    in_flight = {}                               # (s, w) -> (op, proc, rnd)
    histories = {s: [] for s in range(S)}        # per-cluster Op lists
    n_procs = 0
    round_base = warmup_chunks * chunk
    pending_rounds = 200                          # reply SLA before `info`

    T_OF = {"read": T_READ, "write": T_WRITE, "cas": T_CAS}
    OK_OF = {T_READ_OK: "read", T_WRITE_OK: "write", T_CAS_OK: "cas"}

    def complete(s, w, typ, a, at_round):
        op, proc, _rnd = in_flight.pop((s, w))
        f, k, v1, v2 = op
        if typ == 1:                              # definite error (20/22)
            histories[s].append(Op(type="fail", f=f, process=proc,
                                   value=_val(f, k, v1, v2, None),
                                   time=int(at_round * 1e6)))
            return
        if OK_OF.get(typ) != f:
            raise RuntimeError(f"reply type {typ} for op {f}")
        rv = int(a) - 1 if typ == T_READ_OK else None
        histories[s].append(Op(type="ok", f=f, process=proc,
                               value=_val(f, k, v1, v2, rv),
                               time=int(at_round * 1e6)))

    def _val(f, k, v1, v2, read_v):
        if f == "read":
            return [k, read_v]
        if f == "write":
            return [k, v1]
        return [k, [v1, v2]]

    timed_out = {}                # (s, w) -> True after an SLA expiry
    chunks_run = 0
    while chunks_run < max_chunks:
        plan_valid = np.zeros((chunk, S, M), bool)
        plan_dest = np.zeros((chunk, S, M), np.int32)
        plan_type = np.zeros((chunk, S, M), np.int32)
        plan_a = np.zeros((chunk, S, M), np.int32)
        plan_b = np.zeros((chunk, S, M), np.int32)
        plan_c = np.zeros((chunk, S, M), np.int32)
        plan_src = np.full((chunk, S, M), n, np.int32)
        for (s, w), idx in list(cursor.items()):
            if (s, w) in in_flight or idx >= len(scripts[(s, w)]):
                continue
            f, k, v1, v2 = scripts[(s, w)][idx]
            # stagger workers across rounds and nodes: a non-leader
            # proxies at most ONE client request per round, so two
            # same-round arrivals at one node would silently shed one
            # (the interactive runner absorbs that as an RPC timeout;
            # here it would surface as a spurious indeterminate op)
            rr = w % chunk
            plan_valid[rr, s, w] = True
            plan_src[rr, s, w] = n + w
            plan_dest[rr, s, w] = (idx + s + 2 * w) % n
            plan_type[rr, s, w] = T_OF[f]
            plan_a[rr, s, w] = k
            plan_b[rr, s, w] = v1
            plan_c[rr, s, w] = v2
            proc = n_procs
            n_procs += 1
            histories[s].append(Op(
                type="invoke", f=f, process=proc,
                value=_val(f, k, v1, v2, None),
                time=int((round_base + rr) * 1e6)))
            in_flight[(s, w)] = ((f, k, v1, v2), proc, round_base + rr)
            cursor[(s, w)] = idx + 1
        plan = T.Msgs.empty((chunk, S, M)).replace(
            valid=jnp.asarray(plan_valid), src=jnp.asarray(plan_src),
            dest=jnp.asarray(plan_dest), type=jnp.asarray(plan_type),
            a=jnp.asarray(plan_a), b=jnp.asarray(plan_b),
            c=jnp.asarray(plan_c))
        sims, cm = scan_chunk(sims, plan)
        cm = jax.device_get(cm)
        valid = np.asarray(cm.valid)              # [chunk, S, CC]
        types = np.asarray(cm.type)
        dests = np.asarray(cm.dest)
        avals = np.asarray(cm.a)
        for i in range(chunk):
            for s, j in zip(*np.nonzero(valid[i])):
                w = int(dests[i, s, j]) - n
                if (s, w) not in in_flight:
                    # a reply landing after its op's SLA window: the op
                    # was already graded indeterminate (it may indeed
                    # have committed — exactly what `info` means), so
                    # the late ack is dropped, once, not fatal
                    if timed_out.pop((int(s), w), None):
                        continue
                    raise RuntimeError(
                        f"reply for idle worker c{s}/w{w}")
                complete(int(s), w, int(types[i, s, j]),
                         int(avals[i, s, j]), round_base + i)
        round_base += chunk
        chunks_run += 1
        # reply SLA: an op outstanding past the window becomes info
        # (indeterminate: it may still commit later; WGL handles it)
        for sw, (op, proc, rnd) in list(in_flight.items()):
            if round_base - rnd > pending_rounds:
                s, w = sw
                f, k, v1, v2 = op
                histories[s].append(Op(type="info", f=f, process=proc,
                                       value=_val(f, k, v1, v2, None),
                                       time=int(round_base * 1e6)))
                del in_flight[sw]
                timed_out[sw] = True
                cursor[sw] = len(scripts[sw])     # stop this worker
        if not in_flight and all(cursor[sw] >= len(scripts[sw])
                                 for sw in scripts):
            break

    if verbose:
        print(f"raft-graded: {S} clusters x {clients} workers x "
              f"{ops_per_client} ops in {round_base} rounds "
              f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)

    # --- grade every sampled cluster's history ---
    checker = LinearizableRegisterChecker()
    results = []
    for s in range(S):
        # completions sort BEFORE invokes at equal round-quantized
        # timestamps: an op completing at round t must happen-before an
        # op invoked at round t, else the real-time order relaxes in the
        # lenient (false-valid) direction
        ops = sorted(histories[s], key=lambda o: (o.time,
                                                  o.type == "invoke"))
        res = checker.check({}, History(ops), {})
        results.append(res["valid"])
    ok_count = sum(1 for v in results if v is True)
    info_ops = sum(1 for s in range(S) for o in histories[s]
                   if o.type == "info")
    return {
        "sampled_clusters": S,
        "clusters_total": n_clusters,
        "workers_per_cluster": clients,
        "ops_per_worker": ops_per_client,
        "linearizable_clusters": ok_count,
        "all_linearizable": ok_count == S,
        "indeterminate_ops": info_ops,
        "rounds": round_base,
        "wall_s": round(time.perf_counter() - t0, 3),
    }
