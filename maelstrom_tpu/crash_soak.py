"""Kill/resume soak harness: proves byte-identical crash recovery.

Runs a TPU-path test as a subprocess, SIGKILLs it at randomized moments
(always after at least one checkpoint has landed, so every cycle
exercises a real resume), relaunches it with `--resume` from the newest
durable checkpoint, and — once a launch finally runs to completion —
asserts that the stitched history and the checker verdicts are
**bit-identical** to an uninterrupted run with the same seed and
options. This is the executable form of doc/checkpoint.md's recovery
guarantee, and the companion of `run_crash_soak.sh` (the supervisor
relaunch recipe for graceful SIGTERM preemption).

Usage (also wrapped by the `soak`-marked tests in
tests/test_crash_soak.py, opt-in via MAELSTROM_SOAK=1):

    python -m maelstrom_tpu.crash_soak --kills 5 --seed 3
    python -m maelstrom_tpu.crash_soak --kills 5 --mesh 1,2   # sharded

SIGKILL (not SIGTERM) on purpose: the graceful path gets its own
coverage; the soak proves recovery with *no* cooperation from the
victim — the same discipline Jepsen applies to the systems under test.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import time

from .checkpoint import (CHECKPOINT_FILE, EXIT_PREEMPTED,
                         PREV_CHECKPOINT_FILE)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Result blocks that legitimately differ between an interrupted and an
# uninterrupted run: host-transfer/checkpoint counters (drains and saves
# restart per launch), pipeline segmentation, and the resume marker.
# Everything else — workload verdicts, stats, perf (virtual-time
# latencies), validity — must match exactly.
VOLATILE_RESULT_KEYS = ("net", "analysis-pipeline", "resumed-at-round")

# Wall-clock blocks nested inside a checker's own result (the windowed
# stream grading carries checker lag, and the window layout depends on
# drain cadence — doc/streams.md; the availability block is virtual-
# round deterministic EXCEPT its own check wall time); the verdict
# fields beside them must still match bit-for-bit.
VOLATILE_SUBRESULT_KEYS = ("windows", "checker-lag", "check-wall-s")

# Fleet results additionally inline the fleet-level TransferStats
# accounting at the top level (one transfer ledger for the whole fleet)
# and a static-audit block with wall time; both restart per launch.
VOLATILE_FLEET_KEYS = VOLATILE_RESULT_KEYS + (
    "drains", "host-bytes", "host-blocked-s", "host-overlapped-s",
    "ckpt-saves", "ckpt-blocked-s", "ckpt-write-s", "static-audit",
    # host-driver poll accounting (doc/perf.md "vectorized host
    # driver"): a resumed launch only counts polls since its resume
    "host-polls", "host-poll-s", "host-wall-per-wave",
    "max-checker-lag-rounds")

# A small but honest default config: raft-backed lin-kv (durable store,
# so the kill nemesis is recoverable), the full combined fault soup, and
# a checkpoint cadence short enough that every kill lands mid-stretch.
DEFAULT_OPTS = {
    "-w": "lin-kv", "--node": "tpu:lin-kv", "--node-count": "5",
    "--rate": "15", "--time-limit": "10", "--seed": "3",
    "--nemesis": "kill,pause,partition,duplicate",
    "--nemesis-interval": "2",
    "--checkpoint-every": "0.25",
}


def child_env(mesh_devices: int | None = None) -> dict:
    """The subprocess environment: CPU backend, the repo's shared
    persistent compile cache, and (for --mesh runs) enough virtual CPU
    devices to place the requested mesh."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(_REPO, "artifacts", "xla-cache"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    if mesh_devices:
        from .util import xla_device_count_flags
        env["XLA_FLAGS"] = xla_device_count_flags(
            env.get("XLA_FLAGS", ""), mesh_devices)
    return env


def argv_for(store_root: str, opts: dict, resume: str | None = None):
    argv = [sys.executable, "-m", "maelstrom_tpu", "test",
            "--store", store_root]
    for k, v in opts.items():
        if v is True:
            argv.append(k)
        elif v is not None:
            argv += [k, str(v)]
    if resume:
        argv += ["--resume", resume]
    return argv


def run_dirs(store_root: str, name: str) -> list[str]:
    """Timestamped run dirs under store_root/<name>/, oldest first."""
    out = [d for d in glob.glob(os.path.join(store_root, name, "*"))
           if os.path.isdir(d) and not os.path.islink(d)]
    return sorted(out)


def _has_checkpoint(d: str) -> bool:
    """True when `cp.load` could resume from this run dir — including
    the prev-only state a SIGKILL between save's two renames leaves
    behind (checkpoint.prev.pkl without checkpoint.pkl)."""
    return (os.path.exists(os.path.join(d, CHECKPOINT_FILE))
            or os.path.exists(os.path.join(d, PREV_CHECKPOINT_FILE)))


def _mesh_devices(opts: dict) -> int | None:
    spec = opts.get("--mesh")
    if not spec:
        return None
    dp, sp = (int(x) for x in str(spec).split(","))
    return dp * sp


def run_once(store_root: str, opts: dict, log_path: str,
             timeout_s: float = 600.0) -> str:
    """One uninterrupted run to completion; returns its store dir."""
    with open(log_path, "ab") as lf:
        rc = subprocess.call(argv_for(store_root, opts),
                             env=child_env(_mesh_devices(opts)),
                             stdout=lf, stderr=subprocess.STDOUT,
                             timeout=timeout_s)
    if rc != 0:
        raise RuntimeError(
            f"baseline run failed rc={rc}; see {log_path}")
    dirs = run_dirs(store_root, opts["-w"])
    return dirs[-1]


def run_with_kills(store_root: str, opts: dict, kills: int, rng,
                   kill_jitter_s: float = 0.75,
                   launch_timeout_s: float = 600.0,
                   log=lambda m: print(m, file=sys.stderr)) -> dict:
    """Launch/SIGKILL/resume loop: SIGKILLs the first `kills` launches
    at a randomized moment after their first checkpoint lands, then
    lets the final launch run to completion. Returns the completed
    run's store dir plus the kill log."""
    name = opts["-w"]
    known: set = set(run_dirs(store_root, name))
    resume_dir = None
    kill_log: list = []
    launches = 0
    missed = 0
    log_path = os.path.join(store_root, "soak-children.log")
    os.makedirs(store_root, exist_ok=True)
    while True:
        argv = argv_for(store_root, opts, resume=resume_dir)
        launches += 1
        with open(log_path, "ab") as lf:
            lf.write(f"\n=== launch {launches} (resume={resume_dir}) "
                     f"===\n".encode())
            lf.flush()
            proc = subprocess.Popen(argv, env=child_env(_mesh_devices(opts)),
                                    stdout=lf, stderr=subprocess.STDOUT)
            my_dir = None
            if len(kill_log) < kills:
                # wait for this launch's run dir, then for its first
                # checkpoint, then kill at a random moment (possibly
                # mid-write: durability must absorb that too)
                deadline = time.time() + launch_timeout_s
                ckpt = None
                while proc.poll() is None and time.time() < deadline:
                    if my_dir is None:
                        fresh = [d for d in run_dirs(store_root, name)
                                 if d not in known]
                        if fresh:
                            my_dir = fresh[-1]
                            ckpt = os.path.join(my_dir, CHECKPOINT_FILE)
                    elif os.path.exists(ckpt):
                        break
                    time.sleep(0.02)
                if proc.poll() is None and ckpt and os.path.exists(ckpt):
                    delay = rng.uniform(0, kill_jitter_s)
                    time.sleep(delay)
                    # freeze before the coup de grâce: a warm-cache
                    # child could otherwise outrun the kill, complete,
                    # and short the kill quota
                    try:
                        proc.send_signal(signal.SIGSTOP)
                    except ProcessLookupError:  # pragma: no cover
                        pass
                    if proc.poll() is None:
                        proc.send_signal(signal.SIGKILL)
                        proc.wait()
                        kill_log.append({"launch": launches,
                                         "dir": my_dir,
                                         "delay_s": round(delay, 3)})
                        log(f"  SIGKILL #{len(kill_log)} "
                            f"(launch {launches}, +{delay:.2f}s)")
            try:
                rc = proc.wait(timeout=launch_timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                raise RuntimeError(
                    f"soak launch {launches} hung; see {log_path}")
        known.update(run_dirs(store_root, name))
        if rc == 0:
            if len(kill_log) < kills:
                # the child completed before this cycle's kill landed
                # (it finished during the jitter sleep). Determinism
                # makes a redo equivalent: relaunch the SAME cycle —
                # from the escaped launch's own resume point, NOT the
                # completed run's final checkpoint (a resume one
                # cadence from the end can never be killed again) —
                # and draw a fresh kill delay.
                missed += 1
                if missed > 3:
                    raise RuntimeError(
                        f"could not land {kills} kills in "
                        f"{launches} launches ({len(kill_log)} landed); "
                        f"grow --time-limit or shrink kill_jitter_s")
                log(f"  launch {launches} completed before kill "
                    f"#{len(kill_log) + 1}; redoing the cycle")
                continue
            else:
                final = run_dirs(store_root, name)[-1]
                return {"dir": final, "launches": launches,
                        "kills": kill_log, "log": log_path}
        elif rc not in (-signal.SIGKILL, EXIT_PREEMPTED):
            raise RuntimeError(
                f"soak launch {launches} exited rc={rc} (expected kill "
                f"or preempt); see {log_path}")
        # resume from the newest run dir that owns a loadable
        # checkpoint — checkpoint.pkl or the prev-only state a kill
        # mid-save leaves (a launch killed before its first save
        # contributes nothing; the previous checkpoint still owns the
        # most progress)
        with_ckpt = [d for d in sorted(known, reverse=True)
                     if _has_checkpoint(d)]
        resume_dir = with_ckpt[0] if with_ckpt else None


def _strip_volatile(results: dict) -> dict:
    if "clusters" in results:
        # fleet results: volatile accounting lives at the fleet level
        # AND inside each per-cluster result block
        out = {k: v for k, v in results.items()
               if k not in VOLATILE_FLEET_KEYS}
        out["clusters"] = [_strip_volatile(c) for c in results["clusters"]]
        return out
    out = {}
    for k, v in results.items():
        if k in VOLATILE_RESULT_KEYS:
            continue
        if isinstance(v, dict):
            v = {k2: v2 for k2, v2 in v.items()
                 if k2 not in VOLATILE_SUBRESULT_KEYS}
        out[k] = v
    return out


def compare_runs(dir_a: str, dir_b: str) -> dict:
    """Bit-identity verdict between two completed runs' artifacts."""
    with open(os.path.join(dir_a, "history.jsonl"), "rb") as f:
        ha = f.read()
    with open(os.path.join(dir_b, "history.jsonl"), "rb") as f:
        hb = f.read()
    with open(os.path.join(dir_a, "results.json")) as f:
        ra = json.load(f)
    with open(os.path.join(dir_b, "results.json")) as f:
        rb = json.load(f)
    sa, sb = _strip_volatile(ra), _strip_volatile(rb)
    out = {"history_identical": ha == hb,
           "results_identical": sa == sb,
           "valid": (ra.get("valid"), rb.get("valid"))}
    if not out["results_identical"]:
        out["results_diff_keys"] = sorted(
            k for k in set(sa) | set(sb) if sa.get(k) != sb.get(k))
    # fleet runs: the per-cluster artifacts (cluster-XXXX/) must match
    # too — the top-level fleet history is a merged re-encoding, so a
    # bug confined to one cluster's stored rows would not show there
    def _clusters(d):
        return sorted(c for c in os.listdir(d) if c.startswith("cluster-")
                      and os.path.isdir(os.path.join(d, c)))
    ca, cb = _clusters(dir_a), _clusters(dir_b)
    if ca or cb:
        out["clusters_compared"] = len(set(ca) | set(cb))
        if ca != cb:
            out["history_identical"] = out["results_identical"] = False
            out["cluster_dirs"] = (ca, cb)
        for c in (ca if ca == cb else []):
            sub = compare_runs(os.path.join(dir_a, c),
                               os.path.join(dir_b, c))
            out["history_identical"] &= sub["history_identical"]
            if not sub["results_identical"]:
                out["results_identical"] = False
                out.setdefault("results_diff_keys", [])
                out["results_diff_keys"] += [
                    f"{c}:{k}" for k in sub.get("results_diff_keys", [])]
    return out


def soak(store_root: str, kills: int = 5, rng_seed: int = 0,
         mesh: str | None = None, opts_over: dict | None = None,
         log=lambda m: print(m, file=sys.stderr)) -> dict:
    """Baseline + kill/resume soak + bit-identity comparison."""
    import random
    rng = random.Random(rng_seed)
    opts = dict(DEFAULT_OPTS)
    if mesh:
        opts["--mesh"] = mesh
    opts.update(opts_over or {})
    base_root = os.path.join(store_root, "baseline")
    soak_root = os.path.join(store_root, "soak")
    os.makedirs(base_root, exist_ok=True)
    log(f"crash soak: baseline run ({opts['-w']}, mesh={mesh})")
    base_dir = run_once(base_root, opts,
                        os.path.join(base_root, "baseline.log"))
    log(f"crash soak: {kills} randomized SIGKILL+resume cycles")
    soaked = run_with_kills(soak_root, opts, kills, rng, log=log)
    verdict = compare_runs(base_dir, soaked["dir"])
    return {**verdict, "baseline_dir": base_dir, "soak_dir": soaked["dir"],
            "launches": soaked["launches"],
            "kills": len(soaked["kills"]), "kill_log": soaked["kills"]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="maelstrom_tpu.crash_soak",
        description="SIGKILL/resume soak: byte-identical recovery proof")
    ap.add_argument("--kills", type=int, default=5,
                    help="randomized SIGKILL+resume cycles (default 5)")
    ap.add_argument("--seed", type=int, default=0,
                    help="harness rng seed (kill timing)")
    ap.add_argument("--mesh", default=None,
                    help="run the child sharded, e.g. --mesh 1,2")
    ap.add_argument("--store", default=None,
                    help="store root (default: a fresh temp dir)")
    ap.add_argument("--time-limit", type=float, default=None,
                    help="child test duration in virtual seconds")
    args = ap.parse_args(argv)
    store = args.store
    if store is None:
        import tempfile
        store = tempfile.mkdtemp(prefix="maelstrom-crash-soak-")
    over = {}
    if args.time_limit is not None:
        over["--time-limit"] = str(args.time_limit)
    verdict = soak(store, kills=args.kills, rng_seed=args.seed,
                   mesh=args.mesh, opts_over=over)
    print(json.dumps(verdict, indent=2))
    ok = verdict["history_identical"] and verdict["results_identical"]
    print(("crash soak PASSED: byte-identical recovery" if ok else
           "crash soak FAILED: recovery diverged"), file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
