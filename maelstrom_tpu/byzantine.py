"""The Byzantine adversary layer: seeded wire corruption that checkers
must CONVICT.

Every other nemesis package is benign — nodes fail by stopping or
delaying, never by lying. The ``byzantine`` package corrupts *message
contents* mid-flight, attacking the two audit surfaces the repo already
built: the batched-broadcast expansion proofs (doc/perf.md,
`checkers/set_full.py verify_batch_proofs`) and the compartment's
end-to-end ballot fencing (doc/compartment.md). Three attack kinds:

  - ``equivocation``  — a compromised sequencer assigns the same slot
    different commands on different emissions (the corruption varies
    per round, so any two deliveries of one slot/ballot conflict).
  - ``forged-proof``  — a batched-broadcast node acks a `(lo, n,
    checksum)` range it never expanded: the count is inflated on odd
    rounds, the checksum forged on even ones.
  - ``stale-ballot``  — a sequencer's T_ASSIGN traffic is re-stamped
    with a ballot outside its own residue class, the wire-side replay
    of a deposed leader's fenced traffic (ballots are `k*S + me`, so
    an honest ballot always satisfies `bal % S == src`).

Acceptance is inverted relative to the benign packages: a byzantine run
is *valid only if every injected corruption kind is convicted* — a
`(rule, culprit, evidence)` triple in the ``byzantine`` results block —
and benign runs must stay conviction-free. Injected-but-unconvicted is
the framework's own test failure, not the adversary "winning".

Determinism: the attack plan (kind, culprit, nonce) comes from the
``byzantine`` `NemesisDecisions` stream (same contract as kill/pause),
and the per-round injection gate is a pure integer hash of
`(round, nonce)` — no PRNG state is consumed, so enabling the adversary
leaves every benign decision stream byte-identical. On the TPU path the
corruption is a compiled mask rewrite inside the jitted round
(`corrupt_pool` / `corrupt_edge` below — scatter one-hots, no host
transfers); the host path corrupts the delivered copy in `HostNet.send`
from the same decision stream, so both paths inject the identical
adversary schedule per seed (doc/faults.md).
"""

from __future__ import annotations

import jax.numpy as jnp

I32 = jnp.int32

# attack kinds, in decision-stream draw order; index = the device-side
# `byz["attack"]` code and the `injected` ledger slot
ATTACKS = ("equivocation", "forged-proof", "stale-ballot")

# conviction rule -> the attack kind it convicts. Checker rules are
# finer-grained than attack kinds (the proof auditor distinguishes a
# forged checksum from an inflated count), so the validity fold maps
# each rule back to the attack whose injection it proves.
RULE_ATTACK = {
    "equivocation": "equivocation",
    "stale-ballot": "stale-ballot",
    "forged-proof": "forged-proof",
    "forged-count": "forged-proof",
    "truncated-batch": "forged-proof",
    "malformed-ack": "forged-proof",
    "duplicate-in-batch": "forged-proof",
    "replayed-batch": "forged-proof",
}

# 2654435761 (Knuth's multiplicative hash) as a wrapped int32
_HASH_MULT = -1640531535

# payload fields a forged-proof host corruption touches; the host wire
# auditor classifies a send/recv body diff confined to these keys as a
# proof forgery (checkers/byzantine.py)
PROOF_FIELDS = ("lo", "n", "count", "proof", "batch_units")


def byz_enabled(opts) -> bool:
    """Whether the byzantine fault is in this run's fault set — the
    STATIC gate for program-side corruption hooks and evidence state
    (mirrors `runner.tpu_runner.TpuRunner._fault_set`). Static so that
    benign runs compile none of the adversary path and their state
    trees stay byte-identical."""
    pkg = opts.get("nemesis_pkg") or {}
    faults = pkg.get("faults")
    if faults is None:
        faults = opts.get("nemesis")
    if not faults:
        return False
    if isinstance(faults, str):
        return faults == "byzantine"
    if isinstance(faults, (set, frozenset, list, tuple)):
        return "byzantine" in faults
    return False


# --- device-side adversary state -------------------------------------------


def init_state() -> dict:
    """The zeroed adversary carry. Rides `SimState.byz` (a plain dict —
    pytree-friendly, donated with the rest of the carry) when the run's
    fault set includes byzantine; None otherwise, so benign carries are
    shape-identical to pre-adversary builds."""
    z = jnp.zeros((), I32)
    return {"active": z, "attack": z,
            "culprit": jnp.full((), -1, I32),
            "delta": jnp.ones((), I32),
            "rate_q": z,
            # corruptions applied so far, one slot per ATTACKS entry —
            # the ledger the conviction contract is audited against
            "injected": jnp.zeros((len(ATTACKS),), I32)}


def start_state(byz: dict, attack: str, culprit: int, delta: int,
                rate: float) -> dict:
    """start-byzantine surgery: installs one drawn plan (host-side
    scalars; the runner reshards the updated carry)."""
    return {**byz,
            "active": jnp.ones((), I32),
            "attack": jnp.full((), ATTACKS.index(attack), I32),
            "culprit": jnp.full((), int(culprit), I32),
            "delta": jnp.full((), int(delta), I32),
            "rate_q": jnp.full((), int(round(float(rate) * 1000)), I32)}


def stop_state(byz: dict) -> dict:
    """stop-byzantine surgery: deactivates injection, keeping the
    injected ledger (convictions are audited against the whole run)."""
    return {**byz, "active": jnp.zeros((), I32)}


def _gate(byz: dict, rnd):
    """The per-round injection gate: active AND a pure integer hash of
    (round, nonce) clears the rate threshold (permille). No PRNG state
    is consumed, so the benign decision streams never shift."""
    h = (rnd * I32(_HASH_MULT) + byz["delta"]) & I32(0x7FFFFFFF)
    return (byz["active"] > 0) & (h % 1000 < byz["rate_q"])


def culprit_rows(batch, culprit):
    """[N, L] mask selecting the culprit's outbox rows (src is the
    implicit leading row index pre-flatten)."""
    n = batch.valid.shape[0]
    return (jnp.arange(n, dtype=I32) == culprit)[:, None]


def _apply(wires: dict, byz: dict, batch, rnd):
    """Shared applier over one [N, L] Msgs batch: for each attack kind
    the program wires, rewrite the masked rows' payload words and book
    the injection count. Pure jnp — compiles into the round body."""
    gate = _gate(byz, rnd)
    injected = byz["injected"]
    for idx, name in enumerate(ATTACKS):
        fn = wires.get(name)
        if fn is None:
            continue
        mask, na, nb, nc = fn(batch, byz["culprit"], byz["delta"], rnd)
        m = mask & batch.valid & gate & (byz["attack"] == idx)
        batch = batch.replace(a=jnp.where(m, na, batch.a),
                              b=jnp.where(m, nb, batch.b),
                              c=jnp.where(m, nc, batch.c))
        injected = injected.at[idx].add(jnp.sum(m.astype(I32)))
    return {**byz, "injected": injected}, batch


def corrupt_pool(program, byz, outbox, rnd):
    """Applies this round's corruption to the pool-path [N, O] outbox,
    per the program's `byz_wire()` hook: {attack name: fn(outbox,
    culprit, delta, rnd) -> (mask, a, b, c)}. Programs without the hook
    (or attack kinds they don't wire) inject nothing — and an attack
    that injects nothing demands no conviction."""
    hook = getattr(program, "byz_wire", None)
    if byz is None or hook is None:
        return byz, outbox
    wires = hook()
    if not wires:
        return byz, outbox
    return _apply(wires, byz, outbox, rnd)


def corrupt_edge(program, byz, client_out, rnd):
    """The edge-path analogue over the [N, K] client-reply batch, per
    `byz_wire_edge()` (the forged-proof surface: batch acks)."""
    hook = getattr(program, "byz_wire_edge", None)
    if byz is None or hook is None:
        return byz, client_out
    wires = hook()
    if not wires:
        return byz, client_out
    return _apply(wires, byz, client_out, rnd)


# --- conviction assembly ---------------------------------------------------


def conviction(rule: str, culprit, evidence, witness=None) -> dict:
    """One conviction triple, as surfaced in the `byzantine` results
    block: the violated rule, the node it names, and the evidence that
    proves it. `code` is the definite Byzantine error (errors.py)."""
    from .errors import BYZANTINE
    out = {"rule": rule, "culprit": culprit, "evidence": evidence,
           "code": int(BYZANTINE.code)}
    if witness is not None:
        out["witness"] = witness
    return out


def assemble_block(convictions: list, injected: dict) -> dict:
    """Folds the run's convictions against its injection ledger into
    the `byzantine` results block. Valid iff every attack kind that
    injected at least one corruption has >= 1 conviction whose rule
    maps to it (RULE_ATTACK), and no conviction names an attack that
    injected nothing (a spurious conviction on a benign run is a
    checker bug — exactly as failing as a missed one)."""
    inj = {a: int(injected.get(a, 0)) for a in ATTACKS}
    convicted: set = set()
    spurious: list = []
    for c in convictions:
        atk = RULE_ATTACK.get(c.get("rule"))
        if atk is not None and inj.get(atk, 0) > 0:
            convicted.add(atk)
        else:
            spurious.append(c.get("rule"))
    unconvicted = sorted(a for a, k in inj.items()
                         if k > 0 and a not in convicted)
    return {"convictions": list(convictions), "injected": inj,
            "unconvicted": unconvicted, "spurious": spurious,
            "valid": not unconvicted and not spurious}
