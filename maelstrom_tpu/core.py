"""Test composition and execution: the maelstrom-test equivalent.

Builds a full test from parsed options — network, db, workload, nemesis,
composed generator phases, composed checker suite — and runs it end to end,
writing artifacts to the store dir (reference `core.clj:44-91` plus the
jepsen.core/run! orchestration).

Two execution paths, selected like the reference's `--bin` plugin boundary:
  - bin path: external node binaries on the host network (HostDB)
  - tpu path: built-in batched node programs on the TPU network
    (`maelstrom_tpu.runner.tpu_runner`), selected with --node tpu:<name>
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass

from . import generators as g
from . import nemesis as nem
from . import store
from .checkers import Compose, Stats, UnhandledExceptions
from .checkers.netstats import NetStatsChecker
from .checkers.perf import PerfChecker, TimelineChecker
from .db import HostDB
from .net.host import HostNet
from .net.journal import Journal
from .runner.host_runner import run_test as run_host_test
from .workloads import registry

log = logging.getLogger("maelstrom")

DEFAULTS = dict(
    workload="lin-kv", node_count=None, nodes=None, rate=5.0,
    time_limit=10.0, concurrency=None, latency={"mean": 0,
                                                "dist": "constant"},
    nemesis=set(), nemesis_interval=10.0, topology="grid",
    key_count=None, max_txn_length=4, max_writes_per_key=16,
    consistency_models=["strict-serializable"], log_stderr=False,
    log_net_send=False, log_net_recv=False, seed=0, store_root="store",
    client_retries=0, client_backoff_ms=50.0, client_backoff_cap_ms=2000.0,
    # TPU-path scale-out: "dp,sp" device-mesh spec (None = single chip);
    # recorded in the stored test map so a mesh run is reproducible
    mesh=None,
    # overlapped analysis pipeline: background workers that pair,
    # partition, and screen drained history segments while the device
    # runs the next stretch (None = runner default of 1; --no-overlap
    # or check_workers=0 force the sequential analysis path)
    check_workers=None, no_overlap=False,
    # device-resident grading (doc/perf.md): the txn-list-append (elle)
    # checker's edge construction + cycle screen run jitted on the
    # device. "auto" engages past elle_device.AUTO_MIN_TXNS
    # transactions; "on"/"off" force it. Verdicts are bit-equal to the
    # host path on every setting.
    device_checker="auto",
    # preemption-tolerant execution (doc/checkpoint.md): periodic
    # crash-consistent checkpoints off the critical path (background
    # writer unless sync_checkpoint), and SIGTERM/SIGINT graceful
    # shutdown (on_preempt="checkpoint" writes a final checkpoint and
    # exits EXIT_PREEMPTED for a supervised --resume relaunch)
    checkpoint_every=None, resume=None, sync_checkpoint=False,
    on_preempt="checkpoint",
    # static-audit self-report (doc/analyze.md): TPU-path results carry
    # a `static-audit` block (rule counts, baseline-suppressed count,
    # audit wall time). `audit` gates the block entirely; `audit_trace`
    # additionally traces this run's own step functions (the CLI turns
    # it on; library/test callers keep the cheap lint+config-only block)
    audit=True, audit_trace=False,
    # fleet execution (doc/perf.md): --fleet N runs N independent
    # cluster instances inside ONE compiled scan (vmapped over a leading
    # cluster axis, sharded dp under --mesh dp,sp); --fleet-sweep picks
    # the dimension the campaign varies per cluster. nemesis_seed
    # decouples the fault-schedule RNG from the workload seed (the
    # `nemesis` sweep; None = follow seed).
    fleet=1, fleet_sweep="seed", nemesis_seed=None,
    # network weather baseline (doc/streams.md): loss probability and
    # ABSOLUTE latency scale (the slow!/fast! knob) applied identically
    # to the host net and the TPU NetState — the `weather` nemesis
    # toggles both mid-run and stop-weather restores these baselines
    p_loss=None, latency_scale=1.0,
    # continuous generator mode (doc/streams.md): client ops are
    # injected at their seeded offered-rate rounds INSIDE the compiled
    # scan window (open-world stream) instead of one dispatch per op;
    # TPU path only, same-seed runs byte-identical plain, --mesh, and
    # as a --fleet cluster (the vectorized host driver, doc/perf.md).
    # continuous_window_ms is the stream stride: windows cross replies,
    # and the stride bounds a backlogged op's emission delay
    continuous=False, continuous_window_ms=250.0,
    # streaming kafka (doc/streams.md): kafka_groups > 0 switches the
    # kafka workload to consumer groups — long-lived subscriptions,
    # cursor-based fetches (no O(prefix) replies), coordinator-driven
    # rebalancing on membership change, per-group offset commits
    kafka_groups=0, session_timeout_ms=2500.0, poll_batch=8,
    # batched atomic broadcast (doc/perf.md "batched atomic broadcast"):
    # the distiller's batch shape for the broadcast-batched workload —
    # up to batch_max fresh values per batch, a batch_dup_rate fraction
    # of duplicate re-submissions collapsed by distillation
    batch_max=16, batch_dup_rate=0.25,
    # flight recorder (doc/observability.md): --telemetry DIR turns on
    # the device metric rings (an int32 block in the compiled scan
    # carry, drained on the existing dispatch fetches), Chrome-trace
    # phase spans (trace.json), and the telemetry.jsonl window stream.
    # None/off = fully compiled out; histories are byte-identical
    # either way.
    telemetry=None,
    # role-partitioned clusters (doc/compartment.md): `roles` sizes the
    # compartmentalized consensus tiers (--node tpu:compartment;
    # "proxies=P,acceptors=RxC,replicas=R"), `service_roles` the
    # in-cluster service nodes (--node tpu:services), and
    # `nemesis_targets` scopes fault packages to named role groups
    # ("kill=proxies,partition=acceptor-col-0")
    roles=None, service_roles=None, nemesis_targets=None,
    # byzantine adversary (doc/faults.md "byzantine is a conviction
    # driver"): --nemesis byzantine corrupts messages instead of
    # delivery. byz_rate is the per-round injection probability while a
    # window is open (a pure hash gate — no PRNG stream is consumed);
    # byz_attacks restricts the drawn attack kinds (comma list from
    # byzantine.ATTACKS; None = all). Both fingerprint keys: a resumed
    # run must replay the identical adversary.
    byz_rate=1.0, byz_attacks=None,
    # leader election + failover (doc/compartment.md "leader
    # election"): with --roles sequencers=S (S > 1) the compartment's
    # sequencer is ELECTED — ballot-numbered MultiPaxos phase 1 over
    # the acceptor grid. election_timeout_rounds is the failure-
    # detector deadline, ballot_width the fenced ballot-counter width
    # (bits, <= 6); availability_dip_rounds overrides the availability
    # checker's dip threshold (default: the RPC timeout in rounds).
    election_timeout_rounds=60, ballot_width=6,
    availability_dip_rounds=None,
    # client-side leader lease (doc/compartment.md "client lease"):
    # the host's leader guess expires leader_lease_ms of virtual time
    # after the last reply from it, so ops stop piling onto a dead
    # leader's RPC timeout — the failover dip shrinks toward the
    # detection window. None = derived default (2x the election
    # timeout); 0 disables (the pre-lease posture). S == 1 ignores it.
    leader_lease_ms=None,
    # the ordering-layer axis (doc/ordering.md): --ordering
    # raft|compartment|batched runs the workload's state machine as a
    # deterministic applier over that ordering engine's stream
    # (`maelstrom_tpu/ordering/`), graded by the workload's stock
    # checker. None = the workload's welded default program.
    ordering=None,
    # client-session bookkeeping backend (doc/perf.md "columnar client
    # sessions"): "columnar" holds pending/timeout/backoff/redirect
    # state in shared numpy columns advanced one vectorized pass per
    # wave; "coroutine" keeps the per-shell dict/list path. None =
    # columnar under --fleet, coroutine standalone. Byte-identical
    # histories either way (pinned by tests).
    sessions=None,
)

# Keys build_test ADDS to a test dict (derived objects, not user
# options): stripped when re-deriving per-cluster option sets for a
# fleet run, so each cluster's test is rebuilt from scratch exactly as
# a standalone run with those options would be.
_BUILT_KEYS = frozenset({"net", "workload_map", "client", "generator",
                         "checker", "nemesis_pkg", "store_dir",
                         "analysis"})


@dataclass(frozen=True)
class FleetSpec:
    """The fleet campaign described by `--fleet N --fleet-sweep <dim>`:
    N independent cluster instances advancing in lockstep inside one
    compiled scan, differing along ONE swept dimension:

      - ``seed``:     cluster i runs with seed base + i — workload op
                      stream, sim PRNG, and nemesis schedule all follow
                      the seed (the fuzzing/soak campaign).
      - ``nemesis``:  the workload/op stream is fixed (base seed); only
                      the nemesis decision streams vary
                      (nemesis_seed = base + i): same ops under N
                      independent fault schedules.
      - ``capacity``: seed fixed, offered load ramps — cluster i runs
                      at rate * (i + 1): a capacity sweep in one
                      dispatch.

    Static shapes (node count, concurrency, capacities, fault packages)
    stay uniform across the fleet: they define the ONE compiled program
    every cluster shares. All three sweeps compose with `--continuous`
    (each cluster streams its own open-world schedule; the capacity
    sweep ramps the offered rate per stream). The per-cluster contract
    is bit-identity: cluster i's history equals the standalone run of
    `cluster_opts(i)` (pinned by tests/test_fleet_runner.py and
    tests/test_fleet_continuous.py)."""

    fleet: int = 1
    sweep: str = "seed"

    SWEEPS = ("seed", "nemesis", "capacity")

    @classmethod
    def from_test(cls, test: dict) -> "FleetSpec":
        raw = test.get("fleet")
        fleet = 1 if raw is None else int(raw)
        sweep = str(test.get("fleet_sweep") or "seed")
        if fleet < 1:
            raise ValueError(f"--fleet must be >= 1, got {fleet}")
        if sweep not in cls.SWEEPS:
            raise ValueError(f"--fleet-sweep {sweep!r}: expected one of "
                             f"{list(cls.SWEEPS)}")
        return cls(fleet=fleet, sweep=sweep)

    def cluster_opts(self, test: dict, i: int) -> dict:
        """The option set whose STANDALONE run cluster i replays
        bit-identically: the base test's buildable options plus this
        cluster's swept value. Fleet-level mechanics — mesh placement,
        checkpoint cadence/files, per-message journaling, the
        static-audit block — are owned by the fleet and stripped or
        forced here."""
        opts = {k: v for k, v in test.items() if k not in _BUILT_KEYS}
        opts.update(
            # checkpoint_every is KEPT: each shell's dispatch loop
            # requests snapshots at its own stretch boundaries and the
            # fleet coalesces them into one checkpoint file (cadence is
            # observationally neutral — see checkpoint.py)
            fleet=1, fleet_sweep=self.sweep, mesh=None, resume=None,
            # per-message journal rows are a small-run debugging aid;
            # the fleet scan drains only the reply rings
            journal_rows=False,
            # ONE static-audit block at the fleet level (per-cluster
            # blocks would repeat the identical trace F times)
            audit=False, audit_trace=False)
        # windowed grading is the default posture at EVERY fleet size:
        # shells multiplex over one shared AnalysisPool sized by
        # --check-workers (checkers/pipeline.py), so a fleet of 512
        # costs a few grader threads, not 512 (the old past-16
        # no_overlap opt-out is gone)
        base_seed = int(test.get("seed", 0) or 0)
        if self.sweep == "seed":
            opts["seed"] = base_seed + i
        elif self.sweep == "nemesis":
            opts["nemesis_seed"] = base_seed + i
        else:   # capacity: offered-load ramp
            opts["rate"] = float(test.get("rate", DEFAULTS["rate"])) \
                * (i + 1)
        return opts


def parse_nodes(opts: dict) -> list[str]:
    """--node-count N overrides --nodes, generating n0..n(N-1)
    (reference `core.clj:197-204`). Role-partitioned node families
    (--node tpu:compartment / tpu:services) derive their node count
    from the role spec when neither is given."""
    if opts.get("node_count"):
        return [f"n{i}" for i in range(opts["node_count"])]
    if opts.get("nodes"):
        return opts["nodes"]
    spec = str(opts.get("node") or "")
    if spec.startswith("tpu:"):
        from .nodes import partition_node_count
        n = partition_node_count(spec[len("tpu:"):], opts)
        if n:
            return [f"n{i}" for i in range(n)]
    return ["n0", "n1", "n2", "n3", "n4"]


def build_test(opts: dict) -> dict:
    opts = {**DEFAULTS, **opts}
    if opts.get("ordering"):
        # the ordering axis (doc/ordering.md) runs the composed
        # engine x applier program; an explicit conflicting --node is
        # a config error, not something to silently override
        node = opts.get("node")
        if node and str(node) != "tpu:ordered":
            raise ValueError(
                f"--ordering {opts['ordering']!r} selects the composed "
                f"program tpu:ordered; drop --node {node} (the engine "
                f"is the ordering axis, the applier is the workload)")
        opts["node"] = "tpu:ordered"
    nodes = parse_nodes(opts)
    opts["nodes"] = nodes
    if not opts.get("concurrency"):
        opts["concurrency"] = len(nodes)
    name = opts.get("name") or str(opts["workload"])

    net = HostNet(latency=opts["latency"], log_send=opts["log_net_send"],
                  log_recv=opts["log_net_recv"], seed=opts["seed"])
    # p_loss/latency_scale flow SYMMETRICALLY to both network paths:
    # the host net here, the TPU NetState in TpuRunner._build_sim —
    # same option keys, same values, so --p-loss/--latency-scale runs
    # are path-equivalent (an explicit 0.0 is installed too, not
    # truthiness-skipped). The weather nemesis restores exactly these.
    if opts.get("p_loss") is not None:
        net.p_loss = float(opts["p_loss"])
    if opts.get("latency_scale") is not None:
        net.latency_dist = net.latency_dist.unscaled().scaled(
            float(opts["latency_scale"]))
    opts["net"] = net
    workload = registry()[opts["workload"]](opts)

    nemesis_pkg = nem.package(set(opts["nemesis"]),
                              interval_s=opts["nemesis_interval"])
    if opts.get("byz_attacks") is not None:
        from .byzantine import ATTACKS
        raw = opts["byz_attacks"]
        atks = tuple(s.strip() for s in str(raw).split(",")
                     if s.strip()) \
            if isinstance(raw, str) else tuple(raw)
        bad = [a for a in atks if a not in ATTACKS]
        if bad or not atks:
            raise ValueError(f"--byz-attacks: unknown attack(s) {bad}; "
                             f"expected any of {list(ATTACKS)}")
        opts["byz_attacks"] = atks

    # Generator composition (reference core.clj:58-71)
    rate = opts["rate"]
    if rate > 0:
        main = g.stagger(1.0 / rate, workload["generator"])
    else:
        main = g.sleep(opts["time_limit"])
    main = g.time_limit(opts["time_limit"],
                        g.nemesis_wrap(nemesis_pkg["generator"], main))
    # Final phases (reference core.clj:66-71): the nemesis ALWAYS heals
    # every fault type it injected — restart killed nodes, resume paused
    # ones, drop partitions, stop duplication — so checkers grade a
    # recovered cluster; workloads with a final generator then get their
    # recovery window and final reads.
    phase_list = [main]
    if nemesis_pkg["final_generator"] is not None:
        phase_list.append(g.nemesis_gen(nemesis_pkg["final_generator"]))
    if workload.get("final_generator") is not None:
        phase_list += [g.Log("Waiting for recovery..."),
                       g.sleep(opts.get("recovery_s", 10)),
                       g.clients(workload["final_generator"])]
    main = g.phases(*phase_list)

    checker = Compose({
        "perf": PerfChecker(),
        "timeline": TimelineChecker(),
        "exceptions": UnhandledExceptions(),
        "stats": Stats(),
        "net": NetStatsChecker(net),
        "workload": workload["checker"],
    })
    if "byzantine" in set(opts["nemesis"]):
        # the host-path wire auditor (run_tpu_test swaps in the
        # device-evidence checker); Compose assembles the `byzantine`
        # results block from every checker's convictions
        from .checkers.byzantine import ByzantineChecker
        checker.checkers["byzantine"] = ByzantineChecker(net)

    test = {**opts,
            "name": name,
            "net": net,
            "workload_map": workload,
            "client": workload.get("client"),
            "generator": main,
            "checker": checker,
            "nemesis_pkg": nemesis_pkg}
    return test


def run(opts: dict) -> dict:
    """Runs a complete test: setup, drive, teardown, check, store.
    Returns the results map (with "valid")."""
    test = build_test(opts)
    net: HostNet = test["net"]
    test_dir = store.make_test_dir(test["store_root"], test["name"])
    test["store_dir"] = test_dir
    net.journal = Journal(dir=os.path.join(test_dir, "net-journal"))

    # persist the console log alongside the results (the reference's
    # jepsen.log, doc/results.md:17)
    log_handler = logging.FileHandler(os.path.join(test_dir, "run.log"))
    log_handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s [%(name)s] %(message)s"))
    logging.getLogger().addHandler(log_handler)
    try:
        return _run(test, net, test_dir)
    finally:
        logging.getLogger().removeHandler(log_handler)
        log_handler.close()


def _run(test: dict, net: HostNet, test_dir: str) -> dict:

    node_spec = test.get("node")
    if node_spec and str(node_spec).startswith("tpu:"):
        from .runner.tpu_runner import run_tpu_test
        return run_tpu_test(test, test_dir)

    if not test.get("bin"):
        raise ValueError("Expected a --bin PATH_TO_BINARY to test "
                         "(or --node tpu:<name>)")

    db = HostDB(net, test["bin"], test.get("bin_args") or [],
                service_seed=test["seed"])
    # host-path role targeting: bin processes have no role partition,
    # so target groups resolve against literal node names only
    targets = nem.resolve_targets(test.get("nemesis_targets"), {},
                                  test["nodes"])
    # captured BEFORE test["nemesis"] is rebound to the nemesis object
    byz_on = "byzantine" in set(test.get("nemesis") or ())
    test["nemesis"] = (nem.CombinedNemesis(net, test["nodes"],
                                           seed=test["seed"], db=db,
                                           targets=targets,
                                           attacks=test.get("byz_attacks"),
                                           # NOT `or 1.0`: an explicit
                                           # rate of 0.0 must stick
                                           byz_rate=1.0
                                           if test.get("byz_rate") is None
                                           else float(test["byz_rate"]))
                       if test["nemesis_pkg"]["generator"] is not None
                       else None)
    log.info("Running test %s with nodes %s", test["name"], test["nodes"])
    crashes = []
    try:
        db.setup(test)
        history = run_host_test(test)
    finally:
        crashes = db.teardown()
        net.journal.close()

    for e in crashes:
        log.error("node crash: %s", e)
    if byz_on:
        # host injection ledger (HostNet._corrupt books every rewrite):
        # the conviction contract grades against it, same as the TPU
        # path's device ledger
        from .byzantine import ATTACKS
        test["byz_injected"] = {a: int(net.byz_injected.get(a, 0))
                                for a in ATTACKS}
    results = test["checker"].check(test, history, {})
    if crashes:
        results["node-crashes"] = [str(e) for e in crashes]
        results["valid"] = False
    store.write_history(test_dir, history)
    store.write_results(test_dir, results)
    # t0 lets offline analyses (parity_ackstamp) align node-process
    # monotonic stamps with the history's relative-ns timeline
    store.write_test(test_dir,
                     {**{k: test[k] for k in DEFAULTS if k in test},
                      "t0_monotonic_ns": net.t0})
    store.mark_complete(test_dir)
    log.info("Results valid? %s (store: %s)", results["valid"], test_dir)
    return results
