"""maelstrom_tpu: a TPU-native workbench for toy distributed systems.

A brand-new framework with the capabilities of Maelstrom (reference:
jepsen-io/maelstrom): simulated networks with latency distributions, message
loss and partitions; Jepsen-style workload generators, histories and fault
injection; built-in consistency services; network journals and Lamport
diagrams; and checkers up to linearizability and strict serializability.

Instead of one OS process per node (reference `process.clj:168-215`), nodes
are rows of device arrays stepped in lockstep by jitted/vmapped JAX state
machines; the network is scatter/gather over a node-id axis
(reference `net.clj:188-246` becomes `maelstrom_tpu.net.tpu`); faults are
boolean masks. A host compatibility path (`maelstrom_tpu.process`) still runs
external node binaries over newline-delimited JSON stdio, exactly like the
reference.
"""

__version__ = "0.2.0"

# Lazy public API: resolving on first access keeps `import maelstrom_tpu`
# free of jax/numpy imports (several entry points re-pin the platform
# before touching jax, and the CLI wants fast --help).
_EXPORTS = {
    "run": ".core",
    "build_test": ".core",
    "History": ".history",
    "Op": ".history",
    "Journal": ".net.journal",
    "HostNet": ".net.host",
    "SyncClient": ".client",
    "fuzz_broadcast": ".fuzz",
    "honor_jax_platforms": ".util",
}

__all__ = sorted(_EXPORTS) + ["__version__"]


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    obj = getattr(importlib.import_module(mod, __name__), name)
    globals()[name] = obj       # cache: later accesses skip __getattr__
    return obj


def __dir__():
    return __all__
