"""Multi-host DCN execution check (SURVEY.md section 5.8).

`parallel.multihost_mesh` claims the sharded cluster round scales
across hosts with no application changes — XLA routing the mesh
collectives over DCN instead of ICI. This module EXECUTES that claim
without TPU pod hardware: two OS processes, each owning 4 virtual CPU
devices, join one `jax.distributed` cluster (gloo over loopback TCP —
the same cross-process transport shape as DCN), build the global
("dp", "sp") mesh over all 8 devices, and drive the REAL broadcast
cluster round — partitions and message loss active — sharded across
both processes.

Every process also runs the identical simulation unsharded on its
device 0 and digests both final states with the same order-sensitive
checksum. The run passes iff the cross-process sharded digest equals
the local unsharded digest on every process: the multi-host path
preserves semantics bit-for-bit, executed over real cross-process
collectives (not just compiled).

Usage (the test and `python -m maelstrom_tpu.dcn_check` drive this):
    dcn_check worker <process_id> <port>     # run one process
"""

from __future__ import annotations

import json
import os
import sys


def _digest(tree):
    """Order-sensitive int32 wrap-around checksum of every array leaf,
    computed under jit so the sharded case reduces with the mesh's own
    collectives; identical across backends for identical values."""
    import jax
    import jax.numpy as jnp

    total = jnp.zeros((), jnp.int32)
    for leaf in jax.tree.leaves(tree):
        flat = jnp.ravel(leaf).astype(jnp.int32)
        w = (jnp.arange(flat.shape[0], dtype=jnp.int32) % 997) + 1
        total = total + jnp.sum(flat * w, dtype=jnp.int32)
    return total


def worker(process_id: int, port: int, rounds: int = 12,
           n_clusters: int = 4, n_nodes: int = 16) -> dict:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from .parallel import multihost_mesh

    # before any other JAX API: distributed init must precede backend up
    mesh = multihost_mesh(coordinator_address=f"localhost:{port}",
                          num_processes=2, process_id=process_id, dp=2)

    import jax.numpy as jnp

    from .net import tpu as T
    from .nodes import get_program
    from .parallel import (make_cluster_round_fn, make_cluster_sims,
                           sim_shardings)

    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4
    sp = mesh.shape["sp"]

    nodes = [f"n{i}" for i in range(n_nodes)]
    program = get_program(
        "broadcast",
        {"topology": "grid", "max_values": 8, "latency": {"mean": 0}},
        nodes)
    cfg = T.NetConfig(n_nodes=n_nodes, n_clients=1, pool_cap=32 * sp,
                      inbox_cap=program.inbox_cap, client_cap=4)
    inject = T.Msgs.empty((n_clusters, 2))
    inject = inject.replace(
        valid=inject.valid.at[:, 0].set(True),
        src=jnp.full_like(inject.src, n_nodes),
        type=jnp.full_like(inject.type, 10))          # T_BCAST

    split = jnp.asarray([0] * (n_nodes // 2) + [1] * (n_nodes // 2),
                        jnp.int32)

    def set_comp(sims, labels):
        net = sims.net
        return sims.replace(net=net.replace(
            component=net.component.at[:, :n_nodes].set(labels[None, :])))

    def drive(sims, fn):
        for i in range(rounds):
            if i == 3:
                sims = set_comp(sims, split)
            if i == 8:
                sims = set_comp(sims, jnp.zeros_like(split))
            sims, _cm, _io = fn(sims, inject)
        return sims

    def prep(sims):
        return sims.replace(net=sims.net.replace(
            p_loss=jnp.full_like(sims.net.p_loss, 0.05)))

    # local unsharded reference (device 0 of this process)
    sims_u = prep(make_cluster_sims(program, cfg, n_clusters, seed=0))
    sims_u = drive(sims_u, make_cluster_round_fn(program, cfg))
    digest_u = int(jax.device_get(jax.jit(_digest)(sims_u)))

    # the same simulation sharded over the GLOBAL 2-process mesh:
    # dp crosses the process boundary, so every round's collectives
    # ride the cross-process (gloo/DCN) transport
    sims_s = prep(make_cluster_sims(program, cfg, n_clusters, seed=0))
    sims_s = jax.device_put(sims_s, sim_shardings(mesh, sims_s))
    inj_s = jax.device_put(inject, sim_shardings(mesh, inject))
    fn_s = make_cluster_round_fn(program, cfg, mesh=mesh,
                                 example=sims_s, example_inject=inj_s)
    with mesh:
        sims_s = drive(sims_s, lambda s, i: fn_s(s, inj_s))
        # a sharded array spans non-addressable devices: reduce to
        # explicitly-replicated scalars on device, then read this
        # process's local shard (every process sees the same values)
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(mesh, P())

        def pull(s):
            st = s.net.stats
            return (_digest(s),
                    jnp.sum(st.dropped_partition.astype(jnp.int32)),
                    jnp.sum(st.lost.astype(jnp.int32)),
                    jnp.sum(st.dropped_overflow.astype(jnp.int32)))
        vals = jax.jit(pull, out_shardings=rep)(sims_s)
        digest_s, drop_part, lost_n, drop_ovf = (
            int(np.asarray(v.addressable_shards[0].data)) for v in vals)
        stats = {"dropped_partition": drop_part, "lost": lost_n,
                 "dropped_overflow": drop_ovf}

    out = {"process": process_id,
           "devices_global": len(jax.devices()),
           "devices_local": len(jax.local_devices()),
           "mesh": dict(mesh.shape),
           "rounds": rounds,
           "digest_unsharded": digest_u,
           "digest_sharded": digest_s,
           "match": digest_u == digest_s,
           "dropped_partition": stats["dropped_partition"],
           "lost": stats["lost"],
           "dropped_overflow": stats["dropped_overflow"]}
    print(json.dumps(out), flush=True)
    if not out["match"]:
        raise SystemExit(2)
    if not (stats["dropped_partition"] > 0 and stats["lost"] > 0):
        raise SystemExit(3)       # the faults must actually have fired
    return out


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if argv and argv[0] == "worker":
        worker(int(argv[1]), int(argv[2]))
        return 0
    # launcher: spawn both processes and require both to pass. Default
    # port varies by pid so a stale coordinator from a killed run can't
    # wedge the next one; on any failure/timeout both children are
    # reaped and their stderr tails surfaced.
    import subprocess
    port = int(argv[0]) if argv else 12000 + os.getpid() % 4000
    procs = [subprocess.Popen(
        [sys.executable, "-m", "maelstrom_tpu.dcn_check", "worker",
         str(i), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=540))
    except subprocess.TimeoutExpired:
        outs.append(("", "(timed out)"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    ok = all(p.returncode == 0 for p in procs)
    for o, err in outs:
        print(o.strip().splitlines()[-1] if o.strip()
              else f"(no output; stderr tail: {err.strip()[-400:]})")
    print(json.dumps({"dcn_check": "ok" if ok else "FAIL"}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
