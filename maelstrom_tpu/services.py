"""Built-in services: infrastructure nodes Maelstrom runs for your nodes.

Reimplements `src/maelstrom/service.clj`: pure persistent state machines
(`PersistentKV` read/write/cas with create_if_not_exists, `LWWKV` with
Lamport clocks and last-write-wins merge, `PersistentTSO`) wrapped in
consistency adapters:

  - Linearizable: all ops act on the single latest state
    (`service.clj:141-149`)
  - Sequential: ops may act on any past state consistent with per-client
    monotonicity; state-changing ops jump to the newest state
    (`service.clj:161-209`)
  - Eventual: n independent replicas, randomly gossiped/merged
    (`service.clj:213-242`)

Default services (`service.clj:289-295`): lww-kv (eventual LWWKV), seq-kv
(sequential KV), lin-kv (linearizable KV), lin-tso (linearizable TSO).

Services are *pure handlers* plus thin adapters, so the same implementations
run as host threads (reference style, `service_thread`) or synchronously
inside the TPU runner's virtual-time loop.
"""

from __future__ import annotations

import logging
import random
import threading
from collections import deque

from .errors import error_body

log = logging.getLogger("maelstrom.service")


# --- Persistent (pure) services -------------------------------------------

class PersistentKV:
    """Immutable KV state machine (reference `service.clj:31-56`)."""

    def __init__(self, m: dict | None = None):
        self.m = m if m is not None else {}

    def handle(self, message):
        body = message.body
        k = _key(body.get("key"))
        t = body["type"]
        if t == "read":
            if k in self.m:
                return self, {"type": "read_ok", "value": self.m[k]}
            return self, error_body(20, "key does not exist")
        if t == "write":
            return (PersistentKV({**self.m, k: body.get("value")}),
                    {"type": "write_ok"})
        if t == "cas":
            if k in self.m:
                if body.get("from") == self.m[k]:
                    return (PersistentKV({**self.m, k: body.get("to")}),
                            {"type": "cas_ok"})
                return self, error_body(
                    22, f"current value {self.m[k]!r} is not "
                        f"{body.get('from')!r}")
            if body.get("create_if_not_exists"):
                return (PersistentKV({**self.m, k: body.get("to")}),
                        {"type": "cas_ok"})
            return self, error_body(20, "key does not exist")
        return self, error_body(10, f"unsupported op {t!r}")

    def __eq__(self, other):
        return isinstance(other, PersistentKV) and self.m == other.m


class LWWKV:
    """Last-write-wins KV with a Lamport clock; values carry timestamps and
    merge by (ts, then keep-ours) (reference `service.clj:65-106`)."""

    def __init__(self, clock: int = 0, m: dict | None = None):
        self.clock = clock
        self.m = m if m is not None else {}   # key -> (ts, value)

    def handle(self, message):
        body = message.body
        k = _key(body.get("key"))
        t = body["type"]
        if t == "read":
            if k in self.m:
                return self, {"type": "read_ok", "value": self.m[k][1]}
            return self, error_body(20, "key does not exist")
        if t == "write":
            return (LWWKV(self.clock + 1,
                          {**self.m, k: (self.clock, body.get("value"))}),
                    {"type": "write_ok"})
        if t == "cas":
            if k in self.m:
                if body.get("from") == self.m[k][1]:
                    return (LWWKV(self.clock + 1,
                                  {**self.m, k: (self.clock,
                                                 body.get("to"))}),
                            {"type": "cas_ok"})
                return self, error_body(
                    22, f"current value {self.m[k][1]!r} is not "
                        f"{body.get('from')!r}")
            return self, error_body(20, "key does not exist")
        return self, error_body(10, f"unsupported op {t!r}")

    def merge(self, other: "LWWKV") -> "LWWKV":
        """Lamport-clock max; per-key merge by timestamp, ties keep ours
        (reference `service.clj:93-106`)."""
        m = dict(self.m)
        for k, (ts2, v2) in other.m.items():
            if k not in m or m[k][0] < ts2:
                m[k] = (ts2, v2)
        return LWWKV(max(self.clock, other.clock), m)

    def __eq__(self, other):
        return (isinstance(other, LWWKV) and self.clock == other.clock
                and self.m == other.m)


class PersistentTSO:
    """Monotonic timestamp oracle starting at 0
    (reference `service.clj:116-122`)."""

    def __init__(self, ts: int = 0):
        self.ts = ts

    def handle(self, message):
        body = message.body
        if body["type"] == "ts":
            return PersistentTSO(self.ts + 1), {"type": "ts_ok",
                                                "ts": self.ts}
        return self, error_body(10, f"unsupported op {body['type']!r}")

    def __eq__(self, other):
        return isinstance(other, PersistentTSO) and self.ts == other.ts


def _key(k):
    """JSON object keys are strings; normalize numeric keys the way JSON
    round-tripping would, so `0` and `"0"` behave consistently."""
    return k


# --- Consistency adapters -------------------------------------------------

class Linearizable:
    """All ops act atomically on the latest state
    (reference `service.clj:141-149`)."""

    def __init__(self, state):
        self.state = state
        self.lock = threading.Lock()

    def handle(self, message) -> dict:
        with self.lock:
            self.state, res = self.state.handle(message)
            return res


class Sequential:
    """Ops may act on any past state consistent with each client's monotonic
    view; state-changing ops jump to the newest state
    (reference `service.clj:161-209`)."""

    def __init__(self, state, buffer_size: int = 32, seed: int = 0):
        self.buffer = deque([state], maxlen=buffer_size)
        self.last_index = 0
        self.clients: dict[str, int] = {}
        self.rng = random.Random(seed)
        self.lock = threading.Lock()

    def handle(self, message) -> dict:
        client = message.src
        with self.lock:
            client_index = self.clients.get(client, 0)
            # States older than the ring buffer retains are unreachable;
            # clamp lagging clients forward to the oldest retained state.
            oldest = self.last_index - len(self.buffer) + 1
            client_index = max(client_index, oldest)
            span = self.last_index - client_index
            index = client_index + (self.rng.randrange(span + 1)
                                    if span > 0 else 0)
            # negative offset into buffer: -1 is last_index
            service = self.buffer[index - self.last_index - 1]
            service2, res = service.handle(message)
            if service2 == service:
                # read-only on a past state: timeline safe
                self.clients[client] = index
                return res
            # state-changing: execute on the newest state instead
            service2, res = self.buffer[-1].handle(message)
            self.last_index += 1
            self.clients[client] = self.last_index
            self.buffer.append(service2)
            return res


class Eventual:
    """n independent replicas; each op first gossips one random replica into
    another, then applies to a random replica
    (reference `service.clj:213-242`)."""

    def __init__(self, state, n: int = 2, seed: int = 0):
        self.replicas = [state] * n
        self.rng = random.Random(seed)
        self.lock = threading.Lock()

    def handle(self, message) -> dict:
        with self.lock:
            n = len(self.replicas)
            src, dst = self.rng.randrange(n), self.rng.randrange(n)
            self.replicas[dst] = self.replicas[src].merge(self.replicas[dst])
            i = self.rng.randrange(n)
            self.replicas[i], res = self.replicas[i].handle(message)
            return res


# --- Running services ------------------------------------------------------

class ServiceRunner:
    """Runs a map of node-id -> service. In host mode, spawns one handler
    thread per service polling the network (reference `service.clj:244-287`);
    in direct mode (TPU virtual-time runner), `deliver` is called
    synchronously at message-delivery time."""

    def __init__(self, net, services: dict):
        self.net = net
        self.services = services
        self.running = False
        self.threads: list[threading.Thread] = []

    def start(self):
        log.info("Starting services: %s", sorted(self.services))
        self.running = True
        for node_id, service in self.services.items():
            self.net.add_node(node_id)
            t = threading.Thread(target=self._loop,
                                 args=(node_id, service),
                                 name=f"maelstrom {node_id}", daemon=True)
            t.start()
            self.threads.append(t)

    def _loop(self, node_id, service):
        while self.running:
            try:
                msg = self.net.recv(node_id, 1000)
                if msg is not None:
                    self._respond(node_id, service, msg)
            except Exception:
                if self.running:
                    log.exception("Error in service worker!")

    def _respond(self, node_id, service, msg):
        body = service.handle(msg)
        body["in_reply_to"] = msg.body.get("msg_id")
        self.net.send({"src": node_id, "dest": msg.src, "body": body})

    def deliver(self, node_id: str, msg):
        """Direct-mode delivery (virtual time): handle and reply now."""
        self._respond(node_id, self.services[node_id], msg)

    def stop(self):
        self.running = False
        for t in self.threads:
            t.join(timeout=2)
        for node_id in self.services:
            self.net.remove_node(node_id)


def default_services(n_eventual_replicas: int = 2, seed: int = 0) -> dict:
    """lww-kv, seq-kv, lin-kv, lin-tso (reference `service.clj:289-295`)."""
    return {
        "lww-kv": Eventual(LWWKV(), n=n_eventual_replicas, seed=seed),
        "seq-kv": Sequential(PersistentKV(), seed=seed),
        "lin-kv": Linearizable(PersistentKV()),
        "lin-tso": Linearizable(PersistentTSO()),
    }
