"""Simulation composition: network + node program = one jitted round.

The hot loop the reference spreads across OS processes, stdio pumps, and
priority queues (SURVEY.md section 3.4) collapses here into a single
compiled function:

    inject client msgs -> deliver due msgs -> step all nodes -> send outboxes

`make_round_fn` builds that function for interactive (round-per-dispatch,
host clients in the loop) use; `make_run_fn` wraps it in `lax.scan` with a
pre-scheduled injection plan so thousands of rounds run in one dispatch —
the benchmark path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from .net import tpu as T
from .net.tpu import I32, Msgs, NetConfig, NetState


@struct.dataclass
class SimState:
    net: NetState
    nodes: object        # program state pytree, leading axis N
    key: jnp.ndarray


def make_sim(program, cfg: NetConfig, seed: int = 0) -> SimState:
    return SimState(net=T.make_net(cfg), nodes=program.init_state(),
                    key=jax.random.PRNGKey(seed))


def _round(program, cfg: NetConfig, sim: SimState, inject: Msgs):
    """One simulation round. `inject` is a flat Msgs batch of client
    requests (src = client index >= n_nodes). Returns
    (sim', client_msgs, io) where io = (inject_sent, outbox_sent, inbox) —
    id-stamped send views plus this round's deliveries, for journaling."""
    N, O = cfg.n_nodes, program.outbox_cap
    key, k1, k2, k3 = jax.random.split(sim.key, 4)
    net, inject_sent = T._send(cfg, sim.net, inject, k1)
    net, inbox, client_msgs = T._deliver(cfg, net)
    nodes, outbox = program.step(sim.nodes, inbox,
                                 {"round": net.round, "key": k2})
    flat = jax.tree.map(lambda f: f.reshape((N * O,) + f.shape[2:]), outbox)
    flat = flat.replace(src=jnp.repeat(jnp.arange(N, dtype=I32), O))
    net, outbox_sent = T._send(cfg, net, flat, k3)
    net = T.advance(net)
    return (SimState(net=net, nodes=nodes, key=key), client_msgs,
            (inject_sent, outbox_sent, inbox))


def make_round_fn(program, cfg: NetConfig):
    """Jitted interactive round: one XLA dispatch per simulated round."""
    return jax.jit(partial(_round, program, cfg))


def make_run_fn(program, cfg: NetConfig, collect_client_msgs: bool = False):
    """Jitted multi-round run under lax.scan.

    run_fn(sim, plan) -> (sim', per_round_client_counts [R] or Msgs [R, CC])
    where `plan` is a Msgs batch [R, M] of pre-scheduled client injections
    (the compiled-mode analogue of the generator: the whole workload is
    scheduled up front, so R rounds execute without touching the host)."""

    def body(sim, inject):
        sim, client_msgs, _ = _round(program, cfg, sim, inject)
        out = client_msgs if collect_client_msgs else client_msgs.count()
        return sim, out

    @jax.jit
    def run_fn(sim: SimState, plan: Msgs):
        return jax.lax.scan(body, sim, plan)

    return run_fn
