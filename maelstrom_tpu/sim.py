"""Simulation composition: network + node program = one jitted round.

The hot loop the reference spreads across OS processes, stdio pumps, and
priority queues (SURVEY.md section 3.4) collapses here into a single
compiled function:

    inject client msgs -> deliver due msgs -> step all nodes -> send outboxes

`make_round_fn` builds that function for interactive (round-per-dispatch,
host clients in the loop) use; `make_run_fn` wraps it in `lax.scan` with a
pre-scheduled injection plan so thousands of rounds run in one dispatch —
the benchmark path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from .net import static
from .net import tpu as T
from .net.tpu import I32, Msgs, NetConfig, NetState


@struct.dataclass
class SimState:
    net: NetState
    nodes: object        # program state pytree, leading axis N
    key: jnp.ndarray
    channels: object = None   # EdgeChannels for edge programs, else None


def make_sim(program, cfg: NetConfig, seed: int = 0,
             track_edge_send_round: bool = False) -> SimState:
    channels = (static.make_channels(program.edge_cfg,
                                     track_send_round=track_edge_send_round)
                if getattr(program, "is_edge", False) else None)
    return SimState(net=T.make_net(cfg), nodes=program.init_state(),
                    key=jax.random.PRNGKey(seed), channels=channels)


def _round(program, cfg: NetConfig, sim: SimState, inject: Msgs):
    """One simulation round. `inject` is a flat Msgs batch of client
    requests (src = client index >= n_nodes). Returns
    (sim', client_msgs, io) where io = (inject_sent, outbox_sent, inbox) —
    id-stamped send views plus this round's deliveries, for journaling.

    Edge programs (`program.is_edge`) route node<->node traffic over the
    static edge channels (sort-free; `net/static.py`); the flight pool then
    carries only client RPCs."""
    if getattr(program, "is_edge", False):
        return _round_edge(program, cfg, sim, inject)
    N, O = cfg.n_nodes, program.outbox_cap
    key, k1, k2, k3 = jax.random.split(sim.key, 4)
    net, inject_sent = T._send(cfg, sim.net, inject, k1)
    net, inbox, client_msgs = T._deliver(cfg, net)
    nodes, outbox = program.step(sim.nodes, inbox,
                                 {"round": net.round, "key": k2})
    flat = jax.tree.map(lambda f: f.reshape((N * O,) + f.shape[2:]), outbox)
    flat = flat.replace(src=jnp.repeat(jnp.arange(N, dtype=I32), O))
    net, outbox_sent = T._send(cfg, net, flat, k3)
    net = T.advance(net)
    return (SimState(net=net, nodes=nodes, key=key), client_msgs,
            (inject_sent, outbox_sent, inbox))


def _round_edge(program, cfg: NetConfig, sim: SimState, inject: Msgs):
    N, K = cfg.n_nodes, program.inbox_cap
    ecfg = program.edge_cfg
    key, k1, k2, k4, k5 = jax.random.split(sim.key, 5)

    net, inject_sent = T._send(cfg, sim.net, inject, k1)
    net, client_inbox, pool_client_msgs = T._deliver(cfg, net)
    ch, edge_in = static.edge_read(ecfg, sim.channels, program.neighbors,
                                   program.rev, net.round)
    nodes, edge_out, client_out = program.edge_step(
        sim.nodes, edge_in, client_inbox, {"round": net.round, "key": k2})

    # Client replies bypass the pool: clients have zero latency
    # (net.clj:177-186), so valid reply rows are compacted straight into
    # the client buffer. (Scattering the [N*K] flatten into the small pool
    # serializes on TPU — ~350 ms/round at 100k nodes.)
    flat = jax.tree.map(lambda f: f.reshape((N * K,) + f.shape[2:]),
                        client_out)
    flat = flat.replace(src=jnp.repeat(jnp.arange(N, dtype=I32), K))
    CC = max(cfg.client_cap, 2 * cfg.n_clients, 1)
    score = jnp.where(flat.valid, N * K - jnp.arange(N * K, dtype=I32), 0)
    _top, top_idx = jax.lax.top_k(score, min(CC, N * K))
    replies = flat.at_rows(top_idx).replace(valid=_top > 0)
    n_all = jnp.sum(flat.valid.astype(I32))     # stats count every reply
    replies = replies.replace(
        mid=net.next_mid + jnp.cumsum(replies.valid.astype(I32)) - 1)
    net = net.replace(next_mid=net.next_mid + n_all)
    st0 = net.stats
    net = net.replace(stats=st0.replace(
        sent_all=st0.sent_all + n_all,
        recv_all=st0.recv_all + n_all))
    client_msgs = (replies if pool_client_msgs.valid.shape[0] == 0
                   else jax.tree.map(
                       lambda a, b: jnp.concatenate([a, b]),
                       pool_client_msgs, replies))
    outbox_sent = replies

    # edge faults: partitions block edges, loss eats lanes (net.clj:213,233)
    nb = program.neighbors
    safe_nb = jnp.clip(nb, 0, cfg.n_nodes - 1)
    comp = net.component
    blocked = ((comp[jnp.arange(N)][:, None] != comp[safe_nb])
               & (nb >= 0))                                   # [N, D]
    shape = edge_out.valid.shape
    lost = jax.random.uniform(k4, shape) < net.p_loss
    deliver_mask = ~blocked[:, :, None] & ~lost
    lat = T.draw_latency_rounds(cfg, k5, net.latency_scale, shape)
    ch = static.edge_write(ecfg, ch, edge_out, net.round, lat, deliver_mask)

    n_sent = jnp.sum(edge_out.valid.astype(I32))
    st = net.stats
    st = st.replace(
        sent_all=st.sent_all + n_sent,
        sent_servers=st.sent_servers + n_sent,
        recv_all=st.recv_all + jnp.sum(edge_in.valid.astype(I32)),
        recv_servers=st.recv_servers + jnp.sum(edge_in.valid.astype(I32)),
        lost=st.lost + jnp.sum(
            (edge_out.valid & ~blocked[:, :, None] & lost).astype(I32)),
        dropped_partition=st.dropped_partition + jnp.sum(
            (edge_out.valid & blocked[:, :, None]).astype(I32)))
    net = net.replace(stats=st)
    net = T.advance(net)
    return (SimState(net=net, nodes=nodes, key=key, channels=ch),
            client_msgs,
            (inject_sent, outbox_sent, client_inbox, edge_out, edge_in))


def make_round_fn(program, cfg: NetConfig):
    """Jitted interactive round: one XLA dispatch per simulated round."""
    return jax.jit(partial(_round, program, cfg))


def make_scan_fn(program, cfg: NetConfig, journal_cap: int | None = None):
    """Jitted scan-ahead: runs up to k_max injection-free rounds in ONE
    dispatch, stopping early at the first round that produces a client
    reply (lax.while_loop). The interactive runner uses this to cross the
    idle stretches between generator events — e.g. at rate 5/s and 1 ms
    rounds, ~200 rounds separate client ops; per-round dispatch would pay
    ~200 host round-trips where this pays one.

    scan_fn(sim, k_max) -> (sim', client_msgs_of_last_round, k_executed),
    k_executed >= 1. Observable behavior matches k_executed sequential
    `_round` calls exactly (same PRNG stream, same reply round).

    With `journal_cap` set, every scanned round's journal io is also
    collected into [cap, ...] buffers and returned as a fourth element
    (rows beyond k_executed are zeros); the cap bounds k_max. The
    interactive runner uses this variant when a journal is attached, so
    journaling no longer forces one dispatch per round. Client replies
    only appear in the final executed round (the loop exits on the first
    reply), so per-round client message buffers are unnecessary."""

    empty = Msgs.empty(max(cfg.n_clients, 1))
    cap = None if journal_cap is None else max(1, int(journal_cap))

    def cond(st):
        _sim, cm, k, k_max, _buf = st
        return (~cm.valid.any()) & (k < k_max)

    def body(st):
        sim, _cm, k, k_max, buf = st
        sim2, cm2, io = _round(program, cfg, sim, empty)
        if cap is not None:
            buf = jax.tree.map(lambda b, x: b.at[k].set(x), buf, io)
        return (sim2, cm2, k + jnp.int32(1), k_max, buf)

    @jax.jit
    def scan_fn(sim: SimState, k_max):
        sim1, cm1, io1 = _round(program, cfg, sim, empty)
        k_max = jnp.int32(k_max)
        if cap is None:
            buf = ()
        else:
            buf = jax.tree.map(
                lambda x: jnp.zeros((cap,) + x.shape, x.dtype), io1)
            buf = jax.tree.map(lambda b, x: b.at[0].set(x), buf, io1)
            k_max = jnp.minimum(k_max, cap)
        st = (sim1, cm1, jnp.int32(1), k_max, buf)
        sim2, cm, k, _, buf = jax.lax.while_loop(cond, body, st)
        if cap is None:
            return sim2, cm, k
        return sim2, cm, k, buf

    return scan_fn


def make_run_fn(program, cfg: NetConfig, collect_client_msgs: bool = False):
    """Jitted multi-round run under lax.scan.

    run_fn(sim, plan) -> (sim', per_round_client_counts [R] or Msgs [R, CC])
    where `plan` is a Msgs batch [R, M] of pre-scheduled client injections
    (the compiled-mode analogue of the generator: the whole workload is
    scheduled up front, so R rounds execute without touching the host)."""

    def body(sim, inject):
        sim, client_msgs, _ = _round(program, cfg, sim, inject)
        out = client_msgs if collect_client_msgs else client_msgs.count()
        return sim, out

    @jax.jit
    def run_fn(sim: SimState, plan: Msgs):
        return jax.lax.scan(body, sim, plan)

    return run_fn
