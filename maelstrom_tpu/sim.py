"""Simulation composition: network + node program = one jitted round.

The hot loop the reference spreads across OS processes, stdio pumps, and
priority queues (SURVEY.md section 3.4) collapses here into a single
compiled function:

    inject client msgs -> deliver due msgs -> step all nodes -> send outboxes

`make_round_fn` builds that function for interactive (round-per-dispatch,
host clients in the loop) use; `make_run_fn` wraps it in `lax.scan` with a
pre-scheduled injection plan so thousands of rounds run in one dispatch —
the benchmark path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from .net import static
from .net import tpu as T
from .net.tpu import I32, Msgs, NetConfig, NetState
from .nodes import NodeProgram


@struct.dataclass
class SimState:
    net: NetState
    nodes: object        # program state pytree, leading axis N
    key: jnp.ndarray
    channels: object = None   # EdgeChannels for edge programs, else None
    # Durable store for the kill/restart fault package: the subset of
    # node state the program persists (`NodeProgram.durable_view`),
    # synced at every round boundary (each write is "fsynced" before
    # the round's replies leave). A crash-killed node restarts from
    # exactly this (`NodeProgram.restore`); None for fully-persistent
    # programs, whose restart keeps the whole state.
    durable: object = None
    # Flight-recorder metric ring (doc/observability.md): a small int32
    # telemetry carry block (`telemetry.MetricRing`) folded per round
    # when cfg.telemetry is on, drained only at dispatch boundaries.
    # None when telemetry is off — the field (and its round cost)
    # compiles out. Purely observational: the ring never touches the
    # PRNG stream or message contents, so telemetry-on/off runs are
    # byte-identical per seed. Rides checkpoints like the rest of the
    # carry.
    telemetry: object = None
    # Byzantine adversary carry (byzantine.py): the active attack plan
    # plus the injection ledger, threaded through the round when
    # cfg.enable_byz so the compiled corruption masks and their
    # bookkeeping live inside the jitted body (no host transfers). The
    # injection gate is a pure integer hash — no PRNG consumption — so
    # the field is None (and everything compiles out) on benign runs.
    # Rides checkpoints: a resume mid-attack-window keeps the plan.
    byz: object = None


def dealias(tree):
    """Copy every leaf so no two leaves share a device buffer.

    Freshly-built state trees alias heavily — `Msgs.empty` fans one
    zeros array across eight fields, `durable_view` returns views of the
    node state — which is fine under jit, but a DONATED argument may not
    contain the same buffer twice (XLA rejects `f(donate(a), donate(a))`).
    Callers that hand a just-constructed sim to a donating entry point
    (`make_scan_fn`/`make_run_fn`/`make_round_fn` with `donate=True`)
    dealias it once up front; every jit output is already alias-free."""
    return jax.tree.map(lambda a: jnp.array(a, copy=True), tree)


def make_sim(program, cfg: NetConfig, seed: int = 0,
             track_edge_send_round: bool = False) -> SimState:
    channels = (static.make_channels(program.edge_cfg,
                                     track_send_round=track_edge_send_round)
                if getattr(program, "is_edge", False) else None)
    nodes = program.init_state()
    tel = None
    if cfg.telemetry:
        from . import telemetry as TM
        tel = TM.make_ring(cfg)
    byz = None
    if cfg.enable_byz:
        from . import byzantine as BZ
        byz = BZ.init_state()
    return SimState(net=T.make_net(cfg), nodes=nodes,
                    key=jax.random.PRNGKey(seed), channels=channels,
                    durable=program.durable_view(nodes), telemetry=tel,
                    byz=byz)


def _freeze(stall, old, new):
    """Per-leaf select: stalled (killed/paused) nodes keep their old
    state row; live nodes take the stepped one. Leaves lead with the
    node axis."""
    def pick(o, n):
        m = stall.reshape(stall.shape + (1,) * (n.ndim - 1))
        return jnp.where(m, o, n)
    return jax.tree.map(pick, old, new)


def _freeze_nodes(program, stall, old, new):
    """Role-aware freeze: a `RolePartition` state tree nests per-role
    subtrees whose leaves lead with the ROLE's node count, not the
    global node axis, so the partition slices the [N] stall mask per
    role (`freeze_select`); homogeneous programs keep the flat select."""
    sel = getattr(program, "freeze_select", None)
    if sel is not None:
        return sel(stall, old, new)
    return _freeze(stall, old, new)


class RolePartition(NodeProgram):
    """A multi-program node-state tree: contiguous node-id ranges run
    DISTINCT `NodeProgram`s inside the one jitted round.

    Today's `make_sim` takes exactly one program and every node runs it;
    a RolePartition maps role name -> (contiguous node range, program)
    and `step` slices the global inbox per role, steps each role's
    program on its own state subtree (`{role: subtree}`, leaves leading
    with the ROLE's node count), and concatenates the outboxes back to
    the global node axis — one compiled scan, same donated-carry / mesh
    / fleet machinery, NetConfig routing, durable views, kill/restart
    and freeze masks all role-aware:

      - `freeze_select` slices the [N] kill/pause stall mask per role
        (`sim._freeze_nodes` dispatches here);
      - `durable_view`/`restore` delegate per role, so a partition can
        mix fully-persistent roles (acceptors) with volatile ones
        (stateless proxies rebuilt from `init_state` on restart);
      - `fault_groups` names each role's node range (plus any program-
        declared subgroups, e.g. acceptor grid rows/columns) for
        role-targeted nemesis scheduling (`--nemesis-targets`).

    The host boundary (request/encode/decode/completion, smart-client
    routing) delegates to the CLIENT role — the first role, by
    convention the tier clients talk to. A single-role partition is pure
    delegation: same PRNG stream, same inbox/outbox shapes, bit-identical
    histories to running the inner program directly (pinned by
    tests/test_role_partition.py), including edge programs (raft,
    broadcast), which are only legal as a partition's sole role.

    Built-in families: `--node tpu:compartment` (nodes/compartment.py,
    role-partitioned compartmentalized consensus), `--node tpu:services`
    (nodes/services.py, the reference's built-in service nodes), and
    `--node tpu:solo:<program>` (any program wrapped as a one-role
    partition — the regression-pin configuration)."""

    name = "role-partition"

    def __init__(self, opts: dict, nodes: list, roles: list):
        """`roles` is an ordered list of (name, program) with each
        program already constructed over its contiguous slice of
        `nodes`; ranges are assigned in order. Role programs address
        the POOL globally (dest indices are global node ids; clients
        are >= len(nodes))."""
        super().__init__(opts, nodes)
        if not roles:
            raise ValueError("RolePartition needs at least one role")
        if any(isinstance(p, RolePartition) for _n, p in roles):
            raise ValueError(
                "RolePartition roles must be leaf programs (nest roles "
                "by listing them, not by wrapping a partition)")
        self.roles = list(roles)
        self._single = len(self.roles) == 1
        self._bounds = []
        base = 0
        for rname, prog in self.roles:
            c = prog.n_nodes
            self._bounds.append((base, base + c))
            base += c
        if base != self.n_nodes:
            raise ValueError(
                f"role sizes sum to {base} nodes but the cluster has "
                f"{self.n_nodes} ({[(n, p.n_nodes) for n, p in roles]})")
        self.inbox_cap = max(p.inbox_cap for _, p in self.roles)
        self.outbox_cap = max(p.outbox_cap for _, p in self.roles)
        self._client_name, self._client_prog = self.roles[0]
        self._client_base = 0
        cp = self._client_prog
        self.needs_state_reads = bool(
            getattr(cp, "needs_state_reads", False))
        if self.needs_state_reads and not self._single:
            # host state reads index the GLOBAL node axis into every
            # role's (role-local) leaves — only sound when the partition
            # IS the whole cluster (single role)
            raise ValueError(
                "needs_state_reads programs are only supported as a "
                "partition's single role (host state reads index the "
                "global node axis)")
        self.state_reads_final = bool(
            getattr(cp, "state_reads_final", False))
        self.reply_payload_words = int(
            getattr(cp, "reply_payload_words", 0) or 0)
        self.unit_words = tuple(getattr(cp, "unit_words", ()) or ())
        for rname, prog in self.roles[1:]:
            if getattr(prog, "unit_words", ()):
                raise ValueError(
                    f"role {rname!r}: unit_words on a non-client role "
                    f"would collide in the shared NetConfig table")
            if getattr(prog, "needs_state_reads", False):
                raise ValueError(
                    f"role {rname!r}: needs_state_reads is only "
                    f"supported on the client role (host state reads "
                    f"index the global node axis)")
        # edge programs read per-program topology state (neighbors,
        # channels) that has no per-role slicing yet: legal only as the
        # sole role, where the partition is pure delegation
        self.is_edge = bool(getattr(cp, "is_edge", False))
        if any(getattr(p, "is_edge", False) for _, p in self.roles[1:]) \
                or (self.is_edge and not self._single):
            raise ValueError(
                "edge programs are only supported as a partition's "
                "single role (pool-path roles have no static topology)")
        if self.is_edge:
            self.neighbors = cp.neighbors
            self.rev = cp.rev
            self.D = cp.D
            self.lanes = cp.lanes
            self.edge_cfg = cp.edge_cfg
            self.edge_atomic_rpc = cp.edge_atomic_rpc
            self.edge_lanes_symmetric = cp.edge_lanes_symmetric
        self.tolerates_channel_overwrites = any(
            getattr(p, "tolerates_channel_overwrites", False)
            for _, p in self.roles)
        self.tolerates_latency_clipping = any(
            getattr(p, "tolerates_latency_clipping", False)
            for _, p in self.roles)

    # --- device side -----------------------------------------------------

    def _role_ctx(self, ctx, i):
        # single role: the inner program sees the EXACT round ctx (the
        # bit-identity contract); multi-role: independent per-role keys
        if self._single:
            return ctx
        return {**ctx, "key": jax.random.fold_in(ctx["key"], i)}

    @staticmethod
    def _pad_lanes(out: Msgs, O: int) -> Msgs:
        L = out.valid.shape[1]
        if L == O:
            return out
        pad = Msgs.empty((out.valid.shape[0], O - L))
        return jax.tree.map(
            lambda a, b: jnp.concatenate([a, b], axis=1), out, pad)

    def init_state(self):
        return {name: prog.init_state() for name, prog in self.roles}

    def step(self, state, inbox, ctx):
        new_state = {}
        outs = []
        for i, (name, prog) in enumerate(self.roles):
            lo, hi = self._bounds[i]
            ib = jax.tree.map(lambda f: f[lo:hi], inbox)
            st, out = prog.step(state[name], ib, self._role_ctx(ctx, i))
            new_state[name] = st
            outs.append(self._pad_lanes(out, self.outbox_cap))
        if self._single:
            return new_state, outs[0]
        outbox = jax.tree.map(
            lambda *fs: jnp.concatenate(fs, axis=0), *outs)
        return new_state, outbox

    def edge_step(self, state, edge_in, client_in, ctx):
        name, prog = self.roles[0]
        st, edge_out, client_out = prog.edge_step(
            state[name], edge_in, client_in, ctx)
        return {name: st}, edge_out, client_out

    def freeze_select(self, stall, old, new):
        return {name: _freeze(stall[lo:hi], old[name], new[name])
                for (name, prog), (lo, hi)
                in zip(self.roles, self._bounds)}

    def quiescent(self, state):
        # roles without a quiescent hook are stateless between messages
        # (the runner's pool-empty probe covers them): they contribute
        # True, so wrapping never blocks an inner program's fast-forward
        q = jnp.array(True)
        for name, prog in self.roles:
            f = getattr(prog, "quiescent", None)
            if f is not None:
                q = q & f(state[name])
        return q

    def reply_payload(self, state, node_idx):
        lo, hi = self._bounds[0]
        local = jnp.clip(node_idx - lo, 0, self._client_prog.n_nodes - 1)
        return self._client_prog.reply_payload(
            state[self._client_name], local)

    def invalid_counters(self, state) -> dict:
        out = {}
        for name, prog in self.roles:
            for k, v in prog.invalid_counters(state[name]).items():
                out[k if self._single else f"{name}:{k}"] = v
        return out

    # --- durability (kill/restart) ---------------------------------------

    def durable_view(self, state):
        return {name: prog.durable_view(state[name])
                for name, prog in self.roles}

    def restore(self, fresh, durable, state, mask):
        return {name: prog.restore(
                    fresh[name],
                    None if durable is None else durable.get(name),
                    state[name], mask[lo:hi])
                for (name, prog), (lo, hi)
                in zip(self.roles, self._bounds)}

    # --- host boundary: delegated to the client role ----------------------

    def request_for_op(self, op):
        return self._client_prog.request_for_op(op)

    def node_for_op(self, op):
        local = self._client_prog.node_for_op(op)
        if local is not None:
            return self._client_base + int(local)
        if self._single:
            return None
        # heterogeneous cluster: an unrouted op goes to the client tier,
        # never to a worker-bound internal node
        return self._client_base

    def encode_body(self, body, intern):
        return self._client_prog.encode_body(body, intern)

    def decode_body(self, t, a, b, c, intern):
        return self._client_prog.decode_body(t, a, b, c, intern)

    def state_row(self, tree, node_idx: int):
        """Maps the GLOBAL node id into its role's subtree: the host
        view of a partition's state is {role: subtree} with each
        subtree's leaves leading with the ROLE's node count, so the
        homogeneous whole-leaf indexing of `NodeProgram.state_row`
        would read the wrong row (or walk off a smaller role's axis).
        Used by `runner._read_state` for completions that read device
        state (e.g. the ordered-stream compartment engine replaying
        the replica log, doc/ordering.md)."""
        import jax
        import numpy as np
        for (name, _prog), (lo, hi) in zip(self.roles, self._bounds):
            if lo <= node_idx < hi:
                return jax.tree.map(lambda a: np.array(a[node_idx - lo]),
                                    tree[name])
        raise IndexError(f"node {node_idx} outside the partition "
                         f"({self.n_nodes} nodes)")

    def completion(self, op, body, read_state, intern):
        # read_state passes through unwrapped: the runner's state_row
        # extraction already lands in the destination node's ROLE
        # subtree, and programs that read other nodes' rows (the
        # ordered-stream engines) call read_state(i) with explicit ids
        return self._client_prog.completion(op, body, read_state, intern)

    def completion_payload(self, op, body, payload, intern):
        return self._client_prog.completion_payload(op, body, payload,
                                                    intern)

    def host_op(self, op, read_state, intern):
        return self._client_prog.host_op(op, read_state, intern)

    def host_state(self):
        st = {name: prog.host_state() for name, prog in self.roles}
        return None if all(v is None for v in st.values()) else st

    def set_host_state(self, st):
        if st is None:
            return
        for name, prog in self.roles:
            prog.set_host_state(st.get(name))

    # --- role-targeted faults ---------------------------------------------

    def fault_groups(self) -> dict:
        """{group-name: [node names]} for `--nemesis-targets`: every
        role's contiguous slice, plus any subgroups the role program
        declares over its own slice (`fault_subgroups`, e.g. the
        compartment acceptor grid's rows and columns)."""
        out = {}
        for (name, prog), (lo, hi) in zip(self.roles, self._bounds):
            names = list(self.nodes[lo:hi])
            out[name] = names
            sub = getattr(prog, "fault_subgroups", None)
            if sub is not None:
                out.update(sub(names))
        return out

    def dynamic_fault_groups(self) -> tuple:
        """Target-group names resolved against LIVE cluster state at
        fault-invoke time (doc/faults.md) — the movable-role metadata a
        partition exposes on top of its static ranges. A subclass that
        owns a movable role (the compartment's elected `sequencer`)
        overrides this together with the resolver the runner calls
        (`current_leader_host`); role programs may also contribute via
        their own `dynamic_fault_groups`."""
        out: list = []
        for _name, prog in self.roles:
            f = getattr(prog, "dynamic_fault_groups", None)
            if f is not None:
                out += [t for t in f() if t not in out]
        return tuple(out)


def _round(program, cfg: NetConfig, sim: SimState, inject: Msgs):
    """One simulation round. `inject` is a flat Msgs batch of client
    requests (src = client index >= n_nodes). Returns
    (sim', client_msgs, io) where io = (inject_sent, outbox_sent, inbox) —
    id-stamped send views plus this round's deliveries, for journaling.

    Edge programs (`program.is_edge`) route node<->node traffic over the
    static edge channels (sort-free; `net/static.py`); the flight pool then
    carries only client RPCs."""
    if getattr(program, "is_edge", False):
        return _round_edge(program, cfg, sim, inject)
    N, O = cfg.n_nodes, program.outbox_cap
    key, k1, k2, k3 = jax.random.split(sim.key, 4)
    net, inject_sent = T._send(cfg, sim.net, inject, k1)
    net, inbox, client_msgs = T._deliver(cfg, net)
    nodes, outbox = program.step(sim.nodes, inbox,
                                 {"round": net.round, "key": k2})
    if cfg.enable_stall:
        # killed/paused nodes don't act: state frozen, sends suppressed
        # (their inbox rows are already empty — _deliver defers/drops)
        stall = sim.net.down | sim.net.paused
        nodes = _freeze_nodes(program, stall, sim.nodes, nodes)
        outbox = outbox.replace(valid=outbox.valid & ~stall[:, None])
    byz = sim.byz
    if cfg.enable_byz:
        # byzantine wire corruption (byzantine.py): rewrite the active
        # culprit's selected outbox rows before send — the lie travels
        # the same pool path, loss/partition/latency and all
        from . import byzantine as BZ
        byz, outbox = BZ.corrupt_pool(program, byz, outbox, net.round)
    flat = jax.tree.map(lambda f: f.reshape((N * O,) + f.shape[2:]), outbox)
    flat = flat.replace(src=jnp.repeat(jnp.arange(N, dtype=I32), O))
    net, outbox_sent = T._send(cfg, net, flat, k3)
    net = T.advance(net)
    tel = sim.telemetry
    if cfg.telemetry and tel is not None:
        # flight-recorder fold (doc/observability.md): pure int32
        # bookkeeping AFTER all PRNG consumption — the ring can never
        # perturb the simulation (telemetry-on/off byte-identity)
        from . import telemetry as TM
        node_sent = jnp.sum(flat.valid.reshape(N, O).astype(I32), axis=1)
        tel = TM.ring_update(cfg, tel, sim.net.stats, net, None,
                             sim.net.round, node_sent, inject_sent,
                             client_msgs)
    return (SimState(net=net, nodes=nodes, key=key,
                     durable=program.durable_view(nodes), telemetry=tel,
                     byz=byz),
            client_msgs, (inject_sent, outbox_sent, inbox))


def _round_edge(program, cfg: NetConfig, sim: SimState, inject: Msgs):
    N, K = cfg.n_nodes, program.inbox_cap
    ecfg = program.edge_cfg
    if cfg.enable_duplication:
        key, k1, k2, k4, k5, k6, k7 = jax.random.split(sim.key, 7)
    else:
        key, k1, k2, k4, k5 = jax.random.split(sim.key, 5)

    net, inject_sent = T._send(cfg, sim.net, inject, k1)
    net, client_inbox, pool_client_msgs = T._deliver(cfg, net)
    ch, edge_in = static.edge_read(ecfg, sim.channels, program.neighbors,
                                   program.rev, net.round)
    nodes, edge_out, client_out = program.edge_step(
        sim.nodes, edge_in, client_inbox, {"round": net.round, "key": k2})
    if cfg.enable_stall:
        # killed/paused nodes don't act: state frozen, nothing sent.
        # Their incoming edge mail is blocked at write time below; mail
        # already in their ring cells is read-and-ignored (edge traffic
        # toward a stalled node is lost, not deferred — every edge
        # protocol retransmits, and raft explicitly tolerates it)
        stall = sim.net.down | sim.net.paused
        nodes = _freeze_nodes(program, stall, sim.nodes, nodes)
        edge_out = edge_out.replace(
            valid=edge_out.valid & ~stall[:, None, None])
        client_out = client_out.replace(
            valid=client_out.valid & ~stall[:, None])
    byz = sim.byz
    if cfg.enable_byz:
        # byzantine wire corruption on the edge path: the forged-proof
        # surface is the client-facing batch ack (byzantine.py)
        from . import byzantine as BZ
        byz, client_out = BZ.corrupt_edge(program, byz, client_out,
                                          net.round)

    # Client replies bypass the pool: clients have zero latency
    # (net.clj:177-186), so valid reply rows are compacted straight into
    # the client buffer. (Scattering the [N*K] flatten into the small pool
    # serializes on TPU — ~350 ms/round at 100k nodes.)
    flat = jax.tree.map(lambda f: f.reshape((N * K,) + f.shape[2:]),
                        client_out)
    flat = flat.replace(src=jnp.repeat(jnp.arange(N, dtype=I32), K))
    CC = max(cfg.client_cap, 2 * cfg.n_clients, 1)
    score = jnp.where(flat.valid, N * K - jnp.arange(N * K, dtype=I32), 0)
    _top, top_idx = jax.lax.top_k(score, min(CC, N * K))
    replies = flat.at_rows(top_idx).replace(valid=_top > 0)
    n_all = jnp.sum(flat.valid.astype(I32))     # stats count every reply
    replies = replies.replace(
        mid=net.next_mid + jnp.cumsum(replies.valid.astype(I32)) - 1)
    net = net.replace(next_mid=net.next_mid + n_all)
    st0 = net.stats
    if cfg.unit_words:
        # reply units (batch acks carry their op count): booked on both
        # sides — the zero-latency client channel sends and delivers in
        # the same round
        ru = T.payload_units(cfg, flat.type, (flat.a, flat.b, flat.c),
                             flat.valid)
        st0 = st0.replace(sent_units=st0.sent_units + ru,
                          recv_units=st0.recv_units + ru)
    net = net.replace(stats=st0.replace(
        sent_all=st0.sent_all + n_all,
        recv_all=st0.recv_all + n_all,
        sent_by_type=T.count_by_type(st0.sent_by_type, flat.type,
                                     flat.valid)))
    client_msgs = (replies if pool_client_msgs.valid.shape[0] == 0
                   else jax.tree.map(
                       lambda a, b: jnp.concatenate([a, b]),
                       pool_client_msgs, replies))
    outbox_sent = replies

    # edge faults: partitions block edges, loss eats lanes (net.clj:213,233)
    nb = program.neighbors
    safe_nb = jnp.clip(nb, 0, cfg.n_nodes - 1)
    comp = net.component
    blocked = (comp[jnp.arange(N)][:, None] != comp[safe_nb])  # [N, D]
    if cfg.partition_groups > 1:
        # directional grudges: src group n may be blocked toward dest
        # group nb[n, d] (one-way, bridge, majorities-ring)
        bg = net.block_groups
        blocked = blocked | net.block_matrix[bg[jnp.arange(N)][:, None],
                                             bg[safe_nb]]
    blocked = blocked & (nb >= 0)
    if cfg.enable_stall:
        # a killed/paused destination receives nothing (its sends were
        # already suppressed above); booked separately from partition
        # drops so the stats explain WHY traffic vanished
        stalled_dst = ((net.down | net.paused)[safe_nb] & (nb >= 0)
                       & ~blocked)
    else:
        stalled_dst = jnp.zeros_like(blocked)
    blocked = blocked | stalled_dst
    shape = edge_out.valid.shape
    # atomic-RPC programs (raft: AE header on lane 0, its entry window
    # on lanes 3+) emit ONE logical message per (edge, round): the fault
    # draws are shared across lanes — one delay, one loss — so a batch
    # is never torn apart by per-lane reordering. Without this, an AE
    # header can arrive alongside entry lanes from a DIFFERENT AE under
    # randomized latency, and entries (positioned by the paired header's
    # prev_idx) land at wrong log indices — same-term log divergence,
    # observed as a linearizability violation under partition+exp
    # latency. Per-lane independence stays the default: every other
    # program's lanes are self-describing messages. With constant
    # latency and p_loss=0 the two modes are value-identical.
    draw_shape = (shape[0], shape[1], 1) if program.edge_atomic_rpc \
        else shape
    lost = jnp.broadcast_to(
        jax.random.uniform(k4, draw_shape) < net.p_loss, shape)
    deliver_mask = ~blocked[:, :, None] & ~lost
    lat = jnp.broadcast_to(
        T.draw_latency_rounds(cfg, k5, net.latency_scale, draw_shape),
        shape)
    # ecfg.spill (decided by the program, see EdgeConfig): randomized
    # latency can land two sends in one (edge, round) cell; programs
    # whose inbox lanes are interchangeable get the collision-free spill
    # write so bounded rings never destroy a message the reference's
    # unbounded queues would have delivered (net.clj:188-246).
    # Positional-lane programs (raft) keep the overwrite semantics they
    # explicitly tolerate.
    if ecfg.uniform_arrival and cfg.latency_dist != "constant":
        # validity-critical: a broken invariant here would silently route
        # every message to entry-0's arrival cell, so raise (not assert —
        # asserts vanish under python -O)
        raise ValueError(
            "uniform_arrival requires constant latency draws (program "
            "opts and NetConfig disagree about the latency distribution)")
    ch = static.edge_write(ecfg, ch, edge_out, net.round, lat, deliver_mask)

    n_dup = jnp.zeros((), T.I32)
    if cfg.enable_duplication:
        # at-least-once amplification on the edge channels: a delivered
        # message is re-written with probability p_dup under an
        # independent latency draw (atomic-RPC programs share the draw
        # across lanes, like loss — a duplicated AE travels whole)
        dup_roll = jnp.broadcast_to(
            jax.random.uniform(k6, draw_shape) < net.p_dup, shape)
        dup_mask = deliver_mask & dup_roll
        lat_dup = jnp.broadcast_to(
            T.draw_latency_rounds(cfg, k7, net.latency_scale, draw_shape),
            shape)
        if cfg.latency_dist == "constant":
            # constant draws are identical, and a same-cell rewrite
            # would merge the copy into the original; one extra round
            # BEYOND the original's floored arrival (edge_write floors
            # 0-draws to 1) keeps the duplicate an actual second
            # delivery (and keeps the uniform_arrival contract: still
            # one shared cell)
            lat_dup = jnp.maximum(lat_dup, 1) + 1
        ch = static.edge_write(ecfg, ch, edge_out, net.round, lat_dup,
                               dup_mask)
        n_dup = jnp.sum((edge_out.valid & dup_mask).astype(T.I32))

    n_sent = jnp.sum(edge_out.valid.astype(I32))
    st = net.stats
    if cfg.unit_words:
        # batch-expansion accounting (doc/perf.md "batched atomic
        # broadcast"): a distilled range lane is ONE edge message
        # carrying n client-op units; booking them here keeps the
        # ops-per-message economics visible in every result next to the
        # raw counters (the jaxpr gate audits this path like the rest
        # of the round body)
        st = st.replace(
            sent_units=st.sent_units + T.payload_units(
                cfg, edge_out.type, (edge_out.a, edge_out.b, edge_out.c),
                edge_out.valid),
            recv_units=st.recv_units + T.payload_units(
                cfg, edge_in.type, (edge_in.a, edge_in.b, edge_in.c),
                edge_in.valid))
    st = st.replace(
        sent_all=st.sent_all + n_sent,
        sent_servers=st.sent_servers + n_sent,
        recv_all=st.recv_all + jnp.sum(edge_in.valid.astype(I32)),
        recv_servers=st.recv_servers + jnp.sum(edge_in.valid.astype(I32)),
        lost=st.lost + jnp.sum(
            (edge_out.valid & ~blocked[:, :, None] & lost).astype(I32)),
        dropped_partition=st.dropped_partition + jnp.sum(
            (edge_out.valid & (blocked & ~stalled_dst)[:, :, None])
            .astype(I32)),
        dropped_down=st.dropped_down + jnp.sum(
            (edge_out.valid & stalled_dst[:, :, None]).astype(I32)),
        duplicated=st.duplicated + n_dup,
        sent_by_type=T.count_by_type(st.sent_by_type, edge_out.type,
                                     edge_out.valid))
    net = net.replace(stats=st)
    net = T.advance(net)
    tel = sim.telemetry
    if cfg.telemetry and tel is not None:
        # flight-recorder fold: node sends = edge traffic + the
        # compacted client replies; `flat` (every valid reply row, not
        # the CC-capped compaction) feeds the latency buckets
        from . import telemetry as TM
        node_sent = (jnp.sum(edge_out.valid.reshape(N, -1).astype(I32),
                             axis=1)
                     + jnp.sum(flat.valid.reshape(N, K).astype(I32),
                               axis=1))
        tel = TM.ring_update(cfg, tel, sim.net.stats, net, ch,
                             sim.net.round, node_sent, inject_sent,
                             flat)
    return (SimState(net=net, nodes=nodes, key=key, channels=ch,
                     durable=program.durable_view(nodes), telemetry=tel,
                     byz=byz),
            client_msgs,
            (inject_sent, outbox_sent, client_inbox, edge_out, edge_in))


def donation_enabled() -> bool:
    """Whether carry donation is active. Default: on for accelerator
    backends, OFF on the CPU backend — CPU `device_get` hands the host
    zero-copy views into device buffers, and donation then recycles
    those buffers under live host references; observed as rare
    nondeterministic history divergence in CPU soak runs (the TPU path
    always copies device->host, so the hazard class does not exist
    there). MAELSTROM_DONATE=1/0 overrides either way."""
    import os
    v = os.environ.get("MAELSTROM_DONATE")
    if v is not None:
        return v != "0"
    return jax.default_backend() != "cpu"


def _jit_kwargs(donate: bool, shardings, n_args: int,
                n_outs: int) -> dict:
    """Shared jit options for the compiled entry points.

    `donate` marks the SimState carry (argument 0) donated: XLA reuses
    its buffers for the output state instead of allocating a fresh tree
    every dispatch — the caller must treat the passed-in sim as consumed
    and keep only the returned one (every in-tree caller already does).

    `shardings`, when given, is `(sim_sharding_tree, inject_sharding_tree,
    scalar_sharding)` (see `parallel.scan_shardings`); it pins the input
    placement so host-built arrays (nemesis mask surgery, fresh inject
    batches) are automatically re-placed onto the mesh at every call
    instead of silently pulling the whole computation to one device.
    Output shardings are pinned too: the returned sim keeps the same
    canonical shardings as the input carry (a donated arg may not be
    resharded at the next call, and GSPMD would otherwise be free to
    pick a different layout per compiled variant), while the drained
    outputs (reply/io rings, counters) come back replicated — they are
    about to leave for the host anyway. Entry points return the sim
    first, then n_outs - 1 drained outputs."""
    kw: dict = {}
    if donate and donation_enabled():
        kw["donate_argnums"] = (0,)
    if shardings is not None:
        sim_sh, inject_sh, scalar_sh = shardings
        kw["in_shardings"] = (sim_sh, inject_sh) \
            + (scalar_sh,) * (n_args - 2)
        kw["out_shardings"] = (sim_sh,) + (scalar_sh,) * (n_outs - 1)
    return kw


def fleet_shard_map(fn, shardings):
    """Wrap a FLEET entry point (every arg and output leaf leads with the
    fleet axis) in `shard_map` when `shardings` describes a MIXED
    (dp>1 x sp>1) mesh; otherwise return `fn` unchanged.

    On a mixed mesh the body runs MANUAL over the whole device grid: the
    fleet axis is sharded per `parallel.fleet_axis_spec` (over both axes
    when divisible, dp-only with sp replicas otherwise) and each shard
    executes its clusters' scatters as plain local scatters — the GSPMD
    scatter-over-replicated-axis value hazard (corrupted reply rows at
    `--fleet 2 --mesh 2,2`, see `parallel.mesh_is_mixed`) structurally
    cannot occur. Because every boundary leaf leads with the fleet axis,
    the aux sharding's single PartitionSpec serves as a pytree-prefix
    in/out spec for the entire signature, and the jit-level pins built by
    `_jit_kwargs` from the same triple keep donation no-reshard intact
    (in pin == out pin for the donated carry).

    `check_rep=False` is required: the manual body contains while_loops
    and scatters whose replication factors jax cannot infer; correctness
    rests on the specs (sharded or all-replicas-identical), pinned by the
    mixed-mesh bit-identity tests."""
    if shardings is None:
        return fn
    aux = shardings[2]
    mesh = getattr(aux, "mesh", None)
    from .parallel import mesh_is_mixed  # local: parallel imports sim
    if not mesh_is_mixed(mesh):
        return fn
    from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh, in_specs=aux.spec, out_specs=aux.spec,
                     check_rep=False)


def make_round_fn(program, cfg: NetConfig, donate: bool = False,
                  shardings=None):
    """Jitted interactive round: one XLA dispatch per simulated round."""
    return jax.jit(partial(_round, program, cfg),
                   **_jit_kwargs(donate, shardings, 2, 3))


def _build_scan_fn(program, cfg: NetConfig, journal_cap: int | None = None,
                   reply_cap: int | None = None,
                   sched_inject: bool = False):
    """The un-jitted scan-ahead body shared by `make_scan_fn` (which jits
    it directly) and `make_fleet_scan_fn` (which vmaps it over a leading
    cluster axis first). Returns (scan_fn, n_outs).

    `sched_inject` (continuous mode, doc/streams.md) changes the inject
    contract: scan_fn(sim, inject, at_rounds, k_max, stop_on_reply)
    takes a [Q] Msgs batch plus an i32 [Q] vector of ROUND OFFSETS
    relative to the window start, and each scanned round i injects
    exactly the rows with at_rounds == i — client ops land at their
    scheduled rounds INSIDE the compiled window, while faults installed
    before the dispatch are live. An extra `inj_mids` i32 [Q] output
    reports the message id each row was assigned (-1 = not injected,
    e.g. the loop exited before the row's round): mids of mid-window
    injections depend on how many replies preceded them, so the host
    learns them from the drain instead of predicting.

    The scan runs up to k_max injection-free rounds in ONE
    dispatch (lax.while_loop). The interactive runner uses this to cross
    the idle stretches between generator events — e.g. at rate 5/s and
    1 ms rounds, ~200 rounds separate client ops; per-round dispatch
    would pay ~200 host round-trips where this pays one.

    scan_fn(sim, inject, k_max, stop_on_reply) -> (sim',
    client_msgs_of_last_round, k_executed[, replies][, io_buf]),
    k_executed >= 1. `inject` (a Msgs batch, possibly all-invalid) is
    applied in the FIRST round, so an injection and the idle crossing
    that follows it share one dispatch. Observable behavior matches an
    injected `_round` followed by k_executed-1 empty rounds exactly
    (same PRNG stream, same reply rounds).

    `stop_on_reply` (traced bool): when True the loop exits at the first
    round producing a client reply — required when a completion may move
    the generator's next event (worker-starved emission, phase
    advancement on quiescence). When the host proves the next event is
    purely time-gated, it passes False and the scan crosses whole
    reply-bearing stretches in one dispatch, with every reply collected.

    With `reply_cap` set, every client reply in the scanned stretch is
    appended to a compact log (`replies` = Msgs [reply_cap] + a `rounds`
    i32 array + a count) for the host to replay in order; the loop also
    exits when the log could overflow on the next round. With
    `journal_cap` set, every scanned round's journal io is additionally
    collected into [cap, ...] buffers (rows beyond k_executed are
    zeros); that cap bounds k_max.

    The reply log and journal buffers are the device-resident rings the
    production runner drains: replies/io accumulate on device across the
    whole scanned stretch and reach the host as ONE batched fetch per
    dispatch, so host transfers scale with host-relevant rounds (ops,
    timeouts, nemesis boundaries), not simulated rounds."""

    CC = max(cfg.n_clients, 1)
    empty = Msgs.empty(CC)
    cap = None if journal_cap is None else max(1, int(journal_cap))
    # the client-message batch a round produces can be wider than the
    # inject width (reply buffers size by client_cap); the real width is
    # read off the first round's output at trace time, and the log always
    # reserves one full batch of headroom so a permitted round can never
    # overflow it
    rcap_req = None if reply_cap is None else max(1, int(reply_cap))
    rcap = None
    cw = None
    # per-reply state payload (NodeProgram.reply_payload_words): rows
    # snapshot completion state at the reply's own round, on device
    W = int(getattr(program, "reply_payload_words", 0) or 0)

    def append_replies(rlog, rounds, plog, rn, cm, nodes, round_i):
        """Compacts this round's valid client msgs onto the reply log.
        Invalid rows scatter to an out-of-bounds index and are dropped,
        so duplicate-position writes cannot clobber real replies."""
        offs = jnp.cumsum(cm.valid.astype(I32)) - cm.valid.astype(I32)
        pos = jnp.where(cm.valid, rn + offs, rcap)      # OOB when invalid

        def upd(dst, src):
            return dst.at[pos].set(src, mode="drop")
        rlog = jax.tree.map(upd, rlog, cm)
        rounds = rounds.at[pos].set(
            jnp.broadcast_to(round_i, pos.shape), mode="drop")
        if W:
            src_node = jnp.clip(cm.src, 0, cfg.n_nodes - 1)
            rows = program.reply_payload(nodes, src_node)   # [CW, W]
            plog = plog.at[pos].set(rows, mode="drop")
        return rlog, rounds, plog, rn + jnp.sum(cm.valid.astype(I32))

    def cond(st):
        _sim, cm, k, k_max, stop, _buf, _rlog, _rounds, _plog, rn, _im = st
        go = k < k_max
        go = go & ~(stop & cm.valid.any())
        if rcap_req is not None:
            go = go & (rn + cw <= rcap)
        return go

    def _mk_body(inject, at_rounds):
        def body(st):
            sim, _cm, k, k_max, stop, buf, rlog, rounds, plog, rn, im = st
            if sched_inject:
                # continuous mode: this round's injections are the rows
                # scheduled exactly at offset k
                inj = inject.replace(
                    valid=inject.valid & (at_rounds == k))
            else:
                inj = empty
            sim2, cm2, io = _round(program, cfg, sim, inj)
            if sched_inject:
                sent = io[0]        # id-stamped inject view of this round
                im = jnp.where(sent.valid, sent.mid, im)
            if cap is not None:
                buf = jax.tree.map(lambda b, x: b.at[k].set(x), buf, io)
            if rcap is not None:
                # stamp with the post-round counter: the host processes a
                # reply at the round after its producing dispatch, and the
                # replay must use identical times
                rlog, rounds, plog, rn = append_replies(
                    rlog, rounds, plog, rn, cm2, sim2.nodes,
                    sim2.net.round)
            return (sim2, cm2, k + jnp.int32(1), k_max, stop, buf, rlog,
                    rounds, plog, rn, im)
        return body

    def _scan(sim: SimState, inject: Msgs, at_rounds, k_max,
              stop_on_reply):
        nonlocal rcap, cw
        if sched_inject:
            inj0 = inject.replace(valid=inject.valid & (at_rounds == 0))
        else:
            inj0 = inject
        sim1, cm1, io1 = _round(program, cfg, sim, inj0)
        if sched_inject:
            sent0 = io1[0]
            im = jnp.where(sent0.valid, sent0.mid,
                           jnp.full_like(at_rounds, -1))
        else:
            im = jnp.zeros(0, I32)
        k_max = jnp.int32(k_max)
        stop = jnp.asarray(stop_on_reply, bool)
        if cap is None:
            buf = ()
        else:
            buf = jax.tree.map(
                lambda x: jnp.zeros((cap,) + x.shape, x.dtype), io1)
            buf = jax.tree.map(lambda b, x: b.at[0].set(x), buf, io1)
            k_max = jnp.minimum(k_max, cap)
        if rcap_req is None:
            rlog, rounds, plog, rn = ((), jnp.zeros(0, I32), (),
                                      jnp.int32(0))
        else:
            cw = int(cm1.valid.shape[0])
            rcap = max(rcap_req, 2 * cw)
            rlog = Msgs.empty(rcap)
            rounds = jnp.zeros(rcap, I32)
            plog = jnp.zeros((rcap, W), I32) if W else ()
            rlog, rounds, plog, rn = append_replies(
                rlog, rounds, plog, jnp.int32(0), cm1, sim1.nodes,
                sim1.net.round)
        st = (sim1, cm1, jnp.int32(1), k_max, stop, buf, rlog, rounds,
              plog, rn, im)
        sim2, cm, k, _, _, buf, rlog, rounds, plog, rn, im = \
            jax.lax.while_loop(cond, _mk_body(inject, at_rounds), st)
        out = (sim2, cm, k)
        if rcap is not None:
            out = out + ((rlog, rounds, plog, rn),)
        if sched_inject:
            out = out + (im,)
        if cap is not None:
            out = out + (buf,)
        return out

    if sched_inject:
        def scan_fn(sim: SimState, inject: Msgs, at_rounds, k_max,
                    stop_on_reply=True):
            return _scan(sim, inject, jnp.asarray(at_rounds, I32),
                         k_max, stop_on_reply)
    else:
        def scan_fn(sim: SimState, inject: Msgs, k_max,
                    stop_on_reply=True):
            return _scan(sim, inject, None, k_max, stop_on_reply)

    n_outs = (3 + (rcap_req is not None) + int(bool(sched_inject))
              + (cap is not None))
    return scan_fn, n_outs


def make_scan_fn(program, cfg: NetConfig, journal_cap: int | None = None,
                 reply_cap: int | None = None, donate: bool = False,
                 shardings=None, sched_inject: bool = False):
    """Jitted scan-ahead over one cluster (see `_build_scan_fn` for the
    full semantics). `donate=True` donates the SimState carry so the
    reply/io rings and the state tree are reused in place instead of
    reallocated every dispatch; `shardings` pins the input placement for
    mesh (`--mesh`) execution (see `_jit_kwargs`); `sched_inject=True`
    builds the continuous-mode variant (per-row round offsets, an
    `inj_mids` drain output)."""
    scan_fn, n_outs = _build_scan_fn(program, cfg, journal_cap, reply_cap,
                                     sched_inject)
    n_args = 5 if sched_inject else 4
    return jax.jit(scan_fn,
                   **_jit_kwargs(donate, shardings, n_args, n_outs))


def make_fleet_scan_fn(program, cfg: NetConfig,
                       journal_cap: int | None = None,
                       reply_cap: int | None = None, donate: bool = False,
                       shardings=None, sched_inject: bool = False):
    """Jitted FLEET scan: the single-cluster scan body vmapped over a
    leading cluster axis, so N independent cluster instances advance
    inside one compiled dispatch.

    fleet_fn(sim, inject, k_max, stop_on_reply, active) takes
    cluster-batched trees (`sim` leaves lead with the fleet axis F,
    `inject` is a [F, C] Msgs batch) and per-cluster [F] vectors for
    k_max / stop_on_reply / active. Each cluster executes exactly the
    rounds its own (k_max, stop) bounds permit — `lax.while_loop` under
    vmap masks finished lanes with selects, so a cluster's PRNG stream,
    reply rounds, and state trajectory are BIT-IDENTICAL to running it
    standalone with the same seed (pinned by tests/test_fleet_runner.py).

    `active=False` holds a cluster entirely: the lane still computes its
    mandatory first round (vmap executes all lanes), but the result is
    discarded — the returned state row equals the input row, k comes
    back 0, and the reply log reports 0 rows. The fleet runner uses this
    to keep clusters whose host loop is between dispatches (or finished)
    frozen while others scan.

    `sched_inject=True` builds the continuous-mode fleet variant
    (doc/streams.md): fleet_fn(sim, inject, at_rounds, k_max,
    stop_on_reply, active) takes a [F, Q] inject batch plus a [F, Q]
    round-offset tensor, each lane injecting its rows at their scheduled
    offsets inside the compiled window, and drains a [F, Q] `inj_mids`
    output next to the reply log (-1 = not injected; held lanes report
    all -1, since their window never ran). This is the `--fleet N
    --continuous` dispatch: one columnar inj tensor and one inj_mids
    drain per wave for the whole fleet.

    `shardings` pins the cluster-batched placement for `--mesh dp,sp`
    execution (`parallel.fleet_scan_shardings`): on a single-axis mesh
    the fleet axis shards over dp and per-cluster node/pool axes over sp
    (GSPMD partitions the body); on a MIXED dp>1 x sp>1 mesh the whole
    body instead runs manual under `shard_map` with every leaf sharded
    on its fleet axis only (`fleet_shard_map`) — per-cluster scatters
    become plain local scatters, which is what makes the mixed shape
    value-safe at all."""
    scan_fn, n_outs = _build_scan_fn(program, cfg, journal_cap, reply_cap,
                                     sched_inject)
    n_in = 5 if sched_inject else 4
    vscan = jax.vmap(scan_fn, in_axes=(0,) * n_in)
    has_replies = reply_cap is not None

    def _mask_held(out, sim, active):
        """Held (inactive) lanes computed their mandatory first round;
        discard it: state reverts to the input row, k and the reply
        count come back 0, and (sched_inject) no mids are confirmed."""
        sim2, cm, k = out[0], out[1], out[2]
        act = jnp.asarray(active, bool)

        def keep(new, old):
            m = act.reshape(act.shape + (1,) * (new.ndim - 1))
            return jnp.where(m, new, old)
        sim2 = jax.tree.map(keep, sim2, sim)
        k = jnp.where(act, k, 0)
        extra = out[3:]
        if has_replies:
            rlog, rounds, plog, rn = extra[0]
            extra = ((rlog, rounds, plog, jnp.where(act, rn, 0)),) \
                + extra[1:]
        if sched_inject:
            i = 1 if has_replies else 0
            im = jnp.where(act[:, None], extra[i], -1)
            extra = extra[:i] + (im,) + extra[i + 1:]
        return (sim2, cm, k) + extra

    if sched_inject:
        def fleet_fn(sim: SimState, inject: Msgs, at_rounds, k_max,
                     stop_on_reply, active):
            out = vscan(sim, inject, jnp.asarray(at_rounds, jnp.int32),
                        jnp.asarray(k_max, jnp.int32),
                        jnp.asarray(stop_on_reply, bool))
            return _mask_held(out, sim, active)
        n_args = 6
    else:
        def fleet_fn(sim: SimState, inject: Msgs, k_max, stop_on_reply,
                     active):
            out = vscan(sim, inject, jnp.asarray(k_max, jnp.int32),
                        jnp.asarray(stop_on_reply, bool))
            return _mask_held(out, sim, active)
        n_args = 5

    return jax.jit(fleet_shard_map(fleet_fn, shardings),
                   **_jit_kwargs(donate, shardings, n_args, n_outs))


def make_run_fn(program, cfg: NetConfig, collect_client_msgs: bool = False,
                donate: bool = False, shardings=None):
    """Jitted multi-round run under lax.scan.

    run_fn(sim, plan) -> (sim', per_round_client_counts [R] or Msgs [R, CC])
    where `plan` is a Msgs batch [R, M] of pre-scheduled client injections
    (the compiled-mode analogue of the generator: the whole workload is
    scheduled up front, so R rounds execute without touching the host).

    `donate=True` donates the sim carry (argument 0): chunked callers
    (`sim, _ = run_fn(sim, chunk)` in a loop, the bench path) then reuse
    one state allocation across all chunks instead of paying an
    alloc+copy of the full tree per dispatch. The passed-in sim is
    consumed — keep only the returned one."""

    def body(sim, inject):
        sim, client_msgs, _ = _round(program, cfg, sim, inject)
        out = client_msgs if collect_client_msgs else client_msgs.count()
        return sim, out

    def run_fn(sim: SimState, plan: Msgs):
        return jax.lax.scan(body, sim, plan)

    return jax.jit(run_fn, **_jit_kwargs(donate, shardings, 2, 2))
