"""The store directory: persistent test artifacts.

Mirrors the reference's jepsen store layout (`doc/results.md:14-52`):

    store/<test-name>/<timestamp>/
        history.jsonl       the operation history
        results.json        checker output (validity)
        test.json           test parameters
        net-journal/        journal events + batched chunks
        node-logs/          per-node stderr
        messages.svg        Lamport diagram
        timeline.html       per-process op timeline
        latency-raw.svg, latency-quantiles.svg, rate.svg

`store/latest` and `store/<name>/latest` symlinks point at the newest run;
`serve` (maelstrom_tpu.serve) browses past runs like jepsen's web server.
"""

from __future__ import annotations

import json
import os
import time
from datetime import datetime


def make_test_dir(root: str, test_name: str) -> str:
    ts = datetime.now().strftime("%Y%m%dT%H%M%S.%f")[:-3]
    d = os.path.join(root, test_name, ts)
    os.makedirs(d, exist_ok=True)
    # store/current points at the run in progress; the `latest` links only
    # move when a run completes (mark_complete), mirroring the reference's
    # current/latest distinction (doc/results.md:4-5)
    _relink(os.path.join(root, "current"), os.path.join(test_name, ts))
    return d


def mark_complete(d: str):
    """Repoints the `latest` symlinks at a finished run. `d` is the dir
    make_test_dir returned (root/<test-name>/<timestamp>)."""
    d = os.path.normpath(d)
    test_dir, ts = os.path.split(d)
    root, test_name = os.path.split(test_dir)
    _relink(os.path.join(test_dir, "latest"), ts)
    _relink(os.path.join(root, "latest"), os.path.join(test_name, ts))


def _relink(link: str, target: str):
    try:
        if os.path.islink(link):
            os.unlink(link)
        os.symlink(target, link)
    except OSError:
        pass


def write_history(d: str, history):
    with open(os.path.join(d, "history.jsonl"), "w") as f:
        f.write(history.to_jsonl() + "\n")
    # condensed human-readable view (reference history.txt,
    # doc/results.md:23-25): process, type, f, value, error
    with open(os.path.join(d, "history.txt"), "w") as f:
        for o in history:
            err = "" if o.error is None else f"\t{o.error}"
            f.write(f"{o.process}\t{o.type}\t{o.f}\t{o.value}{err}\n")


def write_results(d: str, results: dict):
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump(results, f, indent=2, default=str)


def write_test(d: str, test: dict):
    clean = {k: v for k, v in test.items()
             if isinstance(v, (str, int, float, bool, list, dict,
                               type(None)))}
    with open(os.path.join(d, "test.json"), "w") as f:
        json.dump(clean, f, indent=2, default=str)


def load_results(d: str) -> dict:
    with open(os.path.join(d, "results.json")) as f:
        return json.load(f)
