"""Node lifecycle: setup (services + node + init handshake) and teardown.

Reimplements `src/maelstrom/db.clj`: on setup, the primary node first starts
the built-in services; each node's process is started and then initialized
with the `init` RPC (`{"type": "init", "node_id": ..., "node_ids": [...]}`),
expecting `init_ok` within 10 seconds. Teardown stops the node process
(raising on crashes) and finally the services.
"""

from __future__ import annotations

import logging
import os

from .client import SyncClient
from .errors import Timeout
from .process import NodeProcess
from .services import ServiceRunner, default_services

log = logging.getLogger("maelstrom.db")

INIT_TIMEOUT_MS = 10_000     # reference db.clj:46-69


class InitFailed(Exception):
    pass


def init_node(net, node_id: str, node_ids: list[str],
              timeout_ms: float = INIT_TIMEOUT_MS):
    """Performs the init RPC handshake (reference `db.clj:46-69`)."""
    client = SyncClient(net)
    try:
        try:
            res = client.rpc(node_id,
                             {"type": "init", "node_id": node_id,
                              "node_ids": list(node_ids)},
                             timeout_ms)
        except Timeout:
            raise InitFailed(
                f"Expected node {node_id} to respond to an init message, "
                "but node did not respond.")
        if res.get("type") != "init_ok":
            raise InitFailed(
                f"Expected an init_ok message, but node responded with "
                f"{res!r}")
    finally:
        client.close()


class HostDB:
    """Runs external-binary nodes on the host network
    (the reference's only mode; here it's the compatibility path)."""

    def __init__(self, net, bin: str, args: list[str] | None = None,
                 service_seed: int = 0):
        self.net = net
        self.bin = bin
        self.args = args or []
        self.services: ServiceRunner | None = None
        self.processes: dict[str, NodeProcess] = {}
        self.service_seed = service_seed
        self.test: dict = {}
        self._restarts: dict[str, int] = {}

    def _spawn(self, node_id: str):
        log_dir = os.path.join(self.test.get("store_dir", "store"),
                               "node-logs")
        gen = self._restarts.get(node_id, 0)
        suffix = f".restart{gen}" if gen else ""
        self.processes[node_id] = NodeProcess(
            node_id=node_id, bin=self.bin, args=self.args, net=self.net,
            log_file=os.path.join(log_dir, f"{node_id}{suffix}.log"),
            log_stderr=self.test.get("log_stderr", False))

    def setup(self, test: dict):
        self.test = test
        nodes = test["nodes"]
        # services first (reference db.clj:24-29; primary-only there, but we
        # set up all nodes from one place)
        self.services = ServiceRunner(
            self.net, default_services(seed=self.service_seed))
        self.services.start()
        for node_id in nodes:
            log.info("Setting up %s", node_id)
            self._spawn(node_id)
        for node_id in nodes:
            init_node(self.net, node_id, nodes)

    # --- nemesis process control (kill/pause fault packages) ---

    def kill_node(self, node_id: str):
        """Crash-kill: SIGKILL, no crash report (intentional). The node
        stays down until restart_node respawns it."""
        log.info("nemesis: killing %s", node_id)
        p = self.processes.pop(node_id, None)
        if p is not None:
            p.kill()

    def restart_node(self, node_id: str):
        """Respawn a killed node and rerun the init handshake: the
        binary recovers whatever it persisted itself (its durable
        store); everything in memory is gone."""
        log.info("nemesis: restarting %s", node_id)
        self._restarts[node_id] = self._restarts.get(node_id, 0) + 1
        self._spawn(node_id)
        init_node(self.net, node_id, self.test["nodes"])

    def pause_node(self, node_id: str):
        """SIGSTOP. A node the kill package took down in the meantime
        has no process to stop — the pause is then vacuous (it is
        already maximally stalled)."""
        log.info("nemesis: pausing %s", node_id)
        p = self.processes.get(node_id)
        if p is not None:
            p.pause()

    def resume_node(self, node_id: str):
        log.info("nemesis: resuming %s", node_id)
        p = self.processes.get(node_id)
        if p is not None and p.paused:
            p.resume()

    def teardown(self) -> list[Exception]:
        """Stops everything; returns (rather than raises) crash exceptions
        so all nodes get torn down (crashes still fail the test)."""
        crashes = []
        for node_id, p in list(self.processes.items()):
            log.info("Tearing down %s", node_id)
            try:
                p.stop()
            except Exception as e:
                crashes.append(e)
            del self.processes[node_id]
        if self.services:
            self.services.stop()
            self.services = None
        return crashes
