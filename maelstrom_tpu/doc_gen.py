"""Documentation generator (reference `src/maelstrom/doc.clj`): renders
doc/workloads.md (per-workload RPC schemas from the RPC registry) and
doc/protocol.md (the error table from the error registry)."""

from __future__ import annotations

import json
import os

from .client import RPC_REGISTRY
from .errors import ERROR_REGISTRY
from . import schema as S

PROTOCOL_INTRO = """\
# Protocol

A node is an ordinary OS process wired to the harness through its three
standard streams: each line on STDIN is an incoming message, each line it
writes to STDOUT is an outgoing message, and STDERR is free-form debug
logging. Because STDOUT *is* the wire, a node must never print anything
there except well-formed messages. Within a node, handling is sequential;
all coordination between nodes happens by exchanging these messages.

## Messages

Messages are JSON objects with `src`, `dest`, and `body` fields:

```json
{"src": "c1", "dest": "n1", "body": {"type": "echo", "msg_id": 1,
 "echo": "hello"}}
```

Bodies carry a `type`, an optional `msg_id` (unique per sender), and an
optional `in_reply_to` linking replies to requests.

## Initialization

At the start of a test Maelstrom sends each node an `init` message:

```json
{"type": "init", "msg_id": 1, "node_id": "n3",
 "node_ids": ["n1", "n2", "n3"]}
```

Nodes must respond with `{"type": "init_ok", "in_reply_to": 1}`.

## Errors

Nodes may respond to requests with errors: a body of type `"error"` with an
integer `code` and a free-form `text`. *Definite* errors mean the requested
operation definitely did not happen; *indefinite* errors leave the outcome
unknown.
"""


def render_errors() -> str:
    lines = ["| Code | Name | Definite | Description |",
             "|------|------|----------|-------------|"]
    for code in sorted(ERROR_REGISTRY):
        e = ERROR_REGISTRY[code]
        doc = " ".join(e.doc.split())
        lines.append(f"| {code} | {e.name} | "
                     f"{'✓' if e.definite else ' '} | {doc} |")
    return "\n".join(lines)


def render_protocol() -> str:
    return PROTOCOL_INTRO + "\n" + render_errors() + "\n"


def _schema_block(sch) -> str:
    return "```json\n" + json.dumps(S.explain(sch), indent=2,
                                    default=str) + "\n```"


def _title(ns: str) -> str:
    """lin_kv -> Lin-kv (the reference's heading style)."""
    return ns.replace("_", "-").capitalize()


def render_workloads() -> str:
    """One section per workload namespace, one subsection per RPC, with a
    table of contents (reference `doc.clj:23-64`)."""
    by_ns: dict = {}
    for r in RPC_REGISTRY:
        by_ns.setdefault(r.ns.split(".")[-1], []).append(r)
    out = ["# Workloads",
           "",
           "A workload specifies the semantics of a distributed system: "
           "what operations are performed, how clients submit requests to "
           "the system, what those requests mean, what kind of responses "
           "are expected, which errors can occur, and how to check the "
           "resulting history for safety.",
           "",
           "## Table of Contents",
           ""]
    for ns in sorted(by_ns):
        t = _title(ns)
        out.append(f"- [{t}](#workload-{t.lower()})")
    out.append("")
    for ns in sorted(by_ns):
        out.append(f"## Workload: {_title(ns)}")
        out.append("")
        for r in by_ns[ns]:
            out.append(f"### RPC: {r.name}")
            out.append("")
            out.append(" ".join(r.doc.split()))
            out.append("")
            out.append("Request:")
            out.append(_schema_block(r.send))
            out.append("")
            out.append("Response:")
            out.append(_schema_block(r.recv))
            out.append("")
    return "\n".join(out)


def write_docs(doc_dir: str = "doc"):
    """Regenerates doc/workloads.md and doc/protocol.md
    (reference `doc.clj:87-96`)."""
    # import all workloads so their defrpc/deferror registrations run
    from .workloads import registry
    registry()
    os.makedirs(doc_dir, exist_ok=True)
    with open(os.path.join(doc_dir, "protocol.md"), "w") as f:
        f.write(render_protocol())
    with open(os.path.join(doc_dir, "workloads.md"), "w") as f:
        f.write(render_workloads())
    return [os.path.join(doc_dir, "protocol.md"),
            os.path.join(doc_dir, "workloads.md")]
