"""True checkpoint/resume for TPU-path tests.

The reference cannot snapshot a running test: node state lives inside
opaque OS processes, so a test either runs to completion or is lost
(SURVEY.md section 5.4 — its store dir only enables post-hoc re-analysis).
The TPU path's entire run state is pure data — device arrays including the
PRNG key, picklable generator trees, the history so far, and in-flight RPC
bookkeeping — so a checkpoint is one atomic file, and a resumed run
continues *deterministically*: it produces byte-identical histories to an
uninterrupted run with the same options.

Layout: `store/<test>/<time>/checkpoint.pkl`, rewritten atomically
(tmp + rename) every `--checkpoint-every` virtual seconds. Resume with
`maelstrom_tpu test ... --resume <that dir>` (same workload options).
"""

from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp

CHECKPOINT_FILE = "checkpoint.pkl"

# Options that must match between the checkpointing run and the resuming
# run: they shape the compiled round function, the generator tree, the
# simulated cluster, or the runner's dispatch cadence (anything that can
# change the op stream or the PRNG consumption order).
FINGERPRINT_KEYS = ("workload", "node", "nodes", "rate", "time_limit",
                    "concurrency", "latency", "nemesis", "nemesis_interval",
                    "topology", "seed", "key_count", "max_txn_length",
                    "max_writes_per_key", "min_txn_length", "ops_per_key",
                    "p_loss", "timeout_ms", "ms_per_round", "recovery_s",
                    "journal_rows", "max_scan", "pool_cap", "gossip_fanout")


def fingerprint(test: dict) -> dict:
    return {k: sorted(v) if isinstance(v, set) else v
            for k, v in ((k, test.get(k)) for k in FINGERPRINT_KEYS)}


def save(dir_path: str, state: dict) -> str:
    """Atomically writes a checkpoint into `dir_path`. Device arrays are
    pulled to host numpy first (one transfer for the whole pytree)."""
    os.makedirs(dir_path, exist_ok=True)
    path = os.path.join(dir_path, CHECKPOINT_FILE)
    tmp = path + ".tmp"
    state = dict(state, sim=jax.device_get(state["sim"]))
    with open(tmp, "wb") as f:
        pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)
    return path


def load(dir_path: str) -> dict:
    """Loads a checkpoint; `sim` leaves come back as device arrays."""
    path = os.path.join(dir_path, CHECKPOINT_FILE)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no {CHECKPOINT_FILE} in {dir_path!r} - was the original run "
            "started with --checkpoint-every?")
    with open(path, "rb") as f:
        state = pickle.load(f)
    state["sim"] = jax.tree.map(jnp.asarray, state["sim"])
    return state


def check_fingerprint(ckpt: dict, test: dict):
    want, got = ckpt.get("fingerprint", {}), fingerprint(test)
    diffs = {k: (want.get(k), got.get(k)) for k in want
             if want.get(k) != got.get(k)}
    if diffs:
        raise ValueError(
            "resume options differ from the checkpointed run "
            f"(checkpointed vs given): {diffs}")
