"""True checkpoint/resume for TPU-path tests.

The reference cannot snapshot a running test: node state lives inside
opaque OS processes, so a test either runs to completion or is lost
(SURVEY.md section 5.4 — its store dir only enables post-hoc re-analysis).
The TPU path's entire run state is pure data — device arrays including the
PRNG key, picklable generator trees, the history so far, and in-flight RPC
bookkeeping — so a checkpoint is one atomic file, and a resumed run
continues *deterministically*: it produces byte-identical histories to an
uninterrupted run with the same options.

Layout: `store/<test>/<time>/checkpoint.pkl`, rewritten every
`--checkpoint-every` virtual seconds. Resume with
`maelstrom_tpu test ... --resume <that dir>` (same workload options).

Durability (doc/checkpoint.md): the file is a framed container —
magic, format version, payload length, SHA-256 digest, pickle payload —
written tmp-first with an fsync of both the tmp file and its directory
around the atomic rename, and the previous good checkpoint is kept as
`checkpoint.prev.pkl` so a write torn by SIGKILL/power loss can never
cost more than one checkpoint interval. `load` verifies the frame
(magic/version/length/digest) and falls back to the previous checkpoint
when the newest one is torn.

Writes happen on a background writer thread by default
(`CheckpointWriter`, at most one write in flight) so the device keeps
dispatching while the previous snapshot lands; `--sync-checkpoint`
forces the old synchronous behavior. On SIGTERM/SIGINT the runner
finishes the in-flight compiled stretch, writes a final checkpoint, and
exits with `EXIT_PREEMPTED` so a supervisor can relaunch with
`--resume` (see run_crash_soak.sh).
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import struct
import threading
import time

import jax
import jax.numpy as jnp

log = logging.getLogger("maelstrom.checkpoint")

CHECKPOINT_FILE = "checkpoint.pkl"
PREV_CHECKPOINT_FILE = "checkpoint.prev.pkl"

# Framed container: magic + version + payload length + SHA-256(payload),
# then the pickle payload. The frame is what makes torn/truncated writes
# *detectable* (and old raw-pickle checkpoints cleanly rejectable).
MAGIC = b"MAELCKPT"
# v3 (ISSUE 13): SimState grew the `telemetry` carry field (the
# flight-recorder MetricRing). A v2 pickle restores a SimState without
# the attribute, which would surface as an AttributeError deep inside
# the first jax tree flatten on resume — version the format instead,
# so pre-change checkpoints get the curated CheckpointError.
VERSION = 3
_HEADER = struct.Struct("<8sIQ32s")     # magic, version, payload len, digest

# The exit code of a run that was preempted (SIGTERM/SIGINT) and wrote a
# final checkpoint: distinct from success (0), invalid analysis (1), and
# errors (2), so an outer supervisor knows to relaunch with --resume.
# 75 is sysexits' EX_TEMPFAIL ("temporary failure, retry later").
EXIT_PREEMPTED = 75

# Options that must match between the checkpointing run and the resuming
# run: they shape the compiled round function, the simulated state tree,
# the generator tree, or the runner's dispatch routing (anything that can
# change the op stream or the PRNG consumption order).
#
#   - mesh: sharded runs are bit-identical to single-chip, but the saved
#     sim tree is re-placed via TpuRunner._reshard on resume; requiring
#     the same mesh keeps the donation/sharding invariants trivially true
#     (and a cross-mesh resume is a deliberate, reviewable change).
#   - journal_rows/collect_replies: shape the sim tree (edge send-round
#     tracking) and the dispatch/read_state cadence respectively.
#   - journal_scan_cap/reply_log_cap: size the device-resident io/reply
#     rings the scans are compiled against.
#
# Deliberately NOT fingerprinted:
#   - check_workers/no_overlap/sync_checkpoint/on_preempt: analysis- and
#     durability-side only; they never touch the op stream (pinned by
#     test_checkpoint_resilience.py::test_fingerprint_excludes_analysis_flags).
#   - checkpoint_every: the cadence bounds compiled stretches, but
#     stretch-boundary placement is observationally neutral — generator
#     polls at non-interesting times are side-effect-free and timeouts
#     fire at their deadline rounds either way (pinned by
#     test_checkpoint_resume_identical_history, which compares a
#     checkpointed run against an un-checkpointed baseline).
#   - fleet/fleet_sweep/nemesis_seed: the fleet's cluster axis — they
#     shape the batched state tree (leading cluster dimension), the
#     per-cluster seed/schedule assignment, and the op stream itself; a
#     fleet checkpoint only resumes into the same campaign.
FINGERPRINT_KEYS = ("workload", "node", "nodes", "rate", "time_limit",
                    "concurrency", "latency", "nemesis", "nemesis_interval",
                    "topology", "seed", "key_count", "max_txn_length",
                    "max_writes_per_key", "min_txn_length", "ops_per_key",
                    "p_loss", "timeout_ms", "ms_per_round", "recovery_s",
                    "journal_rows", "max_scan", "pool_cap", "gossip_fanout",
                    "mesh", "journal_scan_cap", "reply_log_cap",
                    "collect_replies", "fleet", "fleet_sweep",
                    "nemesis_seed",
                    # open-world streams (doc/streams.md): injection
                    # mode and the consumer-group protocol shape both
                    # change the op stream, so a resume must match
                    # `sessions` is deliberately ABSENT: the coroutine
                    # and columnar backends are byte-identical and emit
                    # the same checkpoint-meta shapes, so a checkpoint
                    # written under one resumes under the other
                    # (pinned by tests/test_sessions.py)
                    "continuous", "continuous_window_ms",
                    "latency_scale", "kafka_groups",
                    "session_timeout_ms", "poll_batch",
                    # batched atomic broadcast (doc/perf.md): the
                    # distiller's batch shape and the value-table
                    # capacity both change the op stream / wire records
                    "batch_max", "batch_dup_rate", "max_values",
                    # role-partitioned clusters (doc/compartment.md):
                    # tier sizes, capacities, and fault targeting all
                    # shape the wire traffic and the nemesis schedule
                    "roles", "service_roles", "nemesis_targets",
                    "leader_slots", "proxy_slots", "compartment_inbox",
                    "compartment_retry", "log_cap", "kv_keys",
                    # leader election (doc/compartment.md): the
                    # candidate set rides `roles` (sequencers=S); the
                    # failure-detector deadline and fenced ballot width
                    # shape the election schedule, so a resume must
                    # match them exactly — as do the client backoff
                    # knobs, which set the redirect-requeue due rounds
                    # (TpuRunner._backoff_rounds) and budget
                    "election_timeout_rounds", "ballot_width",
                    "client_retries", "client_backoff_ms",
                    "client_backoff_cap_ms",
                    # the client-side leader lease rotates the routing
                    # guess on a round schedule, and the ordering axis
                    # (doc/ordering.md) selects the composed
                    # engine x applier program — both shape the op
                    # stream, so a resume must pin them
                    "leader_lease_ms", "ordering",
                    # byzantine adversary (doc/faults.md): the attack
                    # pool and injection rate shape both the decision
                    # stream and the per-round corruption masks, so a
                    # resumed run must replay the identical adversary
                    # (the package seed rides `seed`/`nemesis_seed`)
                    "byz_rate", "byz_attacks")

# The EXPLICIT allowlist backing the comment block above: every
# core.DEFAULTS key that deliberately stays out of FINGERPRINT_KEYS,
# with the reason. The static gate (analyze.check_fingerprint_coverage,
# rule `fingerprint-coverage`) fails on any DEFAULTS key in neither
# list, so a new CLI knob cannot silently skip resume pinning — adding
# one forces the author to either fingerprint it or justify it here.
FINGERPRINT_EXEMPT = {
    "node_count": "derived: build_test expands it into `nodes` (which "
                  "IS fingerprinted); role-spec programs override it",
    "consistency_models": "grading-side only: selects checker models "
                          "over a finished history",
    "log_stderr": "observability: host logging never touches the op "
                  "stream",
    "log_net_send": "observability: wire logging only",
    "log_net_recv": "observability: wire logging only",
    "store_root": "durability path: where artifacts land, not what "
                  "runs",
    "check_workers": "analysis-side pool sizing (pinned by test_"
                     "checkpoint_resilience.py::test_fingerprint_"
                     "excludes_analysis_flags)",
    "no_overlap": "analysis-side scheduling toggle (same pin)",
    "device_checker": "grading backend selection: host and device "
                      "checkers grade the same history",
    "checkpoint_every": "cadence is observationally neutral for "
                        "round-synchronous runs; fingerprint() adds it "
                        "conditionally for --continuous",
    "resume": "the resume pointer itself",
    "sync_checkpoint": "durability-side write scheduling (same pin as "
                       "check_workers)",
    "on_preempt": "durability-side signal policy (same pin)",
    "audit": "static-analysis results block toggle",
    "audit_trace": "static-analysis trace depth toggle",
    "telemetry": "fingerprint() folds the ring on/off BOOLEAN in as "
                 "telemetry_rings; the output directory may move "
                 "between launches",
    "availability_dip_rounds": "checker threshold: grades the window, "
                               "never shapes it",
    "sessions": "coroutine and columnar session backends are "
                "byte-identical and emit the same checkpoint-meta "
                "shapes (pinned by tests/test_sessions.py)",
}


class CheckpointError(RuntimeError):
    """A checkpoint could not be written or loaded (torn/truncated file,
    unknown or pre-versioning format, digest mismatch, writer failure)."""


class Preempted(RuntimeError):
    """The run was interrupted (SIGTERM/SIGINT) and exited through the
    graceful-preemption path. `checkpoint_dir` names the directory
    holding the final checkpoint (None when the run had no store dir to
    save into); relaunch with `--resume <checkpoint_dir>`."""

    def __init__(self, round_: int, checkpoint_dir: str | None):
        self.round = round_
        self.checkpoint_dir = checkpoint_dir
        where = (f"final checkpoint in {checkpoint_dir!r}"
                 if checkpoint_dir else "no store dir, nothing saved")
        super().__init__(
            f"preempted at virtual round {round_} ({where}); "
            f"relaunch with --resume to continue")


def fingerprint(test: dict) -> dict:
    fp = {k: sorted(v) if isinstance(v, set) else v
          for k, v in ((k, test.get(k)) for k in FINGERPRINT_KEYS)}
    # checkpoint cadence stays OUT of the round-synchronous fingerprint
    # (cadence neutrality is pinned — a resume may change it freely),
    # but continuous-mode op timing depends on window boundaries and
    # checkpoints ARE boundaries: a continuous resume must match
    # (doc/streams.md)
    if test.get("continuous"):
        fp["checkpoint_every"] = test.get("checkpoint_every")
    # flight-recorder rings change the checkpointed carry SHAPE (a
    # MetricRing rides SimState.telemetry), so a resume must match the
    # on/off state — but only the boolean: the output DIRECTORY may
    # move freely between launches (crash-soak roots differ per run)
    v = test.get("telemetry")
    fp["telemetry_rings"] = bool(v) and str(v) != "off"
    return fp


def _encode(state: dict) -> bytes:
    payload = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
    return _HEADER.pack(MAGIC, VERSION, len(payload),
                        hashlib.sha256(payload).digest()) + payload


def _decode(blob: bytes, path: str) -> dict:
    if blob[:1] == b"\x80" and blob[:len(MAGIC)] != MAGIC:
        # a bare pickle protocol marker: the pre-versioning format
        raise CheckpointError(
            f"{path!r}: pre-versioning raw-pickle checkpoint (format "
            f"v1); this build reads v{VERSION} — re-create it with a "
            f"current run")
    if len(blob) < _HEADER.size:
        raise CheckpointError(
            f"{path!r}: truncated checkpoint ({len(blob)} bytes is "
            f"smaller than the {_HEADER.size}-byte v{VERSION} header)")
    magic, version, n, digest = _HEADER.unpack_from(blob)
    if magic != MAGIC:
        raise CheckpointError(
            f"{path!r}: not a maelstrom checkpoint (bad magic)")
    if version != VERSION:
        raise CheckpointError(
            f"{path!r}: checkpoint format v{version} is not supported "
            f"by this build (expected v{VERSION})")
    payload = blob[_HEADER.size:]
    if len(payload) != n:
        raise CheckpointError(
            f"{path!r}: truncated checkpoint (header promises {n} "
            f"payload bytes, file holds {len(payload)})")
    if hashlib.sha256(payload).digest() != digest:
        raise CheckpointError(
            f"{path!r}: corrupt checkpoint (payload digest mismatch — "
            f"torn write?)")
    try:
        return pickle.loads(payload)
    except Exception as e:
        raise CheckpointError(
            f"{path!r}: checkpoint payload failed to unpickle "
            f"({e!r})") from e


def _fsync_dir(dir_path: str):
    fd = os.open(dir_path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def save(dir_path: str, state: dict) -> str:
    """Durably writes a checkpoint into `dir_path`: tmp file + fsync +
    atomic rename + directory fsync, keeping the previous checkpoint as
    `checkpoint.prev.pkl` (the fallback if this write is torn). Device
    arrays in `state["sim"]` are pulled to host numpy first (a no-op
    when the caller already did — the async writer path must, so the
    device pull never happens off the main thread)."""
    os.makedirs(dir_path, exist_ok=True)
    path = os.path.join(dir_path, CHECKPOINT_FILE)
    prev = os.path.join(dir_path, PREV_CHECKPOINT_FILE)
    tmp = path + ".tmp"
    if "sim" in state:
        state = dict(state, sim=jax.device_get(state["sim"]))
    blob = _encode(state)
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(path):
            os.replace(path, prev)      # keep the last good checkpoint
        os.replace(tmp, path)
        _fsync_dir(dir_path)
    finally:
        # never leave a stale .tmp behind on a failed/interrupted write
        try:
            if os.path.exists(tmp):
                os.unlink(tmp)
        except OSError:                 # pragma: no cover - best effort
            pass
    return path


def _load_state(dir_path: str) -> dict:
    path = os.path.join(dir_path, CHECKPOINT_FILE)
    prev = os.path.join(dir_path, PREV_CHECKPOINT_FILE)
    if not os.path.exists(path) and not os.path.exists(prev):
        raise FileNotFoundError(
            f"no {CHECKPOINT_FILE} in {dir_path!r} - was the original run "
            "started with --checkpoint-every?")
    try:
        with open(path, "rb") as f:
            return _decode(f.read(), path)
    except (CheckpointError, OSError) as e:
        if not os.path.exists(prev):
            raise
        log.warning("newest checkpoint unusable (%s); falling back to "
                    "the previous one (%s)", e, prev)
        with open(prev, "rb") as f:
            return _decode(f.read(), prev)


def load(dir_path: str) -> dict:
    """Loads (and integrity-checks) a checkpoint; `sim` leaves come back
    as device arrays, `history` as a rebuilt History. Falls back to the
    previous checkpoint when the newest write is torn."""
    state = _load_state(dir_path)
    # the writer stores the mutable host-side run state (generator tree,
    # pending RPCs, intern tables, nemesis rng) as one blob pickled on
    # the main thread at snapshot time; flatten it back out
    meta = state.pop("meta_blob", None)
    if meta is not None:
        state.update(pickle.loads(meta))
    cols = state.pop("history_columns", None)
    if cols is not None:
        from .history import History
        state["history"] = History.from_columns(cols)
    state["sim"] = jax.tree.map(jnp.asarray, state["sim"])
    return state


def check_fingerprint(ckpt: dict, test: dict):
    want, got = ckpt.get("fingerprint", {}), fingerprint(test)
    diffs = {k: (want.get(k), got.get(k)) for k in want
             if want.get(k) != got.get(k)}
    if diffs:
        raise ValueError(
            "resume options differ from the checkpointed run "
            f"(checkpointed vs given): {diffs}")


class CheckpointWriter:
    """Background checkpoint writer with AT MOST ONE write in flight:
    `submit` hands the pickle+fsync+rename of a fully host-materialized
    state to a daemon thread and returns immediately, so the device
    keeps dispatching while the snapshot lands. A second submit (or
    `wait`) first joins the in-flight write — the invariant is asserted,
    not hoped for — and re-raises any writer failure as a
    CheckpointError on the main thread."""

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._exc: BaseException | None = None
        self.writes = 0
        self.write_s = 0.0          # cumulative background write wall time

    def in_flight(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def submit(self, dir_path: str, state: dict):
        self.wait()                 # enforce the one-in-flight invariant
        assert self._thread is None, "checkpoint writer already in flight"

        def _write():
            t0 = time.perf_counter()
            try:
                save(dir_path, state)
            except BaseException as e:      # surfaced by the next wait()
                self._exc = e
            finally:
                self.writes += 1
                self.write_s += time.perf_counter() - t0

        t = threading.Thread(target=_write, name="maelstrom-ckpt-writer",
                             daemon=True)
        self._thread = t
        t.start()

    def wait(self):
        """Joins the in-flight write (if any); raises if it failed."""
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        if self._exc is not None:
            e, self._exc = self._exc, None
            raise CheckpointError(
                f"background checkpoint write failed: {e!r}") from e
