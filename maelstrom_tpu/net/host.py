"""The host-path simulated network: threads, queues, real time.

A faithful reimplementation of the reference's in-JVM network
(`src/maelstrom/net.clj`): per-node priority queues ordered by latency
deadline, probabilistic loss applied at send, directional partitions applied
at receive, clients given zero latency, every send/recv journaled. This path
exists for compatibility — it runs *external node binaries* and host-side
services exactly like the reference. The TPU path
(`maelstrom_tpu.net.tpu`) replaces it for batched built-in nodes.

Faults (reference `net.clj:104-121`): `drop_link!` adds src to dest's block
set, `heal!` clears partitions, `slow!` scales latency x10, `fast!` unscales,
`flaky!` sets p_loss = 0.5.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import math
import random
import threading
import time as _time
from typing import Optional

from ..errors import RPCError
from ..message import Message, message, validate
from ..util import involves_client
from .journal import Journal

log = logging.getLogger("maelstrom.net")


class LatencyDist:
    """Latency distributions (reference `net.clj:64-76`):
    constant(mean), uniform over [0, 2*mean], exponential with mean."""

    def __init__(self, mean: float = 0, dist: str = "constant",
                 scale: float = 1.0):
        assert dist in ("constant", "uniform", "exponential"), dist
        self.mean = mean
        self.dist = dist
        self.scale = scale

    def draw(self, rng: random.Random) -> float:
        if self.mean <= 0:
            base = 0.0
        elif self.dist == "constant":
            base = self.mean
        elif self.dist == "uniform":
            base = rng.uniform(0, 2 * self.mean)
        else:
            base = rng.expovariate(1.0 / self.mean)
        return base * self.scale

    def scaled(self, factor: float) -> "LatencyDist":
        return LatencyDist(self.mean, self.dist, self.scale * factor)

    def unscaled(self) -> "LatencyDist":
        return LatencyDist(self.mean, self.dist, 1.0)


class _NodeQueue:
    """A blocking priority queue of (deadline, seq, Message), mirroring the
    per-node PriorityBlockingQueue (reference `net.clj:143-144`)."""

    def __init__(self):
        self.heap = []
        self.cond = threading.Condition()
        self.seq = itertools.count()

    def put(self, deadline: float, msg: Message):
        with self.cond:
            heapq.heappush(self.heap, (deadline, next(self.seq), msg))
            self.cond.notify()

    def poll(self, timeout_s: float):
        """Pops the earliest-deadline entry, waiting up to timeout_s.
        Like PriorityBlockingQueue.poll: returns as soon as *any* entry
        exists (the deadline sleep happens in recv)."""
        deadline = _time.monotonic() + timeout_s
        with self.cond:
            while not self.heap:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return None
                self.cond.wait(remaining)
            return heapq.heappop(self.heap)


class HostNet:
    """The mutable simulated network (reference `net.clj:78-102`)."""

    def __init__(self, latency: dict | None = None, log_send: bool = False,
                 log_recv: bool = False, seed: int = 0):
        latency = latency or {}
        self.latency_dist = LatencyDist(latency.get("mean", 0),
                                        latency.get("dist", "constant"))
        self.log_send = log_send
        self.log_recv = log_recv
        self.journal: Journal | None = None
        self.p_loss = 0.0
        self.p_dup = 0.0        # at-least-once duplication (servers only)
        # batched-payload parity with the TPU net (net/tpu.py
        # `NetConfig.unit_words`): a JSON body carrying `batch_units: n`
        # is ONE message transporting n logical client ops; both paths
        # book units next to raw message counts so ops-per-message
        # economics read the same whichever network ran the test
        self.sent_units = 0
        self.recv_units = 0
        self.batched_msgs = 0   # messages that declared batch_units > 1
        # flight-recorder counter parity (doc/observability.md): the
        # host net books the same counter classes the TPU path's device
        # MetricRing accumulates — sends attempted, deliveries, drops
        # (loss + partition), at-least-once duplicates — so both
        # network paths expose one telemetry vocabulary
        # (`telemetry_counters()`, surfaced by NetStatsChecker when
        # --telemetry is on; keys match telemetry.ring_dict)
        self.sent_count = 0
        self.recv_count = 0
        self.lost_count = 0
        self.dropped_partition = 0
        self.dup_count = 0
        self.partitions: dict[str, set[str]] = {}   # dest -> blocked srcs
        # byzantine wire corruption (byzantine.py): the active attack
        # plan, a per-src cache of the last honest body (the stale
        # replay source), and the injection ledger the conviction
        # contract is audited against ({attack name: count})
        self._byz: dict | None = None
        self._byz_rng = random.Random(0)
        self._byz_prev: dict[str, dict] = {}
        self.byz_injected: dict[str, int] = {}
        self.queues: dict[str, _NodeQueue] = {}
        self.next_client_id = itertools.count(0)
        self.next_message_id = itertools.count(0)
        self.rng = random.Random(seed)
        self.lock = threading.Lock()
        self.t0 = _time.monotonic_ns()

    # --- lifecycle ---

    def time_ns(self) -> int:
        """Linear time since network creation."""
        return _time.monotonic_ns() - self.t0

    def add_node(self, node_id: str):
        assert isinstance(node_id, str), f"node id {node_id!r} must be a string"
        with self.lock:
            self.queues[node_id] = _NodeQueue()
        return self

    def remove_node(self, node_id: str):
        with self.lock:
            self.queues.pop(node_id, None)
        return self

    def node_ids(self):
        return list(self.queues)

    def queue_for(self, node: str) -> _NodeQueue:
        q = self.queues.get(node)
        if q is None:
            # reference net.clj:153-163: error 1, definite
            raise RPCError(1, {"text": f"No such node in network: {node!r}"})
        return q

    # --- fault API (reference net.clj:104-121) ---

    def drop_link(self, src: str, dest: str):
        with self.lock:
            self.partitions.setdefault(dest, set()).add(src)

    def heal(self):
        with self.lock:
            self.partitions = {}

    def slow(self, factor: float = 10.0):
        self.latency_dist = self.latency_dist.scaled(factor)

    def fast(self):
        self.latency_dist = self.latency_dist.unscaled()

    def flaky(self, p: float = 0.5):
        self.p_loss = p

    def duplicate(self, p: float = 0.25):
        """At-least-once delivery: each inter-server message is enqueued
        a second time with probability p, under an independent latency
        draw (same message id — it IS the same message, twice)."""
        self.p_dup = p

    def set_byzantine(self, attack: str, culprit: str, delta: int,
                      rate: float = 1.0):
        """Installs one byzantine attack window (byzantine.py): the
        `culprit`'s inter-server messages are corrupted per `attack`
        with probability `rate`, on the DELIVERED copy only — the
        journal keeps the honest body at send, so the wire auditor
        (checkers/byzantine.py) can prove the lie from the record."""
        self._byz = {"attack": attack, "culprit": culprit,
                     "delta": int(delta), "rate": float(rate)}
        # own stream, keyed off the plan nonce: corruption rolls must
        # not perturb the shared loss/latency/dup draws
        self._byz_rng = random.Random(f"byz:{delta}")

    def clear_byzantine(self):
        self._byz = None

    def _corrupt(self, msg: Message) -> Message:
        """Applies the active byzantine window to one send, returning
        the (possibly) corrupted delivery copy and booking the
        injection. Mirrors the TPU path's attack taxonomy on JSON
        bodies: equivocation flips a value-carrying int field,
        forged-proof bumps the proof/count fields, stale-ballot
        replays the culprit's previous (already-journaled) body."""
        bz = self._byz
        if bz is None or involves_client(msg) or msg.src != bz["culprit"]:
            return msg
        body = msg.body if isinstance(msg.body, dict) else None
        if body is None:
            return msg
        from ..byzantine import PROOF_FIELDS
        prev = self._byz_prev.get(msg.src)
        self._byz_prev[msg.src] = dict(body)
        if self._byz_rng.random() >= bz["rate"]:
            return msg
        attack, new = bz["attack"], None
        if attack == "stale-ballot":
            if prev is not None and prev != body:
                new = dict(prev)
        elif attack == "forged-proof":
            forged = {k: body[k] + 1 + (bz["delta"] & 3)
                      for k in PROOF_FIELDS
                      if isinstance(body.get(k), int)
                      and not isinstance(body.get(k), bool)}
            if forged:
                new = {**body, **forged}
        else:   # equivocation
            skip = set(PROOF_FIELDS) | {"type", "msg_id", "in_reply_to"}
            for k, v in body.items():
                if k in skip or isinstance(v, bool) \
                        or not isinstance(v, int):
                    continue
                new = {**body, k: v ^ ((bz["delta"] & 0x3F) | 1)}
                break
        if new is None or new == body:
            return msg
        self.byz_injected[attack] = self.byz_injected.get(attack, 0) + 1
        return Message(id=msg.id, src=msg.src, dest=msg.dest, body=new)

    # --- send / recv (reference net.clj:188-246) ---

    @staticmethod
    def _units(msg: Message) -> int:
        """Logical client-op units one message carries: the declared
        `batch_units` body field for distilled-batch RPCs, else 1 (the
        host half of the TPU net's `payload_units`)."""
        body = msg.body if isinstance(msg.body, dict) else {}
        try:
            return max(int(body.get("batch_units", 1)), 1)
        except (TypeError, ValueError):
            return 1

    def latency_for_ms(self, msg: Message) -> float:
        """Clients get zero latency — latency on clients *hides* consistency
        anomalies (reference `net.clj:177-186`)."""
        if involves_client(msg):
            return 0.0
        return self.latency_dist.draw(self.rng)

    def send(self, msg) -> Message:
        if isinstance(msg, dict):
            msg = message(msg.get("src"), msg.get("dest"), msg.get("body"))
        msg = Message(id=next(self.next_message_id), src=msg.src,
                      dest=msg.dest, body=msg.body)
        validate(msg)
        if msg.src not in self.queues:
            raise AssertionError(f"Invalid source for message {msg!r}")
        dest_q = self.queue_for(msg.dest)
        deadline_ns = self.time_ns() + int(self.latency_for_ms(msg) * 1e6)

        if self.journal is not None:
            self.journal.log_send(msg, self.time_ns())
        u = self._units(msg)
        self.sent_units += u
        self.sent_count += 1
        if u > 1:
            self.batched_msgs += 1
        if self.log_send:
            log.info("send %r", msg)

        if self.rng.random() < self.p_loss:
            self.lost_count += 1
            return msg      # whoops, lost ur packet (net.clj:213-214)
        # byzantine corruption hits the DELIVERED copy, after the send
        # journal booked the honest body (the lie is provable from the
        # record) — and a duplicated lie is the same lie twice
        msg = self._corrupt(msg)
        dest_q.put(deadline_ns, msg)
        if (self.p_dup > 0 and not involves_client(msg)
                and self.rng.random() < self.p_dup):
            # duplicate fault: the copy takes its own latency draw
            # (clients exempt, like partitions — the fault models the
            # server-to-server network)
            dup_deadline = self.time_ns() + int(
                self.latency_for_ms(msg) * 1e6)
            dest_q.put(dup_deadline, msg)
            self.dup_count += 1
        return msg

    def recv(self, node: str, timeout_ms: float) -> Optional[Message]:
        """Receive a message for `node`, waiting up to timeout_ms. Applies
        partitions at delivery time and sleeps until the latency deadline
        (reference `net.clj:222-246`). Returns None on timeout or when the
        popped message is partitioned away (which consumes it)."""
        entry = self.queue_for(node).poll(timeout_ms / 1000.0)
        if entry is None:
            return None
        deadline_ns, _, msg = entry
        blocked = self.partitions.get(node, ())
        if msg.src in blocked:
            self.dropped_partition += 1
            return None     # consumed and dropped, like the reference
        dt_ns = deadline_ns - self.time_ns()
        if dt_ns > 0:
            _time.sleep(dt_ns / 1e9)
        if self.log_recv:
            log.info("recv %r", msg)
        if self.journal is not None:
            self.journal.log_recv(msg, self.time_ns())
        self.recv_units += self._units(msg)
        self.recv_count += 1
        return msg

    def telemetry_counters(self) -> dict:
        """The host half of the flight-recorder counter vocabulary:
        keyed exactly like the device ring's `telemetry.ring_dict`
        message-flow block, so a result (or parity test) reads the same
        whichever network ran the test."""
        return {"sent": self.sent_count, "delivered": self.recv_count,
                "dropped": self.lost_count + self.dropped_partition,
                "duplicated": self.dup_count}
