"""The TPU-path simulated network: message arrays, scatter/gather, masks.

This replaces the reference's thread/queue network (`src/maelstrom/net.clj`)
with a batched discrete-event design. All in-flight messages live in a
fixed-capacity *flight pool* of device arrays; time is an integer round
counter; per-message latency draws map to delivery rounds. One call to
`deliver` + node step + `send` advances the whole N-node network one round
inside a single jitted dispatch.

Semantic mapping to the reference:
  - per-node PriorityBlockingQueue ordered by deadline (`net.clj:143-144`)
      -> flight pool sorted by (dest, due) at delivery; earliest-due messages
         win inbox slots; the rest stay pooled (backpressure, never dropped)
  - probabilistic loss applied at send (`net.clj:213-214`)
      -> Bernoulli mask over new messages
  - directional partitions applied at receive (`net.clj:233`), which
    *consume* the blocked message
      -> component labels per node: a message is blocked iff its endpoints
         are in different components and neither endpoint is a client
         (the partition nemesis only severs node-node links)
  - clients get zero latency (`net.clj:177-186`)
      -> client-involved messages get a 0-round latency draw
  - message ids assigned at send, before the loss roll (`net.clj:205-214`)
      -> ids = next_mid + rank over *all* attempted sends
  - journal hooks on every send/recv (`net.clj:207,243`)
      -> on-device counters (NetStats); the interactive runner additionally
         materializes per-message journal rows on host for small tests

Everything here is pure and jit/scan/shard_map-friendly: static shapes,
no data-dependent control flow.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

I32 = jnp.int32
INT32_MAX = jnp.iinfo(jnp.int32).max


@struct.dataclass
class Msgs:
    """A struct-of-arrays batch of messages. Fields may have any common
    leading shape (pool `[P]`, inbox `[N, K]`, outbox `[N, O]`).

    Bodies are fixed-width: a type code and three payload words. Workload
    programs define their own type codes and word layouts; arbitrary JSON
    bodies exist only at the host boundary (`net/host.py`)."""
    valid: jnp.ndarray      # bool
    src: jnp.ndarray        # i32 node index; clients are indices >= n_nodes
    dest: jnp.ndarray       # i32
    due: jnp.ndarray        # i32 delivery round
    mid: jnp.ndarray        # i32 global message id
    reply_to: jnp.ndarray   # i32 in_reply_to message id, or -1
    type: jnp.ndarray       # i32 body type code (workload-defined)
    a: jnp.ndarray          # i32 payload word
    b: jnp.ndarray          # i32 payload word
    c: jnp.ndarray          # i32 payload word

    @classmethod
    def empty(cls, shape) -> "Msgs":
        if isinstance(shape, int):
            shape = (shape,)
        z = jnp.zeros(shape, I32)
        return cls(valid=jnp.zeros(shape, bool), src=z, dest=z, due=z,
                   mid=z, reply_to=jnp.full(shape, -1, I32), type=z,
                   a=z, b=z, c=z)

    def at_rows(self, idx) -> "Msgs":
        return jax.tree.map(lambda f: f[idx], self)

    def count(self):
        return jnp.sum(self.valid)


@struct.dataclass
class NetStats:
    """On-device journal counters, the TPU analogue of the Fressian journal
    folds (`net/journal.clj:339-347`). "servers" = not client-involved, as in
    `util.clj:12-16`."""
    sent_all: jnp.ndarray
    sent_servers: jnp.ndarray
    recv_all: jnp.ndarray
    recv_servers: jnp.ndarray
    lost: jnp.ndarray
    dropped_partition: jnp.ndarray
    dropped_overflow: jnp.ndarray   # pool-full drops: MUST be 0 for a valid run
    # client-op UNITS transported (batched atomic broadcast,
    # doc/perf.md): a distilled batch row is ONE message carrying n
    # logical client ops — the program registers which type codes are
    # batches and which payload word holds the count
    # (`NetConfig.unit_words`), and the net books units next to raw
    # message counts so ops-per-message economics stay honest. Both stay
    # 0 when no unit_words are configured (the booking compiles out).
    sent_units: jnp.ndarray
    recv_units: jnp.ndarray
    # messages consumed because their destination was crash-killed by
    # the nemesis (the process is down: delivery is connection-refused,
    # unlike pause where the message waits in the pool)
    dropped_down: jnp.ndarray
    # extra at-least-once copies enqueued by the duplicate fault
    duplicated: jnp.ndarray
    # [64] sends per wire-type code: the per-RPC-type breakdown the
    # reference's tesser folds produce from the Fressian journal
    # (net/journal.clj:339-347) — here it survives bench scale, where
    # per-message journal rows don't
    sent_by_type: jnp.ndarray

    @classmethod
    def zeros(cls) -> "NetStats":
        z = jnp.zeros((), I32)
        return cls(z, z, z, z, z, z, z, z, z, z, z,
                   jnp.zeros(TYPE_BUCKETS, I32))


TYPE_BUCKETS = 64     # wire type codes are small ints; 63 = overflow bin


def count_by_type(counter, types, valid):
    """Scatter-add valid message counts into per-type-code buckets."""
    return counter.at[jnp.clip(types.reshape(-1), 0, TYPE_BUCKETS - 1)
                      ].add(valid.reshape(-1).astype(I32))


@struct.dataclass
class NetState:
    pool: Msgs                  # [P] flight pool
    next_mid: jnp.ndarray       # i32 scalar
    round: jnp.ndarray          # i32 scalar
    component: jnp.ndarray      # i32 [n_nodes + n_clients] partition labels
    p_loss: jnp.ndarray         # f32 scalar
    latency_scale: jnp.ndarray  # f32 scalar (slow! = x10, fast! = x1)
    # --- combined-nemesis fault masks ---
    # Directional partitions the component labels cannot express
    # (one-way links, bridge, majorities-ring): node i belongs to block
    # group block_groups[i], and src->dest traffic is blocked iff
    # block_matrix[g_src, g_dest]. Sized by cfg.partition_groups (1 when
    # no partition nemesis runs: a [1, 1] False matrix, inert).
    block_groups: jnp.ndarray   # i32 [n_nodes + n_clients]
    block_matrix: jnp.ndarray   # bool [G, G]
    down: jnp.ndarray           # bool [n_nodes]: crash-killed (drops msgs)
    paused: jnp.ndarray         # bool [n_nodes]: stalled (defers msgs)
    p_dup: jnp.ndarray          # f32 scalar: at-least-once duplication
    stats: NetStats


@dataclass(frozen=True)
class NetConfig:
    """Static network shape/latency configuration (hashable, jit-static)."""
    n_nodes: int
    n_clients: int = 0
    pool_cap: int = 4096          # max in-flight messages
    inbox_cap: int = 8            # max deliveries per node per round
    client_cap: int = 64          # max client deliveries per round (0 = count only)
    latency_mean_rounds: float = 0.0
    latency_dist: str = "constant"
    ms_per_round: float = 1.0     # rounds -> wall-ms mapping for histories
    # --- static fault-capability switches (each enabled path costs a
    # little every round, so runs that can't see the fault don't pay) ---
    partition_groups: int = 1     # block-matrix side; 1 = component-only
    enable_stall: bool = False    # kill/pause masks honored in the round
    enable_duplication: bool = False  # duplicate fault path compiled in
    # byzantine wire corruption (byzantine.py): when True the round body
    # threads the adversary carry (SimState.byz) and applies the
    # program-wired corruption masks to the outbox before send
    enable_byz: bool = False
    # batched payload rows (doc/perf.md "batched atomic broadcast"):
    # ((type_code, word), ...) pairs declaring that messages of
    # `type_code` are distilled batches whose logical client-op count
    # rides payload word `word` (0 = a, 1 = b, 2 = c). Every other
    # message counts 1 unit. Empty = units booking compiles out.
    unit_words: tuple = ()
    # flight-recorder metric rings (doc/observability.md): when True the
    # round body folds per-round telemetry — message-flow deltas,
    # occupancy histograms, per-role send counts, reply-latency buckets
    # — into the SimState.telemetry int32 carry block
    # (telemetry.MetricRing), drained only on the existing
    # dispatch-boundary fetches. Off = the block compiles out entirely;
    # histories are byte-identical either way. `telemetry_roles` is the
    # static ((lo, hi), ...) node-id slicing role_sent buckets by
    # (telemetry.role_bounds).
    telemetry: bool = False
    telemetry_roles: tuple = ()

    @property
    def n_total(self) -> int:
        return self.n_nodes + self.n_clients


def make_net(cfg: NetConfig) -> NetState:
    return NetState(
        pool=Msgs.empty(cfg.pool_cap),
        next_mid=jnp.zeros((), I32),
        round=jnp.zeros((), I32),
        component=jnp.zeros(cfg.n_total, I32),
        p_loss=jnp.zeros((), jnp.float32),
        latency_scale=jnp.ones((), jnp.float32),
        block_groups=jnp.zeros(cfg.n_total, I32),
        block_matrix=jnp.zeros((cfg.partition_groups,
                                cfg.partition_groups), bool),
        down=jnp.zeros(cfg.n_nodes, bool),
        paused=jnp.zeros(cfg.n_nodes, bool),
        p_dup=jnp.zeros((), jnp.float32),
        stats=NetStats.zeros())


def involves_client(cfg: NetConfig, src, dest):
    """Client on either end (reference `util.clj:12-16`)."""
    return (src >= cfg.n_nodes) | (dest >= cfg.n_nodes)


def cat_lanes(*batches: Msgs) -> Msgs:
    """Concatenates [N, L_i] Msgs batches along the lane axis — the
    outbox-assembly helper node programs use to join per-purpose lane
    groups into one outbox."""
    return jax.tree.map(lambda *fs: jnp.concatenate(fs, axis=1),
                        *batches)


def payload_units(cfg: NetConfig, types, words, valid):
    """Total client-op units over a masked message batch: 1 per valid
    message, except registered batch types (`cfg.unit_words`), which
    count their declared payload word (floored at 1 — a batch always
    carries at least its own record). Shapes are whatever the caller's
    batch uses; `words` is the (a, b, c) triple."""
    u = valid.astype(I32)
    for code, w in cfg.unit_words:
        u = jnp.where(valid & (types == code),
                      jnp.maximum(words[w], 1), u)
    return jnp.sum(u)


def draw_latency_rounds(cfg: NetConfig, key, scale, shape):
    """Vectorized latency draw in rounds (reference `net.clj:64-76`):
    constant(mean), uniform over [0, 2*mean], exponential with mean."""
    mean = jnp.float32(cfg.latency_mean_rounds) * scale
    if cfg.latency_dist == "constant":
        base = jnp.broadcast_to(mean, shape)
    elif cfg.latency_dist == "uniform":
        base = jax.random.uniform(key, shape) * (2.0 * mean)
    elif cfg.latency_dist == "exponential":
        base = jax.random.exponential(key, shape) * mean
    else:  # pragma: no cover
        raise ValueError(f"unknown latency dist {cfg.latency_dist!r}")
    return jnp.round(base).astype(I32)


def _scatter_new(cfg: NetConfig, pool: Msgs, incoming: Msgs):
    """Scatter a flat batch of messages (rows where incoming.valid) into
    free pool slots. Free-slot allocation without a sort: rank free slots
    by prefix sum, build rank -> slot via a unique-index scatter, then
    each kept message takes the slot matching its own rank. O(P) instead
    of O(P log^2 P). Returns (pool', ok) where ok marks the rows that
    found a slot."""
    keep = incoming.valid
    free = ~pool.valid
    n_free = jnp.sum(free.astype(I32))
    free_rank = jnp.cumsum(free.astype(I32)) - 1     # rank of each free slot
    P = cfg.pool_cap
    slot_by_rank = jnp.zeros(P, I32).at[
        jnp.where(free, free_rank, P)].set(
            jnp.arange(P, dtype=I32), mode="drop", unique_indices=True)
    k_rank = jnp.cumsum(keep.astype(I32)) - 1
    ok = keep & (k_rank < n_free)
    slot = slot_by_rank[jnp.clip(k_rank, 0, P - 1)]
    # out-of-bounds index => dropped by scatter mode='drop'
    tgt = jnp.where(ok, slot, P)
    pool = jax.tree.map(
        lambda pf, nf: pf.at[tgt].set(nf, mode="drop", unique_indices=True),
        pool, incoming.replace(valid=ok))
    return pool, ok


def _send(cfg: NetConfig, net: NetState, out: Msgs, key):
    """Enqueue a flat batch of outgoing messages `out` (`[M]`) into the
    flight pool: assign ids, draw latencies, roll loss, scatter into free
    slots (reference `net.clj:188-220`). Returns (net', sent_view) where
    sent_view is the id-stamped batch for journaling.

    Messages that find no free pool slot are dropped and counted in
    `stats.dropped_overflow` — a correct run sizes `pool_cap` so this stays
    zero (a silent drop would corrupt set-full checker results)."""
    pool, M = net.pool, out.valid.shape[0]
    if cfg.enable_duplication:
        k_lat, k_loss, k_dup, k_dlat = jax.random.split(key, 4)
    else:
        k_lat, k_loss = jax.random.split(key)

    new = out.valid
    rank = jnp.cumsum(new.astype(I32)) - 1
    mid = net.next_mid + rank                      # ids precede the loss roll
    client = involves_client(cfg, out.src, out.dest)
    lat = jnp.where(client, 0,
                    draw_latency_rounds(cfg, k_lat, net.latency_scale, (M,)))
    # deadline = now + latency (reference `net.clj:201-204`), with a
    # one-round causal floor: a message can never arrive in its own
    # send round. (+1+lat would inflate every hop by one round and bias
    # stable-latency quantiles vs the reference's wall-clock deadlines.)
    due = net.round + jnp.maximum(1, lat)

    lost = new & (jax.random.uniform(k_loss, (M,)) < net.p_loss)
    keep = new & ~lost

    incoming = out.replace(valid=keep, mid=mid, due=due)
    pool, ok = _scatter_new(cfg, pool, incoming)
    # journal view: every attempted send with its assigned id, including
    # messages the loss roll ate (the reference journals before the loss
    # check, net.clj:207,213)
    sent_view = out.replace(valid=new, mid=mid, due=due)

    n_dup = jnp.zeros((), I32)
    if cfg.enable_duplication:
        # at-least-once amplification: each kept inter-server message is
        # re-enqueued with probability p_dup, SAME id (it is the same
        # message delivered twice) but an independent latency draw.
        # Client RPCs are exempt, like partitions (`net.clj:233`): the
        # fault models the server-to-server network. A copy that finds
        # no free slot is silently skipped (amplification is
        # best-effort; it must never flag dropped_overflow).
        dup = (keep & ~client
               & (jax.random.uniform(k_dup, (M,)) < net.p_dup))
        lat2 = draw_latency_rounds(cfg, k_dlat, net.latency_scale, (M,))
        due2 = net.round + jnp.maximum(1, lat2)
        pool, dup_ok = _scatter_new(
            cfg, pool, out.replace(valid=dup, mid=mid, due=due2))
        n_dup = jnp.sum(dup_ok.astype(I32))

    st = net.stats
    st = st.replace(
        sent_all=st.sent_all + jnp.sum(new.astype(I32)),
        sent_servers=st.sent_servers + jnp.sum((new & ~client).astype(I32)),
        lost=st.lost + jnp.sum(lost.astype(I32)),
        dropped_overflow=st.dropped_overflow
        + jnp.sum((keep & ~ok).astype(I32)),
        duplicated=st.duplicated + n_dup,
        sent_by_type=count_by_type(st.sent_by_type, out.type, new))
    if cfg.unit_words:
        st = st.replace(sent_units=st.sent_units + payload_units(
            cfg, out.type, (out.a, out.b, out.c), new))
    net = net.replace(pool=pool, stats=st,
                      next_mid=net.next_mid + jnp.sum(new.astype(I32)))
    return net, sent_view


def _deliver(cfg: NetConfig, net: NetState):
    """Deliver all due messages for the current round.

    Returns `(net', inbox, client_msgs)` where `inbox` is a `[N, K]` Msgs
    batch (per-node, earliest-due first) and `client_msgs` is a flat
    `[client_cap]` Msgs batch of messages addressed to clients. Node messages
    that lose the K-slot race stay pooled for the next round; partitioned
    messages are consumed and dropped, mirroring the reference's recv
    (`net.clj:222-246`).

    Rounds with nothing due skip the whole delivery pipeline under a
    `lax.cond`: edge programs route node traffic over the static
    channels, so their pool is empty most rounds, and the ~5 ms
    composite-key argsort at 100k nodes was pure overhead there. (Under
    vmap — the cluster-parallel path — XLA lowers the cond to executing
    both branches, which is simply the old behavior.)"""
    N, K = cfg.n_nodes, cfg.inbox_cap
    CC = min(cfg.client_cap, cfg.pool_cap)
    any_due = (net.pool.valid & (net.pool.due <= net.round)).any()

    def skip(net):
        return net, Msgs.empty((N, K)), Msgs.empty(CC)

    return jax.lax.cond(any_due, partial(_deliver_due, cfg), skip, net)


def _deliver_due(cfg: NetConfig, net: NetState):
    pool, P, N, K = net.pool, cfg.pool_cap, cfg.n_nodes, cfg.inbox_cap

    due = pool.valid & (pool.due <= net.round)
    client_msg = involves_client(cfg, pool.src, pool.dest)
    src_i = jnp.clip(pool.src, 0, cfg.n_total - 1)
    dest_i = jnp.clip(pool.dest, 0, cfg.n_total - 1)
    blocked = net.component[src_i] != net.component[dest_i]
    if cfg.partition_groups > 1:
        # directional grudges (one-way, bridge, majorities-ring): the
        # block matrix says whether src's group may reach dest's group
        blocked = blocked | net.block_matrix[net.block_groups[src_i],
                                             net.block_groups[dest_i]]
    blocked = blocked & ~client_msg
    if cfg.enable_stall:
        dest_node = pool.dest < N
        nd = jnp.clip(pool.dest, 0, N - 1)
        # paused dest: the message WAITS in the pool (the OS buffers for
        # a stalled process); down dest: consumed and dropped
        # (connection refused — the process is gone)
        due = due & ~(dest_node & net.paused[nd])
        to_down = due & ~blocked & dest_node & net.down[nd]
    else:
        to_down = jnp.zeros_like(due)
    to_client = due & ~blocked & (pool.dest >= N)
    to_node = due & ~blocked & (pool.dest < N) & ~to_down
    dropped = due & blocked

    # --- node delivery: one sort on a composite (dest, due-age) key ---
    # due-age = how overdue a message is, clipped to 14 bits; earlier-due
    # messages rank first within a dest. dest * 2^14 stays within int32 for
    # n_nodes up to ~128k; larger clusters fall back to dest-only order.
    age_bits = 14 if N < (1 << 17) else 0
    age = jnp.clip(pool.due - net.round + (1 << (age_bits - 1))
                   if age_bits else 0, 0, (1 << age_bits) - 1)
    key = jnp.where(to_node, (pool.dest << age_bits) | age, INT32_MAX)
    # explicit pool-index tiebreak operand: equal (dest, age) keys would
    # otherwise rely on argsort STABILITY for their relative order, and
    # GSPMD's partitioned sort does not preserve stability across shard
    # merges — same-seed `--mesh` runs would diverge from single-chip
    # exactly when two messages to one node tie. A unique total order
    # makes every correct sort implementation produce one permutation.
    order = jnp.lexsort((jnp.arange(P, dtype=I32), key))
    sdest = jnp.where(to_node, pool.dest, N)[order]
    first = jnp.searchsorted(sdest, sdest, side="left")
    slot = jnp.arange(P, dtype=I32) - first.astype(I32)
    take = to_node[order] & (slot < K)

    tgt_dest = jnp.where(take, sdest, N)           # N => dropped scatter
    tgt_slot = jnp.clip(slot, 0, K - 1)
    sorted_msgs = pool.at_rows(order)
    inbox = jax.tree.map(
        lambda z, f: z.at[tgt_dest, tgt_slot].set(f, mode="drop",
                                                  unique_indices=True),
        Msgs.empty((N, K)), sorted_msgs.replace(valid=take))

    taken = jnp.zeros(P, bool).at[order].set(take, unique_indices=True)

    # --- client delivery: due-ordered, first client_cap extracted ---
    CC = min(cfg.client_cap, P)
    if CC > 0:
        # same total-order discipline as the node sort above: stability
        # is not portable across sharded sorts, the index operand is
        corder = jnp.lexsort(
            (jnp.arange(P, dtype=I32),
             jnp.where(to_client, pool.due, INT32_MAX)))[:CC]
        client_msgs = pool.at_rows(corder).replace(valid=to_client[corder])
        # corder is a prefix of a permutation: indices are unique and
        # in-bounds, so tell XLA (the scatter is otherwise flagged
        # order-dependent by the static auditor, like `taken` above)
        c_taken = jnp.zeros(P, bool).at[corder].set(client_msgs.valid,
                                                    unique_indices=True)
    else:
        # count-only mode: consume client messages without materializing
        client_msgs = Msgs.empty(0)
        c_taken = to_client

    consumed = taken | dropped | c_taken | to_down
    pool = pool.replace(valid=pool.valid & ~consumed)

    n_node_recv = jnp.sum(taken.astype(I32))
    n_client_recv = jnp.sum(c_taken.astype(I32))
    server_recv = jnp.sum((taken & ~client_msg).astype(I32))
    st = net.stats
    st = st.replace(
        recv_all=st.recv_all + n_node_recv + n_client_recv,
        recv_servers=st.recv_servers + server_recv,
        dropped_partition=st.dropped_partition
        + jnp.sum(dropped.astype(I32)),
        dropped_down=st.dropped_down + jnp.sum(to_down.astype(I32)))
    if cfg.unit_words:
        st = st.replace(recv_units=st.recv_units + payload_units(
            cfg, pool.type, (pool.a, pool.b, pool.c),
            taken | c_taken))
    return net.replace(pool=pool, stats=st), inbox, client_msgs


# Jitted entry points: cfg is static (hashable frozen dataclass), so each
# (cfg, shapes) signature compiles exactly once. In this environment every
# XLA compile costs ~1 s, so eager op-by-op execution is unusable; these
# wrappers also compose freely under an outer jit/scan (inlined, no cost).
send = jax.jit(_send, static_argnums=0)
deliver = jax.jit(_deliver, static_argnums=0)


def advance(net: NetState) -> NetState:
    return net.replace(round=net.round + 1)


# --- fault API (host-side state surgery; reference net.clj:104-121) ---

def partition_components(net: NetState, labels) -> NetState:
    """Install partition component labels (i32 per node; clients exempt).
    The nemesis computes labels host-side (e.g. majority/minority split)."""
    labels = jnp.asarray(labels, I32)
    comp = net.component.at[: labels.shape[0]].set(labels)
    return net.replace(component=comp)


def partition_grudge(net: NetState, groups, matrix) -> NetState:
    """Install a directional grudge: `groups` is an i32 group label per
    node (clients keep group 0; they are exempt at delivery anyway) and
    `matrix[g_src, g_dest]` = True blocks src->dest traffic. Expresses
    every grudge shape — one-way links, bridge, majorities-ring — that
    component labels cannot. Requires cfg.partition_groups >= the label
    count (the matrix shape is static)."""
    groups = jnp.asarray(groups, I32)
    matrix = jnp.asarray(matrix, bool)
    if matrix.shape != net.block_matrix.shape:
        raise ValueError(
            f"grudge matrix shape {matrix.shape} != configured "
            f"{net.block_matrix.shape}; set NetConfig.partition_groups")
    g2 = net.block_groups.at[: groups.shape[0]].set(groups)
    return net.replace(block_groups=g2, block_matrix=matrix)


def set_down(net: NetState, mask) -> NetState:
    """Mark nodes crash-killed: they stop stepping, their in-flight mail
    is consumed and dropped at delivery. Requires cfg.enable_stall."""
    return net.replace(down=jnp.asarray(mask, bool))


def set_paused(net: NetState, mask) -> NetState:
    """Mark nodes paused: they stop stepping but keep state; pool mail
    waits for them. Requires cfg.enable_stall."""
    return net.replace(paused=jnp.asarray(mask, bool))


def set_duplication(net: NetState, p: float) -> NetState:
    """At-least-once amplification probability (server-to-server only).
    Requires cfg.enable_duplication for the draw to be compiled in."""
    return net.replace(p_dup=jnp.full_like(net.p_dup, p))


def heal(net: NetState) -> NetState:
    """Clears partitions — both component labels and directional grudge
    state. Kill/pause/duplicate heal through their own stop ops."""
    return net.replace(component=jnp.zeros_like(net.component),
                       block_groups=jnp.zeros_like(net.block_groups),
                       block_matrix=jnp.zeros_like(net.block_matrix))


def slow(net: NetState, factor: float = 10.0) -> NetState:
    return net.replace(latency_scale=net.latency_scale * factor)


def fast(net: NetState) -> NetState:
    return net.replace(latency_scale=jnp.ones_like(net.latency_scale))


def flaky(net: NetState, p: float = 0.5) -> NetState:
    return net.replace(p_loss=jnp.full_like(net.p_loss, p))


def set_latency_scale(net: NetState, scale: float) -> NetState:
    """Absolute latency-scale install (slow!/fast! are multiplicative;
    the weather nemesis and --latency-scale need idempotent installs)."""
    return net.replace(
        latency_scale=jnp.full_like(net.latency_scale, scale))


def set_weather(net: NetState, p_loss: float, scale: float) -> NetState:
    """One weather-front install: loss probability + latency scale in a
    single surgery (the `weather` nemesis package; stop-weather restores
    the run's baseline values through the same call)."""
    return net.replace(p_loss=jnp.full_like(net.p_loss, p_loss),
                       latency_scale=jnp.full_like(net.latency_scale,
                                                   scale))


def stats_dict(net: NetState, transfer=None) -> dict:
    """Pull the on-device counters to host, in the shape the net-stats
    checker reports (`net/checker.clj:43-70`). On a cluster-batched net
    (leading cluster axis from `parallel.make_cluster_sims`) each
    counter is summed over the fleet. `sent_by_type` becomes a
    {type-code: count} map of the nonzero buckets.

    This is itself a host drain of the device-resident stats ring;
    passing a `TransferStats` as `transfer` books it like every other
    drain, so the counters it reports include their own extraction."""
    import dataclasses

    import numpy as np
    if transfer is not None:
        transfer.record(net.stats)
    st = jax.device_get(net.stats)
    out = {}
    for f in dataclasses.fields(st):
        a = np.asarray(getattr(st, f.name))
        if f.name == "sent_by_type":
            per_type = a.reshape(-1, TYPE_BUCKETS).sum(axis=0)
            out[f.name] = {int(t): int(c) for t, c in
                           enumerate(per_type) if c}
        else:
            out[f.name] = int(a.sum())
    return out
