"""Network simulation: host path (threads + queues, reference semantics)
and TPU path (batched mailbox arrays, `maelstrom_tpu.net.tpu`)."""
