"""Static edge channels: the sort-free fast path for topology traffic.

The flight pool (`net/tpu.py`) is fully general — any node can message any
node — but pays an argsort over the pool every round to group deliveries.
For the traffic that dominates real workloads (gossip between *fixed*
neighbors, quorum traffic inside a *fixed* cluster), the communication
pattern is static, so delivery is a precomputed permutation: message lane
j from node n to its d-th neighbor always lands in the same inbox slot of
that neighbor (its reverse-edge index). One `take_along_axis` gather moves
every in-flight edge message one hop — no sort, no scatter, pure HBM
bandwidth. This is the discrete-event analogue of a halo exchange.

Latency is a small ring of per-edge cells indexed by arrival round; a
message sent at round r with latency L lands in cell (r+max(L,1)) %
ring_depth and is read (and cleared) when the receiver's round pointer
passes it.
Randomized latencies are supported up to ring_depth-1 rounds (clipped,
counted, and gated by the net-stats checker unless tolerated). Two
messages on the same (edge, arrival-round) cell can collide; what
happens depends on the write mode:

  - default (`spill=False`): a collision on the same lane overwrites —
    bounded-channel loss, counted, absent entirely under constant
    latency. Programs whose lanes carry positional meaning (raft:
    lane 0 = request, 1 = reply, 2 = proxy) use this mode and tolerate
    overwrites because every message retransmits until acknowledged.
  - `spill=True`: the cell is repacked — existing messages keep
    flowing, colliding writes probe free lanes of the same cell, and a
    message is destroyed only when the whole cell is full (counted in
    `overwrites`, gated). This matches the reference's guarantee that
    the network never destroys a message except by explicit loss or
    partition (`net.clj:188-246`, unbounded per-node queues), at the
    cost that a message may be delivered on a different lane than it
    was sent on — legal only for programs that dispatch on message
    *type* across all inbox lanes (`NodeProgram.edge_lanes_symmetric`).

Loss and partitions are masks applied at write time: a lost or blocked
message never enters the ring (the reference consumes blocked messages at
receive, `net.clj:233`; for edge traffic the observable behavior — message
vanishes, counted — is identical, the journal counter just attributes it
at send).

Edge messages carry (type, a, b, c); src/dest are implicit in the edge.
Message-id accounting for the net-stats checker is by count (ids are
globally unique by construction in the pool path; edge sends are counted
into the same counters).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from .tpu import I32

__all__ = ["EdgeMsgs", "EdgeChannels", "EdgeConfig", "make_channels",
           "reverse_index", "edge_write", "edge_read"]


@struct.dataclass
class EdgeMsgs:
    """Per-edge message lanes: fields shaped [N, D, LANES]."""
    valid: jnp.ndarray
    type: jnp.ndarray
    a: jnp.ndarray
    b: jnp.ndarray
    c: jnp.ndarray
    # send round of each delivered message, present only when the
    # channels track it (journaled runs): the journal pairs every recv
    # row to its exact send row even under randomized latency draws
    sent: object = None

    @classmethod
    def empty(cls, shape) -> "EdgeMsgs":
        z = jnp.zeros(shape, I32)
        return cls(valid=jnp.zeros(shape, bool), type=z, a=z, b=z, c=z)


@struct.dataclass
class EdgeChannels:
    """In-flight edge messages: fields shaped [N, D, ring, LANES],
    indexed by arrival round % ring."""
    valid: jnp.ndarray
    type: jnp.ndarray
    a: jnp.ndarray
    b: jnp.ndarray
    c: jnp.ndarray
    overwrites: jnp.ndarray     # i32 scalar: messages destroyed by
    #                             collision (spill=False) or cell
    #                             exhaustion (spill=True)
    lat_clipped: jnp.ndarray    # i32 scalar: latency draws clipped to ring
    # [N, D, ring, LANES] packed round * LANE_STRIDE + original send
    # lane, opt-in (journaled runs): the journal reconstructs each
    # message's send-side id even when spill moved it to another lane
    sent: object = None


# send-lane field width in the packed `sent` plane (lanes < 64 always;
# rounds stay well under 2**25 so the pack fits i32)
LANE_STRIDE = 64


@dataclass(frozen=True)
class EdgeConfig:
    """Static shape of the edge exchange. ring must exceed the maximum
    latency draw in rounds (arrival offsets 1..ring-1 are
    representable; larger draws are clipped and counted).

    `spill` selects the collision-free write (see module docstring); it
    is decided ONCE, by the node program that builds this config — from
    its latency opts, its lane semantics (`edge_lanes_symmetric`), and
    the cluster's memory affordability — so the simulation loop, the
    channels, and the lane headroom can never disagree about the mode."""
    n_nodes: int
    degree: int
    lanes: int
    ring: int = 2
    spill: bool = False
    # constant-latency runs: every message written in one round shares
    # one arrival cell (draws are identical within a round even under a
    # live latency-scale nemesis), so edge_write updates that single
    # dynamically-indexed cell instead of masking every ring slot — at
    # ring 1002 (100 ms hops with slow! headroom) that's the difference
    # between a usable round and a ~1000x write blowup.
    # Contract: the latency_rounds array passed to edge_write must be
    # uniform across ALL entries of a round — exactly what
    # draw_latency_rounds produces for the constant distribution (the
    # slot is read from entry 0, valid or not).
    uniform_arrival: bool = False


def make_channels(cfg: EdgeConfig,
                  track_send_round: bool = False) -> EdgeChannels:
    """`track_send_round` adds a per-cell send-round plane so journal
    recv rows pair exactly to their sends; off by default — the bench
    path pays nothing for it."""
    shape = (cfg.n_nodes, cfg.degree, cfg.ring, cfg.lanes)
    z = jnp.zeros(shape, I32)
    return EdgeChannels(valid=jnp.zeros(shape, bool), type=z, a=z, b=z, c=z,
                        overwrites=jnp.zeros((), I32),
                        lat_clipped=jnp.zeros((), I32),
                        sent=z if track_send_round else None)


def reverse_index(neighbors: np.ndarray) -> np.ndarray:
    """rev[n, d] = index e such that neighbors[neighbors[n, d], e] == n
    (the inbox slot this edge occupies at the far end); -1 for missing
    edges. Topologies must be symmetric (all of the reference's are,
    `workload/broadcast.clj:39-177`)."""
    neighbors = np.asarray(neighbors)
    n, deg = neighbors.shape
    rev = np.full((n, deg), -1, dtype=np.int32)
    for i in range(n):
        for d in range(deg):
            m = neighbors[i, d]
            if m < 0:
                continue
            back = np.nonzero(neighbors[m] == i)[0]
            assert back.size, f"topology not symmetric: {i}->{m}"
            rev[i, d] = back[0]
    return rev


def edge_write(cfg: EdgeConfig, ch: EdgeChannels, out: EdgeMsgs,
               round_, latency_rounds, deliver_mask) -> EdgeChannels:
    """Writes this round's outgoing edge messages into the rings.

    latency_rounds: i32 [N, D, LANES_out] per-message delay (>= 0, clipped
    to ring-1); deliver_mask: bool broadcastable to [N, D, LANES_out]
    (False = lost or partitioned, applied at send like `net.clj:213`).

    `cfg.spill` repacks each targeted cell so colliding writes land in
    free lanes instead of overwriting (see module docstring); it also
    allows `out` to have fewer lanes than the channels (headroom lanes
    exist purely as spill capacity)."""
    # deadline = now + latency with a one-round causal floor, matching
    # the pool path (`net/tpu.py _send`) and the reference's wall-clock
    # deadlines (`net.clj:201-204`). Offset ring-1 is safe: the cell it
    # targets was read (and cleared) the previous round.
    L_out = out.valid.shape[2]
    assert L_out <= LANE_STRIDE and cfg.lanes <= LANE_STRIDE
    lat = jnp.maximum(jnp.clip(latency_rounds, 0, cfg.ring - 1), 1)
    arrival = (round_ + lat) % cfg.ring              # [N, D, LANES_out]
    ok = out.valid & deliver_mask
    clipped = jnp.sum((ok & (latency_rounds > cfg.ring - 1)).astype(I32))
    # packed send-side identity for journal pairing (stride, not the
    # lane count: out and channel lane counts may differ under spill)
    sent_val = (jnp.asarray(round_, I32) * LANE_STRIDE
                + jnp.arange(L_out, dtype=I32))

    if cfg.spill:
        return _edge_write_spill(cfg, ch, out, ok, arrival, clipped,
                                 sent_val)
    assert L_out == cfg.lanes, \
        "lane headroom requires spill mode (extra lanes are spill slots)"

    if cfg.uniform_arrival:
        # one shared arrival cell: a single masked dynamic-slice update
        # per field (the general forms pay ring x the passes for slots
        # that can never match under constant latency)
        s0 = arrival.reshape(-1)[0]
        cell_valid = jax.lax.dynamic_index_in_dim(ch.valid, s0, axis=2,
                                                  keepdims=False)
        new_overwrites = jnp.sum((ok & cell_valid).astype(I32))

        def upd(chf, of):
            cell = jax.lax.dynamic_index_in_dim(chf, s0, axis=2,
                                                keepdims=False)
            return chf.at[:, :, s0, :].set(jnp.where(ok, of, cell))

        return ch.replace(
            valid=ch.valid.at[:, :, s0, :].set(cell_valid | ok),
            type=upd(ch.type, out.type), a=upd(ch.a, out.a),
            b=upd(ch.b, out.b), c=upd(ch.c, out.c),
            overwrites=ch.overwrites + new_overwrites,
            lat_clipped=ch.lat_clipped + clipped,
            sent=(None if ch.sent is None
                  else upd(ch.sent, sent_val[None, None, :])))

    if cfg.ring <= 4:
        # tiny rings (constant latency): unrolled per-slot selects beat
        # the broadcast form — no [N, D, ring, L] mask materialization
        # (measured 2.85M vs 1.89M msgs/s on the 100k-node bench)
        new_overwrites = jnp.zeros((), I32)
        for s in range(cfg.ring):
            m = ok & (arrival == s)                  # [N, D, LANES]
            new_overwrites = new_overwrites + jnp.sum(
                (m & ch.valid[:, :, s, :]).astype(I32))

            def upd(chf, of, m=m, s=s):
                return chf.at[:, :, s, :].set(
                    jnp.where(m, of, chf[:, :, s, :]))

            ch = ch.replace(
                valid=ch.valid.at[:, :, s, :].set(ch.valid[:, :, s, :] | m),
                type=upd(ch.type, out.type), a=upd(ch.a, out.a),
                b=upd(ch.b, out.b), c=upd(ch.c, out.c),
                sent=(None if ch.sent is None
                      else upd(ch.sent, sent_val[None, None, :])))
        return ch.replace(overwrites=ch.overwrites + new_overwrites,
                          lat_clipped=ch.lat_clipped + clipped)

    # large rings (randomized latency: ring ~ 8x mean): one broadcast
    # select over the whole ring — the unrolled loop emitted ring x 5
    # update kernels and dominated the round cost (10-15x slower)
    slots = jnp.arange(cfg.ring, dtype=I32)[None, None, :, None]
    m = ok[:, :, None, :] & (arrival[:, :, None, :] == slots)  # [N,D,R,L]
    new_overwrites = jnp.sum((m & ch.valid).astype(I32))

    def upd(chf, of):
        return jnp.where(m, of[:, :, None, :], chf)

    return ch.replace(
        valid=ch.valid | m,
        type=upd(ch.type, out.type), a=upd(ch.a, out.a),
        b=upd(ch.b, out.b), c=upd(ch.c, out.c),
        overwrites=ch.overwrites + new_overwrites,
        lat_clipped=ch.lat_clipped + clipped,
        sent=(None if ch.sent is None
              else jnp.where(m, sent_val[None, None, None, :], ch.sent)))


def _edge_write_spill(cfg: EdgeConfig, ch: EdgeChannels, out: EdgeMsgs,
                      ok, arrival, clipped, sent_val) -> EdgeChannels:
    """Collision-free write: an incoming message takes the next free
    lane of its arrival cell; existing in-flight messages are never
    disturbed. A message is destroyed only when its cell is already
    full — counted in `overwrites` and gated like any other silent
    drop. Used on randomized-latency runs, where collisions actually
    occur (constant latency cannot collide: all of a round's sends
    share one deadline).

    Cells are valid-PREFIX-packed by construction (this writer appends
    at the occupancy frontier; edge_read clears whole cells), so the
    free lane for each incoming message is just occupancy + its rank
    among this round's same-cell messages — a handful of O(Lo^2)
    comparisons and one scatter per field. NOTE: the scatter must NOT
    promise unique_indices — parked (dropped) entries share the
    out-of-bounds cell R, and duplicate indices under that promise are
    undefined behavior. The previous form stable-sorted the ENTIRE
    [N, D, ring, Lc+Lo] ring every round to repack <= Lo touched
    cells; at ring ~242 that sort was ~70x the cost of the whole
    remaining round body on CPU. Delivery equivalence (as a multiset —
    lane positions are not part of the contract) is pinned by
    tests/test_edge_oracle.py's spill property test."""
    N, D, R, Lc = ch.valid.shape
    Lo = out.valid.shape[2]
    occ = jnp.sum(ch.valid.astype(I32), axis=3)          # [N, D, R]
    cell = jnp.where(ok, arrival, R)                     # R = parked
    # rank[l] = #{j < l : ok_j and cell_j == cell_l}
    jl = jnp.arange(Lo, dtype=I32)
    lower = jl[None, :] < jl[:, None]                    # [l, j]
    same = (cell[:, :, None, :] == cell[:, :, :, None])  # [N, D, l, j]
    rank = jnp.sum(same & lower[None, None]
                   & ok[:, :, None, :], axis=3)          # [N, D, Lo]
    occ_at = jnp.take_along_axis(occ, jnp.clip(cell, 0, R - 1), axis=2)
    lane = occ_at + rank
    write = ok & (lane < Lc)
    dropped = jnp.sum((ok & (lane >= Lc)).astype(I32))
    nn = jnp.arange(N, dtype=I32)[:, None, None]
    dd = jnp.arange(D, dtype=I32)[None, :, None]
    c_idx = jnp.where(write, cell, R)        # out of bounds -> dropped
    l_idx = jnp.clip(lane, 0, Lc - 1)

    # no unique_indices promise: parked (dropped) entries share the
    # out-of-bounds cell R, and written targets are unique anyway
    def put(chf, of):
        return chf.at[nn, dd, c_idx, l_idx].set(of, mode="drop")

    return ch.replace(
        valid=ch.valid.at[nn, dd, c_idx, l_idx].set(True, mode="drop"),
        type=put(ch.type, out.type), a=put(ch.a, out.a),
        b=put(ch.b, out.b), c=put(ch.c, out.c),
        overwrites=ch.overwrites + dropped,
        lat_clipped=ch.lat_clipped + clipped,
        sent=None if ch.sent is None else put(
            ch.sent, jnp.broadcast_to(sent_val[None, None, :],
                                      ok.shape)))


def edge_read(cfg: EdgeConfig, ch: EdgeChannels, neighbors, rev,
              round_) -> tuple[EdgeChannels, EdgeMsgs]:
    """Reads (and clears) the cell arriving this round, routed to the
    receiving end of each edge: in_[m, e] = ring cell of (nb[m,e], rev[m,e]).
    Returns (channels', inbox) with inbox shaped [N, D, LANES]; inbox slot
    (m, e) holds what m's e-th neighbor sent it."""
    s = round_ % cfg.ring
    safe_nb = jnp.clip(neighbors, 0, cfg.n_nodes - 1)
    safe_rev = jnp.clip(rev, 0, cfg.degree - 1)
    edge_ok = (neighbors >= 0)
    N, D, L = cfg.n_nodes, cfg.degree, cfg.lanes
    # the routing is a fixed permutation of flat (node, edge) pairs; a
    # row-take over that flat axis lowers to a vectorized gather, where
    # the naive f[nb, rev, s, :] advanced-indexing form lowered to a
    # near-scalar gather (measured 9.7 ms vs 2.7 ms per round for the
    # 100k-node bench shapes)
    flat = (safe_nb * D + safe_rev).reshape(N * D)

    if cfg.ring <= 4:
        # slice the arrival cell first (one [N, D, L] dynamic slice),
        # then route with one flat row-take — the 100k-node bench's
        # fast path (2.85M -> 4.1M msgs/s)
        def route(f):
            sl = jax.lax.dynamic_index_in_dim(f, s, axis=2,
                                              keepdims=False)
            return jnp.take(sl.reshape(N * D, L), flat,
                            axis=0).reshape(N, D, L)
    else:
        # deep rings (randomized/100 ms-latency configs, ring ~1000):
        # keep the advanced-indexing form — small clusters where the
        # gather is cheap, and the slice-first form's dynamic slice of
        # a deep ring proved compile-hostile on the remote TPU backend
        def route(f):
            return f[safe_nb, safe_rev, s, :]

    inbox = EdgeMsgs(
        valid=route(ch.valid) & edge_ok[:, :, None],
        type=route(ch.type), a=route(ch.a), b=route(ch.b), c=route(ch.c),
        sent=None if ch.sent is None else route(ch.sent))
    # clear the consumed cell
    ch = ch.replace(valid=ch.valid.at[:, :, s, :].set(False))
    return ch, inbox
