"""The network journal: a log of every send/recv event.

The journal is the framework's tracing system (reference
`src/maelstrom/net/journal.clj`): every send and receive is recorded as an
Event `(id, time, type, message)` and folded at analysis time into
send/recv/unique-message statistics split across all/clients/servers
(reference `net/checker.clj:28-41`), plus msgs-per-op.

Two ingestion paths:
  - host path: `log_send`/`log_recv` record one Event per call (thread-safe),
    retaining bodies (needed for Lamport diagrams).
  - TPU path: `log_batch` accepts numpy arrays straight off the device —
    thousands of events per call, no per-message Python cost. Bodies stay on
    the device side; only (id, time, type, src_idx, dest_idx) land here.

Events are spilled to `net-journal/` in the store dir as jsonl (host events)
and .npz chunks (batched events).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..util import is_client

SEND = "send"
RECV = "recv"


@dataclass
class Event:
    id: int
    time: int           # linear-time nanoseconds
    type: str           # send | recv
    src: str
    dest: str
    body: Optional[dict] = None


class Journal:
    def __init__(self, dir: str | None = None, retain_bodies: bool = True):
        self.dir = dir
        self.retain_bodies = retain_bodies
        self.events: list[Event] = []
        self.chunks: list[dict] = []    # batched numpy event chunks
        self.host_bytes = 0             # bytes ingested via log_batch
        self.lock = threading.Lock()
        self.closed = False

    # --- host path (reference journal.clj:225-239) ---

    def log_send(self, message, time_ns: int):
        self._log(SEND, message, time_ns)

    def log_recv(self, message, time_ns: int):
        self._log(RECV, message, time_ns)

    def _log(self, type: str, message, time_ns: int):
        e = Event(id=message.id, time=time_ns, type=type, src=message.src,
                  dest=message.dest,
                  body=message.body if self.retain_bodies else None)
        with self.lock:
            self.events.append(e)

    # --- TPU path ---

    def log_batch(self, type: str, ids, times, srcs, dests, node_names=None):
        """Record a batch of events from device arrays. srcs/dests are node
        *indices*; node_names maps index -> node id string (kept per-chunk so
        stats can classify client vs server traffic)."""
        ids = np.asarray(ids)
        if ids.size == 0:
            return
        chunk = {"type": type,
                 "ids": ids.astype(np.int64),
                 "times": np.asarray(times).astype(np.int64),
                 "srcs": np.asarray(srcs).astype(np.int32),
                 "dests": np.asarray(dests).astype(np.int32),
                 "node_names": node_names}
        with self.lock:
            self.chunks.append(chunk)
            self.host_bytes += sum(
                int(chunk[k].nbytes)
                for k in ("ids", "times", "srcs", "dests"))

    # --- folds (reference journal.clj:305-347, net/checker.clj:28-41) ---

    def stats(self, op_count: int | None = None) -> dict:
        """send/recv/unique-message counts for all/clients/servers, plus
        msgs-per-op when op_count is given."""
        groups = {"all": lambda c: True,
                  "clients": lambda c: c,
                  "servers": lambda c: not c}
        counts = {g: {"send-count": 0, "recv-count": 0} for g in groups}
        ids = {g: set() for g in groups}

        with self.lock:
            events = list(self.events)
            chunks = list(self.chunks)

        for e in events:
            involves_client = is_client(e.src) or is_client(e.dest)
            for g, pred in groups.items():
                if pred(involves_client):
                    counts[g][f"{e.type}-count"] += 1
                    ids[g].add(e.id)

        # Batched chunks: vectorized classification
        for ch in chunks:
            names = ch["node_names"]
            if names is not None:
                client_mask = np.array([is_client(n) for n in names])
                involves = (client_mask[ch["srcs"]]
                            | client_mask[ch["dests"]])
            else:
                involves = np.zeros(len(ch["ids"]), dtype=bool)
            key = f"{ch['type']}-count"
            for g, sel in (("all", np.ones_like(involves)),
                           ("clients", involves),
                           ("servers", ~involves)):
                n = int(sel.sum())
                counts[g][key] += n
                if n:
                    ids[g].update(ch["ids"][sel].tolist())

        out = {}
        for g in groups:
            out[g] = {**counts[g], "msg-count": len(ids[g])}
        if op_count:
            out["all"]["msgs-per-op"] = out["all"]["msg-count"] / op_count
            out["servers"]["msgs-per-op"] = (
                out["servers"]["msg-count"] / op_count)
        return out

    def all_events(self) -> list[Event]:
        """Materializes every event (host + batched) sorted by time. Used by
        the Lamport diagram plotter; beware on huge runs (viz caps itself at
        10k events, reference `net/viz.clj:13-16`)."""
        with self.lock:
            events = list(self.events)
            chunks = list(self.chunks)
        for ch in chunks:
            names = ch["node_names"]
            for i in range(len(ch["ids"])):
                src = names[ch["srcs"][i]] if names is not None else str(
                    ch["srcs"][i])
                dest = names[ch["dests"][i]] if names is not None else str(
                    ch["dests"][i])
                events.append(Event(id=int(ch["ids"][i]),
                                    time=int(ch["times"][i]),
                                    type=ch["type"], src=src, dest=dest))
        events.sort(key=lambda e: (e.time, e.id))
        return events

    def counts(self) -> dict:
        with self.lock:
            n_host = len(self.events)
            n_batch = sum(len(c["ids"]) for c in self.chunks)
            host_bytes = self.host_bytes
        return {"host-events": n_host, "batched-events": n_batch,
                "total": n_host + n_batch, "host-bytes": host_bytes}

    # --- persistence (reference journal.clj:183-223 writes stripes) ---

    def close(self):
        if self.closed or not self.dir:
            self.closed = True
            return
        os.makedirs(self.dir, exist_ok=True)
        with self.lock:
            with open(os.path.join(self.dir, "events.jsonl"), "w") as f:
                for e in self.events:
                    f.write(json.dumps(
                        {"id": e.id, "time": e.time, "type": e.type,
                         "src": e.src, "dest": e.dest, "body": e.body},
                        default=str) + "\n")
            for i, ch in enumerate(self.chunks):
                np.savez_compressed(
                    os.path.join(self.dir, f"chunk-{i:06d}.npz"),
                    type=ch["type"], ids=ch["ids"], times=ch["times"],
                    srcs=ch["srcs"], dests=ch["dests"],
                    node_names=np.array(ch["node_names"] or [], dtype=object))
        self.closed = True

    @classmethod
    def load(cls, dir: str) -> "Journal":
        j = cls(dir=dir)
        path = os.path.join(dir, "events.jsonl")
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    d = json.loads(line)
                    j.events.append(Event(**d))
        for name in sorted(os.listdir(dir)):
            if name.startswith("chunk-") and name.endswith(".npz"):
                z = np.load(os.path.join(dir, name), allow_pickle=True)
                j.chunks.append({
                    "type": str(z["type"]), "ids": z["ids"],
                    "times": z["times"], "srcs": z["srcs"],
                    "dests": z["dests"],
                    "node_names": list(z["node_names"]) or None})
        return j
