"""The ordering-engine adapters: `OrderedStream` over the three
existing ordering machines.

Each adapter subclasses the engine's UNCHANGED node program — the
device half (init_state/step/edge_step, durability, quiescence, fault
groups) is the welded program verbatim, so there are no new compiled
entry points and the legacy paths stay byte-identical — and swaps the
HOST boundary for the stream contract (`StreamBoundary`): propose an
opaque interned command id, learn its stream position from the reply,
replay the committed prefix through the applier.

Engine-specific surface (implemented per adapter):
  - `propose_words(cid)`: the wire words that carry a proposal;
  - `reply_slot(body)`: the op's stream position from a decoded reply
    (None: not a stream reply; `SCAN_SLOT`: position unknown, find the
    command in the log — the compartment, whose client replies don't
    carry the slot);
  - `ingest(slot, read_state, intern)`: extend the replay frontier
    through `slot` — from replica state for device-log engines, from
    the intern table for the batched engine;
  - `check_capacity(n)`: the engine's command-id space bound.
"""

from __future__ import annotations

import numpy as np

from . import Applier
from ..checkers.set_full import range_checksum
from ..nodes import EncodeCapacityError
from ..nodes.broadcast_batched import (BroadcastBatchedProgram, T_BATCH,
                                       T_BATCH_OK)
from ..nodes.compartment import (CompartmentProgram, OP_WRITE as C_WRITE,
                                 _unpack_cmd)
from ..nodes.raft import OP_TXN, RaftProgram, T_TXN, T_TXN_OK, T_WRITE

# sentinel slot: the engine's reply proves the command applied but not
# where — ingest() must locate it in the replayed log
SCAN_SLOT = -1


class StreamLagError(RuntimeError):
    """The acked stream position is not yet readable from any node's
    state: volatile commit/applied indexes lag the ack, or a kill
    wiped them mid-stretch (raft's `commit` and the compartment's
    `applied` are rebuilt after restart, not durable). The command DID
    enter the stream — the ack proves it — so the op completes
    indeterminate (:info, may-have-happened), never crashes the run;
    a later replay that reaches the slot applies it exactly once."""


class StreamBoundary:
    """The shared host half of the `OrderedStream` contract (see the
    package docstring for the full protocol). Mixes in FIRST, so its
    request/encode/completion override the engine's welded wire
    vocabulary while decode_body (error shapes, redirect hints) stays
    the engine's."""

    def _stream_init(self, applier: Applier):
        self.applier = applier
        self._oseq = 0               # proposal counter (host_state)
        # replay state — reconstructed from the stream on resume,
        # never checkpointed
        self._app_state = applier.init_state()
        self._applied_ids: set = set()     # at-most-once filter
        self._results: dict = {}           # cid -> apply result
        self._frontier = 0                 # slots replayed so far

    # --- propose -------------------------------------------------------

    def request_for_op(self, op):
        if "_oseq" not in op:
            # stamp the proposal identity ON the op: a redirect requeue
            # or retry re-encodes the SAME (seq, cmd) — the same intern
            # id — so one op can never fork into two stream commands
            op["_oseq"] = self._oseq
            self._oseq += 1
            op["_ocmd"] = self.applier.command(op)
        return {"type": "propose", "seq": op["_oseq"],
                "cmd": op["_ocmd"]}

    def encode_body(self, body, intern):
        if body.get("type") != "propose":
            raise ValueError(f"ordered[{self.stream_engine}]: "
                             f"unexpected body {body.get('type')!r}")
        key = ["os", body["seq"], body["cmd"]]
        cid = intern.peek(key)
        if cid is None:
            self.check_capacity(len(intern))
            cid = intern.id(key)
        return self.propose_words(cid)

    # --- replay --------------------------------------------------------

    def _apply_cid(self, cid: int, intern):
        """Applies one delivered command id (at most once)."""
        if cid in self._applied_ids:
            return
        self._applied_ids.add(cid)
        cmd = intern.value(cid)[2]
        self._app_state, res = self.applier.apply(self._app_state, cmd)
        self._results[cid] = res

    def _own_cid(self, op, intern) -> int:
        cid = intern.peek(["os", op["_oseq"], op["_ocmd"]])
        if cid is None:            # encode ran, so the id must exist
            raise RuntimeError("ordered: completed op was never encoded")
        return cid

    def completion(self, op, body, read_state, intern):
        slot = self.reply_slot(body)
        if slot is None:
            # engine acks that carry no stream position (shouldn't
            # happen for stream proposals) complete bare
            return {**op, "type": "ok"}
        cid = self._own_cid(op, intern)
        if cid not in self._results:
            # replay is pure and slot-ordered, so a command already in
            # the replayed prefix needs no fresh state read — this is
            # what keeps SCAN_SLOT engines (the compartment, which
            # copies every replica row per ingest) from rescanning on
            # every completion of an already-covered stretch
            try:
                self.ingest(slot, read_state, intern)
            except StreamLagError as e:
                return {**op, "type": "info", "error": ["stream-lag",
                                                        str(e)]}
        res = self._results.get(cid)
        if res is None:
            if self.ingest_covers_ack:
                # ingest returned having replayed through the acked
                # slot, so a missing command is a REAL invariant break
                # (id packing / replay bug), not replication lag
                raise RuntimeError(
                    f"ordered[{self.stream_engine}]: command {cid} "
                    f"acked at slot {slot} but absent from the "
                    f"replayed prefix")
            # SCAN_SLOT engines replay to the best visible prefix,
            # which a kill can leave short of the ack — same lag class
            return {**op, "type": "info", "error": [
                "stream-lag", f"command {cid} acked but not yet in "
                              f"any readable applied prefix"]}
        return self.applier.completed(op, res)

    def completion_payload(self, op, body, payload, intern):
        # engines with reply payloads (broadcast) route through the
        # same stream completion; the payload itself is unused
        return self.completion(op, body, None, intern)

    # --- checkpointable host state --------------------------------------

    def host_state(self):
        return {"ostream": {"seq": self._oseq,
                            "applier": self.applier.host_view()},
                "engine": super().host_state()}

    def set_host_state(self, st):
        if isinstance(st, dict) and "ostream" in st:
            self._oseq = int(st["ostream"].get("seq", 0))
            self.applier.restore(st["ostream"].get("applier"))
            super().set_host_state(st.get("engine"))
        else:
            super().set_host_state(st)

    # --- engine-specific surface ----------------------------------------

    # True: a successful ingest(slot, ...) has replayed THROUGH the
    # acked slot, so an acked command missing afterwards is a bug.
    # False (SCAN_SLOT engines): ingest replays to the best visible
    # prefix, which replication lag can leave short of the ack.
    ingest_covers_ack = True

    def propose_words(self, cid: int):
        raise NotImplementedError

    def reply_slot(self, body):
        raise NotImplementedError

    def ingest(self, slot, read_state, intern):
        raise NotImplementedError

    def check_capacity(self, n: int):
        raise NotImplementedError


class OrderedRaft(StreamBoundary, RaftProgram):
    """lin-kv's raft serving an arbitrary applier: commands ride the
    log as OP_TXN entries (16-bit interned ids split over the entry's
    v1/v2 bytes), the leader's apply-point reply carries the commit
    position, and the host replays the committed prefix — the
    `nodes/txn_list_append.py` architecture with the interpreter made
    pluggable. Committed entries are immutable and replica-identical,
    so end-of-stretch state reads are exact (`state_reads_final`)."""

    name = "ordered"
    stream_engine = "raft"
    needs_state_reads = True
    state_reads_final = True

    def __init__(self, opts, nodes, applier: Applier):
        RaftProgram.__init__(self, opts, nodes)
        self._stream_init(applier)

    def check_capacity(self, n):
        if n > 0xFFFF:
            raise EncodeCapacityError(
                "ordered[raft] command table full (65536 commands)")

    def propose_words(self, cid):
        return (T_TXN, cid, 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_TXN_OK:
            return {"type": "txn_ok", "position": int(a)}
        return super().decode_body(t, a, b, c, intern)

    def reply_slot(self, body):
        if body.get("type") == "txn_ok":
            return int(body["position"])
        return None

    def ingest(self, slot, read_state, intern):
        if slot < self._frontier:
            return
        # any replica whose commit reached `slot` serves the prefix
        # (the leader's has; committed entries are final everywhere)
        row = None
        for i in range(self.n_nodes):
            cand = read_state(i)
            if int(cand["commit"]) >= slot and int(cand["log_len"]) > slot:
                row = cand
                break
        if row is None:
            # the leader committed `slot` before acking, but commit
            # indexes are volatile: a kill + partition inside this
            # stretch can leave every readable replica behind the ack
            raise StreamLagError(
                f"ordered[raft]: no readable replica's commit covers "
                f"acked slot {slot}")
        log_a = np.asarray(row["log_a"])
        log_b = np.asarray(row["log_b"])
        for s in range(self._frontier, slot + 1):
            if (int(log_a[s]) & 0xF) != OP_TXN:
                continue           # NOOPs / non-stream entries
            cid = (int(log_b[s]) >> 8 & 0xFF) << 8 | (int(log_b[s]) & 0xFF)
            self._apply_cid(cid, intern)
        self._frontier = slot + 1


class OrderedCompartment(StreamBoundary, CompartmentProgram):
    """The compartmentalized slot sequence serving an arbitrary
    applier: commands ride WRITE slots (the interned id packed into
    the 12-bit key x base-255 value fields), flowing sequencer ->
    proxy tier -> acceptor grid -> replicas exactly as the welded
    lin-kv path — elections, failover, leader redirects, and the
    client lease included (`sim.RolePartition` under one jitted
    round). Client replies don't carry the slot, so the completion
    locates its command by scanning the replica's applied prefix
    (every slot <= `applied` is chosen and final — the same
    `state_reads_final` argument as raft's committed log)."""

    name = "ordered"
    stream_engine = "compartment"
    ingest_covers_ack = False       # SCAN_SLOT: replays to best-visible

    def __init__(self, opts, nodes, applier: Applier):
        CompartmentProgram.__init__(self, opts, nodes)
        # RolePartition.__init__ derived these from the client role
        # (False there), but the ordered boundary DOES read device
        # state in completions, and those reads are final (applied
        # slots are chosen) — assert the declaration as instance state
        # so the runner's collect-replies gate sees it. Sound on a
        # multi-role partition because state_row maps global node ids
        # into role subtrees.
        self.needs_state_reads = True
        self.state_reads_final = True
        self._stream_init(applier)
        self._id_cap = self.lay.keys * 255

    def check_capacity(self, n):
        if n >= self._id_cap:
            raise EncodeCapacityError(
                f"ordered[compartment] command table full "
                f"({self._id_cap}; raise kv_keys)")

    def propose_words(self, cid):
        # a WRITE whose (key, value) words carry the id in base 255:
        # the sequencer stores v1 = value + 1 (1..255), replicas apply
        # kv[key] = v1 — inert for the stream, which only reads the
        # slot sequence back
        return (T_WRITE, cid // 255, cid % 255, 0)

    def reply_slot(self, body):
        if body.get("type") == "write_ok":
            return SCAN_SLOT
        return None

    def ingest(self, slot, read_state, intern):
        lay = self.lay
        best, best_app = None, -1
        for j in range(lay.R):
            row = read_state(lay.r_base + j)
            app = int(row["applied"])
            if app > best_app:
                best_app, best = app, row
        if best is None or best_app < 0:
            # an ack exists, so SOME replica applied the command — but
            # kills can wipe every visible `applied` before this read
            raise StreamLagError("ordered[compartment]: no readable "
                                 "replica has applied anything")
        r_cmd = np.asarray(best["r_cmd"])
        for s in range(self._frontier, best_app + 1):
            key, opc, v1, _v2 = _unpack_cmd(int(r_cmd[s]))
            if opc != C_WRITE or v1 == 0:
                continue    # NOOPs / recovered gap fills apply inert
            self._apply_cid(int(key) * 255 + (int(v1) - 1), intern)
        self._frontier = best_app + 1


class OrderedBatched(StreamBoundary, BroadcastBatchedProgram):
    """Chop Chop-style batched atomic broadcast serving an arbitrary
    applier: the host-side distiller's contiguous id assignment IS the
    sequencer (arxiv 2304.07081 puts the ordering authority in the
    batching layer), so a command's stream position is its interned
    id — assigned between invoke and reply, which is what makes
    id-order serialization real-time consistent. The simulated network
    still carries every batch and its expansion-proof ack (faults
    delay acks, never reorder the stream), and replay needs no device
    reads at all: the host interned every command, so the prefix below
    any id is host-known by construction."""

    name = "ordered"
    stream_engine = "batched"
    needs_state_reads = False

    def __init__(self, opts, nodes, applier: Applier):
        opts = dict(opts)
        # the value table must hold one id per client op: scale the
        # default with the offered op count like raft's log cap
        rate = float(opts.get("rate") or 0.0)
        tl = float(opts.get("time_limit") or 0.0)
        opts.setdefault("max_values", int(2 * rate * tl) + 256)
        BroadcastBatchedProgram.__init__(self, opts, nodes)
        self._stream_init(applier)

    def check_capacity(self, n):
        if n >= self.V:
            raise EncodeCapacityError(
                f"ordered[batched] command table full ({self.V}); "
                f"raise --max-values")

    def propose_words(self, cid):
        return (T_BATCH, cid, 1, range_checksum(cid, 1))

    def reply_slot(self, body):
        if body.get("type") == "batch_ok":
            return int(body["lo"])
        return None

    def ingest(self, slot, read_state, intern):
        # stream order is id order and the host knows every command:
        # replay straight off the intern table
        for cid in range(self._frontier, slot + 1):
            self._apply_cid(cid, intern)
        self._frontier = max(self._frontier, slot + 1)


ENGINE_PROGRAMS = {
    "raft": OrderedRaft,
    "compartment": OrderedCompartment,
    "batched": OrderedBatched,
}
