"""OrderedStream: a pluggable ordering layer under every state machine.

"Stream-based State-Machine Replication" (PAPERS.md, arxiv 2106.13019)
decomposes SMR into two independent halves: an *ordered stream* of
opaque commands (consensus/atomic broadcast — the part that needs a
cluster) and a *deterministic applier* replaying that stream (the part
that defines the service). This package makes the split explicit, so
any state machine runs over any ordering engine instead of the
pairwise welds the repo grew one PR at a time:

    engines  (this package's adapters over existing node programs)
      raft          the raft log (`nodes/raft.py`): commands ride
                    OP_TXN entries, the leader's reply carries the
                    commit position — the `TxnRaftProgram` idiom
                    generalized to any applier
      compartment   the compartmentalized consensus slot sequence
                    (`nodes/compartment.py`, arxiv 2012.15762):
                    commands ride WRITE slots through the sequencer /
                    proxy / acceptor-grid / replica tiers (a
                    `sim.RolePartition`), elections and failover
                    included
      batched       Chop Chop-style batched atomic broadcast
                    (`nodes/broadcast_batched.py`, arxiv 2304.07081):
                    the distiller's contiguous id assignment IS the
                    sequencer — id order is the stream order, and the
                    simulated network carries the dissemination +
                    expansion-proof acks

    appliers (`ordering/appliers.py`)
      lin-kv            read/write/cas over `services.PersistentKV` —
                        the PURE reference state machine is the
                        implementation, not just the oracle
      kafka             per-key append-only logs + committed offsets
                        (the classic full-prefix kafka workload)
      txn-list-append   `nodes.txn_list_append.apply_txn`, the
                        micro-op interpreter the welded raft path uses

Selected with `--ordering raft|compartment|batched` next to the
workload's `-w` axis; the generator and the CHECKER come from the
workload untouched, so every (engine x applier) combination is graded
by the stock checkers — linearizable register, kafka, device-resident
Elle — with zero new checker code, and inherits the whole
nemesis/mesh/fleet/continuous/checkpoint machinery.

How a combination executes (the `OrderedStream` contract,
`engines.StreamBoundary`):

  1. propose: every workload op (reads included) becomes one opaque
     command — `[os, seq, cmd]` interned to a dense int32 id through
     the run's intern table. `seq` is a per-run counter stamped ON the
     op at first encode, so a leader-redirect requeue (or a
     duplicate-nemesis re-delivery) re-proposes the SAME id rather
     than forking the command.
  2. order: the engine's unchanged device program sequences the id —
     raft log position, compartment slot, broadcast value id. The
     legacy welded programs are not touched: their per-seed histories
     stay byte-identical (tests/test_ordering.py pins them).
  3. deliver + apply: the host replays the committed prefix through
     the applier IN SLOT ORDER, with an at-most-once filter (a
     command id applies at its first slot only — the classic session
     dedup the welded paths lack), materializing each op's reply
     exactly at its serialization point. Device-log engines
     (raft/compartment) read the prefix off replica state
     (`state_reads_final`: committed entries are immutable); the
     batched engine replays from the intern table itself (the host
     distilled every command, so it knows the whole stream).

Capacity: one command per client op, bounded by the engine's id space
(raft 65536, compartment `kv_keys * 255`, batched `--max-values`);
exhaustion fails the op definitely (`EncodeCapacityError`), never
silently.

See doc/ordering.md for the interface contract, the engine/applier
tables, and the graded combination matrix.
"""

from __future__ import annotations

ENGINES = ("raft", "compartment", "batched")


class Applier:
    """A deterministic state machine over an ordered command stream —
    the workload half of the SMR split. Pure apply, host-side: the
    same class replays identically on every checker re-run, resume,
    or re-ingestion of the device log.

    Contract:
      - `command(op)` -> a JSON-serializable command value for this
        generator op (called ONCE per op; may read host session state,
        e.g. kafka's polled-offset floors — the returned value is
        stamped on the op and never recomputed);
      - `apply(state, cmd)` -> (state', result): PURE — no host
        bookkeeping, no randomness, no mutation of `state`;
      - `completed(op, result)` -> the completed history op (may
        update host session state: this is the op's single completion
        point);
      - `host_view()` / `restore(view)`: picklable session state for
        checkpoints (polled floors etc.); replay caches themselves are
        reconstructed from the stream, never checkpointed."""

    name = "abstract"

    def __init__(self, opts: dict):
        self.opts = opts

    def init_state(self):
        raise NotImplementedError

    def command(self, op: dict):
        raise NotImplementedError

    def apply(self, state, cmd):
        raise NotImplementedError

    def completed(self, op: dict, result) -> dict:
        raise NotImplementedError

    # --- checkpointable host session state (None = stateless) ---

    def host_view(self):
        return None

    def restore(self, view):
        pass


def fail_completion(op: dict, code: int, text: str = "") -> dict:
    """An applier-level error result -> the completed history op,
    mapped through the error registry exactly like a wire error
    (`runner.tpu_runner._apply_reply`): definite codes fail, unknown
    codes stay indeterminate."""
    from ..errors import ERROR_REGISTRY
    err = ERROR_REGISTRY.get(code)
    definite = err.definite if err else False
    return {**op, "type": "fail" if definite else "info",
            "error": [err.name if err else code, text]}


def get_applier(workload: str, opts: dict) -> Applier:
    from .appliers import APPLIERS
    cls = APPLIERS.get(workload)
    if cls is None:
        raise ValueError(
            f"--ordering: no applier serves workload {workload!r}; "
            f"have {sorted(APPLIERS)}")
    return cls(opts)


def make_ordered(opts: dict, nodes: list):
    """`--node tpu:ordered` (set by the --ordering axis): composes the
    engine adapter named by opts['ordering'] with the applier serving
    opts['workload']."""
    from .engines import ENGINE_PROGRAMS
    engine = str(opts.get("ordering") or "raft")
    cls = ENGINE_PROGRAMS.get(engine)
    if cls is None:
        raise ValueError(f"--ordering {engine!r}: expected one of "
                         f"{list(ENGINES)}")
    applier = get_applier(str(opts.get("workload") or "lin-kv"), opts)
    return cls(opts, nodes, applier)


def ordered_node_count(opts: dict) -> int | None:
    """Node count the composed program derives from its engine spec
    (`core.parse_nodes`): the compartment engine sizes the cluster
    from --roles; raft/batched leave the count to the user."""
    if str(opts.get("ordering") or "raft") == "compartment":
        from ..nodes.compartment import roles_node_count
        return roles_node_count(opts.get("roles"))
    return None
