"""The built-in appliers: deterministic state machines extracted from
the welded lin-kv / kafka / txn-list-append paths.

Each is a pure replay machine (`Applier`): the ordering engine decides
WHERE in the stream a command sits, the applier decides WHAT it means.
The lin-kv applier IS `services.PersistentKV` — the reference's pure
state machine (`service.clj:31-56`) serves as both implementation and
oracle, so the ordered path cannot drift from the semantics the welded
raft/compartment appliers are tested against. The txn applier reuses
`nodes.txn_list_append.apply_txn` (the interpreter the welded raft
path replays through) unchanged. The kafka applier replays the classic
full-prefix workload — per-key append-only logs, full-observation
polls, monotone committed offsets — the shapes
`checkers/kafka.py.grade` audits.

Because every command is replayed at ONE stream position with an
at-most-once filter upstream (`engines.StreamBoundary`), appliers need
no idempotence tricks: `apply` sees each op exactly once, in order.
"""

from __future__ import annotations

from types import SimpleNamespace

from . import Applier, fail_completion
from ..nodes.txn_list_append import apply_txn
from ..services import PersistentKV

# the workloads module defines KV error codes 20/21/22 at import time;
# the applier surfaces the same codes, so the registry must be loaded
from ..workloads import lin_kv as _lin_kv_errors  # noqa: F401


class LinKVApplier(Applier):
    """read/write/cas over the PURE reference KV machine
    (`services.PersistentKV`): values are arbitrary JSON — the ordered
    path has no wire-packing range limits (the welded raft/compartment
    programs cap register values at 254)."""

    name = "lin-kv"

    def init_state(self):
        return PersistentKV()

    def command(self, op):
        k, v = op["value"]
        if op["f"] == "read":
            return {"type": "read", "key": k}
        if op["f"] == "write":
            return {"type": "write", "key": k, "value": v}
        return {"type": "cas", "key": k, "from": v[0], "to": v[1]}

    def apply(self, state, cmd):
        return state.handle(SimpleNamespace(body=dict(cmd)))

    def completed(self, op, result):
        if result.get("type") == "error":
            return fail_completion(op, int(result.get("code", -1)),
                                   result.get("text", ""))
        if op["f"] == "read":
            return {**op, "type": "ok",
                    "value": [op["value"][0], result["value"]]}
        return {**op, "type": "ok"}


class KafkaApplier(Applier):
    """The classic full-prefix kafka workload as a replay machine:
    sends append to per-key logs (the result is the assigned offset),
    polls observe every key's full prefix, commits raise monotone
    per-key floors, lists read them back. Commit claims are fixed at
    COMMAND time from the session's polled floors (like the welded
    program's `_host_polled`), so replay is deterministic and the
    claim provably covers only what this run actually polled."""

    name = "kafka"

    def __init__(self, opts):
        super().__init__(opts)
        self._polled: dict = {}    # str(key) -> max polled offset

    def init_state(self):
        return {"logs": {}, "committed": {}}

    def command(self, op):
        f = op["f"]
        if f == "send":
            k, m = op["value"]
            return ["send", int(k), m]
        if f == "poll":
            return ["poll"]
        if f == "commit":
            return ["commit", dict(self._polled)]
        return ["list"]

    def apply(self, state, cmd):
        tag = cmd[0]
        if tag == "send":
            _t, k, m = cmd
            logs = dict(state["logs"])
            cur = list(logs.get(str(k), ()))
            cur.append(m)
            logs[str(k)] = cur
            return {**state, "logs": logs}, ["send_ok", len(cur) - 1]
        if tag == "poll":
            msgs = {k: [[o, m] for o, m in enumerate(log)]
                    for k, log in state["logs"].items() if log}
            return state, ["poll_ok", msgs]
        if tag == "commit":
            offs = {str(k): int(v) for k, v in cmd[1].items()}
            comm = dict(state["committed"])
            for k, v in offs.items():
                comm[k] = max(comm.get(k, -1), v)
            return {**state, "committed": comm}, ["commit_ok", offs]
        return state, ["list_ok", dict(state["committed"])]

    def completed(self, op, result):
        tag = result[0]
        if tag == "send_ok":
            k, m = op["value"]
            return {**op, "type": "ok", "value": [str(k), m, result[1]]}
        if tag == "poll_ok":
            msgs = result[1]
            for k, pairs in msgs.items():
                if pairs:
                    self._polled[k] = max(self._polled.get(k, -1),
                                          pairs[-1][0])
            return {**op, "type": "ok", "value": msgs}
        return {**op, "type": "ok", "value": result[1]}

    def host_view(self):
        return {"polled": dict(self._polled)}

    def restore(self, view):
        self._polled = dict((view or {}).get("polled") or {})


class TxnListAppendApplier(Applier):
    """Transactional list-append: the welded raft path's micro-op
    interpreter (`apply_txn`) over a persistent dict — reads observe
    the prefix state, appends extend it, graded by the device-resident
    Elle checker under strict serializability."""

    name = "txn-list-append"

    def init_state(self):
        return {}

    def command(self, op):
        return ["txn", op["value"]]

    def apply(self, state, cmd):
        return apply_txn(state, cmd[1])

    def completed(self, op, result):
        return {**op, "type": "ok", "value": result}


APPLIERS = {
    "lin-kv": LinKVApplier,
    "lin-mutex": LinKVApplier,      # lin-mutex rides the lin-kv RPCs
    "kafka": KafkaApplier,
    "txn-list-append": TxnListAppendApplier,
}
