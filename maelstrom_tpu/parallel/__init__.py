"""Scale-out: cluster-axis vmap and device-mesh sharding.

The reference runs one JVM simulation at a time (SURVEY.md section 2.4);
the TPU framework scales along two axes instead:

  - **cluster axis (dp)**: many independent simulated clusters advance in
    lockstep under `vmap` — the "10k independent 5-node raft clusters"
    configuration in BASELINE.json. Pure data parallelism: no cross-cluster
    communication ever.
  - **node axis (sp)**: one big cluster's node/pool arrays sharded across
    chips, the sequence-parallel analogue (SURVEY.md section 5.7-5.8).
    Cross-shard message delivery rides XLA-inserted collectives (GSPMD):
    the round function is jitted with NamedShardings and the compiler
    partitions the scatter/sort/gather plumbing over ICI.

`mesh_for` builds the ("dp", "sp") mesh; `sim_shardings` annotates a
(batched) SimState pytree; `make_cluster_*` build the vmapped entry points.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..net import tpu as T
from ..sim import SimState, _round, make_sim


_dist_initialized = False

# Environment markers that mean "this process is part of a multi-host
# cluster": an explicit coordinator, or a Cloud TPU pod slice (where
# jax.distributed.initialize auto-detects everything from TPU metadata).
_CLUSTER_ENV_MARKERS = ("JAX_COORDINATOR_ADDRESS",
                        "MEGASCALE_COORDINATOR_ADDRESS",
                        "TPU_WORKER_HOSTNAMES")


def multihost_mesh(coordinator_address: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None,
                   dp: int | None = None) -> Mesh:
    """Multi-host scale-out over DCN (SURVEY.md section 5.8): initializes
    `jax.distributed` so every host sees the global device set, then
    builds the ("dp", "sp") mesh over ALL devices. Within a host's slice
    the sharded round's collectives ride ICI; across hosts XLA routes
    them over DCN — no application code changes, the same
    `make_cluster_round_fn(..., mesh=...)` call scales out.

    Distributed setup runs when a coordinator is passed explicitly or a
    cluster environment marker is present (JAX_COORDINATOR_ADDRESS,
    MEGASCALE_COORDINATOR_ADDRESS, or a Cloud TPU pod's
    TPU_WORKER_HOSTNAMES — on pods `jax.distributed.initialize`
    auto-detects everything, so the arguments can stay None). Without
    either, this is simply `mesh_for()` over local devices.

    Call this before any other JAX API: `jax.distributed.initialize`
    must run before the XLA backend comes up (this function deliberately
    avoids touching the backend itself before initializing)."""
    import os
    global _dist_initialized
    want_dist = (coordinator_address is not None
                 or any(os.environ.get(k) for k in _CLUSTER_ENV_MARKERS))
    if want_dist and not _dist_initialized:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        _dist_initialized = True
    return mesh_for(dp=dp)


def mesh_for(n_devices: int | None = None, dp: int | None = None) -> Mesh:
    """A ("dp", "sp") mesh over the first n_devices. dp defaults to the
    largest power-of-two divisor <= sqrt(n)."""
    devs = jax.devices()[: (n_devices or len(jax.devices()))]
    n = len(devs)
    if dp is None:
        dp = 1
        while dp * 2 * dp * 2 <= n and n % (dp * 2) == 0:
            dp *= 2
    sp = n // dp
    assert dp * sp == n, (dp, sp, n)
    return Mesh(np.asarray(devs).reshape(dp, sp), ("dp", "sp"))


def mesh_from_spec(spec) -> Mesh:
    """The production runner's `--mesh dp,sp` parser: "2,4" (or a
    (2, 4) tuple) -> a ("dp", "sp") Mesh over the first dp*sp devices.
    The dp axis carries the cluster/data-parallel dimension (a single
    interactive cluster simply replicates over it); sp shards the big
    per-cluster axes (nodes, pool, channels, durable store)."""
    if isinstance(spec, Mesh):
        return spec
    if isinstance(spec, str):
        parts = [p for p in spec.replace("x", ",").split(",") if p.strip()]
        try:
            dims = tuple(int(p) for p in parts)
        except ValueError:
            raise ValueError(f"--mesh expects 'dp,sp' integers, got "
                             f"{spec!r}") from None
    else:
        dims = tuple(int(p) for p in spec)
    if len(dims) != 2 or min(dims) < 1:
        raise ValueError(f"--mesh expects two positive axes 'dp,sp', "
                         f"got {spec!r}")
    dp, sp = dims
    n_avail = len(jax.devices())
    if dp * sp > n_avail:
        raise ValueError(
            f"--mesh {dp},{sp} needs {dp * sp} devices but only "
            f"{n_avail} are visible (on CPU, set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={dp * sp})")
    return mesh_for(dp * sp, dp=dp)


def scan_shardings(mesh: Mesh, sim: SimState, inject) -> tuple:
    """The `(sim, inject, scalar)` sharding triple `sim.make_scan_fn` /
    `make_round_fn` take as `shardings=`: the (unbatched, single-cluster)
    SimState tree sharded over sp, the inject batch and scalars
    replicated. Used by the production runner's `--mesh` mode."""
    scalar = NamedSharding(mesh, P())
    return (sim_shardings(mesh, sim, batched=False),
            sim_shardings(mesh, inject, batched=False),
            scalar)


def _spec_for(arr, mesh: Mesh, batched: bool) -> P:
    """Shard the cluster axis over dp and the first big per-cluster axis
    over sp (when divisible); everything else replicated. Axes that
    would shard to a single element per device (like a PRNG key's
    trailing 2 at sp=2) stay replicated — splitting them buys nothing
    and forces resharding churn between calls."""
    sp = mesh.shape["sp"]
    dims: list = []
    start = 0
    if batched:
        dims.append("dp")
        start = 1
    if (arr.ndim > start and arr.shape[start] > sp
            and arr.shape[start] % sp == 0):
        dims.append("sp")
    return P(*dims)


def sim_shardings(mesh: Mesh, tree, batched: bool = True):
    """NamedSharding pytree for a (cluster-batched) SimState / Msgs tree."""
    return jax.tree.map(
        lambda a: NamedSharding(mesh, _spec_for(a, mesh, batched)), tree)


def make_cluster_sims(program, cfg: T.NetConfig, n_clusters: int,
                      seed: int = 0) -> SimState:
    """A batch of independent cluster simulations: every array gains a
    leading cluster axis; PRNG keys differ per cluster (split from one
    root key — the bench/fuzz fleets, where no standalone-run equivalence
    is claimed)."""
    base = make_sim(program, cfg, seed=seed)
    batched = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (n_clusters,) + a.shape), base)
    keys = jax.random.split(jax.random.PRNGKey(seed), n_clusters)
    return batched.replace(key=keys)


def make_fleet_sims(program, cfg: T.NetConfig, seeds,
                    track_edge_send_round: bool = False) -> SimState:
    """A cluster-batched SimState whose row i is BIT-IDENTICAL to
    `make_sim(program, cfg, seed=seeds[i])`: the initial state tree is
    seed-independent, so rows share the broadcast base, and each row's
    PRNG key is `PRNGKey(seeds[i])` exactly (NOT a split of one root key
    — the fleet runner's per-cluster equivalence contract is that every
    cluster replays its standalone run)."""
    base = make_sim(program, cfg, seed=0,
                    track_edge_send_round=track_edge_send_round)
    F = len(seeds)
    batched = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (F,) + a.shape), base)
    keys = jnp.stack([jax.random.PRNGKey(int(s)) for s in seeds])
    return batched.replace(key=keys)


def mesh_is_mixed(mesh) -> bool:
    """True for a dp>1 x sp>1 ("pod-scale mixed") mesh — the shape whose
    fleet entry points run MANUAL over the mesh under `shard_map` (see
    `fleet_axis_spec`): GSPMD scatter-set is not value-safe over a mesh
    axis the operands are replicated on (per-replica contributions
    combine additively — corrupted reply rows were observed at `--fleet
    2 --mesh 2,2` before the shard_map rewrite), so a mixed mesh never
    lets the compiler partition the scan body."""
    if mesh is None:
        return False
    shape = getattr(mesh, "shape", None) or {}
    return shape.get("dp", 1) > 1 and shape.get("sp", 1) > 1


def fleet_axis_spec(mesh: Mesh, fleet: int) -> P:
    """The MIXED-mesh partition spec for the fleet (cluster) axis: when
    the fleet divides the whole device grid, the cluster axis shards
    over BOTH mesh axes (`P(("dp", "sp"))` — every device owns
    fleet/(dp*sp) whole clusters, full utilization); otherwise it shards
    over dp only and the sp rows replicate (each sp replica computes
    its dp shard's clusters identically — value-safe because the
    shard_map'd body is manual over the mesh, so no partial per-replica
    scatter contributions exist to combine)."""
    if fleet % mesh.size == 0:
        return P(("dp", "sp"))
    return P("dp")


def fleet_scan_shardings(mesh: Mesh, sim: SimState, inject) -> tuple:
    """The `(sim, inject, aux)` sharding triple for the FLEET entry
    points (`sim.make_fleet_scan_fn` and the fleet runner's batched
    bump/restart).

    Single-axis meshes (dp,1 / 1,sp — the legacy GSPMD regime): the
    cluster-batched SimState tree sharded dp over its leading fleet
    axis and sp over the first big per-cluster axis, the [F, C] inject
    batch likewise, per-cluster [F] vectors and scalars replicated
    (they are tiny and about to leave for the host).

    MIXED meshes (dp>1 x sp>1): every leaf — state, inject, and the
    per-cluster [F] vectors — carries the SAME leading-axis fleet spec
    (`fleet_axis_spec`), nothing shards the per-cluster axes and no
    operand is replicated over a >1 mesh axis with sharded peers. The
    fleet scan then runs manual over the mesh under `shard_map`
    (`sim.make_fleet_scan_fn`): inside each shard the cluster's
    scatters into flight-pool/edge-channel/reply/journal rings are
    plain local scatters with no GSPMD value-safety question."""
    if mesh_is_mixed(mesh):
        fleet = jax.tree.leaves(sim)[0].shape[0]
        fl = NamedSharding(mesh, fleet_axis_spec(mesh, fleet))
        return (jax.tree.map(lambda a: fl, sim),
                jax.tree.map(lambda a: fl, inject),
                fl)
    scalar = NamedSharding(mesh, P())
    return (sim_shardings(mesh, sim, batched=True),
            sim_shardings(mesh, inject, batched=True),
            scalar)


def make_cluster_round_fn(program, cfg: T.NetConfig, mesh: Mesh | None = None,
                          example: SimState | None = None,
                          example_inject=None):
    """Jitted vmapped round over the cluster axis; with a mesh, the inputs
    and outputs are sharded (dp = clusters, sp = node/pool axis) and GSPMD
    partitions the round body across chips."""
    f = jax.vmap(partial(_round, program, cfg))
    if mesh is None:
        return jax.jit(f)
    assert example is not None and example_inject is not None
    if mesh_is_mixed(mesh):
        # mixed mesh: manual body under shard_map, cluster axis only
        # (every vmapped output leaf leads with it) — same regime as
        # sim.fleet_shard_map, same value-safety argument
        from jax.experimental.shard_map import shard_map
        n = jax.tree.leaves(example)[0].shape[0]
        spec = fleet_axis_spec(mesh, n)
        fl = NamedSharding(mesh, spec)
        f = shard_map(f, mesh, in_specs=spec, out_specs=spec,
                      check_rep=False)
        in_sh = (jax.tree.map(lambda a: fl, example),
                 jax.tree.map(lambda a: fl, example_inject))
        return jax.jit(f, in_shardings=in_sh)
    in_sh = (sim_shardings(mesh, example), sim_shardings(mesh,
                                                         example_inject))
    return jax.jit(f, in_shardings=in_sh)
