"""External node processes: spawning and stdio pumping.

The compatibility boundary with the reference (`src/maelstrom/process.clj`):
a node is any binary speaking newline-delimited JSON on STDIN/STDOUT and
logging to STDERR. We spawn one OS process per node with three pump threads
(stdin <- net.recv, stdout -> parse -> net.send, stderr -> log file), keep
32-line ring buffers of recent output for crash reports, and detect crashes
at teardown (any exit before teardown -- even status 0 -- raises a rich
exception, matching the reference `process.clj:222-250`: nodes must run
until killed).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import threading
from collections import deque

from .errors import RPCError
from .message import MalformedMessage, parse_msg

log = logging.getLogger("maelstrom.process")

DEBUG_BUFFER_SIZE = 32      # reference process.clj:22-24


class NodeCrashed(Exception):
    def __init__(self, node_id, exit_code, stdout_tail, stderr_tail,
                 log_file):
        self.node_id = node_id
        self.exit_code = exit_code
        super().__init__(
            f"Node {node_id} crashed with exit status {exit_code}. Before "
            "crashing, it wrote to STDOUT:\n\n" + "\n".join(stdout_tail) +
            "\n\nAnd to STDERR:\n\n" + "\n".join(stderr_tail) +
            f"\n\nFull STDERR logs are available in {log_file}")


class NodeProcess:
    """A running node binary plus its three I/O pump threads
    (reference `process.clj:168-215`)."""

    def __init__(self, node_id: str, bin: str, args: list[str], net,
                 log_file: str, log_stderr: bool = False, dir: str = None):
        self.node_id = node_id
        self.net = net
        self.log_file = log_file
        self.running = True
        self.paused = False
        self.stdout_buffer = deque(maxlen=DEBUG_BUFFER_SIZE)
        self.stderr_buffer = deque(maxlen=DEBUG_BUFFER_SIZE)

        net.add_node(node_id)
        os.makedirs(os.path.dirname(log_file) or ".", exist_ok=True)
        self.log_writer = open(log_file, "w")
        bin_path = os.path.abspath(bin)
        log.info("launching %s %r", bin_path, args)
        # Node binaries are plain protocol speakers: strip accelerator
        # hookup vars so images whose sitecustomize registers a remote
        # backend (e.g. the tunneled-TPU 'axon' one, ~2 s of import per
        # interpreter) don't tax every spawned node — at 5 nodes on one
        # core that serialized past the 10 s init handshake.
        child_env = {k: v for k, v in os.environ.items()
                     if not k.startswith(("PALLAS_AXON_", "AXON_"))}
        self.process = subprocess.Popen(
            [bin_path] + list(args),
            cwd=dir or None, env=child_env,
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, bufsize=1)
        self.log_stderr = log_stderr

        self.threads = [
            threading.Thread(target=self._stdin_loop,
                             name=f"{node_id} stdin", daemon=True),
            threading.Thread(target=self._stdout_loop,
                             name=f"{node_id} stdout", daemon=True),
            threading.Thread(target=self._stderr_loop,
                             name=f"{node_id} stderr", daemon=True),
        ]
        for t in self.threads:
            t.start()

    # --- pumps (reference process.clj:115-166) ---

    def _stdin_loop(self):
        """net.recv -> process stdin (reference `process.clj:154-166`)."""
        while self.running:
            try:
                msg = self.net.recv(self.node_id, 1000)
                if msg is not None:
                    self.process.stdin.write(
                        json.dumps(msg.to_json()) + "\n")
                    self.process.stdin.flush()
            except (BrokenPipeError, ValueError, OSError):
                pass    # process crashed; teardown will report it
            except Exception:
                log.exception("Error in %s stdin pump", self.node_id)

    def _stdout_loop(self):
        """process stdout -> parse -> net.send
        (reference `process.clj:136-152`)."""
        for line in self.process.stdout:
            line = line.rstrip("\n")
            if not line:
                continue
            self.stdout_buffer.append(line)
            try:
                self.net.send(parse_msg(self.node_id, line))
            except MalformedMessage as e:
                log.error("%s", e)
            except RPCError as e:
                if e.code == 1:
                    # destination already torn down (expected during
                    # shutdown: peers keep heartbeating)
                    log.debug("%s -> departed node: %s", self.node_id,
                              e.body.get("text"))
                elif self.running:
                    log.exception("Error handling stdout of %s",
                                  self.node_id)
            except Exception:
                if self.running:
                    log.exception("Error handling stdout of %s",
                                  self.node_id)

    def _stderr_loop(self):
        """process stderr -> log file + ring buffer
        (reference `process.clj:115-134`)."""
        for line in self.process.stderr:
            line = line.rstrip("\n")
            if self.log_stderr:
                log.info("%s: %s", self.node_id, line)
            self.stderr_buffer.append(line)
            try:
                self.log_writer.write(line + "\n")
                self.log_writer.flush()
            except ValueError:
                break   # log closed during teardown

    # --- nemesis process control (jepsen db/Process + nemesis SIGSTOP) ---

    def pause(self):
        """SIGSTOP: the node stops being scheduled but keeps all state —
        the GC/VM-stall fault. Messages queue in the stdin pipe."""
        import signal
        self.paused = True
        os.kill(self.process.pid, signal.SIGSTOP)

    def resume(self):
        """SIGCONT: the node picks up exactly where it stopped."""
        import signal
        self.paused = False
        os.kill(self.process.pid, signal.SIGCONT)

    def kill(self) -> dict:
        """Nemesis crash-kill: SIGKILL with no warning, torn down
        WITHOUT the crash report (the death is intentional). The node
        loses everything it didn't persist itself; a later respawn
        models restart-from-durable-state."""
        import signal
        if getattr(self, "paused", False):
            # a stopped process can't die until it's continued
            os.kill(self.process.pid, signal.SIGCONT)
            self.paused = False
        self.running = False
        if self.process.poll() is None:
            self.process.kill()
            self.process.wait(timeout=5)
        for t in self.threads:
            t.join(timeout=2)
        self.net.remove_node(self.node_id)
        self.log_writer.close()
        return {"exit": self.process.returncode, "killed": True}

    # --- teardown (reference process.clj:217-256) ---

    def stop(self) -> dict:
        if getattr(self, "paused", False):
            self.resume()       # SIGKILL queues on a stopped process
        crashed = self.process.poll() is not None
        if not crashed:
            self.process.kill()
            self.process.wait(timeout=5)
        self.running = False
        for t in self.threads:
            t.join(timeout=2)
        self.net.remove_node(self.node_id)
        self.log_writer.close()
        if crashed:
            raise NodeCrashed(self.node_id, self.process.returncode,
                              list(self.stdout_buffer),
                              list(self.stderr_buffer), self.log_file)
        return {"exit": self.process.returncode}
