"""The host-path generator interpreter: real threads, real time.

The equivalent of jepsen.core/run!'s worker loop for the compatibility path
(external node binaries): N client worker threads each own a connection;
the main loop asks the generator for ops, dispatches them to free workers,
and records invoke/completion pairs in the history. The nemesis runs as one
extra worker applying fault ops to the network
(reference call stack, SURVEY.md section 3.1).
"""

from __future__ import annotations

import logging
import queue
import threading
import time as _time

from .. import generators as g
from ..history import History, Op

log = logging.getLogger("maelstrom.runner")


class Worker(threading.Thread):
    """One client worker: owns a connection, executes ops serially."""

    def __init__(self, process, client, node: str, test: dict,
                 results: "queue.Queue"):
        super().__init__(name=f"worker-{process}", daemon=True)
        self.process = process
        self.client = client
        self.node = node
        self.test = test
        self.results = results
        self.inbox: "queue.Queue" = queue.Queue()
        self.running = True

    def run(self):
        while self.running:
            try:
                op = self.inbox.get(timeout=0.1)
            except queue.Empty:
                continue
            if op is None:
                return
            try:
                completed = self.client.invoke(self.test, op)
            except Exception as e:
                log.exception("process %s op crashed", self.process)
                completed = {**op, "type": "info",
                             "error": ["exception", repr(e)]}
            self.results.put((self.process, completed))

    def stop(self):
        self.running = False
        self.inbox.put(None)


def run_test(test: dict) -> History:
    """Drives the generator against live clients. `test` needs:
    nodes, net, client (factory with open/setup/invoke/close),
    generator (composed), concurrency, nemesis (invoke(op) executor or
    None), time_source (callable -> ns, defaults to net.time_ns)."""
    net = test["net"]
    nodes = test["nodes"]
    concurrency = test.get("concurrency", len(nodes))
    time_source = test.get("time_source", net.time_ns)
    gen = g.to_gen(test["generator"])
    nemesis = test.get("nemesis")

    history = History()
    results: "queue.Queue" = queue.Queue()
    workers: dict = {}
    processes = []

    for i in range(concurrency):
        node = nodes[i % len(nodes)]
        client = test["client"].open(test, node)
        client.setup(test)
        w = Worker(i, client, node, test, results)
        w.start()
        workers[i] = w
        processes.append(i)
    if nemesis is not None:
        processes.append(g.NEMESIS)

    free = set(processes)
    deadline = _time.monotonic() + test.get("hard_deadline_s", 3600)
    lock = threading.Lock()

    dispatches = [0]

    def free_rotated():
        return g.rotate_free(free, dispatches[0])

    def nemesis_invoke(op):
        # a nemesis op that raises (a node binary died on its own before
        # a pause/kill reached it, a respawn missed its init window)
        # must still complete, or the NEMESIS process never returns to
        # the free set and the run spins until hard_deadline_s
        try:
            completed = nemesis.invoke(op)
        except Exception as e:
            log.exception("nemesis op %r crashed", op.get("f"))
            completed = {**op, "type": "info",
                         "error": ["nemesis-exception", repr(e)]}
        results.put((g.NEMESIS, completed))

    try:
        while _time.monotonic() < deadline:
            # Drain completions
            try:
                while True:
                    process, completed = results.get_nowait()
                    op = Op(type=completed.get("type", "info"),
                            f=completed.get("f"),
                            value=completed.get("value"),
                            process=process, time=time_source(),
                            error=completed.get("error"),
                            final=completed.get("final", False))
                    history.append(op)
                    free.add(process)
                    ctx = {"time": time_source(), "free": free_rotated(),
                           "processes": processes}
                    gen = gen.update(ctx, completed)
            except queue.Empty:
                pass

            ctx = {"time": time_source(), "free": free_rotated(),
                   "processes": processes}
            res, gen = gen.op(ctx)
            if res is None:
                if len(free) == len(processes):
                    break       # exhausted and quiescent
                _time.sleep(0.001)
                continue
            if res == g.PENDING:
                _time.sleep(0.001)
                continue
            # Dispatch
            dispatches[0] += 1
            process = res["process"]
            free.discard(process)
            invoke = Op(type="invoke", f=res.get("f"),
                        value=res.get("value"), process=process,
                        time=time_source(),
                        final=res.get("final", False))
            history.append(invoke)
            op_for_worker = {k: v for k, v in res.items() if k != "time"}
            if process == g.NEMESIS:
                threading.Thread(target=nemesis_invoke,
                                 args=(op_for_worker,), daemon=True).start()
            else:
                workers[process].inbox.put(op_for_worker)
    finally:
        for w in workers.values():
            w.stop()
        for w in workers.values():
            w.join(timeout=2)
        for w in workers.values():
            try:
                w.client.close()
            except Exception:
                pass
    return history
