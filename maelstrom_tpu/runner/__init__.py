"""Test runners: the generator interpreters that drive clients against the
network and record histories. `host_runner` uses real threads and wall-clock
time (for external-binary nodes); `tpu_runner` drives the batched TPU
simulation in virtual time."""
