"""Fleet execution: thousands of independent test instances in ONE
compiled scan.

The standalone `TpuRunner` simulates one cluster; a campaign — a
seed x workload x nemesis-schedule x capacity sweep — is N independent
clusters. `FleetRunner` gives the `("dp", "sp")` mesh's dp axis its
meaning: every cluster's whole hot-loop tree (node state, flight pool,
edge channels, durable store, freeze/nemesis masks, reply rings) gains a
leading *cluster* axis, the compiled scan is vmapped over it
(`sim.make_fleet_scan_fn`), and `--mesh dp,sp` shards that axis over dp
while sp keeps sharding the per-cluster node/pool axes. One device
program, N replicas, throughput in clusters/sec — the data-parallel
scaling playbook (PAPERS.md: "Scale MLPerf-0.6 models on Google TPU-v3
Pods", "Exploring the limits of Concurrency in ML Training on Google
TPUs") applied to simulation.

Architecture: each cluster is a full `TpuRunner` *shell* — its own
generator tree, pending-RPC map, history, nemesis decision streams,
intern tables — built from the option set its STANDALONE run would use
(`core.FleetSpec.cluster_opts`). The shells' dispatch loops are the
same `_loop_steps` coroutine the standalone runner drives; the fleet
merely answers their yielded device requests in lockstep *waves*:

    quiet probes  -> one vmapped probe over the batched tree
    bumps         -> one batched round-counter add (k=0 holds a row)
    scans         -> one vmapped `fleet_scan_fn` dispatch; clusters
                     between stretches are held by the `active` mask
    cscans        -> (`--continuous`) one vmapped sched-inject dispatch:
                     the whole fleet's pre-scheduled windows ride ONE
                     columnar [fleet, Q] inject tensor + round-offset
                     tensor, and ONE packed drain returns every lane's
                     replies and confirmed `inj_mids`

Because the loop code and the per-row compiled math are identical to
the standalone path, every cluster's history is **bit-identical** to
running it alone with the same options (pinned by
tests/test_fleet_runner.py and tests/test_fleet_continuous.py) — the
fleet changes batching, never semantics.

The host side is the vectorized multi-cluster driver (doc/perf.md
"vectorized host driver"): per wave, the gather pass advances every
ready cluster's coroutine, the inject encode fills numpy-columnar
[fleet, C] buffers (one `jnp.asarray` per field — no per-cluster device
constructions), and one dispatch + one packed fetch serve the whole
fleet. `TransferStats.host_polls` books ONE poll pass per wave, so the
O(waves)-not-O(clusters) host-cost claim is a measured counter
(`BENCH_MODE=fleet_stream`), not an assertion.

Checkpointing is per-cluster-consistent: each shell snapshots itself at
its own stretch boundaries (sim row + host meta, pickled immediately),
and the fleet coalesces the freshest snapshots into one crash-consistent
checkpoint file per wave (same framed format, `checkpoint.py`), so
SIGKILL/SIGTERM + `--resume` recovers every cluster byte-identically.
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import core as core_mod
from .. import generators as g
from .. import store
from ..history import History
from ..net import tpu as T
from ..sim import dealias, donation_enabled
from .tpu_runner import TpuNetStats, TpuRunner

log = logging.getLogger("maelstrom.fleet")


class _FleetClusterShell(TpuRunner):
    """One cluster of a fleet: a full TpuRunner whose device
    interactions are redirected to its row of the fleet's batched tree.
    Its `_loop_steps` coroutine (inherited verbatim) is driven by the
    FleetRunner; the overrides below cover every path that would
    otherwise touch the shell's own (discarded) sim."""

    def __init__(self, test: dict, fleet: "FleetRunner", idx: int):
        self.fleet = fleet
        self.idx = idx
        super().__init__(test)

    def _net_surgery(self, fn):
        self.fleet.apply_net_row(self.idx, fn)

    def restart_nodes(self, mask):
        self.fleet.restart_row(self.idx, mask)
        self._state_cache = None

    def _read_state(self, node_idx: int):
        return self.fleet.read_state(self.idx, node_idx)

    def _nodes_host(self):
        return self.fleet.nodes_host_row(self.idx)

    def _init_next_mid(self):
        self._next_mid = self.fleet.shell_next_mid(self.idx)

    def _save_checkpoint(self, gen, history, sessions, free, r,
                         sync: bool = False):
        # stretch-boundary snapshot: the fleet coalesces these into one
        # checkpoint file per wave (the shell's own cadence fields drive
        # WHEN this is called — same sites as the standalone runner)
        self.fleet.snapshot_cluster(self.idx, gen, history, sessions,
                                    free, r)

    def _build_sim(self):
        # the fleet owns ONE batched tree (parallel.make_fleet_sims,
        # row i == make_sim(seed_i) exactly); a per-shell device sim
        # would allocate the whole fleet tree F times over
        return None


class FleetRunner:
    """Drives `--fleet N`: N cluster shells in lockstep against one
    cluster-batched SimState, scanned/bumped/probed in single vmapped
    dispatches and sharded `("dp", "sp")` under `--mesh dp,sp`."""

    def __init__(self, test: dict):
        self.test = test
        self.spec = core_mod.FleetSpec.from_test(test)
        F = self.spec.fleet
        self.mesh = None
        self._shardings = None
        self._mixed_mesh = False
        mesh_spec = test.get("mesh")
        if mesh_spec:
            from .. import parallel
            self.mesh = parallel.mesh_from_spec(mesh_spec)
            dp = self.mesh.shape["dp"]
            if F % dp:
                raise ValueError(
                    f"--fleet {F} with --mesh {mesh_spec}: the fleet "
                    f"axis shards over dp, so fleet must be a multiple "
                    f"of dp={dp}")
            # dp>1 x sp>1 (mixed) meshes run the scan body manual under
            # shard_map (sim.fleet_shard_map) — the PR 2 GSPMD
            # scatter-over-replicated-axis hazard cannot occur there, so
            # no mixed-mesh rejection remains.
        # one full runner shell per cluster, each built from the exact
        # option set its standalone run would use
        self.shells: list[_FleetClusterShell] = []
        for i in range(F):
            t_i = core_mod.build_test(self.spec.cluster_opts(test, i))
            # shells never write files; the fleet's dir lets graceful
            # preemption (Preempted.checkpoint_dir) name the right place
            t_i["store_dir"] = test.get("store_dir")
            shell = _FleetClusterShell(t_i, fleet=self, idx=i)
            # the nemesis truthiness rewrite comes AFTER construction,
            # mirroring run_tpu_test's ordering exactly: program
            # builders sniff the fault SET (edge_timing grows the edge
            # ring +2 under `duplicate` so second deliveries are
            # representable), and rewriting first silently sized fleet
            # rings without that headroom — every duplicate clipped/
            # self-overwrote, flagged invalid by the net checker (found
            # by the ISSUE 12 verification; the old test solos made the
            # same premature rewrite, masking it)
            t_i["nemesis"] = (True if t_i["nemesis_pkg"]["generator"]
                              is not None else None)
            self.shells.append(shell)
        s0 = self.shells[0]
        self.program, self.cfg = s0.program, s0.cfg
        self.concurrency = s0.concurrency
        self.reply_log_cap = s0.reply_log_cap
        for sh in self.shells[1:]:
            # the fleet shares ONE compiled program: every swept
            # dimension must leave the static shapes untouched
            if sh.cfg != s0.cfg:
                raise ValueError(
                    f"fleet clusters disagree on the compiled network "
                    f"shape (cluster 0: {s0.cfg} vs cluster {sh.idx}: "
                    f"{sh.cfg}); sweeps may only vary seeds/schedules/"
                    f"rates")
        # batched state: row i IS shell i's standalone initial state —
        # parallel.make_fleet_sims pins row i == make_sim(seed_i)
        # exactly (one broadcast seed-independent base + stacked PRNG
        # keys, instead of F full per-shell device trees). The
        # broadcast rows (and durable's view of nodes) alias the base
        # buffers, so dealias before donation; p_loss is uniform across
        # the fleet (sweeps only vary seeds/schedules/rates)
        from .. import parallel
        self.sim = parallel.make_fleet_sims(
            self.program, self.cfg,
            seeds=[sh.test.get("seed", 0) for sh in self.shells])
        if donation_enabled():
            self.sim = dealias(self.sim)
        if test.get("p_loss"):
            self.sim = self.sim.replace(
                net=T.flaky(self.sim.net, float(test["p_loss"])))
        if self.mesh is not None:
            from .. import parallel
            inject_ex = jax.tree.map(
                lambda a: jnp.broadcast_to(a, (F,) + a.shape),
                T.Msgs.empty(max(self.concurrency, 1)))
            self._shardings = parallel.fleet_scan_shardings(
                self.mesh, self.sim, inject_ex)
            self.sim = jax.device_put(self.sim, self._shardings[0])
            self._mixed_mesh = parallel.mesh_is_mixed(self.mesh)
            if self._mixed_mesh:
                log.info(
                    "fleet MIXED mesh mode: %d clusters over dp=%d "
                    "sp=%d (%d devices), shard_map manual body, fleet "
                    "axis %s", F, self.mesh.shape["dp"],
                    self.mesh.shape["sp"], self.mesh.size,
                    parallel.fleet_axis_spec(self.mesh, F))
            else:
                log.info("fleet mesh mode: %d clusters over dp=%d sp=%d "
                         "(%d devices)", F, self.mesh.shape["dp"],
                         self.mesh.shape["sp"], self.mesh.size)

        from ..checkers.netstats import TransferStats
        self.transfer = TransferStats()
        # fleet-level grader pool (doc/perf.md "vectorized host
        # driver"): every shell's AnalysisPipeline multiplexes over ONE
        # shared worker pool sized by --check-workers (default: a few
        # threads) instead of spawning a dedicated grader thread per
        # cluster — what makes `--fleet 512 --continuous` windowed
        # grading the default posture rather than a 512-thread opt-in.
        # Per-pipeline segment order is preserved (verdicts bit-equal
        # to the dedicated-thread path, tests/test_ordering.py).
        self.analysis_pool = None
        if F > 1 and not test.get("no_overlap"):
            from ..checkers.pipeline import AnalysisPool
            cw = test.get("check_workers")
            workers = int(cw) if cw is not None else min(
                4, os.cpu_count() or 1)
            if workers > 0:
                self.analysis_pool = AnalysisPool(workers)
                for sh in self.shells:
                    sh._analysis_pool = self.analysis_pool
        # flight recorder (doc/observability.md): ONE TelemetrySession
        # for the whole fleet — shells share it (their per-wave records
        # carry the cluster index), the fleet driver lands its own
        # dispatch/fetch spans on the "fleet" trace row, and close()
        # renders the per-cluster heatmap. Ring state is per cluster (a
        # leading fleet axis on the MetricRing, like the rest of the
        # carry).
        from .. import telemetry as TM
        self.telemetry_rings = s0.telemetry_rings
        self.session = None
        if self.telemetry_rings:
            self.session = TM.TelemetrySession(
                TM.resolve_dir(test.get("telemetry"),
                               test.get("store_dir") or "."),
                ms_per_round=s0.ms_per_round, fleet=F)
            for sh in self.shells:
                sh.telemetry = self.session
        # open-world fleets (doc/streams.md x doc/perf.md): continuous
        # shells run `_loop_steps_continuous` and yield cscan requests;
        # the fleet answers them with the vmapped sched-inject scan
        self.continuous = bool(test.get("continuous"))
        self._state_cache = None     # host nodes cache (read_state)
        self._sim_cache = None       # host full-tree cache (snapshots)
        self._scan_fn = None
        self._cscan_fn = None        # sched-inject (continuous) variant
        self._quiet_fn = None
        self._restart_fn = None
        self._pack = None
        self._pack_c = None          # continuous drain (replies + mids)
        self._empty_inject = T.Msgs.empty(max(self.concurrency, 1))
        donate = (0,) if donation_enabled() else ()
        from ..sim import fleet_shard_map
        self._bump_fn = jax.jit(
            fleet_shard_map(
                lambda sim, ks: sim.replace(net=sim.net.replace(
                    round=sim.net.round + ks)),
                self._shardings),
            donate_argnums=donate, **self._pins(n_args=2))
        # fleet checkpointing (per-cluster snapshots coalesced per wave)
        ck = test.get("checkpoint_every")
        self.checkpoint_every = ck
        self.sync_checkpoint = bool(test.get("sync_checkpoint"))
        self.on_preempt = str(test.get("on_preempt") or "checkpoint")
        self._snaps: list[dict | None] = [None] * F
        self._snaps_dirty = False
        self._ckpt_writer = None
        self._preempt = threading.Event()
        self._setup_mids = None
        self._states: list[dict | None] = [None] * F
        self.final_rounds = [0] * F
        # columnar client sessions (doc/perf.md): ONE shared [F, Q]
        # session table across all shells, refreshed by a single
        # vectorized pass per wave (`encode_wave`) instead of F
        # per-shell dict scans. `--sessions coroutine` keeps the legacy
        # per-shell bookkeeping alive for the byte-identity pins.
        from .sessions import SESSION_MODES, ColumnarSessions
        mode = test.get("sessions")
        mode = "columnar" if mode is None else str(mode)
        if mode not in SESSION_MODES:
            raise ValueError(f"--sessions {mode!r}: pick one of "
                             f"{'|'.join(SESSION_MODES)}")
        self.sessions_mode = mode
        self._session_table = None
        if mode == "columnar":
            self._session_table = ColumnarSessions(F, self.concurrency)
            for i, sh in enumerate(self.shells):
                sh._fleet_sessions = (self._session_table, i)

    # --- device plumbing -------------------------------------------------

    def _tel_span(self, name, t0, t1, args=None):
        """Fleet-level phase span (no-op without a session): lands on
        the trace's "fleet" thread row, distinct from the per-cluster
        shell rows."""
        if self.session is not None:
            self.session.span(name, t0, t1, tid="fleet", args=args)

    def _drain_rings(self, ring_h, reqs):
        """Hands each serviced shell its row of the drained [F, ...]
        metric ring (the shells' `_tel_wave` reads it on their next
        loop iteration)."""
        if not self.telemetry_rings:
            return
        for i in reqs:
            self.shells[i]._ring_host = jax.tree.map(
                lambda a, i=i: a[i], ring_h)

    def _pins(self, n_args: int) -> dict:
        if self._shardings is None:
            return {}
        sim_sh, _inj_sh, scalar_sh = self._shardings
        return {"in_shardings": (sim_sh,) + (scalar_sh,) * (n_args - 1),
                "out_shardings": sim_sh}

    def _reshard(self):
        if self._shardings is not None:
            self.sim = jax.device_put(self.sim, self._shardings[0])

    def _invalidate(self):
        self._state_cache = None
        self._sim_cache = None

    def apply_net_row(self, i: int, fn):
        """Nemesis mask surgery on ONE cluster's net row: extract row i,
        apply the host-side update, scatter it back. Eager (outside
        jit) like the standalone path — nemesis ops are rare."""
        net = self.sim.net
        row = jax.tree.map(lambda a: a[i], net)
        new = fn(row)
        self.sim = self.sim.replace(net=jax.tree.map(
            lambda b, x: b.at[i].set(x), net, new))
        self._reshard()
        self._invalidate()

    def restart_row(self, i: int, mask):
        """Crash-restart (stop-kill) for one cluster: the vmapped
        restore runs over the whole fleet with an all-False mask
        everywhere but row i — restore under a False mask is the
        identity, so other clusters' values are untouched."""
        if self._restart_fn is None:
            prog = self.program

            def _one(sim, m):
                nodes = prog.restore(prog.init_state(), sim.durable,
                                     sim.nodes, m)
                net = sim.net.replace(down=sim.net.down & ~m)
                return sim.replace(nodes=nodes, net=net,
                                   durable=prog.durable_view(nodes))
            from ..sim import fleet_shard_map
            self._restart_fn = jax.jit(
                fleet_shard_map(jax.vmap(_one), self._shardings),
                donate_argnums=(0,) if donation_enabled() else (),
                **self._pins(n_args=2))
        m = np.zeros((self.spec.fleet, self.cfg.n_nodes), bool)
        m[i] = np.asarray(mask, bool)
        self.sim = self._restart_fn(self.sim, jnp.asarray(m))
        self._invalidate()

    def read_state(self, i: int, node_idx: int):
        if self._state_cache is None:
            self._state_cache = self.transfer.fetch(self.sim.nodes)
        # copy the row out (CPU device_get returns zero-copy views; see
        # TpuRunner._read_state); extraction is program-defined so
        # role partitions land in the right role subtree
        row = jax.tree.map(lambda a: a[i], self._state_cache)
        return self.shells[i].program.state_row(row, node_idx)

    def nodes_host_row(self, i: int):
        """Cluster i's whole node-state tree on the host (the shell's
        `_nodes_host`: dynamic nemesis targets, election reports)."""
        if self._state_cache is None:
            self._state_cache = self.transfer.fetch(self.sim.nodes)
        return jax.tree.map(lambda a: np.array(a[i]), self._state_cache)

    def shell_next_mid(self, i: int) -> int:
        if self._setup_mids is None:
            self._setup_mids = np.asarray(
                self.transfer.fetch(self.sim.net.next_mid))
        return int(self._setup_mids[i])

    def _probe_quiet(self) -> np.ndarray:
        if self._quiet_fn is None:
            prog_q = getattr(self.program, "quiescent", None)

            def quiet(sim):
                q = ~sim.net.pool.valid.any()
                if sim.channels is not None:
                    q = q & ~sim.channels.valid.any()
                if prog_q is not None:
                    q = q & prog_q(sim.nodes)
                return q
            self._quiet_fn = jax.jit(jax.vmap(quiet))
        return np.asarray(self.transfer.fetch(self._quiet_fn(self.sim)))

    def _bump_rows(self, ks_by_idx: dict):
        ks = np.zeros(self.spec.fleet, np.int32)
        for i, k in ks_by_idx.items():
            ks[i] = k
        self.sim = self._bump_fn(self.sim, jnp.asarray(ks))
        self._invalidate()

    def _columnar_inject(self, fill, width: int) -> "T.Msgs":
        """The fleet's [F, width] inject batch assembled numpy-columnar
        (doc/perf.md "vectorized host driver"): `fill(valid, src, dest,
        typ, a, b, c)` writes cluster rows into preallocated host
        buffers, then ONE `jnp.asarray` per field materializes the
        whole fleet's batch — O(1) device constructions per wave
        instead of one Msgs build + tree-stack per cluster."""
        F = self.spec.fleet
        shape = (F, width)
        valid = np.zeros(shape, bool)
        src = np.zeros(shape, np.int32)
        dest = np.zeros(shape, np.int32)
        typ = np.zeros(shape, np.int32)
        a = np.zeros(shape, np.int32)
        b = np.zeros(shape, np.int32)
        c = np.zeros(shape, np.int32)
        fill(valid, src, dest, typ, a, b, c)
        z = jnp.zeros(shape, T.I32)
        return T.Msgs(valid=jnp.asarray(valid), src=jnp.asarray(src),
                      dest=jnp.asarray(dest), due=z, mid=z,
                      reply_to=jnp.full(shape, -1, T.I32),
                      type=jnp.asarray(typ), a=jnp.asarray(a),
                      b=jnp.asarray(b), c=jnp.asarray(c))

    def _exec_fleet_scan(self, reqs: dict) -> dict:
        """One vmapped dispatch covering every cluster with a pending
        scan request; the rest are held by the active mask. Returns
        {cluster: (k_executed, replies)}."""
        t0 = time.perf_counter()
        F = self.spec.fleet
        N, C = self.cfg.n_nodes, max(self.concurrency, 1)
        kmax = np.ones(F, np.int32)
        stop = np.ones(F, bool)
        active = np.zeros(F, bool)

        def fill(valid, src, dest, typ, a, b, c):
            for i, req in reqs.items():
                inject_rows, k_max, st, _hist, _r = req
                kmax[i], stop[i], active[i] = k_max, st, True
                m = len(inject_rows)
                if not m:
                    continue
                cols = np.asarray(
                    [(p, ni, t, aa, bb, cc) for (p, _o, ni, t, aa,
                                                 bb, cc) in inject_rows],
                    np.int64).T
                valid[i, :m] = True
                src[i, :m] = cols[0] + N
                dest[i, :m] = cols[1]
                typ[i, :m] = cols[2]
                a[i, :m] = cols[3]
                b[i, :m] = cols[4]
                c[i, :m] = cols[5]

        inject = self._columnar_inject(fill, C)
        if self._scan_fn is None:
            from ..sim import make_fleet_scan_fn
            self._scan_fn = make_fleet_scan_fn(
                self.program, self.cfg, reply_cap=self.reply_log_cap,
                donate=True, shardings=self._shardings)
        self.transfer.host_poll_s += time.perf_counter() - t0
        t_d0 = time.perf_counter()
        self.sim, _cm, k, rl = self._scan_fn(
            self.sim, inject, jnp.asarray(kmax), jnp.asarray(stop),
            jnp.asarray(active))
        self._tel_span("dispatch", t_d0, time.perf_counter(),
                       args={"clusters": len(reqs)})
        self._invalidate()
        # the batched stretch is in flight: overlap each cluster's
        # host-side analysis of its last segment with the device time
        for i, req in sorted(reqs.items()):
            self.shells[i]._overlap_feed(req[3])
        # the fleet metric ring rides the SAME packed fetch ([F, ...]
        # rows; an empty tuple when rings are off)
        ring = self.sim.telemetry if self.telemetry_rings else ()
        tree = (rl, k, self.sim.net.next_mid, ring)
        if self._pack is None:
            self._pack = TpuRunner._make_packer(
                tree, fleet_dim=self._mixed_mesh)
        pack, unpack = self._pack
        # ONE fetched array for the whole fleet per wave
        t_f0 = time.perf_counter()
        flat = self.transfer.fetch(pack(tree))
        self._tel_span("device-get", t_f0, time.perf_counter(),
                       args={"drains": self.transfer.drains,
                             "host-bytes": self.transfer.host_bytes})
        (rlog, rounds, plog, rn), k, next_mid, ring_h = unpack(flat)
        self._drain_rings(ring_h, reqs)
        W = int(getattr(self.program, "reply_payload_words", 0) or 0)
        out = {}
        for i in sorted(reqs):
            sh = self.shells[i]
            sh._next_mid = int(next_mid[i])
            row_log = jax.tree.map(lambda a, i=i: a[i], rlog)
            out[i] = (int(k[i]), sh._decode_replies(
                row_log, rounds[i], plog[i] if W else (), int(rn[i])))
        return out

    def _exec_fleet_cscan(self, reqs: dict) -> dict:
        """One vmapped SCHED-INJECT dispatch (`--fleet N --continuous`):
        every requesting cluster's pre-scheduled window rides one
        columnar [F, Q] inject tensor + round-offset tensor, held lanes
        masked inactive; one packed fetch drains replies AND the
        per-row confirmed message ids for the whole fleet. Returns
        {cluster: (k_executed, replies, inj_mids_row)} — exactly what
        each shell's `_loop_steps_continuous` expects from its own
        standalone `_exec_cscan`."""
        t0 = time.perf_counter()
        F = self.spec.fleet
        N, Q = self.cfg.n_nodes, max(self.concurrency, 1)
        at = np.full((F, Q), -1, np.int32)
        kmax = np.ones(F, np.int32)
        stop = np.ones(F, bool)
        active = np.zeros(F, bool)

        def fill(valid, src, dest, typ, a, b, c):
            for i, req in reqs.items():
                rows, k_max, st, _hist, r = req
                kmax[i], stop[i], active[i] = k_max, st, True
                cols = g.sched_columns(rows, r, Q, N)
                at[i] = cols["at"]
                valid[i] = cols["valid"]
                src[i] = cols["src"]
                dest[i] = cols["dest"]
                typ[i] = cols["type"]
                a[i] = cols["a"]
                b[i] = cols["b"]
                c[i] = cols["c"]

        inject = self._columnar_inject(fill, Q)
        if self._cscan_fn is None:
            from ..sim import make_fleet_scan_fn
            self._cscan_fn = make_fleet_scan_fn(
                self.program, self.cfg, reply_cap=self.reply_log_cap,
                donate=True, shardings=self._shardings,
                sched_inject=True)
        self.transfer.host_poll_s += time.perf_counter() - t0
        t_d0 = time.perf_counter()
        self.sim, _cm, k, rl, im = self._cscan_fn(
            self.sim, inject, jnp.asarray(at), jnp.asarray(kmax),
            jnp.asarray(stop), jnp.asarray(active))
        self._tel_span("dispatch", t_d0, time.perf_counter(),
                       args={"clusters": len(reqs)})
        self._invalidate()
        # the batched window is in flight: overlap each cluster's
        # analysis of its last drained segment with the device time
        # (the PR 7 windowed graders run per shell, so checker-lag
        # stays a per-cluster metric while the fleet streams)
        for i, req in sorted(reqs.items()):
            self.shells[i]._overlap_feed(req[3])
        ring = self.sim.telemetry if self.telemetry_rings else ()
        tree = (rl, im, k, self.sim.net.next_mid, ring)
        if self._pack_c is None:
            self._pack_c = TpuRunner._make_packer(
                tree, fleet_dim=self._mixed_mesh)
        pack, unpack = self._pack_c
        # ONE fetched array for the whole fleet per wave: replies,
        # confirmed inj_mids, per-lane k, and the mid counters together
        t_f0 = time.perf_counter()
        flat = self.transfer.fetch(pack(tree))
        self._tel_span("device-get", t_f0, time.perf_counter(),
                       args={"drains": self.transfer.drains,
                             "host-bytes": self.transfer.host_bytes})
        (rlog, rounds, plog, rn), im, k, next_mid, ring_h = unpack(flat)
        self._drain_rings(ring_h, reqs)
        W = int(getattr(self.program, "reply_payload_words", 0) or 0)
        out = {}
        for i in sorted(reqs):
            sh = self.shells[i]
            sh._next_mid = int(next_mid[i])
            row_log = jax.tree.map(lambda a, i=i: a[i], rlog)
            out[i] = (int(k[i]), sh._decode_replies(
                row_log, rounds[i], plog[i] if W else (), int(rn[i])),
                im[i])
        return out

    # --- checkpoint / preemption ----------------------------------------

    def _sim_host(self):
        if self._sim_cache is None:
            self._sim_cache = self.transfer.fetch(self.sim)
        return self._sim_cache

    def snapshot_cluster(self, i, gen, history, sessions, free, r):
        """A stretch-boundary snapshot of ONE cluster: its sim row
        (device-sliced first, so the host pull is O(row) — not the
        whole fleet tree per snapshot) and its mutable host state,
        pickled immediately so later mutation can't tear it."""
        sh = self.shells[i]
        t0 = time.perf_counter()
        row = jax.tree.map(np.array, self.transfer.fetch(
            jax.tree.map(lambda a, i=i: a[i], self.sim)))
        sess_meta = sessions.to_meta()
        meta = {
            "r": r,
            "dispatches": sh._dispatches,
            "gen": gen,
            "pending": sess_meta["pending"],
            "free": set(free),
            "intern": sh.intern,
            "nemesis_rng": (sh.nemesis.rng_state()
                            if sh.nemesis else None),
            # continuous-mode carry (scheduled-but-uninjected rows +
            # the drawn nemesis/host ops — the schedule cannot be
            # re-drawn; None on round-synchronous shells) and the
            # program's host session state (kafka consumer sessions):
            # both ride the coalesced fleet checkpoint exactly like the
            # standalone checkpoint's meta
            "carry": getattr(sh, "_carry_live", None),
            # leader-redirect requeue (open retried invokes) rides the
            # coalesced checkpoint like the standalone meta — the
            # session backends emit the same legacy shapes, so
            # fingerprints don't move
            "requeue": sess_meta["requeue"],
            "program_host": sh.program.host_state(),
            "history_columns": history.snapshot_columns(),
        }
        self._snaps[i] = {
            "r": r, "sim": row,
            "blob": pickle.dumps(meta,
                                 protocol=pickle.HIGHEST_PROTOCOL)}
        self._snaps_dirty = True
        self.transfer.ckpt_blocked_s += time.perf_counter() - t0

    def _seed_initial_snaps(self):
        """Before the first dispatch, every cluster's snapshot is its
        initial state (blob None = resume starts it fresh), so a fleet
        checkpoint written early still covers the whole fleet."""
        host = self._sim_host()
        for i in range(self.spec.fleet):
            if self._snaps[i] is None:
                self._snaps[i] = {
                    "r": 0, "blob": None,
                    "sim": jax.tree.map(lambda a, i=i: np.array(a[i]),
                                        host)}

    def _seed_resume_snaps(self, resume: dict, rounds: list):
        """On --resume, every cluster's snapshot starts as exactly what
        the checkpoint recorded (sim row + meta blob), so a fleet
        checkpoint written before cluster i's next stretch boundary
        still resumes i from its CHECKPOINTED state — never from
        scratch with a mid-run sim row."""
        metas = resume["clusters"]
        for i in range(self.spec.fleet):
            self._snaps[i] = {
                "r": rounds[i], "blob": metas[i],
                "sim": jax.tree.map(lambda a, i=i: np.array(a[i]),
                                    resume["sim"])}

    def _write_checkpoint(self, done, sync: bool = False):
        """Coalesces the freshest per-cluster snapshots into one framed
        checkpoint file (checkpoint.py): background writer unless
        --sync-checkpoint/preemption forces the inline write."""
        from .. import checkpoint as cp
        if not self._snaps_dirty:
            return
        t0 = time.perf_counter()
        rows = [s["sim"] for s in self._snaps]
        state = {
            "fingerprint": cp.fingerprint(self.test),
            "r": min(s["r"] for s in self._snaps),
            "sim": jax.tree.map(lambda *xs: np.stack(xs), *rows),
            "meta_blob": pickle.dumps(
                {"clusters": [s["blob"] for s in self._snaps],
                 "done": list(done),
                 "finals": list(self.final_rounds)},
                protocol=pickle.HIGHEST_PROTOCOL),
        }
        store_dir = self.test["store_dir"]
        if sync or self.sync_checkpoint:
            if self._ckpt_writer is not None:
                self._ckpt_writer.wait()
            cp.save(store_dir, state)
        else:
            if self._ckpt_writer is None:
                self._ckpt_writer = cp.CheckpointWriter()
            self._ckpt_writer.submit(store_dir, state)
        self._snaps_dirty = False
        self.transfer.ckpt_saves += 1
        self.transfer.ckpt_blocked_s += time.perf_counter() - t0
        log.info("fleet checkpoint (%d clusters) -> %s%s",
                 self.spec.fleet, store_dir, " (sync)" if sync else "")

    def _finish_checkpoints(self):
        if self._ckpt_writer is not None:
            self._ckpt_writer.wait()
            self.transfer.ckpt_write_s = self._ckpt_writer.write_s

    # --- the wave scheduler ----------------------------------------------

    def run(self, resume: dict | None = None) -> list[History]:
        """Runs the whole fleet to completion; returns one History per
        cluster (index-aligned with the shells)."""
        from .. import checkpoint as cp
        F = self.spec.fleet
        # `finished` = the cluster's loop COMPLETED (its history is
        # final; a resume replays it from the checkpoint). Clusters this
        # run stops early (preemption) are merely descheduled — the
        # checkpoint must record them as unfinished so --resume
        # continues them.
        finished = [False] * F
        cluster_resumes: list[dict | None] = [None] * F
        if resume is not None:
            metas = resume["clusters"]
            finished = list(resume["done"])
            self.final_rounds = list(resume["finals"])
            self.sim = (dealias(resume["sim"]) if donation_enabled()
                        else resume["sim"])
            for i, blob in enumerate(metas):
                if blob is None:
                    continue
                meta = pickle.loads(blob)
                meta["history"] = History.from_columns(
                    meta.pop("history_columns"))
                cluster_resumes[i] = meta
            # seed the coalesced-checkpoint state from the checkpoint
            # itself BEFORE the device tree can move on
            self._seed_resume_snaps(
                resume, [m["r"] if m else 0 for m in cluster_resumes])
            self._reshard()
            self._invalidate()
            live = [m["r"] for i, m in enumerate(cluster_resumes)
                    if m and not finished[i]]
            log.info("fleet resumed: %d/%d clusters done, live rounds "
                     "%s..%s", sum(finished), F,
                     min(live) if live else "-", max(live) if live else "-")

        # per-shell host state + coroutines (finished clusters only
        # replay their checkpointed history)
        self._setup_mids = None
        steps: list = [None] * F
        for i, sh in enumerate(self.shells):
            if finished[i]:
                st = cluster_resumes[i] or {}
                self._states[i] = {"history": st.get("history",
                                                     History())}
                continue
            self._states[i] = sh._setup_run(cluster_resumes[i])
            loop = (sh._loop_steps_continuous if sh.continuous
                    else sh._loop_steps)
            steps[i] = loop(**self._states[i])
        if self.checkpoint_every:
            self._seed_initial_snaps()

        # graceful preemption: same contract as the standalone runner —
        # finish in-flight work, checkpoint the fleet, exit 75
        import signal as _signal
        prev_handlers = {}
        if self.on_preempt == "checkpoint" and \
                threading.current_thread() is threading.main_thread():
            def _on_signal(signum, frame):
                if self._preempt.is_set():
                    for s, h in prev_handlers.items():
                        try:
                            _signal.signal(s, h)
                        except (ValueError, OSError):  # pragma: no cover
                            pass
                    raise KeyboardInterrupt
                log.warning("received %s: draining the in-flight wave, "
                            "then checkpointing the fleet (signal again "
                            "to abort)", _signal.Signals(signum).name)
                self._preempt.set()
            for sig in (_signal.SIGTERM, _signal.SIGINT):
                try:
                    prev_handlers[sig] = _signal.signal(sig, _on_signal)
                except (ValueError, OSError):   # pragma: no cover
                    pass
        try:
            self._waves(steps, finished)
        except BaseException:
            for sh in self.shells:
                if sh.pipeline is not None:
                    sh.pipeline.close()
            if self.analysis_pool is not None:
                self.analysis_pool.close()
            try:
                self._finish_checkpoints()
            except Exception as e:
                log.error("fleet checkpoint writer failed during "
                          "unwind: %s", e)
            raise
        finally:
            for sig, h in prev_handlers.items():
                try:
                    _signal.signal(sig, h)
                except (ValueError, OSError):   # pragma: no cover
                    pass
        self._finish_checkpoints()
        histories = []
        for i, sh in enumerate(self.shells):
            history = self._states[i]["history"]
            sh.final_round = self.final_rounds[i]
            if sh.pipeline is not None:
                overlapped = sh.pipeline.busy_s
                sh._overlap_feed(history)
                sh.pipeline.finish()
                self.transfer.overlapped_s += overlapped
            histories.append(history)
        if self.analysis_pool is not None:
            # every pipeline has finished (their queues are drained);
            # release the shared grader threads
            self.analysis_pool.close()
        log.info("fleet run finished: %d clusters, rounds %d..%d, "
                 "%d history ops total, %d host drains (%d bytes)",
                 F, min(self.final_rounds), max(self.final_rounds),
                 sum(len(h) for h in histories), self.transfer.drains,
                 self.transfer.host_bytes)
        return histories

    def _waves(self, steps, finished):
        """Advances every live cluster's coroutine to its next scan
        request (servicing quiet probes and bumps in batched
        sub-waves), then answers all scans with ONE vmapped dispatch.
        Repeats until the whole fleet is done (or every live cluster
        has honored a preemption signal — `stopped` but not
        `finished`, so a --resume continues them)."""
        from .. import checkpoint as cp
        F = self.spec.fleet
        preempted = False
        stopped = list(finished)
        ready = [(i, None) for i in range(F) if not stopped[i]]
        while True:
            if self._preempt.is_set() and not preempted:
                preempted = True
                for i in range(F):
                    if not stopped[i]:
                        self.shells[i]._preempt.set()
            scan_reqs: dict = {}
            cscan_reqs: dict = {}
            # one host poll pass per wave: ONE vectorized refresh of the
            # shared columnar session table (per-shell deadline/requeue
            # aggregates become O(1) cache reads for the whole wave),
            # then advancing every ready cluster's coroutine (their
            # generator scheduling runs in here) — booked ONCE for the
            # whole fleet, the O(waves) counter the fleet_stream bench
            # compares against per-cluster standalone polls
            _poll_t0 = time.perf_counter()
            if self._session_table is not None:
                self._session_table.encode_wave()
            while ready:
                quiet_wait, bump_wait = [], {}
                for i, resp in ready:
                    try:
                        req = steps[i].send(resp)
                    except StopIteration as e:
                        finished[i] = stopped[i] = True
                        self.final_rounds[i] = e.value
                        if self.checkpoint_every:
                            # final snapshot: a later checkpoint must
                            # carry this cluster's complete history
                            st = self._states[i]
                            self.snapshot_cluster(
                                i, self.shells[i]._gen_live,
                                st["history"], st["sessions"],
                                st["free"], e.value)
                        continue
                    except cp.Preempted:
                        # the shell wrote its boundary snapshot via
                        # _save_checkpoint before unwinding; it is NOT
                        # finished — a resume picks it back up
                        stopped[i] = True
                        self.final_rounds[i] = self.shells[i]._r_live
                        continue
                    kind = req[0]
                    if kind == "quiet":
                        quiet_wait.append(i)
                    elif kind == "bump":
                        bump_wait[i] = req[1]
                    elif kind == "cscan":
                        cscan_reqs[i] = req[1:]
                    else:
                        scan_reqs[i] = req[1:]
                ready = []
                if bump_wait:
                    self._bump_rows(bump_wait)
                    ready += [(i, None) for i in sorted(bump_wait)]
                if quiet_wait:
                    qs = self._probe_quiet()
                    ready += [(i, bool(qs[i]))
                              for i in sorted(quiet_wait)]
            if scan_reqs or cscan_reqs:
                _poll_t1 = time.perf_counter()
                self.transfer.record_poll(_poll_t1 - _poll_t0)
                self._tel_span("schedule-encode", _poll_t0, _poll_t1,
                               args={"clusters": len(scan_reqs)
                                     + len(cscan_reqs)})
            if scan_reqs:
                results = self._exec_fleet_scan(scan_reqs)
                ready += [(i, results[i]) for i in sorted(scan_reqs)]
            if cscan_reqs:
                results = self._exec_fleet_cscan(cscan_reqs)
                ready += [(i, results[i]) for i in sorted(cscan_reqs)]
            if self.checkpoint_every:
                self._write_checkpoint(finished)
            if preempted and not ready:
                live = [i for i in range(F) if not stopped[i]]
                if not live:
                    # the whole fleet has drained: one final sync
                    # checkpoint covering every cluster's freshest
                    # snapshot (finished clusters that never snapshotted
                    # — no --checkpoint-every — snapshot now, so their
                    # complete histories survive the resume)
                    if not self.checkpoint_every:
                        for i in range(F):
                            if finished[i] and self._states[i] and \
                                    "sessions" in (self._states[i] or {}):
                                st = self._states[i]
                                self.snapshot_cluster(
                                    i, self.shells[i]._gen_live,
                                    st["history"], st["sessions"],
                                    st["free"], self.final_rounds[i])
                        self._seed_initial_snaps()
                    self._write_checkpoint(finished, sync=True)
                    store_dir = self.test.get("store_dir")
                    raise cp.Preempted(
                        min(self.final_rounds[i] for i in range(F)
                            if not finished[i]) if not all(finished)
                        else max(self.final_rounds),
                        store_dir or None)
            if not ready:
                return


def run_fleet_test(test: dict, test_dir: str) -> dict:
    """Executes a `--fleet N` TPU-path test end to end: run the fleet,
    check every cluster with its own checker tree, store per-cluster
    artifacts under `cluster-XXXX/`, and write a fleet-level results
    summary. Routed from `run_tpu_test`."""
    from .. import checkpoint as cp
    from .tpu_runner import TpuRunner
    if "byzantine" in TpuRunner._fault_set(test):
        # per-cluster adversary state (SimState.byz) is not threaded
        # through the vmapped fleet tree yet; reject up front rather
        # than silently running the fleet benign (doc/faults.md)
        raise ValueError(
            "--nemesis byzantine does not compose with --fleet yet: "
            "run the adversary on a standalone cluster (--fleet 1)")
    test["store_dir"] = test_dir
    # the fleet re-derives each cluster's option set from the ORIGINAL
    # options (FleetSpec.cluster_opts), so the runner is built before
    # run_tpu_test's usual nemesis truthiness rewrite
    runner = FleetRunner(test)
    test["nemesis"] = True if test["nemesis_pkg"]["generator"] is not None \
        else None

    resume = None
    if test.get("resume"):
        resume = cp.load(test["resume"])
        cp.check_fingerprint(resume, test)

    try:
        histories = runner.run(resume=resume)
    except BaseException:
        # a flight recorder must land its trace ESPECIALLY when the
        # fleet died unexpectedly (and on graceful preemption)
        if runner.session is not None:
            runner.session.close()
        raise

    F = runner.spec.fleet
    cluster_results = []
    all_valid = True
    try:
        for i, sh in enumerate(runner.shells):
            # give the shell its row back: the per-cluster checkers (device
            # counters, invalid-state counters) read runner.sim
            sh.sim = jax.tree.map(lambda a, i=i: a[i], runner.sim)
            t_i = sh.test
            cdir = os.path.join(test_dir, f"cluster-{i:04d}")
            os.makedirs(cdir, exist_ok=True)
            t_i["store_dir"] = cdir
            t_i["checker"].checkers["net"] = TpuNetStats(sh)
            # per-cluster availability block: same shape as standalone
            # (the per-cluster bit-identity contract covers it)
            from ..checkers.availability import AvailabilityChecker
            t_i["checker"].checkers["availability"] = \
                AvailabilityChecker(sh)
            if sh.pipeline is not None:
                t_i["analysis"] = sh.pipeline
            if runner.session is not None:
                # per-cluster final record: cumulative quantiles over the
                # whole cluster history (== the cluster's PerfChecker block)
                runner.session.flush(
                    histories[i], runner.final_rounds[i], cluster=i,
                    ring=(sh._ring_dict() if sh._final_ring() is not None
                          else None),
                    pipeline=sh.pipeline)
            res_i = t_i["checker"].check(t_i, histories[i], {})
            if sh.pipeline is not None:
                # per-cluster rows only: each pipeline saw exactly its own
                # cluster's history (no fleet-level double counting)
                res_i["analysis-pipeline"] = sh.pipeline.report()
            res_i["cluster"] = i
            res_i["seed"] = t_i.get("seed")
            if runner.spec.sweep == "nemesis":
                res_i["nemesis-seed"] = t_i.get("nemesis_seed")
            if runner.spec.sweep == "capacity":
                res_i["rate"] = t_i.get("rate")
            store.write_history(cdir, histories[i])
            store.write_results(cdir, res_i)
            all_valid = all_valid and bool(res_i.get("valid"))
            cluster_results.append(res_i)
    finally:
        # land the trace even when a per-cluster checker raises
        if runner.session is not None:
            runner.session.close()

    results = {
        "fleet": F,
        "fleet-sweep": runner.spec.sweep,
        "mesh": str(test.get("mesh")) if test.get("mesh") else None,
        "continuous": bool(test.get("continuous")),
        "sessions": runner.sessions_mode,
        "valid": all_valid,
        "clusters": cluster_results,
        "final-rounds": list(runner.final_rounds),
        **runner.transfer.as_dict(),
    }
    # fleet-level checker-lag roll-up (doc/streams.md): the worst lag
    # any cluster's windowed grader recorded — bounded lag means the
    # per-cluster stream graders kept up while the whole fleet ran
    lags = [(c.get("analysis-pipeline") or {}).get("max-lag-rounds")
            for c in cluster_results]
    lags = [v for v in lags if v is not None]
    if lags:
        results["max-checker-lag-rounds"] = max(lags)
    if resume is not None:
        results["resumed-at-round"] = resume["r"]
    # ONE static-audit block for the whole fleet: the vmapped fleet
    # step functions are shared by every cluster, so per-cluster blocks
    # would repeat the identical trace F times
    if test.get("audit", True) and \
            os.environ.get("MAELSTROM_AUDIT") != "0":
        from ..analyze import audit_fleet_runner, cost_fleet_runner
        results["static-audit"] = audit_fleet_runner(
            runner, trace=bool(test.get("audit_trace")))
        # ONE cost block likewise (doc/analyze.md "cost model"):
        # roofline totals for the shared vmapped fleet step functions
        results["cost"] = cost_fleet_runner(
            runner, trace=bool(test.get("audit_trace")))

    store.write_history(test_dir, histories[0] if F == 1 else
                        _merged_history(histories))
    store.write_results(test_dir, results)
    from ..core import DEFAULTS
    store.write_test(test_dir, {k: str(test[k]) for k in DEFAULTS
                                if k in test})
    store.mark_complete(test_dir)
    log.info("Fleet results valid? %s (%d clusters, store: %s)",
             results["valid"], F, test_dir)
    return results


def _merged_history(histories) -> History:
    """A fleet-level history view for the store dir: every cluster's
    ops concatenated with the process tagged `c<cluster>:<process>` so
    rows stay attributable. Checking always runs per cluster — this
    exists only so `serve` has something to render at the top level."""
    merged = History()
    for i, h in enumerate(histories):
        for o in h:
            merged.append_row(o.type, o.f, o.value,
                              f"c{i}:{o.process}", o.time, o.error,
                              o.final)
    return merged
