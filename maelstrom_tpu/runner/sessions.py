"""Client-session bookkeeping (doc/perf.md "columnar client sessions").

Every in-flight client RPC is a *session row*: its pending message id,
timeout deadline, owning worker process, contacted node — and, when a
leader redirect re-issued it, the retry-attempt counter and the
backoff-delayed requeue row. The dispatch loops
(`runner.tpu_runner._loop_steps` / `_loop_steps_continuous`) used to
keep this state in per-runner Python dict/list/set structures; at
`--fleet 512` the per-shell Python scans over them (min-deadline
bounds, timeout expiry, due-retry merges) were the last O(F) host cost
per wave.

Two interchangeable backends, selected by `--sessions`:

  - ``CoroutineSessions`` — the original dict/list/set bookkeeping,
    one instance per runner. Default for standalone runs.
  - ``ColumnarSessions`` — ONE shared table for the whole fleet:
    ``[F, S]`` numpy deadline/validity columns refreshed by a single
    vectorized pass per wave (`encode_wave`), consumed through
    per-shell `SessionView` facades that give the loops the same
    operations. Default under ``--fleet``.

The columnar table is deliberately hybrid: numpy holds exactly the
columns the wave pass reduces over (pending validity + deadline,
requeue validity + due round, retry counters), while the per-EVENT
bookkeeping — mid -> slot lookup, free-slot recycling, the op payload
— lives in per-shell dict/stack mirrors, because a numpy point op
costs microseconds of call overhead where a dict op costs nanoseconds.
The win is the per-WAVE term: `encode_wave` refreshes every shell's
min-deadline / min-due bound in one masked reduction, so a shell that
saw no events answers its scan-bound and expiry queries in O(1)
instead of re-scanning its pending set, and a shell that did see
events falls back to exactly the coroutine backend's Python scan —
never worse, O(1) when quiet.

The contract between the backends is BYTE-IDENTITY: same seed => same
histories, same results, and checkpoint meta in the exact legacy
shapes (`to_meta`), so a checkpoint written by one backend resumes
under the other and the test fingerprint does not change (`sessions`
is deliberately NOT a checkpoint fingerprint key). The
ordering-sensitive operations — timeout-expiry order (dict insertion
order) and due-retry merge order (append order, stable-sorted by due
round) — are reproduced exactly: the mid -> slot dict IS
insertion-ordered, and requeue rows carry an append ``seq``. Pinned
by tests/test_sessions.py and the columnar variants of the PR 12
fleet byte-identity pins.
"""

from __future__ import annotations

import os

import numpy as np

_I64MAX = np.iinfo(np.int64).max
_I32MAX = np.iinfo(np.int32).max

_WAVE_REDUCE = None


def _wave_reduce_fn():
    """The jitted device kernel behind `ColumnarSessions.encode_wave`'s
    device mode (ISSUE 18, PR 17 follow-on): the same two masked min
    reductions as the numpy pass, compiled once and dispatched
    asynchronously — the fleet driver calls encode_wave inside its
    poll-gather span, so on an accelerator the reduction overlaps the
    rest of the poll instead of serializing an O(F*S) host loop.
    int32 in/out: deadlines and due rounds are virtual-round values
    (int32-safe by construction); the int64 table sentinel is restored
    on the way out."""
    global _WAVE_REDUCE
    if _WAVE_REDUCE is None:
        import jax
        import jax.numpy as jnp

        def reduce_(p_mid, p_dl, r_valid, r_due):
            dl = jnp.where(p_mid >= 0, p_dl, _I32MAX).min(axis=1)
            due = jnp.where(r_valid, r_due, _I32MAX).min(axis=1)
            return dl, due

        _WAVE_REDUCE = jax.jit(reduce_)
    return _WAVE_REDUCE


def trunc_exp_bound(base, cap, attempt: int):
    """The truncated-exponential backoff bound shared by every retry
    path: min(cap, base * 2^attempt), with the shift clamped so a long
    redirect chain cannot overflow. `client.RetryPolicy` draws wall
    milliseconds under this bound (full jitter); the runner's
    leader-redirect requeue draws virtual ROUNDS under it from a
    seeded hash (`tpu_runner._backoff_rounds`)."""
    return min(cap, base * (1 << min(int(attempt), 16)))


class CoroutineSessions:
    """The original per-runner session bookkeeping: a pending dict
    (insertion-ordered, mid -> (process, op, node, deadline)), the
    redirect-requeue list, and the retry attempt/open structures —
    wrapped behind the Sessions interface the loops consume so the
    columnar backend can slot in without touching loop code."""

    def __init__(self):
        self._pending: dict[int, tuple] = {}
        self._requeue: list[tuple] = []
        self._attempt: dict[int, int] = {}
        self._open: set[int] = set()

    # --- pending RPCs ---------------------------------------------------

    def register(self, mid: int, process, op, node: int, deadline: int):
        self._pending[mid] = (process, op, node, deadline)

    def absorb_results(self, mids) -> list:
        """Folds a batch of drained reply ids into the table: pops and
        returns the (process, op, node, deadline) entry per mid, None
        for a stale reply (already completed/timed out)."""
        pop = self._pending.pop
        return [pop(m, None) for m in mids]

    def take_expired(self, r: int) -> list:
        """Pops every pending row whose timeout deadline has passed, in
        REGISTRATION order (the dict-insertion order the timeout
        completions have always used). Returns (process, op, node)."""
        expired = [m for m, (_, _, _, dl) in self._pending.items()
                   if dl <= r]
        return [self._pending.pop(m)[:3] for m in expired]

    def min_deadline(self):
        if not self._pending:
            return None
        return min(v[3] for v in self._pending.values())

    def __len__(self):
        return len(self._pending)

    def __bool__(self):
        return bool(self._pending)

    # --- leader-redirect requeue ----------------------------------------

    def requeue(self, due, process, op, node, t, a, b, c):
        self._requeue.append((due, process, op, node, t, a, b, c))

    def has_requeue(self) -> bool:
        return bool(self._requeue)

    def requeue_min_due(self):
        if not self._requeue:
            return None
        return min(rw[0] for rw in self._requeue)

    def take_due_requeues(self, r: int) -> list:
        """Pops rows whose backoff elapsed (due <= r), stable-sorted by
        due round (append order preserved within a round). Returns
        (process, op, node, t, a, b, c) rows ready to inject."""
        due_rows = sorted((rw for rw in self._requeue if rw[0] <= r),
                          key=lambda rw: rw[0])
        if due_rows:
            self._requeue = [rw for rw in self._requeue if rw[0] > r]
        return [rw[1:] for rw in due_rows]

    def drain_requeues(self, r: int) -> list:
        """Pops EVERY row (continuous mode: retries join the scheduled
        stream), due rounds clamped to the current window start, append
        order preserved. Returns (due, process, op, node, t, a, b, c)."""
        rows = [(max(int(rw[0]), r),) + tuple(rw[1:])
                for rw in self._requeue]
        self._requeue = []
        return rows

    # --- retry / redirect chains ----------------------------------------

    def attempt(self, process) -> int:
        return self._attempt.get(process, 0)

    def open_retry(self, process, attempt: int):
        self._attempt[process] = attempt
        self._open.add(process)

    def retry_is_open(self, process) -> bool:
        return process in self._open

    def close_retry(self, process):
        self._attempt.pop(process, None)
        self._open.discard(process)

    # --- checkpoint meta (the legacy shapes, byte-compatible) -----------

    def to_meta(self) -> dict:
        return {"pending": dict(self._pending),
                "requeue": {"rows": list(self._requeue),
                            "attempt": dict(self._attempt),
                            "open": sorted(self._open)}}

    def load_meta(self, pending, requeue):
        self._pending = dict(pending or {})
        rq = requeue or {}
        self._requeue = [tuple(rw) for rw in (rq.get("rows") or [])]
        self._attempt = dict(rq.get("attempt") or {})
        self._open = set(rq.get("open") or ())


class ColumnarSessions:
    """One shared client-session table for a whole fleet: pending-RPC,
    timeout-deadline, retry/backoff, and redirect-requeue state beside
    ``[F, S]`` numpy validity/deadline columns. `encode_wave()` is the
    single vectorized pass per wave — it refreshes every shell's
    min-deadline / min-due aggregates in ONE masked reduction over the
    whole table, so the per-shell scan bounds the loops read each wave
    are cached O(1) lookups for every shell the wave left untouched.
    Shells mutate through `SessionView` facades (`view(i)`); per-event
    point ops (register / absorb / pop) go through per-shell
    insertion-ordered mid -> slot dicts and free-slot stacks — O(1)
    each, matching the coroutine backend op-for-op — while the numpy
    columns shadow just the fields the wave reduction needs. A
    mutation that can lower a cached bound updates it in place; one
    that can raise it (popping the current min) marks only that
    shell's cache row dirty, and a dirty shell recomputes its bound
    with the same Python scan the coroutine backend always pays.

    Capacity starts at 2x concurrency (a worker holds at most one RPC
    in flight) and doubles on demand. Slot payload tuples are
    ``(process, op, node, deadline, mid)`` for pending rows and the
    legacy ``(due, process, op, node, t, a, b, c)`` row plus an append
    ``seq`` for requeues — see the module docstring's byte-identity
    contract."""

    def __init__(self, fleet: int, concurrency: int, cap: int = 0,
                 device_reduce: bool | None = None):
        F = max(int(fleet), 1)
        C = max(int(concurrency), 1)
        S = int(cap) or max(2 * C, 8)
        R = max(C, 8)
        self.F, self.C = F, C
        # device mode (ISSUE 18): run the wave reduction as a jitted
        # kernel instead of host numpy. None = auto (on once the fleet
        # is big enough that the [F, S] host pass shows up in the poll
        # span); MAELSTROM_SESSIONS_DEVICE=0|1 forces either path.
        # Both paths produce identical aggregates — pinned in
        # tests/test_sessions.py.
        if device_reduce is None:
            env = os.environ.get("MAELSTROM_SESSIONS_DEVICE", "")
            device_reduce = env == "1" if env in ("0", "1") else F >= 64
        self.device_reduce = bool(device_reduce)
        # wave-pass columns [F, S]: mid < 0 marks a free slot; ONLY
        # what encode_wave reduces over lives in numpy
        self.p_mid = np.full((F, S), -1, np.int64)
        self.p_dl = np.zeros((F, S), np.int64)
        # requeue columns [F, R]
        self.r_valid = np.zeros((F, R), bool)
        self.r_due = np.zeros((F, R), np.int64)
        # retry columns [F, C]: attempt counter + open-chain flag per
        # worker process (only client processes redirect)
        self.attempt_col = np.zeros((F, C), np.int32)
        self.open_col = np.zeros((F, C), bool)
        # per-event mirrors: _slots[i] is the insertion-ordered
        # mid -> slot dict (it IS the coroutine pending-dict ordering);
        # _pmeta[i][s] the slot payload; _pfree[i] the free-slot stack
        self._slots = [dict() for _ in range(F)]
        self._pmeta = [[None] * S for _ in range(F)]
        self._pfree = [list(range(S - 1, -1, -1)) for _ in range(F)]
        self._rqmeta = [[None] * R for _ in range(F)]
        self._rqfree = [list(range(R - 1, -1, -1)) for _ in range(F)]
        self._rqn = [0] * F
        self._rqseq = [0] * F
        # per-wave aggregate cache (refreshed by encode_wave, consumed
        # by the views' min_deadline/requeue_min_due; exact whenever
        # _cache_ok — lowering mutations update it in place, raising
        # ones dirty only their own shell row)
        self._cache_ok = np.zeros(F, bool)
        self._min_dl = np.full(F, _I64MAX, np.int64)
        self._min_due = np.full(F, _I64MAX, np.int64)

    def view(self, i: int) -> "SessionView":
        return SessionView(self, i)

    # --- the per-wave table pass ----------------------------------------

    def encode_wave(self):
        """THE single vectorized pass per wave: one masked reduction
        over the whole [F, S] table refreshes every shell's
        min-deadline / min-due-retry aggregates at once. The fleet
        driver calls it at each wave start (inside the
        `record_poll`/schedule-encode span, so the win is visible in
        the flight recorder); shells the wave leaves untouched then
        answer their scan bounds from the cache instead of scanning
        their pending sets."""
        if self.device_reduce:
            # the jitted segment reduction (ISSUE 18): int32 views in,
            # async dispatch, int64 sentinel restored on the way out so
            # the cached aggregates are bit-identical to the numpy
            # path's
            dl, due = _wave_reduce_fn()(
                self.p_mid.astype(np.int32),
                np.minimum(self.p_dl, _I32MAX).astype(np.int32),
                self.r_valid,
                np.minimum(self.r_due, _I32MAX).astype(np.int32))
            dl = np.asarray(dl).astype(np.int64)
            due = np.asarray(due).astype(np.int64)
            dl[dl == _I32MAX] = _I64MAX
            due[due == _I32MAX] = _I64MAX
            self._min_dl, self._min_due = dl, due
            self._cache_ok[:] = True
            return
        pvalid = self.p_mid >= 0
        self._min_dl = np.where(pvalid, self.p_dl, _I64MAX).min(axis=1)
        self._min_due = np.where(self.r_valid, self.r_due,
                                 _I64MAX).min(axis=1)
        self._cache_ok[:] = True

    def _refresh_shell(self, i: int):
        # the dirty-shell fallback: the same Python scans the
        # coroutine backend pays every wave, here only after a
        # mutation raised a bound
        meta = self._pmeta[i]
        self._min_dl[i] = min(
            (meta[s][3] for s in self._slots[i].values()),
            default=_I64MAX)
        if self._rqn[i]:
            self._min_due[i] = min(m[0] for m in self._rqmeta[i]
                                   if m is not None)
        else:
            self._min_due[i] = _I64MAX
        self._cache_ok[i] = True

    # --- pending RPCs ---------------------------------------------------

    def _grow_pending(self):
        F, S = self.p_mid.shape
        self.p_mid = np.concatenate(
            [self.p_mid, np.full((F, S), -1, np.int64)], axis=1)
        self.p_dl = np.concatenate(
            [self.p_dl, np.zeros((F, S), np.int64)], axis=1)
        grown = range(2 * S - 1, S - 1, -1)
        for i in range(F):
            self._pmeta[i].extend([None] * S)
            self._pfree[i].extend(grown)

    def register(self, i, mid, process, op, node, deadline):
        free = self._pfree[i]
        if not free:
            self._grow_pending()
            free = self._pfree[i]
        s = free.pop()
        mid = int(mid)
        deadline = int(deadline)
        self.p_mid[i, s] = mid
        self.p_dl[i, s] = deadline
        self._pmeta[i][s] = (process, op, node, deadline, mid)
        self._slots[i][mid] = s
        if self._cache_ok[i] and deadline < self._min_dl[i]:
            self._min_dl[i] = deadline

    def absorb_results(self, i, mids) -> list:
        """Batch-pop of a wave's drained reply ids for shell i: each
        pop is one dict op + a column clear. None per stale reply."""
        slots = self._slots[i]
        meta = self._pmeta[i]
        free = self._pfree[i]
        out = []
        for m in mids:
            s = slots.pop(int(m), -1)
            if s < 0:
                out.append(None)
                continue
            mt = meta[s]
            meta[s] = None
            self.p_mid[i, s] = -1
            free.append(s)
            if self._cache_ok[i] and mt[3] <= self._min_dl[i]:
                self._cache_ok[i] = False
            out.append(mt[:4])
        return out

    def take_expired(self, i, r) -> list:
        slots = self._slots[i]
        if not slots:
            return []
        if self._cache_ok[i] and r < self._min_dl[i]:
            # the wave-pass bound says nothing expired: O(1), no scan
            return []
        meta = self._pmeta[i]
        expired = [s for s in slots.values() if meta[s][3] <= r]
        if not expired:
            # the bound was stale-low; rebuild it so the following
            # waves are O(1) again
            self._refresh_shell(i)
            return []
        out = []
        free = self._pfree[i]
        for s in expired:          # dict order == registration order
            mt = meta[s]
            out.append(mt[:3])
            del slots[mt[4]]
            meta[s] = None
            self.p_mid[i, s] = -1
            free.append(s)
        self._cache_ok[i] = False
        return out

    def min_deadline(self, i):
        if not self._slots[i]:
            return None
        if not self._cache_ok[i]:
            self._refresh_shell(i)
        return int(self._min_dl[i])

    # --- leader-redirect requeue ----------------------------------------

    def _grow_requeue(self):
        F, R = self.r_valid.shape
        self.r_valid = np.concatenate(
            [self.r_valid, np.zeros((F, R), bool)], axis=1)
        self.r_due = np.concatenate(
            [self.r_due, np.zeros((F, R), np.int64)], axis=1)
        grown = range(2 * R - 1, R - 1, -1)
        for i in range(F):
            self._rqmeta[i].extend([None] * R)
            self._rqfree[i].extend(grown)

    def requeue(self, i, due, process, op, node, t, a, b, c):
        free = self._rqfree[i]
        if not free:
            self._grow_requeue()
            free = self._rqfree[i]
        s = free.pop()
        due = int(due)
        self.r_valid[i, s] = True
        self.r_due[i, s] = due
        self._rqmeta[i][s] = (due, process, op, node, t, a, b, c,
                              self._rqseq[i])
        self._rqseq[i] += 1
        self._rqn[i] += 1
        if self._cache_ok[i] and due < self._min_due[i]:
            self._min_due[i] = due

    def has_requeue(self, i) -> bool:
        return self._rqn[i] > 0

    def requeue_min_due(self, i):
        if not self._rqn[i]:
            return None
        if not self._cache_ok[i]:
            self._refresh_shell(i)
        return int(self._min_due[i])

    def _rq_pop(self, i, s):
        self.r_valid[i, s] = False
        self._rqmeta[i][s] = None
        self._rqfree[i].append(s)
        self._rqn[i] -= 1

    def take_due_requeues(self, i, r) -> list:
        if not self._rqn[i]:
            return []
        if self._cache_ok[i] and r < self._min_due[i]:
            return []
        live = [(s, m) for s, m in enumerate(self._rqmeta[i])
                if m is not None and m[0] <= r]
        if not live:
            self._refresh_shell(i)
            return []
        # stable by due round, append (seq) order within a round —
        # exactly `sorted(rows, key=due)` over the legacy list
        live.sort(key=lambda sm: (sm[1][0], sm[1][8]))
        for s, _ in live:
            self._rq_pop(i, s)
        self._cache_ok[i] = False
        return [m[1:8] for _, m in live]

    def drain_requeues(self, i, r) -> list:
        if not self._rqn[i]:
            return []
        live = [(s, m) for s, m in enumerate(self._rqmeta[i])
                if m is not None]
        live.sort(key=lambda sm: sm[1][8])      # append order
        for s, _ in live:
            self._rq_pop(i, s)
        self._cache_ok[i] = False
        return [(max(m[0], r),) + m[1:8] for _, m in live]

    # --- retry / redirect chains ----------------------------------------

    def _retry_slot(self, process) -> bool:
        # retry state only ever attaches to client processes (int
        # worker ids < C); the nemesis completes through the same
        # `_complete` path with a string id — no column for it, and
        # the coroutine backend's dict silently holds nothing either
        return isinstance(process, int) and 0 <= process < self.C

    def attempt(self, i, process) -> int:
        if not self._retry_slot(process):
            return 0
        return int(self.attempt_col[i, process])

    def open_retry(self, i, process, attempt):
        self.attempt_col[i, process] = attempt
        self.open_col[i, process] = True

    def retry_is_open(self, i, process) -> bool:
        return self._retry_slot(process) \
            and bool(self.open_col[i, process])

    def close_retry(self, i, process):
        if self._retry_slot(process) and self.open_col[i, process]:
            self.attempt_col[i, process] = 0
            self.open_col[i, process] = False

    # --- checkpoint meta (the legacy shapes, byte-compatible) -----------

    def to_meta(self, i) -> dict:
        meta = self._pmeta[i]
        pending = {mid: meta[s][:4]
                   for mid, s in self._slots[i].items()}
        live = sorted((m for m in self._rqmeta[i] if m is not None),
                      key=lambda m: m[8])
        open_ = [int(p) for p in np.nonzero(self.open_col[i])[0]]
        return {"pending": pending,
                "requeue": {"rows": [m[:8] for m in live],
                            "attempt": {p: int(self.attempt_col[i, p])
                                        for p in open_},
                            "open": open_}}

    def load_meta(self, i, pending, requeue):
        # clear shell i, then replay the legacy meta in its recorded
        # order so the dict/seq mirrors reproduce the original
        # insertion order
        meta = self._pmeta[i]
        free = self._pfree[i]
        for s in self._slots[i].values():
            self.p_mid[i, s] = -1
            meta[s] = None
            free.append(s)
        self._slots[i].clear()
        for s, m in enumerate(self._rqmeta[i]):
            if m is not None:
                self._rq_pop(i, s)
        self.attempt_col[i] = 0
        self.open_col[i] = False
        for mid, (process, op, node, dl) in (pending or {}).items():
            self.register(i, mid, process, op, node, dl)
        rq = requeue or {}
        for rw in (rq.get("rows") or []):
            due, process, op, node, t, a, b, c = rw
            self.requeue(i, due, process, op, node, t, a, b, c)
        att = dict(rq.get("attempt") or {})
        for p in (rq.get("open") or ()):
            self.open_col[i, p] = True
        for p, n in att.items():
            self.attempt_col[i, p] = n
        self._cache_ok[i] = False


class SessionView:
    """One shell's facade over the shared `ColumnarSessions` table:
    the same operations `CoroutineSessions` exposes, delegated to the
    table with this shell's row index. The dispatch loops hold one of
    these (or a CoroutineSessions) and never know which."""

    __slots__ = ("table", "i")

    def __init__(self, table: ColumnarSessions, i: int):
        self.table, self.i = table, i

    def register(self, mid, process, op, node, deadline):
        self.table.register(self.i, mid, process, op, node, deadline)

    def absorb_results(self, mids):
        return self.table.absorb_results(self.i, mids)

    def take_expired(self, r):
        return self.table.take_expired(self.i, r)

    def min_deadline(self):
        return self.table.min_deadline(self.i)

    def __len__(self):
        return len(self.table._slots[self.i])

    def __bool__(self):
        return bool(self.table._slots[self.i])

    def requeue(self, due, process, op, node, t, a, b, c):
        self.table.requeue(self.i, due, process, op, node, t, a, b, c)

    def has_requeue(self):
        return self.table.has_requeue(self.i)

    def requeue_min_due(self):
        return self.table.requeue_min_due(self.i)

    def take_due_requeues(self, r):
        return self.table.take_due_requeues(self.i, r)

    def drain_requeues(self, r):
        return self.table.drain_requeues(self.i, r)

    def attempt(self, process):
        return self.table.attempt(self.i, process)

    def open_retry(self, process, attempt):
        self.table.open_retry(self.i, process, attempt)

    def retry_is_open(self, process):
        return self.table.retry_is_open(self.i, process)

    def close_retry(self, process):
        self.table.close_retry(self.i, process)

    def to_meta(self):
        return self.table.to_meta(self.i)

    def load_meta(self, pending, requeue):
        self.table.load_meta(self.i, pending, requeue)


SESSION_MODES = ("coroutine", "columnar")


def resolve_mode(test: dict) -> str:
    """The effective --sessions mode for this test: an explicit choice
    sticks; None = auto (columnar for a fleet, coroutine standalone —
    the backends are byte-identical, so the default just picks the
    cheaper host path per topology)."""
    mode = test.get("sessions")
    if mode is None:
        return ("columnar" if int(test.get("fleet") or 1) > 1
                else "coroutine")
    mode = str(mode)
    if mode not in SESSION_MODES:
        raise ValueError(f"--sessions {mode!r}: expected one of "
                         f"{SESSION_MODES}")
    return mode


def make_sessions(test: dict, concurrency: int):
    """Builds a standalone runner's session backend (the fleet driver
    instead shares ONE ColumnarSessions table across its shells and
    hands each a view — see FleetRunner)."""
    if resolve_mode(test) == "columnar":
        return ColumnarSessions(1, concurrency).view(0)
    return CoroutineSessions()
