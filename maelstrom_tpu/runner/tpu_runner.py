"""The TPU-path test runner: virtual-time lockstep generator interpreter.

Replaces the host path's thread-per-client real-time loop
(`runner/host_runner.py`) with a synchronous round loop over the jitted
simulation (`maelstrom_tpu.sim`): each iteration polls the (pure, virtual-
time) generators for client ops, encodes them into the injection batch, runs
one compiled network+nodes round, decodes client replies into history
completions, applies timeouts, and lets the nemesis rewrite fault masks at
round boundaries.

Time is virtual: 1 round = `ms_per_round` milliseconds (default 1), so the
same generator combinators (stagger/time-limit/sleep) and the same checkers
(perf quantiles, stable-latency) read it exactly like the host path's
wall-clock nanoseconds. Quiescent stretches — empty flight pool, quiescent
node program, no outstanding RPCs — are fast-forwarded without dispatching
rounds, so a 10-virtual-second test with rate 5 costs ~hundreds of
dispatches, not 10,000.

Production scale-out (`--mesh dp,sp`): the whole hot-loop state tree is
sharded over a ("dp", "sp") device mesh (`parallel.sim_shardings`) and
the compiled scan runs with those shardings pinned and its carry donated,
so node/pool/channel/durable arrays live distributed across chips and are
reused in place across dispatches. Extraction stays off the hot path:
client replies and journal io accumulate in the scan's device-resident
rings and reach the host as one batched drain per dispatch
(`TransferStats` books every drain; see doc/perf.md).
"""

from __future__ import annotations

import logging
import threading
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import generators as g
from .. import store
from ..checkers import Checker
from ..errors import ERROR_REGISTRY
from ..history import History
from ..nemesis import NemesisDecisions
from ..nemesis import grudge_matrix as _grudge_matrix
from ..net import tpu as T
from ..nodes import HOST, EncodeCapacityError, Intern, get_program
from ..sim import SimState, dealias, donation_enabled, make_sim

log = logging.getLogger("maelstrom.tpu")


def _wants_analysis(checker) -> bool:
    """True when the test's checker tree contains a consumer of the
    overlapped pipeline's partitions (`consumes_analysis`); other
    workloads skip the background pairing/partitioning entirely."""
    if checker is None:
        return False
    if getattr(checker, "consumes_analysis", False):
        return True
    subs = getattr(checker, "checkers", None)
    if isinstance(subs, dict):
        return any(_wants_analysis(c) for c in subs.values())
    return False


def _stream_observers(checker, test) -> dict:
    """{name: observer} from every checker in the tree that registers
    an incremental stream observer (doc/streams.md) — the pipeline
    feeds them completed pairs and closes a grading window per drained
    segment."""
    out: dict = {}

    def walk(c):
        if c is None:
            return
        mk = getattr(c, "make_stream_observer", None)
        if mk is not None:
            ob = mk(test)
            if ob is not None:
                out[getattr(c, "name", type(c).__name__)] = ob
        subs = getattr(c, "checkers", None)
        if isinstance(subs, dict):
            for sub in subs.values():
                walk(sub)

    walk(checker)
    return out




class TpuCombinedNemesis(NemesisDecisions):
    """Applies the combined fault packages to the TPU network's mask
    vectors (the device analogue of `net.clj:108-121` plus process
    control): partitions install directional block matrices, kill/pause
    set per-node down/paused masks, duplicate sets the amplification
    probability, and restart rebuilds killed nodes from the durable
    store via `NodeProgram.restore`. Fault decisions come from the
    per-package seeded streams shared with the host path
    (`NemesisDecisions`), so both paths draw identical schedules."""

    def __init__(self, runner, nodes, seed=0, targets=None, attacks=None,
                 byz_rate=1.0):
        super().__init__(nodes, seed, targets=targets, attacks=attacks)
        self.runner = runner
        self.byz_rate = float(byz_rate)
        self.killed: list = []
        self.paused_nodes: list = []
        self._idx = {n: i for i, n in enumerate(self.nodes)}

    def _mask(self, targets):
        m = np.zeros(len(self.nodes), bool)
        for t in targets:
            m[self._idx[t]] = True
        return m

    def invoke(self, op):
        # All mask surgery routes through runner._net_surgery(net -> net')
        # so the SAME executor serves the standalone runner (which swaps
        # its own sim.net) and one cluster of a fleet (whose shell
        # targets its row of the batched fleet tree).
        f = op["f"]
        r = self.runner
        if f == "start-partition":
            name, grudge = self.next_grudge()
            groups, matrix = _grudge_matrix(self.nodes, grudge)
            r._net_surgery(
                lambda net: T.partition_grudge(net, groups, matrix))
            return {**op, "type": "info", "value": name}
        if f == "stop-partition":
            r._net_surgery(T.heal)
            return {**op, "type": "info", "value": "healed"}
        if f == "start-kill":
            # targets come straight from the kill decision stream — no
            # cross-package filtering (see CombinedNemesis): the op's
            # value depends only on this package's RNG. A node both
            # paused and killed is simply down until both faults lift.
            targets = self.next_kill_targets()
            self.killed = sorted(set(self.killed) | set(targets))
            mask = self._mask(self.killed)
            r._net_surgery(lambda net: T.set_down(net, mask))
            r._state_cache = None
            return {**op, "type": "info", "value": f"killed {targets}"}
        if f == "stop-kill":
            restarted, self.killed = self.killed, []
            r.restart_nodes(self._mask(restarted))
            return {**op, "type": "info",
                    "value": f"restarted {restarted}"}
        if f == "start-pause":
            targets = self.next_pause_targets()
            self.paused_nodes = sorted(set(self.paused_nodes)
                                       | set(targets))
            mask = self._mask(self.paused_nodes)
            r._net_surgery(lambda net: T.set_paused(net, mask))
            return {**op, "type": "info", "value": f"paused {targets}"}
        if f == "stop-pause":
            resumed, self.paused_nodes = self.paused_nodes, []
            mask = self._mask([])
            r._net_surgery(lambda net: T.set_paused(net, mask))
            return {**op, "type": "info", "value": f"resumed {resumed}"}
        if f == "start-duplicate":
            p = self.next_dup_prob()
            r._net_surgery(lambda net: T.set_duplication(net, p))
            return {**op, "type": "info", "value": f"duplicate p={p}"}
        if f == "stop-duplicate":
            r._net_surgery(lambda net: T.set_duplication(net, 0.0))
            return {**op, "type": "info", "value": "duplicate off"}
        if f == "start-weather":
            name, p, scale = self.next_weather()
            r._net_surgery(lambda net: T.set_weather(net, p, scale))
            return {**op, "type": "info",
                    "value": f"weather {name} p_loss={p} scale={scale}"}
        if f == "stop-weather":
            # restore the run's CONFIGURED baseline (--p-loss /
            # --latency-scale), not hardcoded zeros: the final heal must
            # hand the checkers exactly the network the test asked for
            base_p = float(r.test.get("p_loss") or 0.0)
            base_s = float(r.test.get("latency_scale") or 1.0)
            r._net_surgery(lambda net: T.set_weather(net, base_p, base_s))
            return {**op, "type": "info", "value": "weather cleared"}
        if f == "start-byzantine":
            # same decision stream as the host executor, so the info
            # op's value string is identical per seed (the parity pin)
            from .. import byzantine as BZ
            attack, culprit, delta = self.next_byz_plan()
            ci, rate = self._idx[culprit], self.byz_rate
            r._byz_surgery(
                lambda byz: BZ.start_state(byz, attack, ci, delta, rate))
            return {**op, "type": "info",
                    "value": f"byzantine {attack} culprit={culprit}"}
        if f == "stop-byzantine":
            from .. import byzantine as BZ
            r._byz_surgery(BZ.stop_state)
            return {**op, "type": "info", "value": "byzantine cleared"}
        raise ValueError(f"unknown nemesis op {f!r}")


# Backwards-compatible name (the partition-only executor grew into the
# combined one; partition ops behave identically)
TpuPartitionNemesis = TpuCombinedNemesis


class TpuNetStats(Checker):
    """Net statistics from the on-device counters, shaped like the journal
    fold output (`net/checker.clj:28-41`). Unique msg-count equals the send
    count because the TPU network assigns globally unique ids."""

    name = "net"

    def __init__(self, runner):
        self.runner = runner

    def check(self, test, history, opts=None):
        c = T.stats_dict(self.runner.sim.net,
                         transfer=getattr(self.runner, "transfer", None))
        op_count = sum(1 for o in history
                       if o.type == "invoke" and o.process != "nemesis")
        groups = {
            "all": {"send-count": c["sent_all"], "recv-count": c["recv_all"],
                    "msg-count": c["sent_all"]},
            "servers": {"send-count": c["sent_servers"],
                        "recv-count": c["recv_servers"],
                        "msg-count": c["sent_servers"]},
            "clients": {
                "send-count": c["sent_all"] - c["sent_servers"],
                "recv-count": c["recv_all"] - c["recv_servers"],
                "msg-count": c["sent_all"] - c["sent_servers"]},
        }
        if op_count:
            groups["all"]["msgs-per-op"] = (
                groups["all"]["msg-count"] / op_count)
            groups["servers"]["msgs-per-op"] = (
                groups["servers"]["msg-count"] / op_count)
        out = dict(groups)
        if getattr(getattr(self.runner, "cfg", None), "unit_words", ()):
            # batched payload rows: logical client-op units transported,
            # next to the raw message counters (ops-per-message is the
            # batching win the bench records; doc/perf.md)
            out["sent-units"] = c["sent_units"]
            out["recv-units"] = c["recv_units"]
            if c["recv_all"]:
                out["units-per-msg"] = round(
                    c["recv_units"] / c["recv_all"], 3)
        out["lost"] = c["lost"]
        out["dropped-partition"] = c["dropped_partition"]
        out["dropped-overflow"] = c["dropped_overflow"]
        out["dropped-down"] = c["dropped_down"]
        out["duplicated"] = c["duplicated"]
        # per-RPC-type send breakdown (the reference derives this from
        # journal folds; the device counter survives bench scale where
        # journal rows don't). Wire codes name themselves through the
        # program module's T_* constants.
        by_type = c.get("sent_by_type") or {}
        if by_type:
            import sys

            from .. import nodes as _nodes_mod
            mod = sys.modules.get(type(self.runner.program).__module__)
            names = _nodes_mod.wire_name_table(mod)
            out["send-count-by-type"] = {
                names.get(t, f"type-{t}"): n
                for t, n in sorted(by_type.items())}
        ch = self.runner.sim.channels
        overwrites = 0
        lat_clipped = 0
        if ch is not None:
            overwrites = int(jax.device_get(ch.overwrites))
            out["channel-overwrites"] = overwrites
            lat_clipped = int(jax.device_get(ch.lat_clipped))
            out["latency-clipped"] = lat_clipped
        journal = self.runner.journal
        store_dir = test.get("store_dir")
        if journal is not None and store_dir:
            try:
                import os
                from ..viz.lamport import plot_lamport
                plot_lamport(journal, os.path.join(store_dir,
                                                   "messages.svg"))
            except Exception as e:  # viz must never fail the test
                out["viz-error"] = repr(e)
        # silently destroyed messages invalidate the run: pool overflow
        # always; ring overwrites are a bounded-channel drop of the same
        # class (legal only if a workload opts in)
        tolerated = (test.get("allow_channel_overwrites")
                     or getattr(self.runner.program,
                                "tolerates_channel_overwrites", False))
        # clipped latency draws silently shorten delays — a distortion of
        # the latency model the same class as an overwrite drop; gate it
        # unless the test (or program) explicitly accepts it
        clip_tolerated = (test.get("allow_latency_clipping")
                          or getattr(self.runner.program,
                                     "tolerates_latency_clipping", False))
        ok = (c["dropped_overflow"] == 0
              and (overwrites == 0 or tolerated)
              and (lat_clipped == 0 or clip_tolerated))
        # program-state capacity failures (e.g. raft log-overflow) are the
        # same class of silent degradation as pool overflow
        for name, arr in self.runner.program.invalid_counters(
                self.runner.sim.nodes).items():
            n_bad = int(np.sum(jax.device_get(arr)))
            out[name] = n_bad
            ok = ok and n_bad == 0
        # flight-recorder ring (doc/observability.md): the drained
        # device telemetry block, next to the raw counters it refines.
        # Off by default — classic results keep their exact shape.
        if getattr(self.runner, "telemetry_rings", False):
            try:
                from .. import telemetry as TM
                ring = self.runner._final_ring()
                if ring is not None:
                    out["telemetry"] = TM.ring_dict(
                        ring,
                        role_labels=TM.role_names(self.runner.program))
            except Exception as e:  # observational: never fail the run
                out["telemetry-error"] = repr(e)
        # host-transfer accounting: drains must stay O(host-relevant
        # rounds) — one batched fetch per dispatch — not O(simulated
        # rounds); a regression here is a performance bug even when the
        # run is semantically valid
        tr = getattr(self.runner, "transfer", None)
        if tr is not None:
            out.update(tr.as_dict())
        if journal is not None:
            out["journal"] = journal.counts()
        # static-audit self-report (doc/analyze.md): rule counts from
        # the trace-time hazard audit of this run's own configuration.
        # Purely informational — the CI gate (`maelstrom_tpu analyze`)
        # owns failing on new findings, a production run only REPORTS
        # them — so it never flips `valid`. MAELSTROM_AUDIT=0 or
        # `audit: False` disables the block; `audit_trace` (on for CLI
        # runs) adds the per-config jaxpr trace of round_fn/scan_fn.
        import os as _os
        if test.get("audit", True) and \
                _os.environ.get("MAELSTROM_AUDIT") != "0":
            from ..analyze import audit_runner, cost_runner
            out["static-audit"] = audit_runner(
                self.runner, trace=bool(test.get("audit_trace")))
            # cost self-report (doc/analyze.md "cost model"): static
            # roofline totals + predicted rounds/s for this run's own
            # step functions. Same contract as static-audit: memoized
            # per config, informational, never flips `valid`.
            out["cost"] = cost_runner(
                self.runner, trace=bool(test.get("audit_trace")))
        out["valid"] = bool(ok)
        return out


class TpuRunner:
    def __init__(self, test: dict):
        self.test = test
        nodes = test["nodes"]
        self.nodes = nodes
        spec = str(test["node"]).split(":", 1)[1]   # "tpu:<program>"
        self.concurrency = int(test.get("concurrency") or len(nodes))
        self.ms_per_round = float(test.get("ms_per_round", 1.0))
        test.setdefault("ms_per_round", self.ms_per_round)
        self.program = get_program(spec, test, nodes)
        lat = test.get("latency") or {}
        mean_rounds = float(lat.get("mean", 0)) / self.ms_per_round
        n = len(nodes)
        if getattr(self.program, "is_edge", False):
            # edge programs route node<->node traffic over static channels;
            # the pool only ever holds in-flight *client requests*, so a
            # tight pool keeps the per-round argsort cheap
            default_pool = max(8 * self.concurrency, 64)
        else:
            default_pool = max(4096, 4 * n * self.program.outbox_cap)
        pool_cap = int(test.get("pool_cap") or default_pool)
        # fault capabilities are static config: runs without a given
        # fault package pay nothing for its round-path machinery
        faults = self._fault_set(test)
        self.faults = faults
        # flight recorder (doc/observability.md): --telemetry DIR turns
        # on the device metric rings (a static cfg capability — off
        # costs nothing) and, for top-level runs, a TelemetrySession
        # (spans + telemetry.jsonl), attached by run_tpu_test /
        # FleetRunner AFTER construction. Rings never change histories.
        from .. import telemetry as TM
        self.telemetry_rings = TM.enabled(test)
        self.telemetry = None
        self._ring_host = None
        self.cfg = T.NetConfig(
            n_nodes=n, n_clients=self.concurrency, pool_cap=pool_cap,
            inbox_cap=self.program.inbox_cap,
            client_cap=max(2 * self.concurrency, 8),
            latency_mean_rounds=mean_rounds,
            latency_dist=lat.get("dist", "constant"),
            ms_per_round=self.ms_per_round,
            partition_groups=n if "partition" in faults else 1,
            enable_stall=bool({"kill", "pause"} & faults),
            enable_duplication="duplicate" in faults,
            enable_byz="byzantine" in faults,
            # batched payload rows (doc/perf.md): programs whose wire
            # records carry multiple client ops per message declare the
            # (type, count-word) mapping; the net books units next to
            # raw message counts
            unit_words=tuple(getattr(self.program, "unit_words", ())
                             or ()),
            telemetry=self.telemetry_rings,
            telemetry_roles=(TM.role_bounds(self.program)
                             if self.telemetry_rings else ()))
        # continuous generator mode (doc/streams.md): client ops are
        # pre-scheduled onto their offered-rate rounds and injected
        # INSIDE the compiled scan window (the open-world stream), so
        # traffic lands while nemesis faults are live mid-window and a
        # whole offered-rate stretch costs one dispatch instead of one
        # per op. Same-seed runs are byte-identical, plain and --mesh.
        self.continuous = bool(test.get("continuous"))
        # per-message journal rows: on by default for small clusters, where
        # Lamport diagrams are readable and the per-round device pull is
        # cheap; large runs keep only the on-device counters. Tracking is
        # keyed off the config (not an attached journal object) so
        # assigning `runner.journal` after construction still pairs
        # exactly (the net's journal is only snapshotted here, not
        # re-read later). Continuous mode keeps only the counters: the
        # journaled scan variant is a per-round debugging aid and the
        # whole point of the stream window is to not stop per round.
        self.journal_rows = bool(test.get("journal_rows", n <= 64)) \
            and not self.continuous
        self.journal = (getattr(test.get("net"), "journal", None)
                        if self.journal_rows else None)
        # dealias: the runner's compiled dispatches donate their sim
        # carry, and a donated tree may not contain one buffer twice
        # (skipped when donation is off — it's a one-time full-tree copy)
        self.sim = self._build_sim()
        # host-transfer accounting: every device->host drain is booked
        # here, so tests and benches can assert extraction stays off the
        # hot path (drains ~ dispatches, not ~ simulated rounds)
        from ..checkers.netstats import TransferStats
        self.transfer = TransferStats()
        # overlapped analysis (--check-workers / --no-overlap): drained
        # history segments stream to a background worker that pairs,
        # partitions, and screens while the device runs the next
        # stretch; the checkers then consume the prebuilt partitions.
        # Purely an accelerator — never changes histories or verdicts.
        self.no_overlap = bool(test.get("no_overlap"))
        cw = test.get("check_workers")
        self.check_workers = 1 if cw is None else int(cw)
        self.pipeline = None
        self._fed_upto = 0
        # --mesh dp,sp: shard the whole hot-loop state tree — node
        # state, flight pool, edge channels, inject buffers, reply/io
        # rings, nemesis masks (down/paused/block matrices), freeze
        # masks, and the durable store — across a ("dp", "sp") device
        # mesh. The scan/round fns are jitted with these shardings
        # pinned, so GSPMD partitions the round body (collectives over
        # ICI/DCN) while host-built arrays (nemesis surgery, fresh
        # inject batches) are re-placed automatically at each dispatch.
        # Sharding changes placement, never semantics: same-seed mesh
        # runs are bit-identical to single-chip runs (pinned by
        # tests/test_sharded_runner.py and the MULTICHIP dryruns).
        self.mesh = None
        self._shardings = None
        mesh_spec = test.get("mesh")
        if mesh_spec:
            from .. import parallel
            self.mesh = parallel.mesh_from_spec(mesh_spec)
            if self.mesh.shape["dp"] != 1:
                # dp shards the fleet's CLUSTER axis; a standalone
                # TpuRunner simulates exactly one cluster, so dp > 1
                # would merely replicate state over dp — and GSPMD's
                # scatter partitioning is not value-safe for replicated
                # scatter-set operands (observed: per-replica
                # contributions combined additively, doubling inbox
                # rows). The fleet runner (--fleet N with N a multiple
                # of dp) owns the dp axis.
                raise ValueError(
                    f"--mesh {mesh_spec}: this run simulates one "
                    f"cluster, so the cluster axis must be 1 (use "
                    f"--mesh 1,{self.mesh.size}, or give dp a fleet to "
                    f"shard: --fleet N --mesh "
                    f"{self.mesh.shape['dp']},"
                    f"{self.mesh.shape['sp']} runs N independent "
                    f"cluster instances, N % dp == 0)")
            inject_ex = T.Msgs.empty(max(self.concurrency, 1))
            self._shardings = parallel.scan_shardings(
                self.mesh, self.sim, inject_ex)
            self.sim = jax.device_put(self.sim, self._shardings[0])
            log.info("mesh mode: dp=%d sp=%d over %d devices",
                     self.mesh.shape["dp"], self.mesh.shape["sp"],
                     self.mesh.size)
        self._scan_fn = None         # built lazily
        self._scan_journal_fn = None  # journaled variant (io-collecting)
        self._cscan_fn = None        # continuous variant (sched inject)
        self._pack_buf = None         # single-array packers (remote
        self._pack_replies = None     # backends pay a RT per array)
        self._pack_creplies = None    # continuous drain (replies + mids)
        self._quiet_fn = None
        self.max_scan = int(test.get("max_scan", 65536))
        self.journal_scan_cap = int(test.get("journal_scan_cap", 256))
        self.reply_log_cap = int(test.get("reply_log_cap", 256))
        # collect-replies mode: scans cross whole reply-bearing stretches
        # (the per-reply early exit costs ~3 dispatches per op; on remote
        # backends each dispatch is a ~160 ms round trip). Requires reply
        # completions not to read mutable device state: values
        # materialized via read_state would otherwise reflect
        # end-of-stretch state instead of reply-round state. Committed
        # raft log prefixes are immutable, so txn opts back in via
        # state_reads_final.
        self.collect_replies = bool(test.get("collect_replies", True)) and (
            not self.program.needs_state_reads
            or getattr(self.program, "state_reads_final", False)
            # reply payloads are snapshotted at the reply's own round
            # inside the scan, so crossing reply-bearing stretches can
            # no longer skew completion values
            or getattr(self.program, "reply_payload_words", 0) > 0)
        if self.continuous and not self.collect_replies:
            # the stream window crosses reply-bearing stretches by
            # construction; a program whose completions read mutable
            # end-of-stretch state would complete with wrong values
            raise ValueError(
                f"--continuous: program {self.program.name!r} cannot "
                f"cross reply-bearing stretches (needs_state_reads "
                f"without state_reads_final or a reply payload); run it "
                f"round-synchronous")
        # stream stride (doc/streams.md): the continuous window length
        # in rounds. Windows cross replies; the stride bounds how long a
        # freed worker waits before the generator is polled again (and
        # with it the emission delay of a backlogged offered op)
        self.continuous_stride = max(1, int(
            float(test.get("continuous_window_ms", 250.0))
            / self.ms_per_round))
        self.intern = Intern()
        self.timeout_rounds = max(
            int(float(test.get("timeout_ms", 5000)) / self.ms_per_round), 10)
        self.node_names = list(nodes) + [f"c{i}"
                                         for i in range(self.concurrency)]
        self._dispatches = 0
        self._state_cache = None
        self.final_round = 0
        # checkpoint/resume (no reference equivalent; SURVEY.md section 5.4)
        ckpt_s = test.get("checkpoint_every")
        self.checkpoint_every_rounds = (
            int(float(ckpt_s) * 1000.0 / self.ms_per_round)
            if ckpt_s else None)
        # async crash-consistent checkpointing (doc/checkpoint.md): the
        # main thread snapshots (device pull + a pickle of the mutable
        # host state) and a background writer lands the file, so saves
        # stay off the dispatch critical path; --sync-checkpoint forces
        # the old inline write. On SIGTERM/SIGINT (--on-preempt
        # checkpoint, the default) the run finishes the in-flight
        # stretch, writes a final checkpoint, and exits EXIT_PREEMPTED.
        self.sync_checkpoint = bool(test.get("sync_checkpoint"))
        self.on_preempt = str(test.get("on_preempt") or "checkpoint")
        self._ckpt_writer = None
        self._preempt = threading.Event()
        self.nemesis = None
        # leader-redirect requeue (doc/compartment.md "leader
        # election"): a not-leader reply (definite: the op did NOT
        # execute) re-issues the SAME op — same open invoke window —
        # against the hinted node after a seeded exponential backoff in
        # ROUNDS. Budget from --client-retries; client_retries=0 is the
        # global DEFAULT ("no generic RPC retries", core.DEFAULTS /
        # client.py's falsy idiom), so 0 means UNSPECIFIED here and the
        # budget falls back to 16 hops — a real client always follows
        # redirects, and failover must work on a default config.
        # Backoff pacing from --client-backoff-ms /
        # --client-backoff-cap-ms. Rows are (due_round, process, op,
        # node_idx, t, a, b, c) — the continuous carry_sched shape —
        # and ride checkpoints. All of this state lives in the
        # session table (runner/sessions.py, built per run in
        # _setup_run): --sessions picks the dict/list bookkeeping or
        # the fleet-shared columnar table, byte-identical either way.
        self._sessions = None
        self._redirect_budget = int(test.get("client_retries") or 0) or 16
        # donated carry: the bump is pure round-counter surgery on the
        # full state tree, so buffer reuse saves a whole-tree copy per
        # quiescent fast-forward. In mesh mode its shardings are pinned
        # like the scan's: a donated argument cannot be resharded at the
        # call boundary, so every producer of self.sim must hand back
        # the canonical placement.
        self._bump = jax.jit(
            lambda sim, k: sim.replace(net=sim.net.replace(
                round=sim.net.round + k)),
            donate_argnums=(0,) if donation_enabled() else (),
            **self._sim_jit_shardings(n_args=2))
        self._restart_fn = None

    def _sim_jit_shardings(self, n_args: int) -> dict:
        """in/out sharding pins for jitted sim->sim helpers (bump,
        restart): argument 0 and the output are the canonical sim tree,
        trailing args replicated. Empty in single-chip mode."""
        if self._shardings is None:
            return {}
        sim_sh, _inject_sh, scalar_sh = self._shardings
        return {"in_shardings": (sim_sh,) + (scalar_sh,) * (n_args - 1),
                "out_shardings": sim_sh}

    def _reshard(self):
        """Re-places self.sim onto the canonical mesh shardings after
        host-side state surgery (nemesis fault installs, resume):
        eager ops on sharded arrays may commit their outputs with a
        different layout, and the donating dispatches refuse to reshard
        donated args implicitly."""
        if self._shardings is not None:
            self.sim = jax.device_put(self.sim, self._shardings[0])

    def _net_surgery(self, fn):
        """Applies a host-side fault update `net -> net'` (partition
        grudges, down/paused masks, duplication probability) to this
        runner's simulation. A fleet cluster shell overrides this to
        target its own row of the batched fleet tree
        (runner/fleet_runner.py)."""
        self.sim = self.sim.replace(net=fn(self.sim.net))

    def _byz_surgery(self, fn):
        """Applies a host-side adversary update `byz -> byz'` (the
        start-/stop-byzantine plan installs) to the simulation's
        adversary carry. Eager host scalars land off-mesh, so the
        updated tree is re-placed like a resume's (`_reshard`)."""
        if self.sim.byz is None:
            raise ValueError(
                "byzantine nemesis op without enable_byz: the fault set "
                "is static compile capability (TpuRunner._fault_set)")
        self.sim = self.sim.replace(byz=fn(self.sim.byz))
        self._reshard()

    def _init_next_mid(self):
        """Primes the host mirror of the device message-id counter
        (refreshed by every dispatch's combined fetch). The fleet shell
        overrides this to read its row of the batched counter."""
        self._next_mid = int(self.transfer.fetch(self.sim.net.next_mid))

    # --- flight recorder (doc/observability.md) ---

    def _tel_span(self, name, t0, t1, args=None):
        """Records one phase span when a telemetry session is attached
        (spans are Chrome trace events; see telemetry.py). Fleet shells
        land on their own trace thread row via the cluster index."""
        if self.telemetry is not None:
            tid = f"c{self.idx}" if hasattr(self, "idx") else "runner"
            self.telemetry.span(name, t0, t1, tid=tid, args=args)

    def _ring_dict(self):
        """The last drained metric ring as a plain dict (None before
        the first drain or with rings off)."""
        if self._ring_host is None:
            return None
        from .. import telemetry as TM
        return TM.ring_dict(self._ring_host,
                            role_labels=TM.role_names(self.program))

    def _final_ring(self):
        """The ring's end-of-run value, fetched once (a post-run drain
        — never on the dispatch hot path). Used by the results block
        and the session's final record."""
        if not self.telemetry_rings:
            return None
        if self.sim is not None and self.sim.telemetry is not None:
            self._ring_host = self.transfer.fetch(self.sim.telemetry)
        return self._ring_host

    def _tel_wave(self, history, r):
        """One per-wave telemetry.jsonl record (no-op without a
        session): windowed/cumulative latency quantiles from the rows
        this wave exposed, ring deltas, checker lag. Fleet shells
        report the FLEET's transfer ledger — all device fetches run
        through the fleet driver, so the shell's own TransferStats
        never books a drain."""
        if self.telemetry is not None:
            fleet = getattr(self, "fleet", None)
            self.telemetry.wave(history, r,
                                cluster=getattr(self, "idx", None),
                                ring=self._ring_dict(),
                                pipeline=self.pipeline,
                                transfer=(fleet.transfer if fleet
                                          is not None else
                                          self.transfer))

    # --- helpers ---

    def _build_sim(self):
        """This runner's INITIAL simulation state (seeded PRNG key,
        loss probability installed, dealiased when donation is on).
        Factored out of __init__ so the fleet runner can rebuild a
        cluster's pristine row on demand (checkpointing a cluster that
        has not reached its first stretch boundary yet)."""
        sim = make_sim(self.program, self.cfg,
                       seed=self.test.get("seed", 0),
                       track_edge_send_round=self.journal_rows)
        if donation_enabled():
            sim = dealias(sim)
        # mirror core.build_test's host-net install exactly (same keys,
        # same gating): --p-loss/--latency-scale runs are path-equivalent
        if self.test.get("p_loss") is not None:
            sim = sim.replace(
                net=T.flaky(sim.net, float(self.test["p_loss"])))
        if self.test.get("latency_scale") is not None:
            sim = sim.replace(net=T.set_latency_scale(
                sim.net, float(self.test["latency_scale"])))
        return sim

    @staticmethod
    def _fault_set(test: dict) -> set:
        """The nemesis fault packages this run can see (static compile
        capability, so it must be known before any round compiles)."""
        pkg = test.get("nemesis_pkg") or {}
        faults = set(pkg.get("faults") or ())
        if not faults:
            nm = test.get("nemesis")
            if isinstance(nm, (set, frozenset, list, tuple)):
                faults = set(nm)
            elif nm:                    # bare truthy: legacy partition
                faults = {"partition"}
        return faults

    def restart_nodes(self, mask):
        """Crash-restart (stop-kill): masked nodes come back with
        volatile state rebuilt from the durable store
        (`NodeProgram.restore`), and their down flag clears."""
        if self._restart_fn is None:
            prog = self.program

            @partial(jax.jit,
                     donate_argnums=(0,) if donation_enabled() else (),
                     **self._sim_jit_shardings(n_args=2))
            def fn(sim, m):
                nodes = prog.restore(prog.init_state(), sim.durable,
                                     sim.nodes, m)
                net = sim.net.replace(down=sim.net.down & ~m)
                return sim.replace(nodes=nodes, net=net,
                                   durable=prog.durable_view(nodes))
            self._restart_fn = fn
        self.sim = self._restart_fn(self.sim, jnp.asarray(mask))
        self._state_cache = None

    def _time_ns(self, r: int) -> int:
        return int(r * self.ms_per_round * 1e6)

    def _read_state(self, node_idx: int):
        """Pulls one node's state row at the current round (cached per
        round)."""
        if self._state_cache is None:
            self._state_cache = self.transfer.fetch(self.sim.nodes)
        # copy the row out: on CPU, device_get returns zero-copy views
        # into device buffers, and a donated dispatch may recycle those
        # buffers while a completion (or the history it built) still
        # holds the row. Extraction is program-defined (state_row):
        # role partitions map the global node id into their role's
        # subtree instead of indexing every leaf by it.
        return self.program.state_row(self._state_cache, node_idx)

    def _nodes_host(self):
        """A host copy of the whole node-state tree at the current
        round (cached per round; values are read synchronously by the
        callers, so the CPU zero-copy hazard window never spans a
        dispatch). The fleet shell overrides this to read its row of
        the batched tree."""
        if self._state_cache is None:
            self._state_cache = self.transfer.fetch(self.sim.nodes)
        return self._state_cache

    def _resolve_dynamic_target(self, token: str) -> list:
        """Expands one dynamic nemesis target group against live
        cluster state (nemesis.NemesisDecisions._expand_pool). Today's
        vocabulary: "sequencer" -> the program's current elected leader
        (`current_leader_host`) — `--nemesis-targets kill=sequencer` is
        the failover driver."""
        if token == "sequencer":
            fn = getattr(self.program, "current_leader_host", None)
            if fn is None:
                raise ValueError(
                    f"program {self.program.name!r} has no movable "
                    f"sequencer to target")
            idx = int(fn(self._nodes_host()))
            return [self.nodes[idx]]
        raise ValueError(f"unknown dynamic nemesis target {token!r}")

    def _backoff_rounds(self, process, attempt: int) -> int:
        """Seeded truncated-exponential backoff in ROUNDS for the
        leader-redirect requeue: full jitter like client.RetryPolicy,
        but drawn from a stable hash of (seed, process, attempt) so a
        checkpoint/SIGKILL-resume replays the identical schedule
        without carrying RNG state."""
        import hashlib

        from .sessions import trunc_exp_bound
        bo_ms = self.test.get("client_backoff_ms")
        cap_ms = self.test.get("client_backoff_cap_ms")
        base = max(1, int(float(50.0 if bo_ms is None else bo_ms)
                          / self.ms_per_round))
        cap = max(base, int(float(2000.0 if cap_ms is None else cap_ms)
                            / self.ms_per_round))
        bound = trunc_exp_bound(base, cap, attempt)
        h = int.from_bytes(hashlib.md5(
            f"{self.test.get('seed', 0)}:{process}:{attempt}"
            .encode()).digest()[:4], "big")
        return 1 + (h % bound)

    def _complete(self, history, gen, ctx, process, completed, free):
        # columnar segment-append: completion rows go straight into the
        # history's columns, no per-op Op materialization on the hot path
        history.append_row(completed.get("type", "info"),
                           completed.get("f"), completed.get("value"),
                           process, ctx["time"], completed.get("error"),
                           completed.get("final", False))
        free.add(process)
        # the op's redirect-retry chain (if any) ends with its window
        self._sessions.close_retry(process)
        return gen.update(ctx, completed)


    def _free_rotated(self, free, history):
        return g.rotate_free(free, self._dispatches)

    def _overlap_feed(self, history):
        """Hands newly-appended history rows to the background analysis
        pipeline. Called right after a compiled dispatch is submitted
        (XLA dispatch is async), so the analysis worker chews segment N
        on the host while the device runs stretch N+1."""
        if self.pipeline is None:
            return
        hi = len(history)
        if hi > self._fed_upto:
            self.pipeline.feed(history, self._fed_upto, hi)
            self._fed_upto = hi

    @staticmethod
    def _make_packer(example, fleet_dim: bool = False):
        """(pack_fn, unpack) shipping a bool/int32 pytree as ONE int32
        array: remote backends pay a round trip per fetched array, and
        journal io trees have ~50 leaves.

        `fleet_dim=True` (the fleet runner's MIXED dp>1 x sp>1 meshes):
        every leaf leads with the fleet axis, and the pack keeps it —
        leaves reshape to [F, -1] and concatenate along axis 1 instead
        of flattening. Flatten-concat is NOT value-safe there: the 1-D
        reshape reshards the fleet-sharded dim, and GSPMD assembles that
        reshard as a masked SUM over the whole mesh, double-counting the
        sp replicas of `fleet_axis_spec`'s A-mode (observed: -1 packed
        as -2, k=8 as 16). The [F, -1] form keeps the sharded dim intact
        so no cross-replica assembly happens inside the jit."""
        leaves, treedef = jax.tree.flatten(example)
        shapes = [(x.shape, np.dtype(x.dtype)) for x in leaves]
        if fleet_dim:
            pack = jax.jit(lambda t: jnp.concatenate(
                [x.astype(jnp.int32).reshape(x.shape[0], -1)
                 for x in jax.tree.leaves(t)], axis=1))

            def unpack(flat: np.ndarray):
                out, off = [], 0
                for shape, dt in shapes:
                    n_el = int(np.prod(shape[1:]))
                    out.append(flat[:, off:off + n_el].reshape(shape)
                               .astype(dt))
                    off += n_el
                return jax.tree.unflatten(treedef, out)
            return pack, unpack
        pack = jax.jit(lambda t: jnp.concatenate(
            [x.astype(jnp.int32).reshape(-1) for x in jax.tree.leaves(t)]))

        def unpack(flat: np.ndarray):
            out, off = [], 0
            for shape, dt in shapes:
                n_el = int(np.prod(shape))
                out.append(flat[off:off + n_el].reshape(shape).astype(dt))
                off += n_el
            return jax.tree.unflatten(treedef, out)
        return pack, unpack

    def _stop_on_reply(self, gen, ctx, sessions, free) -> bool:
        """True = the scan must EXIT at the first client reply; False =
        it may cross whole reply-bearing stretches. Crossing is safe iff
        a completion cannot move the generator's next emission earlier
        than the scan bound. The `Gen.next_interesting_time` contract
        encodes exactly this: a finite time means purely time-gated
        (completions don't move it); +inf means only a completion event
        can unblock (worker-starved emission, EachThread waiting on a
        specific process, Phases waiting on quiescence). Worker
        starvation is additionally checked directly, because a mixed
        generator (e.g. a time-gated nemesis beside starved clients) can
        report the finite branch's time."""
        if not self.collect_replies:
            return True
        if not sessions:
            return False            # nothing in flight: no replies at all
        if not (set(ctx["free"]) - {g.NEMESIS}):
            return True             # starved: a completion enables emission
        import math
        return gen.next_interesting_time(ctx) == math.inf

    def _scan_bound(self, gen, ctx, sessions, r, next_ckpt,
                    max_rounds) -> int:
        """How many injection-free rounds may run in one compiled dispatch
        without the host needing to look: bounded by the generator's next
        interesting time, the earliest RPC timeout deadline, the next
        checkpoint, and max_rounds. Always >= 1."""
        import math
        ns_pr = self.ms_per_round * 1e6
        bound = r + self.max_scan
        nt = gen.next_interesting_time(ctx)
        if nt != math.inf:
            bound = min(bound, int(math.ceil(nt / ns_pr)))
        dl = sessions.min_deadline()
        if dl is not None:
            bound = min(bound, dl)
        due = sessions.requeue_min_due()
        if due is not None:
            # a redirect retry becomes injectable at its due round
            bound = min(bound, due)
        if next_ckpt is not None:
            bound = min(bound, next_ckpt)
        bound = min(bound, max_rounds)
        return max(bound - r, 1)

    # --- checkpoint/resume (SURVEY.md section 5.4: the reference can't) ---

    def _save_checkpoint(self, gen, history, sessions, free, r,
                         sync: bool = False):
        """Snapshots the run. Main-thread work is only what MUST happen
        before the next dispatch mutates state: the sim device pull
        (copied when donation may recycle buffers), one pickle of the
        small mutable host objects (generator tree, pending RPCs,
        intern tables, nemesis rng — the loop keeps mutating the live
        ones), and an O(columns) view-snapshot of the history. The big
        pickle + fsync + rename runs on the background writer unless
        `sync` (or --sync-checkpoint)."""
        import pickle
        import time as _time

        from .. import checkpoint as cp
        t0 = _time.perf_counter()
        sim_host = jax.device_get(self.sim)
        if donation_enabled():
            # CPU device_get returns zero-copy views into device
            # buffers; a later donated dispatch may recycle them while
            # the writer is still pickling (same hazard as _read_state)
            sim_host = jax.tree.map(np.array, sim_host)
        sess_meta = sessions.to_meta()
        meta = {
            "r": r,
            "dispatches": self._dispatches,
            "gen": gen,
            "pending": sess_meta["pending"],
            "free": set(free),
            "intern": self.intern,
            "nemesis_rng": (self.nemesis.rng_state()
                            if self.nemesis else None),
            # continuous-mode carry (None on the round-synchronous path)
            "carry": getattr(self, "_carry_live", None),
            # leader-redirect requeue: retried ops whose invoke windows
            # are still open must re-issue identically after a resume
            # (both session backends emit the same legacy meta shape)
            "requeue": sess_meta["requeue"],
            # program host-side session state (kafka consumer sessions,
            # polled-offset tracking, the compartment's leader guess):
            # the op stream depends on it
            "program_host": self.program.host_state(),
        }
        state = {
            "fingerprint": cp.fingerprint(self.test),
            "r": r,
            "sim": sim_host,
            "meta_blob": pickle.dumps(meta,
                                      protocol=pickle.HIGHEST_PROTOCOL),
            "history_columns": history.snapshot_columns(),
        }
        store_dir = self.test["store_dir"]
        if sync or self.sync_checkpoint:
            if self._ckpt_writer is not None:
                self._ckpt_writer.wait()    # never two writers on one file
            path = cp.save(store_dir, state)
            self.transfer.ckpt_saves += 1
            self.transfer.ckpt_blocked_s += _time.perf_counter() - t0
            log.info("checkpointed round %d -> %s (sync)", r, path)
        else:
            if self._ckpt_writer is None:
                self._ckpt_writer = cp.CheckpointWriter()
            self._ckpt_writer.submit(store_dir, state)
            self.transfer.ckpt_saves += 1
            self.transfer.ckpt_blocked_s += _time.perf_counter() - t0
            log.info("checkpoint snapshot at round %d -> background "
                     "writer (%s)", r, store_dir)
        self._tel_span("checkpoint-snapshot", t0, _time.perf_counter(),
                       args={"round": r})

    def _finish_checkpoints(self):
        """Joins the background writer (if any) and books its wall time
        into the transfer counters, so results show how much save work
        the writer amortized off the critical path."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.wait()
            self.transfer.ckpt_write_s = self._ckpt_writer.write_s

    def _check_preempted(self, gen, history, sessions, free, r):
        """The graceful-preemption point, called at stretch boundaries:
        the in-flight compiled stretch has completed and its replies are
        folded into the history, so the state is checkpointable. Writes
        a final (synchronous) checkpoint and unwinds with Preempted."""
        if not self._preempt.is_set():
            return
        from .. import checkpoint as cp
        store_dir = self.test.get("store_dir")
        if store_dir:
            self._save_checkpoint(gen, history, sessions, free, r,
                                  sync=True)
        log.warning("preempted at virtual round %d (%d history ops, "
                    "%d in flight): exiting %d for supervised relaunch",
                    r, len(history), len(sessions), cp.EXIT_PREEMPTED)
        raise cp.Preempted(r, store_dir or None)

    # --- main loop ---

    def _setup_run(self, resume: dict | None = None) -> dict:
        """Builds the host-side run state the dispatch loop consumes —
        generator tree, nemesis executor, worker bookkeeping, history —
        applying a resume checkpoint when given. Returns the keyword
        dict `_loop_steps` takes. Shared by the standalone `run()` and
        the fleet runner (which calls it on every cluster shell; a
        shell's resume meta carries no "sim" entry — the fleet restores
        the batched tree itself)."""
        test = self.test
        C = self.concurrency
        gen = g.to_gen(test["generator"])
        # per-cluster nemesis decision streams: a fleet's `nemesis`
        # sweep varies only the fault schedule across clusters, so the
        # nemesis seed is independently overridable
        nem_seed = test.get("nemesis_seed")
        if nem_seed is None:
            nem_seed = test.get("seed", 0)
        # role-targeted faults (--nemesis-targets): group tokens resolve
        # against the node family's fault groups (role ranges, acceptor
        # grid rows/columns) plus literal node names; dynamic groups
        # (the compartment's live `sequencer`) stay symbolic and expand
        # at invoke time against the runner's cluster state
        from .. import nemesis as nem
        groups = getattr(self.program, "fault_groups", lambda: {})()
        dyn = getattr(self.program, "dynamic_fault_groups",
                      lambda: ())()
        targets = nem.resolve_targets(test.get("nemesis_targets"),
                                      groups, self.nodes, dynamic=dyn)
        # NOT `or 1.0`: an explicit --byz-rate 0 must stick (the
        # armed-detectors-on-honest-traffic configuration)
        byz_rate = test.get("byz_rate")
        nemesis = (TpuCombinedNemesis(self, self.nodes, nem_seed,
                                      targets=targets,
                                      attacks=test.get("byz_attacks"),
                                      byz_rate=1.0 if byz_rate is None
                                      else float(byz_rate))
                   if test.get("nemesis_pkg", {}).get("generator") is not None
                   or test.get("nemesis") else None)
        if nemesis is not None:
            nemesis.resolve_dynamic = self._resolve_dynamic_target
        self.nemesis = nemesis
        processes = list(range(C)) + ([g.NEMESIS] if nemesis else [])
        free = set(processes)
        # client-session table (doc/perf.md "columnar client sessions"):
        # pending RPCs, timeout deadlines, retry/backoff and redirect
        # state. A fleet shell gets a view of the fleet's ONE shared
        # columnar table; standalone runs build their own backend per
        # --sessions (byte-identical either way).
        shared = getattr(self, "_fleet_sessions", None)
        if shared is not None:
            sessions = shared[0].view(shared[1])
        else:
            from .sessions import make_sessions
            sessions = make_sessions(test, C)
        self._sessions = sessions
        history = History()
        max_rounds = int(test.get("max_rounds", 2_000_000))

        r = 0
        if resume is not None:
            r = resume["r"]
            self._dispatches = resume["dispatches"]
            if "sim" in resume:
                self.sim = (dealias(resume["sim"]) if donation_enabled()
                            else resume["sim"])
                self._reshard()
            self._state_cache = None
            gen = resume["gen"]
            rh = resume["history"]
            history = rh if isinstance(rh, History) else History(rh)
            # session state restores through the same legacy meta
            # shapes both backends emit, so a checkpoint written under
            # --sessions coroutine resumes under columnar (and back)
            sessions.load_meta(resume["pending"],
                               resume.get("requeue"))
            free = set(resume["free"])
            self.intern = resume["intern"]
            if nemesis and resume.get("nemesis_rng") is not None:
                nemesis.set_rng_state(resume["nemesis_rng"])
            self.program.set_host_state(resume.get("program_host"))
            log.info("resumed at virtual round %d (%d history ops, "
                     "%d in flight)", r, len(history), len(sessions))
            if self.journal is not None:
                log.warning(
                    "resume with journaling: net-journal rows and the "
                    "Lamport diagram cover only rounds >= %d; "
                    "history/results cover the whole run", r)
        # checkpoint cadence stays GRID-ALIGNED across resume: the next
        # boundary is the next cadence multiple after r, not r + cadence
        # — a graceful-preemption checkpoint lands at an arbitrary
        # stretch boundary, and in continuous mode checkpoint
        # boundaries are window boundaries (op timing depends on them),
        # so a resumed run must reproduce the original grid to stay
        # byte-identical (caught by the fleet-continuous resume seam)
        ce = self.checkpoint_every_rounds
        next_ckpt = ((r // ce) + 1) * ce if ce else None
        if not self.no_overlap and self.check_workers > 0 \
                and _wants_analysis(test.get("checker")):
            from ..checkers.pipeline import AnalysisPipeline
            self.pipeline = AnalysisPipeline(
                workers=self.check_workers,
                # fleet-level grader pool (doc/perf.md): shells share
                # ONE worker pool instead of one thread per cluster;
                # None (standalone) keeps the dedicated thread
                pool=getattr(self, "_analysis_pool", None),
                observers=_stream_observers(test.get("checker"), test),
                ns_per_round=self.ms_per_round * 1e6,
                head_round=lambda: getattr(self, "_r_live", 0),
                # fleet shells stamp their cluster index on window
                # records/reports (None for a standalone runner)
                label=getattr(self, "idx", None),
                # flight recorder: per-segment grading spans land on
                # the trace's "analysis" thread row
                tracer=self.telemetry)
        self._fed_upto = 0
        if resume is not None and self.pipeline is not None and \
                len(history) > 0:
            # pipeline-aware resume: seed the overlap bookkeeping with
            # the resumed rows as segment 0, so the pipeline covers the
            # whole stitched history and the checkers keep their fast
            # path (a partial pipeline would fail the check-time
            # row-count match and decline service, silently losing the
            # overlap on every resumed run). In fleet mode each shell
            # seeds its OWN pipeline with its own rows — per-cluster
            # blocks never double-count another cluster's history.
            self.pipeline.seed_resumed(history, len(history))
            self._fed_upto = len(history)
        # continuous-mode carry: ops already drawn from the generator
        # but not yet injected at checkpoint time (the schedule cannot
        # be re-drawn — generators share mutable RNGs across states)
        self._resume_carry = resume.get("carry") if resume else None
        # host mirror of the device message-id counter (refreshed by
        # every dispatch's combined fetch)
        self._init_next_mid()
        return dict(test=test, cfg=self.cfg, program=self.program,
                    gen=gen, nemesis=nemesis, processes=processes,
                    free=free, sessions=sessions, history=history,
                    max_rounds=max_rounds, next_ckpt=next_ckpt, r=r)

    def run(self, resume: dict | None = None) -> History:
        # read state BEFORE the signal handlers install: a transfer
        # failure in setup must not leak them
        st = self._setup_run(resume)
        history = st["history"]
        # graceful preemption (doc/checkpoint.md): SIGTERM/SIGINT set a
        # flag; the loop finishes the in-flight compiled stretch, writes
        # a final checkpoint, and unwinds with Preempted so the CLI can
        # exit EXIT_PREEMPTED for a supervised --resume relaunch.
        # Installed only on the main thread (signal() is refused
        # elsewhere) and only for --on-preempt checkpoint.
        import signal as _signal
        prev_handlers = {}
        if self.on_preempt == "checkpoint" and \
                threading.current_thread() is threading.main_thread():
            def _on_signal(signum, frame):
                if self._preempt.is_set():
                    # second signal: the user wants OUT, not graceful —
                    # restore the previous handlers and abort now
                    for s, h in prev_handlers.items():
                        try:
                            _signal.signal(s, h)
                        except (ValueError, OSError):  # pragma: no cover
                            pass
                    raise KeyboardInterrupt
                log.warning(
                    "received %s: finishing the in-flight stretch, then "
                    "writing a final checkpoint (signal again to abort "
                    "immediately)", _signal.Signals(signum).name)
                self._preempt.set()
            for sig in (_signal.SIGTERM, _signal.SIGINT):
                try:
                    prev_handlers[sig] = _signal.signal(sig, _on_signal)
                except (ValueError, OSError):   # pragma: no cover
                    pass
        try:
            steps = (self._loop_steps_continuous(**st) if self.continuous
                     else self._loop_steps(**st))
            r = self._drive(steps)
        except BaseException:
            # don't leak the analysis worker (and its history refs) on
            # generator/client errors or KeyboardInterrupt; land (or
            # surface the failure of) any in-flight checkpoint write
            if self.pipeline is not None:
                self.pipeline.close()
            try:
                self._finish_checkpoints()
            except Exception as e:
                log.error("checkpoint writer failed during unwind: %s", e)
            raise
        finally:
            for sig, h in prev_handlers.items():
                try:
                    _signal.signal(sig, h)
                except (ValueError, OSError):   # pragma: no cover
                    pass
        try:
            self._finish_checkpoints()
        except BaseException:
            # a failed background write surfaces here on the success
            # path; don't leak the analysis worker on the way out
            if self.pipeline is not None:
                self.pipeline.close()
            raise
        if r >= st["max_rounds"]:
            log.warning("TPU runner hit max_rounds=%d", st["max_rounds"])
        self.final_round = r
        if self.pipeline is not None:
            # overlapped_s counts only worker time that ran while the
            # device was still computing; the tail segment (analyzed
            # after the last dispatch, device idle) is excluded
            overlapped = self.pipeline.busy_s
            self._overlap_feed(history)
            self.pipeline.finish()
            self.transfer.overlapped_s += overlapped
        log.info("TPU run finished at virtual round %d (%.1f virtual s), "
                 "%d history ops, %d host drains (%d bytes, "
                 "%.3fs blocked / %.3fs analysis overlapped)",
                 r, r * self.ms_per_round / 1e3, len(history),
                 self.transfer.drains, self.transfer.host_bytes,
                 self.transfer.blocked_s, self.transfer.overlapped_s)
        return history

    def _drive(self, steps) -> int:
        """Standalone device driver for the `_loop_steps` coroutine:
        answers quiet probes with this runner's own jitted probe,
        performs bumps on self.sim, and executes scan requests as single
        compiled dispatches. The fleet runner drives MANY clusters'
        coroutines against one batched fleet tree instead
        (runner/fleet_runner.py), batching their requests into vmapped
        dispatches — the loop itself is identical, which is what makes
        fleet clusters bit-identical to standalone runs."""
        resp = None
        while True:
            try:
                req = steps.send(resp)
            except StopIteration as e:
                return e.value
            kind = req[0]
            if kind == "scan":
                resp = self._exec_scan(*req[1:])
            elif kind == "cscan":
                resp = self._exec_cscan(*req[1:])
            elif kind == "bump":
                self.sim = self._bump(self.sim, jnp.int32(req[1]))
                resp = None
            else:                   # "quiet"
                resp = self._quiet()

    def _loop_steps(self, test, cfg, program, gen, nemesis, processes,
                    free, sessions, history, max_rounds, next_ckpt, r):
        """The host-side dispatch loop as a device-agnostic coroutine.

        All device interaction happens through three yielded request
        kinds — ``("quiet",) -> bool``, ``("bump", k) -> None``, and
        ``("scan", inject_rows, k_max, stop, history, r) ->
        (k_executed, replies)`` — so the SAME loop code drives a
        standalone runner (`_drive` answers against self.sim) and one
        cluster of a fleet (`FleetRunner` coalesces many loops' requests
        into single vmapped dispatches over the batched cluster axis).
        Returns the final virtual round.

        `self._gen_live`/`self._r_live` expose the (rebound) generator
        tree and round at every stretch boundary: the fleet's coalesced
        checkpointing snapshots them — everything else it needs
        (sessions/free/history/intern/nemesis) is shared mutable
        state."""
        N, C = cfg.n_nodes, self.concurrency
        exhausted = False
        observe_round = getattr(self.program, "observe_round", None)
        while r < max_rounds:
            self._gen_live, self._r_live = gen, r
            # stretch boundary: the previous dispatch has landed and its
            # replies are in the history, so this is the graceful spot
            # to honor a pending SIGTERM/SIGINT
            self._check_preempted(gen, history, sessions, free, r)
            if observe_round is not None:
                # programs with host-side routing leases (the
                # compartment's client-side leader lease) read the
                # current round before this boundary's ops are routed
                observe_round(r)
            # one host poll pass per stretch boundary: the generator
            # poll loop below (plus the pending/deadline scans riding
            # this iteration) — surfaced as host-polls/host-poll-s so
            # the O(waves)-not-O(clusters) fleet claim is measurable
            _poll_t0 = time.perf_counter()
            ctx = {"time": self._time_ns(r), "free": self._free_rotated(free, history),
                   "processes": processes}
            inject_rows = []
            while True:
                res, gen = gen.op(ctx)
                if res is None:
                    exhausted = True
                    break
                exhausted = False
                if res == g.PENDING:
                    break
                process = res["process"]
                self._dispatches += 1
                free.discard(process)
                op = {k: v for k, v in res.items() if k != "time"}
                history.append_row("invoke", op.get("f"), op.get("value"),
                                   process, ctx["time"],
                                   final=op.get("final", False))
                if process == g.NEMESIS:
                    completed = nemesis.invoke(op)
                    # fault installs are eager host-side surgery on the
                    # sharded state; restore canonical placement before
                    # the next donating dispatch
                    self._reshard()
                    gen = self._complete(history, gen, ctx, process,
                                         completed, free)
                else:
                    # default routing: worker's bound node. A program
                    # may route specific ops (smart-client routing, the
                    # way real kafka clients route to partition
                    # leaders): node_for_op returns an index or None
                    routed = self.program.node_for_op(op)
                    if routed is None:
                        node_idx = process % N
                    else:
                        node_idx = int(routed)
                        if not 0 <= node_idx < N:
                            raise ValueError(
                                f"{self.program.name}.node_for_op "
                                f"returned {routed} for a {N}-node "
                                f"cluster")
                    body = program.request_for_op(op)
                    if body is HOST:
                        completed = program.host_op(
                            op, lambda i=node_idx: self._read_state(i),
                            self.intern)
                        gen = self._complete(history, gen, ctx, process,
                                             completed, free)
                    else:
                        try:
                            t, a, b, c = program.encode_body(body,
                                                             self.intern)
                        except EncodeCapacityError as e:
                            # encode-capacity exhaustion (e.g. the txn
                            # command table) fails the op definitely
                            # instead of crashing the run; any other
                            # exception is a bug and propagates
                            completed = {**op, "type": "fail",
                                         "error": ["encode-error", str(e)]}
                            gen = self._complete(history, gen, ctx,
                                                 process, completed, free)
                        else:
                            inject_rows.append((process, op, node_idx, t,
                                                a, b, c))
                ctx = {"time": self._time_ns(r),
                       "free": self._free_rotated(free, history),
                       "processes": processes}

            _poll_t1 = time.perf_counter()
            self.transfer.record_poll(_poll_t1 - _poll_t0)
            self._tel_span("schedule-encode", _poll_t0, _poll_t1)

            # leader-redirect retries whose backoff elapsed re-inject
            # now (their invoke windows are already open — no new
            # history rows, just fresh pending registrations)
            inject_rows += sessions.take_due_requeues(r)

            if exhausted and not sessions and not sessions.has_requeue() \
                    and free == set(processes):
                break

            # fast-forward quiescent stretches (nothing in flight, nothing
            # to inject, program idle): jump straight to the generator's
            # next interesting round in ONE bump — never overshoot (the
            # scan path stops there too, and the two must stay
            # observationally identical; fruitless generator polls are
            # side-effect-free, so skipping them is equivalent). Jumping
            # the full bound matters on remote devices, where every bump
            # is a host<->device round trip.
            if not inject_rows and not sessions and (yield ("quiet",)):
                k = self._scan_bound(gen, ctx, sessions, r, next_ckpt,
                                     max_rounds)
                yield ("bump", k)
                r += k
                if next_ckpt is not None and r >= next_ckpt:
                    self._save_checkpoint(gen, history, sessions, free,
                                          r)
                    next_ckpt = r + self.checkpoint_every_rounds
                continue

            if inject_rows:
                # next_mid is mirrored on the host (refreshed in every
                # dispatch's combined fetch) — reading it from the
                # device here would cost a round trip per injection
                base_mid = self._next_mid
                for j, (p, o, ni, *_rest) in enumerate(inject_rows):
                    sessions.register(base_mid + j, p, o, ni,
                                      r + self.timeout_rounds)

            # one fused dispatch: this round's injections (possibly none)
            # plus the scan to the next host-relevant round, with every
            # reply collected into a compact log. On remote backends each
            # dispatch is a full round trip, so op count per dispatch is
            # the whole performance story. The bound is computed with the
            # just-injected ops already pending, so their timeout
            # deadlines cap the stretch.
            k_max = self._scan_bound(gen, ctx, sessions, r, next_ckpt,
                                     max_rounds)
            stop = self._stop_on_reply(gen, ctx, sessions, free)
            k, replies = yield ("scan", inject_rows, k_max, stop,
                                history, r)
            r += k
            ctx = {"time": self._time_ns(r), "free": self._free_rotated(free, history),
                   "processes": processes}

            # one batched table pass pops this wave's reply sessions
            # (None = stale), then each completion folds in
            entries = sessions.absorb_results([rep[5] for rep in replies])
            for rep, entry in zip(replies, entries):
                gen = self._apply_reply(program, gen, history, sessions,
                                        free, processes, rep, entry)

            # timeouts -> indefinite :info (client.clj:214-233); a
            # timed-out node may be a dead leader — let the program
            # rotate its routing guess so new ops probe elsewhere
            nt = getattr(self.program, "note_timeout", None)
            for process, op, ni in sessions.take_expired(r):
                if nt is not None:
                    nt(ni)
                completed = {**op, "type": "info", "error": "net-timeout"}
                gen = self._complete(history, gen, ctx, process, completed,
                                     free)

            # flight recorder: one telemetry.jsonl record per wave, AFTER
            # this wave's replies/timeouts folded into the history
            self._tel_wave(history, r)

            if next_ckpt is not None and r >= next_ckpt:
                self._save_checkpoint(gen, history, sessions, free, r)
                next_ckpt = r + self.checkpoint_every_rounds

        self._gen_live, self._r_live = gen, r
        return r

    def _apply_reply(self, program, gen, history, sessions, free,
                     processes, rep, entry):
        """Decodes one drained reply row — (round_stamp, type, a, b, c,
        reply_to, payload-or-None) — and folds its completion into the
        history and generator state. `entry` is the session row the
        caller absorbed for it (`sessions.absorb_results`). Returns the
        rebound generator. Shared by the round-synchronous and
        continuous loops."""
        stamp, t_, a_, b_, c_, rt, payload = rep
        if entry is None:
            return gen              # stale reply (client.clj:167-168)
        process, op, node_idx, _dl = entry
        body = program.decode_body(t_, a_, b_, c_, self.intern)
        # any reply (success OR error) proves the contacted node alive:
        # programs with a client-side leader lease refresh it here
        nr = getattr(program, "note_reply", None)
        if nr is not None:
            nr(node_idx, int(stamp))
        if body.get("type") == "error":
            # leader redirect (doc/compartment.md): a not-leader reply
            # is definite — the op did NOT execute — so re-issue the
            # SAME op (same open invoke window) against the hinted node
            # under seeded backoff instead of completing it. Budget
            # exhaustion falls through to the ordinary definite fail.
            hint_fn = getattr(program, "redirect_hint", None)
            if hint_fn is not None:
                h = hint_fn(body)
                if h is not None:
                    attempt = sessions.attempt(process)
                    if attempt < self._redirect_budget:
                        target = int(h)
                        if not 0 <= target < self.cfg.n_nodes:
                            # no live leader known: probe the tier
                            target = int(program.next_probe(node_idx))
                        note = getattr(program, "note_leader", None)
                        if note is not None:
                            note(target)
                        t2, a2, b2, c2 = program.encode_body(
                            program.request_for_op(op), self.intern)
                        sessions.open_retry(process, attempt + 1)
                        due = int(stamp) + self._backoff_rounds(process,
                                                                attempt)
                        sessions.requeue(due, process, op, target,
                                         t2, a2, b2, c2)
                        return gen
            err = ERROR_REGISTRY.get(body.get("code"))
            definite = err.definite if err else False
            completed = {**op,
                         "type": "fail" if definite else "info",
                         "error": [err.name if err
                                   else body.get("code"),
                                   body.get("text")]}
        elif payload is not None:
            # state snapshotted at the reply round, on device —
            # no host<->device round trip per completion
            completed = program.completion_payload(
                op, body, payload, self.intern)
        else:
            completed = program.completion(
                op, body, lambda i2=node_idx: self._read_state(i2),
                self.intern)
        cctx = {"time": self._time_ns(stamp),
                "free": self._free_rotated(free, history),
                "processes": processes}
        return self._complete(history, gen, cctx, process, completed,
                              free)

    # --- continuous mode (doc/streams.md) ---

    def _run_nemesis_op(self, gen, nemesis, nop, history, free,
                        processes, r):
        """Executes one nemesis op at the current round. Host-side fault
        surgery is a window boundary in continuous mode: the scan cannot
        rewrite its own masks mid-flight, so the loop stops exactly at
        the fault's round, applies it, and opens the next window with
        the fault live."""
        ctx = {"time": self._time_ns(r),
               "free": self._free_rotated(free, history),
               "processes": processes}
        process = nop["process"]
        self._dispatches += 1
        free.discard(process)
        op = {k: v for k, v in nop.items() if k != "time"}
        history.append_row("invoke", op.get("f"), op.get("value"),
                           process, self._time_ns(r),
                           final=op.get("final", False))
        completed = nemesis.invoke(op)
        self._reshard()
        return self._complete(history, gen, ctx, process, completed,
                              free)

    def _encode_events(self, evs, carry_sched, carry_host, history, gen,
                       free, processes):
        """Encodes freshly pre-scheduled client ops into carry_sched
        rows (round, process, op, node_idx, t, a, b, c). HOST-routed ops
        become window boundaries (completed from device state at their
        round); encode-capacity failures complete as definite fails on
        the spot, like the round-synchronous path."""
        N = self.cfg.n_nodes
        program = self.program
        for rd, res in evs:
            op = {k: v for k, v in res.items() if k != "time"}
            process = res["process"]
            routed = program.node_for_op(op)
            if routed is None:
                node_idx = process % N
            else:
                node_idx = int(routed)
                if not 0 <= node_idx < N:
                    raise ValueError(
                        f"{program.name}.node_for_op returned {routed} "
                        f"for a {N}-node cluster")
            body = program.request_for_op(op)
            if body is HOST:
                carry_host.append((rd, process, op, node_idx))
                continue
            try:
                t, a, b, c = program.encode_body(body, self.intern)
            except EncodeCapacityError as e:
                ctx = {"time": self._time_ns(rd),
                       "free": self._free_rotated(free, history),
                       "processes": processes}
                history.append_row("invoke", op.get("f"),
                                   op.get("value"), process,
                                   self._time_ns(rd),
                                   final=op.get("final", False))
                completed = {**op, "type": "fail",
                             "error": ["encode-error", str(e)]}
                gen = self._complete(history, gen, ctx, process,
                                     completed, free)
                continue
            carry_sched.append((rd, process, op, node_idx, t, a, b, c))
        return gen

    def _loop_steps_continuous(self, test, cfg, program, gen, nemesis,
                               processes, free, sessions, history,
                               max_rounds, next_ckpt, r):
        """The continuous-mode dispatch loop (doc/streams.md).

        Instead of stopping the device at every generator event, the
        host PRE-SCHEDULES the next stretch of client ops onto their
        offered-rate rounds (`generators.schedule_ahead`) and one
        sched-inject scan lands them INSIDE the compiled window — client
        traffic arrives while whatever faults the nemesis installed at
        the boundary are live mid-window. Nemesis surgery, HOST-routed
        completions, and checkpoints remain window boundaries. Yields
        the `_loop_steps` request kinds plus
        ``("cscan", rows, k_max, stop, history, r) ->
        (k_executed, replies, inj_mids)``.

        Determinism contract: scheduling consumes only generator state
        and the (deterministic) reply timing of previous windows, so a
        seed fixes the whole history byte-for-byte — plain and --mesh
        (pinned by tests/test_continuous.py). Rows a window did not
        reach (early stop on a reply or ring capacity) carry with their
        rounds intact; an op enters the history only once its injection
        is confirmed by the drain's `inj_mids`."""
        N, C = cfg.n_nodes, self.concurrency
        ns_pr = self.ms_per_round * 1e6
        rc = getattr(self, "_resume_carry", None) or {}
        self._resume_carry = None
        carry_sched: list = list(rc.get("sched") or [])
        carry_nem = rc.get("nem")
        carry_host: list = list(rc.get("host") or [])
        exhausted = False
        observe_round = getattr(self.program, "observe_round", None)
        while r < max_rounds:
            self._gen_live, self._r_live = gen, r
            self._carry_live = {"sched": carry_sched, "nem": carry_nem,
                                "host": carry_host}
            # stretch boundary: the previous window has landed and its
            # replies are folded in — the graceful SIGTERM spot
            self._check_preempted(gen, history, sessions, free, r)
            if observe_round is not None:
                # host-side routing leases see the window-boundary round
                observe_round(r)

            # host-boundary work due now
            while carry_nem is not None and carry_nem[0] <= r:
                nop = carry_nem[1]
                carry_nem = None
                gen = self._run_nemesis_op(gen, nemesis, nop, history,
                                           free, processes, r)
            while carry_host and carry_host[0][0] <= r:
                _rd, process, op, node_idx = carry_host.pop(0)
                ctx = {"time": self._time_ns(r),
                       "free": self._free_rotated(free, history),
                       "processes": processes}
                history.append_row("invoke", op.get("f"),
                                   op.get("value"), process,
                                   self._time_ns(r),
                                   final=op.get("final", False))
                completed = program.host_op(
                    op, lambda i=node_idx: self._read_state(i),
                    self.intern)
                gen = self._complete(history, gen, ctx, process,
                                     completed, free)

            def horizon():
                h = r + self.max_scan
                if next_ckpt is not None:
                    h = min(h, next_ckpt)
                h = min(h, max_rounds)
                if carry_nem is not None:
                    h = min(h, carry_nem[0])
                if carry_host:
                    h = min(h, carry_host[0][0])
                return max(h, r + 1)

            # pre-schedule the window; nemesis ops due NOW execute
            # immediately (fault surgery before the dispatch) and
            # scheduling resumes with the masks installed. This whole
            # block is ONE host poll pass (scheduling + encode) per
            # window boundary — the unit host-polls/host-poll-s counts
            _poll_t0 = time.perf_counter()
            while True:
                gen, evs, nem, _end, end_kind = g.schedule_ahead(
                    gen, processes, free, r, horizon(), ns_pr,
                    self._dispatches)
                self._dispatches += len(evs)
                for _rd, res in evs:
                    free.discard(res["process"])
                gen = self._encode_events(evs, carry_sched, carry_host,
                                          history, gen, free, processes)
                if nem is not None and nem[0] <= r:
                    gen = self._run_nemesis_op(gen, nemesis, nem[1],
                                               history, free, processes,
                                               r)
                    continue
                if nem is not None:
                    carry_nem = nem
                break
            exhausted = end_kind == "exhausted"
            # leader-redirect retries join the scheduled rows (their
            # due rounds clamp to this window's start; rd gates the
            # in-window injection like any scheduled op)
            carry_sched += sessions.drain_requeues(r)
            # stable by round: carried rows precede same-round new ones
            carry_sched.sort(key=lambda rw: rw[0])
            _poll_t1 = time.perf_counter()
            self.transfer.record_poll(_poll_t1 - _poll_t0)
            self._tel_span("schedule-encode", _poll_t0, _poll_t1)
            self._carry_live = {"sched": carry_sched, "nem": carry_nem,
                                "host": carry_host}

            if exhausted and not sessions and not carry_sched \
                    and carry_nem is None and not carry_host \
                    and free == set(processes):
                break

            # fast-forward quiescent gaps before the first due row (same
            # discipline as the round-synchronous loop)
            first_due = carry_sched[0][0] if carry_sched else None
            h = horizon()
            if not sessions and (first_due is None or first_due > r) \
                    and (yield ("quiet",)):
                target = h if first_due is None else min(first_due, h)
                k = max(target - r, 1)
                yield ("bump", k)
                r += k
                if next_ckpt is not None and r >= next_ckpt:
                    self._save_checkpoint(gen, history, sessions, free, r)
                    next_ckpt = r + self.checkpoint_every_rounds
                continue

            # one window: bounded by the stream stride, the horizon,
            # and every timeout deadline (already-pending plus this
            # window's scheduled injections). The window CROSSES replies
            # (stop_on_reply False): completions fold in at the window
            # close, so one dispatch carries a whole offered-rate
            # stretch — the stride bounds how stale a freed worker can
            # get before the generator is polled again.
            k_abs = min(h, r + self.continuous_stride)
            dl = sessions.min_deadline()
            if dl is not None:
                k_abs = min(k_abs, dl)
            for rw in carry_sched:
                k_abs = min(k_abs, rw[0] + self.timeout_rounds)
            k_max = max(k_abs - r, 1)
            k, replies, inj_mids = yield ("cscan", carry_sched, k_max,
                                          False, history, r)

            injected = [(j, rw) for j, rw in enumerate(carry_sched)
                        if rw[0] - r < k]
            carry_sched = [rw for rw in carry_sched if rw[0] - r >= k]
            # merge confirmed injections and replies in time order
            # (completions first at equal rounds, like the synchronous
            # loop's boundary behavior); an injection's own reply is
            # always stamped after its round, so pending registration
            # precedes it
            events = [(rw[0], 1, j, rw) for j, rw in injected]
            events += [(int(rep[0]), 0, i, rep)
                       for i, rep in enumerate(replies)]
            events.sort(key=lambda e: (e[0], e[1], e[2]))
            r += k
            for rd, kind, seq, item in events:
                if kind == 1:
                    _rd0, process, op, node_idx = item[:4]
                    mid = int(inj_mids[seq])
                    if mid < 0:     # pragma: no cover - device contract
                        raise RuntimeError(
                            f"continuous scan executed {k} rounds but "
                            f"reported no mid for row {seq} at round "
                            f"{rd}")
                    if not sessions.retry_is_open(process):
                        # a leader-redirect retry keeps its original
                        # open invoke window — no second invoke row
                        history.append_row("invoke", op.get("f"),
                                           op.get("value"), process,
                                           self._time_ns(rd),
                                           final=op.get("final", False))
                    sessions.register(mid, process, op, node_idx,
                                      rd + self.timeout_rounds)
                else:
                    entry = sessions.absorb_results([int(item[5])])[0]
                    gen = self._apply_reply(program, gen, history,
                                            sessions, free, processes,
                                            item, entry)

            # timeouts -> indefinite :info (client.clj:214-233)
            ctx = {"time": self._time_ns(r),
                   "free": self._free_rotated(free, history),
                   "processes": processes}
            nt = getattr(self.program, "note_timeout", None)
            for process, op, _ni in sessions.take_expired(r):
                if nt is not None:
                    nt(_ni)
                completed = {**op, "type": "info",
                             "error": "net-timeout"}
                gen = self._complete(history, gen, ctx, process,
                                     completed, free)

            # flight recorder: one record per window, replies folded
            self._tel_wave(history, r)

            if next_ckpt is not None and r >= next_ckpt:
                self._carry_live = {"sched": carry_sched,
                                    "nem": carry_nem,
                                    "host": carry_host}
                self._save_checkpoint(gen, history, sessions, free, r)
                next_ckpt = r + self.checkpoint_every_rounds

        self._gen_live, self._r_live = gen, r
        self._carry_live = {"sched": carry_sched, "nem": carry_nem,
                            "host": carry_host}
        return r

    def _encode_inject(self, inject_rows) -> "T.Msgs":
        """Encodes this stretch's pending client ops into the [C] inject
        batch the scan takes (an all-invalid batch when there are
        none)."""
        C, N = self.concurrency, self.cfg.n_nodes
        inject = T.Msgs.empty(max(C, 1))
        if not inject_rows:
            return inject
        M = len(inject_rows)
        proc, _, nidx, ts, as_, bs, cs = zip(*inject_rows)
        return inject.replace(
            valid=jnp.arange(max(C, 1)) < M,
            src=jnp.asarray(
                list(np.array(proc) + N) + [0] * (max(C, 1) - M),
                T.I32),
            dest=jnp.asarray(list(nidx) + [0] * (max(C, 1) - M),
                             T.I32),
            type=jnp.asarray(list(ts) + [0] * (max(C, 1) - M),
                             T.I32),
            a=jnp.asarray(list(as_) + [0] * (max(C, 1) - M),
                          T.I32),
            b=jnp.asarray(list(bs) + [0] * (max(C, 1) - M),
                          T.I32),
            c=jnp.asarray(list(cs) + [0] * (max(C, 1) - M),
                          T.I32))

    def _exec_scan(self, inject_rows, k_max, stop, history, r):
        """One fused compiled dispatch: encode the injections, run the
        scan (journal-collecting when journaling), drain the
        device-resident rings as ONE packed fetch, and decode the reply
        rows. Returns (k_executed, replies), replies rows being
        (round_stamp, type, a, b, c, reply_to, payload-or-None)."""
        C = self.concurrency
        program, cfg = self.program, self.cfg
        inject = self._encode_inject(inject_rows)
        if self.journal is not None:
            if self._scan_journal_fn is None:
                from ..sim import make_scan_fn
                self._scan_journal_fn = make_scan_fn(
                    program, cfg, journal_cap=self.journal_scan_cap,
                    reply_cap=self.reply_log_cap, donate=True,
                    shardings=self._shardings)
            t_d0 = time.perf_counter()
            self.sim, _cm, k, rl, buf = self._scan_journal_fn(
                self.sim, inject, jnp.int32(k_max), stop)
            self._tel_span("dispatch", t_d0, time.perf_counter())
            self._state_cache = None
            # stretch N+1 is in flight: overlap the host-side
            # analysis of segment N with its device time
            self._overlap_feed(history)
            # the metric ring rides the SAME packed fetch (zero new
            # host transfers; an empty tuple when rings are off)
            ring = self.sim.telemetry if self.telemetry_rings else ()
            tree = (buf, rl, k, self.sim.net.next_mid, ring)
            if self._pack_buf is None:
                self._pack_buf = self._make_packer(tree)
            pack, unpack = self._pack_buf
            # ONE fetched array per dispatch: k and next_mid ride the
            # packed buffer (every separately fetched array is its own
            # round trip on remote backends)
            packed = pack(tree)
            t_f0 = time.perf_counter()
            flat = self.transfer.fetch(packed)
            self._tel_span("device-get", t_f0, time.perf_counter(),
                           args={"drains": self.transfer.drains,
                                 "host-bytes": self.transfer.host_bytes})
            buf, (rlog, rounds, plog, rn), k, self._next_mid, ring_h = \
                unpack(flat)
            if self.telemetry_rings:
                self._ring_host = ring_h
            k, self._next_mid = int(k), int(self._next_mid)
            quiet_cm = jax.tree.map(
                lambda a: np.zeros_like(a[:max(C, 1)]), rlog)
            for i in range(k):
                io_i = jax.tree.map(lambda b, i=i: b[i], buf)
                self._journal_round(io_i, quiet_cm, r + i)
            rn = int(rn)
            if rn:
                # reply recv rows at their true rounds (stamps are
                # post-round: the producing round is stamp-1)
                self.journal.log_batch(
                    "recv", rlog.mid[:rn],
                    np.asarray([self._time_ns(int(s) - 1)
                                for s in rounds[:rn]]),
                    rlog.src[:rn], rlog.dest[:rn],
                    node_names=self.node_names)
        else:
            if self._scan_fn is None:
                from ..sim import make_scan_fn
                self._scan_fn = make_scan_fn(
                    program, cfg, reply_cap=self.reply_log_cap,
                    donate=True, shardings=self._shardings)
            t_d0 = time.perf_counter()
            self.sim, _cm, k, rl = self._scan_fn(
                self.sim, inject, jnp.int32(k_max), stop)
            self._tel_span("dispatch", t_d0, time.perf_counter())
            self._state_cache = None
            # stretch N+1 is in flight: overlap the host-side
            # analysis of segment N with its device time
            self._overlap_feed(history)
            ring = self.sim.telemetry if self.telemetry_rings else ()
            tree = (rl, k, self.sim.net.next_mid, ring)
            if self._pack_replies is None:
                self._pack_replies = self._make_packer(tree)
            pack, unpack = self._pack_replies
            # ONE fetched array per dispatch (see journal branch)
            packed = pack(tree)
            t_f0 = time.perf_counter()
            flat = self.transfer.fetch(packed)
            self._tel_span("device-get", t_f0, time.perf_counter(),
                           args={"drains": self.transfer.drains,
                                 "host-bytes": self.transfer.host_bytes})
            (rlog, rounds, plog, rn), k, self._next_mid, ring_h = \
                unpack(flat)
            if self.telemetry_rings:
                self._ring_host = ring_h
            k, self._next_mid = int(k), int(self._next_mid)
            rn = int(rn)
        return k, self._decode_replies(rlog, rounds, plog, rn)

    def _exec_cscan(self, rows, k_max, stop, history, r):
        """One continuous-mode dispatch: encode the scheduled rows as a
        [Q] inject batch with per-row round offsets (relative to r), run
        the sched-inject scan, and drain replies + per-row assigned mids
        as ONE packed fetch. Returns (k_executed, replies, inj_mids);
        inj_mids[j] is -1 for rows the window did not reach."""
        C = self.concurrency
        program, cfg = self.program, self.cfg
        N, Q = cfg.n_nodes, max(self.concurrency, 1)
        # numpy-columnar encode (generators.sched_columns, shared with
        # the fleet driver's [fleet, Q] batch assembly): one asarray per
        # field instead of per-row Python loops
        cols = g.sched_columns(rows, r, Q, N)
        inject = T.Msgs.empty(Q)
        at = cols["at"]
        if rows:
            inject = inject.replace(
                valid=jnp.asarray(cols["valid"]),
                src=jnp.asarray(cols["src"]),
                dest=jnp.asarray(cols["dest"]),
                type=jnp.asarray(cols["type"]),
                a=jnp.asarray(cols["a"]),
                b=jnp.asarray(cols["b"]),
                c=jnp.asarray(cols["c"]))
        if self._cscan_fn is None:
            from ..sim import make_scan_fn
            self._cscan_fn = make_scan_fn(
                program, cfg, reply_cap=self.reply_log_cap, donate=True,
                shardings=self._shardings, sched_inject=True)
        t_d0 = time.perf_counter()
        self.sim, _cm, k, rl, im = self._cscan_fn(
            self.sim, inject, jnp.asarray(at), jnp.int32(k_max), stop)
        self._tel_span("dispatch", t_d0, time.perf_counter())
        self._state_cache = None
        # window N+1 is in flight: overlap segment N's analysis
        self._overlap_feed(history)
        ring = self.sim.telemetry if self.telemetry_rings else ()
        tree = (rl, im, k, self.sim.net.next_mid, ring)
        if self._pack_creplies is None:
            self._pack_creplies = self._make_packer(tree)
        pack, unpack = self._pack_creplies
        packed = pack(tree)
        t_f0 = time.perf_counter()
        flat = self.transfer.fetch(packed)
        self._tel_span("device-get", t_f0, time.perf_counter(),
                       args={"drains": self.transfer.drains,
                             "host-bytes": self.transfer.host_bytes})
        (rlog, rounds, plog, rn), im, k, self._next_mid, ring_h = \
            unpack(flat)
        if self.telemetry_rings:
            self._ring_host = ring_h
        k, self._next_mid = int(k), int(self._next_mid)
        return (k, self._decode_replies(rlog, rounds, plog, int(rn)),
                im)

    def _decode_replies(self, rlog, rounds, plog, rn: int) -> list:
        """Materializes the drained reply-log rows as plain tuples for
        the loop's completion pass (shared with the fleet driver, which
        feeds each cluster its own row of the batched log)."""
        use_payload = getattr(self.program,
                              "reply_payload_words", 0) > 0
        return [(int(rounds[j]), int(rlog.type[j]),
                 int(rlog.a[j]), int(rlog.b[j]),
                 int(rlog.c[j]), int(rlog.reply_to[j]),
                 plog[j] if use_payload else None)
                for j in range(rn)]

    def _journal_round(self, io, client_msgs, r: int):
        """Materializes this round's device messages as journal rows
        (the interactive-mode analogue of the send!/recv! hooks,
        reference `net.clj:207,243`)."""
        import numpy as np
        io = jax.device_get(io)
        inject_sent, outbox_sent, inbox = io[0], io[1], io[2]
        cm = jax.device_get(client_msgs)
        t_ns = self._time_ns(r)
        for batch, typ in ((inject_sent, "send"), (outbox_sent, "send"),
                           (inbox, "recv"), (cm, "recv")):
            valid = np.asarray(batch.valid).reshape(-1)
            if not valid.any():
                continue
            mid = np.asarray(batch.mid).reshape(-1)[valid]
            src = np.asarray(batch.src).reshape(-1)[valid]
            dest = np.asarray(batch.dest).reshape(-1)[valid]
            self.journal.log_batch(typ, mid, np.full(mid.shape, t_ns),
                                   src, dest, node_names=self.node_names)
        if len(io) >= 5:
            self._journal_edges(io[3], io[4], r)

    def _journal_edges(self, edge_out, edge_in, r: int):
        """Synthesizes journal rows for static edge-channel traffic. Ids
        are deterministic functions of (send round, edge, send lane): the
        send side stamps round * LANE_STRIDE + lane, the channels carry
        it with the message (`EdgeChannels.sent`, tracked on journaled
        runs), so every recv row pairs exactly to its send — under any
        latency distribution, live slow!/fast! scale, or spill-mode lane
        reassignment (the reference's journal is exact too,
        `net/journal.clj:225-239`). High id bit space keeps edge ids
        disjoint from pool message ids."""
        import numpy as np
        prog = self.program
        N, D = self.cfg.n_nodes, prog.D
        L = prog.lanes
        if not hasattr(self, "_edge_topo"):
            # static for the runner's lifetime: materialize once
            self._edge_topo = (np.asarray(prog.neighbors),
                               np.asarray(prog.rev))
        nb, rev = self._edge_topo
        base = 1 << 40

        ov = np.asarray(edge_out.valid)              # [N, D, L]
        if ov.any():
            n_i, d_i, l_i = np.nonzero(ov)
            ids = base + (r * (N * D * L)
                          + (n_i * D + d_i) * L + l_i).astype(np.int64)
            self.journal.log_batch(
                "send", ids, np.full(ids.shape, self._time_ns(r)),
                n_i.astype(np.int32), nb[n_i, d_i].astype(np.int32),
                node_names=self.node_names)
        iv = np.asarray(edge_in.valid)               # [N, D, Lc] (receiver)
        if iv.any():
            from ..net.static import LANE_STRIDE
            m_i, e_i, l_i = np.nonzero(iv)
            senders = nb[m_i, e_i]
            send_d = rev[m_i, e_i]
            packed = np.asarray(edge_in.sent)[m_i, e_i, l_i]
            send_round = packed // LANE_STRIDE
            send_lane = packed % LANE_STRIDE         # pre-spill lane
            ids = base + (send_round.astype(np.int64) * (N * D * L)
                          + (senders * D + send_d) * L + send_lane
                          ).astype(np.int64)
            self.journal.log_batch(
                "recv", ids, np.full(ids.shape, self._time_ns(r)),
                senders.astype(np.int32), m_i.astype(np.int32),
                node_names=self.node_names)

    def _quiet(self) -> bool:
        """Fused quiescence probe, one jitted dispatch: pool empty (no
        in-flight messages) AND edge channels drained (ring cells are
        addressed by round % ring, so rings must empty before virtual time
        may skip) AND the node program reports itself idle."""
        if self._quiet_fn is None:
            prog_q = getattr(self.program, "quiescent", None)

            def quiet(sim):
                q = ~sim.net.pool.valid.any()
                if sim.channels is not None:
                    q = q & ~sim.channels.valid.any()
                if prog_q is not None:
                    q = q & prog_q(sim.nodes)
                return q
            self._quiet_fn = jax.jit(quiet)
        return bool(self.transfer.fetch(self._quiet_fn(self.sim)))


def run_tpu_test(test: dict, test_dir: str) -> dict:
    """Executes a full TPU-path test: run, check, store. The drop-in
    equivalent of the bin path in `core.run` (reference jepsen.core/run!).
    `--fleet N` (N > 1) routes to the fleet runner: N independent
    cluster instances inside one compiled scan, each checked and stored
    per cluster."""
    if int(test.get("fleet") or 1) > 1:
        # --continuous composes since ISSUE 12 (doc/perf.md "vectorized
        # host driver"): continuous shells yield cscan requests and the
        # fleet answers them with one vmapped sched-inject dispatch per
        # wave. Programs whose completions read mutable end-of-stretch
        # state remain rejected per shell (the TpuRunner constructor's
        # continuous guard), exactly as standalone.
        from .fleet_runner import run_fleet_test
        return run_fleet_test(test, test_dir)
    runner = TpuRunner(test)
    test["store_dir"] = test_dir
    # flight recorder (doc/observability.md): --telemetry DIR attaches
    # the session AFTER construction (fleet shells share their fleet's
    # session instead; rings themselves are a cfg capability)
    if runner.telemetry_rings:
        from .. import telemetry as TM
        runner.telemetry = TM.TelemetrySession(
            TM.resolve_dir(test.get("telemetry"), test_dir),
            ms_per_round=runner.ms_per_round)
    # swap the host-net stats checker for the device-counter one, and
    # add the availability block (no-committed-reply gaps in virtual
    # rounds + election accounting; doc/compartment.md) — deterministic
    # per seed apart from its stripped check-wall-s
    from ..checkers.availability import AvailabilityChecker
    test["checker"].checkers["net"] = TpuNetStats(runner)
    test["checker"].checkers["availability"] = AvailabilityChecker(runner)
    if "byzantine" in runner.faults:
        # swap the host wire auditor for the device-evidence one: the
        # TPU journal keeps no bodies, so convictions come from the
        # program's compiled evidence ledgers (checkers/byzantine.py)
        from ..checkers.byzantine import TpuByzantine
        test["checker"].checkers["byzantine"] = TpuByzantine(runner)
    test["nemesis"] = True if test["nemesis_pkg"]["generator"] is not None \
        else None

    from .. import checkpoint as cp
    resume = None
    if test.get("resume"):
        resume = cp.load(test["resume"])
        cp.check_fingerprint(resume, test)

    try:
        try:
            history = runner.run(resume=resume)
        except cp.Preempted:
            # graceful preemption: the final checkpoint is on disk;
            # flush the journal and let the CLI exit EXIT_PREEMPTED
            # (the store dir keeps its in-progress shape — no results,
            # not marked complete)
            if runner.journal is not None:
                runner.journal.close()
            raise
        if runner.telemetry is not None:
            # final record: cumulative quantiles over the WHOLE history
            # — the value the acceptance test pins against PerfChecker
            runner.telemetry.flush(history, runner.final_round,
                                   ring=runner._ring_dict()
                                   if runner._final_ring() is not None
                                   else None,
                                   pipeline=runner.pipeline)
        if runner.pipeline is not None:
            # checkers consume the incrementally-built partitions
            # (register fast path); verdicts stay bit-identical to the
            # sequential path
            test["analysis"] = runner.pipeline
        # the device-resident checker (doc/perf.md "device-resident
        # grading") books its edge-build/screen wall time into the
        # run's TransferStats so results show that work leaving
        # host-blocked time
        test["transfer"] = runner.transfer
        if runner.cfg.enable_byz and runner.sim.byz is not None:
            # the run's injection ledger, straight off the device: the
            # conviction contract is graded against exactly what the
            # compiled masks rewrote (byzantine.assemble_block)
            from .. import byzantine as BZ
            inj = np.asarray(
                runner.transfer.fetch(runner.sim.byz["injected"]))
            test["byz_injected"] = {a: int(inj[i])
                                    for i, a in enumerate(BZ.ATTACKS)}
        results = test["checker"].check(test, history, {})
    finally:
        # a flight recorder must land its trace ESPECIALLY when the run
        # died unexpectedly: close() is idempotent and writes
        # trace.json from whatever spans were recorded
        if runner.telemetry is not None:
            runner.telemetry.close()
    net_block = results.get("net")
    if isinstance(net_block, dict) and "drains" in net_block:
        # the net block renders before the workload checker runs:
        # refresh the transfer ledger so check-time device work
        # (checker-device-s) and any check-time fetches are reported
        net_block.update(runner.transfer.as_dict())
    if runner.pipeline is not None:
        results["analysis-pipeline"] = runner.pipeline.report()
    if resume is not None:
        results["resumed-at-round"] = resume["r"]
    if runner.journal is not None:
        runner.journal.close()
    store.write_history(test_dir, history)
    store.write_results(test_dir, results)
    from ..core import DEFAULTS
    store.write_test(test_dir, {k: str(test[k]) for k in DEFAULTS
                                if k in test})
    store.mark_complete(test_dir)
    log.info("Results valid? %s (store: %s)", results["valid"], test_dir)
    return results
