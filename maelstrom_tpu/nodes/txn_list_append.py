"""Batched transactional list-append over Raft
(serving `workload/txn_list_append.clj`).

Architecture: the raft cluster replicates *opaque commands* — each
transaction is interned host-side to a 16-bit id and rides the raft log as
an `OP_TXN` entry (the classic replicated-state-machine split: consensus
orders commands it does not interpret). The leader's reply carries the
transaction's commit position; the host then deterministically replays the
committed log prefix (same interned commands, same order, on every replica)
to materialize read results exactly as of the transaction's serialization
point. Total order through a single log => strict serializability, the
default consistency model the checker demands (`core.clj:126-131`).

The reference reaches the same guarantee differently (CAS on a root
pointer in lin-kv over immutable thunks, `demo/ruby/datomic_list_append.rb`
— see `demo/python/datomic_list_append.py` for that design on the host
path);
running the data plane through raft instead exercises the batched
consensus machinery end to end."""

from __future__ import annotations

import numpy as np

from . import EncodeCapacityError, register
from .raft import OP_TXN, RaftProgram, T_TXN, T_TXN_OK


def apply_txn(db: dict, txn) -> tuple[dict, list]:
    """Pure micro-op interpreter (same semantics as the reference's
    datomic demos): reads observe the current list (None if absent),
    appends extend it."""
    out = []
    for f, k, v in txn:
        key = str(k)
        if f == "r":
            got = db.get(key)
            out.append([f, k, list(got) if got is not None else None])
        else:
            db = {**db, key: list(db.get(key) or []) + [v]}
            out.append([f, k, v])
    return db, out


@register
class TxnRaftProgram(RaftProgram):
    name = "txn-list-append"
    # the replicated command machinery is micro-op-agnostic; subclasses
    # swap the interpreter to serve other transactional workloads
    # (nodes/txn_rw_register.py)
    apply = staticmethod(apply_txn)
    needs_state_reads = True
    # completion() reads only committed log entries (final and
    # replica-identical), so end-of-stretch state reads are exact and the
    # runner's collect-replies scan mode stays sound
    state_reads_final = True

    def __init__(self, opts, nodes):
        super().__init__(opts, nodes)
        # incremental replay cache: committed entries are final and
        # identical on every replica, so the db materialized up to
        # `_replay_next - 1` and the per-position outputs never change —
        # each completion extends the replay instead of re-running the
        # whole prefix (O(total ops), not O(ops^2) across a run)
        self._replay_db: dict = {}
        self._replay_outs: dict[int, list] = {}
        self._replay_next = 0

    # --- host boundary ---

    def request_for_op(self, op):
        return {"type": "txn", "txn": op["value"]}

    def encode_body(self, body, intern):
        tid = intern.peek(body["txn"])
        if tid is None:
            if len(intern) > 0xFFFF:
                raise EncodeCapacityError(
                    "txn command table full (65536 commands)")
            tid = intern.id(body["txn"])
        return (T_TXN, tid, 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_TXN_OK:
            return {"type": "txn_ok", "position": int(a)}
        return super().decode_body(t, a, b, c, intern)

    def completion(self, op, body, read_state, intern):
        if body["type"] != "txn_ok":
            return super().completion(op, body, read_state, intern)
        p = body["position"]
        if p >= self._replay_next:
            # extend the replay from any replica whose commit has reached
            # p (the leader's has; entries <= commit are final and
            # identical on every replica)
            row = None
            for i in range(self.n_nodes):
                cand = read_state(i)
                if int(cand["commit"]) >= p and int(cand["log_len"]) > p:
                    row = cand
                    break
            assert row is not None, "no replica has the committed prefix"
            log_a = np.asarray(row["log_a"])
            log_b = np.asarray(row["log_b"])
            for i in range(self._replay_next, p + 1):
                if (log_a[i] & 0xF) != OP_TXN:
                    continue
                tid = ((log_b[i] >> 8) & 0xFF) << 8 | (log_b[i] & 0xFF)
                txn = intern.value(int(tid))
                self._replay_db, out = self.apply(self._replay_db, txn)
                self._replay_outs[i] = out
            self._replay_next = p + 1
        completed = self._replay_outs.get(p)
        assert completed is not None, f"no OP_TXN entry at position {p}"
        return {**op, "type": "ok", "value": completed}
