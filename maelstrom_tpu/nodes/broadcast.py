"""Batched broadcast node: partition-tolerant gossip over a topology.

The TPU-native analogue of the reference's retrying broadcast demo
(`demo/ruby/broadcast.rb` serving `workload/broadcast.clj`), built on the
static edge-channel fast path (`net/static.py`): gossip and acknowledgements
move over fixed neighbor edges as pure gathers; only client RPCs touch the
general flight pool.

Protocol (per round, all N nodes at once):
  - new values (from clients or arriving gossip) are marked `seen` and
    queued `pending` toward every neighbor except the edge they arrived on
    (the skip-sender optimization,
    reference `doc/03-broadcast/02-performance.md:73-76`)
  - each edge sends up to `gossip_per_neighbor` pending values per round,
    rotating the selection window so a slow acknowledgement round-trip
    cannot starve newer values
  - acknowledgement is a *seen-digest*: a 64-bit window of the sender's
    `seen` bitmap, owed on an edge whenever gossip arrives on it (one owed
    window is paid per edge per round). Receiving a digest clears
    `pending`/`inflight` for every covered value the neighbor already has.
    Digests are idempotent, so loss and partitions only delay convergence:
    unacknowledged values are requeued by a two-generation retry tick and
    retransmitted, which re-triggers the digest owing — the gossip
    analogue of the reference demo's retry-until-ack loop, with no
    per-message timer state.

State per node: `seen` [V] and per-edge `pending` [D, V] bit-planes; one
step is elementwise mask algebra plus a per-edge top_k — no scatters, no
sorts (XLA:TPU serializes colliding scatters; see net/static.py).

Reads reply with a bare `read_ok` on the wire; the set itself is
materialized host-side from the `seen` row at completion time (see
`maelstrom_tpu.nodes` docstring)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..net.static import EdgeConfig, EdgeMsgs, reverse_index
from ..net.tpu import I32
from ..workloads.broadcast import TOPOLOGIES, topology_indices
from . import (EncodeCapacityError, NodeProgram, edge_capacity,
               edge_timing,
               register)

T_BCAST = 10      # client -> node: a = value index
T_BCAST_OK = 11
T_READ = 12
T_READ_OK = 13    # bare ack; value materialized host-side
T_GOSSIP = 14     # edge: a = value index
T_DIGEST = 15     # edge: a = window, b|c = 64-bit seen bits of that window


@register
class BroadcastProgram(NodeProgram):
    name = "broadcast"
    needs_state_reads = True
    is_edge = True
    # every inbox lane is decoded by message type (gossip/digest), never
    # by position: safe for the spill write's lane reassignment
    edge_lanes_symmetric = True

    def __init__(self, opts, nodes):
        super().__init__(opts, nodes)
        topo = (opts.get("topology_map")
                or TOPOLOGIES[opts.get("topology", "grid")](nodes))
        nb = topology_indices(topo, nodes)
        self.neighbors = jnp.asarray(nb)              # [N, D]
        self.rev = jnp.asarray(reverse_index(nb))
        self.D = int(self.neighbors.shape[1])
        self.V = int(opts.get("max_values", 1024))
        self.n_windows = (self.V + 63) // 64
        self.Vp = self.n_windows * 64                 # padded bitmap width
        self.per_nb = min(int(opts.get("gossip_per_neighbor", 4)), self.V)
        # eager mode: resend pending values every round until a digest
        # clears them (no send-once aging) — maximum message load per
        # round; used by the throughput benchmark. Default is the
        # efficient send-once-plus-retry protocol.
        self.eager_resend = bool(opts.get("eager_resend", False))
        # naive mode: forward each new value once per edge (optionally
        # skipping the arrival edge), no digests, no retransmission —
        # the exact protocol of the reference's non-retrying
        # `demo/ruby/broadcast.rb`, whose message economics the tutorial
        # measurements are built on
        # (`doc/03-broadcast/02-performance.md:22-260`). Values CAN be
        # lost under partitions/loss, exactly as the reference
        # demonstrates — that's the teaching point.
        self.naive = bool(opts.get("naive_broadcast", False))
        self.skip_sender = bool(opts.get("skip_sender", True))
        # digest mode retransmits every value until acknowledged, so a
        # destroyed in-flight copy only delays convergence; the naive
        # mode sends each value once — destroying one is permanent,
        # undetectable loss, so it must invalidate the run
        # (reference `net.clj:188-246` never destroys without loss/
        # partition; VERDICT r2 "grid 25, 100 ms exponential")
        self.tolerates_channel_overwrites = not self.naive
        self.lanes = self.per_nb + (0 if self.naive else 1)  # +digest lane
        self.ring, retry, _lat = edge_timing(opts, len(nodes))
        # a digest for any window returns within the round-trip plus one
        # full window rotation
        self.retry_rounds = retry + self.n_windows
        self.inbox_cap = int(opts.get("inbox_cap", 4))   # client RPCs only
        self.outbox_cap = self.inbox_cap
        # read completions decode the node's seen bitmap from the reply
        # log's payload (packed 32 values per i32 word): exact at the
        # reply round, zero extra device round trips, and collect-mode
        # safe (see NodeProgram.reply_payload_words)
        self.reply_payload_words = self.n_windows * 2
        spill, chan_lanes, uniform = edge_capacity(opts, self)
        self.edge_cfg = EdgeConfig(n_nodes=self.n_nodes, degree=self.D,
                                   lanes=chan_lanes, ring=self.ring,
                                   spill=spill, uniform_arrival=uniform)

    def init_state(self):
        N, D, V = self.n_nodes, self.D, self.V
        return {"seen": jnp.zeros((N, V), bool),
                "pending": jnp.zeros((N, D, V), bool),
                # two inflight generations: young -> old -> requeued at
                # successive retry ticks, so no value is retransmitted
                # before a digest has had a full period to arrive
                "inflight": jnp.zeros((N, D, V), bool),
                "inflight_old": jnp.zeros((N, D, V), bool),
                # digest windows owed per edge (set by gossip arrivals)
                "owed": jnp.zeros((N, D, self.n_windows), bool)}

    def _select_gossip(self, pending, round_):
        """Rotating top_k gossip selection per edge: up to `per_nb`
        pending values, window rotated by round so a slow round trip
        cannot starve newer values. Returns (sel [N,D,per_nb] bool,
        topi value indices, sent [N,D,V] one-hot union)."""
        V = self.V
        vee = jnp.arange(V, dtype=I32)
        rot = (vee - round_ * self.per_nb) % V
        prio = jnp.where(pending, V - rot, 0)
        topv, topi = jax.lax.top_k(prio, self.per_nb)
        sel = topv > 0
        sent = jnp.zeros(pending.shape, bool)
        for j in range(self.per_nb):
            sent |= sel[:, :, j, None] & (topi[:, :, j, None] == vee)
        return sel, topi, sent

    def _digest_known(self, edge_in: EdgeMsgs, L: int):
        """Digest receive: [N, D, V] bool of values each edge's neighbor
        has proven it holds. Lane content reduced over lanes. Normally
        one digest per edge per round; the spill write can land two
        (sent in different rounds) in one cell — last lane wins, the
        ignored one is re-owed when its gossip retransmits (digests are
        idempotent). Shared with the batched node (broadcast_batched.py):
        acknowledgement is value-based, so it is independent of how the
        values traveled (single-value gossip or distilled ranges)."""
        N, D, V = self.n_nodes, self.D, self.V
        vee = jnp.arange(V, dtype=I32)
        d_in = edge_in.valid & (edge_in.type == T_DIGEST)
        has_digest = d_in.any(axis=2)                       # [N, D]

        def lane_pick(field):
            out = jnp.zeros((N, D), I32)
            for l in range(L):
                out = jnp.where(d_in[:, :, l], field[:, :, l], out)
            return out
        w_in = lane_pick(edge_in.a)
        b_in, c_in = lane_pick(edge_in.b), lane_pick(edge_in.c)
        j = vee - w_in[:, :, None] * 64                     # [N, D, V]
        in_window = (j >= 0) & (j < 64)
        bit = jnp.where(
            j < 32,
            (b_in[:, :, None] >> jnp.clip(j, 0, 31)) & 1,
            (c_in[:, :, None] >> jnp.clip(j - 32, 0, 31)) & 1)
        return has_digest[:, :, None] & in_window & (bit == 1)

    def _digest_out(self, seen, owed, arrived):
        """Digest send half (shared with broadcast_batched.py): owe the
        windows gossip arrived in, pay one owed window per edge per
        round. Returns (owed', have_owed [N, D], w_send, b_out, c_out).

        Digest payload: 64 seen-bits of each edge's owed window. Words
        are packed once per node per window, then selected per edge with
        an unrolled compare — a dynamic [N, D, 64] gather here serializes
        on TPU (~300 ms/round at 100k nodes)."""
        N, D, V, W = self.n_nodes, self.D, self.V, self.n_windows
        arrived_pad = jnp.pad(arrived, ((0, 0), (0, 0), (0, self.Vp - V)))
        owed = owed | arrived_pad.reshape(N, D, W, 64).any(axis=3)
        have_owed = owed.any(axis=2)                        # [N, D]
        www = jnp.arange(W, dtype=I32)
        w_send = jnp.argmax(owed.astype(I32) * (W - www), axis=2)  # [N, D]
        owed = owed & ~(have_owed[:, :, None] & (w_send[:, :, None] == www))

        seen_pad = jnp.pad(seen, ((0, 0), (0, self.Vp - V)))
        wins = seen_pad.reshape(N, W, 64)
        words_b = jnp.zeros((N, W), I32)
        words_c = jnp.zeros((N, W), I32)
        for jj in range(32):
            words_b |= wins[:, :, jj].astype(I32) << jj
            words_c |= wins[:, :, 32 + jj].astype(I32) << jj
        b_out = jnp.zeros((N, D), I32)
        c_out = jnp.zeros((N, D), I32)
        for w in range(W):
            m = w_send == w
            b_out = jnp.where(m, words_b[:, w][:, None], b_out)
            c_out = jnp.where(m, words_c[:, w][:, None], c_out)
        return owed, have_owed, w_send, b_out, c_out

    def edge_step(self, state, edge_in: EdgeMsgs, client_in, ctx):
        """(state, edge_in [N,D,L], client_in Msgs [N,K]) ->
        (state', edge_out [N,D,L], client_out Msgs [N,K])."""
        N, D, V = self.n_nodes, self.D, self.V
        L = int(edge_in.valid.shape[2])   # channel lanes (>= out lanes)
        seen, pending = state["seen"], state["pending"]
        inflight = state["inflight"]
        vee = jnp.arange(V, dtype=I32)
        edge_ok = self.neighbors >= 0                       # [N, D]

        # --- gossip arrivals -> arrived[n, d, v] ---
        g_in = edge_in.valid & (edge_in.type == T_GOSSIP)   # [N, D, L]
        gv = jnp.clip(edge_in.a, 0, V - 1)
        arrived = jnp.zeros((N, D, V), bool)
        for l in range(L):
            arrived |= (g_in[:, :, l, None]
                        & (gv[:, :, l, None] == vee))

        # --- client broadcasts -> cb[n, v] ---
        K = client_in.valid.shape[1]
        is_cb = client_in.valid & (client_in.type == T_BCAST)
        is_read = client_in.valid & (client_in.type == T_READ)
        cv = jnp.clip(client_in.a, 0, V - 1)
        cb = jnp.zeros((N, V), bool)
        for k in range(K):
            cb |= is_cb[:, k, None] & (cv[:, k, None] == vee)

        new = (arrived.any(axis=1) | cb) & ~seen            # [N, V]
        seen = seen | arrived.any(axis=1) | cb

        # --- client replies (shared by both protocols) ---
        reply_type = jnp.where(is_cb, T_BCAST_OK,
                               jnp.where(is_read, T_READ_OK, 0))
        client_out = client_in.replace(
            valid=is_cb | is_read, dest=client_in.src,
            reply_to=client_in.mid, type=reply_type,
            a=jnp.zeros_like(client_in.a))
        if self.V <= 64:
            # the value set fits the wire: T_READ_OK carries the node's
            # post-arrival seen bitmap in b|c (words 0|1 of the shared
            # `_pack_seen_words` layout), so a read's observed set is
            # exact at its serve round — no host-side snapshot needed.
            # bench_graded's racing reads (and its phase-B cross-check)
            # grade real propagation lag from this payload.
            words = self._pack_seen_words(seen)            # [N, 2]
            client_out = client_out.replace(
                b=jnp.where(is_read, words[:, 0][:, None], 0),
                c=jnp.where(is_read, words[:, 1][:, None], 0))

        if self.naive:
            # forward each new value once per edge; skip-sender drops the
            # FIRST arrival edge only (reference
            # `doc/03-broadcast/02-performance.md:73-76`: the node
            # processes one message at a time, so it forwards deg-1
            # copies on first receipt even when duplicates arrive
            # concurrently); nothing is retransmitted or acknowledged
            first_arrival = arrived & (
                jnp.cumsum(arrived.astype(I32), axis=1) == 1)
            known = (first_arrival if self.skip_sender
                     else jnp.zeros((N, D, V), bool))
            pending = ((pending | (new[:, None, :] & edge_ok[:, :, None]))
                       & ~known)
            sel, topi, sent = self._select_gossip(pending, ctx["round"])
            pending = pending & ~sent
            edge_out = EdgeMsgs(
                valid=sel & edge_ok[:, :, None],
                type=jnp.full((N, D, self.per_nb), T_GOSSIP, I32),
                a=topi.astype(I32),
                b=jnp.zeros((N, D, self.per_nb), I32),
                c=jnp.zeros((N, D, self.per_nb), I32))
            return ({"seen": seen, "pending": pending,
                     "inflight": state["inflight"],
                     "inflight_old": state["inflight_old"],
                     "owed": state["owed"]},
                    edge_out, client_out)

        # --- digests clear pending for values the neighbor has ---
        neighbor_has = self._digest_known(edge_in, L)

        # queue new values everywhere except their arrival edge; drop
        # pending/inflight the moment we know the neighbor has the value.
        # A value is sent once (pending -> inflight) and retransmitted by
        # the periodic global requeue below until a digest proves delivery
        # — send-once-plus-retry, like the reference demo's retry loop,
        # with digest idempotence instead of per-message timers.
        known = arrived | neighbor_has
        inflight_old = state["inflight_old"]
        requeue = (ctx["round"] % self.retry_rounds) == 0
        pending = ((pending | (new[:, None, :] & edge_ok[:, :, None])
                    | (inflight_old & requeue))
                   & ~known)
        inflight_old = jnp.where(requeue, inflight, inflight_old) & ~known
        inflight = inflight & ~known & ~requeue

        # --- pick gossip to send: rotating top_k per edge ---
        sel, topi, sent = self._select_gossip(pending, ctx["round"])
        if not self.eager_resend:
            pending = pending & ~sent
            inflight = inflight | sent

        # --- digest scheduling: ack exactly the windows gossip arrived in,
        # one owed window per edge per round ---
        owed, have_owed, w_send, b_out, c_out = self._digest_out(
            seen, state["owed"], arrived)

        # --- assemble edge output: digest lane 0, gossip lanes 1.. ---
        send_digest = have_owed & edge_ok
        e_valid = jnp.concatenate(
            [send_digest[:, :, None], sel & edge_ok[:, :, None]], axis=2)
        e_type = jnp.concatenate(
            [jnp.full((N, D, 1), T_DIGEST, I32),
             jnp.full((N, D, self.per_nb), T_GOSSIP, I32)], axis=2)
        e_a = jnp.concatenate([w_send[:, :, None], topi.astype(I32)],
                              axis=2)
        e_b = jnp.concatenate(
            [b_out[:, :, None], jnp.zeros((N, D, self.per_nb), I32)],
            axis=2)
        e_c = jnp.concatenate(
            [c_out[:, :, None], jnp.zeros((N, D, self.per_nb), I32)],
            axis=2)
        edge_out = EdgeMsgs(valid=e_valid, type=e_type, a=e_a, b=e_b,
                            c=e_c)

        return ({"seen": seen, "pending": pending, "inflight": inflight,
                 "inflight_old": inflight_old, "owed": owed},
                edge_out, client_out)

    def quiescent(self, state):
        """True when no value is awaiting digest confirmation (edge
        channels are checked separately by the runner)."""
        return ~(state["pending"].any() | state["inflight"].any()
                 | state["inflight_old"].any())

    def _pack_seen_words(self, rows):
        """[..., V] bool seen rows -> [..., n_windows*2] i32, 32 values
        per word (low bit = lowest value index). The ONE bitmap layout:
        both the reply-log payload and the V<=64 read-reply wire words
        (b = word 0, c = word 1) derive from it."""
        lead = rows.shape[:-1]
        pad = jnp.pad(rows, [(0, 0)] * (rows.ndim - 1)
                      + [(0, self.Vp - self.V)])
        bits = pad.reshape(*lead, self.n_windows * 2, 32).astype(I32)
        return (bits << jnp.arange(32, dtype=I32)).sum(axis=-1)

    def reply_payload(self, state, node_idx):
        """[M] node indices -> [M, W] i32: the nodes' seen bitmaps."""
        return self._pack_seen_words(state["seen"][node_idx])

    def completion_payload(self, op, body, payload, intern):
        if body["type"] == "read_ok":
            words = np.asarray(payload, dtype=np.uint32)
            vals = []
            for w in range(len(words)):
                bits = int(words[w])
                base = w * 32
                while bits:
                    b = bits & -bits
                    vals.append(base + b.bit_length() - 1)
                    bits ^= b
            return {**op, "type": "ok",
                    "value": [intern.value(v) for v in vals
                              if v < self.V]}
        return {**op, "type": "ok"}

    # --- host boundary ---

    def request_for_op(self, op):
        if op["f"] == "broadcast":
            return {"type": "broadcast", "message": op["value"]}
        return {"type": "read"}

    def encode_body(self, body, intern):
        if body["type"] == "broadcast":
            i = intern.peek(body["message"])
            if i is None:
                if len(intern) >= self.V:
                    # capacity check before interning: the failure path
                    # is survivable, so it must not grow the table
                    raise EncodeCapacityError(
                        f"broadcast value table full ({self.V}); raise "
                        f"--max-values")
                i = intern.id(body["message"])
            return (T_BCAST, i, 0, 0)
        return (T_READ, 0, 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_BCAST_OK:
            return {"type": "broadcast_ok"}
        if t == T_READ_OK:
            return {"type": "read_ok"}
        return super().decode_body(t, a, b, c, intern)

    def completion(self, op, body, read_state, intern):
        if body["type"] == "read_ok":
            seen_row = np.asarray(read_state()["seen"])
            return {**op, "type": "ok",
                    "value": [intern.value(int(i))
                              for i in np.nonzero(seen_row)[0]]}
        return {**op, "type": "ok"}
