"""Batched broadcast node: partition-tolerant gossip over a topology.

The TPU-native analogue of the reference's retrying broadcast demo
(`demo/ruby/broadcast.rb` serving `workload/broadcast.clj`): each node keeps
a `seen` set; new values are forwarded to every neighbor except the sender
(the skip-sender optimization, reference `doc/03-broadcast/02-performance.md:73-76`),
acknowledged on receipt, and retransmitted until acknowledged so values
survive partitions and message loss.

All N nodes' sets live in three bit-plane arrays:

  seen     [N, V]     value v is in node n's set
  pending  [N, D, V]  v must be sent to neighbor d (not yet sent / requeued)
  inflight [N, D, V]  v was sent to d, awaiting gossip_ok

One step is a handful of masked scatters over these planes plus a top_k
per (node, neighbor) to pick the next gossip batch — no per-node control
flow, so the whole cluster advances in one XLA dispatch.

Reads reply with a bare `read_ok` on the wire; the set itself (unbounded,
doesn't fit a fixed body) is materialized host-side from the `seen` row at
completion time (see `maelstrom_tpu.nodes` docstring)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..net.tpu import I32, Msgs
from ..workloads.broadcast import TOPOLOGIES, topology_indices
from . import NodeProgram, register

T_BCAST = 10      # client -> node: a = value index
T_BCAST_OK = 11
T_READ = 12
T_READ_OK = 13    # bare ack; value materialized host-side
T_GOSSIP = 14     # node -> node: a = value index
T_GOSSIP_OK = 15  # ack: a = value index


@register
class BroadcastProgram(NodeProgram):
    name = "broadcast"
    needs_state_reads = True

    def __init__(self, opts, nodes):
        super().__init__(opts, nodes)
        topo = TOPOLOGIES[opts.get("topology", "grid")](nodes)
        self.neighbors = jnp.asarray(
            topology_indices(topo, nodes))            # [N, D]
        self.D = self.neighbors.shape[1]
        self.V = int(opts.get("max_values", 1024))
        self.per_nb = int(opts.get("gossip_per_neighbor", 4))
        lat = (opts.get("latency") or {}).get("mean", 0)
        ms_per_round = opts.get("ms_per_round", 1.0)
        # retransmit after a round-trip (2 hops) plus slack
        self.retry_rounds = max(int(4 * lat / ms_per_round), 10)
        self.inbox_cap = int(opts.get("inbox_cap", 2 * self.D + 4))
        self.outbox_cap = self.inbox_cap + self.D * self.per_nb

    def init_state(self):
        N, D, V = self.n_nodes, self.D, self.V
        return {"seen": jnp.zeros((N, V), bool),
                "pending": jnp.zeros((N, D, V), bool),
                "inflight": jnp.zeros((N, D, V), bool),
                "next_retry": jnp.zeros((N, D), I32)}

    def step(self, state, inbox, ctx):
        N, K, D, V = self.n_nodes, self.inbox_cap, self.D, self.V
        nb = self.neighbors
        seen, pending = state["seen"], state["pending"]
        inflight, next_retry = state["inflight"], state["next_retry"]

        rows = jnp.broadcast_to(jnp.arange(N, dtype=I32)[:, None], (N, K))
        v = jnp.clip(inbox.a, 0, V - 1)
        is_gossip = inbox.valid & (inbox.type == T_GOSSIP)
        is_cb = inbox.valid & (inbox.type == T_BCAST)
        is_ack = inbox.valid & (inbox.type == T_GOSSIP_OK)
        is_read = inbox.valid & (inbox.type == T_READ)
        carrier = is_gossip | is_cb

        new = carrier & ~seen[rows, v]
        seen = seen.at[jnp.where(carrier, rows, N), v].set(True, mode="drop")

        # [N, K, D] slot-neighbor masks
        nb_valid = nb >= 0
        src_is_nb = nb[:, None, :] == inbox.src[:, :, None]
        n3 = jnp.broadcast_to(jnp.arange(N, dtype=I32)[:, None, None],
                              (N, K, D))
        d3 = jnp.broadcast_to(jnp.arange(D, dtype=I32)[None, None, :],
                              (N, K, D))
        v3 = jnp.broadcast_to(v[:, :, None], (N, K, D))

        # forward new values to all neighbors except the sender
        add = new[:, :, None] & nb_valid[:, None, :] & ~src_is_nb
        pend_add = jnp.zeros((N, D, V), bool).at[
            jnp.where(add, n3, N), d3, v3].set(True, mode="drop")
        # the sender evidently has the value: stop sending it to them
        clear = (is_gossip | is_ack)[:, :, None] & src_is_nb
        pend_clear = jnp.zeros((N, D, V), bool).at[
            jnp.where(clear, n3, N), d3, v3].set(True, mode="drop")

        pending = (pending | pend_add) & ~pend_clear
        inflight = inflight & ~pend_clear

        # retransmit timer: requeue unacked sends. The timer tracks the
        # OLDEST outstanding send (armed only when inflight was empty), so
        # a steady stream of new sends can't starve a lost message of its
        # retransmission.
        requeue = ctx["round"] >= next_retry
        pending = pending | (inflight & requeue[:, :, None])
        inflight = inflight & ~requeue[:, :, None]
        had_inflight = inflight.any(axis=2)             # [N, D]

        # pick up to per_nb lowest-index pending values per neighbor
        prio = jnp.where(pending,
                         V - jnp.arange(V, dtype=I32)[None, None, :], 0)
        topv, topi = jax.lax.top_k(prio, self.per_nb)   # [N, D, per_nb]
        sel = topv > 0
        ns = jnp.broadcast_to(jnp.arange(N, dtype=I32)[:, None, None],
                              sel.shape)
        ds = jnp.broadcast_to(jnp.arange(D, dtype=I32)[None, :, None],
                              sel.shape)
        sent = jnp.zeros((N, D, V), bool).at[
            jnp.where(sel, ns, N), ds, topi].set(True, mode="drop")
        pending = pending & ~sent
        inflight = inflight | sent
        arm = sel.any(axis=2) & ~had_inflight
        next_retry = jnp.where(arm, ctx["round"] + self.retry_rounds,
                               next_retry)

        # outbox: replies to this round's inbox + gossip batch
        reply_type = jnp.where(
            is_gossip, T_GOSSIP_OK,
            jnp.where(is_cb, T_BCAST_OK,
                      jnp.where(is_read, T_READ_OK, 0)))
        replies = inbox.replace(
            valid=is_gossip | is_cb | is_read,
            dest=inbox.src, reply_to=inbox.mid, type=reply_type,
            a=jnp.where(is_gossip, inbox.a, 0))

        G = D * self.per_nb
        gossip = Msgs.empty((N, G)).replace(
            valid=sel.reshape(N, G) & (jnp.repeat(nb, self.per_nb, axis=1)
                                       >= 0),
            dest=jnp.repeat(nb, self.per_nb, axis=1),
            type=jnp.full((N, G), T_GOSSIP, I32),
            a=topi.reshape(N, G))

        outbox = jax.tree.map(
            lambda r, g: jnp.concatenate([r, g], axis=1), replies, gossip)
        state = {"seen": seen, "pending": pending, "inflight": inflight,
                 "next_retry": next_retry}
        return state, outbox

    def quiescent(self, state):
        """True when no gossip or retransmission is outstanding — lets the
        runner fast-forward idle virtual time."""
        return ~(state["pending"].any() | state["inflight"].any())

    # --- host boundary ---

    def request_for_op(self, op):
        if op["f"] == "broadcast":
            return {"type": "broadcast", "message": op["value"]}
        return {"type": "read"}

    def encode_body(self, body, intern):
        if body["type"] == "broadcast":
            i = intern.id(body["message"])
            if i >= self.V:
                raise ValueError(
                    f"broadcast value table full ({self.V}); raise "
                    f"--max-values")
            return (T_BCAST, i, 0, 0)
        return (T_READ, 0, 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_BCAST_OK:
            return {"type": "broadcast_ok"}
        if t == T_READ_OK:
            return {"type": "read_ok"}
        return super().decode_body(t, a, b, c, intern)

    def completion(self, op, body, read_state, intern):
        if body["type"] == "read_ok":
            seen_row = np.asarray(read_state()["seen"])
            return {**op, "type": "ok",
                    "value": [intern.value(int(i))
                              for i in np.nonzero(seen_row)[0]]}
        return {**op, "type": "ok"}
