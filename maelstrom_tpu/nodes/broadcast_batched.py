"""Batched atomic broadcast: distilled client batches on the wire.

The Chop Chop-shaped sibling of `nodes/broadcast.py` (PAPERS.md, arxiv
2304.07081 "Chop Chop: Byzantine Atomic Broadcast to the Network Limit"):
instead of one network message per client value, client submissions are
*distilled* on the sending side — deduplicated and id-compressed into a
compact columnar record (a contiguous id range ``[lo, lo+n)`` plus an
arithmetic checksum) — and ONE simulated-network message carries the whole
batch. Node-to-node gossip moves the same way: each edge lane carries a
maximal *run* of pending value ids per round (``T_GRANGE``), so a backlog
of n contiguous values crosses an edge in one message instead of n.

Receivers expand batches with a **server-side expansion proof**: the reply
to a batch echoes the id range the server actually expanded, the count of
ids it marked, and the checksum it recomputed *from its own expansion*
(``sum(ids)`` over the expanded mask, mod 2^31-1). The
`BatchedBroadcastChecker` (checkers/set_full.py) verifies every proof
against the batch's claimed values — forged counts, truncated batches,
in-batch duplicates, and replayed batches are each a definite fail — and
then grades the *expanded* per-value stream with the stock set-full
semantics, so the verdict is bit-equal to the unbatched broadcast checker
on the same op stream by construction.

Acknowledgement between nodes reuses the broadcast seen-digest protocol
unchanged (`BroadcastProgram._digest_known` / `_digest_out`): digests are
value-based bitmaps, so they are independent of whether the values
traveled as single-value gossip or as distilled ranges — loss and
partitions only delay convergence, exactly as in the parent protocol.

Message accounting: batch rows carry their op count in payload word `b`;
the program declares this via `unit_words` so the simulated network books
`sent_units`/`recv_units` (client-op units transported) next to the raw
message counters — the Chop Chop headline is ops/s at a fixed msgs/s
budget, and the counters keep that ratio honest (`net/tpu.py`,
`doc/perf.md`)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..checkers.set_full import PROOF_MOD, range_checksum
from ..net.static import EdgeMsgs
from ..net.tpu import I32
from . import EncodeCapacityError, register
from .broadcast import BroadcastProgram, T_DIGEST

__all__ = ["BroadcastBatchedProgram", "PROOF_MOD", "range_checksum"]

T_BATCH = 20       # client -> node: a = lo, b = n, c = claim checksum
T_BATCH_OK = 21    # node -> client: a = lo, b = expanded count,
#                    c = server-recomputed checksum (the expansion proof)
T_BREAD = 22
T_BREAD_OK = 23    # bare ack; set materialized from the reply payload
T_GRANGE = 24      # edge gossip: a = lo, b = n (a run of value ids)


@register
class BroadcastBatchedProgram(BroadcastProgram):
    name = "broadcast-batched"

    def __init__(self, opts, nodes):
        opts = dict(opts)
        # the naive (send-once, no-digest) mode is a teaching device of
        # the parent; batches always retransmit until digest-acked
        opts["naive_broadcast"] = False
        # `gossip_per_neighbor` here counts RANGES per edge per round,
        # not single values; one range lane usually drains a whole
        # contiguous backlog, so the default is small
        opts.setdefault("gossip_per_neighbor", 2)
        super().__init__(opts, nodes)
        # batch rows carry their client-op count in payload word b
        # (0 = a, 1 = b, 2 = c): the net books units alongside messages
        self.unit_words = ((T_BATCH, 1), (T_BATCH_OK, 1), (T_GRANGE, 1))
        # cap the run length a single CLIENT batch record may claim —
        # the same `batch_max` knob as the distiller (--batch-max), so
        # encode rejects any record larger than the batcher may build
        # (wire honesty: the count field is what the expansion proof
        # audits). Gossip ranges are NOT capped by it: node-to-node
        # T_GRANGE runs are server-side re-distillation — arriving
        # batches merge into longer contiguous runs, audited by the
        # digest protocol rather than a per-record proof.
        self.max_batch = min(int(opts.get("batch_max") or self.V),
                             self.V)
        # byzantine forged-proof surface (byzantine.py): when the run's
        # fault set includes the adversary, the culprit's T_BATCH_OK
        # acks are corrupted on the wire (byz_wire_edge) and the proof
        # auditor must convict (checkers/set_full.py)
        from ..byzantine import byz_enabled
        self.byz = byz_enabled(opts)

    def byz_wire_edge(self):
        """Compiled corruption of the client-facing batch acks: the
        culprit node lies about its expansion — the count is inflated
        on odd rounds, the checksum forged on even ones. Both shapes
        are definite `verify_batch_proofs` failures (forged-count /
        truncated-batch / forged-proof), and the corruption leaves the
        honest `lo` so the record still pairs with its invoke."""
        if not self.byz:
            return {}
        from ..byzantine import culprit_rows

        def forge(client_out, culprit, delta, rnd):
            m = (culprit_rows(client_out, culprit)
                 & (client_out.type == T_BATCH_OK))
            odd = (rnd & 1) > 0
            nb = jnp.where(odd, client_out.b + 1 + (delta & 3),
                           client_out.b)
            nc = jnp.where(odd, client_out.c,
                           client_out.c ^ ((delta & 0xFFFF) | 1))
            return m, client_out.a, nb, nc

        return {"forged-proof": forge}

    def _select_ranges(self, pending):
        """Per-edge maximal-run extraction: up to `per_nb` runs of
        contiguous pending value ids, longest-prefix first. Returns
        (lanes [(has, lo, n)], sent [N, D, V] union mask). The gossip
        analogue of `_select_gossip`, except one lane moves a whole run."""
        N, D, V = self.n_nodes, self.D, self.V
        vee = jnp.arange(V, dtype=I32)
        rem = pending
        sent = jnp.zeros(pending.shape, bool)
        lanes = []
        for _ in range(self.per_nb):
            has = rem.any(axis=2)                           # [N, D]
            lo = jnp.argmax(rem, axis=2).astype(I32)        # first pending
            after = vee[None, None, :] >= lo[:, :, None]
            brk = after & ~rem
            first_brk = jnp.min(jnp.where(brk, vee, V), axis=2)
            n = jnp.clip(first_brk - lo, 0, V)
            n = jnp.where(has, jnp.maximum(n, 1), 0)
            mask = (after & (vee[None, None, :] < (lo + n)[:, :, None])
                    & has[:, :, None])
            lanes.append((has, lo, n))
            rem = rem & ~mask
            sent = sent | mask
        return lanes, sent

    def edge_step(self, state, edge_in: EdgeMsgs, client_in, ctx):
        N, D, V = self.n_nodes, self.D, self.V
        L = int(edge_in.valid.shape[2])
        seen, pending = state["seen"], state["pending"]
        inflight = state["inflight"]
        vee = jnp.arange(V, dtype=I32)
        edge_ok = self.neighbors >= 0                       # [N, D]

        # --- range-gossip arrivals: expand [lo, lo+n) per lane ---
        g_in = edge_in.valid & (edge_in.type == T_GRANGE)   # [N, D, L]
        glo = jnp.clip(edge_in.a, 0, V)
        gn = jnp.clip(edge_in.b, 0, V)
        arrived = jnp.zeros((N, D, V), bool)
        for l in range(L):
            arrived |= (g_in[:, :, l, None]
                        & (vee >= glo[:, :, l, None])
                        & (vee < (glo + gn)[:, :, l, None]))

        # --- client batches: expand, and prove the expansion ---
        K = client_in.valid.shape[1]
        is_batch = client_in.valid & (client_in.type == T_BATCH)
        is_read = client_in.valid & (client_in.type == T_BREAD)
        blo = jnp.clip(client_in.a, 0, V)
        bn = jnp.clip(client_in.b, 0, V)
        cb = jnp.zeros((N, V), bool)
        exp_cnt = jnp.zeros((N, K), I32)
        exp_sum = jnp.zeros((N, K), I32)
        for k in range(K):
            m = ((vee[None, :] >= blo[:, k, None])
                 & (vee[None, :] < (blo + bn)[:, k, None]))  # [N, V]
            cb |= is_batch[:, k, None] & m
            # the proof is computed from the server's OWN expansion mask
            # — a range clipped by V (or tampered in flight) yields a
            # count/checksum that cannot match the client's claim
            exp_cnt = exp_cnt.at[:, k].set(m.sum(axis=1).astype(I32))
            exp_sum = exp_sum.at[:, k].set(
                ((vee[None, :] * m).sum(axis=1) % PROOF_MOD).astype(I32))

        new = (arrived.any(axis=1) | cb) & ~seen            # [N, V]
        seen = seen | arrived.any(axis=1) | cb

        # --- client replies: batch acks carry the expansion proof ---
        reply_type = jnp.where(is_batch, T_BATCH_OK,
                               jnp.where(is_read, T_BREAD_OK, 0))
        client_out = client_in.replace(
            valid=is_batch | is_read, dest=client_in.src,
            reply_to=client_in.mid, type=reply_type,
            a=jnp.where(is_batch, client_in.a, 0),
            b=jnp.where(is_batch, exp_cnt, 0),
            c=jnp.where(is_batch, exp_sum, 0))

        # --- digest receive + retry bookkeeping (parent protocol) ---
        neighbor_has = self._digest_known(edge_in, L)
        known = arrived | neighbor_has
        inflight_old = state["inflight_old"]
        requeue = (ctx["round"] % self.retry_rounds) == 0
        pending = ((pending | (new[:, None, :] & edge_ok[:, :, None])
                    | (inflight_old & requeue))
                   & ~known)
        inflight_old = jnp.where(requeue, inflight, inflight_old) & ~known
        inflight = inflight & ~known & ~requeue

        # --- pick ranges to gossip ---
        lanes, sent = self._select_ranges(pending)
        if not self.eager_resend:
            pending = pending & ~sent
            inflight = inflight | sent

        # --- digest send (parent protocol) ---
        owed, have_owed, w_send, b_out, c_out = self._digest_out(
            seen, state["owed"], arrived)

        # --- assemble edge output: digest lane 0, range lanes 1.. ---
        send_digest = have_owed & edge_ok
        e_valid = jnp.concatenate(
            [send_digest[:, :, None]]
            + [(h & edge_ok)[:, :, None] for h, _lo, _n in lanes], axis=2)
        e_type = jnp.concatenate(
            [jnp.full((N, D, 1), T_DIGEST, I32),
             jnp.full((N, D, self.per_nb), T_GRANGE, I32)], axis=2)
        e_a = jnp.concatenate(
            [w_send[:, :, None]] + [lo[:, :, None] for _h, lo, _n in lanes],
            axis=2)
        e_b = jnp.concatenate(
            [b_out[:, :, None]] + [n[:, :, None] for _h, _lo, n in lanes],
            axis=2)
        e_c = jnp.concatenate(
            [c_out[:, :, None], jnp.zeros((N, D, self.per_nb), I32)],
            axis=2)
        edge_out = EdgeMsgs(valid=e_valid, type=e_type, a=e_a, b=e_b,
                            c=e_c)

        return ({"seen": seen, "pending": pending, "inflight": inflight,
                 "inflight_old": inflight_old, "owed": owed},
                edge_out, client_out)

    # --- host boundary ---

    def request_for_op(self, op):
        if op["f"] == "broadcast-batch":
            return {"type": "batch", "values": list(op["value"])}
        return {"type": "read"}

    def encode_body(self, body, intern):
        if body["type"] == "batch":
            vals = body["values"]
            if not vals:
                raise EncodeCapacityError("empty distilled batch")
            ids = []
            for v in vals:
                i = intern.peek(v)
                if i is None:
                    if len(intern) >= self.V:
                        raise EncodeCapacityError(
                            f"broadcast value table full ({self.V}); "
                            f"raise --max-values")
                    i = intern.id(v)
                ids.append(i)
            n = len(ids)
            lo = min(ids)
            # the distiller contract: a batch is deduped, and its ids —
            # fresh sequential interns of sorted fresh values — form one
            # contiguous run. A violation is a batcher bug; failing the
            # op definitely beats shipping a record whose columnar form
            # silently claims values the batch does not contain.
            if len(set(ids)) != n:
                raise EncodeCapacityError(
                    "duplicate value inside a distilled batch")
            if sorted(ids) != list(range(lo, lo + n)) or n > self.max_batch:
                raise EncodeCapacityError(
                    f"distilled batch ids not one contiguous run of <= "
                    f"{self.max_batch} (got {n} ids from {lo})")
            return (T_BATCH, lo, n, range_checksum(lo, n))
        return (T_BREAD, 0, 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_BATCH_OK:
            return {"type": "batch_ok", "lo": int(a), "n": int(b),
                    "proof": int(c)}
        if t == T_BREAD_OK:
            return {"type": "read_ok"}
        return super(BroadcastProgram, self).decode_body(t, a, b, c,
                                                         intern)

    def completion_payload(self, op, body, payload, intern):
        if body["type"] == "batch_ok":
            lo, n = body["lo"], body["n"]
            return {**op, "type": "ok",
                    "value": {"lo": lo, "n": n, "proof": body["proof"],
                              "expanded": [intern.value(i)
                                           for i in range(lo, lo + n)
                                           if i < len(intern._rev)]}}
        return super().completion_payload(op, body, payload, intern)

    def completion(self, op, body, read_state, intern):
        if body["type"] == "batch_ok":
            return self.completion_payload(op, body, None, intern)
        if body["type"] == "read_ok":
            seen_row = np.asarray(read_state()["seen"])
            return {**op, "type": "ok",
                    "value": [intern.value(int(i))
                              for i in np.nonzero(seen_row)[0]]}
        return {**op, "type": "ok"}
