"""Batched Raft serving lin-kv: every node of every cluster steps in one
XLA dispatch.

The TPU-native analogue of the reference's Raft demos
(`demo/python/raft.py`, `demo/ruby/raft.rb`, serving
`workload/lin_kv.clj`): leader election with randomized timeouts, log
replication with conflict truncation, majority commit, and a KV state
machine applied in log order — reads are logged too, so every operation
linearizes at its apply point (passes the Knossos-style register checker).

Where the reference demos branch per node (follower/candidate/leader
methods, callbacks per RPC), here every rule is a masked update over arrays
with a leading node axis — `role` is data, not control flow — so 10,000
independent 5-node clusters advance under one `vmap` (the BASELINE
"10k x 5-node raft clusters" configuration; see `maelstrom_tpu.parallel`).

Cluster topology is the full mesh over the static edge channels
(`net/static.py`). Per-edge lanes:

  lane 0: request   — RequestVote or AppendEntries header
  lane 1: reply     — vote or append result
  lane 2: proxy     — a non-leader forwards one client request per round
                      to its known leader (replies go straight from the
                      leader to the client)
  lanes 3..3+E:     — log entries riding an AppendEntries header

Log entries pack to three words (term/key/op | client/values | request
mid); values are the workload's small registers (0..254), keys are bounded
by `kv_keys`. The leader resends its window every round until acknowledged
— duplicates are idempotent overwrites, and the AppendEntries reply stream
advances `next`/`match` exactly as in the paper (sections 5.3-5.4)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..net.static import EdgeConfig, EdgeMsgs, reverse_index
from ..net.tpu import I32
from ..workloads.broadcast import TOPOLOGIES, topology_indices
from . import NodeProgram, T_ERROR as T_ERR, register

# client RPCs
T_READ = 10       # a = key
T_READ_OK = 11    # a = value+1 (0 = key absent -> error 20)
T_WRITE = 12      # a = key, b = value
T_WRITE_OK = 13
T_CAS = 14        # a = key, b = from, c = to
T_CAS_OK = 15
T_TXN = 16        # a = interned txn id (opaque replicated command)
T_TXN_OK = 17     # a = commit position in the raft log
# raft RPCs (edge lanes)
T_RV = 20         # a = term, b = last_log_idx, c = last_log_term
T_RV_REPLY = 21   # a = term, b = granted
T_AE = 22         # a = term, b = prev_idx<<16 | prev_term, c = commit<<4|cnt
T_AE_REPLY = 23   # a = term, b = success, c = match idx (or len hint)
T_PROXY = 24      # packed like an entry, minus the term
T_ENTRY = 25      # a = term<<16|key<<4|op, b = client<<16|v1<<8|v2, c = mid

OP_NOOP, OP_WRITE, OP_CAS, OP_READ, OP_TXN = 0, 1, 2, 3, 4

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2


class LinKVWire:
    """The lin-kv host-boundary wire vocabulary (RPC surface per
    `workload/lin_kv.clj`): shared by every node family that serves the
    workload — raft here, the compartmentalized consensus family
    (`nodes/compartment.py`) — so the protocol JSON <-> word encoding
    cannot drift between backends."""

    def request_for_op(self, op):
        k, v = op["value"]
        if op["f"] == "read":
            return {"type": "read", "key": k}
        if op["f"] == "write":
            return {"type": "write", "key": k, "value": v}
        return {"type": "cas", "key": k, "from": v[0], "to": v[1]}

    def encode_body(self, body, intern):
        if body["type"] == "read":
            return (T_READ, int(body["key"]), 0, 0)
        if body["type"] == "write":
            return (T_WRITE, int(body["key"]), int(body["value"]), 0)
        return (T_CAS, int(body["key"]), int(body["from"]),
                int(body["to"]))

    def decode_body(self, t, a, b, c, intern):
        if t == T_READ_OK:
            return {"type": "read_ok", "value": int(a) - 1}
        if t == T_WRITE_OK:
            return {"type": "write_ok"}
        if t == T_CAS_OK:
            return {"type": "cas_ok"}
        if t == 1:
            return {"type": "error", "code": int(a), "text": "kv error"}
        return super().decode_body(t, a, b, c, intern)

    def completion(self, op, body, read_state, intern):
        if body["type"] == "read_ok":
            k = op["value"][0]
            return {**op, "type": "ok", "value": [k, body["value"]]}
        return {**op, "type": "ok"}


@register
class RaftProgram(LinKVWire, NodeProgram):
    name = "lin-kv"
    needs_state_reads = False
    is_edge = True
    tolerates_channel_overwrites = True   # AE windows resend every round
    # an AE is one RPC: its entry lanes are positioned by the header's
    # prev_idx, so header and entries must share one fault draw per
    # (edge, round) — per-lane reordering would write entries at wrong
    # log indices (same-term log divergence, a real linearizability
    # break found by the raft fault fuzz under exponential latency)
    edge_atomic_rpc = True
    # trace-time phase ablation for in-context profiling ONLY
    # (maelstrom_tpu.profile_raft); production paths never set it
    ablate: frozenset = frozenset()
    # crash durability (paper section 5.1 "persistent state"): the log,
    # current term, and vote survive a kill; kv/commit/applied/role and
    # all replication bookkeeping are volatile and rebuilt by replay as
    # the restarted follower re-learns commit from the leader.
    # log_overflow rides along so a capacity invalidation can't be
    # erased by a crash.
    durable_keys = ("log_a", "log_b", "log_c", "log_len", "term",
                    "voted_for", "log_overflow")

    def __init__(self, opts, nodes):
        super().__init__(opts, nodes)
        topo = TOPOLOGIES["total"](nodes)
        nb = topology_indices(topo, nodes)
        self.neighbors = jnp.asarray(nb)
        self.rev = jnp.asarray(reverse_index(nb))
        self.D = int(self.neighbors.shape[1])
        self.E = int(opts.get("ae_entries", 4))
        self.lanes = 3 + self.E
        # default log capacity scales with the expected operation count
        # (every client op, reads included, appends an entry), so long
        # runs don't hit the static bound; a run that does anyway is
        # flagged invalid via invalid_counters
        rate = float(opts.get("rate") or 0.0)
        tl = float(opts.get("time_limit") or 0.0)
        expected = int(2 * rate * tl) + 256
        self.cap = int(opts.get("log_cap", min(max(256, expected), 0x7FFF)))
        self.keys = int(opts.get("kv_keys", 256))
        # packed wire-field widths (entry: term<<16|key<<4|op; AE header:
        # commit<<4|cnt with prev_idx in 16 bits)
        assert self.E <= 15, "ae_entries must fit the 4-bit cnt field"
        assert self.keys <= 4096, "kv_keys must fit the 12-bit key field"
        # 15-bit, not 16: (prev_idx+1) << 16 must stay positive in int32
        # (arithmetic shift-right on a negative word would corrupt the
        # decoded index). Terms share the top half of entry words; term
        # growth is ~1 per election (>= 24 rounds), far below 2^15 in any
        # practical run.
        assert self.cap <= 0x7FFF, "log_cap must fit 15-bit prev_idx"
        from . import edge_timing
        self.ring, _retry, lat_rounds = edge_timing(opts, len(nodes))
        self.election = max(8 * (lat_rounds + 1), 24)
        self.heartbeat = max(self.election // 8, 2)
        self.inbox_cap = int(opts.get("inbox_cap", 4))
        self.outbox_cap = self.inbox_cap
        # positional lanes forbid spill (edge_capacity returns False:
        # AE/RV retransmit every round, so overwrites are tolerated),
        # but the single-cell constant-latency write (uniform_arrival)
        # is orthogonal: it never moves a message between lanes
        from . import edge_capacity
        spill, chan_lanes, uniform = edge_capacity(opts, self)
        if spill or chan_lanes != self.lanes:
            raise ValueError(
                f"raft requires positional lanes (no spill, lanes="
                f"{self.lanes}); edge_capacity returned spill={spill}, "
                f"lanes={chan_lanes}")
        self.edge_cfg = EdgeConfig(n_nodes=self.n_nodes, degree=self.D,
                                   lanes=self.lanes, ring=self.ring,
                                   uniform_arrival=uniform)

    def init_state(self):
        N, D, C = self.n_nodes, self.D, self.cap
        z = lambda *s: jnp.zeros(s, I32)  # noqa: E731
        return {
            "role": z(N), "term": z(N),
            "voted_for": jnp.full((N,), -1, I32),
            "votes": jnp.zeros((N, N), bool),
            "log_a": z(N, C), "log_b": z(N, C), "log_c": z(N, C),
            "log_len": z(N),
            "commit": jnp.full((N,), -1, I32),
            "applied": jnp.full((N,), -1, I32),
            "next": z(N, D), "match": jnp.full((N, D), -1, I32),
            "kv": z(N, self.keys),          # value+1; 0 = absent
            "deadline": z(N),               # election deadline (round)
            "leader_hint": jnp.full((N,), -1, I32),  # believed leader edge
            "log_overflow": z(N),
        }

    def invalid_counters(self, state):
        # a leader whose log hit `log_cap` silently sheds client requests
        # (the client sees only a timeout); that is a static-capacity
        # failure of the simulation, not of the protocol, so it must
        # invalidate the run the way pool overflow does
        return {"log-overflow": state["log_overflow"]}

    # --- packing helpers ---

    @staticmethod
    def _pack_entry(term, key, op, client, v1, v2):
        a = (term << 16) | (key << 4) | op
        b = (client << 16) | (v1 << 8) | v2
        return a, b

    @staticmethod
    def _unpack_a(a):
        return a >> 16, (a >> 4) & 0xFFF, a & 0xF       # term, key, op

    @staticmethod
    def _unpack_b(b):
        return b >> 16, (b >> 8) & 0xFF, b & 0xFF       # client, v1, v2

    def edge_step(self, state, edge_in: EdgeMsgs, client_in, ctx):
        # The round is kernel-count-bound, not bandwidth-bound (the whole
        # 10k x 5 x 256 log is ~50 MB): every phase below is a single
        # batched gather/scatter over a stacked [N, C, 3] log instead of
        # an unrolled Python loop of one-hot [N, C] masked writes — the
        # unrolled form traced to ~2,800 jaxpr eqns with 121 fusion-
        # breaking gather/scatters and ran 60x slower per node than the
        # broadcast round (doc/performance.md methodology).
        N, D, C, E = self.n_nodes, self.D, self.cap, self.E
        nb, rnd = self.neighbors, ctx["round"]
        edge_ok = nb >= 0
        s = dict(state)
        me = jnp.arange(N, dtype=I32)
        # one stacked log: fields (a, b, c) ride the trailing axis so
        # each append/read phase costs ONE scatter/gather, not three
        log = jnp.stack([s["log_a"], s["log_b"], s["log_c"]], axis=-1)

        # ------------------------------------------------ inbound decode
        req = jax.tree.map(lambda f: f[:, :, 0], edge_in)   # lane 0
        rep = jax.tree.map(lambda f: f[:, :, 1], edge_in)   # lane 1
        prx = jax.tree.map(lambda f: f[:, :, 2], edge_in)   # lane 2

        is_rv = req.valid & (req.type == T_RV)
        is_ae = req.valid & (req.type == T_AE)
        is_rvr = rep.valid & (rep.type == T_RV_REPLY)
        is_aer = rep.valid & (rep.type == T_AE_REPLY)
        is_prx = prx.valid & (prx.type == T_PROXY)

        # ------------------------------------------------ term catch-up
        # any message with a newer term makes us a follower of that term
        # (paper section 5.1)
        terms_seen = jnp.maximum(
            jnp.where(is_rv | is_ae, req.a, 0).max(axis=1),
            jnp.where(is_rvr | is_aer, rep.a, 0).max(axis=1))
        newer = terms_seen > s["term"]
        s["term"] = jnp.where(newer, terms_seen, s["term"])
        s["role"] = jnp.where(newer, FOLLOWER, s["role"])
        s["voted_for"] = jnp.where(newer, -1, s["voted_for"])

        # ------------------------------------------------ election timer
        key_r = jax.random.fold_in(ctx["key"], 17)
        jitter = jax.random.randint(key_r, (N,), 0, self.election)
        timed_out = (s["role"] != LEADER) & (rnd >= s["deadline"])
        became_candidate = timed_out
        s["term"] = jnp.where(timed_out, s["term"] + 1, s["term"])
        s["role"] = jnp.where(timed_out, CANDIDATE, s["role"])
        s["voted_for"] = jnp.where(timed_out, jnp.arange(N, dtype=I32),
                                   s["voted_for"])
        s["votes"] = jnp.where(timed_out[:, None], False, s["votes"])
        s["deadline"] = jnp.where(timed_out,
                                  rnd + self.election + jitter,
                                  s["deadline"])
        s["leader_hint"] = jnp.where(timed_out, -1, s["leader_hint"])

        last_idx = s["log_len"] - 1
        last_term_arr = self._unpack_a(
            jnp.take_along_axis(s["log_a"],
                                jnp.clip(last_idx, 0, C - 1)[:, None],
                                axis=1))[0][:, 0]
        last_term = jnp.where(last_idx >= 0, last_term_arr, 0)

        # ------------------------------------------------ votes (5.2)
        # grant at most one vote per round: neighbors are distinct, so
        # the sequential "first eligible edge wins" unroll is exactly a
        # first-True pick over the joint eligibility mask
        grant = jnp.zeros((N, D), bool)
        if "votes" not in self.ablate:
            rv_ok = is_rv & (req.a == s["term"][:, None])       # [N, D]
            log_ok = ((req.c > last_term[:, None])
                      | ((req.c == last_term[:, None])
                         & (req.b >= last_idx[:, None])))
            can_vote = ((s["voted_for"][:, None] < 0)
                        | (s["voted_for"][:, None] == nb))
            elig = rv_ok & can_vote & log_ok
            any_g = elig.any(axis=1)
            first = jnp.argmax(elig, axis=1)
            grant = elig & (jnp.arange(D, dtype=I32)[None, :]
                            == first[:, None])
            cand = jnp.take_along_axis(nb, first[:, None], axis=1)[:, 0]
            s["voted_for"] = jnp.where(any_g, cand, s["voted_for"])
            s["deadline"] = jnp.where(any_g, rnd + self.election + jitter,
                                      s["deadline"])

        # count granted replies; self-vote is implicit
        rv_granted = (is_rvr & (rep.a == s["term"][:, None])
                      & (rep.b > 0))
        votes_add = jnp.zeros((N, N), bool)
        if "votes" not in self.ablate:
            votes_add = (rv_granted[:, :, None]
                         & (nb[:, :, None] == me[None, None, :])).any(
                             axis=1)
        s["votes"] = (s["votes"] | votes_add) & \
            (s["role"] == CANDIDATE)[:, None]
        won = (s["role"] == CANDIDATE) & \
            (s["votes"].sum(axis=1) + 1 > (N // 2))
        s["role"] = jnp.where(won, LEADER, s["role"])
        s["next"] = jnp.where(won[:, None], s["log_len"][:, None],
                              s["next"])
        s["match"] = jnp.where(won[:, None], -1, s["match"])

        # ------------------------------------------------ append entries
        # decode the AE header and its entry lanes (follower side, 5.3)
        cur = is_ae & (req.a == s["term"][:, None])
        # the sender of a current-term AE is the leader
        lead_edge = jnp.where(cur.any(axis=1),
                              jnp.argmax(cur, axis=1), -1)
        s["leader_hint"] = jnp.where(cur.any(axis=1), lead_edge,
                                     s["leader_hint"])
        s["deadline"] = jnp.where(cur.any(axis=1),
                                  rnd + self.election + jitter,
                                  s["deadline"])
        s["role"] = jnp.where(cur.any(axis=1) & (s["role"] == CANDIDATE),
                              FOLLOWER, s["role"])

        ae_prev_idx = (req.b >> 16) - 1          # stored +1 to keep >=0
        ae_prev_term = req.b & 0xFFFF
        ae_commit = (req.c >> 4) - 1
        ae_cnt = req.c & 0xF

        prev_in_log = ae_prev_idx < s["log_len"][:, None]
        prev_term_here = self._unpack_a(
            jnp.take_along_axis(s["log_a"],
                                jnp.clip(ae_prev_idx, 0, C - 1), axis=1))[0]
        prev_match = (ae_prev_idx < 0) | (
            prev_in_log & (prev_term_here == ae_prev_term))
        accept = cur & prev_match
        reject = cur & ~prev_match

        # Append/overwrite the entry window; truncate on conflict. Entry
        # lanes lose and delay independently of the header (per-lane loss
        # draws in sim._round_edge), so ONLY a contiguous prefix of arrived
        # entries may be appended and acknowledged — a header-only ack
        # would let the leader commit entries a follower never stored
        # (zero-filled log hole -> linearizability violation).
        acc_any = accept.any(axis=1)
        acc_d = jnp.argmax(accept, axis=1)
        acc_prev = jnp.take_along_axis(ae_prev_idx, acc_d[:, None],
                                       axis=1)[:, 0]
        acc_cnt = jnp.take_along_axis(ae_cnt, acc_d[:, None], axis=1)[:, 0]

        conflict = jnp.zeros((N,), bool)
        new_len = s["log_len"]
        contig_cnt = jnp.zeros((N,), I32)
        if "entries" not in self.ablate:
            # all E entry lanes of the accepted edge in one gather each:
            # [N, E] per field (acc_d indexes the D axis)
            def at_acc(f):
                return jnp.take_along_axis(
                    f[:, :, 3:3 + E], acc_d[:, None, None], axis=1)[:, 0]
            lv, lt = at_acc(edge_in.valid), at_acc(edge_in.type)
            ea = at_acc(edge_in.a)
            eb = at_acc(edge_in.b)
            ec = at_acc(edge_in.c)
            e_i = jnp.arange(E, dtype=I32)[None, :]
            present = acc_any[:, None] & lv & (lt == T_ENTRY)   # [N, E]
            expected = acc_any[:, None] & (e_i < acc_cnt[:, None])
            # only a contiguous prefix of arrived entries may append:
            # contig_before[e] = all earlier lanes present-or-unexpected
            bad = (~(present | ~expected)).astype(I32)
            contig_before = jnp.cumsum(
                jnp.pad(bad[:, :-1], ((0, 0), (1, 0))), axis=1) == 0
            eff = present & contig_before & expected
            pos = acc_prev[:, None] + 1 + e_i                   # [N, E]
            in_cap = eff & (pos < C)
            contig_cnt = in_cap.astype(I32).sum(axis=1)
            had = pos < s["log_len"][:, None]
            old_a = jnp.take_along_axis(
                log[:, :, 0], jnp.clip(pos, 0, C - 1), axis=1)  # [N, E]
            conflict = (in_cap & had
                        & ((old_a >> 16) != (ea >> 16))).any(axis=1)
            vals = jnp.stack([ea, eb, ec], axis=-1)             # [N, E, 3]
            log = log.at[me[:, None], jnp.where(in_cap, pos, C)].set(
                vals, mode="drop")
            new_len = jnp.maximum(
                s["log_len"],
                jnp.where(in_cap, pos + 1, 0).max(axis=1))

        window_end = acc_prev + 1 + contig_cnt
        # conflict => adopt exactly the sent prefix (truncate suffix)
        s["log_len"] = jnp.where(
            acc_any,
            jnp.where(conflict, jnp.minimum(new_len, window_end), new_len),
            s["log_len"])
        acc_commit = jnp.take_along_axis(ae_commit, acc_d[:, None],
                                         axis=1)[:, 0]
        # bound by the VERIFIED prefix (prev match + contiguously appended
        # entries), i.e. the paper's "index of last new entry" — bounding
        # by log_len-1 would let a stale uncommitted suffix from a deposed
        # leader be committed and applied
        s["commit"] = jnp.where(
            acc_any,
            jnp.maximum(s["commit"],
                        jnp.minimum(acc_commit, acc_prev + contig_cnt)),
            s["commit"])

        # ------------------------------------------------ AE replies (leader)
        aer_ok = (is_aer & (rep.a == s["term"][:, None])
                  & (s["role"] == LEADER)[:, None])
        succ = aer_ok & (rep.b > 0)
        fail = aer_ok & (rep.b == 0)
        s["match"] = jnp.where(succ, jnp.maximum(s["match"], rep.c),
                               s["match"])
        s["next"] = jnp.where(succ, jnp.maximum(s["next"], rep.c + 1),
                              s["next"])
        s["next"] = jnp.where(
            fail, jnp.clip(jnp.minimum(s["next"] - 1, rep.c + 1), 0, C),
            s["next"])

        # commit advance: the majority-replicated index is the
        # (majority)-th largest of {match_d} + {own log end}; commit moves
        # there iff that entry is from the current term (5.4.2)
        repl = jnp.concatenate(
            [s["match"], (s["log_len"] - 1)[:, None]], axis=1)  # [N, D+1]
        sorted_desc = -jnp.sort(-repl, axis=1)
        best = sorted_desc[:, N // 2]           # majority = N//2 + 1 values
        best_term = jnp.where(
            best >= 0,
            self._unpack_a(jnp.take_along_axis(
                s["log_a"], jnp.clip(best, 0, C - 1)[:, None],
                axis=1))[0][:, 0],
            -1)
        is_leader = s["role"] == LEADER
        s["commit"] = jnp.where(is_leader & (best_term == s["term"]),
                                jnp.maximum(s["commit"], best), s["commit"])

        # ------------------------------------------------ client requests
        K = client_in.valid.shape[1]
        creq = client_in.valid & ((client_in.type == T_READ)
                                  | (client_in.type == T_WRITE)
                                  | (client_in.type == T_CAS)
                                  | (client_in.type == T_TXN))
        op_of = jnp.where(
            client_in.type == T_WRITE, OP_WRITE,
            jnp.where(client_in.type == T_CAS, OP_CAS,
                      jnp.where(client_in.type == T_TXN, OP_TXN,
                                OP_READ)))
        # batched append of direct requests (leader); a non-leader
        # remembers its FIRST unserved request to proxy toward the leader
        proxy_slot = jnp.full((N,), -1, I32)
        proxy_a = jnp.zeros((N,), I32)
        proxy_b = jnp.zeros((N,), I32)
        proxy_c = jnp.zeros((N,), I32)
        shed = jnp.zeros((N, K), bool)
        if "client" not in self.ablate and K > 0:
            is_txn = client_in.type == T_TXN                    # [N, K]
            keyk = jnp.where(is_txn, 0,
                             jnp.clip(client_in.a, 0, self.keys - 1))
            # OP_TXN carries a 16-bit opaque command id split across v1/v2
            v1 = jnp.where(
                is_txn, (client_in.a >> 8) & 0xFF,
                jnp.where((client_in.type == T_WRITE)
                          | (client_in.type == T_CAS),
                          client_in.b + 1, 0))
            v2 = jnp.where(is_txn, client_in.a & 0xFF,
                           jnp.where(client_in.type == T_CAS,
                                     client_in.c + 1, 0))
            client_idx = jnp.clip(client_in.src - N, 0, 0xFFFF)
            v1c, v2c = jnp.clip(v1, 0, 0xFF), jnp.clip(v2, 0, 0xFF)
            ea = (s["term"][:, None] << 16) | (keyk << 4) | op_of
            eb = (client_idx << 16) | (v1c << 8) | v2c
            # append positions: log_len + how many earlier slots append;
            # once a position passes C every later one does too, so
            # counting wishes (not successes) is exact
            wish = creq & is_leader[:, None]
            nbefore = jnp.cumsum(
                jnp.pad(wish[:, :-1], ((0, 0), (1, 0))).astype(I32),
                axis=1)
            pos = s["log_len"][:, None] + nbefore
            do = wish & (pos < C)
            vals = jnp.stack([ea, eb, client_in.mid], axis=-1)  # [N, K, 3]
            log = log.at[me[:, None], jnp.where(do, pos, C)].set(
                vals, mode="drop")
            s["log_len"] = s["log_len"] + do.astype(I32).sum(axis=1)
            s["log_overflow"] = s["log_overflow"] + (
                wish & (pos >= C)).astype(I32).sum(axis=1)
            want = creq & ~is_leader[:, None]
            any_w = want.any(axis=1)
            k0 = jnp.argmax(want, axis=1)
            pick = lambda f: jnp.where(  # noqa: E731
                any_w, jnp.take_along_axis(f, k0[:, None], axis=1)[:, 0], 0)
            proxy_slot = jnp.where(any_w, k0, proxy_slot)
            proxy_a = pick((keyk << 4) | op_of)
            proxy_b = pick(eb)
            proxy_c = pick(client_in.mid)
            # a request this node can NEITHER serve NOR forward —
            # no known leader, or not the one proxy slot this round —
            # fails fast with error 11 (temporarily-unavailable,
            # definite), like the reference raft demo's not-a-leader
            # reply: the client retries immediately instead of eating
            # the full RPC timeout on a silently shed request
            have_hint = (s["leader_hint"] >= 0)[:, None]
            slot_i = jnp.arange(K, dtype=I32)[None, :]
            shed = want & (~have_hint | (slot_i != k0[:, None]))

        # proxied requests arriving at the leader: append (one per edge)
        if "proxy" not in self.ablate:
            wish = is_prx & is_leader[:, None]                  # [N, D]
            nbefore = jnp.cumsum(
                jnp.pad(wish[:, :-1], ((0, 0), (1, 0))).astype(I32),
                axis=1)
            pos = s["log_len"][:, None] + nbefore
            do = wish & (pos < C)
            key_d = (prx.a >> 4) & 0xFFF
            ea = (s["term"][:, None] << 16) | (key_d << 4) | (prx.a & 0xF)
            vals = jnp.stack([ea, prx.b, prx.c], axis=-1)       # [N, D, 3]
            log = log.at[me[:, None], jnp.where(do, pos, C)].set(
                vals, mode="drop")
            s["log_len"] = s["log_len"] + do.astype(I32).sum(axis=1)
            s["log_overflow"] = s["log_overflow"] + (
                wish & (pos >= C)).astype(I32).sum(axis=1)

        # ------------------------------------------------ apply + replies
        # entries apply strictly in log order: applied+1+j while active.
        # ONE gather fetches all A candidate entries; the per-step loop
        # keeps only the tiny [N] algebra (a CAS may read a key the
        # previous step wrote, so the kv chain is inherently sequential)
        A = K                                    # replies share client slots
        outs = []
        if "apply" not in self.ablate and A > 0:
            start = s["applied"] + 1
            idxs = start[:, None] + jnp.arange(A, dtype=I32)[None, :]
            entries = log[me[:, None], jnp.clip(idxs, 0, C - 1)]  # [N,A,3]
            for j in range(A):
                idx = start + j
                active = idx <= s["commit"]
                ea, eb, ec = (entries[:, j, 0], entries[:, j, 1],
                              entries[:, j, 2])
                _t, key, op = self._unpack_a(ea)
                client, v1, v2 = self._unpack_b(eb)
                safe_key = jnp.clip(key, 0, self.keys - 1)
                cur_v = jnp.take_along_axis(s["kv"], safe_key[:, None],
                                            axis=1)[:, 0]
                cas_ok = (op == OP_CAS) & (cur_v == v1) & (cur_v > 0)
                do_write = active & ((op == OP_WRITE) | cas_ok)
                new_v = jnp.where(op == OP_WRITE, v1, v2)
                s["kv"] = s["kv"].at[
                    me, jnp.where(do_write, safe_key, self.keys)].set(
                        new_v, mode="drop")
                s["applied"] = jnp.where(active, idx, s["applied"])
                # leader replies to the originating client
                say = active & is_leader & (op != OP_NOOP)
                rtype = jnp.where(
                    op == OP_TXN, T_TXN_OK,
                    jnp.where(
                        op == OP_READ,
                        jnp.where(cur_v > 0, T_READ_OK, 1),  # 1 = T_ERROR
                        jnp.where(op == OP_WRITE, T_WRITE_OK,
                                  jnp.where(cas_ok, T_CAS_OK, 1))))
                ra = jnp.where(
                    op == OP_TXN, idx,                   # commit position
                    jnp.where(op == OP_READ,
                              jnp.where(cur_v > 0, cur_v, 20),
                              jnp.where((op == OP_CAS) & ~cas_ok,
                                        jnp.where(cur_v > 0, 22, 20), 0)))
                outs.append((say, N + client, rtype, ra, ec))
        if outs:
            out_valid = jnp.stack([o[0] for o in outs], axis=1)
            out_dest = jnp.stack([o[1] for o in outs], axis=1)
            out_type = jnp.stack([o[2] for o in outs], axis=1)
            out_a = jnp.stack([o[3] for o in outs], axis=1)
            out_reply = jnp.stack([o[4] for o in outs], axis=1)
        else:
            out_valid = jnp.zeros((N, A), bool)
            out_dest = jnp.zeros((N, A), I32)
            out_type = jnp.zeros((N, A), I32)
            out_a = jnp.zeros((N, A), I32)
            out_reply = jnp.full((N, A), -1, I32)

        # log writes are complete: unstack back to the state planes
        s["log_a"] = log[:, :, 0]
        s["log_b"] = log[:, :, 1]
        s["log_c"] = log[:, :, 2]

        # ------------------------------------------------ outbound lanes
        # lane 0 requests: candidates ask for votes; leaders send AE
        send_rv = became_candidate[:, None] & edge_ok
        nxt = jnp.minimum(s["next"], s["log_len"][:, None])
        cnt = jnp.clip(s["log_len"][:, None] - nxt, 0, E)
        beat = (rnd % self.heartbeat) == 0
        send_ae = (is_leader[:, None] & edge_ok & ((cnt > 0) | beat))
        prev_idx = nxt - 1
        prev_term = jnp.where(
            prev_idx >= 0,
            jnp.take_along_axis(
                log[:, :, 0], jnp.clip(prev_idx, 0, C - 1), axis=1) >> 16,
            0)
        l0_valid = send_rv | send_ae
        l0_type = jnp.where(send_rv, T_RV, T_AE)
        l0_a = jnp.broadcast_to(s["term"][:, None], (N, D))
        l0_b = jnp.where(send_rv,
                         jnp.broadcast_to(last_idx[:, None], (N, D)),
                         ((prev_idx + 1) << 16) | prev_term)
        l0_c = jnp.where(send_rv,
                         jnp.broadcast_to(last_term[:, None], (N, D)),
                         ((s["commit"][:, None] + 1) << 4) | cnt)

        # lane 1 replies: vote results and append results
        ae_reply = cur                     # reply to every current-term AE
        l1_valid = is_rv | ae_reply
        l1_type = jnp.where(is_rv, T_RV_REPLY, T_AE_REPLY)
        # ack only the contiguously-appended prefix, never the header's
        # claimed window (entry lanes may have been lost independently)
        match_val = jnp.where(accept,
                              (acc_prev + contig_cnt)[:, None],
                              jnp.minimum(s["log_len"][:, None] - 1,
                                          ae_prev_idx - 1))
        l1_a = jnp.broadcast_to(s["term"][:, None], (N, D))
        l1_b = jnp.where(is_rv, grant.astype(I32), accept.astype(I32))
        l1_c = jnp.where(is_rv, 0, match_val)

        # lane 2 proxy: forward the remembered request to the leader edge
        lh = s["leader_hint"]
        l2_valid = (proxy_slot >= 0)[:, None] & \
            (lh[:, None] == jnp.arange(D, dtype=I32)[None, :]) & edge_ok
        l2_type = jnp.full((N, D), T_PROXY, I32)
        l2_a = jnp.broadcast_to(proxy_a[:, None], (N, D))
        l2_b = jnp.broadcast_to(proxy_b[:, None], (N, D))
        l2_c = jnp.broadcast_to(proxy_c[:, None], (N, D))

        # entry lanes: the leader's per-neighbor send window, fetched as
        # ONE [N, D, E, 3] gather from the stacked log
        if "outlanes" in self.ablate:
            ev = jnp.zeros((N, D, E), bool)
            window = jnp.zeros((N, D, E, 3), I32)
        else:
            e_i = jnp.arange(E, dtype=I32)[None, None, :]
            pos = jnp.clip(nxt[:, :, None] + e_i, 0, C - 1)     # [N, D, E]
            ev = send_ae[:, :, None] & (e_i < cnt[:, :, None])
            window = log[me[:, None, None], pos]                # [N,D,E,3]

        def pack3(x0, x1, x2, xe):
            return jnp.concatenate(
                [jnp.stack([x0, x1, x2], axis=2), xe], axis=2)

        edge_out = EdgeMsgs(
            valid=pack3(l0_valid, l1_valid, l2_valid, ev),
            type=pack3(l0_type, l1_type, l2_type,
                       jnp.full((N, D, E), T_ENTRY, I32)),
            a=pack3(l0_a, l1_a, l2_a, window[:, :, :, 0]),
            b=pack3(l0_b, l1_b, l2_b, window[:, :, :, 1]),
            c=pack3(l0_c, l1_c, l2_c, window[:, :, :, 2]))

        # merge the shed-request error replies: apply replies exist only
        # on leaders, sheds only on non-leaders — the slot sets are
        # disjoint by construction
        out_valid = out_valid | shed
        out_dest = jnp.where(shed, client_in.src, out_dest)
        out_type = jnp.where(shed, T_ERR, out_type)
        out_a = jnp.where(shed, 11, out_a)
        out_reply = jnp.where(shed, client_in.mid, out_reply)
        client_out = client_in.replace(
            valid=out_valid, dest=out_dest, type=out_type, a=out_a,
            b=jnp.zeros((N, A), I32), c=jnp.zeros((N, A), I32),
            reply_to=out_reply, src=jnp.broadcast_to(me[:, None], (N, A)))

        return s, edge_out, client_out

    def quiescent(self, state):
        # raft is never quiescent: heartbeats and election timers tick
        return jnp.array(False)

    # host boundary (RPC surface per workload/lin_kv.clj): LinKVWire
