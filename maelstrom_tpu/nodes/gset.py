"""Batched grow-only set node (serving `workload/g_set.clj`): the broadcast
gossip machine with the g-set RPC surface.

A g-set IS broadcast state — a monotone set replicated by gossip — so this
reuses `BroadcastProgram`'s edge-channel protocol (pending/digest/retry)
wholesale, like the reference's generic CRDT server serves g-set
(`demo/ruby/crdt.rb`). Differences are at the boundaries:

  - RPCs are `add`/`add_ok` and `read`/`read_ok` with an `elements` set
  - default gossip graph: fully connected for small clusters (the
    reference demo gossips to all peers, `demo/ruby/crdt.rb`), or a fixed
    random `gossip_fanout`-regular graph for large ones (the BASELINE
    "1k nodes, gossip fanout 3" configuration) — static topology keeps
    delivery a precomputed gather."""

from __future__ import annotations

import random

from .broadcast import (BroadcastProgram, T_BCAST, T_BCAST_OK, T_READ,
                        T_READ_OK)
from . import EncodeCapacityError, register


def fanout_topology(nodes, k: int, seed: int = 0):
    """A fixed random symmetric graph with ~k links per node (degree in
    [k, 2k] after symmetrization); connected via a Hamiltonian backbone."""
    rng = random.Random(seed)
    n = len(nodes)
    k = min(k, n - 1)           # a node has at most n-1 distinct neighbors
    order = list(range(n))
    rng.shuffle(order)
    adj = {i: set() for i in range(n)}
    for i in range(n):                       # ring backbone: connectivity
        a, b = order[i], order[(i + 1) % n]
        if a != b:
            adj[a].add(b)
            adj[b].add(a)
    for i in range(n):
        while len(adj[i]) < k and n > 1:
            j = rng.randrange(n)
            if j != i:
                adj[i].add(j)
                adj[j].add(i)
    return {nodes[i]: [nodes[j] for j in sorted(adj[i])] for i in range(n)}


def gossip_topology_opts(opts: dict, nodes) -> dict:
    """Shared CRDT gossip-graph policy: an explicit `gossip_fanout` builds
    a fixed random graph; otherwise gossip with all peers, like the
    reference demo (`demo/ruby/crdt.rb`)."""
    opts = dict(opts)
    fan = opts.get("gossip_fanout")
    if fan:
        opts["topology_map"] = fanout_topology(nodes, int(fan),
                                               opts.get("seed", 0))
    else:
        opts.setdefault("topology", "total")
    return opts


@register
class GSetProgram(BroadcastProgram):
    name = "g-set"

    def __init__(self, opts, nodes):
        super().__init__(gossip_topology_opts(opts, nodes), nodes)

    # --- host boundary (RPC surface per workload/g_set.clj) ---

    def request_for_op(self, op):
        if op["f"] == "add":
            return {"type": "add", "element": op["value"]}
        return {"type": "read"}

    def encode_body(self, body, intern):
        if body["type"] == "add":
            i = intern.peek(body["element"])
            if i is None:
                if len(intern) >= self.V:
                    # capacity check before interning (survivable
                    # failure must not grow the table)
                    raise EncodeCapacityError(
                        f"g-set value table full ({self.V}); "
                        f"raise --max-values")
                i = intern.id(body["element"])
            return (T_BCAST, i, 0, 0)
        return (T_READ, 0, 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_BCAST_OK:
            return {"type": "add_ok"}
        if t == T_READ_OK:
            return {"type": "read_ok"}
        return super(BroadcastProgram, self).decode_body(t, a, b, c, intern)
