"""Compartmentalized consensus: role-partitioned sequencer/proxy/
acceptor/replica tiers serving lin-kv, with live leader failover.

"Scaling Replicated State Machines with Compartmentalization" (PAPERS.md,
arxiv 2012.15762) decouples MultiPaxos' leader into independently-scalable
compartments: the leader only SEQUENCES (assigns log slots — O(1) messages
per command), stateless proxy leaders take over the quadratic work
(broadcast phase-2a to the acceptor grid, collect the quorum, teach the
replicas), and a replica tier applies the chosen log and answers clients.
Client throughput then scales with the PROXY count, not the leader's
message budget — the claim `bench.py BENCH_MODE=compartment` measures
(doc/compartment.md).

This is the first user of `sim.RolePartition` (the multi-program
node-state tree): four roles over contiguous node-id ranges,

    nodes [0, S)                sequencers (candidates; node 0 leads at
                                            ballot 0 — durable)
    nodes [S, S+P)              proxies    (stateless, VOLATILE: a kill
                                            wipes them; the live leader's
                                            resend rebuilds their work)
    nodes [S+P, S+P+A)          acceptors  (rows x cols grid, durable)
    nodes [S+P+A, N)            replicas   (apply the log, durable)

selected with `--node tpu:compartment --roles sequencers=S,proxies=P,
acceptors=RxC,replicas=R` and graded by the stock linearizable register
checker.

Phase 2 (the stable-leader pipeline, PR 9):

  1. clients send read/write/cas to the sequencer they believe leads
     (reads are logged too, so every op linearizes at its apply point,
     like `nodes/raft.py`);
  2. the leader assigns the next slot, parks the command in a durable
     in-flight table, and sends T_ASSIGN to proxy `slot % P` — resending
     on a retry tick until the command is fully executed, which makes
     the leader the retry root: a crashed (volatile) proxy loses
     nothing, the next resend rebuilds its state;
  3. the proxy broadcasts T_P2A to all acceptors and collects T_P2B acks
     per GRID ROW; any complete row is a write quorum;
  4. on quorum the proxy teaches all replicas (T_LEARN) until every
     replica acks STORAGE (T_EXEC), then reports T_DONE to the
     assigning leader (`ballot % S`);
  5. replicas store learned commands at their slots, acking every
     deduped learn the moment it is durably stored (apply-point acks
     would deadlock the proxy table behind slot gaps), apply strictly
     in slot order, and the DESIGNATED replica (`slot % R`) answers the
     client with the apply-point value.

Phase 1 (leader election and recovery — this module's `sequencers=S`
extension; with S == 1 all of it compiles out and the cluster is
byte-identical to the PR 9 stable-leader program):

  - Ballots are `k * S + candidate_id`: every candidate owns a disjoint
    residue class, so ballots are globally unique without coordination.
    A candidate's own ballot floor is DURABLE (a restarted candidate can
    never reuse a ballot it already burned).
  - Failure detection: the elected leader heartbeats the other
    candidates (T_HB) on the retry tick; a candidate whose deadline
    expires (election_timeout_rounds + a per-candidate stagger + a
    seeded per-round jitter, the raft idiom) starts a candidacy after
    its randomized backoff — competing candidates converge
    deterministically per seed on both the plain and mesh paths.
  - Prepare/promise runs over the acceptor grid with COLUMN quorums:
    phase-2 write quorums are rows, and every column intersects every
    row in exactly one cell, so a promised column fences every past and
    future row quorum at a lower ballot. (Promising rows instead would
    NOT intersect other rows — the grid geometry is the safety
    argument.) Acceptors persist `promised` and reject stale T_PREP
    (T_REJP) and stale-ballot T_P2A (T_P2R), so a deposed sequencer can
    never split the log: its in-flight T_ASSIGN/T_P2A traffic dies at
    the grid.
  - Recovery: promises carry each acceptor's max accepted slot AND its
    commit watermark (the highest contiguous slot some leader saw
    DONE — stored on ALL replicas — piggybacked to the grid as T_CMT
    on the retry tick, durable and monotone at the acceptor). The
    winner takes `next_slot = hi + 1` and pulls only the slots in
    (watermark, hi] into its table in QUERY phase — recovery work is
    bounded by the in-flight window, NOT the history length, which is
    what keeps late-run failover dips flat — T_QRY fans to the grid, T_QVAL answers
    with the acceptor's (cmd, accepted-ballot), and a COLUMN quorum of
    answers resolves the slot to the highest-ballot value (or a NO-OP
    when none was accepted: gaps must fill or the replicas' in-order
    apply stalls forever). Resolved slots re-propose through the normal
    proxy path at the new ballot with mid = -1 (recovered commands
    never re-reply; their clients timed out as indefinite info ops).
  - Proxies carry the assigning ballot end to end (T_ASSIGN packs it,
    T_P2A/T_P2B echo it): a higher-ballot assign REPLACES a stale row
    for the same slot, a stale assign is dropped, and a T_P2R nack
    drops the row and notifies the stale leader (T_NLDR), which steps
    down and drops its table.
  - Clients: a non-leading sequencer answers T_ERR code 31 (not-leader)
    with a hint (the candidate owning the highest live ballot it has
    heard, or -1 mid-election); the host runner follows hints under
    seeded exponential backoff (doc/compartment.md "election section").

Loss, partitions, duplication, pause, and kill therefore only delay —
and killing the live sequencer (`--nemesis-targets kill=sequencer`) is a
FAILOVER, not durable downtime: an availability dip bounded by the
failure-detector timeout plus the election+recovery window, never a
linearizability violation (`checkers/availability.py` measures exactly
this claim).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..byzantine import byz_enabled, culprit_rows
from ..net.tpu import I32, Msgs, cat_lanes as _cat_lanes
from ..sim import RolePartition
from . import NodeProgram, register
from .raft import (LinKVWire, T_READ, T_WRITE, T_CAS,
                   OP_NOOP, OP_WRITE, OP_CAS, OP_READ)

# client wire codes (shared with raft via LinKVWire): 10..15
T_ERR = 1
T_READ_OK = 11
T_WRITE_OK = 13
T_CAS_OK = 15
# compartment phase-2 RPCs
T_ASSIGN = 30    # leader -> proxy:    a = packed(bal, client, slot), b = cmd, c = mid
T_P2A = 31       # proxy -> acceptor:  a = slot, b = cmd, c = ballot
T_P2B = 32       # acceptor -> proxy:  a = slot, b = acceptor grid index, c = ballot
T_LEARN = 33     # proxy -> replica:   a = packed(client, slot), b = cmd, c = mid
T_EXEC = 34      # replica -> proxy:   a = slot, b = replica index
T_DONE = 35      # proxy -> leader:    a = slot
# phase-1 (election + recovery) RPCs — only ever sent when S > 1
T_PREP = 36      # candidate -> acceptor: a = ballot
T_PROM = 37      # acceptor -> candidate: a = ballot, b = grid index, c = hi+1
T_REJP = 38      # acceptor -> candidate: a = rejected ballot, c = promised
T_P2R = 39       # acceptor -> proxy:     a = slot, b = grid index, c = promised
T_QRY = 40       # leader -> acceptor:    a = slot, c = ballot
T_QVAL = 41      # acceptor -> leader:    a = slot, b = cmd, c = idx<<16 | bal+1
T_HB = 42        # leader -> candidates:  a = ballot
T_NLDR = 43      # proxy -> stale leader: a = higher ballot seen
T_CMT = 44       # leader -> acceptor:    a = done-frontier watermark

# protocol error codes on the client surface
E_UNAVAILABLE = 11   # leader table full: definite backpressure shed
E_NOT_LEADER = 31    # contacted sequencer does not lead; b = hint or -1
E_BYZANTINE = 32     # receiver convicted the message of lying (errors.py)

NOOP_CMD = 0         # key 0 / OP_NOOP: fills recovered gaps, applies inert

_DEFAULT_ROLES = {"sequencers": 1, "proxies": 2, "rows": 2, "cols": 2,
                  "replicas": 2}
DEFAULT_ROLES = "sequencers=1,proxies=2,acceptors=2x2,replicas=2"


def parse_roles(spec) -> dict:
    """`--roles sequencers=S,proxies=P,acceptors=RxC,replicas=R` ->
    {sequencers, proxies, rows, cols, replicas}; omitted roles keep
    their defaults (one stable sequencer — the PR 9 shape). A plain
    acceptor count A is a 1 x A grid (single row: the write quorum is
    all acceptors, the phase-1 quorum any single acceptor)."""
    spec = spec or DEFAULT_ROLES
    out = {"sequencers": None, "proxies": None, "rows": None,
           "cols": None, "replicas": None}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        k, sep, val = part.partition("=")
        k, val = k.strip(), val.strip()
        if not sep or not val:
            raise ValueError(f"--roles: expected name=count, got {part!r}")
        if k == "sequencers":
            out["sequencers"] = int(val)
        elif k == "proxies":
            out["proxies"] = int(val)
        elif k == "acceptors":
            if "x" in val:
                r, c = val.split("x", 1)
                out["rows"], out["cols"] = int(r), int(c)
            else:
                out["rows"], out["cols"] = 1, int(val)
        elif k == "replicas":
            out["replicas"] = int(val)
        else:
            raise ValueError(
                f"--roles: unknown role {k!r} (expected sequencers, "
                f"proxies, acceptors, replicas)")
    for k, v in out.items():
        if v is None:
            out[k] = _DEFAULT_ROLES[k]
        elif v < 1:
            raise ValueError(f"--roles: {k} must be >= 1, got {v}")
    return out


def roles_node_count(spec) -> int:
    r = parse_roles(spec)
    return (r["sequencers"] + r["proxies"] + r["rows"] * r["cols"]
            + r["replicas"])


class Layout:
    """Static shape of one compartmentalized cluster, shared by every
    role program so bases, capacities, ballot packing, and retry pacing
    can never disagree."""

    # S > 1 wire packing: T_ASSIGN's a-word carries bal<<24 |
    # client<<12 | slot, so the elected configuration narrows the slot
    # and client fields (the stable S == 1 configuration keeps the PR 9
    # client<<16 | slot layout bit-for-bit)
    SLOT_BITS = 12
    CLIENT_BITS = 12
    MAX_BAL_BITS = 6

    def __init__(self, opts: dict, n_nodes: int):
        r = parse_roles(opts.get("roles"))
        self.S = r["sequencers"]
        self.P = r["proxies"]
        self.rows, self.cols = r["rows"], r["cols"]
        self.A = self.rows * self.cols
        self.R = r["replicas"]
        self.n_nodes = n_nodes
        self.leader = 0              # the ballot-0 leader
        self.s_base = 0
        self.p_base = self.S
        self.a_base = self.S + self.P
        self.r_base = self.S + self.P + self.A
        want = self.S + self.P + self.A + self.R
        if want != n_nodes:
            raise ValueError(
                f"--roles {opts.get('roles')!r} needs {want} nodes "
                f"({self.S} sequencers + {self.P} proxies + {self.A} "
                f"acceptors + {self.R} replicas) but the cluster has "
                f"{n_nodes}; drop --node-count/--nodes and let --roles "
                f"size it")
        # slot capacity scales with the expected op count like raft's
        # log (every client op, reads included, takes a slot)
        rate = float(opts.get("rate") or 0.0)
        tl = float(opts.get("time_limit") or 0.0)
        expected = int(2 * rate * tl) + 256
        slot_max = (1 << self.SLOT_BITS) - 1 if self.S > 1 else 0x7FFF
        self.cap = int(opts.get("log_cap",
                                min(max(256, expected), slot_max)))
        self.keys = int(opts.get("kv_keys", 256))
        conc = int(opts.get("concurrency") or n_nodes)
        # leader in-flight table: the sequencer's fixed capacity (the
        # bench sweep holds it constant while P varies)
        self.QL = int(opts.get("leader_slots", max(32, 2 * conc)))
        # per-proxy in-flight table: the proxy tier's unit of capacity
        self.QP = int(opts.get("proxy_slots", 8))
        self.K = int(opts.get("compartment_inbox", 8))
        self.AP = self.K              # replica apply chunk per round
        self.retry = int(opts.get("compartment_retry", 10))
        # election pacing (S > 1; fingerprinted — doc/compartment.md):
        # the failure-detector deadline and the fenced ballot-counter
        # width (ballots live in a 6-bit wire field; a narrower width
        # only lowers the overflow threshold)
        self.etimeout = int(opts.get("election_timeout_rounds") or 60)
        self.bal_width = int(opts.get("ballot_width") or self.MAX_BAL_BITS)
        # packed-word field widths
        if self.cap > slot_max:
            raise ValueError(
                f"log_cap must fit {12 if self.S > 1 else 15}-bit slots "
                f"(<= {slot_max}{' with sequencers > 1' if self.S > 1 else ''})")
        if self.keys > 4095:
            raise ValueError("kv_keys must fit the 12-bit key field")
        if conc > ((1 << self.CLIENT_BITS) - 1 if self.S > 1 else 0x7FFF):
            raise ValueError(
                "concurrency must fit the "
                f"{self.CLIENT_BITS if self.S > 1 else 15}-bit client id "
                f"field{' with sequencers > 1' if self.S > 1 else ''}")
        if self.S > 1:
            if not 1 <= self.bal_width <= self.MAX_BAL_BITS:
                raise ValueError(
                    f"ballot_width must be in [1, {self.MAX_BAL_BITS}], "
                    f"got {self.bal_width}")
            if self.A > 30:
                raise ValueError(
                    "sequencers > 1 needs the acceptor grid to fit a "
                    f"31-bit promise mask (A <= 30, got {self.A})")
            if self.S >= (1 << self.bal_width):
                raise ValueError(
                    f"{self.S} sequencers need ballot_width > "
                    f"{self.bal_width} (each candidate owns a residue "
                    f"class)")
            if self.etimeout < 2 * self.retry:
                raise ValueError(
                    "election_timeout_rounds must cover at least two "
                    f"heartbeat ticks (>= {2 * self.retry})")
        self.AR = max(self.A, self.R)

    # --- ballot/client/slot wire packing -------------------------------

    def pack_assign_a(self, bal, client, slot):
        if self.S == 1:
            return (client << 16) | slot
        return (bal << 24) | (client << 12) | slot

    def unpack_assign_a(self, a):
        """-> (bal, client, slot)."""
        if self.S == 1:
            return jnp.zeros_like(a), a >> 16, a & 0x7FFF
        return (a >> 24) & 0x3F, (a >> 12) & 0xFFF, a & 0xFFF

    def pack_learn_a(self, client, slot):
        if self.S == 1:
            return (client << 16) | slot
        return (client << 12) | slot

    def unpack_learn_a(self, a):
        """-> (client, slot)."""
        if self.S == 1:
            return a >> 16, a & 0x7FFF
        return (a >> 12) & 0xFFF, a & 0xFFF


def _pack_cmd(key, op, v1, v2):
    return (key << 18) | (op << 16) | (v1 << 8) | v2


def _unpack_cmd(cmd):
    return ((cmd >> 18) & 0xFFF, (cmd >> 16) & 0x3,
            (cmd >> 8) & 0xFF, cmd & 0xFF)


def _alloc_rows(occupied, want):
    """Free-row allocation without a sort: rank free rows and wanted
    entries by prefix sum and pair rank-for-rank. Returns (ok, row):
    `ok` marks entries that found a row, `row` its index. Scatter
    targets are unique by construction (distinct ranks -> distinct
    rows; parked columns get distinct out-of-bounds targets), so the
    writes may soundly promise unique_indices."""
    n, Q = occupied.shape
    free = ~occupied
    n_free = jnp.sum(free.astype(I32), axis=1)
    free_rank = jnp.cumsum(free.astype(I32), axis=1) - 1
    rows_ar = jnp.broadcast_to(jnp.arange(Q, dtype=I32)[None, :], (n, Q))
    nn = jnp.arange(n, dtype=I32)[:, None]
    row_by_rank = jnp.zeros((n, Q), I32).at[
        nn, jnp.where(free, free_rank, Q + rows_ar)].set(
            rows_ar, mode="drop", unique_indices=True)
    want_rank = jnp.cumsum(want.astype(I32), axis=1) - 1
    ok = want & (want_rank < n_free[:, None])
    row = jnp.take_along_axis(row_by_rank,
                              jnp.clip(want_rank, 0, Q - 1), axis=1)
    return ok, row


def _put_rows(dst, ok, row, val):
    """Scatter per-entry values into allocated rows ([n, K] -> [n, Q]);
    parked entries target distinct out-of-bounds rows (drop)."""
    n, Q = dst.shape[0], dst.shape[1]
    K = ok.shape[1]
    nn = jnp.arange(n, dtype=I32)[:, None]
    kk = jnp.broadcast_to(jnp.arange(K, dtype=I32)[None, :], (n, K))
    return dst.at[nn, jnp.where(ok, row, Q + kk)].set(
        val, mode="drop", unique_indices=True)


def _first_per_key(valid, key):
    """In-round dedup: keeps only the first valid entry per key among
    the K inbox lanes (duplicated RPCs — resends, the duplicate
    nemesis — must not double-apply within one round, and deduped
    writes may promise unique scatter indices)."""
    K = valid.shape[1]
    earlier = (jnp.arange(K, dtype=I32)[None, :]
               < jnp.arange(K, dtype=I32)[:, None])        # [k, j]: j < k
    same = valid[:, None, :] & (key[:, :, None] == key[:, None, :])
    dup = (same & earlier[None]).any(axis=2)
    return valid & ~dup


def _match_rows(row_valid, row_slot, msg_valid, msg_slot):
    """[n, Q, K] mask: table row q matches inbox entry k on slot."""
    return (row_valid[:, :, None] & msg_valid[:, None, :]
            & (row_slot[:, :, None] == msg_slot[:, None, :]))


def _col_quorum(lay: Layout, bits):
    """True where the acceptor bitmask `bits` (grid index r*cols+c)
    covers at least one COMPLETE grid column — the phase-1 quorum that
    intersects every phase-2 row quorum. Works on any leading shape."""
    pos = (jnp.arange(lay.rows, dtype=I32)[:, None] * lay.cols
           + jnp.arange(lay.cols, dtype=I32)[None, :])     # [rows, cols]
    have = ((bits[..., None, None] >> pos) & 1).astype(bool)
    return have.all(axis=-2).any(axis=-1)


def _out(shape, **fields) -> Msgs:
    out = Msgs.empty(shape)
    return out.replace(**fields)


class SequencerRole(NodeProgram):
    """The sequencer candidates: slot assignment + the in-flight table
    (the retry root that makes volatile proxies safe) PLUS, with S > 1,
    ballot-numbered MultiPaxos phase 1 — failure detection, column-
    quorum prepare/promise, in-flight slot recovery, and client
    redirects. All state is durable: ballot floors, the table, and an
    in-progress candidacy ride the durable store, so a mid-election
    kill/restart (or checkpoint/SIGKILL-resume) continues exactly where
    it stopped."""

    name = "compartment-sequencer"
    durable_keys = None          # sequencer state fsyncs before acting

    def __init__(self, opts, nodes, lay: Layout):
        super().__init__(opts, nodes)
        self.lay = lay
        self.inbox_cap = lay.K
        if lay.S == 1:
            self.outbox_cap = lay.QL + lay.K
        else:
            # per-row fan lanes (T_ASSIGN on lane 0 / T_QRY per
            # acceptor) + prepare lanes + commit-watermark lanes +
            # heartbeat lanes + client shed/redirect lanes
            self.outbox_cap = (lay.QL * lay.AR + 2 * lay.A + lay.S
                               + lay.K)

    def init_state(self):
        n, Q = self.n_nodes, self.lay.QL
        z = lambda *s: jnp.zeros(s, I32)  # noqa: E731
        st = {"next_slot": z(n),
              "t_valid": jnp.zeros((n, Q), bool),
              "t_slot": z(n, Q), "t_cmd": z(n, Q),
              "t_client": z(n, Q), "t_mid": z(n, Q),
              "t_last": jnp.full((n, Q), -(1 << 20), I32)}
        if self.lay.S > 1:
            me = jnp.arange(n, dtype=I32)
            st.update({
                # ballots: own floor (durable monotonic), highest seen
                "bal": z(n), "seen": z(n),
                "leading": me == 0,            # node 0 leads at ballot 0
                "electing": jnp.zeros(n, bool),
                "prom": z(n),                  # promise bitmask (grid idx)
                "cand_round": z(n),
                "rec_hi": jnp.full((n,), -1, I32),
                "rec_next": z(n),
                # failure detector + pacing
                "heard": z(n),
                "deadline": (jnp.full((n,), self.lay.etimeout, I32)
                             + me * 2 * self.lay.retry),
                "boff": z(n),
                "hb_last": jnp.full((n,), -(1 << 20), I32),
                "elect_last": jnp.full((n,), -(1 << 20), I32),
                # per-row ballot + recovery-query bookkeeping
                "t_bal": z(n, Q),
                "t_q": jnp.zeros((n, Q), bool),
                "t_qmask": z(n, Q),
                "t_qbal": jnp.full((n, Q), -1, I32),
                # commit watermark: done_bits marks slots retired via
                # T_DONE (stored on ALL replicas); dfront is the
                # contiguous frontier piggybacked to the grid (T_CMT)
                # so the NEXT leader's recovery skips the completed
                # prefix
                "done_bits": jnp.zeros((n, self.lay.cap), bool),
                "dfront": jnp.full((n,), -1, I32),
                # election accounting (checkers/availability.py)
                "won_count": z(n), "won_sum": z(n), "won_max": z(n),
                "bal_overflow": z(n)})
        return st

    # ------------------------------------------------------------------
    # S == 1: the PR 9 stable-leader path, bit-for-bit (no ballots, no
    # elections, legacy client<<16|slot packing, Q-lane outbox)
    # ------------------------------------------------------------------

    def _step_stable(self, state, inbox, ctx):
        lay, rnd = self.lay, ctx["round"]
        n, Q, K, C = self.n_nodes, lay.QL, lay.K, lay.cap
        s = dict(state)
        v = inbox.valid

        # T_DONE: the command executed everywhere — retire its row
        done = v & (inbox.type == T_DONE)
        hit = _match_rows(s["t_valid"], s["t_slot"], done, inbox.a)
        s["t_valid"] = s["t_valid"] & ~hit.any(axis=2)

        # new client commands -> slots + table rows
        creq = v & ((inbox.type == T_READ) | (inbox.type == T_WRITE)
                    | (inbox.type == T_CAS))
        op_of = jnp.where(inbox.type == T_WRITE, OP_WRITE,
                          jnp.where(inbox.type == T_CAS, OP_CAS, OP_READ))
        keyk = jnp.clip(inbox.a, 0, lay.keys - 1)
        wc = (inbox.type == T_WRITE) | (inbox.type == T_CAS)
        v1 = jnp.clip(jnp.where(wc, inbox.b + 1, 0), 0, 0xFF)
        v2 = jnp.clip(jnp.where(inbox.type == T_CAS, inbox.c + 1, 0),
                      0, 0xFF)
        cmd = _pack_cmd(keyk, op_of, v1, v2)
        client = jnp.clip(inbox.src - lay.n_nodes, 0, 0x7FFF)
        ok, row = _alloc_rows(s["t_valid"], creq)
        ok_rank = jnp.cumsum(ok.astype(I32), axis=1) - 1
        slot = s["next_slot"][:, None] + ok_rank
        do = ok & (slot < C)
        s["t_valid"] = _put_rows(s["t_valid"], do, row, True)
        s["t_slot"] = _put_rows(s["t_slot"], do, row, slot)
        s["t_cmd"] = _put_rows(s["t_cmd"], do, row, cmd)
        s["t_client"] = _put_rows(s["t_client"], do, row, client)
        s["t_mid"] = _put_rows(s["t_mid"], do, row, inbox.mid)
        # fresh rows are due immediately (t_last = rnd - retry)
        s["t_last"] = _put_rows(s["t_last"], do, row, rnd - lay.retry)
        s["next_slot"] = s["next_slot"] + jnp.sum(do.astype(I32), axis=1)

        # table/slot exhaustion sheds DEFINITELY (error 11: temporarily
        # unavailable) — visible backpressure, never a silent drop
        shed = creq & ~do
        shed_out = _out((n, K), valid=shed, dest=inbox.src,
                        type=jnp.full((n, K), T_ERR, I32),
                        a=jnp.full((n, K), E_UNAVAILABLE, I32),
                        reply_to=inbox.mid)

        # T_ASSIGN resends: every live row on the retry tick
        due = s["t_valid"] & (rnd - s["t_last"] >= lay.retry)
        s["t_last"] = jnp.where(due, rnd, s["t_last"])
        assign_out = _out(
            (n, Q), valid=due,
            dest=lay.p_base + (s["t_slot"] % lay.P),
            type=jnp.full((n, Q), T_ASSIGN, I32),
            a=(s["t_client"] << 16) | s["t_slot"],
            b=s["t_cmd"], c=s["t_mid"])
        return s, _cat_lanes(assign_out, shed_out)

    # ------------------------------------------------------------------
    # S > 1: ballot-numbered elections + recovery + fenced assignment
    # ------------------------------------------------------------------

    def _step_elect(self, state, inbox, ctx):
        lay, rnd = self.lay, ctx["round"]
        n, Q, K, C = self.n_nodes, lay.QL, lay.K, lay.cap
        A, S = lay.A, lay.S
        s = dict(state)
        v = inbox.valid
        me = jnp.arange(n, dtype=I32)

        # ---- observe ballots: heartbeats, depose notices, rejections
        is_hb = v & (inbox.type == T_HB)
        is_nl = v & (inbox.type == T_NLDR)
        is_rj = v & (inbox.type == T_REJP)
        obs = jnp.max(jnp.where(is_hb | is_nl, inbox.a,
                                jnp.where(is_rj, inbox.c, -1)),
                      axis=1, initial=-1)
        seen = jnp.maximum(s["seen"], obs)
        # only a CURRENT leader's heartbeat refreshes the failure
        # detector (a stale leader's HB must not suppress elections)
        hb_cur = (is_hb & (inbox.a >= seen[:, None])).any(axis=1)
        heard = jnp.where(hb_cur, rnd, s["heard"])

        # deposed/overtaken: a higher ballot exists — step down, abort
        # any candidacy, and drop the table (its rows are fenced at the
        # grid; chosen ones will be recovered by the new leader)
        higher = seen > s["bal"]
        dep = s["leading"] & higher
        abort = (s["electing"]
                 & (higher
                    | (is_rj & (inbox.a == s["bal"][:, None])).any(axis=1)))
        leading = s["leading"] & ~dep
        electing = s["electing"] & ~abort
        t_valid = s["t_valid"] & ~dep[:, None]

        # seeded per-round jitter (the raft election-timer idiom):
        # deterministic per seed, identical plain and --mesh
        key_r = jax.random.fold_in(ctx["key"], 23)
        jit1 = jax.random.randint(key_r, (n,), 0, 2 * lay.retry + 1)
        boff = jnp.where(abort | dep, rnd + lay.retry + jit1, s["boff"])
        # re-arm the failure detector on leader activity (or on losing)
        deadline = jnp.where(
            hb_cur | abort | dep,
            rnd + lay.etimeout + me * 2 * lay.retry + jit1,
            s["deadline"])

        # ---- T_DONE retires rows (slot-keyed: DONE means chosen AND
        # stored everywhere, so retiring even a query-phase row is
        # sound — the value needs no re-proposal) and feeds the commit
        # watermark: the contiguous done-frontier bounds the NEXT
        # leader's recovery scan
        done = v & (inbox.type == T_DONE)
        hit = _match_rows(t_valid, s["t_slot"], done, inbox.a)
        t_valid = t_valid & ~hit.any(axis=2)
        done_d = _first_per_key(done, inbox.a)
        d_ok = done_d & (inbox.a >= 0) & (inbox.a < C)
        nn = me[:, None]
        kk0 = jnp.broadcast_to(jnp.arange(K, dtype=I32)[None, :], (n, K))
        done_bits = s["done_bits"].at[
            nn, jnp.where(d_ok, jnp.clip(inbox.a, 0, C - 1),
                          C + kk0)].set(True, mode="drop",
                                        unique_indices=True)
        dfront = s["dfront"]
        for _ in range(8):            # bounded advance; backlog drains
            nxt = jnp.clip(dfront + 1, 0, C - 1)
            bit = jnp.take_along_axis(done_bits, nxt[:, None],
                                      axis=1)[:, 0]
            dfront = jnp.where(bit & (dfront + 1 < C), dfront + 1,
                               dfront)

        # ---- T_PROM folds onto an open candidacy (c packs the
        # acceptor's commit watermark and max accepted slot, 13 bits
        # each: cap <= 4095 guarantees the fit)
        pr = (v & (inbox.type == T_PROM) & electing[:, None]
              & (inbox.a == s["bal"][:, None]))
        prom = s["prom"]
        rec_hi = s["rec_hi"]
        for k in range(K):
            bit = 1 << jnp.clip(inbox.b[:, k], 0, 30)
            prom = jnp.where(pr[:, k], prom | bit, prom)
            rec_hi = jnp.where(pr[:, k],
                               jnp.maximum(rec_hi,
                                           (inbox.c[:, k] & 0x1FFF) - 1),
                               rec_hi)
            dfront = jnp.where(pr[:, k],
                               jnp.maximum(dfront,
                                           (inbox.c[:, k] >> 13) - 1),
                               dfront)
        won = electing & _col_quorum(lay, prom)
        leading = leading | won
        electing = electing & ~won
        next_slot = jnp.where(won, rec_hi + 1, s["next_slot"])
        # recovery starts ABOVE the commit watermark: slots <= dfront
        # are stored on every replica already
        rec_next = jnp.where(won, dfront + 1, s["rec_next"])
        heard = jnp.where(won, rnd, heard)
        dur = rnd - s["cand_round"]
        won_count = s["won_count"] + won.astype(I32)
        won_sum = s["won_sum"] + jnp.where(won, dur, 0)
        won_max = jnp.maximum(s["won_max"], jnp.where(won, dur, 0))
        hb_last = jnp.where(won, rnd - lay.retry, s["hb_last"])

        # ---- T_QVAL folds onto query-phase rows (recovery reads)
        qv = v & (inbox.type == T_QVAL)
        q_idx = (inbox.c >> 16) & 0x7FFF
        q_bal = (inbox.c & 0xFFFF) - 1          # -1 = nothing accepted
        qmask, qbal = s["t_qmask"], s["t_qbal"]
        t_cmd = s["t_cmd"]
        for k in range(K):
            m = (t_valid & s["t_q"] & qv[:, k][:, None]
                 & (s["t_slot"] == inbox.a[:, k][:, None]))
            bit = (1 << jnp.clip(q_idx[:, k], 0, 30))[:, None]
            qmask = jnp.where(m, qmask | bit, qmask)
            better = m & (q_bal[:, k][:, None] > qbal)
            qbal = jnp.where(better, q_bal[:, k][:, None], qbal)
            t_cmd = jnp.where(better, inbox.b[:, k][:, None], t_cmd)
        # a COLUMN of answers resolves the slot: highest-ballot value,
        # or the inert NO-OP when nothing was accepted (gap fill)
        res = t_valid & s["t_q"] & _col_quorum(lay, qmask)
        t_q = s["t_q"] & ~res
        t_cmd = jnp.where(res & (qbal < 0), NOOP_CMD, t_cmd)
        t_last = jnp.where(res, rnd - lay.retry, s["t_last"])

        # ---- client commands: serve when leading, redirect otherwise
        creq = v & ((inbox.type == T_READ) | (inbox.type == T_WRITE)
                    | (inbox.type == T_CAS))
        op_of = jnp.where(inbox.type == T_WRITE, OP_WRITE,
                          jnp.where(inbox.type == T_CAS, OP_CAS, OP_READ))
        keyk = jnp.clip(inbox.a, 0, lay.keys - 1)
        wc = (inbox.type == T_WRITE) | (inbox.type == T_CAS)
        v1 = jnp.clip(jnp.where(wc, inbox.b + 1, 0), 0, 0xFF)
        v2 = jnp.clip(jnp.where(inbox.type == T_CAS, inbox.c + 1, 0),
                      0, 0xFF)
        cmd_in = _pack_cmd(keyk, op_of, v1, v2)
        client = jnp.clip(inbox.src - lay.n_nodes, 0,
                          (1 << lay.CLIENT_BITS) - 1)
        serve = creq & leading[:, None]
        redir = creq & ~leading[:, None]

        # recovery pulls first (low slots keep the replica apply
        # frontier moving), then client allocations on what's left
        t_slot, t_client, t_mid = s["t_slot"], s["t_client"], s["t_mid"]
        t_bal = s["t_bal"]
        kk = jnp.arange(K, dtype=I32)[None, :]
        want_rec = (leading[:, None]
                    & (rec_next[:, None] + kk <= rec_hi[:, None]))
        okr, rowr = _alloc_rows(t_valid, want_rec)
        rec_slot = rec_next[:, None] + kk
        t_valid = _put_rows(t_valid, okr, rowr, True)
        t_slot = _put_rows(t_slot, okr, rowr, rec_slot)
        t_cmd = _put_rows(t_cmd, okr, rowr, NOOP_CMD)
        t_client = _put_rows(t_client, okr, rowr, 0)
        t_mid = _put_rows(t_mid, okr, rowr, -1)    # recovered: no reply
        t_bal = _put_rows(t_bal, okr, rowr, s["bal"][:, None])
        t_q = _put_rows(t_q, okr, rowr, True)
        qmask = _put_rows(qmask, okr, rowr, 0)
        qbal = _put_rows(qbal, okr, rowr, -1)
        t_last = _put_rows(t_last, okr, rowr, rnd - lay.retry)
        rec_next = rec_next + jnp.sum(okr.astype(I32), axis=1)

        ok, row = _alloc_rows(t_valid, serve)
        ok_rank = jnp.cumsum(ok.astype(I32), axis=1) - 1
        slot = next_slot[:, None] + ok_rank
        do = ok & (slot < C)
        t_valid = _put_rows(t_valid, do, row, True)
        t_slot = _put_rows(t_slot, do, row, slot)
        t_cmd = _put_rows(t_cmd, do, row, cmd_in)
        t_client = _put_rows(t_client, do, row, client)
        t_mid = _put_rows(t_mid, do, row, inbox.mid)
        t_bal = _put_rows(t_bal, do, row, s["bal"][:, None])
        t_q = _put_rows(t_q, do, row, False)
        t_last = _put_rows(t_last, do, row, rnd - lay.retry)
        next_slot = next_slot + jnp.sum(do.astype(I32), axis=1)

        # shed (backpressure, code 11) and redirect (code 31 + hint)
        shed = serve & ~do
        know = (rnd - heard <= lay.etimeout) & ((seen % S) != me)
        hint = jnp.where(know, seen % S, -1)
        err_valid = shed | redir
        err_out = _out(
            (n, K), valid=err_valid, dest=inbox.src,
            type=jnp.full((n, K), T_ERR, I32),
            a=jnp.where(redir, E_NOT_LEADER, E_UNAVAILABLE),
            b=jnp.where(redir, hint[:, None],
                        jnp.zeros((n, K), I32)),
            reply_to=inbox.mid)

        # ---- candidacy start: failure detector fired, backoff spent
        start = (~leading & ~electing & (rnd > deadline) & (rnd >= boff))
        newbal = (jnp.maximum(s["bal"], seen) // S + 1) * S + me
        over = start & (newbal >= (1 << lay.bal_width))
        start = start & ~over
        bal = jnp.where(start, newbal, s["bal"])
        bal_overflow = s["bal_overflow"] + over.astype(I32)
        # `newbal` is monotone, so an overflowed candidate is out of
        # ballots until a live leader re-arms its detector (hb_cur
        # above): park the deadline so the counter records EVENTS —
        # stalled candidacies — not every remaining round of the run
        deadline = jnp.where(over, jnp.int32(0x7FFFFFFF), deadline)
        electing = electing | start
        prom = jnp.where(start, 0, prom)
        rec_hi = jnp.where(start, -1, rec_hi)
        cand_round = jnp.where(start, rnd, s["cand_round"])
        t_valid = t_valid & ~start[:, None]     # stale rows are fenced
        elect_last = jnp.where(start, rnd - lay.retry, s["elect_last"])

        # ---- outbox lanes
        # prepares: electing, retry tick, only acceptors not yet heard
        ptick = electing & (rnd - elect_last >= lay.retry)
        elect_last = jnp.where(ptick, rnd, elect_last)
        jjA = jnp.arange(A, dtype=I32)[None, :]
        prep_out = _out(
            (n, A),
            valid=ptick[:, None] & (((prom[:, None] >> jjA) & 1) == 0),
            dest=jnp.broadcast_to(lay.a_base + jjA, (n, A)),
            type=jnp.full((n, A), T_PREP, I32),
            a=jnp.broadcast_to(bal[:, None], (n, A)))
        # heartbeats: leading, retry tick, to the other candidates;
        # the commit watermark rides the same tick to the grid (T_CMT)
        htick = leading & (rnd - hb_last >= lay.retry)
        hb_last = jnp.where(htick, rnd, hb_last)
        jjS = jnp.arange(S, dtype=I32)[None, :]
        hb_out = _out(
            (n, S),
            valid=htick[:, None] & (jjS != me[:, None]),
            dest=jnp.broadcast_to(lay.s_base + jjS, (n, S)),
            type=jnp.full((n, S), T_HB, I32),
            a=jnp.broadcast_to(bal[:, None], (n, S)))
        cmt_out = _out(
            (n, A),
            valid=htick[:, None] & (dfront >= 0)[:, None]
            & jnp.ones((n, A), bool),
            dest=jnp.broadcast_to(lay.a_base + jjA, (n, A)),
            type=jnp.full((n, A), T_CMT, I32),
            a=jnp.broadcast_to(dfront[:, None], (n, A)))
        # per-row fan: query rows ask unanswered acceptors, assign rows
        # send T_ASSIGN (lane 0) to the slot's proxy — on the retry tick
        due = t_valid & (rnd - t_last >= lay.retry)
        t_last = jnp.where(due, rnd, t_last)
        AR = lay.AR
        jj = jnp.arange(AR, dtype=I32)[None, None, :]
        isq = t_q[:, :, None]
        unanswered = ((qmask[:, :, None] >> jj) & 1) == 0
        lane_valid = due[:, :, None] & jnp.where(
            isq, (jj < A) & unanswered, jj == 0)
        lane_dest = jnp.where(
            isq, lay.a_base + jj,
            jnp.broadcast_to((lay.p_base + (t_slot % lay.P))[:, :, None],
                             (n, Q, AR)))
        lane_type = jnp.where(isq, T_QRY, T_ASSIGN)
        pack_a = lay.pack_assign_a(t_bal, t_client, t_slot)
        lane_a = jnp.broadcast_to(
            jnp.where(t_q, t_slot, pack_a)[:, :, None], (n, Q, AR))
        lane_b = jnp.broadcast_to(
            jnp.where(t_q, 0, t_cmd)[:, :, None], (n, Q, AR))
        lane_c = jnp.broadcast_to(
            jnp.where(t_q, t_bal, t_mid)[:, :, None], (n, Q, AR))
        fan_out = _out(
            (n, Q * AR),
            valid=lane_valid.reshape(n, Q * AR),
            dest=lane_dest.reshape(n, Q * AR),
            type=jnp.broadcast_to(lane_type, (n, Q, AR)
                                  ).reshape(n, Q * AR),
            a=lane_a.reshape(n, Q * AR),
            b=lane_b.reshape(n, Q * AR),
            c=lane_c.reshape(n, Q * AR))

        s.update(next_slot=next_slot, t_valid=t_valid, t_slot=t_slot,
                 t_cmd=t_cmd, t_client=t_client, t_mid=t_mid,
                 t_last=t_last, bal=bal, seen=seen, leading=leading,
                 electing=electing, prom=prom, cand_round=cand_round,
                 rec_hi=rec_hi, rec_next=rec_next, heard=heard,
                 deadline=deadline, boff=boff, hb_last=hb_last,
                 elect_last=elect_last, t_bal=t_bal, t_q=t_q,
                 t_qmask=qmask, t_qbal=qbal, done_bits=done_bits,
                 dfront=dfront, won_count=won_count,
                 won_sum=won_sum, won_max=won_max,
                 bal_overflow=bal_overflow)
        return s, _cat_lanes(fan_out, prep_out, cmt_out, hb_out,
                             err_out)

    def step(self, state, inbox, ctx):
        if self.lay.S == 1:
            return self._step_stable(state, inbox, ctx)
        return self._step_elect(state, inbox, ctx)

    def quiescent(self, state):
        if self.lay.S == 1:
            return ~state["t_valid"].any()
        # an elected cluster is never quiescent: heartbeats and failure
        # detectors tick in real (virtual) time, so skipping rounds
        # would fire spurious elections (the raft posture)
        return jnp.array(False)

    def invalid_counters(self, state) -> dict:
        if self.lay.S == 1:
            return {}
        # a candidacy that ran out of fenced ballot space stalls
        # failover silently — the same class as a capacity shed
        return {"ballot-overflow": state["bal_overflow"]}


class ProxyRole(NodeProgram):
    """The stateless fan-out tier: phase-2a broadcast to the acceptor
    grid, row-quorum collection, then learn-until-every-replica-acks.
    VOLATILE (`durable_keys = ()`): a crash wipes the table and the
    leader's resends rebuild it — kill faults exercise exactly the
    paper's 'any proxy can do any command' property. With S > 1, rows
    carry their assigning BALLOT: higher-ballot assigns replace stale
    rows, acks must echo the row's ballot, and a T_P2R fence nack drops
    the row and notifies the stale leader (T_NLDR)."""

    name = "compartment-proxy"
    durable_keys = ()            # stateless tier: nothing survives

    def __init__(self, opts, nodes, lay: Layout):
        super().__init__(opts, nodes)
        self.lay = lay
        # byzantine conviction duty (byzantine.py): when the run's fault
        # set includes the adversary, the proxy carries evidence
        # counters and NACKs convicted messages (K extra outbox lanes).
        # Static, so benign state trees stay byte-identical.
        self.byz = byz_enabled(opts)
        self.inbox_cap = lay.K
        self.outbox_cap = lay.QP * lay.AR + lay.QP \
            + (lay.K if lay.S > 1 else 0) \
            + (lay.K if self.byz else 0)

    def init_state(self):
        n, Q, AR = self.n_nodes, self.lay.QP, self.lay.AR
        z = lambda *s: jnp.zeros(s, I32)  # noqa: E731
        st = {"p_valid": jnp.zeros((n, Q), bool),
              "p_learn": jnp.zeros((n, Q), bool),
              "p_slot": z(n, Q), "p_cmd": z(n, Q),
              "p_client": z(n, Q), "p_mid": z(n, Q),
              "p_last": jnp.full((n, Q), -(1 << 20), I32),
              "p_acks": jnp.zeros((n, Q, AR), bool)}
        if self.lay.S > 1:
            st["p_bal"] = z(n, Q)
        if self.byz:
            # conviction evidence: per-proxy counts of equivocating
            # re-assignments / residue-class ballot violations, plus
            # the latest (src, slot-or-ballot) witness pair each.
            # VOLATILE like the rest of the tier — evidence a kill
            # wipes is evidence the run must re-collect.
            st["z_eq"] = z(n)
            st["z_sb"] = z(n)
            st["z_eq_src"] = jnp.full((n,), -1, I32)
            st["z_eq_slot"] = jnp.full((n,), -1, I32)
            st["z_eq_rnd"] = jnp.full((n,), -1, I32)
            st["z_sb_src"] = jnp.full((n,), -1, I32)
            st["z_sb_bal"] = jnp.full((n,), -1, I32)
            st["z_sb_rnd"] = jnp.full((n,), -1, I32)
        return st

    def step(self, state, inbox, ctx):
        lay, rnd = self.lay, ctx["round"]
        n, Q, K, AR = self.n_nodes, lay.QP, lay.K, lay.AR
        S = lay.S
        s = dict(state)
        v = inbox.valid
        idx_ar = jnp.arange(AR, dtype=I32)[None, :]
        onehot = (inbox.b[:, :, None] == idx_ar[None])        # [n, K, AR]

        # acceptor acks onto phase-2 rows; replica acks onto learn rows.
        # With ballots, a P2B must echo the row's ballot (an ack for a
        # superseded proposal of the same slot must not count toward
        # the new ballot's quorum)
        p2b = _match_rows(s["p_valid"] & ~s["p_learn"], s["p_slot"],
                          v & (inbox.type == T_P2B), inbox.a)
        if S > 1:
            p2b = p2b & (s["p_bal"][:, :, None]
                         == inbox.c[:, None, :])
        ex = _match_rows(s["p_valid"] & s["p_learn"], s["p_slot"],
                         v & (inbox.type == T_EXEC), inbox.a)
        s["p_acks"] = s["p_acks"] | (
            ((p2b | ex)[:, :, :, None]) & onehot[:, None]).any(axis=2)

        # every replica acked: retire the row and report T_DONE to the
        # ASSIGNING leader (ballot % S — the movable sequencer)
        done = (s["p_valid"] & s["p_learn"]
                & s["p_acks"][:, :, :lay.R].all(axis=2))
        done_dest = (jnp.full((n, Q), lay.leader, I32) if S == 1
                     else lay.s_base + (s["p_bal"] % S))
        done_out = _out(
            (n, Q), valid=done, dest=done_dest,
            type=jnp.full((n, Q), T_DONE, I32), a=s["p_slot"])
        s["p_valid"] = s["p_valid"] & ~done

        # flexible grid quorum: any complete acceptor ROW chooses
        grid = s["p_acks"][:, :, :lay.A].reshape(n, Q, lay.rows, lay.cols)
        chosen = (s["p_valid"] & ~s["p_learn"]
                  & grid.all(axis=3).any(axis=2))
        s["p_learn"] = s["p_learn"] | chosen
        s["p_acks"] = jnp.where(chosen[:, :, None], False, s["p_acks"])
        s["p_last"] = jnp.where(chosen, rnd - lay.retry, s["p_last"])

        # T_P2R: the grid fenced this row's ballot — drop the row and
        # tell the stale leader it is deposed (T_NLDR carries the
        # higher promised ballot, routed by the ROW's ballot residue)
        nldr_out = None
        if S > 1:
            rej = v & (inbox.type == T_P2R)
            rejhit = (_match_rows(s["p_valid"], s["p_slot"], rej,
                                  inbox.a)
                      & (s["p_bal"][:, :, None] < inbox.c[:, None, :]))
            drop = rejhit.any(axis=2)
            lane_hit = rejhit.any(axis=1)                    # [n, K]
            stale_bal = jnp.max(
                jnp.where(rejhit, s["p_bal"][:, :, None], 0), axis=1)
            s["p_valid"] = s["p_valid"] & ~drop
            nldr_out = _out(
                (n, K), valid=lane_hit,
                dest=lay.s_base + (stale_bal % S),
                type=jnp.full((n, K), T_NLDR, I32), a=inbox.c)

        # new assignments (slot-keyed dedup; with ballots, a HIGHER-
        # ballot assign replaces a stale row — reset acks/learn — and a
        # stale assign is dropped; a full table drops and the leader's
        # retry tick re-delivers)
        if S == 1:
            bal_in = jnp.zeros((n, K), I32)
            _b, client_in, slot_in = lay.unpack_assign_a(inbox.a)
        else:
            bal_in, client_in, slot_in = lay.unpack_assign_a(inbox.a)
        asg = _first_per_key(v & (inbox.type == T_ASSIGN), slot_in)
        byz_nack = None
        if self.byz:
            # Byzantine convictions at the protocol seam (byzantine.py,
            # doc/faults.md): two invariants honest traffic can never
            # violate. (1) equivocation — a T_ASSIGN hitting a live row
            # for the same slot at the SAME ballot with a DIFFERENT
            # command (an honest leader resends identical payloads, and
            # two leaders never share a ballot); (2) stale ballot — an
            # assign whose ballot lies outside the sender's residue
            # class (honest ballots are k*S + me, so bal % S == src).
            # Convicted messages are counted, dropped, and NACKed
            # T_ERR/E_BYZANTINE to the offending source.
            hit0 = _match_rows(s["p_valid"], s["p_slot"], asg, slot_in)
            cmd_neq = (s["p_cmd"][:, :, None] != inbox.b[:, None, :])
            if S > 1:
                eq_lane = (hit0 & cmd_neq
                           & (s["p_bal"][:, :, None]
                              == bal_in[:, None, :])).any(axis=1)
                sb_lane = asg & (bal_in % S != inbox.src)
            else:
                eq_lane = (hit0 & cmd_neq).any(axis=1)
                sb_lane = jnp.zeros((n, K), bool)
            asg = asg & ~eq_lane & ~sb_lane
            # first-conviction round stamp (conviction latency,
            # BENCH_MODE=byzantine): set once, when the counter leaves 0
            s["z_eq_rnd"] = jnp.where(
                (s["z_eq"] == 0) & eq_lane.any(axis=1),
                rnd, s["z_eq_rnd"])
            s["z_sb_rnd"] = jnp.where(
                (s["z_sb"] == 0) & sb_lane.any(axis=1),
                rnd, s["z_sb_rnd"])
            s["z_eq"] = s["z_eq"] + jnp.sum(eq_lane.astype(I32), axis=1)
            s["z_sb"] = s["z_sb"] + jnp.sum(sb_lane.astype(I32), axis=1)
            wit = lambda lane, f: jnp.max(    # noqa: E731
                jnp.where(lane, f, -1), axis=1)
            s["z_eq_src"] = jnp.where(eq_lane.any(axis=1),
                                      wit(eq_lane, inbox.src),
                                      s["z_eq_src"])
            s["z_eq_slot"] = jnp.where(eq_lane.any(axis=1),
                                       wit(eq_lane, slot_in),
                                       s["z_eq_slot"])
            s["z_sb_src"] = jnp.where(sb_lane.any(axis=1),
                                      wit(sb_lane, inbox.src),
                                      s["z_sb_src"])
            s["z_sb_bal"] = jnp.where(sb_lane.any(axis=1),
                                      wit(sb_lane, bal_in),
                                      s["z_sb_bal"])
            convicted = eq_lane | sb_lane
            byz_nack = _out(
                (n, K), valid=convicted, dest=inbox.src,
                type=jnp.full((n, K), T_ERR, I32),
                a=jnp.full((n, K), E_BYZANTINE, I32),
                b=slot_in, c=bal_in)
        hitS = _match_rows(s["p_valid"], s["p_slot"], asg, slot_in)
        if S > 1:
            stale_msg = (hitS & (s["p_bal"][:, :, None]
                                 >= bal_in[:, None, :])).any(axis=1)
            upgrade = (hitS & (s["p_bal"][:, :, None]
                               < bal_in[:, None, :])).any(axis=2)
            s["p_valid"] = s["p_valid"] & ~upgrade
            asg = asg & ~stale_msg
        else:
            asg = asg & ~hitS.any(axis=1)
        ok, row = _alloc_rows(s["p_valid"], asg)
        s["p_valid"] = _put_rows(s["p_valid"], ok, row, True)
        s["p_learn"] = _put_rows(s["p_learn"], ok, row, False)
        s["p_slot"] = _put_rows(s["p_slot"], ok, row, slot_in)
        s["p_cmd"] = _put_rows(s["p_cmd"], ok, row, inbox.b)
        s["p_client"] = _put_rows(s["p_client"], ok, row, client_in)
        s["p_mid"] = _put_rows(s["p_mid"], ok, row, inbox.c)
        s["p_last"] = _put_rows(s["p_last"], ok, row, rnd - lay.retry)
        if S > 1:
            s["p_bal"] = _put_rows(s["p_bal"], ok, row, bal_in)
        nn = jnp.arange(n, dtype=I32)[:, None]
        kk = jnp.broadcast_to(jnp.arange(K, dtype=I32)[None, :], (n, K))
        s["p_acks"] = s["p_acks"].at[
            nn, jnp.where(ok, row, Q + kk)].set(False, mode="drop",
                                                unique_indices=True)

        # fan-out lanes: row q, lane j -> acceptor j (phase 2a) or
        # replica j (learn), on the retry tick
        due = s["p_valid"] & (rnd - s["p_last"] >= lay.retry)
        s["p_last"] = jnp.where(due, rnd, s["p_last"])
        jj = jnp.broadcast_to(idx_ar[None], (n, Q, AR))
        learn = s["p_learn"][:, :, None]
        lane_valid = due[:, :, None] & jnp.where(
            learn, jj < lay.R, jj < lay.A)
        lane_dest = jnp.where(learn, lay.r_base + jj, lay.a_base + jj)
        lane_type = jnp.where(learn, T_LEARN, T_P2A)
        learn_a = lay.pack_learn_a(s["p_client"], s["p_slot"])
        lane_a = jnp.where(learn,
                           learn_a[:, :, None],
                           jnp.broadcast_to(s["p_slot"][:, :, None],
                                            (n, Q, AR)))
        lane_b = jnp.broadcast_to(s["p_cmd"][:, :, None], (n, Q, AR))
        p2a_c = (jnp.zeros((n, Q), I32) if S == 1 else s["p_bal"])
        lane_c = jnp.where(learn, s["p_mid"][:, :, None],
                           p2a_c[:, :, None])
        fan_out = _out(
            (n, Q * AR),
            valid=lane_valid.reshape(n, Q * AR),
            dest=lane_dest.reshape(n, Q * AR),
            type=jnp.broadcast_to(lane_type, (n, Q, AR)
                                  ).reshape(n, Q * AR),
            a=lane_a.reshape(n, Q * AR),
            b=lane_b.reshape(n, Q * AR),
            c=jnp.broadcast_to(lane_c, (n, Q, AR)).reshape(n, Q * AR))
        outs = [fan_out, done_out]
        if nldr_out is not None:
            outs.append(nldr_out)
        if byz_nack is not None:
            outs.append(byz_nack)
        return s, _cat_lanes(*outs)

    def quiescent(self, state):
        return ~state["p_valid"].any()


class AcceptorRole(NodeProgram):
    """One grid cell: stores the command proposed for each slot and acks
    with its grid index so proxies can assemble row quorums. Durable:
    accepted state fsyncs before the ack leaves. With S > 1 it is a
    full Paxos acceptor: `promised` (durable) fences stale T_PREP
    (T_REJP) and stale-ballot T_P2A (T_P2R), promises carry the max
    accepted slot for `next_slot` recovery, and T_QRY reads back the
    per-slot (cmd, accepted-ballot) pair for value recovery."""

    name = "compartment-acceptor"
    durable_keys = None

    def __init__(self, opts, nodes, lay: Layout):
        super().__init__(opts, nodes)
        self.lay = lay
        self.inbox_cap = lay.K
        self.outbox_cap = lay.K

    def init_state(self):
        n, C = self.n_nodes, self.lay.cap
        st = {"acc_cmd": jnp.zeros((n, C), I32),
              "acc_has": jnp.zeros((n, C), bool)}
        if self.lay.S > 1:
            st.update({"promised": jnp.zeros((n,), I32),
                       "acc_bal": jnp.zeros((n, C), I32),
                       "acc_hi": jnp.full((n,), -1, I32),
                       "acc_cmt": jnp.full((n,), -1, I32)})
        return st

    def _step_stable(self, state, inbox, ctx):
        lay = self.lay
        n, K, C = self.n_nodes, lay.K, lay.cap
        s = dict(state)
        p2a = _first_per_key(inbox.valid & (inbox.type == T_P2A),
                             inbox.a)
        in_cap = p2a & (inbox.a >= 0) & (inbox.a < C)
        nn = jnp.arange(n, dtype=I32)[:, None]
        kk = jnp.broadcast_to(jnp.arange(K, dtype=I32)[None, :], (n, K))
        tgt = jnp.where(in_cap, jnp.clip(inbox.a, 0, C - 1), C + kk)
        s["acc_cmd"] = s["acc_cmd"].at[nn, tgt].set(
            inbox.b, mode="drop", unique_indices=True)
        s["acc_has"] = s["acc_has"].at[nn, tgt].set(
            True, mode="drop", unique_indices=True)
        me = jnp.arange(n, dtype=I32)[:, None]
        acks = _out((n, K), valid=in_cap, dest=inbox.src,
                    type=jnp.full((n, K), T_P2B, I32), a=inbox.a,
                    b=jnp.broadcast_to(me, (n, K)))
        return s, acks

    def _step_elect(self, state, inbox, ctx):
        lay = self.lay
        n, K, C = self.n_nodes, lay.K, lay.cap
        s = dict(state)
        v = inbox.valid
        nn = jnp.arange(n, dtype=I32)[:, None]
        kk = jnp.broadcast_to(jnp.arange(K, dtype=I32)[None, :], (n, K))
        me = jnp.arange(n, dtype=I32)[:, None]

        # commit watermark (monotone, durable): "all slots <= cmt are
        # stored on every replica" — a fact piggybacked by leaders
        # (T_CMT) that bounds the next recovery scan
        cmt = v & (inbox.type == T_CMT)
        s["acc_cmt"] = jnp.maximum(
            s["acc_cmt"],
            jnp.max(jnp.where(cmt, inbox.a, -1), axis=1, initial=-1))

        # promises: only the round's highest prepare is promised (a
        # strictly sound batching of the sequential rule); the rest are
        # rejected with the new floor
        prep = _first_per_key(v & (inbox.type == T_PREP), inbox.a)
        pmax = jnp.maximum(
            s["promised"],
            jnp.max(jnp.where(prep, inbox.a, -1), axis=1, initial=-1))
        prom_ok = prep & (inbox.a == pmax[:, None])
        prom_rej = prep & ~prom_ok
        s["promised"] = pmax

        # phase 2a: accept iff the proposal's ballot clears the promise
        # floor; fenced proposals nack (T_P2R) so stale proxies/leaders
        # learn they are deposed instead of retrying forever
        p2a = _first_per_key(v & (inbox.type == T_P2A), inbox.a)
        in_cap = p2a & (inbox.a >= 0) & (inbox.a < C)
        ok2a = in_cap & (inbox.c >= pmax[:, None])
        nack = in_cap & ~ok2a
        # accepting ballot b IMPLIES promising b (the classic acceptor
        # rule): without raising the floor here, an acceptor that never
        # saw the new leader's prepare (promise quorums are one COLUMN)
        # would happily let a stale lower-ballot proposal overwrite the
        # higher-ballot value it accepted — erasing a possibly-CHOSEN
        # command, which a later recovery would then resolve wrongly
        s["promised"] = jnp.maximum(
            pmax, jnp.max(jnp.where(ok2a, inbox.c, -1), axis=1,
                          initial=-1))
        tgt = jnp.where(ok2a, jnp.clip(inbox.a, 0, C - 1), C + kk)
        s["acc_cmd"] = s["acc_cmd"].at[nn, tgt].set(
            inbox.b, mode="drop", unique_indices=True)
        s["acc_has"] = s["acc_has"].at[nn, tgt].set(
            True, mode="drop", unique_indices=True)
        s["acc_bal"] = s["acc_bal"].at[nn, tgt].set(
            inbox.c, mode="drop", unique_indices=True)
        s["acc_hi"] = jnp.maximum(
            s["acc_hi"],
            jnp.max(jnp.where(ok2a, inbox.a, -1), axis=1, initial=-1))

        # recovery reads: per-slot (cmd, accepted ballot) snapshot,
        # post-update (deterministic same-round ordering)
        qry = v & (inbox.type == T_QRY)
        qs = jnp.clip(inbox.a, 0, C - 1)
        g_cmd = jnp.take_along_axis(s["acc_cmd"], qs, axis=1)
        g_bal = jnp.take_along_axis(s["acc_bal"], qs, axis=1)
        g_has = (jnp.take_along_axis(s["acc_has"], qs, axis=1)
                 & (inbox.a >= 0) & (inbox.a < C))

        # one reply per inbox lane (each lane is exactly one RPC kind)
        rvalid = ok2a | nack | prom_ok | prom_rej | qry
        rtype = jnp.where(
            ok2a, T_P2B,
            jnp.where(nack, T_P2R,
                      jnp.where(prom_ok, T_PROM,
                                jnp.where(prom_rej, T_REJP, T_QVAL))))
        rb = jnp.where(qry, g_cmd,
                       jnp.where(prom_rej, 0,
                                 jnp.broadcast_to(me, (n, K))))
        qval_c = (me << 16) | jnp.where(g_has, g_bal + 1, 0)
        prom_c = ((s["acc_cmt"] + 1) << 13) | (s["acc_hi"] + 1)
        rc = jnp.where(
            ok2a, inbox.c,
            jnp.where(nack | prom_rej, pmax[:, None],
                      jnp.where(prom_ok,
                                jnp.broadcast_to(prom_c[:, None],
                                                 (n, K)),
                                qval_c)))
        out = _out((n, K), valid=rvalid, dest=inbox.src,
                   type=rtype, a=inbox.a, b=rb, c=rc)
        return s, out

    def step(self, state, inbox, ctx):
        if self.lay.S == 1:
            return self._step_stable(state, inbox, ctx)
        return self._step_elect(state, inbox, ctx)

    def quiescent(self, state):
        return jnp.array(True)


class ReplicaRole(NodeProgram):
    """The apply tier: learned commands land at their slots and every
    deduped learn acks back (T_EXEC) the moment it is durably stored —
    storage acks, NOT apply acks, so one slot's completion never waits
    on another's (see the module docstring's deadlock note). Commands
    apply strictly in slot order, and the designated replica
    (`slot % R`) answers the client with the apply-point value.
    Recovered commands (mid = -1: re-proposals and no-op gap fills
    whose clients already timed out) apply without replying."""

    name = "compartment-replica"
    durable_keys = None

    def __init__(self, opts, nodes, lay: Layout):
        super().__init__(opts, nodes)
        self.lay = lay
        self.inbox_cap = lay.K
        self.outbox_cap = lay.AP + lay.K

    def init_state(self):
        n, C = self.n_nodes, self.lay.cap
        z = lambda *s: jnp.zeros(s, I32)  # noqa: E731
        return {"r_cmd": z(n, C), "r_client": z(n, C), "r_mid": z(n, C),
                "r_has": jnp.zeros((n, C), bool),
                "applied": jnp.full((n,), -1, I32),
                "kv": z(n, self.lay.keys)}

    def step(self, state, inbox, ctx):
        lay = self.lay
        n, K, C = self.n_nodes, lay.K, lay.cap
        s = dict(state)
        me = jnp.arange(n, dtype=I32)
        _client_in, slot_in = lay.unpack_learn_a(inbox.a)
        lr = _first_per_key(inbox.valid & (inbox.type == T_LEARN),
                            slot_in)
        in_cap = lr & (slot_in < C)
        nn = me[:, None]
        kk = jnp.broadcast_to(jnp.arange(K, dtype=I32)[None, :], (n, K))
        tgt = jnp.where(in_cap, jnp.clip(slot_in, 0, C - 1), C + kk)

        def put(dst, val):
            return dst.at[nn, tgt].set(val, mode="drop",
                                       unique_indices=True)
        s["r_cmd"] = put(s["r_cmd"], inbox.b)
        s["r_client"] = put(s["r_client"], _client_in)
        s["r_mid"] = put(s["r_mid"], inbox.c)
        s["r_has"] = put(s["r_has"], True)

        # storage acks: EVERY deduped learn acks once stored (covers
        # fresh stores and re-learns of already-stored slots — lost-ack
        # recovery), so a slot's chain completes independently of the
        # in-order apply frontier
        ack_out = _out((n, K), valid=in_cap, dest=inbox.src,
                       type=jnp.full((n, K), T_EXEC, I32), a=slot_in,
                       b=jnp.broadcast_to(me[:, None], (n, K)))

        # in-order apply, one chunk per round (a CAS may read the key
        # the previous step wrote: the kv chain is inherently sequential)
        lanes = []
        for _j in range(lay.AP):
            idx = s["applied"] + 1
            safe = jnp.clip(idx, 0, C - 1)
            active = (idx < C) & jnp.take_along_axis(
                s["r_has"], safe[:, None], axis=1)[:, 0]
            cmd = jnp.take_along_axis(s["r_cmd"], safe[:, None],
                                      axis=1)[:, 0]
            client = jnp.take_along_axis(s["r_client"], safe[:, None],
                                         axis=1)[:, 0]
            mid = jnp.take_along_axis(s["r_mid"], safe[:, None],
                                      axis=1)[:, 0]
            key, op, v1, v2 = _unpack_cmd(cmd)
            cur_v = jnp.take_along_axis(s["kv"], key[:, None],
                                        axis=1)[:, 0]
            cas_ok = (op == OP_CAS) & (cur_v == v1) & (cur_v > 0)
            do_write = active & ((op == OP_WRITE) | cas_ok)
            new_v = jnp.where(op == OP_WRITE, v1, v2)
            s["kv"] = s["kv"].at[
                me, jnp.where(do_write, key, lay.keys)].set(
                    new_v, mode="drop", unique_indices=True)
            s["applied"] = jnp.where(active, idx, s["applied"])
            # the designated replica answers the client with the
            # apply-point value (storage was acked at the learn);
            # recovered commands (mid < 0) apply silently
            desig = active & ((idx % lay.R) == me) & (mid >= 0) \
                & (op != OP_NOOP)
            rtype = jnp.where(
                op == OP_READ,
                jnp.where(cur_v > 0, T_READ_OK, T_ERR),
                jnp.where(op == OP_WRITE, T_WRITE_OK,
                          jnp.where(cas_ok, T_CAS_OK, T_ERR)))
            ra = jnp.where(
                op == OP_READ, jnp.where(cur_v > 0, cur_v, 20),
                jnp.where((op == OP_CAS) & ~cas_ok,
                          jnp.where(cur_v > 0, 22, 20), 0))
            rep = (desig, lay.n_nodes + client, rtype, ra,
                   jnp.zeros((n,), I32), mid)
            lanes.append(rep)
        AL = len(lanes)
        apply_out = _out(
            (n, AL),
            valid=jnp.stack([ln[0] for ln in lanes], axis=1),
            dest=jnp.stack([jnp.broadcast_to(ln[1], (n,))
                            for ln in lanes], axis=1),
            type=jnp.stack([jnp.broadcast_to(ln[2], (n,))
                            for ln in lanes], axis=1),
            a=jnp.stack([jnp.broadcast_to(ln[3], (n,))
                         for ln in lanes], axis=1),
            b=jnp.stack([jnp.broadcast_to(ln[4], (n,))
                         for ln in lanes], axis=1),
            reply_to=jnp.stack([jnp.broadcast_to(ln[5], (n,))
                                for ln in lanes], axis=1))
        return s, _cat_lanes(apply_out, ack_out)

    def quiescent(self, state):
        nxt = jnp.clip(state["applied"] + 1, 0, self.lay.cap - 1)
        pending = jnp.take_along_axis(state["r_has"], nxt[:, None],
                                      axis=1)[:, 0]
        return ~pending.any()


class GridAcceptors(AcceptorRole):
    """Acceptor role with named fault subgroups: the grid's rows and
    columns, for `--nemesis-targets partition=acceptor-col-0` style
    role-targeted faults."""

    def fault_subgroups(self, names):
        lay = self.lay
        out = {}
        for c in range(lay.cols):
            out[f"acceptor-col-{c}"] = [names[r * lay.cols + c]
                                        for r in range(lay.rows)]
        for r in range(lay.rows):
            out[f"acceptor-row-{r}"] = list(
                names[r * lay.cols:(r + 1) * lay.cols])
        return out


@register
class CompartmentProgram(LinKVWire, RolePartition):
    """`--node tpu:compartment`: the role-partitioned compartmentalized
    consensus cluster (see module docstring). Serves lin-kv through the
    shared wire vocabulary; clients talk to the sequencer the host
    currently believes leads, following not-leader redirects (code 31
    with a `hint` node) through the runner's seeded backoff requeue."""

    name = "compartment"

    def __init__(self, opts, nodes):
        lay = Layout(opts, len(nodes))
        self.lay = lay
        self.byz = byz_enabled(opts)
        # host-side leader guess: where new client ops are routed.
        # Updated by redirect hints and probed round-robin on timeouts;
        # checkpointed (host_state) so a resumed run replays the same
        # routing decisions.
        self._leader_guess = lay.leader
        # client-side leader LEASE (doc/compartment.md "client lease",
        # the ISSUE 14 follow-on): without it, a dead leader's clients
        # discover the failover only by waiting out the full RPC
        # timeout per in-flight op — the PR 14 availability dip was
        # ~the 400-round timeout, not the 2-round election. The lease
        # expires the guess `leader_lease_ms` of virtual time after
        # the last REPLY from it (any reply proves liveness: the
        # runner's note_reply hook), so new ops rotate to the next
        # candidate at detection-window speed. One probe per expired
        # window (the expiry re-arms), deterministic per seed; rides
        # host_state for resume; S == 1 (and lease 0) disables — the
        # stable-sequencer path keeps its byte-identical routing.
        self._host_round = 0
        self._lease_ok = None       # not armed until the first contact
        self._lease_rounds = 0
        if lay.S > 1:
            mpr = float(opts.get("ms_per_round", 1.0) or 1.0)
            lease_ms = opts.get("leader_lease_ms")
            if lease_ms is None:
                self._lease_rounds = 2 * lay.etimeout
            else:
                self._lease_rounds = max(0, int(float(lease_ms) / mpr))
        roles = [
            ("sequencers",
             SequencerRole(opts, nodes[:lay.p_base], lay)),
            ("proxies",
             ProxyRole(opts, nodes[lay.p_base:lay.a_base], lay)),
            ("acceptors",
             GridAcceptors(opts, nodes[lay.a_base:lay.r_base], lay)),
            ("replicas", ReplicaRole(opts, nodes[lay.r_base:], lay)),
        ]
        RolePartition.__init__(self, opts, nodes, roles)

    def node_for_op(self, op):
        if self._lease_rounds:
            if self._lease_ok is None:
                # arm at the first routed op: the lease measures
                # silence since last contact, and before any op was
                # ever sent there is nothing to be silent about — an
                # idle start must not rotate off the true leader
                self._lease_ok = self._host_round
            elif self._host_round - self._lease_ok > self._lease_rounds:
                # lease expired: rotate to the next candidate and
                # re-arm, so each expired window probes one new node
                self._leader_guess = (self._leader_guess + 1) % self.lay.S
                self._lease_ok = self._host_round
        return self._leader_guess

    def observe_round(self, r: int):
        """Runner hook: the current virtual round at each routing
        boundary (what the lease expiry is measured against)."""
        self._host_round = int(r)

    def note_reply(self, node_idx: int, rnd: int | None = None):
        """Runner hook: ANY reply from the guessed leader (ok, shed,
        or redirect) proves it alive and renews the lease."""
        if int(node_idx) == self._leader_guess:
            r = int(rnd) if rnd is not None else self._host_round
            self._lease_ok = (r if self._lease_ok is None
                              else max(self._lease_ok, r))

    # --- leader-redirect client routing (runner hooks) ------------------

    def decode_body(self, t, a, b, c, intern):
        if t == T_ERR and a == E_NOT_LEADER:
            return {"type": "error", "code": E_NOT_LEADER,
                    "text": "not leader", "hint": int(b)}
        if t == T_ERR and a == E_BYZANTINE:
            # a convicted-Byzantine NACK (errors.py code 32): proxies
            # address these to the lying sequencer, but the decode is
            # total so any path that surfaces one reads it correctly
            return {"type": "error", "code": E_BYZANTINE,
                    "text": "byzantine", "slot": int(b), "bal": int(c)}
        return super().decode_body(t, a, b, c, intern)

    # --- byzantine adversary wiring (byzantine.py) ----------------------

    def byz_wire(self):
        """Compiled corruption masks over the pool-path outbox: the
        adversary rewrites the culprit sequencer's T_ASSIGN lanes.
        Equivocation xors the command's value byte with a ROUND-VARYING
        nonzero pattern, so any two emissions of one (slot, ballot)
        conflict — a consistent lie would be indistinguishable from an
        honest assignment. Stale-ballot re-stamps the packed ballot
        outside the sender's residue class (the wire image of a deposed
        leader's replayed traffic); S == 1 has no ballot field, so only
        the equivocation surface exists there."""
        if not self.byz:
            return {}
        lay = self.lay

        def equiv(outbox, culprit, delta, rnd):
            m = culprit_rows(outbox, culprit) & (outbox.type == T_ASSIGN)
            x = ((((rnd ^ delta) & 0x3F) | 1) << 8)
            return m, outbox.a, outbox.b ^ x, outbox.c

        wires = {"equivocation": equiv}
        if lay.S > 1:
            def stale(outbox, culprit, delta, rnd):
                m = (culprit_rows(outbox, culprit)
                     & (outbox.type == T_ASSIGN))
                _bal, client, slot = lay.unpack_assign_a(outbox.a)
                na = lay.pack_assign_a((culprit + 1) % lay.S, client,
                                       slot)
                return m, na, outbox.b, outbox.c

            wires["stale-ballot"] = stale
        return wires

    def byz_evidence(self, nodes_host) -> list:
        """Converts the proxy tier's device evidence counters into
        conviction triples (the TPU path's half of the conviction
        contract; the host path proves the same rules from the wire
        journal — checkers/byzantine.py)."""
        if not self.byz:
            return []
        import numpy as np

        from ..byzantine import conviction
        px = nodes_host["proxies"]
        lay, out = self.lay, []
        for rule, cnt_key, src_key, ev_key, ev_name in (
                ("equivocation", "z_eq", "z_eq_src", "z_eq_slot",
                 "slot"),
                ("stale-ballot", "z_sb", "z_sb_src", "z_sb_bal",
                 "ballot")):
            cnt = np.asarray(px[cnt_key])
            if int(cnt.sum()) == 0:
                continue
            w = int(cnt.argmax())           # the loudest witness proxy
            src = int(np.asarray(px[src_key])[w])
            culprit = (self.nodes[src]
                       if 0 <= src < len(self.nodes) else src)
            # earliest first-conviction round across witness proxies
            # (-1 stamps mean "never convicted" and are masked out)
            rnds = np.asarray(px[cnt_key + "_rnd"])
            live = rnds[rnds >= 0]
            out.append(conviction(
                rule, culprit,
                {"count": int(cnt.sum()),
                 ev_name: int(np.asarray(px[ev_key])[w]),
                 "round": int(live.min()) if live.size else -1},
                witness=self.nodes[lay.p_base + w]))
        return out

    def redirect_hint(self, body):
        """A leader-redirect error body -> the hinted node id (-1 = no
        live leader known: probe the next candidate), or None for every
        other error (complete normally)."""
        if body.get("code") == E_NOT_LEADER:
            return int(body.get("hint", -1))
        return None

    def next_probe(self, contacted: int) -> int:
        """Round-robin candidate probe when a redirect carries no hint
        (mid-election)."""
        return (int(contacted) + 1) % self.lay.S

    def note_leader(self, node_idx: int):
        if 0 <= int(node_idx) < self.lay.S:
            self._leader_guess = int(node_idx)
            # a fresh hint is lease evidence: don't immediately expire
            # the node a redirect just pointed at
            self._lease_ok = self._host_round

    def note_timeout(self, node_idx: int):
        """An RPC to `node_idx` timed out: if that was our leader guess
        (killed/paused/partitioned leader), rotate to the next
        candidate so new ops probe the rest of the tier."""
        if self.lay.S > 1 and int(node_idx) == self._leader_guess:
            self._leader_guess = (self._leader_guess + 1) % self.lay.S

    # --- host session state (rides checkpoints) -------------------------

    def host_state(self):
        st = RolePartition.host_state(self)
        if self.lay.S <= 1:
            return st
        return {"roles": st, "leader_guess": self._leader_guess,
                "lease": [self._host_round, self._lease_ok]}

    def set_host_state(self, st):
        if isinstance(st, dict) and "leader_guess" in st:
            self._leader_guess = int(st["leader_guess"])
            lease = st.get("lease")
            if lease is not None:
                self._host_round = int(lease[0])
                self._lease_ok = (None if lease[1] is None
                                  else int(lease[1]))
            RolePartition.set_host_state(self, st.get("roles"))
        else:
            RolePartition.set_host_state(self, st)

    # --- dynamic nemesis targeting + election accounting ----------------

    def dynamic_fault_groups(self):
        """`--nemesis-targets kill=sequencer` resolves at invoke time to
        the LIVE leader (the failover driver), unlike the static
        `sequencers` group (the whole candidate tier)."""
        return ("sequencer",)

    def current_leader_host(self, nodes_host) -> int:
        """The live leader's global node id, from a host copy of the
        node state tree (the nemesis reads this at each targeted kill;
        deterministic per seed because the state is)."""
        if self.lay.S == 1:
            return self.lay.leader
        import numpy as np
        seq = nodes_host["sequencers"]
        lead = np.asarray(seq["leading"])
        bal = np.asarray(seq["bal"])
        if lead.any():
            return int(np.argmax(np.where(lead, bal, -1)))
        return int(np.max(np.asarray(seq["seen"])) % self.lay.S)

    def election_report(self, nodes_host) -> dict | None:
        """Election accounting for `checkers/availability.py`: completed
        failovers (wins past node 0's ballot-0 incumbency), rounds from
        candidacy to win (mean/max), highest ballot burned, and the
        current leader. None with a stable (S == 1) sequencer."""
        if self.lay.S == 1:
            return None
        import numpy as np
        seq = nodes_host["sequencers"]
        won = np.asarray(seq["won_count"])
        wsum = np.asarray(seq["won_sum"])
        wmax = np.asarray(seq["won_max"])
        total = int(won.sum())
        rep = {
            "candidates": int(self.lay.S),
            "failovers": total,
            "wins-per-candidate": [int(x) for x in won],
            "ballot": int(np.asarray(seq["bal"]).max()),
            "leader": self.current_leader_host(nodes_host),
            "ballot-overflows": int(
                np.asarray(seq["bal_overflow"]).sum()),
        }
        if total:
            rep["rounds-to-leader"] = {
                "mean": round(float(wsum.sum()) / total, 2),
                "max": int(wmax.max()),
            }
        return rep
