"""Compartmentalized consensus: role-partitioned proxy/acceptor/replica
tiers serving lin-kv.

"Scaling Replicated State Machines with Compartmentalization" (PAPERS.md,
arxiv 2012.15762) decouples MultiPaxos' leader into independently-scalable
compartments: the leader only SEQUENCES (assigns log slots — O(1) messages
per command), stateless proxy leaders take over the quadratic work
(broadcast phase-2a to the acceptor grid, collect the quorum, teach the
replicas), and a replica tier applies the chosen log and answers clients.
Client throughput then scales with the PROXY count, not the leader's
message budget — the claim `bench.py BENCH_MODE=compartment` measures
(doc/compartment.md).

This is the first user of `sim.RolePartition` (the multi-program
node-state tree): four roles over contiguous node-id ranges,

    node 0                      leader     (sequencer, durable)
    nodes [1, 1+P)              proxies    (stateless, VOLATILE: a kill
                                            wipes them; the leader's
                                            resend rebuilds their work)
    nodes [1+P, 1+P+A)          acceptors  (rows x cols grid, durable)
    nodes [1+P+A, N)            replicas   (apply the log, durable)

selected with `--node tpu:compartment --roles proxies=P,acceptors=RxC,
replicas=R` and graded by the stock linearizable register checker.

Protocol (stable-leader MultiPaxos phase 2, simplified: the leader never
changes, so ballots are unnecessary — slot ownership is unique by
construction and every stage is idempotent):

  1. clients send read/write/cas to the leader (reads are logged too, so
     every op linearizes at its apply point, like `nodes/raft.py`);
  2. the leader assigns the next slot, parks the command in a durable
     in-flight table, and sends T_ASSIGN to proxy `slot % P` — resending
     on a retry tick until the command is fully executed, which makes
     the leader the retry root: a crashed (volatile) proxy loses
     nothing, the next resend rebuilds its state;
  3. the proxy broadcasts T_P2A to all acceptors and collects T_P2B acks
     per GRID ROW; any complete row is a write quorum (the paper's
     flexible grid quorums: phase-1 — which we never run — would read
     columns, so killing a full column stalls writes but loses nothing);
  4. on quorum the proxy teaches all replicas (T_LEARN) until every
     replica acks STORAGE (T_EXEC), then reports T_DONE to the leader;
  5. replicas store learned commands at their slots — EVERY deduped
     learn is acked the moment it is durably stored, so a slot's
     leader->proxy->replica chain completes independently of every
     other slot (acking at the apply point instead deadlocks: the
     proxy table fills with high slots that can never apply while the
     low slots they wait on can never be admitted) — and apply strictly
     in slot order, the DESIGNATED replica (`slot % R`) answering the
     client with the value computed at the apply point. Re-learns of
     stored slots re-ack (never re-reply), so lost acks always recover;
     liveness holds because the leader retires a slot only once all
     replicas stored it, so every gap below a stored slot is itself a
     slot the leader is still pushing to storage.

Loss, partitions, duplication, pause, and kill therefore only delay:
duplicates are slot-keyed no-ops, resends are idempotent overwrites of
identical values, and the only permanent state is fsynced-before-action
(leader table, acceptor grid, replica log — `durable_keys = None`).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..net.tpu import I32, Msgs, cat_lanes as _cat_lanes
from ..sim import RolePartition
from . import NodeProgram, register
from .raft import (LinKVWire, T_READ, T_WRITE, T_CAS,
                   OP_WRITE, OP_CAS, OP_READ)

# client wire codes (shared with raft via LinKVWire): 10..15
T_ERR = 1
T_READ_OK = 11
T_WRITE_OK = 13
T_CAS_OK = 15
# compartment RPCs
T_ASSIGN = 30    # leader -> proxy:    a = client<<16|slot, b = cmd, c = mid
T_P2A = 31       # proxy -> acceptor:  a = slot, b = cmd
T_P2B = 32       # acceptor -> proxy:  a = slot, b = acceptor grid index
T_LEARN = 33     # proxy -> replica:   a = client<<16|slot, b = cmd, c = mid
T_EXEC = 34      # replica -> proxy:   a = slot, b = replica index
T_DONE = 35      # proxy -> leader:    a = slot

_DEFAULT_ROLES = {"proxies": 2, "rows": 2, "cols": 2, "replicas": 2}
DEFAULT_ROLES = "proxies=2,acceptors=2x2,replicas=2"


def parse_roles(spec) -> dict:
    """`--roles proxies=P,acceptors=RxC,replicas=R` -> {proxies, rows,
    cols, replicas}; omitted roles keep their defaults. A plain
    acceptor count A is a 1 x A grid (single row: the write quorum is
    all acceptors)."""
    spec = spec or DEFAULT_ROLES
    out = {"proxies": None, "rows": None, "cols": None, "replicas": None}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        k, sep, val = part.partition("=")
        k, val = k.strip(), val.strip()
        if not sep or not val:
            raise ValueError(f"--roles: expected name=count, got {part!r}")
        if k == "proxies":
            out["proxies"] = int(val)
        elif k == "acceptors":
            if "x" in val:
                r, c = val.split("x", 1)
                out["rows"], out["cols"] = int(r), int(c)
            else:
                out["rows"], out["cols"] = 1, int(val)
        elif k == "replicas":
            out["replicas"] = int(val)
        else:
            raise ValueError(
                f"--roles: unknown role {k!r} (expected proxies, "
                f"acceptors, replicas)")
    for k, v in out.items():
        if v is None:
            out[k] = _DEFAULT_ROLES[k]
        elif v < 1:
            raise ValueError(f"--roles: {k} must be >= 1, got {v}")
    return out


def roles_node_count(spec) -> int:
    r = parse_roles(spec)
    return 1 + r["proxies"] + r["rows"] * r["cols"] + r["replicas"]


class Layout:
    """Static shape of one compartmentalized cluster, shared by every
    role program so bases, capacities, and retry pacing can never
    disagree."""

    def __init__(self, opts: dict, n_nodes: int):
        r = parse_roles(opts.get("roles"))
        self.P = r["proxies"]
        self.rows, self.cols = r["rows"], r["cols"]
        self.A = self.rows * self.cols
        self.R = r["replicas"]
        self.n_nodes = n_nodes
        self.leader = 0
        self.p_base = 1
        self.a_base = 1 + self.P
        self.r_base = 1 + self.P + self.A
        want = 1 + self.P + self.A + self.R
        if want != n_nodes:
            raise ValueError(
                f"--roles {opts.get('roles')!r} needs {want} nodes "
                f"(1 leader + {self.P} proxies + {self.A} acceptors + "
                f"{self.R} replicas) but the cluster has {n_nodes}; "
                f"drop --node-count/--nodes and let --roles size it")
        # slot capacity scales with the expected op count like raft's
        # log (every client op, reads included, takes a slot)
        rate = float(opts.get("rate") or 0.0)
        tl = float(opts.get("time_limit") or 0.0)
        expected = int(2 * rate * tl) + 256
        self.cap = int(opts.get("log_cap",
                                min(max(256, expected), 0x7FFF)))
        self.keys = int(opts.get("kv_keys", 256))
        conc = int(opts.get("concurrency") or n_nodes)
        # leader in-flight table: the sequencer's fixed capacity (the
        # bench sweep holds it constant while P varies)
        self.QL = int(opts.get("leader_slots", max(32, 2 * conc)))
        # per-proxy in-flight table: the proxy tier's unit of capacity
        self.QP = int(opts.get("proxy_slots", 8))
        self.K = int(opts.get("compartment_inbox", 8))
        self.AP = self.K              # replica apply chunk per round
        self.retry = int(opts.get("compartment_retry", 10))
        # packed-word field widths: slot 15 bits, client 15 bits,
        # key 12 bits + 2-bit op + two value bytes in the cmd word
        if self.cap > 0x7FFF:
            raise ValueError("log_cap must fit 15-bit slots")
        if self.keys > 4095:
            raise ValueError("kv_keys must fit the 12-bit key field")
        if conc > 0x7FFF:
            raise ValueError("concurrency must fit 15-bit client ids")
        self.AR = max(self.A, self.R)


def _pack_cmd(key, op, v1, v2):
    return (key << 18) | (op << 16) | (v1 << 8) | v2


def _unpack_cmd(cmd):
    return ((cmd >> 18) & 0xFFF, (cmd >> 16) & 0x3,
            (cmd >> 8) & 0xFF, cmd & 0xFF)


def _alloc_rows(occupied, want):
    """Free-row allocation without a sort: rank free rows and wanted
    entries by prefix sum and pair rank-for-rank. Returns (ok, row):
    `ok` marks entries that found a row, `row` its index. Scatter
    targets are unique by construction (distinct ranks -> distinct
    rows; parked columns get distinct out-of-bounds targets), so the
    writes may soundly promise unique_indices."""
    n, Q = occupied.shape
    free = ~occupied
    n_free = jnp.sum(free.astype(I32), axis=1)
    free_rank = jnp.cumsum(free.astype(I32), axis=1) - 1
    rows_ar = jnp.broadcast_to(jnp.arange(Q, dtype=I32)[None, :], (n, Q))
    nn = jnp.arange(n, dtype=I32)[:, None]
    row_by_rank = jnp.zeros((n, Q), I32).at[
        nn, jnp.where(free, free_rank, Q + rows_ar)].set(
            rows_ar, mode="drop", unique_indices=True)
    want_rank = jnp.cumsum(want.astype(I32), axis=1) - 1
    ok = want & (want_rank < n_free[:, None])
    row = jnp.take_along_axis(row_by_rank,
                              jnp.clip(want_rank, 0, Q - 1), axis=1)
    return ok, row


def _put_rows(dst, ok, row, val):
    """Scatter per-entry values into allocated rows ([n, K] -> [n, Q]);
    parked entries target distinct out-of-bounds rows (drop)."""
    n, Q = dst.shape[0], dst.shape[1]
    K = ok.shape[1]
    nn = jnp.arange(n, dtype=I32)[:, None]
    kk = jnp.broadcast_to(jnp.arange(K, dtype=I32)[None, :], (n, K))
    return dst.at[nn, jnp.where(ok, row, Q + kk)].set(
        val, mode="drop", unique_indices=True)


def _first_per_key(valid, key):
    """In-round dedup: keeps only the first valid entry per key among
    the K inbox lanes (duplicated RPCs — resends, the duplicate
    nemesis — must not double-apply within one round, and deduped
    writes may promise unique scatter indices)."""
    K = valid.shape[1]
    earlier = (jnp.arange(K, dtype=I32)[None, :]
               < jnp.arange(K, dtype=I32)[:, None])        # [k, j]: j < k
    same = valid[:, None, :] & (key[:, :, None] == key[:, None, :])
    dup = (same & earlier[None]).any(axis=2)
    return valid & ~dup


def _match_rows(row_valid, row_slot, msg_valid, msg_slot):
    """[n, Q, K] mask: table row q matches inbox entry k on slot."""
    return (row_valid[:, :, None] & msg_valid[:, None, :]
            & (row_slot[:, :, None] == msg_slot[:, None, :]))


def _out(shape, **fields) -> Msgs:
    out = Msgs.empty(shape)
    return out.replace(**fields)


class LeaderRole(NodeProgram):
    """The sequencer: assigns slots, parks commands in a durable
    in-flight table, resends T_ASSIGN on the retry tick until T_DONE —
    the retry root that makes volatile proxies safe. O(1) messages per
    command: its fixed table/inbox budget is the 'leader capacity' the
    proxy tier scales past."""

    name = "compartment-leader"
    durable_keys = None          # sequencer state fsyncs before acting

    def __init__(self, opts, nodes, lay: Layout):
        super().__init__(opts, nodes)
        self.lay = lay
        self.inbox_cap = lay.K
        self.outbox_cap = lay.QL + lay.K

    def init_state(self):
        n, Q = self.n_nodes, self.lay.QL
        z = lambda *s: jnp.zeros(s, I32)  # noqa: E731
        return {"next_slot": z(n),
                "t_valid": jnp.zeros((n, Q), bool),
                "t_slot": z(n, Q), "t_cmd": z(n, Q),
                "t_client": z(n, Q), "t_mid": z(n, Q),
                "t_last": jnp.full((n, Q), -(1 << 20), I32)}

    def step(self, state, inbox, ctx):
        lay, rnd = self.lay, ctx["round"]
        n, Q, K, C = self.n_nodes, lay.QL, lay.K, lay.cap
        s = dict(state)
        v = inbox.valid

        # T_DONE: the command executed everywhere — retire its row
        done = v & (inbox.type == T_DONE)
        hit = _match_rows(s["t_valid"], s["t_slot"], done, inbox.a)
        s["t_valid"] = s["t_valid"] & ~hit.any(axis=2)

        # new client commands -> slots + table rows
        creq = v & ((inbox.type == T_READ) | (inbox.type == T_WRITE)
                    | (inbox.type == T_CAS))
        op_of = jnp.where(inbox.type == T_WRITE, OP_WRITE,
                          jnp.where(inbox.type == T_CAS, OP_CAS, OP_READ))
        keyk = jnp.clip(inbox.a, 0, lay.keys - 1)
        wc = (inbox.type == T_WRITE) | (inbox.type == T_CAS)
        v1 = jnp.clip(jnp.where(wc, inbox.b + 1, 0), 0, 0xFF)
        v2 = jnp.clip(jnp.where(inbox.type == T_CAS, inbox.c + 1, 0),
                      0, 0xFF)
        cmd = _pack_cmd(keyk, op_of, v1, v2)
        client = jnp.clip(inbox.src - lay.n_nodes, 0, 0x7FFF)
        ok, row = _alloc_rows(s["t_valid"], creq)
        ok_rank = jnp.cumsum(ok.astype(I32), axis=1) - 1
        slot = s["next_slot"][:, None] + ok_rank
        do = ok & (slot < C)
        s["t_valid"] = _put_rows(s["t_valid"], do, row, True)
        s["t_slot"] = _put_rows(s["t_slot"], do, row, slot)
        s["t_cmd"] = _put_rows(s["t_cmd"], do, row, cmd)
        s["t_client"] = _put_rows(s["t_client"], do, row, client)
        s["t_mid"] = _put_rows(s["t_mid"], do, row, inbox.mid)
        # fresh rows are due immediately (t_last = rnd - retry)
        s["t_last"] = _put_rows(s["t_last"], do, row, rnd - lay.retry)
        s["next_slot"] = s["next_slot"] + jnp.sum(do.astype(I32), axis=1)

        # table/slot exhaustion sheds DEFINITELY (error 11: temporarily
        # unavailable) — visible backpressure, never a silent drop
        shed = creq & ~do
        shed_out = _out((n, K), valid=shed, dest=inbox.src,
                        type=jnp.full((n, K), T_ERR, I32),
                        a=jnp.full((n, K), 11, I32),
                        reply_to=inbox.mid)

        # T_ASSIGN resends: every live row on the retry tick
        due = s["t_valid"] & (rnd - s["t_last"] >= lay.retry)
        s["t_last"] = jnp.where(due, rnd, s["t_last"])
        assign_out = _out(
            (n, Q), valid=due,
            dest=lay.p_base + (s["t_slot"] % lay.P),
            type=jnp.full((n, Q), T_ASSIGN, I32),
            a=(s["t_client"] << 16) | s["t_slot"],
            b=s["t_cmd"], c=s["t_mid"])
        return s, _cat_lanes(assign_out, shed_out)

    def quiescent(self, state):
        return ~state["t_valid"].any()


class ProxyRole(NodeProgram):
    """The stateless fan-out tier: phase-2a broadcast to the acceptor
    grid, row-quorum collection, then learn-until-every-replica-acks.
    VOLATILE (`durable_keys = ()`): a crash wipes the table and the
    leader's resends rebuild it — kill faults exercise exactly the
    paper's 'any proxy can do any command' property."""

    name = "compartment-proxy"
    durable_keys = ()            # stateless tier: nothing survives

    def __init__(self, opts, nodes, lay: Layout):
        super().__init__(opts, nodes)
        self.lay = lay
        self.inbox_cap = lay.K
        self.outbox_cap = lay.QP * lay.AR + lay.QP

    def init_state(self):
        n, Q, AR = self.n_nodes, self.lay.QP, self.lay.AR
        z = lambda *s: jnp.zeros(s, I32)  # noqa: E731
        return {"p_valid": jnp.zeros((n, Q), bool),
                "p_learn": jnp.zeros((n, Q), bool),
                "p_slot": z(n, Q), "p_cmd": z(n, Q),
                "p_client": z(n, Q), "p_mid": z(n, Q),
                "p_last": jnp.full((n, Q), -(1 << 20), I32),
                "p_acks": jnp.zeros((n, Q, AR), bool)}

    def step(self, state, inbox, ctx):
        lay, rnd = self.lay, ctx["round"]
        n, Q, K, AR = self.n_nodes, lay.QP, lay.K, lay.AR
        s = dict(state)
        v = inbox.valid
        idx_ar = jnp.arange(AR, dtype=I32)[None, :]
        onehot = (inbox.b[:, :, None] == idx_ar[None])        # [n, K, AR]

        # acceptor acks onto phase-2 rows; replica acks onto learn rows
        p2b = _match_rows(s["p_valid"] & ~s["p_learn"], s["p_slot"],
                          v & (inbox.type == T_P2B), inbox.a)
        ex = _match_rows(s["p_valid"] & s["p_learn"], s["p_slot"],
                         v & (inbox.type == T_EXEC), inbox.a)
        s["p_acks"] = s["p_acks"] | (
            ((p2b | ex)[:, :, :, None]) & onehot[:, None]).any(axis=2)

        # every replica acked: retire the row and report T_DONE
        done = (s["p_valid"] & s["p_learn"]
                & s["p_acks"][:, :, :lay.R].all(axis=2))
        done_out = _out(
            (n, Q), valid=done,
            dest=jnp.full((n, Q), lay.leader, I32),
            type=jnp.full((n, Q), T_DONE, I32), a=s["p_slot"])
        s["p_valid"] = s["p_valid"] & ~done

        # flexible grid quorum: any complete acceptor ROW chooses
        grid = s["p_acks"][:, :, :lay.A].reshape(n, Q, lay.rows, lay.cols)
        chosen = (s["p_valid"] & ~s["p_learn"]
                  & grid.all(axis=3).any(axis=2))
        s["p_learn"] = s["p_learn"] | chosen
        s["p_acks"] = jnp.where(chosen[:, :, None], False, s["p_acks"])
        s["p_last"] = jnp.where(chosen, rnd - lay.retry, s["p_last"])

        # new assignments (slot-keyed dedup: duplicates and re-deliveries
        # of slots already in the table are no-ops; a full table drops —
        # the leader's retry tick re-delivers)
        asg = _first_per_key(v & (inbox.type == T_ASSIGN), inbox.a)
        slot_in = inbox.a & 0x7FFF
        known = _match_rows(s["p_valid"], s["p_slot"], asg,
                            slot_in).any(axis=1)
        asg = asg & ~known
        ok, row = _alloc_rows(s["p_valid"], asg)
        s["p_valid"] = _put_rows(s["p_valid"], ok, row, True)
        s["p_learn"] = _put_rows(s["p_learn"], ok, row, False)
        s["p_slot"] = _put_rows(s["p_slot"], ok, row, slot_in)
        s["p_cmd"] = _put_rows(s["p_cmd"], ok, row, inbox.b)
        s["p_client"] = _put_rows(s["p_client"], ok, row, inbox.a >> 16)
        s["p_mid"] = _put_rows(s["p_mid"], ok, row, inbox.c)
        s["p_last"] = _put_rows(s["p_last"], ok, row, rnd - lay.retry)
        nn = jnp.arange(n, dtype=I32)[:, None]
        kk = jnp.broadcast_to(jnp.arange(K, dtype=I32)[None, :], (n, K))
        s["p_acks"] = s["p_acks"].at[
            nn, jnp.where(ok, row, Q + kk)].set(False, mode="drop",
                                                unique_indices=True)

        # fan-out lanes: row q, lane j -> acceptor j (phase 2a) or
        # replica j (learn), on the retry tick
        due = s["p_valid"] & (rnd - s["p_last"] >= lay.retry)
        s["p_last"] = jnp.where(due, rnd, s["p_last"])
        jj = jnp.broadcast_to(idx_ar[None], (n, Q, AR))
        learn = s["p_learn"][:, :, None]
        lane_valid = due[:, :, None] & jnp.where(
            learn, jj < lay.R, jj < lay.A)
        lane_dest = jnp.where(learn, lay.r_base + jj, lay.a_base + jj)
        lane_type = jnp.where(learn, T_LEARN, T_P2A)
        lane_a = jnp.where(learn,
                           (s["p_client"][:, :, None] << 16)
                           | s["p_slot"][:, :, None],
                           jnp.broadcast_to(s["p_slot"][:, :, None],
                                            (n, Q, AR)))
        lane_b = jnp.broadcast_to(s["p_cmd"][:, :, None], (n, Q, AR))
        lane_c = jnp.where(learn, s["p_mid"][:, :, None], 0)
        fan_out = _out(
            (n, Q * AR),
            valid=lane_valid.reshape(n, Q * AR),
            dest=lane_dest.reshape(n, Q * AR),
            type=jnp.broadcast_to(lane_type, (n, Q, AR)
                                  ).reshape(n, Q * AR),
            a=lane_a.reshape(n, Q * AR),
            b=lane_b.reshape(n, Q * AR),
            c=jnp.broadcast_to(lane_c, (n, Q, AR)).reshape(n, Q * AR))
        return s, _cat_lanes(fan_out, done_out)

    def quiescent(self, state):
        return ~state["p_valid"].any()


class AcceptorRole(NodeProgram):
    """One grid cell: stores the command proposed for each slot (single
    stable proposer: first write is the only value ever proposed;
    re-accepts are idempotent overwrites) and acks with its grid index
    so proxies can assemble row quorums. Durable: accepted state
    fsyncs before the ack leaves."""

    name = "compartment-acceptor"
    durable_keys = None

    def __init__(self, opts, nodes, lay: Layout):
        super().__init__(opts, nodes)
        self.lay = lay
        self.inbox_cap = lay.K
        self.outbox_cap = lay.K

    def init_state(self):
        n, C = self.n_nodes, self.lay.cap
        return {"acc_cmd": jnp.zeros((n, C), I32),
                "acc_has": jnp.zeros((n, C), bool)}

    def step(self, state, inbox, ctx):
        lay = self.lay
        n, K, C = self.n_nodes, lay.K, lay.cap
        s = dict(state)
        p2a = _first_per_key(inbox.valid & (inbox.type == T_P2A),
                             inbox.a)
        in_cap = p2a & (inbox.a >= 0) & (inbox.a < C)
        nn = jnp.arange(n, dtype=I32)[:, None]
        kk = jnp.broadcast_to(jnp.arange(K, dtype=I32)[None, :], (n, K))
        tgt = jnp.where(in_cap, jnp.clip(inbox.a, 0, C - 1), C + kk)
        s["acc_cmd"] = s["acc_cmd"].at[nn, tgt].set(
            inbox.b, mode="drop", unique_indices=True)
        s["acc_has"] = s["acc_has"].at[nn, tgt].set(
            True, mode="drop", unique_indices=True)
        me = jnp.arange(n, dtype=I32)[:, None]
        acks = _out((n, K), valid=in_cap, dest=inbox.src,
                    type=jnp.full((n, K), T_P2B, I32), a=inbox.a,
                    b=jnp.broadcast_to(me, (n, K)))
        return s, acks

    def quiescent(self, state):
        return jnp.array(True)


class ReplicaRole(NodeProgram):
    """The apply tier: learned commands land at their slots and every
    deduped learn acks back (T_EXEC) the moment it is durably stored —
    storage acks, NOT apply acks, so one slot's completion never waits
    on another's (see the module docstring's deadlock note). Commands
    apply strictly in slot order, and the designated replica
    (`slot % R`) answers the client with the apply-point value.
    Re-learns of stored slots re-ack — never re-reply (a duplicate
    client reply would be stale anyway, but the ack must always be
    recoverable)."""

    name = "compartment-replica"
    durable_keys = None

    def __init__(self, opts, nodes, lay: Layout):
        super().__init__(opts, nodes)
        self.lay = lay
        self.inbox_cap = lay.K
        self.outbox_cap = lay.AP + lay.K

    def init_state(self):
        n, C = self.n_nodes, self.lay.cap
        z = lambda *s: jnp.zeros(s, I32)  # noqa: E731
        return {"r_cmd": z(n, C), "r_client": z(n, C), "r_mid": z(n, C),
                "r_has": jnp.zeros((n, C), bool),
                "applied": jnp.full((n,), -1, I32),
                "kv": z(n, self.lay.keys)}

    def step(self, state, inbox, ctx):
        lay = self.lay
        n, K, C = self.n_nodes, lay.K, lay.cap
        s = dict(state)
        me = jnp.arange(n, dtype=I32)
        lr = _first_per_key(inbox.valid & (inbox.type == T_LEARN),
                            inbox.a & 0x7FFF)
        slot_in = inbox.a & 0x7FFF
        in_cap = lr & (slot_in < C)
        nn = me[:, None]
        kk = jnp.broadcast_to(jnp.arange(K, dtype=I32)[None, :], (n, K))
        tgt = jnp.where(in_cap, jnp.clip(slot_in, 0, C - 1), C + kk)

        def put(dst, val):
            return dst.at[nn, tgt].set(val, mode="drop",
                                       unique_indices=True)
        s["r_cmd"] = put(s["r_cmd"], inbox.b)
        s["r_client"] = put(s["r_client"], inbox.a >> 16)
        s["r_mid"] = put(s["r_mid"], inbox.c)
        s["r_has"] = put(s["r_has"], True)

        # storage acks: EVERY deduped learn acks once stored (covers
        # fresh stores and re-learns of already-stored slots — lost-ack
        # recovery), so a slot's chain completes independently of the
        # in-order apply frontier
        ack_out = _out((n, K), valid=in_cap, dest=inbox.src,
                       type=jnp.full((n, K), T_EXEC, I32), a=slot_in,
                       b=jnp.broadcast_to(me[:, None], (n, K)))

        # in-order apply, one chunk per round (a CAS may read the key
        # the previous step wrote: the kv chain is inherently sequential)
        lanes = []
        for _j in range(lay.AP):
            idx = s["applied"] + 1
            safe = jnp.clip(idx, 0, C - 1)
            active = (idx < C) & jnp.take_along_axis(
                s["r_has"], safe[:, None], axis=1)[:, 0]
            cmd = jnp.take_along_axis(s["r_cmd"], safe[:, None],
                                      axis=1)[:, 0]
            client = jnp.take_along_axis(s["r_client"], safe[:, None],
                                         axis=1)[:, 0]
            mid = jnp.take_along_axis(s["r_mid"], safe[:, None],
                                      axis=1)[:, 0]
            key, op, v1, v2 = _unpack_cmd(cmd)
            cur_v = jnp.take_along_axis(s["kv"], key[:, None],
                                        axis=1)[:, 0]
            cas_ok = (op == OP_CAS) & (cur_v == v1) & (cur_v > 0)
            do_write = active & ((op == OP_WRITE) | cas_ok)
            new_v = jnp.where(op == OP_WRITE, v1, v2)
            s["kv"] = s["kv"].at[
                me, jnp.where(do_write, key, lay.keys)].set(
                    new_v, mode="drop", unique_indices=True)
            s["applied"] = jnp.where(active, idx, s["applied"])
            # the designated replica answers the client with the
            # apply-point value (storage was acked at the learn)
            desig = active & ((idx % lay.R) == me)
            rtype = jnp.where(
                op == OP_READ,
                jnp.where(cur_v > 0, T_READ_OK, T_ERR),
                jnp.where(op == OP_WRITE, T_WRITE_OK,
                          jnp.where(cas_ok, T_CAS_OK, T_ERR)))
            ra = jnp.where(
                op == OP_READ, jnp.where(cur_v > 0, cur_v, 20),
                jnp.where((op == OP_CAS) & ~cas_ok,
                          jnp.where(cur_v > 0, 22, 20), 0))
            rep = (desig, lay.n_nodes + client, rtype, ra,
                   jnp.zeros((n,), I32), mid)
            lanes.append(rep)
        AL = len(lanes)
        apply_out = _out(
            (n, AL),
            valid=jnp.stack([ln[0] for ln in lanes], axis=1),
            dest=jnp.stack([jnp.broadcast_to(ln[1], (n,))
                            for ln in lanes], axis=1),
            type=jnp.stack([jnp.broadcast_to(ln[2], (n,))
                            for ln in lanes], axis=1),
            a=jnp.stack([jnp.broadcast_to(ln[3], (n,))
                         for ln in lanes], axis=1),
            b=jnp.stack([jnp.broadcast_to(ln[4], (n,))
                         for ln in lanes], axis=1),
            reply_to=jnp.stack([jnp.broadcast_to(ln[5], (n,))
                                for ln in lanes], axis=1))
        return s, _cat_lanes(apply_out, ack_out)

    def quiescent(self, state):
        nxt = jnp.clip(state["applied"] + 1, 0, self.lay.cap - 1)
        pending = jnp.take_along_axis(state["r_has"], nxt[:, None],
                                      axis=1)[:, 0]
        return ~pending.any()


class GridAcceptors(AcceptorRole):
    """Acceptor role with named fault subgroups: the grid's rows and
    columns, for `--nemesis-targets partition=acceptor-col-0` style
    role-targeted faults."""

    def fault_subgroups(self, names):
        lay = self.lay
        out = {}
        for c in range(lay.cols):
            out[f"acceptor-col-{c}"] = [names[r * lay.cols + c]
                                        for r in range(lay.rows)]
        for r in range(lay.rows):
            out[f"acceptor-row-{r}"] = list(
                names[r * lay.cols:(r + 1) * lay.cols])
        return out


@register
class CompartmentProgram(LinKVWire, RolePartition):
    """`--node tpu:compartment`: the role-partitioned compartmentalized
    consensus cluster (see module docstring). Serves lin-kv through the
    shared wire vocabulary; clients talk to the leader (node 0)."""

    name = "compartment"

    def __init__(self, opts, nodes):
        lay = Layout(opts, len(nodes))
        self.lay = lay
        roles = [
            ("leader", LeaderRole(opts, nodes[:1], lay)),
            ("proxies",
             ProxyRole(opts, nodes[lay.p_base:lay.a_base], lay)),
            ("acceptors",
             GridAcceptors(opts, nodes[lay.a_base:lay.r_base], lay)),
            ("replicas", ReplicaRole(opts, nodes[lay.r_base:], lay)),
        ]
        RolePartition.__init__(self, opts, nodes, roles)

    def node_for_op(self, op):
        return self.lay.leader
