"""Batched transactional read/write registers over Raft (serving
`workloads/txn_rw_register.py`).

The replicated-command machinery is `nodes/txn_list_append.py`'s,
unchanged: transactions are interned to opaque ids, ride the raft log
as OP_TXN entries, and materialize host-side by replaying the
committed prefix. Only the micro-op interpreter differs — registers
overwrite where lists append."""

from __future__ import annotations

from . import register
from .txn_list_append import TxnRaftProgram


def apply_rw_txn(db: dict, txn) -> tuple[dict, list]:
    out = []
    for f, k, v in txn:
        key = str(k)
        if f == "r":
            out.append([f, k, db.get(key)])
        else:
            db = {**db, key: v}
            out.append([f, k, v])
    return db, out


@register
class RWRegisterRaftProgram(TxnRaftProgram):
    name = "txn-rw-register"
    apply = staticmethod(apply_rw_txn)
