"""Batched echo node: reply to every `echo` with an `echo_ok` carrying the
same payload (the TPU-native analogue of `demo/python/echo.py` and the
reference's `demo/ruby/echo.rb`, serving `workload/echo.clj`).

Stateless: the whole step is a masked relabeling of the inbox — dest/src
swapped, type rewritten, payload word passed through. No per-node Python,
no loops; one fused XLA kernel for all N nodes."""

from __future__ import annotations

import jax.numpy as jnp

from ..net.tpu import I32
from . import NodeProgram, register

T_ECHO = 10
T_ECHO_OK = 11


@register
class EchoProgram(NodeProgram):
    name = "echo"

    def init_state(self):
        # no per-node state; a placeholder row keeps the pytree non-empty
        return {"rounds": jnp.zeros((self.n_nodes,), I32)}

    def step(self, state, inbox, ctx):
        out = inbox.replace(
            valid=inbox.valid & (inbox.type == T_ECHO),
            dest=inbox.src,
            reply_to=inbox.mid,
            type=jnp.full_like(inbox.type, T_ECHO_OK))
        return {"rounds": state["rounds"] + 1}, out

    # --- host boundary ---

    def request_for_op(self, op):
        return {"type": "echo", "echo": op["value"]}

    def encode_body(self, body, intern):
        assert body["type"] == "echo"
        return (T_ECHO, intern.id(body["echo"]), 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_ECHO_OK:
            return {"type": "echo_ok", "echo": intern.value(a)}
        return super().decode_body(t, a, b, c, intern)

    def completion(self, op, body, read_state, intern):
        return {**op, "type": "ok",
                "value": {k: v for k, v in body.items() if k != "type"}}
