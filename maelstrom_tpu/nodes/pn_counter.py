"""Batched PN-counter node (serving `workload/pn_counter.clj` and, through
the non-negative generator, `workload/g_counter.clj`).

CRDT design, like the reference's gossip counter demo
(`demo/ruby/pn_counter.rb`): each node is an *origin*; state is a pair of
per-origin contribution vectors `pos`/`neg` `[N, M]` (M = n_nodes origins)
merged by elementwise max — a PN-counter as two G-counters. The counter's
value at a node is `sum(pos_row) - sum(neg_row)`.

Replication rides the static edge channels with the same shape as
broadcast's machinery, adapted to monotone *values* instead of set bits:

  - a local add or a merge that raises an origin's entry marks it changed
    and queues it `pending` toward every edge (queueing back toward the
    teaching edge is the acknowledgement: the neighbor observes our merged
    entry equals theirs and marks the edge `synced`)
  - an arriving entry >= our merged entry proves the neighbor is up to
    date: `synced[n, d, o]` is set; changes clear it
  - a periodic tick requeues unsynced nonzero origins (`pending |=
    ~synced`), so lost messages are repaired by retransmission — gossip
    repeats until both ends provably agree, then the edge falls silent
    (unlike the reference demo's every-5s-forever gossip, this converges
    to zero traffic, which also lets the runner fast-forward idle time)

Reads are answered host-side from the state row (`read_ok` ack on the
wire), like broadcast reads."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..net.static import EdgeConfig, EdgeMsgs
from ..net.tpu import I32
from ..net.static import reverse_index
from ..workloads.broadcast import TOPOLOGIES, topology_indices
from .gset import gossip_topology_opts
from . import NodeProgram, edge_capacity, edge_timing, register

T_ADD = 10        # client -> node: a = delta
T_ADD_OK = 11
T_READ = 12
T_READ_OK = 13    # bare ack; value materialized host-side
T_ENTRY = 14      # edge: a = origin, b = pos count, c = neg count


@register
class PnCounterProgram(NodeProgram):
    name = "pn-counter"
    needs_state_reads = True
    is_edge = True
    tolerates_channel_overwrites = True   # entries retransmit until synced
    # lanes are decoded by message type across every slot: spill-safe
    edge_lanes_symmetric = True

    def __init__(self, opts, nodes):
        super().__init__(opts, nodes)
        opts = gossip_topology_opts(opts, nodes)
        topo = (opts.get("topology_map")
                or TOPOLOGIES[opts["topology"]](nodes))
        nb = topology_indices(topo, nodes)
        self.neighbors = jnp.asarray(nb)
        self.rev = jnp.asarray(reverse_index(nb))
        self.D = int(self.neighbors.shape[1])
        self.M = self.n_nodes                 # one origin per node
        self.per_nb = min(int(opts.get("gossip_per_neighbor", 4)), self.M)
        self.lanes = self.per_nb
        self.ring, self.retry_rounds, _lat = edge_timing(opts, len(nodes))
        self.inbox_cap = int(opts.get("inbox_cap", 4))
        self.outbox_cap = self.inbox_cap
        spill, chan_lanes, uniform = edge_capacity(opts, self)
        self.edge_cfg = EdgeConfig(n_nodes=self.n_nodes, degree=self.D,
                                   lanes=chan_lanes, ring=self.ring,
                                   spill=spill, uniform_arrival=uniform)
        # read completions take the counter value from the reply-round
        # payload (one word: sum(pos) - sum(neg) at the serving node)
        self.reply_payload_words = 1

    def init_state(self):
        N, D, M = self.n_nodes, self.D, self.M
        return {"pos": jnp.zeros((N, M), I32),
                "neg": jnp.zeros((N, M), I32),
                "pending": jnp.zeros((N, D, M), bool),
                "synced": jnp.zeros((N, D, M), bool)}

    def edge_step(self, state, edge_in: EdgeMsgs, client_in, ctx):
        N, D, M = self.n_nodes, self.D, self.M
        L = int(edge_in.valid.shape[2])   # channel lanes (>= out lanes)
        pos, neg = state["pos"], state["neg"]
        pending, synced = state["pending"], state["synced"]
        origins = jnp.arange(M, dtype=I32)
        edge_ok = self.neighbors >= 0

        # --- client adds: own-origin contributions ---
        K = client_in.valid.shape[1]
        is_add = client_in.valid & (client_in.type == T_ADD)
        is_read = client_in.valid & (client_in.type == T_READ)
        deltas = jnp.where(is_add, client_in.a, 0)
        dpos = jnp.sum(jnp.maximum(deltas, 0), axis=1)        # [N]
        dneg = jnp.sum(jnp.maximum(-deltas, 0), axis=1)
        eye = jnp.eye(N, M, dtype=bool)
        pos = pos + jnp.where(eye, dpos[:, None], 0)
        neg = neg + jnp.where(eye, dneg[:, None], 0)
        local_changed = eye & ((dpos > 0) | (dneg > 0))[:, None]

        # --- merge arriving entries (elementwise max per origin) ---
        e_in = edge_in.valid & (edge_in.type == T_ENTRY)
        p_in = jnp.full((N, D, M), -1, I32)     # -1 = no entry seen
        n_in = jnp.full((N, D, M), -1, I32)
        for l in range(L):
            oh = (jnp.clip(edge_in.a[:, :, l, None], 0, M - 1) == origins)
            m = e_in[:, :, l, None] & oh
            p_in = jnp.maximum(p_in, jnp.where(m, edge_in.b[:, :, l, None],
                                               -1))
            n_in = jnp.maximum(n_in, jnp.where(m, edge_in.c[:, :, l, None],
                                               -1))
        pos2 = jnp.maximum(pos, p_in.max(axis=1))
        neg2 = jnp.maximum(neg, n_in.max(axis=1))
        changed = (pos2 > pos) | (neg2 > neg) | local_changed

        # an entry >= our merged value proves this neighbor is current
        entry_arrived = p_in >= 0
        nb_ge = (entry_arrived & (p_in >= pos2[:, None, :])
                 & (n_in >= neg2[:, None, :]))
        synced_prev = synced
        synced = (synced & ~changed[:, None, :]) | nb_ge

        # Queueing rules:
        #  - teach: changed origins go to every edge not already proven
        #    current this round
        #  - echo: an arriving entry from a not-yet-synced edge is answered
        #    with our merged entry — it both acknowledges (the sender
        #    observes >= and sets its sync bit) and teaches if we know more.
        #    Without the echo, senders are never acknowledged and the retry
        #    tick retransmits forever.
        #  - retry: unsynced nonzero origins requeue periodically, repairing
        #    any loss; sync bits end the cycle.
        pend_teach = changed[:, None, :] & edge_ok[:, :, None] & ~nb_ge
        pend_echo = entry_arrived & ~synced_prev & edge_ok[:, :, None]
        nonzero = (pos2 > 0) | (neg2 > 0)
        requeue = (ctx["round"] % self.retry_rounds) == 0
        pend_retry = (requeue & (~synced & nonzero[:, None, :]
                                 & edge_ok[:, :, None]))
        pending = (pending & ~nb_ge) | pend_teach | pend_echo | pend_retry

        # --- pick entries to send: rotating top_k per edge ---
        rot = (origins - ctx["round"] * self.per_nb) % M
        prio = jnp.where(pending, M - rot, 0)
        topv, topi = jax.lax.top_k(prio, self.per_nb)   # [N, D, per_nb]
        sel = topv > 0
        sent = jnp.zeros((N, D, M), bool)
        for j in range(self.per_nb):
            sent |= sel[:, :, j, None] & (topi[:, :, j, None] == origins)
        pending = pending & ~sent

        p_sel = jnp.take_along_axis(
            jnp.broadcast_to(pos2[:, None, :], (N, D, M)), topi, axis=2)
        n_sel = jnp.take_along_axis(
            jnp.broadcast_to(neg2[:, None, :], (N, D, M)), topi, axis=2)
        edge_out = EdgeMsgs(
            valid=sel & edge_ok[:, :, None],
            type=jnp.full((N, D, self.per_nb), T_ENTRY, I32),
            a=topi.astype(I32), b=p_sel, c=n_sel)

        # --- client replies ---
        reply_type = jnp.where(is_add, T_ADD_OK,
                               jnp.where(is_read, T_READ_OK, 0))
        client_out = client_in.replace(
            valid=is_add | is_read, dest=client_in.src,
            reply_to=client_in.mid, type=reply_type,
            a=jnp.zeros_like(client_in.a))

        return ({"pos": pos2, "neg": neg2, "pending": pending,
                 "synced": synced}, edge_out, client_out)

    def quiescent(self, state):
        nonzero = (state["pos"] > 0) | (state["neg"] > 0)
        edge_ok = self.neighbors >= 0
        unsynced = (~state["synced"] & nonzero[:, None, :]
                    & edge_ok[:, :, None])
        return ~(state["pending"].any() | unsynced.any())

    # --- host boundary (RPC surface per workload/pn_counter.clj) ---

    def request_for_op(self, op):
        if op["f"] == "add":
            return {"type": "add", "delta": op["value"]}
        return {"type": "read"}

    def encode_body(self, body, intern):
        if body["type"] == "add":
            return (T_ADD, int(body["delta"]), 0, 0)
        return (T_READ, 0, 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_ADD_OK:
            return {"type": "add_ok"}
        if t == T_READ_OK:
            return {"type": "read_ok"}
        return super().decode_body(t, a, b, c, intern)

    def completion(self, op, body, read_state, intern):
        if body["type"] == "read_ok":
            row = read_state()
            value = int(np.asarray(row["pos"]).sum()
                        - np.asarray(row["neg"]).sum())
            return {**op, "type": "ok", "value": value}
        return {**op, "type": "ok"}

    def reply_payload(self, state, node_idx):
        vals = (state["pos"][node_idx].sum(axis=1)
                - state["neg"][node_idx].sum(axis=1))
        return vals.astype(I32)[:, None]                  # [M, 1]

    def completion_payload(self, op, body, payload, intern):
        if body["type"] == "read_ok":
            return {**op, "type": "ok", "value": int(payload[0])}
        return {**op, "type": "ok"}


@register
class GCounterProgram(PnCounterProgram):
    """g-counter = pn-counter whose generator never emits negative deltas
    (reference `workload/g_counter.clj:13-14` reuses the pn-counter
    machinery the same way)."""
    name = "g-counter"
