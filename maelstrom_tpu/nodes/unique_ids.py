"""Batched unique-id node: ids are (node index, per-node counter) pairs
minted with zero coordination (the TPU-native analogue of
`demo/python/unique_ids.py`, serving `workloads/unique_ids.py` —
doc/tutorial/09-workloads.md's worked example).

Vectorization note: several `generate` requests can land in one node's
inbox in the same round, and each must get a distinct counter value —
the per-row prefix sum over request slots assigns ranks, the counter
advances by the row's request count, and the whole thing stays one
fused elementwise+cumsum kernel for all N nodes."""

from __future__ import annotations

import jax.numpy as jnp

from ..net.tpu import I32
from . import NodeProgram, register

T_GEN = 10
T_GEN_OK = 11


@register
class UniqueIdsProgram(NodeProgram):
    name = "unique-ids"

    def init_state(self):
        return {"counter": jnp.zeros((self.n_nodes,), I32)}

    def step(self, state, inbox, ctx):
        is_gen = inbox.valid & (inbox.type == T_GEN)        # [N, K]
        # rank each request within its row so same-round requests at
        # one node mint distinct counters
        rank = jnp.cumsum(is_gen.astype(I32), axis=1) - 1
        n_idx = jnp.arange(self.n_nodes, dtype=I32)[:, None]
        minted = state["counter"][:, None] + 1 + rank
        out = inbox.replace(
            valid=is_gen,
            dest=inbox.src,
            reply_to=inbox.mid,
            type=jnp.full_like(inbox.type, T_GEN_OK),
            a=jnp.broadcast_to(n_idx, inbox.a.shape),
            b=minted)
        state = {"counter": state["counter"]
                 + is_gen.astype(I32).sum(axis=1)}
        return state, out

    # --- host boundary ---

    def request_for_op(self, op):
        return {"type": "generate"}

    def encode_body(self, body, intern):
        assert body["type"] == "generate"
        return (T_GEN, 0, 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_GEN_OK:
            return {"type": "generate_ok", "id": f"n{int(a)}-{int(b)}"}
        return super().decode_body(t, a, b, c, intern)

    def completion(self, op, body, read_state, intern):
        return {**op, "type": "ok", "value": body["id"]}
