"""Batched kafka-style replicated log (serving `workloads/kafka.py`;
the TPU-native counterpart of `demo/python/kafka.py`).

Design — ownership for assignment, anti-entropy for reads:

  - key k is OWNED by node k % N: only the owner appends (exclusive
    offset assignment with no coordination — the CAS loop of the demo
    becomes a plain array append, because ownership already serializes)
    and a send arriving elsewhere fails definitely with error 11, which
    the workload records as a clean :fail and retries elsewhere;
  - every node REPLICATES every log over one edge lane per key carrying
    (my_len, offset_being_sent, msg): a node offers the entry at the
    offset its neighbor last advertised — re-offered EVERY round while
    the neighbor trails, so entry loss/overwrite only delays — and a
    node appends an incoming entry only when it lands exactly at its
    own length (in-order, idempotent, hole-free: the full-prefix
    contract the kafka checker's lost-write rule leans on). Length
    advertisements (the ack channel) are event-driven — a node whose
    length changed advertises next round — plus a `beat_rounds`
    heartbeat that bounds recovery when an ack itself is lost;
  - polls are served from ANY node's replica, materialized host-side
    from the node's state row at completion time (needs_state_reads);
  - committed offsets live on node 0 (the coordinator): commit/list
    elsewhere fail definitely with error 11. Commit maps pack into the
    three wire words (up to 6 keys x 15-bit offsets, checked at
    encode), so the marks advance on-device with a max — monotone by
    construction, and rule 4's real-time obligation holds because every
    observation serializes through the one coordinator row."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..net.static import EdgeConfig, EdgeMsgs, reverse_index
from ..net.tpu import I32
from ..workloads.broadcast import TOPOLOGIES, topology_indices
from . import EncodeCapacityError, NodeProgram, T_ERROR, register

T_SEND = 10        # a = key, b = interned msg
T_SEND_OK = 11     # a = offset
T_POLL = 12
T_POLL_OK = 13     # payload materialized host-side (needs_state_reads)
T_COMMIT = 14      # a|b|c = packed per-key offsets (+1, 16 bits each)
T_COMMIT_OK = 15
T_LIST = 16
T_LIST_OK = 17     # a|b|c = packed committed offsets (+1)
# T_ERROR (= 1) comes from the shared reply vocabulary in nodes/__init__
T_REPL = 20        # edge lane k: a = sender len, b = offset, c = msg

# --- consumer-group streaming protocol (kafka_groups > 0, doc/streams.md)
T_SUB = 30         # a = group<<10 | member
T_SUB_OK = 31      # a = generation, b|c = packed key->member assignment
T_FETCH = 32       # a = group<<10 | member, b = key<<16 | (cursor+1),
                   # c = max batch — the cursor poll: NO full-prefix reply
T_FETCH_OK = 33    # a = key<<16 | (start+1), b = n entries (host slices
                   # the replica log [start, start+n), state_reads_final)
T_GCOMMIT = 34     # a = bank<<30 | group<<26 | member<<16 | gen16,
                   # b|c = packed offsets for the 4-key BANK the header
                   # names (bank 0 = keys 0..3, bank 1 = keys 4..7):
                   # commits are split per bank, lifting the old
                   # key_count <= 4 cap to 8 without widening the wire
T_GCOMMIT_OK = 35  # a = bank<<30 | gen30, b|c echo the applied offsets
T_REBAL = 36       # fenced commit: a = NEW generation, b|c = packed
                   # assignment — the member was evicted/staled and has
                   # been rejoined; it must re-fetch from committed
T_GLIST = 37       # a = bank<<30 | group
T_GLIST_OK = 38    # a = bank<<30 | gen30, b|c = packed committed
                   # offsets (+1) of the requested bank

MAX_PACK_KEYS = 6  # 2 x 16-bit fields per wire word, 3 words
BANK_KEYS = 4      # keys per commit bank (2 words x 2 fields)
MAX_GROUP_KEYS = 2 * BANK_KEYS   # group mode: 2 banks on the wire
MAX_GROUPS = 16    # group id rides 4 header bits (26..29; bank is 30)
# member ids ride two field widths: 10 bits in the sub/fetch/gcommit
# request headers AND 8-bit member+1 fields in the packed ASSIGNMENT
# replies (_pack_assign/_unpack_assign) — the tighter one binds
MAX_MEMBERS = 254
COORDINATOR = 0    # node holding the authoritative committed-offset row
                   # AND the consumer-group coordinator state


def _pack_offsets(offs: dict, keys: int, base: int = 0) \
        -> tuple[int, int, int]:
    """Packs offsets for keys [base, base+keys) into up to three wire
    words (field j = key base+j). Legacy commits pack keys 0..K-1 across
    a|b|c; banked group commits pack one 4-key bank into b|c."""
    words = [0, 0, 0]
    for j in range(keys):
        k = base + j
        o = offs.get(str(k), offs.get(k))
        if o is None:
            continue
        if o >= 0x7FFF:
            raise EncodeCapacityError(
                f"kafka committed offset {o} exceeds the 15-bit wire "
                f"field")
        words[j // 2] |= (int(o) + 1) << (16 * (j % 2))
    return words[0], words[1], words[2]


def _device_pack(vals_plus1):
    """[N, K] int32 (0 = absent, v+1 otherwise) -> three packed wire
    words, the device half of _pack_offsets' convention (16-bit fields,
    2 per word). Covers keys 0..5 only — the legacy 3-word reply forms
    (T_POLL_OK/T_LIST_OK); group mode past 4 keys uses the banked
    2-word forms instead, so the truncation is never observable there."""
    words = [jnp.zeros((vals_plus1.shape[0],), I32) for _ in range(3)]
    for k in range(min(vals_plus1.shape[1], MAX_PACK_KEYS)):
        words[k // 2] = words[k // 2] | (vals_plus1[:, k]
                                         << (16 * (k % 2)))
    return words


def _unpack_offsets(a: int, b: int, c: int, keys: int,
                    base: int = 0) -> dict:
    out = {}
    for j in range(keys):
        v = ((a, b, c)[j // 2] >> (16 * (j % 2))) & 0xFFFF
        if v:
            out[str(base + j)] = v - 1
    return out


def _unpack_assign(b: int, c: int, keys: int) -> dict:
    """Two packed assignment words -> {key: member or None}: 8-bit
    member+1 fields, four per word (keys 0..3 in b, 4..7 in c — the
    full group-mode key range)."""
    out = {}
    for k in range(keys):
        v = ((b, c)[k // 4] >> (8 * (k % 4))) & 0xFF
        out[k] = (v - 1) if v else None
    return out


@register
class KafkaProgram(NodeProgram):
    name = "kafka"
    is_edge = True
    needs_state_reads = True            # polls materialize replica rows
    # logs are append-only and replicas hole-free, and poll replies
    # carry their reply-round lengths — an end-of-stretch state read
    # sliced to those lengths is exact, so the collect-replies fast
    # path stays sound (same argument as txn_list_append)
    state_reads_final = True
    # entry offers repeat every round while a neighbor trails, and a
    # lost length-ack is re-covered by the beat heartbeat — so a
    # collision-overwritten lane message only ever delays
    tolerates_channel_overwrites = True

    def __init__(self, opts, nodes):
        super().__init__(opts, nodes)
        self.K = int(opts.get("key_count") or 4)
        # group mode lifts the legacy 3-word cap to 8 via banked commit
        # words; the classic full-prefix forms stay bound by the 3-word
        # replies (poll lengths / committed maps ride a|b|c)
        key_cap = (MAX_GROUP_KEYS if int(opts.get("kafka_groups") or 0)
                   else MAX_PACK_KEYS)
        if self.K > key_cap:
            raise ValueError(
                f"kafka supports at most {key_cap} keys on the wire for "
                f"this mode (got {self.K}); shard keys across runs")
        rate = float(opts.get("rate") or 0.0)
        tl = float(opts.get("time_limit") or 0.0)
        # cap+1 must fit a 15-bit packed length field ((len+1) << 16
        # stays positive in int32)
        self.cap = int(opts.get("log_cap",
                                min(max(64, int(rate * tl) + 32), 0x7FFE)))
        if self.cap > 0x7FFE:
            # (len+1) << 16 must stay positive in int32 for the packed
            # poll-length fields; an explicit override past that would
            # silently corrupt poll completions
            raise ValueError(
                f"kafka log_cap {self.cap} exceeds the 15-bit packed "
                f"length field (max {0x7FFE})")
        topo = TOPOLOGIES["total"](nodes)
        nb = topology_indices(topo, nodes)
        self.neighbors = jnp.asarray(nb)
        self.rev = jnp.asarray(reverse_index(nb))
        self.D = int(self.neighbors.shape[1])
        self.lanes = self.K                 # one replication lane per key
        from . import edge_capacity, edge_timing
        self.ring, _retry, _lat = edge_timing(opts, len(nodes))
        self.inbox_cap = int(opts.get("inbox_cap", 4))
        self.outbox_cap = self.inbox_cap
        spill, chan_lanes, uniform = edge_capacity(opts, self)
        if spill or chan_lanes != self.lanes:
            raise ValueError("kafka lanes are positional (one per key); "
                             "spill must be off")
        self._host_polled: dict = {}   # key -> max offset seen by polls
        self.beat_rounds = int(opts.get("beat_rounds", 64))
        # consumer-group streaming mode (doc/streams.md): G > 0 switches
        # the workload's polls to long-lived subscriptions with
        # cursor-based fetches; the coordinator row owns membership,
        # generations, and per-group committed offsets
        self.G = int(opts.get("kafka_groups") or 0)
        if self.G:
            if self.G > MAX_GROUPS:
                raise ValueError(f"kafka_groups {self.G} exceeds the "
                                 f"packed header width ({MAX_GROUPS})")
            # commit offsets ride two wire words = one 4-key bank; the
            # header's bank bit splits wider key spaces across
            # alternating per-bank commits (key_count <= 8)
            self._list_bank = 0     # glist bank rotation (host side)
            self.M = int(opts.get("concurrency") or len(nodes))
            if self.M > MAX_MEMBERS:
                raise ValueError(f"{self.M} workers exceed the member "
                                 f"field width ({MAX_MEMBERS})")
            ms_pr = float(opts.get("ms_per_round", 1.0))
            self.session_rounds = max(2, int(
                float(opts.get("session_timeout_ms", 2500.0)) / ms_pr))
            self.poll_batch = max(1, int(opts.get("poll_batch", 8)))
            # per-worker subscription sessions (host side of the
            # consumer protocol): generation, assigned keys, fetch
            # cursors, last-known committed floors, fetch round-robin
            self._subs: dict = {}
        self.edge_cfg = EdgeConfig(n_nodes=self.n_nodes, degree=self.D,
                                   lanes=self.lanes, ring=self.ring,
                                   uniform_arrival=uniform)

    def init_state(self):
        N, K, C = self.n_nodes, self.K, self.cap
        z = lambda *s: jnp.zeros(s, I32)  # noqa: E731
        s = {
            "log": z(N, K, C),           # interned msg per offset
            "log_len": z(N, K),
            "peer_len": z(N, self.D, K),  # neighbor's last advertised len
            "committed": jnp.full((N, K), -1, I32),   # node 0's row rules
            "log_overflow": z(N),
        }
        if self.G:
            G, M = self.G, self.M
            # group state: every node carries the arrays for shape
            # uniformity, but only the coordinator row ever changes or
            # is read (group RPCs are coordinator-routed); it is durable
            # like the logs (kafka persists __consumer_offsets)
            s["gactive"] = jnp.zeros((N, G, M), bool)
            s["gseen"] = z(N, G, M)       # last heartbeat round
            s["ggen"] = z(N, G)           # rebalance generation
            s["gcommitted"] = jnp.full((N, G, K), -1, I32)
        return s

    def invalid_counters(self, state):
        return {"log-overflow": state["log_overflow"]}

    def edge_step(self, state, edge_in: EdgeMsgs, client_in, ctx):
        N, K, C, D = self.n_nodes, self.K, self.cap, self.D
        s = dict(state)
        me = jnp.arange(N, dtype=I32)

        # ---------------- inbound replication (lane k = key k)
        rep_valid = edge_in.valid & (edge_in.type == T_REPL)  # [N, D, K]
        s["peer_len"] = jnp.where(rep_valid, edge_in.a, s["peer_len"])
        # accept the offered entry iff it lands exactly at my length
        # (in-order => hole-free replicas); several edges may offer the
        # same next entry — owners assign uniquely, so any accepted
        # duplicate writes the same value and a single pick suffices
        offer = rep_valid & (edge_in.b == s["log_len"][:, None, :]) \
            & (edge_in.b < edge_in.a) & (edge_in.b < C)
        any_offer = offer.any(axis=1)                         # [N, K]
        pick = jnp.argmax(offer, axis=1)                      # [N, K]
        val = jnp.take_along_axis(edge_in.c, pick[:, None, :],
                                  axis=1)[:, 0]               # [N, K]
        pos = jnp.where(any_offer, s["log_len"], C)   # C = dropped
        s["log"] = s["log"].at[
            me[:, None], jnp.arange(K, dtype=I32)[None, :], pos].set(
                val, mode="drop")
        s["log_len"] = s["log_len"] + any_offer.astype(I32)
        changed = any_offer                            # [N, K] len grew

        # ---------------- consumer-group maintenance (group mode)
        if self.G:
            # evict members whose heartbeat (commit/subscribe arrival at
            # the coordinator) is older than the session timeout: the
            # kill/pause nemesis parks a member's worker on RPC
            # timeouts, the coordinator notices the silence here, and
            # the generation bump fences the member's next commit —
            # membership change drives the rebalance
            expired = s["gactive"] & (
                (ctx["round"] - s["gseen"]) > self.session_rounds)
            s["gactive"] = s["gactive"] & ~expired
            s["ggen"] = s["ggen"] + expired.any(-1).astype(I32)

        # ---------------- client requests (inbox_cap is tiny: unrolled)
        A = client_in.valid.shape[1]
        outs = []
        is_leader0 = me == COORDINATOR
        for j in range(A):
            v = client_in.valid[:, j]
            t = client_in.type[:, j]
            aw, bw, cw = (client_in.a[:, j], client_in.b[:, j],
                          client_in.c[:, j])
            key = jnp.clip(client_in.a[:, j], 0, K - 1)
            owner = (key % N) == me
            # send: owner appends (offset = len before)
            is_send = v & (t == T_SEND)
            full = jnp.take_along_axis(s["log_len"], key[:, None],
                                       axis=1)[:, 0] >= C
            do_send = is_send & owner & ~full
            off = jnp.take_along_axis(s["log_len"], key[:, None],
                                      axis=1)[:, 0]
            s["log"] = s["log"].at[
                me, key, jnp.where(do_send, off, C)].set(
                    client_in.b[:, j], mode="drop")
            s["log_len"] = s["log_len"].at[me, key].add(
                do_send.astype(I32))
            changed = changed | (do_send[:, None]
                                 & (jnp.arange(K, dtype=I32)[None, :]
                                    == key[:, None]))
            s["log_overflow"] = s["log_overflow"] + (
                is_send & owner & full).astype(I32)
            # commit: node 0 maxes its committed row with the packed map
            # (legacy 3-word form: keys 0..5 only — group mode past 4
            # keys commits through the banked T_GCOMMIT instead)
            is_cmt = v & (t == T_COMMIT) & is_leader0
            for k in range(min(K, MAX_PACK_KEYS)):
                w = (client_in.a[:, j], client_in.b[:, j],
                     client_in.c[:, j])[k // 2]
                o = ((w >> (16 * (k % 2))) & 0xFFFF) - 1
                s["committed"] = s["committed"].at[:, k].max(
                    jnp.where(is_cmt, o, -1))
            is_list = v & (t == T_LIST) & is_leader0
            la, lb, lc = _device_pack(
                jnp.where(s["committed"] >= 0, s["committed"] + 1, 0))
            is_poll = v & (t == T_POLL)
            misrouted = v & (((t == T_SEND) & ~owner)
                             | (((t == T_COMMIT) | (t == T_LIST))
                                & ~is_leader0))
            send_full = is_send & owner & full
            # poll replies carry the per-key log lengths in the same
            # packed form as committed offsets: completions slice the
            # (append-only) log to the REPLY-round lengths, which makes
            # end-of-stretch state reads exact and lets the runner keep
            # the collect-replies fast path (state_reads_final)
            pa, pb, pc = _device_pack(s["log_len"] + 1)
            rtype = jnp.where(
                do_send, T_SEND_OK,
                jnp.where(is_cmt, T_COMMIT_OK,
                          jnp.where(is_list, T_LIST_OK,
                                    jnp.where(is_poll, T_POLL_OK,
                                              T_ERROR))))
            # commit replies echo the committed map (the history's
            # completion must carry it for the checker's rule 4);
            # errors: 11 = misrouted, 14 = log full (both definite)
            ra = jnp.where(
                do_send, off,
                jnp.where(is_cmt, client_in.a[:, j],
                          jnp.where(is_list, la,
                                    jnp.where(is_poll, pa,
                                              jnp.where(send_full, 14,
                                                        11)))))
            rb = jnp.where(is_cmt, client_in.b[:, j],
                           jnp.where(is_list, lb,
                                     jnp.where(is_poll, pb, 0)))
            rc = jnp.where(is_cmt, client_in.c[:, j],
                           jnp.where(is_list, lc,
                                     jnp.where(is_poll, pc, 0)))
            say = v & (do_send | is_cmt | is_list | is_poll | misrouted
                       | send_full)

            # ------------ consumer-group RPCs (group mode; overlaid on
            # the legacy chain — wire types are disjoint)
            if self.G:
                G, M = self.G, self.M
                is_sub = v & (t == T_SUB) & is_leader0
                is_fetch = v & (t == T_FETCH)
                is_gcmt = v & (t == T_GCOMMIT) & is_leader0
                is_glist = v & (t == T_GLIST) & is_leader0
                g_mis = v & ((t == T_SUB) | (t == T_GCOMMIT)
                             | (t == T_GLIST)) & ~is_leader0
                # header fields (sub/fetch pack group<<10|member in a;
                # gcommit packs bank<<30|group<<26|member<<16|gen16;
                # glist a = bank<<30|group). The bank bit names which
                # 4-key window b|c cover (keys 4*bank .. 4*bank+3).
                g_any = jnp.clip(
                    jnp.where(is_gcmt, (aw >> 26) & 0xF,
                              jnp.where(is_glist, aw & 0xFFFF, aw >> 10)),
                    0, G - 1)
                bank = jnp.where(is_gcmt | is_glist, (aw >> 30) & 1, 0)
                m_any = jnp.clip(
                    jnp.where(is_gcmt, (aw >> 16) & 0x3FF, aw & 1023),
                    0, M - 1)
                gen16 = aw & 0xFFFF
                # fencing is judged against the PRE-join state: a stale
                # generation or an evicted membership rejects the commit
                old_act = s["gactive"][me, g_any, m_any]
                old_gen = s["ggen"][me, g_any]
                fenced = is_gcmt & (((old_gen & 0xFFFF) != gen16)
                                    | ~old_act)
                ok_cmt = is_gcmt & ~fenced
                # membership: subscribe always joins; a fenced commit
                # REJOINS (kafka's fenced-consumer-must-rejoin), so the
                # kill->silence->evict->return loop self-heals without
                # extra ops. Generation bumps only on actual change.
                join = is_sub | fenced
                newly = join & ~old_act
                s["gactive"] = s["gactive"].at[me, g_any, m_any].set(
                    old_act | join, unique_indices=True)
                beats = is_sub | is_gcmt
                s["gseen"] = s["gseen"].at[me, g_any, m_any].set(
                    jnp.where(beats, ctx["round"],
                              s["gseen"][me, g_any, m_any]),
                    unique_indices=True)
                s["ggen"] = s["ggen"].at[me, g_any].add(
                    newly.astype(I32), unique_indices=True)
                new_gen = s["ggen"][me, g_any]
                # post-join assignment for THIS slot's group row only
                asg_g = self._assign_members(
                    s["gactive"][me, g_any])               # [N, K]
                asg_b, asg_c = self._pack_assign(asg_g)
                # non-fenced commit: advance the group's committed marks
                # for the member's OWN assigned keys only (per-key
                # fencing), within the bank the header names; the stored
                # mark is monotone by construction
                for k in range(K):
                    kb, kj = divmod(k, BANK_KEYS)
                    w = bw if kj < 2 else cw
                    o = ((w >> (16 * (kj % 2))) & 0xFFFF) - 1
                    mine = ok_cmt & (asg_g[:, k] == m_any) & (bank == kb)
                    s["gcommitted"] = s["gcommitted"].at[
                        me, g_any, k].max(jnp.where(mine, o, -1),
                                          unique_indices=True)
                # glist reply words: the requested bank's 4-key window
                # of the group's committed floors
                gplus = jnp.where(s["gcommitted"][me, g_any] >= 0,
                                  s["gcommitted"][me, g_any] + 1, 0)

                def _bank_words(base):
                    wb = jnp.zeros((N,), I32)
                    wc = jnp.zeros((N,), I32)
                    for kj in range(min(BANK_KEYS, K - base)):
                        f = gplus[:, base + kj] << (16 * (kj % 2))
                        if kj < 2:
                            wb = wb | f
                        else:
                            wc = wc | f
                    return wb, wc
                glb, glc = _bank_words(0)
                if K > BANK_KEYS:
                    wb1, wc1 = _bank_words(BANK_KEYS)
                    glb = jnp.where(bank == 1, wb1, glb)
                    glc = jnp.where(bank == 1, wc1, glc)
                # cursor fetch, served from ANY replica: b = key<<16 |
                # (start+1); n entries exist at reply-round length, the
                # host slices the append-only log (state_reads_final)
                fk = jnp.clip(bw >> 16, 0, K - 1)
                fcur = (bw & 0xFFFF) - 1
                flen = s["log_len"][me, fk]
                fn = jnp.where(fcur >= 0,
                               jnp.clip(flen - fcur, 0,
                                        jnp.clip(cw, 0, 0x7FFF)), 0)
                rtype = jnp.where(
                    is_fetch, T_FETCH_OK,
                    jnp.where(is_sub, T_SUB_OK,
                              jnp.where(fenced, T_REBAL,
                                        jnp.where(ok_cmt, T_GCOMMIT_OK,
                                                  jnp.where(is_glist,
                                                            T_GLIST_OK,
                                                            rtype)))))
                # commit/list replies echo the bank in bit 30 so the
                # decode labels the offsets with their true keys
                # (bank 0 leaves the word bit-identical to the pre-bank
                # wire format)
                gen_banked = (new_gen & 0x3FFFFFFF) | (bank << 30)
                ra = jnp.where(is_fetch, (fk << 16) | (fcur + 1),
                               jnp.where(ok_cmt | is_glist, gen_banked,
                                         jnp.where(is_sub | fenced,
                                                   new_gen, ra)))
                rb = jnp.where(is_fetch, fn,
                               jnp.where(is_sub | fenced, asg_b,
                                         jnp.where(ok_cmt, bw,
                                                   jnp.where(is_glist,
                                                             glb,
                                                             rb))))
                rc = jnp.where(is_sub | fenced, asg_c,
                               jnp.where(ok_cmt, cw,
                                         jnp.where(is_glist, glc,
                                                   jnp.where(is_fetch,
                                                             0, rc))))
                say = say | is_fetch | is_sub | fenced | ok_cmt \
                    | is_glist | g_mis
            outs.append((say, client_in.src[:, j], rtype, ra, rb, rc,
                         client_in.mid[:, j]))

        out_valid = jnp.stack([o[0] for o in outs], axis=1)
        client_out = client_in.replace(
            valid=out_valid,
            dest=jnp.stack([o[1] for o in outs], axis=1),
            type=jnp.stack([o[2] for o in outs], axis=1),
            a=jnp.stack([o[3] for o in outs], axis=1),
            b=jnp.stack([o[4] for o in outs], axis=1),
            c=jnp.stack([o[5] for o in outs], axis=1),
            reply_to=jnp.stack([o[6] for o in outs], axis=1),
            src=jnp.broadcast_to(me[:, None], (N, A)))

        # ---------------- outbound replication: offer each neighbor,
        # per key, the entry at the offset it last advertised as its len
        want = s["peer_len"]                                   # [N, D, K]
        have = s["log_len"][:, None, :]
        posT = jnp.clip(want, 0, C - 1).transpose(0, 2, 1)     # [N, K, D]
        entry = jnp.take_along_axis(s["log"], posT,
                                    axis=2).transpose(0, 2, 1)  # [N,D,K]
        # a lane fires when it has an entry to offer (every round while
        # the neighbor trails — the loss-tolerant re-offer), when this
        # node's length CHANGED this round (the ack: an accepted entry
        # advertises the new length immediately, so catch-up pipelines
        # at ~1 entry per 2 rounds instead of 1 per beat), or on the
        # low-cadence beat (default 64 rounds = 64 ms — the anti-
        # entropy timer that bounds recovery when an ack is lost).
        # Always-on lanes cost ~2,400 server msgs-per-op at interactive
        # rates for zero information.
        beat = (ctx["round"] % self.beat_rounds) == 0
        edge_out = EdgeMsgs(
            valid=(((want < have) | beat | changed[:, None, :])
                   & (self.neighbors >= 0)[:, :, None]),
            type=jnp.full((N, D, K), T_REPL, I32),
            a=jnp.broadcast_to(have, (N, D, K)),
            b=want,
            c=jnp.where(want < have, entry, 0))

        return s, edge_out, client_out

    def quiescent(self, state):
        # conservative: the beat timer ticks forever
        return jnp.array(False)

    # --- consumer-group device helpers (group mode) ---

    def _assign_members(self, gactive):
        """[..., M] active-member mask -> [..., K] i32 member-per-key
        assignment (-1 = unassigned): key k goes to the member of rank
        (k mod count) in member-id order — the deterministic round-robin
        every correct implementation (device and host) agrees on.
        (edge_step calls this on ONE group's [N, M] row per inbox slot;
        building the full [N, G, K, M] tensor per slot was pure waste —
        membership is per-slot state, so XLA cannot CSE the copies.)"""
        K = self.K
        cnt = gactive.sum(-1).astype(I32)                    # [...]
        rank = jnp.cumsum(gactive.astype(I32), axis=-1) - 1  # [..., M]
        ks = jnp.arange(K, dtype=I32)
        shape = (1,) * (gactive.ndim - 1) + (K,)
        want = ks.reshape(shape) % jnp.maximum(cnt[..., None], 1)
        hit = gactive[..., None, :] & (rank[..., None, :]
                                       == want[..., :, None])  # [...,K,M]
        mem = jnp.argmax(hit, axis=-1).astype(I32)
        return jnp.where(hit.any(-1), mem, -1)

    def _pack_assign(self, asg):
        """[N, K] member-per-key -> two packed wire words (8-bit
        member+1 fields, four per word; the device half of
        `_unpack_assign`)."""
        b = jnp.zeros(asg.shape[0], I32)
        c = jnp.zeros_like(b)
        for k in range(self.K):
            f = jnp.where(asg[:, k] >= 0, asg[:, k] + 1, 0)
            if k < 4:
                b = b | (f << (8 * k))
            else:
                c = c | (f << (8 * (k - 4)))
        return b, c

    # --- host boundary ---

    def owner_of(self, key: int) -> int:
        """The single source of truth for key ownership — edge_step's
        on-device owner mask and the host-side routing must agree.
        Only defined for in-range keys (encode_body rejects the rest)."""
        return int(key) % self.n_nodes

    def node_for_op(self, op):
        # smart-client routing (like real kafka clients): sends go to
        # the key's owner, commit/list to the coordinator; polls are
        # served by any replica (the worker's bound node — which is
        # what makes polls observe replication, not just the owner).
        # Out-of-range keys aren't routed: encode_body fails them
        # definitely before they reach any node.
        if op["f"] == "send":
            k = int(op["value"][0])
            return self.owner_of(k) if 0 <= k < self.K else None
        if self.G:
            if op["f"] in ("subscribe", "commit", "list"):
                return COORDINATOR
            if op["f"] == "poll":
                # an unsubscribed (or unassigned) worker's poll turns
                # into a subscribe — coordinator-routed; real fetches go
                # to the worker's bound replica
                sub = self._subs.get(int(op["process"]))
                if sub is None or not sub["keys"]:
                    return COORDINATOR
                return None
            return None
        if op["f"] in ("commit", "list"):
            return COORDINATOR
        return None

    def _group_request(self, op):
        """The host half of a consumer session (doc/streams.md): each
        worker is one group member; polls round-robin cursor fetches
        over its assigned keys, commits claim exactly its cursors, and
        anything without a live subscription becomes a subscribe."""
        member = int(op["process"])
        g = member % self.G
        sub = self._subs.get(member)
        f = op["f"]
        if f == "subscribe" or (f in ("poll", "commit") and sub is None) \
                or (f == "poll" and not sub["keys"]):
            return {"type": "subscribe", "group": g, "member": member}
        if f == "poll":
            keys = sub["keys"]
            k = keys[sub["rr"] % len(keys)]
            sub["rr"] += 1
            return {"type": "fetch", "group": g, "member": member,
                    "key": k, "cursor": int(sub["cursors"].get(k, 0)),
                    "batch": self.poll_batch}
        if f == "commit":
            # claim = everything this member consumed on its OWN keys;
            # an empty claim still round-trips (it is the heartbeat).
            # The wire carries one 4-key bank per commit: successive
            # commits rotate over the banks that hold claims, so every
            # key's floor still advances (at half the per-key cadence
            # past 4 keys) and the heartbeat cadence is unchanged.
            offs_all = {k: sub["cursors"][k] - 1 for k in sub["keys"]
                        if sub["cursors"].get(k, 0) > 0}
            banks = sorted({k // BANK_KEYS for k in offs_all}) or [0]
            cb = int(sub.get("cb", 0))
            sub["cb"] = cb + 1
            bank = banks[cb % len(banks)]
            offs = {k: v for k, v in offs_all.items()
                    if k // BANK_KEYS == bank}
            return {"type": "commit_group", "group": g,
                    "member": member, "gen": int(sub["gen"]),
                    "bank": bank, "offsets": offs}
        bank = 0
        if self.K > BANK_KEYS:
            # lists rotate banks too: floors past key 3 stay observable
            bank = self._list_bank % ((self.K + BANK_KEYS - 1)
                                      // BANK_KEYS)
            self._list_bank += 1
        return {"type": "list_group", "group": g, "bank": bank}

    def request_for_op(self, op):
        f = op["f"]
        if f == "send":
            k, m = op["value"]
            return {"type": "send", "key": int(k), "msg": m}
        if self.G:
            return self._group_request(op)
        if f == "poll":
            return {"type": "poll"}
        if f == "commit":
            # the TPU path drives ops through the program, not the
            # workload's stateful client, so the program tracks what
            # has been polled (host-side bookkeeping: the max offset
            # any completed poll observed per key — a legal commit
            # claim, deterministic given the history)
            offs = op.get("value") or dict(self._host_polled)
            return {"type": "commit_offsets", "offsets": offs}
        return {"type": "list_committed_offsets"}

    def encode_body(self, body, intern):
        t = body["type"]
        if t == "send":
            if not 0 <= int(body["key"]) < self.K:
                # the device clips keys into range, which would silently
                # append to the WRONG log; fail the op definitely instead
                raise EncodeCapacityError(
                    f"kafka key {body['key']} outside configured "
                    f"key_count {self.K}")
            return (T_SEND, int(body["key"]), intern.id(body["msg"]), 0)
        if t == "poll":
            return (T_POLL, 0, 0, 0)
        if t == "commit_offsets":
            a, b, c = _pack_offsets(body["offsets"],
                                    min(self.K, MAX_PACK_KEYS))
            return (T_COMMIT, a, b, c)
        if t == "subscribe":
            return (T_SUB,
                    (int(body["group"]) << 10) | int(body["member"]),
                    0, 0)
        if t == "fetch":
            cur = int(body["cursor"])
            if cur > 0x7FFE:
                raise EncodeCapacityError(
                    f"kafka fetch cursor {cur} exceeds the 15-bit wire "
                    f"field")
            return (T_FETCH,
                    (int(body["group"]) << 10) | int(body["member"]),
                    (int(body["key"]) << 16) | (cur + 1),
                    int(body["batch"]))
        if t == "commit_group":
            bank = int(body.get("bank", 0))
            w = _pack_offsets(body["offsets"],
                              min(BANK_KEYS, self.K - BANK_KEYS * bank),
                              base=BANK_KEYS * bank)
            return (T_GCOMMIT,
                    (bank << 30)
                    | (int(body["group"]) << 26)
                    | (int(body["member"]) << 16)
                    | (int(body["gen"]) & 0xFFFF), w[0], w[1])
        if t == "list_group":
            return (T_GLIST,
                    (int(body.get("bank", 0)) << 30)
                    | int(body["group"]), 0, 0)
        return (T_LIST, 0, 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_SEND_OK:
            return {"type": "send_ok", "offset": int(a)}
        if t == T_COMMIT_OK:
            return {"type": "commit_offsets_ok",
                    "offsets": _unpack_offsets(int(a), int(b), int(c),
                                               min(self.K,
                                                   MAX_PACK_KEYS))}
        if t == T_LIST_OK:
            return {"type": "list_committed_offsets_ok",
                    "offsets": _unpack_offsets(int(a), int(b), int(c),
                                               min(self.K,
                                                   MAX_PACK_KEYS))}
        if t == T_POLL_OK:
            return {"type": "poll_ok",
                    "lens": _unpack_offsets(int(a), int(b), int(c),
                                            min(self.K, MAX_PACK_KEYS))}
        if t == T_SUB_OK:
            return {"type": "subscribe_ok", "gen": int(a),
                    "assign": _unpack_assign(int(b), int(c), self.K)}
        if t == T_FETCH_OK:
            return {"type": "fetch_ok", "key": int(a) >> 16,
                    "start": (int(a) & 0xFFFF) - 1, "n": int(b)}
        if t == T_GCOMMIT_OK:
            bank = (int(a) >> 30) & 1
            return {"type": "commit_group_ok",
                    "gen": int(a) & 0x3FFFFFFF,
                    "offsets": _unpack_offsets(
                        int(b), int(c), 0,
                        min(BANK_KEYS, self.K - BANK_KEYS * bank),
                        base=BANK_KEYS * bank)}
        if t == T_REBAL:
            return {"type": "rebalance", "gen": int(a),
                    "assign": _unpack_assign(int(b), int(c), self.K)}
        if t == T_GLIST_OK:
            bank = (int(a) >> 30) & 1
            return {"type": "list_group_ok",
                    "gen": int(a) & 0x3FFFFFFF, "bank": bank,
                    "offsets": _unpack_offsets(
                        int(b), int(c), 0,
                        min(BANK_KEYS, self.K - BANK_KEYS * bank),
                        base=BANK_KEYS * bank)}
        if t == T_ERROR:
            return {"type": "error", "code": int(a),
                    "text": ("log full" if int(a) == 14 else
                             "misrouted (owner/coordinator elsewhere)")}
        return super().decode_body(t, a, b, c, intern)

    def _apply_assignment(self, op, body):
        """Folds a subscribe_ok/rebalance reply into the worker's
        session: generation, assigned keys, and fetch cursors for newly
        assigned keys, which resume from the group's committed floor as
        far as this member knows it (at-least-once — re-reads across a
        rebalance are consumer-group semantics, not anomalies)."""
        member = int(op["process"])
        keys = sorted(k for k, m2 in body["assign"].items()
                      if m2 == member)
        sub = self._subs.setdefault(
            member, {"cursors": {}, "known_commit": {}, "rr": 0})
        old = set(sub.get("keys") or ())
        sub["group"] = member % self.G
        sub["gen"] = int(body["gen"])
        sub["keys"] = keys
        for k in keys:
            if k not in old or k not in sub["cursors"]:
                sub["cursors"][k] = sub["known_commit"].get(k, -1) + 1
        return keys

    def host_state(self):
        # both modes keep host-side session state the history depends
        # on: resumed runs must replay it (tpu_runner checkpoints this)
        st = {"polled": dict(self._host_polled)}
        if self.G:
            st["subs"] = {m: {**s, "cursors": dict(s["cursors"]),
                              "known_commit": dict(s["known_commit"]),
                              "keys": list(s.get("keys") or ())}
                          for m, s in self._subs.items()}
            st["lb"] = self._list_bank
        return st

    def set_host_state(self, st):
        if not st:
            return
        self._host_polled = dict(st.get("polled") or {})
        if self.G:
            self._subs = {m: dict(s)
                          for m, s in (st.get("subs") or {}).items()}
            self._list_bank = int(st.get("lb", 0))

    def _learn_commits(self, member: int, offsets: dict):
        sub = self._subs.get(member)
        if sub is not None:
            for k, o in offsets.items():
                ik = int(k)
                sub["known_commit"][ik] = max(
                    sub["known_commit"].get(ik, -1), int(o))

    def completion(self, op, body, read_state, intern):
        import numpy as np
        if body["type"] == "subscribe_ok":
            keys = self._apply_assignment(op, body)
            if op["f"] == "subscribe":
                return {**op, "type": "ok",
                        "value": {"gen": body["gen"], "assigned": keys}}
            # auto-subscribe on behalf of a poll/commit: the op itself
            # consumed/claimed nothing (an empty observation)
            return {**op, "type": "ok", "value": {}}
        if body["type"] == "rebalance":
            # fenced commit: it definitely did NOT apply; the reply
            # carries the new generation + assignment, so the session
            # rejoins and the next ops run in the new generation
            self._apply_assignment(op, body)
            return {**op, "type": "fail",
                    "error": ["rebalanced", int(body["gen"])]}
        if body["type"] == "fetch_ok":
            member = int(op["process"])
            k, start, n = body["key"], max(int(body["start"]), 0), \
                int(body["n"])
            pairs = []
            if n:
                # reply-round entry count over the append-only log: the
                # end-of-stretch state read is exact (state_reads_final)
                row = read_state()
                log = np.asarray(row["log"])
                pairs = [[o, intern.value(int(log[k, o]))]
                         for o in range(start, start + n)]
                sub = self._subs.get(member)
                if sub is not None:
                    sub["cursors"][k] = max(
                        int(sub["cursors"].get(k, 0)), start + n)
            return {**op, "type": "ok", "value": {str(k): pairs}}
        if body["type"] == "commit_group_ok":
            member = int(op["process"])
            offs = {str(k): int(v)
                    for k, v in body.get("offsets", {}).items()}
            self._learn_commits(member, offs)
            return {**op, "type": "ok",
                    "value": {"group": member % self.G,
                              "offsets": offs}}
        if body["type"] == "list_group_ok":
            member = int(op["process"])
            offs = {str(k): int(v)
                    for k, v in body.get("offsets", {}).items()}
            self._learn_commits(member, offs)
            value = {"group": member % self.G, "offsets": offs}
            if self.K > BANK_KEYS:
                # banked lists are PARTIAL observations: declare which
                # keys this reply covers so the checker's floor rule
                # audits exactly the observed bank (an absent key
                # outside the bank is unobserved, not a regression)
                bank = int(body.get("bank", 0))
                value["keys"] = [
                    str(k) for k in range(BANK_KEYS * bank,
                                          min(BANK_KEYS * (bank + 1),
                                              self.K))]
            return {**op, "type": "ok", "value": value}
        if body["type"] == "send_ok":
            k, m = op["value"]
            return {**op, "type": "ok",
                    "value": [str(k), m, body["offset"]]}
        if body["type"] == "poll_ok":
            # the reply words carry the REPLY-round per-key lengths;
            # slicing the final (append-only) log to them reconstructs
            # the exact replica prefix of the reply round, which is
            # what makes end-of-stretch state reads sound here
            row = read_state()
            log = np.asarray(row["log"])
            reply_lens = body.get("lens", {})
            msgs = {}
            for k in range(self.K):
                n = int(reply_lens.get(str(k), 0))
                if n:
                    msgs[str(k)] = [[o, intern.value(int(log[k, o]))]
                                    for o in range(n)]
                    self._host_polled[str(k)] = max(
                        self._host_polled.get(str(k), -1), n - 1)
            return {**op, "type": "ok", "value": msgs}
        if body["type"] == "commit_offsets_ok":
            return {**op, "type": "ok", "value": body.get("offsets", {})}
        if body["type"] == "list_committed_offsets_ok":
            return {**op, "type": "ok", "value": body["offsets"]}
        return {**op, "type": "ok"}
