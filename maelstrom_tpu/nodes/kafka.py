"""Batched kafka-style replicated log (serving `workloads/kafka.py`;
the TPU-native counterpart of `demo/python/kafka.py`).

Design — ownership for assignment, anti-entropy for reads:

  - key k is OWNED by node k % N: only the owner appends (exclusive
    offset assignment with no coordination — the CAS loop of the demo
    becomes a plain array append, because ownership already serializes)
    and a send arriving elsewhere fails definitely with error 11, which
    the workload records as a clean :fail and retries elsewhere;
  - every node REPLICATES every log over one edge lane per key carrying
    (my_len, offset_being_sent, msg): a node offers the entry at the
    offset its neighbor last advertised — re-offered EVERY round while
    the neighbor trails, so entry loss/overwrite only delays — and a
    node appends an incoming entry only when it lands exactly at its
    own length (in-order, idempotent, hole-free: the full-prefix
    contract the kafka checker's lost-write rule leans on). Length
    advertisements (the ack channel) are event-driven — a node whose
    length changed advertises next round — plus a `beat_rounds`
    heartbeat that bounds recovery when an ack itself is lost;
  - polls are served from ANY node's replica, materialized host-side
    from the node's state row at completion time (needs_state_reads);
  - committed offsets live on node 0 (the coordinator): commit/list
    elsewhere fail definitely with error 11. Commit maps pack into the
    three wire words (up to 6 keys x 15-bit offsets, checked at
    encode), so the marks advance on-device with a max — monotone by
    construction, and rule 4's real-time obligation holds because every
    observation serializes through the one coordinator row."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..net.static import EdgeConfig, EdgeMsgs, reverse_index
from ..net.tpu import I32
from ..workloads.broadcast import TOPOLOGIES, topology_indices
from . import EncodeCapacityError, NodeProgram, T_ERROR, register

T_SEND = 10        # a = key, b = interned msg
T_SEND_OK = 11     # a = offset
T_POLL = 12
T_POLL_OK = 13     # payload materialized host-side (needs_state_reads)
T_COMMIT = 14      # a|b|c = packed per-key offsets (+1, 16 bits each)
T_COMMIT_OK = 15
T_LIST = 16
T_LIST_OK = 17     # a|b|c = packed committed offsets (+1)
# T_ERROR (= 1) comes from the shared reply vocabulary in nodes/__init__
T_REPL = 20        # edge lane k: a = sender len, b = offset, c = msg

MAX_PACK_KEYS = 6  # 2 x 16-bit fields per wire word, 3 words
COORDINATOR = 0    # node holding the authoritative committed-offset row


def _pack_offsets(offs: dict, keys: int) -> tuple[int, int, int]:
    words = [0, 0, 0]
    for k in range(keys):
        o = offs.get(str(k), offs.get(k))
        if o is None:
            continue
        if o >= 0x7FFF:
            raise EncodeCapacityError(
                f"kafka committed offset {o} exceeds the 15-bit wire "
                f"field")
        words[k // 2] |= (int(o) + 1) << (16 * (k % 2))
    return words[0], words[1], words[2]


def _device_pack(vals_plus1):
    """[N, K] int32 (0 = absent, v+1 otherwise) -> three packed wire
    words, the device half of _pack_offsets' convention (16-bit fields,
    2 per word)."""
    words = [jnp.zeros((vals_plus1.shape[0],), I32) for _ in range(3)]
    for k in range(vals_plus1.shape[1]):
        words[k // 2] = words[k // 2] | (vals_plus1[:, k]
                                         << (16 * (k % 2)))
    return words


def _unpack_offsets(a: int, b: int, c: int, keys: int) -> dict:
    out = {}
    for k in range(keys):
        v = ((a, b, c)[k // 2] >> (16 * (k % 2))) & 0xFFFF
        if v:
            out[str(k)] = v - 1
    return out


@register
class KafkaProgram(NodeProgram):
    name = "kafka"
    is_edge = True
    needs_state_reads = True            # polls materialize replica rows
    # logs are append-only and replicas hole-free, and poll replies
    # carry their reply-round lengths — an end-of-stretch state read
    # sliced to those lengths is exact, so the collect-replies fast
    # path stays sound (same argument as txn_list_append)
    state_reads_final = True
    # entry offers repeat every round while a neighbor trails, and a
    # lost length-ack is re-covered by the beat heartbeat — so a
    # collision-overwritten lane message only ever delays
    tolerates_channel_overwrites = True

    def __init__(self, opts, nodes):
        super().__init__(opts, nodes)
        self.K = int(opts.get("key_count") or 4)
        if self.K > MAX_PACK_KEYS:
            raise ValueError(
                f"kafka supports at most {MAX_PACK_KEYS} keys on the "
                f"wire (got {self.K}); raise MAX_PACK_KEYS or shard")
        rate = float(opts.get("rate") or 0.0)
        tl = float(opts.get("time_limit") or 0.0)
        # cap+1 must fit a 15-bit packed length field ((len+1) << 16
        # stays positive in int32)
        self.cap = int(opts.get("log_cap",
                                min(max(64, int(rate * tl) + 32), 0x7FFE)))
        if self.cap > 0x7FFE:
            # (len+1) << 16 must stay positive in int32 for the packed
            # poll-length fields; an explicit override past that would
            # silently corrupt poll completions
            raise ValueError(
                f"kafka log_cap {self.cap} exceeds the 15-bit packed "
                f"length field (max {0x7FFE})")
        topo = TOPOLOGIES["total"](nodes)
        nb = topology_indices(topo, nodes)
        self.neighbors = jnp.asarray(nb)
        self.rev = jnp.asarray(reverse_index(nb))
        self.D = int(self.neighbors.shape[1])
        self.lanes = self.K                 # one replication lane per key
        from . import edge_capacity, edge_timing
        self.ring, _retry, _lat = edge_timing(opts, len(nodes))
        self.inbox_cap = int(opts.get("inbox_cap", 4))
        self.outbox_cap = self.inbox_cap
        spill, chan_lanes, uniform = edge_capacity(opts, self)
        if spill or chan_lanes != self.lanes:
            raise ValueError("kafka lanes are positional (one per key); "
                             "spill must be off")
        self._host_polled: dict = {}   # key -> max offset seen by polls
        self.beat_rounds = int(opts.get("beat_rounds", 64))
        self.edge_cfg = EdgeConfig(n_nodes=self.n_nodes, degree=self.D,
                                   lanes=self.lanes, ring=self.ring,
                                   uniform_arrival=uniform)

    def init_state(self):
        N, K, C = self.n_nodes, self.K, self.cap
        z = lambda *s: jnp.zeros(s, I32)  # noqa: E731
        return {
            "log": z(N, K, C),           # interned msg per offset
            "log_len": z(N, K),
            "peer_len": z(N, self.D, K),  # neighbor's last advertised len
            "committed": jnp.full((N, K), -1, I32),   # node 0's row rules
            "log_overflow": z(N),
        }

    def invalid_counters(self, state):
        return {"log-overflow": state["log_overflow"]}

    def edge_step(self, state, edge_in: EdgeMsgs, client_in, ctx):
        N, K, C, D = self.n_nodes, self.K, self.cap, self.D
        s = dict(state)
        me = jnp.arange(N, dtype=I32)

        # ---------------- inbound replication (lane k = key k)
        rep_valid = edge_in.valid & (edge_in.type == T_REPL)  # [N, D, K]
        s["peer_len"] = jnp.where(rep_valid, edge_in.a, s["peer_len"])
        # accept the offered entry iff it lands exactly at my length
        # (in-order => hole-free replicas); several edges may offer the
        # same next entry — owners assign uniquely, so any accepted
        # duplicate writes the same value and a single pick suffices
        offer = rep_valid & (edge_in.b == s["log_len"][:, None, :]) \
            & (edge_in.b < edge_in.a) & (edge_in.b < C)
        any_offer = offer.any(axis=1)                         # [N, K]
        pick = jnp.argmax(offer, axis=1)                      # [N, K]
        val = jnp.take_along_axis(edge_in.c, pick[:, None, :],
                                  axis=1)[:, 0]               # [N, K]
        pos = jnp.where(any_offer, s["log_len"], C)   # C = dropped
        s["log"] = s["log"].at[
            me[:, None], jnp.arange(K, dtype=I32)[None, :], pos].set(
                val, mode="drop")
        s["log_len"] = s["log_len"] + any_offer.astype(I32)
        changed = any_offer                            # [N, K] len grew

        # ---------------- client requests (inbox_cap is tiny: unrolled)
        A = client_in.valid.shape[1]
        outs = []
        is_leader0 = me == COORDINATOR
        for j in range(A):
            v = client_in.valid[:, j]
            t = client_in.type[:, j]
            key = jnp.clip(client_in.a[:, j], 0, K - 1)
            owner = (key % N) == me
            # send: owner appends (offset = len before)
            is_send = v & (t == T_SEND)
            full = jnp.take_along_axis(s["log_len"], key[:, None],
                                       axis=1)[:, 0] >= C
            do_send = is_send & owner & ~full
            off = jnp.take_along_axis(s["log_len"], key[:, None],
                                      axis=1)[:, 0]
            s["log"] = s["log"].at[
                me, key, jnp.where(do_send, off, C)].set(
                    client_in.b[:, j], mode="drop")
            s["log_len"] = s["log_len"].at[me, key].add(
                do_send.astype(I32))
            changed = changed | (do_send[:, None]
                                 & (jnp.arange(K, dtype=I32)[None, :]
                                    == key[:, None]))
            s["log_overflow"] = s["log_overflow"] + (
                is_send & owner & full).astype(I32)
            # commit: node 0 maxes its committed row with the packed map
            is_cmt = v & (t == T_COMMIT) & is_leader0
            for k in range(K):
                w = (client_in.a[:, j], client_in.b[:, j],
                     client_in.c[:, j])[k // 2]
                o = ((w >> (16 * (k % 2))) & 0xFFFF) - 1
                s["committed"] = s["committed"].at[:, k].max(
                    jnp.where(is_cmt, o, -1))
            is_list = v & (t == T_LIST) & is_leader0
            la, lb, lc = _device_pack(
                jnp.where(s["committed"] >= 0, s["committed"] + 1, 0))
            is_poll = v & (t == T_POLL)
            misrouted = v & (((t == T_SEND) & ~owner)
                             | (((t == T_COMMIT) | (t == T_LIST))
                                & ~is_leader0))
            send_full = is_send & owner & full
            # poll replies carry the per-key log lengths in the same
            # packed form as committed offsets: completions slice the
            # (append-only) log to the REPLY-round lengths, which makes
            # end-of-stretch state reads exact and lets the runner keep
            # the collect-replies fast path (state_reads_final)
            pa, pb, pc = _device_pack(s["log_len"] + 1)
            rtype = jnp.where(
                do_send, T_SEND_OK,
                jnp.where(is_cmt, T_COMMIT_OK,
                          jnp.where(is_list, T_LIST_OK,
                                    jnp.where(is_poll, T_POLL_OK,
                                              T_ERROR))))
            # commit replies echo the committed map (the history's
            # completion must carry it for the checker's rule 4);
            # errors: 11 = misrouted, 14 = log full (both definite)
            ra = jnp.where(
                do_send, off,
                jnp.where(is_cmt, client_in.a[:, j],
                          jnp.where(is_list, la,
                                    jnp.where(is_poll, pa,
                                              jnp.where(send_full, 14,
                                                        11)))))
            rb = jnp.where(is_cmt, client_in.b[:, j],
                           jnp.where(is_list, lb,
                                     jnp.where(is_poll, pb, 0)))
            rc = jnp.where(is_cmt, client_in.c[:, j],
                           jnp.where(is_list, lc,
                                     jnp.where(is_poll, pc, 0)))
            say = v & (do_send | is_cmt | is_list | is_poll | misrouted
                       | send_full)
            outs.append((say, client_in.src[:, j], rtype, ra, rb, rc,
                         client_in.mid[:, j]))

        out_valid = jnp.stack([o[0] for o in outs], axis=1)
        client_out = client_in.replace(
            valid=out_valid,
            dest=jnp.stack([o[1] for o in outs], axis=1),
            type=jnp.stack([o[2] for o in outs], axis=1),
            a=jnp.stack([o[3] for o in outs], axis=1),
            b=jnp.stack([o[4] for o in outs], axis=1),
            c=jnp.stack([o[5] for o in outs], axis=1),
            reply_to=jnp.stack([o[6] for o in outs], axis=1),
            src=jnp.broadcast_to(me[:, None], (N, A)))

        # ---------------- outbound replication: offer each neighbor,
        # per key, the entry at the offset it last advertised as its len
        want = s["peer_len"]                                   # [N, D, K]
        have = s["log_len"][:, None, :]
        posT = jnp.clip(want, 0, C - 1).transpose(0, 2, 1)     # [N, K, D]
        entry = jnp.take_along_axis(s["log"], posT,
                                    axis=2).transpose(0, 2, 1)  # [N,D,K]
        # a lane fires when it has an entry to offer (every round while
        # the neighbor trails — the loss-tolerant re-offer), when this
        # node's length CHANGED this round (the ack: an accepted entry
        # advertises the new length immediately, so catch-up pipelines
        # at ~1 entry per 2 rounds instead of 1 per beat), or on the
        # low-cadence beat (default 64 rounds = 64 ms — the anti-
        # entropy timer that bounds recovery when an ack is lost).
        # Always-on lanes cost ~2,400 server msgs-per-op at interactive
        # rates for zero information.
        beat = (ctx["round"] % self.beat_rounds) == 0
        edge_out = EdgeMsgs(
            valid=(((want < have) | beat | changed[:, None, :])
                   & (self.neighbors >= 0)[:, :, None]),
            type=jnp.full((N, D, K), T_REPL, I32),
            a=jnp.broadcast_to(have, (N, D, K)),
            b=want,
            c=jnp.where(want < have, entry, 0))

        return s, edge_out, client_out

    def quiescent(self, state):
        # conservative: the beat timer ticks forever
        return jnp.array(False)

    # --- host boundary ---

    def owner_of(self, key: int) -> int:
        """The single source of truth for key ownership — edge_step's
        on-device owner mask and the host-side routing must agree.
        Only defined for in-range keys (encode_body rejects the rest)."""
        return int(key) % self.n_nodes

    def node_for_op(self, op):
        # smart-client routing (like real kafka clients): sends go to
        # the key's owner, commit/list to the coordinator; polls are
        # served by any replica (the worker's bound node — which is
        # what makes polls observe replication, not just the owner).
        # Out-of-range keys aren't routed: encode_body fails them
        # definitely before they reach any node.
        if op["f"] == "send":
            k = int(op["value"][0])
            return self.owner_of(k) if 0 <= k < self.K else None
        if op["f"] in ("commit", "list"):
            return COORDINATOR
        return None

    def request_for_op(self, op):
        f = op["f"]
        if f == "send":
            k, m = op["value"]
            return {"type": "send", "key": int(k), "msg": m}
        if f == "poll":
            return {"type": "poll"}
        if f == "commit":
            # the TPU path drives ops through the program, not the
            # workload's stateful client, so the program tracks what
            # has been polled (host-side bookkeeping: the max offset
            # any completed poll observed per key — a legal commit
            # claim, deterministic given the history)
            offs = op.get("value") or dict(self._host_polled)
            return {"type": "commit_offsets", "offsets": offs}
        return {"type": "list_committed_offsets"}

    def encode_body(self, body, intern):
        t = body["type"]
        if t == "send":
            if not 0 <= int(body["key"]) < self.K:
                # the device clips keys into range, which would silently
                # append to the WRONG log; fail the op definitely instead
                raise EncodeCapacityError(
                    f"kafka key {body['key']} outside configured "
                    f"key_count {self.K}")
            return (T_SEND, int(body["key"]), intern.id(body["msg"]), 0)
        if t == "poll":
            return (T_POLL, 0, 0, 0)
        if t == "commit_offsets":
            a, b, c = _pack_offsets(body["offsets"], self.K)
            return (T_COMMIT, a, b, c)
        return (T_LIST, 0, 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_SEND_OK:
            return {"type": "send_ok", "offset": int(a)}
        if t == T_COMMIT_OK:
            return {"type": "commit_offsets_ok",
                    "offsets": _unpack_offsets(int(a), int(b), int(c),
                                               self.K)}
        if t == T_LIST_OK:
            return {"type": "list_committed_offsets_ok",
                    "offsets": _unpack_offsets(int(a), int(b), int(c),
                                               self.K)}
        if t == T_POLL_OK:
            return {"type": "poll_ok",
                    "lens": _unpack_offsets(int(a), int(b), int(c),
                                            self.K)}
        if t == T_ERROR:
            return {"type": "error", "code": int(a),
                    "text": ("log full" if int(a) == 14 else
                             "misrouted (owner/coordinator elsewhere)")}
        return super().decode_body(t, a, b, c, intern)

    def completion(self, op, body, read_state, intern):
        import numpy as np
        if body["type"] == "send_ok":
            k, m = op["value"]
            return {**op, "type": "ok",
                    "value": [str(k), m, body["offset"]]}
        if body["type"] == "poll_ok":
            # the reply words carry the REPLY-round per-key lengths;
            # slicing the final (append-only) log to them reconstructs
            # the exact replica prefix of the reply round, which is
            # what makes end-of-stretch state reads sound here
            row = read_state()
            log = np.asarray(row["log"])
            reply_lens = body.get("lens", {})
            msgs = {}
            for k in range(self.K):
                n = int(reply_lens.get(str(k), 0))
                if n:
                    msgs[str(k)] = [[o, intern.value(int(log[k, o]))]
                                    for o in range(n)]
                    self._host_polled[str(k)] = max(
                        self._host_polled.get(str(k), -1), n - 1)
            return {**op, "type": "ok", "value": msgs}
        if body["type"] == "commit_offsets_ok":
            return {**op, "type": "ok", "value": body.get("offsets", {})}
        if body["type"] == "list_committed_offsets_ok":
            return {**op, "type": "ok", "value": body["offsets"]}
        return {**op, "type": "ok"}
