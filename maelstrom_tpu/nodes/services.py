"""The reference's built-in service nodes as role-partitioned in-cluster
programs (PAPER.md layer 5, `service.clj:289-295`).

Where the reference runs lin-tso / seq-kv / lww-kv as host threads
(`maelstrom_tpu/services.py`, which stays as the PURE ORACLE — the role
programs here are pinned against those state machines in
tests/test_services_roles.py), `--node tpu:services` runs them as
heterogeneous IN-CLUSTER nodes on the TPU path: one `RolePartition` with

    node 0                 lin-tso   (linearizable timestamp oracle)
    node 1                 seq-kv    (single-copy KV: linearizable, hence
                                      trivially sequentially consistent)
    nodes [2, 2+n)         lww-kv    (n last-write-wins replicas with
                                      Lamport clocks, converging by
                                      per-key dirty-set gossip)

selected with `--service-roles lin-tso=1,seq-kv=1,lww-kv=3` (the default
5-node layout). The `lin-tso` workload smokes the TSO tier end to end
(`-w lin-tso --node tpu:services`, graded by `checkers/tso.py`); the KV
tiers serve the shared lin-kv wire codes for in-cluster callers and the
oracle suites — mixed-workload clusters ride the same RolePartition
machinery as follow-ons (ROADMAP)."""

from __future__ import annotations

import jax.numpy as jnp

from ..net.tpu import I32, Msgs, cat_lanes
from ..sim import RolePartition
from . import NodeProgram, register
from .raft import T_READ, T_WRITE, T_CAS

T_ERR = 1
T_READ_OK = 11
T_WRITE_OK = 13
T_CAS_OK = 15
T_TS = 40        # -> lin-tso
T_TS_OK = 41     # a = timestamp
T_MERGE = 45     # lww gossip: a = key, b = write ts, c = value+1

DEFAULT_SERVICE_ROLES = "lin-tso=1,seq-kv=1,lww-kv=3"
_SERVICE_NAMES = ("lin-tso", "seq-kv", "lww-kv")


def parse_service_roles(spec) -> dict:
    spec = spec or DEFAULT_SERVICE_ROLES
    out: dict = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        k, sep, val = part.partition("=")
        k = k.strip()
        if k not in _SERVICE_NAMES:
            raise ValueError(f"--service-roles: unknown service {k!r} "
                             f"(expected {list(_SERVICE_NAMES)})")
        n = int(val) if sep else 1
        if n < 1:
            raise ValueError(f"--service-roles: {k} must be >= 1")
        out[k] = n
    for name in _SERVICE_NAMES:
        out.setdefault(name, 0)
    if out["lin-tso"] != 1 or out["seq-kv"] > 1:
        raise ValueError(
            "--service-roles: lin-tso and seq-kv are single-copy "
            "services (exactly one lin-tso, at most one seq-kv)")
    return out


def roles_node_count(spec) -> int:
    r = parse_service_roles(spec)
    return r["lin-tso"] + r["seq-kv"] + r["lww-kv"]


def _kv_reply(t, cur, cas_ok):
    """Shared lin-kv reply encoding for wire-type-dispatched KV tiers:
    (rtype, ra) per the raft conventions — READ_OK carries value+1,
    absent keys error 20, failed cas 22 (or 20 when absent)."""
    rtype = jnp.where(
        t == T_READ, jnp.where(cur > 0, T_READ_OK, T_ERR),
        jnp.where(t == T_WRITE, T_WRITE_OK,
                  jnp.where(cas_ok, T_CAS_OK, T_ERR)))
    ra = jnp.where(
        t == T_READ, jnp.where(cur > 0, cur, 20),
        jnp.where((t == T_CAS) & ~cas_ok,
                  jnp.where(cur > 0, 22, 20), 0))
    return rtype, ra


class TSORole(NodeProgram):
    """`PersistentTSO` on device: a strictly monotonic timestamp oracle
    (reply carries the pre-increment value, like `service.clj:116-122`).
    Multiple requests landing in one round are linearized by inbox lane
    order — their op windows overlap, so any total order is legal."""

    name = "lin-tso"

    def __init__(self, opts, nodes):
        super().__init__(opts, nodes)
        self.inbox_cap = int(opts.get("service_inbox", 8))
        self.outbox_cap = self.inbox_cap

    def init_state(self):
        return {"ts": jnp.zeros((self.n_nodes,), I32)}

    def step(self, state, inbox, ctx):
        req = inbox.valid & (inbox.type == T_TS)
        rank = jnp.cumsum(req.astype(I32), axis=1) - req.astype(I32)
        out = inbox.replace(
            valid=req, dest=inbox.src, reply_to=inbox.mid,
            type=jnp.full_like(inbox.type, T_TS_OK),
            a=state["ts"][:, None] + rank,
            b=jnp.zeros_like(inbox.b), c=jnp.zeros_like(inbox.c))
        return ({"ts": state["ts"]
                 + jnp.sum(req.astype(I32), axis=1)}, out)

    def quiescent(self, state):
        return jnp.array(True)


class SeqKVRole(NodeProgram):
    """`PersistentKV` on device, single copy: read/write/cas applied in
    arrival order (a linearizable implementation, which is a legal
    refinement of the reference's sequential adapter). Values are small
    ints stored as value+1 (0 = absent), the lin-kv wire convention."""

    name = "seq-kv"

    def __init__(self, opts, nodes):
        super().__init__(opts, nodes)
        self.keys = int(opts.get("kv_keys", 256))
        self.inbox_cap = int(opts.get("service_inbox", 8))
        self.outbox_cap = self.inbox_cap

    def init_state(self):
        return {"kv": jnp.zeros((self.n_nodes, self.keys), I32)}

    def step(self, state, inbox, ctx):
        n, K = self.n_nodes, inbox.valid.shape[1]
        kv = state["kv"]
        me = jnp.arange(n, dtype=I32)
        lanes = []
        # lanes apply strictly in order: a cas may read the key the
        # previous lane wrote, so the chain is sequential like a log
        for k in range(K):
            valid = inbox.valid[:, k]
            t = inbox.type[:, k]
            key = jnp.clip(inbox.a[:, k], 0, self.keys - 1)
            req = valid & ((t == T_READ) | (t == T_WRITE) | (t == T_CAS))
            cur = jnp.take_along_axis(kv, key[:, None], axis=1)[:, 0]
            frm = jnp.clip(inbox.b[:, k] + 1, 0, 0xFF)
            cas_ok = (t == T_CAS) & (cur > 0) & (cur == frm)
            new_v = jnp.where(t == T_WRITE,
                              jnp.clip(inbox.b[:, k] + 1, 0, 0xFF),
                              jnp.clip(inbox.c[:, k] + 1, 0, 0xFF))
            do = req & ((t == T_WRITE) | cas_ok)
            kv = kv.at[me, jnp.where(do, key, self.keys)].set(
                new_v, mode="drop", unique_indices=True)
            rtype, ra = _kv_reply(t, cur, cas_ok)
            lanes.append((req, inbox.src[:, k], rtype, ra,
                          inbox.mid[:, k]))
        out = Msgs.empty((n, K)).replace(
            valid=jnp.stack([ln[0] for ln in lanes], axis=1),
            dest=jnp.stack([ln[1] for ln in lanes], axis=1),
            type=jnp.stack([ln[2] for ln in lanes], axis=1),
            a=jnp.stack([ln[3] for ln in lanes], axis=1),
            reply_to=jnp.stack([ln[4] for ln in lanes], axis=1))
        return {"kv": kv}, out

    def quiescent(self, state):
        return jnp.array(True)


class LWWKVRole(NodeProgram):
    """`LWWKV` on device: n replicas, Lamport write timestamps, per-key
    last-write-wins merge (ties keep ours), converging by dirty-set
    gossip — each round every replica ships up to `gossip_keys` dirty
    (key, ts, value) triples to its ring successor, and adoption marks
    the key dirty at the receiver, so an update propagates the whole
    ring and the dirty set drains (the quiescence signal)."""

    name = "lww-kv"

    def __init__(self, opts, nodes, base: int = 0):
        super().__init__(opts, nodes)
        self.base = base
        self.keys = int(opts.get("kv_keys", 256))
        self.G = int(opts.get("gossip_keys", 8))
        self.inbox_cap = int(opts.get("service_inbox", 8))
        self.outbox_cap = self.inbox_cap + self.G

    def init_state(self):
        n = self.n_nodes
        return {"kv": jnp.zeros((n, self.keys), I32),
                "vts": jnp.full((n, self.keys), -1, I32),
                "clock": jnp.zeros((n,), I32),
                "dirty": jnp.zeros((n, self.keys), bool)}

    def step(self, state, inbox, ctx):
        n, K, keys = self.n_nodes, inbox.valid.shape[1], self.keys
        s = dict(state)
        me = jnp.arange(n, dtype=I32)
        lanes = []
        for k in range(K):
            valid = inbox.valid[:, k]
            t = inbox.type[:, k]
            key = jnp.clip(inbox.a[:, k], 0, keys - 1)
            cur = jnp.take_along_axis(s["kv"], key[:, None], axis=1)[:, 0]
            kts = jnp.take_along_axis(s["vts"], key[:, None],
                                      axis=1)[:, 0]
            # gossip merge: adopt strictly-newer stamps (ties keep ours)
            mg = valid & (t == T_MERGE)
            adopt = mg & (inbox.b[:, k] > kts)
            frm = jnp.clip(inbox.b[:, k] + 1, 0, 0xFF)
            cas_ok = valid & (t == T_CAS) & (cur > 0) & (cur == frm)
            wr = valid & ((t == T_WRITE) | cas_ok)
            new_v = jnp.where(
                adopt, inbox.c[:, k],
                jnp.where(t == T_WRITE,
                          jnp.clip(inbox.b[:, k] + 1, 0, 0xFF),
                          jnp.clip(inbox.c[:, k] + 1, 0, 0xFF)))
            new_ts = jnp.where(adopt, inbox.b[:, k], s["clock"])
            do = adopt | wr
            tgt = jnp.where(do, key, keys)
            s["kv"] = s["kv"].at[me, tgt].set(new_v, mode="drop",
                                              unique_indices=True)
            s["vts"] = s["vts"].at[me, tgt].set(new_ts, mode="drop",
                                                unique_indices=True)
            s["dirty"] = s["dirty"].at[me, tgt].set(
                True, mode="drop", unique_indices=True)
            s["clock"] = jnp.where(
                wr, s["clock"] + 1,
                jnp.maximum(s["clock"],
                            jnp.where(mg, inbox.b[:, k] + 1, 0)))
            rtype, ra = _kv_reply(t, cur, cas_ok)
            req = valid & ((t == T_READ) | (t == T_WRITE) | (t == T_CAS))
            lanes.append((req, inbox.src[:, k], rtype, ra,
                          inbox.mid[:, k]))
        reply_out = Msgs.empty((n, K)).replace(
            valid=jnp.stack([ln[0] for ln in lanes], axis=1),
            dest=jnp.stack([ln[1] for ln in lanes], axis=1),
            type=jnp.stack([ln[2] for ln in lanes], axis=1),
            a=jnp.stack([ln[3] for ln in lanes], axis=1),
            reply_to=jnp.stack([ln[4] for ln in lanes], axis=1))

        # dirty-set gossip to the ring successor (skipped for a single
        # replica, where there is nobody to converge with)
        G = self.G
        if n > 1 and G > 0:
            dirty = s["dirty"]
            rank = jnp.cumsum(dirty.astype(I32), axis=1) - 1
            sel = dirty & (rank < G)
            key_ar = jnp.broadcast_to(
                jnp.arange(keys, dtype=I32)[None, :], (n, keys))
            nn = me[:, None]
            lane_tgt = jnp.where(sel, rank, G + key_ar)

            def pick(src, fill):
                buf = jnp.full((n, G), fill, src.dtype)
                return buf.at[nn, lane_tgt].set(src, mode="drop",
                                                unique_indices=True)
            g_key = pick(key_ar, 0)
            g_ts = pick(s["vts"], 0)
            g_val = pick(s["kv"], 0)
            g_valid = pick(sel, False)
            s["dirty"] = dirty & ~sel
            succ = self.base + (me + 1) % n
            gossip_out = Msgs.empty((n, G)).replace(
                valid=g_valid,
                dest=jnp.broadcast_to(succ[:, None], (n, G)),
                type=jnp.full((n, G), T_MERGE, I32),
                a=g_key, b=g_ts, c=g_val)
            reply_out = cat_lanes(reply_out, gossip_out)
        return s, reply_out

    def quiescent(self, state):
        if self.n_nodes <= 1:
            return jnp.array(True)
        return ~state["dirty"].any()


@register
class ServicesProgram(RolePartition):
    """`--node tpu:services`: the built-in service nodes as one
    role-partitioned in-cluster tree (see module docstring). The client
    role is lin-tso — the `lin-tso` workload's smoke surface."""

    name = "services"

    def __init__(self, opts, nodes):
        r = parse_service_roles(opts.get("service_roles"))
        roles = []
        base = 0
        if r["lin-tso"]:
            roles.append(("lin-tso",
                          TSORole(opts, nodes[base:base + r["lin-tso"]])))
            base += r["lin-tso"]
        if r["seq-kv"]:
            roles.append(("seq-kv",
                          SeqKVRole(opts,
                                    nodes[base:base + r["seq-kv"]])))
            base += r["seq-kv"]
        if r["lww-kv"]:
            roles.append(("lww-kv",
                          LWWKVRole(opts,
                                    nodes[base:base + r["lww-kv"]],
                                    base=base)))
            base += r["lww-kv"]
        RolePartition.__init__(self, opts, nodes, roles)

    # --- host boundary: the lin-tso RPC surface -------------------------

    def request_for_op(self, op):
        return {"type": "ts"}

    def node_for_op(self, op):
        return 0

    def encode_body(self, body, intern):
        assert body["type"] == "ts"
        return (T_TS, 0, 0, 0)

    def decode_body(self, t, a, b, c, intern):
        if t == T_TS_OK:
            return {"type": "ts_ok", "ts": int(a)}
        return NodeProgram.decode_body(self, t, a, b, c, intern)

    def completion(self, op, body, read_state, intern):
        return {**op, "type": "ok", "value": int(body["ts"])}
