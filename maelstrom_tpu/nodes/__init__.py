"""Built-in TPU node programs.

Where the reference runs one OS process per node speaking JSON over stdio
(`src/maelstrom/process.clj`, `demo/**`), a *node program* here is a pure,
batched JAX state machine: per-node state is a pytree of arrays with a
leading node axis, and one `step(state, inbox, ctx) -> (state', outbox)`
advances every node one round inside the jitted simulation loop
(`maelstrom_tpu.sim`).

Each program also defines the host-boundary contract that keeps the JSON
protocol (`doc/protocol.md` parity) as the compatibility surface:

  - `request_for_op(op)`: generator op -> protocol JSON body (or HOST when
    the op is answered host-side from device state)
  - `encode_body(body, intern)` / `decode_body(t, a, b, c, intern)`:
    JSON body <-> fixed-width words (type code + 3 payload words). Opaque
    payloads (e.g. echo strings) go through the run's interning table.
  - `completion(op, body, read_state, intern)`: reply body -> completed history op.
    `read_state()` returns the destination node's state row, pulled at
    completion time — reads whose values don't fit in a message body (e.g.
    a broadcast node's whole set) reply with a bare ack on the wire and
    materialize the value here, which keeps message accounting faithful and
    places the read's linearization point inside its op window.

Type code 0 is reserved (invalid slot), 1 is the shared RPC error reply;
programs define their own codes from 10 up.
"""

from __future__ import annotations

import json
from typing import Any, Callable

T_INVALID = 0
T_ERROR = 1     # error reply: a = error code, b = interned text

HOST = "host"   # sentinel: op handled host-side, no message injected


class EncodeCapacityError(ValueError):
    """A static encode capacity (value table, command table) is
    exhausted. The runner completes the op as a definite fail instead of
    crashing the run; any other exception from encode_body still
    propagates (a programming error must not be swallowed)."""


class Intern:
    """Bidirectional value <-> int32 table for opaque payloads crossing the
    host/device boundary (SURVEY.md section 7 'hard parts')."""

    def __init__(self):
        self._fwd: dict[str, int] = {}
        self._rev: list[Any] = []

    def id(self, value) -> int:
        key = json.dumps(value, sort_keys=True, default=str)
        i = self._fwd.get(key)
        if i is None:
            i = len(self._rev)
            self._fwd[key] = i
            self._rev.append(value)
        return i

    def peek(self, value):
        """Existing id for a value, or None — without growing the
        table (capacity checks must not leak entries for ops that are
        about to fail)."""
        return self._fwd.get(json.dumps(value, sort_keys=True,
                                        default=str))

    def __len__(self):
        return len(self._rev)

    def value(self, i: int):
        return self._rev[i]


class NodeProgram:
    """Base class for built-in batched node programs."""

    name = "abstract"
    inbox_cap = 8
    outbox_cap = 8
    needs_state_reads = False   # runner pulls node state rows for reads
    # edge programs: True when the inbox lanes are interchangeable (the
    # step dispatches on message *type* across every lane, never on lane
    # position). Prerequisite for building an `EdgeConfig(spill=True)` —
    # the collision-free write reassigns lanes (net/static.py). Raft
    # reads lanes positionally (0 = request, 1 = reply, 2 = proxy) and
    # must leave this False.
    edge_lanes_symmetric = False
    # True when the program's per-round emission toward one neighbor is
    # ONE logical RPC whose lanes must arrive together (raft: the AE
    # header's prev_idx positions the entry lanes). The net then shares
    # the latency and loss draws across that edge's lanes for the round
    # — the packet travels whole — instead of drawing per lane. Leave
    # False for programs whose lanes are independent self-describing
    # messages (gossip values, kafka per-key offers).
    edge_atomic_rpc = False
    # latency draws beyond the edge ring are clipped and counted; runs
    # that clip are invalid unless the program (or test opts) accept the
    # distortion explicitly
    tolerates_latency_clipping = False
    # reply-time state payload: when > 0, the compiled scan snapshots
    # `reply_payload(state, node)` (an [M] -> [M, W] i32 device fn) for
    # every client reply, ON DEVICE, AT THE REPLY ROUND, into the reply
    # log; the host completes the op from that payload
    # (`completion_payload`) instead of pulling device state. This is
    # both more exact (the row is from the round that produced the
    # reply, not end-of-dispatch) and much cheaper on remote backends
    # (a read completion costs zero extra round trips) — and it makes
    # the collect-replies fast path sound for programs whose
    # completions read mutable state.
    reply_payload_words = 0

    # Durability contract for the kill/restart fault package
    # (`maelstrom_tpu.nemesis`): what survives a crash.
    #   None (default): the node persists ALL of its state — modeled as
    #     a server that fsyncs every update before acking (the honest
    #     reading of a CRDT node whose acked payload must survive).
    #     Kill+restart is then pure downtime plus in-flight loss.
    #   a tuple of state-dict keys: ONLY those entries survive; the rest
    #     is rebuilt from init_state() at restart. Raft persists
    #     log+term+vote and rebuilds kv/commit/applied by replay, so the
    #     kill fault actually exercises its recovery path.
    durable_keys: tuple | None = None

    def __init__(self, opts: dict, nodes: list[str]):
        self.opts = opts
        self.nodes = nodes
        self.n_nodes = len(nodes)

    # --- device side ---

    def init_state(self):
        """Per-node state pytree, leading axis n_nodes."""
        raise NotImplementedError

    def step(self, state, inbox, ctx):
        """Batched step: inbox is a Msgs batch [N, K]; returns
        (state', outbox Msgs [N, O]). ctx: {"round": i32, "key": PRNGKey}.
        The outbox's src/mid/due fields are overwritten by the network."""
        raise NotImplementedError

    # --- host boundary ---

    def request_for_op(self, op: dict):
        """Generator op -> protocol body dict, or HOST."""
        raise NotImplementedError

    def node_for_op(self, op: dict):
        """Optional smart-client routing: the node index this op should
        be sent to, or None for the worker's bound node (the default).
        Real Maelstrom clients choose who they talk to (e.g. kafka
        clients route to partition owners); programs whose RPCs have a
        natural home override this."""
        return None

    def encode_body(self, body: dict, intern: Intern):
        """Protocol body -> (type, a, b, c) words."""
        raise NotImplementedError

    def decode_body(self, t: int, a: int, b: int, c: int, intern: Intern):
        """Words -> protocol body dict."""
        if t == T_ERROR:
            return {"type": "error", "code": int(a),
                    "text": intern.value(b) if 0 <= b < len(intern._rev)
                    else ""}
        raise ValueError(f"{self.name}: unknown reply type code {t}")

    def completion(self, op: dict, body: dict,
                   read_state: Callable[[], Any],
                   intern: Intern) -> dict:
        """Reply body -> completed op (type ok). Error bodies are mapped by
        the runner before this is called."""
        return {**op, "type": "ok"}

    def reply_payload(self, state, node_idx):
        """Device hook (see `reply_payload_words`): [M] node indices ->
        [M, W] i32 payload rows snapshotting whatever this program's
        completions need, evaluated inside the compiled round."""
        raise NotImplementedError

    def completion_payload(self, op: dict, body: dict, payload,
                           intern: Intern) -> dict:
        """Reply body + reply-round payload row -> completed op. Used
        instead of `completion` when `reply_payload_words > 0`."""
        raise NotImplementedError

    def host_op(self, op: dict, read_state: Callable[[], Any],
                intern: Intern) -> dict:
        """Completes a HOST-routed op from device state."""
        raise NotImplementedError

    def state_row(self, tree, node_idx: int):
        """One node's state rows, copied out of a host view of the
        state tree (`runner._read_state`). Homogeneous programs index
        every leaf by the node id; `sim.RolePartition` overrides to
        map the GLOBAL id into its role's subtree (whose leaves lead
        with the role's node count, not the cluster's)."""
        import jax
        import numpy as np
        return jax.tree.map(lambda a: np.array(a[node_idx]), tree)

    # --- checkpointable host-side session state ---

    def host_state(self):
        """Picklable host-side bookkeeping this program keeps between
        ops (kafka: consumer-group sessions + polled-offset tracking),
        carried in checkpoints so a resumed run replays identically.
        None = stateless."""
        return None

    def set_host_state(self, st):
        """Restores what `host_state` returned (no-op for None)."""

    # --- durable store (kill/restart fault package) ---

    def durable_view(self, state):
        """The persisted subset of `state` (same arrays, no copy),
        carried in `SimState.durable` and synced at each round boundary.
        None when everything is durable (see `durable_keys`)."""
        if self.durable_keys is None:
            return None
        return {k: state[k] for k in self.durable_keys}

    def restore(self, fresh, durable, state, mask):
        """Crash-restart: nodes where `mask` is True come back with
        volatile state rebuilt from `fresh` (an init_state() pytree)
        overlaid with their `durable` entries; other nodes keep `state`.
        Pure and jit-friendly (the nemesis applies it between rounds)."""
        import jax
        import jax.numpy as jnp
        if self.durable_keys is None:
            return state            # fully persistent: restart keeps all
        recovered = {**fresh, **durable}

        def pick(o, r):
            m = mask.reshape(mask.shape + (1,) * (r.ndim - 1))
            return jnp.where(m, r, o)
        return jax.tree.map(pick, state, recovered)

    def invalid_counters(self, state) -> dict:
        """Program-state counters that invalidate the run when nonzero,
        surfaced by the net-stats checker next to `dropped_overflow`: a
        node that silently sheds work because a static capacity was hit
        degrades results as badly as a silently dropped message. Returns
        {stat-name: int array} (summed and reported per counter)."""
        return {}

    # --- movable-role fault targeting + client redirect hooks ---
    #
    # Programs with a MOVABLE role (an elected leader) may additionally
    # implement, all consumed via getattr by the runner/nemesis:
    #   - dynamic_fault_groups() -> tuple of target-group names resolved
    #     at fault-invoke time (e.g. "sequencer" -> the live leader;
    #     `--nemesis-targets kill=sequencer` becomes a failover driver);
    #   - current_leader_host(nodes_host) -> global node id, from a host
    #     copy of the state tree (the dynamic-group resolver);
    #   - redirect_hint(error_body) -> hinted node id / -1 / None — a
    #     not-leader reply the runner requeues under seeded backoff
    #     instead of completing, plus next_probe(contacted),
    #     note_leader(i), note_timeout(i) to steer the host-side guess;
    #   - election_report(nodes_host) -> accounting dict for
    #     checkers/availability.py (failovers, rounds-to-leader, ...).

    def dynamic_fault_groups(self) -> tuple:
        """Fault-target groups resolved against live cluster state at
        invoke time; () for programs whose roles never move."""
        return ()


def edge_timing(opts: dict, n_nodes: int) -> tuple[int, int, int]:
    """Shared edge-channel sizing: (ring, retry_rounds, lat_rounds).

    The ring must cover the worst latency draw (randomized dists get 8x
    slack, clipped draws are counted) plus headroom for the slow! fault
    (x10) on clusters small enough to afford the memory; the retry tick
    must exceed a full acknowledgement round trip."""
    import math
    lat = (opts.get("latency") or {}).get("mean", 0)
    ms_per_round = opts.get("ms_per_round", 1.0)
    lat_rounds = int(math.ceil(lat / ms_per_round))
    dist = (opts.get("latency") or {}).get("dist", "constant")
    slack = 1 if dist == "constant" else 8
    scale_headroom = int(opts.get("max_latency_scale",
                                  10 if n_nodes <= 4096 else 1))
    ring = max(2, lat_rounds * slack * scale_headroom + 2)
    # the duplicate fault re-delivers one round past the original's
    # (floored) arrival; a minimal ring (zero-latency constant: depth 2,
    # offsets {1}) has no cell for that second arrival, and the draw
    # would be clipped — counted and gated as a latency-model
    # distortion. Two extra cells make the duplicate representable.
    nm = opts.get("nemesis")
    if isinstance(nm, (set, frozenset, list, tuple)) and "duplicate" in nm:
        ring += 2
    retry_rounds = max(2 * (lat_rounds + 1) + 4, 10)
    return ring, retry_rounds, lat_rounds


def edge_capacity(opts: dict, program) -> tuple[bool, int, bool]:
    """Shared spill-mode decision + lane sizing for a program's
    EdgeConfig: (spill, channel_lanes, uniform_arrival).

    Spill (the collision-free write, net/static.py) is *mandatory* when
    a destroyed message would change protocol semantics (randomized
    latency + no retransmission) and an *optimization* for retrying
    protocols, taken only where its sort working set is affordable (the
    same <=4096-node cut as edge_timing's slow! headroom). Spill runs on
    small clusters also get doubled lanes so colliding arrivals
    essentially never exhaust a cell — capped at LANE_STRIDE, the send-
    lane field width in the packed journal stamp."""
    from ..net.static import LANE_STRIDE
    n = program.n_nodes
    lanes = program.lanes
    # validity-critical guards raise (not assert): they must survive
    # python -O, or a forbidden config silently runs lossy channels
    if lanes > LANE_STRIDE:
        raise ValueError(
            f"{program.name}: {lanes} edge lanes exceed LANE_STRIDE")
    dist = (opts.get("latency") or {}).get("dist", "constant")
    tolerates = getattr(program, "tolerates_channel_overwrites", False)
    if dist != "constant" and not tolerates \
            and not program.edge_lanes_symmetric:
        # lossless delivery is required but spill reassigns lanes: a
        # positional-lane program cannot run this config correctly
        raise ValueError(
            f"{program.name}: randomized latency with no retransmission "
            f"requires spill-mode channels, which need type-dispatched "
            f"(symmetric) inbox lanes")
    spill = (program.edge_lanes_symmetric and dist != "constant"
             and (n <= 4096 or not tolerates))
    if spill and n <= 4096:
        lanes = min(2 * lanes, LANE_STRIDE)
    # constant draws are identical within a round: edge_write can update
    # the single shared arrival cell (EdgeConfig.uniform_arrival)
    return spill, lanes, dist == "constant"


def wire_name_table(program_module) -> dict[int, str]:
    """Explicit wire-code -> name table for the send-count-by-type
    netstats breakdown.

    A module may pin names outright with a ``WIRE_NAMES = {code: name}``
    dict; otherwise names derive from its ``T_*`` int constants. Aliased
    codes (two constants sharing a value) resolve to the
    alphabetically-first constant name — a deterministic winner, where
    raw ``vars(module)`` iteration made the report depend on definition
    order. The program's own names shadow the shared reply vocabulary
    (``T_ERROR`` etc.) defined here."""
    import sys
    names: dict[int, str] = {}
    shared = sys.modules[__name__]
    for source in (program_module, shared):
        if source is None:
            continue
        for code, name in (getattr(source, "WIRE_NAMES", None)
                           or {}).items():
            names.setdefault(int(code), str(name))
        for k in sorted(vars(source)):
            v = vars(source)[k]
            if k.startswith("T_") and isinstance(v, int):
                names.setdefault(v, k[2:].lower())
    return names


PROGRAMS: dict[str, Callable] = {}


def register(cls):
    PROGRAMS[cls.name] = cls
    return cls


def get_program(name: str, opts: dict, nodes: list[str]) -> NodeProgram:
    # import for side effect: program registration
    from . import (echo, broadcast, broadcast_batched,  # noqa: F401
                   compartment, gset, pn_counter, raft,  # noqa: F401
                   services, txn_list_append,  # noqa: F401
                   txn_rw_register, unique_ids,  # noqa: F401
                   kafka)  # noqa: F401
    if name == "ordered":
        # the ordering-layer axis (doc/ordering.md): the engine named
        # by opts["ordering"] composed with the applier serving
        # opts["workload"] — `--ordering raft|compartment|batched`
        from ..ordering import make_ordered
        return make_ordered(opts, nodes)
    if name.startswith("solo:"):
        # any built-in program wrapped as a ONE-role RolePartition:
        # pure delegation, bit-identical histories (the role-partition
        # regression-pin configuration, tests/test_role_partition.py)
        from ..sim import RolePartition
        inner = get_program(name[len("solo:"):], opts, nodes)
        return RolePartition(opts, nodes, [("r0", inner)])
    if name not in PROGRAMS:
        raise ValueError(f"no built-in TPU node program {name!r}; "
                         f"have {sorted(PROGRAMS)}")
    return PROGRAMS[name](opts, nodes)


def partition_node_count(name: str, opts: dict) -> int | None:
    """Node count a role-partitioned program family derives from its
    role spec (None for homogeneous programs, whose count the user
    picks freely). `core.parse_nodes` consults this so
    `--node tpu:compartment --roles proxies=2,...` sizes the cluster
    without a redundant --node-count."""
    if name == "compartment":
        from .compartment import roles_node_count
        return roles_node_count(opts.get("roles"))
    if name == "services":
        from .services import roles_node_count
        return roles_node_count(opts.get("service_roles"))
    if name == "ordered":
        from ..ordering import ordered_node_count
        return ordered_node_count(opts)
    return None
