"""Checker-graded broadcast run at benchmark scale.

The north star (BASELINE.json) reads ">= 1M simulated msgs/sec ...
passing the stock broadcast checker". bench.py's timed scan supplies the
throughput half; this module supplies the grading half at the same
scale: a real operation history synthesized from actual protocol
traffic, graded by the stock `BroadcastChecker`
(`maelstrom_tpu/checkers/set_full.py`) — not a device-state peek.

How the history is honest:

  - every broadcast op's invoke is its injection round and its ok is the
    round its `broadcast_ok` reply actually came back through the
    client-message path (collected from the scanned rounds);
  - read ops go *through the protocol* (T_READ -> T_READ_OK), and their
    observed value sets come off the wire: with V <= 64 the reply
    payload carries the serving node's seen bitmap in (b, c)
    (`nodes/broadcast.py`), so a read's result is exact at its serve
    round regardless of how many rounds one dispatch scans;
  - *racing* reads are injected WHILE values propagate — every few
    rounds, at rotating nodes — so the stock checker's stable-latency
    machinery grades real propagation-visibility lag at full scale
    (nonzero quantiles, sanity-bounded by the grid's hop depth);
  - *final* reads after verified convergence pin stable/lost for every
    value; their wire payloads are cross-checked bit-for-bit against
    host-materialized `seen` rows (the contract the interactive
    runner's `completion()` relies on);
  - the run fails loudly if convergence is not reached, any ack goes
    missing, or the network dropped anything (`dropped_overflow`).

Used by bench.py (BENCH_GRADED) and unit-tested at small scale on CPU.
"""

from __future__ import annotations

import json
import os
import time


def run_graded(n_nodes: int, values: int, chunk: int = 100,
               pool_cap: int = 8192, reads: int = 16, seed: int = 2,
               max_rounds: int = 1600, per_neighbor: int = 4,
               racing_read_every: int = 16,
               out_dir: str | None = None, verbose: bool = True) -> dict:
    """Runs a graded broadcast at `n_nodes` and returns a summary dict
    (checker results + net stats). Writes results.json + history.jsonl
    to `out_dir` when given."""
    import sys

    import jax
    import jax.numpy as jnp
    import numpy as np

    from .checkers.set_full import BroadcastChecker
    from .history import History, Op
    from .net import tpu as T
    from .nodes import get_program
    from .nodes.broadcast import T_BCAST, T_BCAST_OK, T_READ, T_READ_OK
    from .sim import make_run_fn, make_sim

    N, V = n_nodes, values
    nodes = [f"n{i}" for i in range(N)]
    # the efficient send-once-plus-retry protocol (interactive default)
    program = get_program(
        "broadcast",
        {"topology": "grid", "max_values": V, "latency": {"mean": 0},
         "gossip_per_neighbor": per_neighbor}, nodes)
    cfg = T.NetConfig(n_nodes=N, n_clients=1, pool_cap=pool_cap,
                      inbox_cap=program.inbox_cap, client_cap=4)
    run_fn = make_run_fn(program, cfg, collect_client_msgs=True)
    conv_fn = jax.jit(lambda sim: sim.nodes["seen"][:, :V].all())

    ms_per_round = 1.0
    t_ns = lambda r: int(r * ms_per_round * 1e6)  # noqa: E731

    def make_plan(rows):
        """rows: [(round_in_chunk, slot, dest, type, a)] -> Msgs
        [chunk, 2]. Slot 0 carries broadcasts, slot 1 reads, so a read
        scheduled on an injection round never clobbers the injection."""
        plan = T.Msgs.empty((chunk, 2))
        if not rows:
            return plan
        rr, ss, dd, tt, aa = (np.asarray(x) for x in zip(*rows))
        valid = np.zeros((chunk, 2), bool)
        dest = np.zeros((chunk, 2), np.int32)
        typ = np.zeros((chunk, 2), np.int32)
        a = np.zeros((chunk, 2), np.int32)
        valid[rr, ss] = True
        dest[rr, ss] = dd
        typ[rr, ss] = tt
        a[rr, ss] = aa
        return plan.replace(valid=jnp.asarray(valid),
                            src=jnp.full((chunk, 2), N, T.I32),
                            dest=jnp.asarray(dest), type=jnp.asarray(typ),
                            a=jnp.asarray(a))

    # --- phase A: inject the V broadcast values, run to convergence ---
    inj_round = {2 * v: v for v in range(V)}      # round -> value
    dest_of = lambda v: int((v * 2654435761) % N)  # noqa: E731

    if V > 64:
        raise ValueError("graded bench needs V <= 64 (read replies carry "
                         "the seen bitmap on the wire)")

    sim = make_sim(program, cfg, seed=seed)
    t0 = time.perf_counter()
    ops = []              # assembled out of order; time-sorted at the end
    # FIFO per op kind: client RPCs have zero network latency and a fixed
    # reply delay, so acks of one kind return in injection order
    outstanding = {"broadcast": [], "read": []}
    n_procs = 0
    r = 0
    converged_at = None
    wire_reads = {}       # process -> decoded value list (cross-check)

    def decode_bits(b, c):
        bits = (int(np.uint32(b)) | (int(np.uint32(c)) << 32))
        return [v for v in range(V) if (bits >> v) & 1]

    def drain_acks(cm_chunk, base_round):
        """Walks a chunk's collected client messages, appending ok ops
        for each ack in arrival order. Read values are decoded from the
        reply payload (the serving node's seen bitmap) — exact at the
        serve round. Guards raise (not assert): the honesty contract
        must survive python -O."""
        valid = np.asarray(cm_chunk.valid)         # [chunk, CC]
        types = np.asarray(cm_chunk.type)
        bs, cs = np.asarray(cm_chunk.b), np.asarray(cm_chunk.c)
        for i in range(valid.shape[0]):
            for j in np.nonzero(valid[i])[0]:
                t = int(types[i, j])
                kind = {T_BCAST_OK: "broadcast", T_READ_OK: "read"}.get(t)
                if kind is None:
                    raise RuntimeError(f"unexpected reply type {t}")
                if not outstanding[kind]:
                    raise RuntimeError(f"{kind} ack with nothing in flight")
                val, inv_r, proc = outstanding[kind].pop(0)
                value = (decode_bits(bs[i, j], cs[i, j])
                         if kind == "read" else val)
                if kind == "read":
                    wire_reads[proc] = value
                ops.append(Op(type="ok", f=kind, value=value,
                              process=proc, time=t_ns(base_round + i)))

    # --- phase A: inject the V broadcasts; READS RACE PROPAGATION ---
    # a racing read every `racing_read_every` rounds at a rotating
    # pseudorandom node: reads that begin after a value is acked but
    # before the flood reaches their node push the checker's
    # last-absent marker — real, nonzero stable latencies at full scale
    racing_procs = []
    while r < max_rounds:
        rows = []
        for rc in range(chunk):
            v = inj_round.get(r + rc)
            if v is not None:
                rows.append((rc, 0, dest_of(v), T_BCAST, v))
                ops.append(Op(type="invoke", f="broadcast", value=v,
                              process=n_procs, time=t_ns(r + rc)))
                outstanding["broadcast"].append((v, r + rc, n_procs))
                n_procs += 1
            if (r + rc) % racing_read_every == 0:
                node = dest_of((r + rc) * 11 + 5)
                rows.append((rc, 1, node, T_READ, 0))
                ops.append(Op(type="invoke", f="read", value=None,
                              process=n_procs, time=t_ns(r + rc)))
                outstanding["read"].append((node, r + rc, n_procs))
                racing_procs.append(n_procs)
                n_procs += 1
        sim, cm = run_fn(sim, make_plan(rows))
        cm = jax.device_get(cm)
        drain_acks(cm, r)
        r += chunk
        if r >= 2 * V and bool(jax.device_get(conv_fn(sim))):
            converged_at = r
            break
    if converged_at is None:
        raise SystemExit(f"graded run did not converge in {max_rounds} "
                         f"rounds")
    if outstanding["broadcast"] or outstanding["read"]:
        raise RuntimeError(
            f"{len(outstanding['broadcast'])} broadcasts / "
            f"{len(outstanding['read'])} reads never acked")
    if verbose:
        print(f"graded: converged at round {converged_at}, "
              f"{len(racing_procs)} racing reads "
              f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)

    # --- phase B: final reads after verified convergence; wire payloads
    # cross-checked against host-materialized seen rows ---
    read_nodes = sorted({dest_of(k * 7 + 3) for k in range(reads)}
                        | {0, N - 1})
    seen_rows = np.asarray(jax.device_get(
        sim.nodes["seen"][jnp.asarray(read_nodes), :V]))
    materialized = {n: [int(v) for v in np.nonzero(seen_rows[i])[0]]
                    for i, n in enumerate(read_nodes)}

    read_sched = {r + 2 * k: node for k, node in enumerate(read_nodes)}
    final_proc_node = {}
    last_read_round = max(read_sched)
    while read_sched or outstanding["read"]:
        rows = []
        for rc in range(chunk):
            node = read_sched.pop(r + rc, None)
            if node is not None:
                rows.append((rc, 1, node, T_READ, 0))
                ops.append(Op(type="invoke", f="read", value=None,
                              process=n_procs, time=t_ns(r + rc),
                              final=True))
                outstanding["read"].append((node, r + rc, n_procs))
                final_proc_node[n_procs] = node
                n_procs += 1
        sim, cm = run_fn(sim, make_plan(rows))
        cm = jax.device_get(cm)
        drain_acks(cm, r)
        r += chunk
        if r > last_read_round + 4 * chunk:
            break
    if outstanding["read"]:
        raise RuntimeError(f"{len(outstanding['read'])} reads never acked")
    for proc, node in final_proc_node.items():
        if wire_reads[proc] != materialized[node]:
            raise RuntimeError(
                f"wire/materialized mismatch at node {node}: "
                f"{wire_reads[proc]} != {materialized[node]}")

    # --- grade with the stock checker ---
    ops.sort(key=lambda o: (o.time, o.type != "invoke"))
    history = History(ops)
    checker = BroadcastChecker()
    res = checker.check({}, history, {})
    st = T.stats_dict(sim.net)
    # sanity bound on the graded latencies: a value's visibility lag is
    # at most the grid's propagation depth (diameter hops at one hop per
    # round at zero link latency) plus per-edge queueing of the V values
    # through `per_neighbor`-wide lanes, with 50% slack
    import math
    hop_bound_ms = 1.5 * (2 * math.ceil(math.sqrt(N)) + V) * ms_per_round
    stable_max = (res["stable-latencies"] or {}).get("1") or 0.0
    if stable_max > hop_bound_ms:
        raise RuntimeError(
            f"graded stable-latency max {stable_max}ms exceeds the "
            f"hop-depth bound {hop_bound_ms}ms — latency model broken")
    summary = {
        "nodes": N, "values": V, "reads": len(read_nodes),
        "racing_reads": len(racing_procs),
        "rounds": r, "converged_at_round": converged_at,
        "checker": res, "checker_valid": res["valid"],
        "stable_count": res["stable-count"],
        "lost_count": res["lost-count"],
        "stale_count": res.get("stale-count"),
        "hop_bound_ms": hop_bound_ms,
        "messages_delivered": st["recv_all"],
        "dropped_overflow": st["dropped_overflow"],
        "history_ops": len(history),
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "results.json"), "w") as f:
            json.dump({"valid": res["valid"], "workload": res,
                       "net": {k: v for k, v in st.items()},
                       "config": {"nodes": N, "values": V,
                                  "topology": "grid",
                                  "reads": len(read_nodes),
                                  "rounds": r, "seed": seed}},
                      f, indent=2, default=str)
        with open(os.path.join(out_dir, "history.jsonl"), "w") as f:
            f.write(history.to_jsonl())
    return summary
