"""Checker-graded broadcast run at benchmark scale.

The north star (BASELINE.json) reads ">= 1M simulated msgs/sec ...
passing the stock broadcast checker". bench.py's timed scan supplies the
throughput half; this module supplies the grading half at the same
scale: a real operation history synthesized from actual protocol
traffic, graded by the stock `BroadcastChecker`
(`maelstrom_tpu/checkers/set_full.py`) — not a device-state peek.

How the history is honest:

  - every broadcast op's invoke is its injection round and its ok is the
    round its `broadcast_ok` reply actually came back through the
    client-message path (collected from the scanned rounds);
  - read ops are injected *through the protocol* (T_READ -> T_READ_OK
    acks) strictly after convergence has been verified on device, so
    materializing their values from the (monotone, complete) `seen` rows
    is exact — the same contract the interactive runner's
    `completion()` uses (`maelstrom_tpu/nodes/__init__.py` docstring);
  - the run fails loudly if convergence is not reached, any ack goes
    missing, or the network dropped anything (`dropped_overflow`).

Because reads are scheduled strictly after convergence, no read ever
observes a value missing, so the checker's stable-latency quantiles are
all 0 by construction (jepsen semantics: latency = known -> last-absent
lag). The grade exercises the attempt/ack/lost/stable machinery; the
latency machinery is exercised by the interactive runs and the parity
suite (`maelstrom_tpu/parity.py`), whose reads race propagation.

Used by bench.py (BENCH_GRADED) and unit-tested at small scale on CPU.
"""

from __future__ import annotations

import json
import os
import time


def run_graded(n_nodes: int, values: int, chunk: int = 100,
               pool_cap: int = 8192, reads: int = 16, seed: int = 2,
               max_rounds: int = 1600, per_neighbor: int = 4,
               out_dir: str | None = None, verbose: bool = True) -> dict:
    """Runs a graded broadcast at `n_nodes` and returns a summary dict
    (checker results + net stats). Writes results.json + history.jsonl
    to `out_dir` when given."""
    import sys

    import jax
    import jax.numpy as jnp
    import numpy as np

    from .checkers.set_full import BroadcastChecker
    from .history import History, Op
    from .net import tpu as T
    from .nodes import get_program
    from .nodes.broadcast import T_BCAST, T_BCAST_OK, T_READ, T_READ_OK
    from .sim import make_run_fn, make_sim

    N, V = n_nodes, values
    nodes = [f"n{i}" for i in range(N)]
    # the efficient send-once-plus-retry protocol (interactive default)
    program = get_program(
        "broadcast",
        {"topology": "grid", "max_values": V, "latency": {"mean": 0},
         "gossip_per_neighbor": per_neighbor}, nodes)
    cfg = T.NetConfig(n_nodes=N, n_clients=1, pool_cap=pool_cap,
                      inbox_cap=program.inbox_cap, client_cap=4)
    run_fn = make_run_fn(program, cfg, collect_client_msgs=True)
    conv_fn = jax.jit(lambda sim: sim.nodes["seen"][:, :V].all())

    ms_per_round = 1.0
    t_ns = lambda r: int(r * ms_per_round * 1e6)  # noqa: E731

    def make_plan(rows):
        """rows: [(round_in_chunk, dest, type, a)] -> Msgs [chunk, 1]."""
        plan = T.Msgs.empty((chunk, 1))
        if not rows:
            return plan
        rr, dd, tt, aa = (np.asarray(x) for x in zip(*rows))
        valid = np.zeros((chunk, 1), bool)
        dest = np.zeros((chunk, 1), np.int32)
        typ = np.zeros((chunk, 1), np.int32)
        a = np.zeros((chunk, 1), np.int32)
        valid[rr, 0] = True
        dest[rr, 0] = dd
        typ[rr, 0] = tt
        a[rr, 0] = aa
        return plan.replace(valid=jnp.asarray(valid),
                            src=jnp.full((chunk, 1), N, T.I32),
                            dest=jnp.asarray(dest), type=jnp.asarray(typ),
                            a=jnp.asarray(a))

    # --- phase A: inject the V broadcast values, run to convergence ---
    inj_round = {2 * v: v for v in range(V)}      # round -> value
    dest_of = lambda v: int((v * 2654435761) % N)  # noqa: E731

    sim = make_sim(program, cfg, seed=seed)
    t0 = time.perf_counter()
    ops = []              # assembled out of order; time-sorted at the end
    outstanding = []      # FIFO of (f, value, invoke_round, process)
    n_procs = 0
    r = 0
    converged_at = None

    def drain_acks(cm_chunk, base_round, expect_type, read_values=None):
        """Walks a chunk's collected client messages, appending ok ops
        for each ack in arrival order (at most one op is ever in flight,
        so FIFO pairing is exact). Each op gets its own process so
        History.pairs() matches invoke to completion unambiguously.
        Guards raise (not assert): the docstring's honesty contract must
        survive python -O."""
        valid = np.asarray(cm_chunk.valid)         # [chunk, CC]
        types = np.asarray(cm_chunk.type)
        for i in range(valid.shape[0]):
            for j in np.nonzero(valid[i])[0]:
                t = int(types[i, j])
                if t != expect_type:
                    raise RuntimeError(
                        f"unexpected reply type {t} (want {expect_type})")
                if not outstanding:
                    raise RuntimeError("ack with nothing in flight")
                kind, val, inv_r, proc = outstanding.pop(0)
                value = (read_values[val] if read_values is not None
                         else val)
                ops.append(Op(type="ok", f=kind, value=value,
                              process=proc, time=t_ns(base_round + i)))

    while r < max_rounds:
        rows = []
        for rc in range(chunk):
            v = inj_round.get(r + rc)
            if v is not None:
                rows.append((rc, dest_of(v), T_BCAST, v))
                ops.append(Op(type="invoke", f="broadcast", value=v,
                              process=n_procs, time=t_ns(r + rc)))
                outstanding.append(("broadcast", v, r + rc, n_procs))
                n_procs += 1
        sim, cm = run_fn(sim, make_plan(rows))
        cm = jax.device_get(cm)
        drain_acks(cm, r, T_BCAST_OK)
        r += chunk
        if r >= 2 * V and bool(jax.device_get(conv_fn(sim))):
            converged_at = r
            break
    if converged_at is None:
        raise SystemExit(f"graded run did not converge in {max_rounds} "
                         f"rounds")
    if outstanding:
        raise RuntimeError(f"{len(outstanding)} broadcasts never acked")
    if verbose:
        print(f"graded: converged at round {converged_at} "
              f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)

    # --- phase B: reads through the protocol, after verified convergence
    # (seen is monotone and complete, so the rows pulled here are exactly
    # what each read observed) ---
    read_nodes = sorted({dest_of(k * 7 + 3) for k in range(reads)}
                        | {0, N - 1})
    seen_rows = np.asarray(jax.device_get(
        sim.nodes["seen"][jnp.asarray(read_nodes), :V]))
    read_values = {n: [int(v) for v in np.nonzero(seen_rows[i])[0]]
                   for i, n in enumerate(read_nodes)}

    read_sched = {r + 2 * k: node for k, node in enumerate(read_nodes)}
    last_read_round = max(read_sched)
    while read_sched or outstanding:
        rows = []
        for rc in range(chunk):
            node = read_sched.pop(r + rc, None)
            if node is not None:
                rows.append((rc, node, T_READ, 0))
                ops.append(Op(type="invoke", f="read", value=None,
                              process=n_procs, time=t_ns(r + rc),
                              final=True))
                outstanding.append(("read", node, r + rc, n_procs))
                n_procs += 1
        sim, cm = run_fn(sim, make_plan(rows))
        cm = jax.device_get(cm)
        drain_acks(cm, r, T_READ_OK, read_values=read_values)
        r += chunk
        if r > last_read_round + 4 * chunk:
            break
    if outstanding:
        raise RuntimeError(f"{len(outstanding)} reads never acked")

    # --- grade with the stock checker ---
    ops.sort(key=lambda o: (o.time, o.type != "invoke"))
    history = History(ops)
    checker = BroadcastChecker()
    res = checker.check({}, history, {})
    st = T.stats_dict(sim.net)
    summary = {
        "nodes": N, "values": V, "reads": len(read_nodes),
        "rounds": r, "converged_at_round": converged_at,
        "checker": res, "checker_valid": res["valid"],
        "stable_count": res["stable-count"],
        "lost_count": res["lost-count"],
        "messages_delivered": st["recv_all"],
        "dropped_overflow": st["dropped_overflow"],
        "history_ops": len(history),
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "results.json"), "w") as f:
            json.dump({"valid": res["valid"], "workload": res,
                       "net": {k: v for k, v in st.items()},
                       "config": {"nodes": N, "values": V,
                                  "topology": "grid",
                                  "reads": len(read_nodes),
                                  "rounds": r, "seed": seed}},
                      f, indent=2, default=str)
        with open(os.path.join(out_dir, "history.jsonl"), "w") as f:
            f.write(history.to_jsonl())
    return summary
