"""The command-line interface: `python -m maelstrom_tpu <cmd>`.

Subcommands mirror the reference CLI (`core.clj:224-241`): `test` runs a
single test, `serve` browses the store dir, `demo` runs the bundled demo
binaries against their workloads as a self-test suite, and `doc` regenerates
the protocol/workload documentation. Flags follow `core.clj:113-195`.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="maelstrom_tpu",
        description="A TPU-native workbench for toy distributed systems.")
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("test", help="Run a single test")
    t.add_argument("--bin", help="Path to binary which runs a node")
    t.add_argument("--node", help="Built-in TPU node program, e.g. "
                                  "tpu:broadcast (instead of --bin)")
    t.add_argument("-w", "--workload", default="lin-kv",
                   choices=["broadcast", "broadcast-batched", "echo",
                            "g-set", "g-counter",
                            "pn-counter", "lin-kv", "lin-mutex",
                            "lin-tso", "txn-list-append", "unique-ids",
                            "kafka", "txn-rw-register"],
                   help="What workload to run")
    t.add_argument("--ordering", choices=["raft", "compartment",
                                          "batched"],
                   help="Run the workload's state machine as a "
                        "deterministic applier over this ordering "
                        "engine's command stream (doc/ordering.md): "
                        "'raft' = the raft log, 'compartment' = the "
                        "compartmentalized slot sequence (elections/"
                        "failover included; --roles sizes it), "
                        "'batched' = Chop Chop-style batched atomic "
                        "broadcast. Composes with -w lin-kv / kafka / "
                        "txn-list-append; the workload's stock checker "
                        "grades every combination. Implies --node "
                        "tpu:ordered")
    t.add_argument("--leader-lease-ms", type=float, default=None,
                   help="Client-side leader lease for the elected "
                        "compartment (doc/compartment.md): the host's "
                        "leader guess expires this much virtual time "
                        "after the last reply from it, so new ops "
                        "rotate off a dead leader at detection speed "
                        "instead of waiting out the RPC timeout "
                        "(default: 2x the election timeout; 0 "
                        "disables)")
    t.add_argument("--node-count", type=int,
                   help="How many nodes to run. Overrides --nodes.")
    t.add_argument("--nodes", help="Comma-separated node names")
    t.add_argument("--rate", type=float, default=5.0,
                   help="Approximate number of requests/sec")
    t.add_argument("--time-limit", type=float, default=10.0,
                   help="Test duration in seconds")
    t.add_argument("--concurrency", type=int,
                   help="Number of client workers")
    t.add_argument("--latency", type=float, default=0,
                   help="Mean network latency in ms")
    t.add_argument("--latency-dist", default="constant",
                   choices=["constant", "uniform", "exponential"],
                   help="Latency distribution shape")
    t.add_argument("--p-loss", type=float, default=0.0,
                   help="Probability each message is lost in transit")
    t.add_argument("--latency-scale", type=float, default=1.0,
                   help="Baseline latency scale factor (the slow!/fast! "
                        "knob), applied identically on the host and TPU "
                        "network paths; the weather nemesis toggles it "
                        "mid-run and restores this baseline")
    t.add_argument("--nemesis", default="",
                   help="Comma-separated fault packages to compose: "
                        "partition, kill, pause, duplicate, weather "
                        "(e.g. --nemesis "
                        "kill,pause,partition,duplicate,weather)")
    t.add_argument("--nemesis-interval", type=float, default=10.0,
                   help="Seconds between nemesis operations")
    t.add_argument("--roles", default=None,
                   help="Role-partitioned cluster tiers for --node "
                        "tpu:compartment (doc/compartment.md): "
                        "'sequencers=S,proxies=P,acceptors=RxC,"
                        "replicas=R' (a plain acceptor count is a "
                        "1-row grid). Sizes the cluster: S + P + R*C + "
                        "R nodes — drop --node-count and let --roles "
                        "derive it. sequencers > 1 makes the leader "
                        "ELECTED (ballot-numbered MultiPaxos phase 1): "
                        "kills of the live sequencer fail over instead "
                        "of stalling")
    t.add_argument("--election-timeout-rounds", type=int, default=None,
                   help="Failure-detector deadline for sequencer "
                        "elections, in virtual rounds (default 60; "
                        "needs --roles sequencers>1)")
    t.add_argument("--ballot-width", type=int, default=None,
                   help="Fenced election ballot-counter width in bits "
                        "(<= 6, default 6); overflow stalls failover "
                        "and invalidates the run visibly")
    t.add_argument("--compartment-retry", type=int, default=None,
                   help="Sequencer T_ASSIGN resend cadence in rounds "
                        "(default 10). Byzantine equivocation runs "
                        "want it tight: a conviction needs a second "
                        "delivery of the same slot inside the attack "
                        "window (doc/faults.md)")
    t.add_argument("--timeout-ms", type=float, default=None,
                   help="Client RPC timeout in virtual ms (default "
                        "5000). Failover runs want it tight: ops in "
                        "flight to a killed leader hold their worker "
                        "for exactly this window")
    t.add_argument("--service-roles", default=None,
                   help="In-cluster service tiers for --node "
                        "tpu:services: 'lin-tso=1,seq-kv=1,lww-kv=N' "
                        "(default 5 nodes; doc/compartment.md)")
    t.add_argument("--nemesis-targets", default=None,
                   help="Scope fault packages to named role groups "
                        "(role-partitioned nodes only), e.g. "
                        "'kill=proxies,partition=acceptor-col-0': kill/"
                        "pause sample within the group, partition cuts "
                        "the group off the rest of the cluster; "
                        "'kill=sequencer' targets the LIVE elected "
                        "leader (the failover driver). Groups "
                        "come from the node family's fault_groups "
                        "(role names, acceptor grid rows/columns) or "
                        "literal node names; '+' joins several")
    t.add_argument("--byz-rate", type=float, default=1.0,
                   help="Byzantine injection probability per round "
                        "while an attack window is open (--nemesis "
                        "byzantine; a pure hash gate, so the benign "
                        "decision streams never shift)")
    t.add_argument("--byz-attacks", default=None,
                   help="Restrict the byzantine package's attack pool: "
                        "comma list from equivocation, forged-proof, "
                        "stale-ballot (default: all three; "
                        "doc/faults.md)")
    t.add_argument("--nemesis-seed", type=int, default=None,
                   help="Decouple the fault-schedule RNG from --seed "
                        "(default: follow --seed). This is how a single "
                        "cluster of a --fleet-sweep nemesis campaign is "
                        "reproduced standalone: --seed <base> "
                        "--nemesis-seed <base + i>")
    t.add_argument("--client-retries", type=int, default=0,
                   help="Client RPC retry budget: failed/unavailable "
                        "RPCs re-issue up to N times under exponential "
                        "backoff with jitter (0 = no retries)")
    t.add_argument("--client-backoff-ms", type=float, default=50.0,
                   help="Base client retry backoff in ms (doubles per "
                        "attempt)")
    t.add_argument("--client-backoff-cap-ms", type=float, default=2000.0,
                   help="Upper bound on a single client retry backoff")
    t.add_argument("--topology", default="grid",
                   choices=["line", "grid", "tree", "tree2", "tree3",
                            "tree4", "total"],
                   help="Network topology offered to broadcast nodes")
    t.add_argument("--key-count", type=int,
                   help="Keys to work on at once (append test)")
    t.add_argument("--batch-max", type=int,
                   help="Batched broadcast: max client values distilled "
                        "into one batch (broadcast-batched workload; "
                        "default 16)")
    t.add_argument("--max-values", type=int,
                   help="Broadcast value-table capacity (broadcast / "
                        "broadcast-batched nodes; default 1024)")
    t.add_argument("--max-txn-length", type=int, default=4,
                   help="Max micro-ops per transaction")
    t.add_argument("--max-writes-per-key", type=int, default=16,
                   help="Max writes to any single key (append test)")
    t.add_argument("--consistency-models", default="strict-serializable",
                   help="Comma-separated consistency models to check")
    t.add_argument("--log-stderr", action="store_true",
                   help="Relay node stderr to the console")
    t.add_argument("--log-net-send", action="store_true",
                   help="Log packets as they're sent")
    t.add_argument("--log-net-recv", action="store_true",
                   help="Log packets as they're received")
    t.add_argument("--seed", type=int, default=0, help="PRNG seed")
    t.add_argument("--store", default="store", help="Store directory root")
    t.add_argument("--mesh",
                   help="Shard the TPU-path simulation over a dp,sp "
                        "device mesh (e.g. --mesh 1,4): dp = cluster/"
                        "data-parallel axis (carries the --fleet "
                        "cluster dimension; must be 1 without a "
                        "fleet), sp = node/pool axis. Same-seed runs "
                        "stay bit-identical to single-chip. Requires "
                        "--node tpu:<program> and dp*sp visible "
                        "devices (see doc/perf.md)")
    t.add_argument("--fleet", type=int,
                   help="Run N independent cluster instances inside "
                        "ONE compiled scan (the fleet runner): a "
                        "seed/nemesis/capacity campaign becomes one "
                        "device program, sharded ('dp','sp') under "
                        "--mesh dp,sp with N %% dp == 0 — mixed "
                        "meshes (dp>1 AND sp>1, e.g. --mesh 2,2) run "
                        "the scan body manual under shard_map "
                        "(doc/perf.md 'pod-scale mixed mesh'). "
                        "Composes "
                        "with --continuous: N open-world clusters in "
                        "one vmapped sched-inject scan, host polls "
                        "amortized to one pass per wave (doc/perf.md "
                        "'vectorized host driver'). Every cluster's "
                        "history is bit-identical to its standalone "
                        "run (doc/perf.md). TPU path only")
    t.add_argument("--sessions", choices=["coroutine", "columnar"],
                   help="Client-session bookkeeping backend (default: "
                        "columnar under --fleet, coroutine standalone): "
                        "'columnar' keeps pending/timeout/backoff/"
                        "redirect state in ONE shared numpy column "
                        "table advanced one vectorized pass per wave; "
                        "'coroutine' keeps the per-shell dict/list "
                        "path. Histories are byte-identical either way "
                        "(doc/perf.md 'columnar client sessions')")
    t.add_argument("--fleet-sweep", choices=["seed", "nemesis",
                                             "capacity"],
                   help="What the fleet varies per cluster (default "
                        "seed): 'seed' offsets the whole seed (ops + "
                        "faults), 'nemesis' fixes the op stream and "
                        "varies only the fault schedules, 'capacity' "
                        "ramps the offered load (rate x cluster-index)")
    t.add_argument("--max-scan", type=int,
                   help="Upper bound on rounds per compiled scan "
                        "dispatch (default 65536)")
    t.add_argument("--journal-scan-cap", type=int,
                   help="Device journal ring: io rows buffered on "
                        "device per dispatch on journaled runs "
                        "(default 256)")
    t.add_argument("--reply-log-cap", type=int,
                   help="Device reply ring: client replies buffered on "
                        "device per dispatch (default 256)")
    t.add_argument("--check-workers", type=int,
                   help="Overlapped analysis (TPU path only): one "
                        "ordered background worker pairs, partitions, "
                        "and screens drained history segments while "
                        "the device runs the next stretch; values > 1 "
                        "additionally fan the per-key linearizability "
                        "screens over a thread pool at check time "
                        "(default 1; 0 disables, same as --no-overlap)")
    t.add_argument("--no-overlap", action="store_true",
                   help="Disable the overlapped analysis pipeline and "
                        "run all checking sequentially after the run "
                        "(verdicts are bit-identical either way)")
    t.add_argument("--device-checker", choices=["auto", "on", "off"],
                   default=None,
                   help="Device-resident grading for the "
                        "txn-list-append (elle) checker (doc/perf.md): "
                        "dependency-edge construction runs jitted on "
                        "the device and an on-device cycle screen "
                        "skips Tarjan outright on certified-acyclic "
                        "histories. 'auto' (default) engages on large "
                        "histories; verdicts are bit-equal to the host "
                        "path either way")
    t.add_argument("--continuous", action="store_true",
                   help="Continuous generator mode (TPU path only): "
                        "client ops are injected at their seeded "
                        "offered-rate rounds INSIDE the compiled scan "
                        "window — traffic lands while nemeses are "
                        "mid-fault — instead of one dispatch per op. "
                        "Same seed => byte-identical history, plain, "
                        "--mesh, and as a --fleet N cluster "
                        "(doc/streams.md, doc/perf.md)")
    t.add_argument("--continuous-window-ms", type=float,
                   help="Continuous-mode stream stride in virtual ms "
                        "(default 250): windows cross replies, and the "
                        "stride bounds how stale a freed worker can get "
                        "before the generator is polled again")
    t.add_argument("--kafka-groups", type=int,
                   help="Streaming kafka consumer groups (kafka "
                        "workload, TPU path): N > 0 switches polls to "
                        "long-lived group subscriptions with "
                        "cursor-based fetches, coordinator rebalancing, "
                        "and per-group offset commits (doc/streams.md)")
    t.add_argument("--session-timeout-ms", type=float,
                   help="Consumer-group session timeout: a member "
                        "silent (no commit/subscribe heartbeat) this "
                        "long is evicted and its keys rebalance "
                        "(default 2500)")
    t.add_argument("--poll-batch", type=int,
                   help="Max entries per streaming kafka fetch "
                        "(default 8)")
    t.add_argument("--ms-per-round", type=float, default=1.0,
                   help="Virtual milliseconds per simulation round "
                        "(TPU path; coarser = faster, less latency "
                        "resolution)")
    t.add_argument("--checkpoint-every", type=float,
                   help="Checkpoint the run every N virtual seconds "
                        "(TPU path only; crash-consistent and written "
                        "by a background thread — see doc/checkpoint.md)")
    t.add_argument("--resume",
                   help="Resume from the checkpoint in this store test dir "
                        "(TPU path only; same options as the original run)")
    t.add_argument("--sync-checkpoint", action="store_true",
                   help="Write checkpoints synchronously on the main "
                        "thread instead of the background writer "
                        "(escape hatch; saves then block dispatching)")
    t.add_argument("--no-audit", action="store_true",
                   help="Skip the static-audit self-report block "
                        "(TPU path: results.json normally carries rule "
                        "counts from a trace-time hazard audit of this "
                        "run's own step functions — doc/analyze.md)")
    t.add_argument("--telemetry", nargs="?", const="auto", default=None,
                   metavar="DIR|off",
                   help="Flight recorder (TPU path, doc/observability"
                        ".md): device-resident metric rings folded "
                        "inside the compiled scan (message flow, "
                        "pool/channel occupancy, per-role sends, "
                        "latency-in-rounds buckets — drained on the "
                        "existing dispatch fetches, zero extra host "
                        "transfers), Chrome-trace phase spans "
                        "(trace.json opens in Perfetto), and a "
                        "telemetry.jsonl stream of per-window "
                        "p50/p95/p99 latency + rates + checker lag "
                        "(tail it with `maelstrom_tpu top`). DIR names "
                        "the output directory; bare --telemetry lands "
                        "it in the store dir; 'off' (the default) "
                        "disables. Histories are byte-identical "
                        "telemetry on or off")
    t.add_argument("--on-preempt", choices=["checkpoint", "abort"],
                   default="checkpoint",
                   help="What SIGTERM/SIGINT does to a TPU-path run: "
                        "'checkpoint' (default) finishes the in-flight "
                        "compiled stretch, writes a final checkpoint, "
                        "and exits code 75 so a supervisor can relaunch "
                        "with --resume; 'abort' dies immediately")

    s = sub.add_parser("serve", help="Serve the store directory")
    s.add_argument("--port", type=int, default=8080)
    s.add_argument("--store", default="store")

    tp = sub.add_parser(
        "top", help="Live tail of a run's telemetry stream: freshest "
                    "window per cluster — round, ops, delivered rate, "
                    "p50/p95/p99 latency, checker lag "
                    "(doc/observability.md)")
    tp.add_argument("path", nargs="?", default="store/latest",
                    help="telemetry dir, telemetry.jsonl file, or a "
                         "store test dir (default: store/latest)")
    tp.add_argument("--follow", action="store_true",
                    help="re-render every --interval seconds until "
                         "interrupted")
    tp.add_argument("--interval", type=float, default=1.0)

    d = sub.add_parser("demo", help="Run the bundled demo suite")
    d.add_argument("--store", default="store")
    d.add_argument("--time-limit", type=float, default=5.0)
    d.add_argument("--only", help="Run only demos whose name contains this")

    doc = sub.add_parser("doc", help="Regenerate protocol/workload docs")
    doc.add_argument("--dir", default="doc")

    b = sub.add_parser("bench", help="Run the TPU benchmark")
    b.add_argument("--nodes", type=int, default=None,
                   help="Node count (default: bench.py's BENCH_NODES)")
    b.add_argument("--rounds", type=int, default=None,
                   help="Round count (default: bench.py's BENCH_ROUNDS)")

    f = sub.add_parser("fuzz", help="Fault-mix sweeps at scale "
                                    "(BASELINE config 5): broadcast "
                                    "set-full, graded raft fleet, kafka")
    f.add_argument("--program", choices=["broadcast", "raft", "kafka"],
                   default="broadcast")
    f.add_argument("--nodes", type=int, default=None,
                   help="broadcast: node count (default 4096); raft: "
                        "cluster count (default 1000); kafka: node "
                        "count (default 5)")
    f.add_argument("--values", type=int, default=32)
    f.add_argument("--seed", type=int, default=0)

    az = sub.add_parser(
        "analyze",
        help="Static determinism & hot-path audit: trace the production "
             "step functions and lint the hot host modules for "
             "unstable-sort / host-transfer / dtype-promotion / "
             "donation hazards, gated on analyze/baseline.json "
             "(doc/analyze.md)")
    from .analyze.cli import add_analyze_args
    add_analyze_args(az)

    pa = sub.add_parser(
        "parity", help="Reproduce the reference's protocol-efficiency "
                       "numbers (msgs-per-op, stable latencies)")
    pa.add_argument("--quick", action="store_true",
                    help="CI-sized subset of configs")
    pa.add_argument("--render-only", action="store_true",
                    help="regenerate doc/parity.md + gate verdict from "
                         "the existing artifacts/parity.json")
    return p


def _check_models(spec: str) -> list[str]:
    """Validates --consistency-models against the checker's known model
    names (the reference validates against elle's, `core.clj:126-131`)."""
    from .checkers.elle import MODELS
    models = [m.strip() for m in spec.split(",") if m.strip()]
    unknown = [m for m in models if m not in MODELS]
    if unknown or not models:
        raise SystemExit(
            f"unknown consistency model(s) {unknown or [spec]}; expected "
            f"any of {MODELS}")
    return models


def opts_from_args(args) -> dict:
    opts = {
        "workload": args.workload,
        "bin": args.bin,
        "node": args.node,
        "node_count": args.node_count,
        "nodes": args.nodes.split(",") if isinstance(args.nodes, str)
        else None,
        "rate": args.rate,
        "time_limit": args.time_limit,
        "concurrency": args.concurrency,
        "latency": {"mean": args.latency, "dist": args.latency_dist},
        "p_loss": args.p_loss,
        "latency_scale": args.latency_scale,
        "continuous": args.continuous,
        "nemesis": set(filter(None, args.nemesis.split(","))),
        "nemesis_interval": args.nemesis_interval,
        "client_retries": args.client_retries,
        "client_backoff_ms": args.client_backoff_ms,
        "client_backoff_cap_ms": args.client_backoff_cap_ms,
        "topology": args.topology,
        "key_count": args.key_count,
        "max_txn_length": args.max_txn_length,
        "max_writes_per_key": args.max_writes_per_key,
        "consistency_models": _check_models(args.consistency_models),
        "log_stderr": args.log_stderr,
        "log_net_send": args.log_net_send,
        "log_net_recv": args.log_net_recv,
        "seed": args.seed,
        "store_root": args.store,
        "ms_per_round": args.ms_per_round,
        "checkpoint_every": args.checkpoint_every,
        "resume": args.resume,
        "sync_checkpoint": args.sync_checkpoint,
        "on_preempt": args.on_preempt,
        "no_overlap": args.no_overlap,
        # static-audit self-report (doc/analyze.md): CLI-driven runs
        # trace their own step functions into a `static-audit` results
        # block; --no-audit drops the block entirely (library/test
        # callers get the cheap lint-only block unless they opt in to
        # the trace via audit_trace)
        "audit": not args.no_audit,
        "audit_trace": not args.no_audit,
    }
    # TPU-path performance knobs: only forwarded when given, so the
    # runner's own defaults stay in one place
    for k in ("mesh", "max_scan", "journal_scan_cap", "reply_log_cap",
              "check_workers", "device_checker",
              "fleet", "fleet_sweep", "nemesis_seed",
              "kafka_groups", "session_timeout_ms", "poll_batch",
              "continuous_window_ms", "batch_max", "max_values",
              "roles", "service_roles", "nemesis_targets",
              "election_timeout_rounds", "ballot_width", "timeout_ms",
              "ordering", "leader_lease_ms", "byz_rate", "byz_attacks",
              "compartment_retry", "sessions"):
        v = getattr(args, k, None)
        if v is not None:
            opts[k] = v
    if opts.get("ordering") and not opts.get("node"):
        # the ordering axis is TPU-path by construction: resolve the
        # composed program spec here so the TPU-path guards below see it
        opts["node"] = args.node = "tpu:ordered"
    # flight recorder: "off" is the explicit disable spelling
    if args.telemetry and args.telemetry != "off":
        opts["telemetry"] = args.telemetry
    if opts.get("telemetry") and not (
            args.node and str(args.node).startswith("tpu:")):
        raise SystemExit("--telemetry needs the TPU path (--node "
                         "tpu:<program>): the metric rings live in the "
                         "compiled scan carry")
    if (args.checkpoint_every or args.resume) and not (
            args.node and str(args.node).startswith("tpu:")):
        raise SystemExit("--checkpoint-every/--resume need the TPU path "
                         "(--node tpu:<program>): external --bin processes "
                         "hold opaque state that cannot be snapshotted")
    if args.mesh and not (args.node and str(args.node).startswith("tpu:")):
        raise SystemExit("--mesh needs the TPU path (--node tpu:<program>):"
                         " external --bin processes don't run on a device "
                         "mesh")
    if (args.fleet or 1) > 1 and not (
            args.node and str(args.node).startswith("tpu:")):
        raise SystemExit("--fleet needs the TPU path (--node "
                         "tpu:<program>): the cluster axis is a vmapped "
                         "dimension of the compiled scan")
    if args.continuous and not (
            args.node and str(args.node).startswith("tpu:")):
        raise SystemExit("--continuous needs the TPU path (--node "
                         "tpu:<program>): scheduled in-scan injection "
                         "is a compiled-scan feature (the host path is "
                         "already real-time-continuous)")
    if (args.kafka_groups or 0) > 0 and not (
            args.node and str(args.node).startswith("tpu:")):
        raise SystemExit("--kafka-groups needs the TPU path (--node "
                         "tpu:kafka): the bin-path client speaks the "
                         "classic full-prefix kafka workload only")
    return opts


# The bundled demo suite (reference `core.clj:93-103`)
DEMOS = [
    {"workload": "echo", "bin": "demo/python/echo.py"},
    {"workload": "echo", "bin": "demo/python/echo_full.py"},
    # compiled C nodes (make -C demo/c); skipped when not built
    {"workload": "echo", "bin": "demo/c/echo"},
    # nodes on the reusable C library (demo/c/maelstrom_node.h)
    {"workload": "echo", "bin": "demo/c/echo_lib"},
    {"workload": "g-set", "bin": "demo/c/gset"},
    # perl nodes on demo/perl/MaelstromNode.pm (third userland language)
    {"workload": "echo", "bin": "demo/perl/echo.pl"},
    {"workload": "broadcast", "bin": "demo/perl/broadcast.pl"},
    {"workload": "g-set", "bin": "demo/perl/g_set.pl"},
    {"workload": "broadcast", "bin": "demo/python/broadcast.py"},
    {"workload": "g-set", "bin": "demo/python/g_set.py"},
    {"workload": "g-counter", "bin": "demo/python/g_counter.py"},
    {"workload": "g-counter", "bin": "demo/python/g_counter_seq_kv.py"},
    {"workload": "pn-counter", "bin": "demo/python/pn_counter.py"},
    {"workload": "lin-kv", "bin": "demo/python/lin_kv_proxy.py",
     "concurrency": 10},
    {"workload": "lin-kv", "bin": "demo/python/raft.py",
     "concurrency": 10, "time_limit_min": 8.0},
    {"workload": "txn-list-append",
     "bin": "demo/python/datomic_list_append.py"},
    {"workload": "unique-ids", "bin": "demo/python/unique_ids.py"},
    {"workload": "kafka", "bin": "demo/python/kafka.py"},
    {"workload": "txn-rw-register", "bin": "demo/python/txn_rw_register.py"},
    # native batched node programs (the TPU path's userland)
    {"workload": "broadcast", "node": "tpu:broadcast", "topology": "tree4"},
    {"workload": "g-set", "node": "tpu:g-set"},
    {"workload": "pn-counter", "node": "tpu:pn-counter"},
    {"workload": "g-counter", "node": "tpu:g-counter"},
    {"workload": "lin-kv", "node": "tpu:lin-kv"},
    {"workload": "lin-mutex", "node": "tpu:lin-kv"},
    {"workload": "txn-list-append", "node": "tpu:txn-list-append"},
    {"workload": "unique-ids", "node": "tpu:unique-ids"},
    {"workload": "kafka", "node": "tpu:kafka"},
    {"workload": "txn-rw-register", "node": "tpu:txn-rw-register"},
]


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [%(name)s] %(message)s")
    from .util import honor_jax_platforms
    honor_jax_platforms()
    args = build_parser().parse_args(argv)

    if args.cmd == "test":
        from . import checkpoint as cp
        from . import core
        try:
            results = core.run(opts_from_args(args))
        except cp.Preempted as e:
            # graceful preemption: distinct exit code so a supervisor
            # (run_crash_soak.sh) relaunches with --resume
            print(f"\npreempted: {e}", file=sys.stderr)
            return cp.EXIT_PREEMPTED
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        valid = results.get("valid")
        print(f"\nEverything looks good! ヽ(‘ー`)ノ" if valid is True else
              ("\nValidity unknown (;￣ー￣)" if valid == "unknown" else
               "\nAnalysis invalid! (ﾉಥ益ಥ)ﾉ ┻━┻"))
        return 0 if valid is True else (2 if valid == "unknown" else 1)

    if args.cmd == "serve":
        from .serve import serve
        serve(args.store, args.port)
        return 0

    if args.cmd == "top":
        from .telemetry import top_main
        return top_main(args.path, follow=args.follow,
                        interval=args.interval)

    if args.cmd == "demo":
        from . import core
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        failures = []
        for demo in DEMOS:
            runner = demo.get("bin") or demo["node"]
            if args.only and args.only not in runner:
                continue
            opts = {**demo, "node_count": 3,
                    "time_limit": max(args.time_limit,
                                      demo.get("time_limit_min", 0)),
                    "rate": 10,
                    "store_root": args.store, "recovery_s": 2.5}
            opts.pop("time_limit_min", None)
            if "bin" in demo:
                bin_path = os.path.join(repo, demo["bin"])
                if not os.path.exists(bin_path):
                    print(f"skip {demo['bin']} (not present)")
                    continue
                opts["bin"] = bin_path
            print(f"\n=== {demo['workload']} :: {runner} ===")
            r = core.run(opts)
            print(f"valid: {r.get('valid')}")
            if r.get("valid") is not True:
                failures.append(demo)
        if failures:
            print(f"\n{len(failures)} demo(s) failed: {failures}")
            return 1
        print("\nAll demos passed.")
        return 0

    if args.cmd == "doc":
        from .doc_gen import write_docs
        for path in write_docs(args.dir):
            print(f"wrote {path}")
        return 0

    if args.cmd == "bench":
        import subprocess
        # bench.py is configured through BENCH_* env vars; explicit flags
        # override them, unset flags leave the user's env alone
        env = dict(os.environ)
        if args.nodes is not None:
            env["BENCH_NODES"] = str(args.nodes)
        if args.rounds is not None:
            env["BENCH_ROUNDS"] = str(args.rounds)
        return subprocess.call([sys.executable, "bench.py"], env=env)

    if args.cmd == "fuzz":
        from .fuzz import main as fuzz_main
        return fuzz_main(args.nodes, args.values, args.seed,
                         program=args.program)

    if args.cmd == "analyze":
        from .analyze.cli import run_analyze
        return run_analyze(args)

    if args.cmd == "parity":
        from .parity import main as parity_main
        pargs = (["--quick"] if args.quick else []) + \
            (["--render-only"] if args.render_only else [])
        return parity_main(pargs)
    return 1


if __name__ == "__main__":
    sys.exit(main())
