"""Broadcast fuzz harness: partitions + latency sweep at scale.

The last BASELINE.json graded config ("broadcast fuzz: 100k nodes, random
partitions + latency sweep") and SURVEY.md build step 9: drive the
compiled broadcast simulation through a sweep of fault configurations —
latency distributions, message loss, random partitions injected
mid-broadcast and healed — and verify the workload's safety property
directly on the final state: every node saw every value (the essence of
the set-full checker: lost-count == 0), with zero silent drops.

Each config runs entirely in `lax.scan` chunks; partitions flip between
chunks (the nemesis acting at chunk boundaries). Usage:

    python -m maelstrom_tpu fuzz --nodes 100000          # full sweep
    python -m maelstrom_tpu fuzz --nodes 4096 --seed 7   # quick
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

DEFAULT_SWEEP = [
    {"name": "zero-latency+partition", "latency": 0, "dist": "constant",
     "p_loss": 0.0, "partition": True},
    {"name": "latency2+loss5%+partition", "latency": 2, "dist": "constant",
     "p_loss": 0.05, "partition": True},
    {"name": "uniform-latency+partition", "latency": 2, "dist": "uniform",
     "p_loss": 0.0, "partition": True},
    {"name": "exponential-latency+loss2%", "latency": 2,
     "dist": "exponential", "p_loss": 0.02, "partition": False},
]


def fuzz_broadcast(n_nodes: int = 4096, values: int = 32,
                   sweep=None, seed: int = 0, chunk: int = 100,
                   max_rounds: int = 20_000, log=print) -> list[dict]:
    import jax
    import jax.numpy as jnp

    from .net import tpu as T
    from .nodes import get_program
    from .nodes.broadcast import T_BCAST
    from .sim import make_run_fn, make_sim

    rng = np.random.default_rng(seed)
    results = []
    for ci, c in enumerate(sweep or DEFAULT_SWEEP):
        nodes = [f"n{i}" for i in range(n_nodes)]
        program = get_program(
            "broadcast",
            {"topology": "grid", "max_values": values,
             "latency": {"mean": c["latency"], "dist": c["dist"]},
             "ms_per_round": 1.0},
            nodes)
        cfg = T.NetConfig(
            n_nodes=n_nodes, n_clients=1, pool_cap=max(64, 2 * values),
            inbox_cap=program.inbox_cap, client_cap=0,
            latency_mean_rounds=float(c["latency"]),
            latency_dist=c["dist"])
        run_fn = make_run_fn(program, cfg)
        sim = make_sim(program, cfg, seed=seed + ci)
        if c["p_loss"]:
            sim = sim.replace(net=T.flaky(sim.net, c["p_loss"]))

        # injections target a 4-chunk span (step clamps at one per round,
        # so large value counts extend it); the partition covers chunks
        # 1-2, so values born inside the partitioned cluster must cross
        # after healing (the nemesis flips at chunk boundaries, where the
        # host regains control of the scan). Convergence may only be
        # declared once the LAST injection round has passed.
        step = max(1, 4 * chunk // values)
        inj_rounds = step * values
        inj_span = -(-inj_rounds // chunk) * chunk

        def make_chunk(r0):
            rr = np.arange(r0, r0 + chunk)
            on = (rr % step == 0) & (rr // step < values)
            val = (rr // step) % values
            dest = (val.astype(np.int64) * 2654435761) % n_nodes
            return T.Msgs.empty((chunk, 1)).replace(
                valid=jnp.asarray(on[:, None]),
                src=jnp.full((chunk, 1), n_nodes, T.I32),
                dest=jnp.asarray(dest.astype(np.int32)[:, None]),
                type=jnp.full((chunk, 1), T_BCAST, T.I32),
                a=jnp.asarray(val.astype(np.int32)[:, None]))

        # partition window: cuts the cluster into 2 random components
        # while values are still being injected, heals afterwards
        part_from, part_until = chunk, 3 * chunk
        labels = rng.integers(0, 2, size=n_nodes).tolist()

        t0 = time.perf_counter()
        r = 0
        converged_at = None
        partitioned = False
        while r < max_rounds:
            want = c["partition"] and part_from <= r < part_until
            if want != partitioned:      # flip fault state at boundaries
                sim = sim.replace(
                    net=(T.partition_components(sim.net, labels) if want
                         else T.heal(sim.net)))
                partitioned = want
            sim, _counts = run_fn(sim, make_chunk(r))
            r += chunk
            if r >= inj_span:
                seen = jax.device_get(sim.nodes["seen"][:, :values])
                # like the set-full checker, a value whose *injection* was
                # eaten by message loss is indeterminate (no node ever saw
                # it) and doesn't count against convergence; every value
                # that was born must reach every node
                born = seen.any(axis=0)
                # probe convergence only with the network healed (gate on
                # the live fault flag; the heal is applied at loop-top, so
                # comparing r to part_until would probe one chunk early)
                if (seen.all(axis=0) == born).all() and not partitioned:
                    converged_at = r
                    n_born = int(born.sum())
                    break
        dt = time.perf_counter() - t0

        st = T.stats_dict(sim.net)
        ch = sim.channels
        overwrites = int(jax.device_get(ch.overwrites)) if ch is not None \
            else 0
        # overwrites on the edge rings are a bounded-channel drop; legal
        # only for programs that retransmit until acknowledged (mirrors
        # TpuNetStats's tolerated-overwrites contract)
        tolerated = getattr(program, "tolerates_channel_overwrites", False)
        # randomized-dist configs accept clipped tail draws explicitly:
        # at 100k nodes the ring is sized to 8x the mean (memory), the
        # exponential tail beyond that is clipped shorter — which can
        # only speed convergence, the property this harness checks. The
        # toleration is recorded so no run hides it.
        clipped = (int(jax.device_get(ch.lat_clipped))
                   if ch is not None else 0)
        clip_tolerated = c["dist"] != "constant"
        ok = (converged_at is not None and st["dropped_overflow"] == 0
              and (overwrites == 0 or tolerated)
              and (clipped == 0 or clip_tolerated))
        res = {
            "config": c["name"], "nodes": n_nodes, "values": values,
            "values_born": n_born if converged_at is not None else None,
            "ok": bool(ok), "converged_at_round": converged_at,
            "wall_s": round(dt, 2),
            "delivered": st["recv_all"], "lost": st["lost"],
            "dropped_partition": st["dropped_partition"],
            "dropped_overflow": st["dropped_overflow"],
            "channel_overwrites": overwrites,
            "latency_clipped": clipped,
            "latency_clip_tolerated": bool(clip_tolerated),
        }
        results.append(res)
        log(json.dumps(res))
    return results


def main(n_nodes: int | None, values: int, seed: int,
         program: str = "broadcast") -> int:
    if program == "broadcast":
        results = fuzz_broadcast(n_nodes=n_nodes or 4096, values=values,
                                 seed=seed)
    elif program == "raft":
        # --nodes is the fleet size here (clusters of 5)
        results = fuzz_raft(n_clusters=n_nodes or 1000, seed=seed)
    elif program == "kafka":
        results = fuzz_kafka(n_nodes=n_nodes or 5, seed=seed)
    else:
        raise SystemExit(f"unknown fuzz program {program!r}")
    ok = all(r["ok"] for r in results)
    print(json.dumps({"fuzz": program, "configs": len(results),
                      "all_ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(int(sys.argv[1]) if len(sys.argv) > 1 else 4096, 32, 0))


RAFT_SWEEP = [
    {"name": "partition-only", "p_loss": 0.0, "latency": None,
     "partition": True},
    {"name": "loss3%+partition", "p_loss": 0.03, "latency": None,
     "partition": True},
    {"name": "latency2-uniform+loss2%", "p_loss": 0.02,
     "latency": {"mean": 2, "dist": "uniform"}, "partition": False},
    {"name": "latency3-exponential+partition", "p_loss": 0.0,
     "latency": {"mean": 3, "dist": "exponential"}, "partition": True},
    # everything at once: the mix most likely to compose failure modes
    # (the torn-AE bug needed reordering AND elections; loss on top
    # exercises the retry machinery under both)
    {"name": "loss4%+latency2-exponential+partition", "p_loss": 0.04,
     "latency": {"mean": 2, "dist": "exponential"}, "partition": True},
]


def fuzz_raft(n_clusters: int = 1000, sample: int = 32, seed: int = 0,
              sweep=None, log=print) -> list[dict]:
    """Raft-fleet fuzz: the graded vmapped fleet (bench_raft_graded)
    swept across fault mixes — partitions, message loss, latency
    distributions — with per-config sampled WGL grading and a fleet-wide
    conservation audit (zero silent drops)."""
    from .bench_raft_graded import run_raft_graded

    results = []
    for ci, c in enumerate(sweep or RAFT_SWEEP):
        kw = dict(n_clusters=n_clusters, sample=sample,
                  seed=seed + 101 * ci, verbose=False,
                  p_loss=c["p_loss"], latency=c["latency"],
                  # loss/latency slow elections and commits down:
                  # grant extra warmup and runway
                  warmup_chunks=14 if (c["p_loss"] or c["latency"])
                  else 8,
                  max_chunks=600)
        if c["partition"]:
            kw.update(partition_at=4, partition_chunks=12)
        r = run_raft_graded(**kw)
        res = {
            "config": c["name"], "clusters": n_clusters,
            "sampled": r["sampled_clusters"],
            "ok": bool(r["all_linearizable"]
                       and r["dropped_overflow"] == 0),
            "all_linearizable": r["all_linearizable"],
            "indeterminate_ops": r["indeterminate_ops"],
            "dropped_overflow": r["dropped_overflow"],
            "net_stats": r["net_stats"],
            "rounds": r["rounds"], "wall_s": r["wall_s"],
        }
        results.append(res)
        log(json.dumps(res))
    return results


KAFKA_SWEEP = [
    {"name": "partition", "p_loss": 0.0, "latency": None,
     "partition": True},
    {"name": "loss3%+partition", "p_loss": 0.03, "latency": None,
     "partition": True},
    {"name": "latency3-uniform+loss2%", "p_loss": 0.02,
     "latency": {"mean": 3, "dist": "uniform"}, "partition": False},
    {"name": "latency5-exponential+partition", "p_loss": 0.0,
     "latency": {"mean": 5, "dist": "exponential"}, "partition": True},
    {"name": "loss3%+latency3-exponential+partition", "p_loss": 0.03,
     "latency": {"mean": 3, "dist": "exponential"}, "partition": True},
]


def fuzz_kafka(n_nodes: int = 5, seed: int = 0, time_limit: float = 6.0,
               rate: float = 20.0, sweep=None, log=print) -> list[dict]:
    """Kafka fuzz: the replicated-log program end to end through the
    interactive runner under the fault sweep, graded by the stock kafka
    checker (lost-writes/monotonicity/committed-floor) with the
    conservation audit gating each run."""
    from . import core

    results = []
    for ci, c in enumerate(sweep or KAFKA_SWEEP):
        opts = dict(
            store_root="/tmp/maelstrom-tpu-fuzz-store",
            seed=seed + 31 * ci, workload="kafka", node="tpu:kafka",
            node_count=n_nodes, rate=rate, time_limit=time_limit,
            journal_rows=False, p_loss=c["p_loss"])
        if c["latency"]:
            opts["latency"] = c["latency"]
        if c["partition"]:
            opts.update(nemesis={"partition"}, nemesis_interval=2.0)
        r = core.run(opts)
        # the "net" sub-result is the conservation audit; it already
        # gates r["valid"], recorded here so every row shows its drops
        net = r.get("net") or {}
        res = {
            "config": c["name"], "nodes": n_nodes,
            "ok": bool(r["valid"]),
            "valid": r["valid"],
            "ops": (r.get("stats") or {}).get("count"),
            "dropped_overflow": net.get("dropped-overflow"),
            "lost": net.get("lost"),
            "dropped_partition": net.get("dropped-partition"),
        }
        results.append(res)
        log(json.dumps(res))
    return results
