"""Fault injection: the nemesis.

Reimplements the reference's nemesis package (`src/maelstrom/nemesis.clj` +
jepsen.nemesis.combined/partition-package): a special 'nemesis' process
receives `start-partition` / `stop-partition` ops from its own generator and
applies them to the network as directional block-sets (reference
`net.clj:108-112`). Partition grudges: random halves, majorities-ring, or a
single isolated node. The package generator emits a fault roughly every
`interval` seconds and the final generator heals everything so
eventually-consistent workloads are graded post-recovery
(reference `core.clj:63-70`).
"""

from __future__ import annotations

import random

from . import generators as g


def split_half(nodes, rng: random.Random):
    """Random majority/minority split; returns (name, grudge) where grudge
    maps dest -> set of blocked srcs (both directions blocked)."""
    nodes = list(nodes)
    rng.shuffle(nodes)
    k = len(nodes) // 2
    a, b = set(nodes[:k]), set(nodes[k:])
    grudge = {}
    for d in a:
        grudge[d] = set(b)
    for d in b:
        grudge[d] = set(a)
    return f"halves {sorted(a)} | {sorted(b)}", grudge


def isolate_node(nodes, rng: random.Random):
    """Cuts one node off from everyone else."""
    nodes = list(nodes)
    n = rng.choice(nodes)
    rest = set(nodes) - {n}
    grudge = {n: set(rest)}
    for d in rest:
        grudge[d] = {n}
    return f"isolated {n}", grudge


GRUDGES = [split_half, isolate_node]


class PartitionNemesis:
    """Executes nemesis ops against the network's fault API."""

    def __init__(self, net, nodes, seed: int = 0):
        self.net = net
        self.nodes = list(nodes)
        self.rng = random.Random(seed)

    def invoke(self, op: dict) -> dict:
        f = op["f"]
        if f == "start-partition":
            name, grudge = self.rng.choice(GRUDGES)(self.nodes, self.rng)
            for dest, srcs in grudge.items():
                for src in srcs:
                    self.net.drop_link(src, dest)
            return {**op, "type": "info", "value": name}
        if f == "stop-partition":
            self.net.heal()
            return {**op, "type": "info", "value": "healed"}
        raise ValueError(f"unknown nemesis op {f!r}")


def package(faults: set, interval_s: float = 10.0):
    """Builds {generator, final_generator} for the requested fault set
    (only :partition, like the reference CLI, `core.clj:40-42`)."""
    if "partition" not in faults:
        return {"generator": None, "final_generator": None}

    # g.cycle pickles (checkpoint/resume); Seq never mutates the pristine
    # Sleep instances it re-yields each lap
    schedule = g.cycle([
        g.sleep(interval_s),
        {"f": "start-partition", "type": "invoke"},
        g.sleep(interval_s),
        {"f": "stop-partition", "type": "invoke"},
    ])

    return {"generator": g.Seq(schedule),
            "final_generator": g.Once({"f": "stop-partition",
                                       "type": "invoke"})}
